"""Pass 4 — static race detector over the host threading seams.

PRs 13–18 threaded the checker: background flush workers
(utils/flushq), the double-buffered prefetcher (utils/prefetch), the
shared dedup pool (utils/keyset), AOT-compile threads (serve/sched),
chaos stalkers (serve/chaos, campaign/chaos), the non-blocking EventLog
writer and the lock-protected PhaseTimers (obs/events, obs/phases —
whose off-owner accumulation race in the tracing PR was found by hand).
This pass applies the checker's own discipline to that host code: model
every thread entry point, compute which ``self.<attr>`` / module-global
names are reachable from more than one side of a spawn, and demand that
each such shared name is provably disciplined.

**Model.**  Stdlib-``ast`` only, whole-package, name-based:

- *Entry points*: every ``threading.Thread(target=...)`` and every
  executor ``submit``/``map`` whose first argument is a resolvable
  function.  The worker set is the call-graph closure from those
  targets; the main set is the closure from every other function
  (constructors included — publishing in ``__init__`` is the main
  thread's half of the handshake).
- *Call graph*: bare names resolve within the module (nested ``def``
  first), ``self.m(...)`` within the class, ``Cls.m(...)``/``Cls(...)``
  to that class (a constructor call also reaches ``__enter__``/
  ``__exit__``/``__call__`` — the context-manager protocol), and
  ``obj.m(...)`` to the *unique* scanned class defining ``m`` when there
  is exactly one.  Unresolvable calls get no edge: the pass prefers
  missing an edge to inventing one.
- *Shared names*: a field is analyzed when it belongs to a
  synchronization-bearing owner — a class (or module) that spawns a
  thread, holds a lock/handoff object, or is stored in a field of one —
  and is accessed from both the worker and the main closure.  Fields of
  plain value/handle classes (per-call objects that never cross a
  spawn) are presumed thread-confined; giving a class a lock or a
  thread is what opts it into scrutiny.
- *Local aliases* are tracked one level deep (``timers = self._timers;
  acc = timers._acc; acc[k] = ...`` mutates the timers' field) — the
  exact shape of the off-owner PhaseTimers race.

**The discipline.**  Every mutating access to a shared name must be

(a) guarded — inside a ``with self._lock:``-style context whose lock
    name is a ``Lock``/``RLock``/``Condition`` field of the owner, or
    in a helper every one of whose in-package call sites holds that
    lock (the ``_foo_locked`` convention, checked rather than trusted);
(b) published-before-spawn — a constructor write at or above the
    constructor's first spawn statement (or anywhere in a spawn-free
    constructor);
(c) a handoff — the field holds a queue/Event/Semaphore/executor/
    thread-local built in the constructor and is never rebound; or
(d) waived — ``# lint: thread-ok <reason>`` on the mutating line.  The
    reason is mandatory; pass 5 audits that every waiver still
    suppresses a live finding.

Anything else is an ``unguarded-shared-mutation`` error citing both the
mutation and a conflicting access on the other side of the spawn (or
``post-spawn-publish`` for a constructor write below the spawn).  All
findings are errors: a race the pass cannot rule out is a soundness
hole, the same severity contract as Pass 1's width overflows.
"""

from __future__ import annotations

import ast
import dataclasses
import os

from raft_tla_tpu.analysis.report import ERROR, THREAD, Finding

WAIVER = "lint: thread-ok"

# Constructor-call names that make a field a lock (guard-capable).
LOCK_TYPES = frozenset({"Lock", "RLock", "Condition"})

# Constructor-call names that make a field a handoff object: its whole
# purpose is cross-thread use and its own synchronization is internal.
HANDOFF_TYPES = frozenset({
    "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue", "Event",
    "Semaphore", "BoundedSemaphore", "Barrier", "ThreadPoolExecutor",
    "local", "count",
})

# Method names that mutate their receiver in place.
MUTATORS = frozenset({
    "append", "appendleft", "add", "update", "clear", "extend",
    "remove", "discard", "pop", "popleft", "popitem", "insert",
    "setdefault", "sort", "reverse",
})


# --------------------------------------------------------------------------
# model records


@dataclasses.dataclass
class _Access:
    field: tuple                 # ("attr", cls_key, name) | ("global", mod, name)
    write: bool
    path: str
    line: int
    guards: frozenset            # active lock/with names
    waiver: str | None           # None = not waived; "" = waived, no reason
    func: tuple                  # owning function key
    in_ctor_of: tuple | None     # cls_key when written via self in __init__


@dataclasses.dataclass
class _Func:
    key: tuple                   # (path, qualname)
    name: str                    # bare name (call resolution)
    cls: tuple | None            # (path, ClsName) of enclosing class
    parent: tuple | None         # enclosing function key (nested defs)
    node: ast.AST = None
    accesses: list = dataclasses.field(default_factory=list)
    calls: list = dataclasses.field(default_factory=list)
    spawn_targets: list = dataclasses.field(default_factory=list)
    spawn_lines: list = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class _Class:
    key: tuple                   # (path, name)
    name: str
    fields: set = dataclasses.field(default_factory=set)
    locks: set = dataclasses.field(default_factory=set)
    handoffs: set = dataclasses.field(default_factory=set)
    field_types: dict = dataclasses.field(default_factory=dict)
    ctor_spawn_line: int | None = None
    owns_spawn: bool = False


@dataclasses.dataclass
class _Module:
    path: str
    globals_: set = dataclasses.field(default_factory=set)
    global_locks: set = dataclasses.field(default_factory=set)
    global_handoffs: set = dataclasses.field(default_factory=set)
    has_spawn: bool = False


@dataclasses.dataclass
class Result:
    """Findings plus the waiver bookkeeping pass 5 audits."""
    findings: list
    used_waivers: set            # {(path, line)} waivers suppressing a finding


# --------------------------------------------------------------------------
# phase A: skeletons (classes, fields, globals) — needed before any
# access can be attributed


def _call_type_name(node: ast.AST) -> str | None:
    """For ``x = Foo(...)`` / ``x = mod.Foo(...)``, the ``Foo``."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


class _Skeleton:
    def __init__(self):
        self.modules: dict[str, _Module] = {}
        self.classes: dict[tuple, _Class] = {}
        self.funcs: dict[tuple, _Func] = {}
        self.class_names: dict[str, list] = {}     # bare name -> cls keys
        self.field_owners: dict[str, set] = {}     # field name -> cls keys
        self.method_owners: dict[str, set] = {}    # method name -> cls keys

    def collect(self, path: str, tree: ast.Module):
        mod = _Module(path)
        self.modules[path] = mod
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_func(path, stmt, cls=None, parent=None,
                               prefix="")
            elif isinstance(stmt, ast.ClassDef):
                self._add_class(path, stmt)
            elif isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        mod.globals_.add(t.id)
                        tn = _call_type_name(stmt.value)
                        if tn in LOCK_TYPES:
                            mod.global_locks.add(t.id)
                        elif tn in HANDOFF_TYPES:
                            mod.global_handoffs.add(t.id)
            elif isinstance(stmt, ast.AnnAssign) and \
                    isinstance(stmt.target, ast.Name):
                mod.globals_.add(stmt.target.id)

    def _add_class(self, path: str, node: ast.ClassDef):
        key = (path, node.name)
        cls = _Class(key, node.name)
        self.classes[key] = cls
        self.class_names.setdefault(node.name, []).append(key)
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_func(path, stmt, cls=key, parent=None,
                               prefix=node.name + ".")
                self.method_owners.setdefault(stmt.name, set()).add(key)
                is_ctor = stmt.name == "__init__"
                for sub in ast.walk(stmt):
                    if isinstance(sub, (ast.Assign, ast.AugAssign,
                                        ast.AnnAssign)):
                        targets = sub.targets if isinstance(sub, ast.Assign) \
                            else [sub.target]
                        for t in targets:
                            if isinstance(t, ast.Attribute) and \
                                    isinstance(t.value, ast.Name) and \
                                    t.value.id == "self":
                                cls.fields.add(t.attr)
                                self.field_owners.setdefault(
                                    t.attr, set()).add(key)
                                if is_ctor and isinstance(sub, ast.Assign):
                                    tn = _call_type_name(sub.value)
                                    if tn in LOCK_TYPES:
                                        cls.locks.add(t.attr)
                                    elif tn in HANDOFF_TYPES:
                                        cls.handoffs.add(t.attr)
                                    elif tn:
                                        cls.field_types[t.attr] = tn

    def _add_func(self, path, node, cls, parent, prefix):
        key = (path, prefix + node.name)
        self.funcs[key] = _Func(key, node.name, cls, parent, node)
        # nested defs become first-class functions (the serve/chaos
        # `def run(): ...; Thread(target=run)` shape); their bodies are
        # excluded from the enclosing function's access set
        for sub in node.body:
            self._walk_nested(path, sub, cls, key, prefix + node.name)

    def _walk_nested(self, path, stmt, cls, parent, prefix):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._add_func(path, stmt, cls=cls, parent=parent,
                           prefix=prefix + ".<locals>.")
            return
        if isinstance(stmt, ast.ClassDef):
            return                          # function-local class: opaque
        for sub in ast.iter_child_nodes(stmt):
            if isinstance(sub, ast.stmt):
                self._walk_nested(path, sub, cls, parent, prefix)


# --------------------------------------------------------------------------
# phase B: per-function analysis (accesses, guards, calls, spawns)


class _FuncAnalyzer:
    def __init__(self, sk: _Skeleton, fn: _Func, src_lines: list):
        self.sk = sk
        self.fn = fn
        self.path = fn.key[0]
        self.mod = sk.modules[self.path]
        self.cls = sk.classes.get(fn.cls) if fn.cls else None
        self.src_lines = src_lines
        self.guards: list = []
        self.aliases: dict = {}
        self.locals_: set = set()
        self.global_decls: set = set()
        self._seen: set = set()
        node = fn.node
        args = node.args
        for p in args.args + args.posonlyargs + args.kwonlyargs:
            self.locals_.add(p.arg)
        if args.vararg:
            self.locals_.add(args.vararg.arg)
        if args.kwarg:
            self.locals_.add(args.kwarg.arg)
        self.is_ctor = fn.cls is not None and fn.name == "__init__" and \
            "<locals>" not in fn.key[1]

    def run(self):
        for stmt in self.fn.node.body:
            self._stmt(stmt)

    # -- helpers ------------------------------------------------------------

    def _waiver(self, line: int) -> str | None:
        txt = self.src_lines[line - 1] if line <= len(self.src_lines) else ""
        idx = txt.find(WAIVER)
        if idx < 0:
            return None
        return txt[idx + len(WAIVER):].strip(" -—:#").strip()

    def _record(self, field, write, line, in_ctor=False):
        if field is None:
            return
        dedup = (field, write, line)
        if dedup in self._seen:
            return
        self._seen.add(dedup)
        self.fn.accesses.append(_Access(
            field=field, write=write, path=self.path, line=line,
            guards=frozenset(self.guards), waiver=self._waiver(line),
            func=self.fn.key,
            in_ctor_of=self.fn.cls if (in_ctor and self.is_ctor) else None))

    def _unique_field_owner(self, name: str):
        owners = self.sk.field_owners.get(name, ())
        if len(owners) == 1:
            return next(iter(owners))
        return None

    def _field_of_cls(self, cls_key, name):
        return ("attr", cls_key, name)

    def _resolve_chain(self, base_field: str, attr: str):
        """Owner of ``self.<base_field>.<attr>`` (one level deep)."""
        if self.cls is not None:
            tn = self.cls.field_types.get(base_field)
            if tn and tn in self.sk.class_names and \
                    len(self.sk.class_names[tn]) == 1:
                ck = self.sk.class_names[tn][0]
                return self._field_of_cls(ck, attr)
            if base_field in self.cls.handoffs:
                return None                 # handoff internals: not ours
        owner = self._unique_field_owner(attr)
        if owner is not None:
            return self._field_of_cls(owner, attr)
        return None

    def _resolve_ref(self, node: ast.AST):
        """Field key a reference expression denotes, or None."""
        if isinstance(node, ast.Name):
            a = self.aliases.get(node.id)
            if a and a[0] == "fieldref":
                return a[1]
            if node.id in self.mod.globals_ and \
                    node.id not in self.locals_:
                return ("global", self.path, node.id)
            return None
        if isinstance(node, ast.Attribute):
            base = node.value
            if isinstance(base, ast.Name):
                if base.id == "self" and self.cls is not None:
                    if node.attr in self.cls.fields:
                        return self._field_of_cls(self.cls.key, node.attr)
                    owner = self._unique_field_owner(node.attr)
                    if owner is not None:
                        return self._field_of_cls(owner, node.attr)
                    return None
                a = self.aliases.get(base.id)
                if a:
                    if a[0] == "self" and self.cls is not None:
                        return self._field_of_cls(self.cls.key, node.attr)
                    if a[0] == "selfattr":
                        return self._resolve_chain(a[1], node.attr)
                return None
            if isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id == "self":
                return self._resolve_chain(base.attr, node.attr)
        return None

    def _alias_for(self, value: ast.AST):
        if isinstance(value, ast.Name):
            if value.id == "self":
                return ("self",)
            return self.aliases.get(value.id)
        if isinstance(value, ast.Attribute) and \
                isinstance(value.value, ast.Name):
            if value.value.id == "self":
                return ("selfattr", value.attr)
            a = self.aliases.get(value.value.id)
            if a and a[0] == "self":
                return ("selfattr", value.attr)
            if a and a[0] == "selfattr":
                fk = self._resolve_chain(a[1], value.attr)
                if fk is not None:
                    return ("fieldref", fk)
        fk = self._resolve_ref(value)
        if fk is not None:
            return ("fieldref", fk)
        return None

    # -- statements ---------------------------------------------------------

    def _stmt(self, s: ast.stmt):
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.locals_.add(s.name)        # analyzed as its own _Func
            return
        if isinstance(s, ast.ClassDef):
            return
        if isinstance(s, ast.Global):
            self.global_decls.update(s.names)
            return
        if isinstance(s, ast.Assign):
            self._expr(s.value)
            alias = self._alias_for(s.value)
            for t in s.targets:
                self._target(t, alias)
            return
        if isinstance(s, ast.AnnAssign):
            if s.value is not None:
                self._expr(s.value)
                self._target(s.target, self._alias_for(s.value))
            return
        if isinstance(s, ast.AugAssign):
            self._expr(s.value)
            self._target(s.target, None)
            return
        if isinstance(s, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in s.items:
                ce = item.context_expr
                self._expr(ce)
                g = None
                if isinstance(ce, ast.Attribute):
                    g = ce.attr
                elif isinstance(ce, ast.Name):
                    g = ce.id
                if g is not None:
                    self.guards.append(g)
                    pushed += 1
                if item.optional_vars is not None:
                    self._target(item.optional_vars, None)
            for sub in s.body:
                self._stmt(sub)
            for _ in range(pushed):
                self.guards.pop()
            return
        if isinstance(s, ast.For):
            self._expr(s.iter)
            self._target(s.target, None)
            for sub in s.body + s.orelse:
                self._stmt(sub)
            return
        # everything else: visit child expressions, recurse into child
        # statements (If/While/Try/Return/Expr/Raise/...)
        for child in ast.iter_child_nodes(s):
            if isinstance(child, ast.stmt):
                self._stmt(child)
            elif isinstance(child, ast.expr):
                self._expr(child)
            elif isinstance(child, (ast.excepthandler,)):
                for sub in child.body:
                    self._stmt(sub)

    def _target(self, t: ast.AST, alias):
        if isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                self._target(el, None)
            return
        if isinstance(t, ast.Starred):
            self._target(t.value, None)
            return
        if isinstance(t, ast.Name):
            if t.id in self.global_decls and t.id in self.mod.globals_:
                self._record(("global", self.path, t.id), True, t.lineno)
                return
            self.locals_.add(t.id)
            if alias is not None:
                self.aliases[t.id] = alias
            else:
                self.aliases.pop(t.id, None)
            return
        if isinstance(t, ast.Attribute):
            base = t.value
            if isinstance(base, ast.Name) and base.id == "self" and \
                    self.cls is not None:
                self._record(self._field_of_cls(self.cls.key, t.attr),
                             True, t.lineno, in_ctor=True)
                return
            fk = self._resolve_ref(t)
            if fk is not None:
                self._record(fk, True, t.lineno)
            return
        if isinstance(t, ast.Subscript):
            fk = self._resolve_ref(t.value)
            if fk is not None:
                self._record(fk, True, t.lineno)
            else:
                self._expr(t.value)
            self._expr(t.slice)
            return

    # -- expressions --------------------------------------------------------

    def _expr(self, e: ast.AST):
        for node in ast.walk(e):
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.ctx, ast.Load):
                fk = self._resolve_ref(node)
                if fk is not None:
                    self._record(fk, False, node.lineno)
            elif isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load):
                if node.id in self.mod.globals_ and \
                        node.id not in self.locals_ and \
                        node.id not in self.aliases:
                    self._record(("global", self.path, node.id), False,
                                 node.lineno)
            elif isinstance(node, ast.Call):
                self._call(node)

    def _call(self, node: ast.Call):
        f = node.func
        # spawn: threading.Thread(target=...) / Thread(target=...)
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else None)
        if name == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    self._spawn(kw.value, node.lineno)
            return
        # spawn: executor.submit(fn, ...) / executor.map(fn, ...)
        if isinstance(f, ast.Attribute) and f.attr in ("submit", "map") \
                and node.args:
            self._spawn(node.args[0], node.lineno, require_resolved=True)
        # mutator method on a resolvable field reference
        if isinstance(f, ast.Attribute):
            fk = self._resolve_ref(f.value)
            if fk is not None:
                self._record(fk, f.attr in MUTATORS, f.lineno)
        # call edges
        self._edge(node)

    def _spawn(self, target: ast.AST, line: int, require_resolved=False):
        ref = None
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self" and self.fn.cls is not None:
            ref = ("method", self.fn.cls, target.attr)
        elif isinstance(target, ast.Name):
            ref = ("localname", self.fn.key, target.id)
        elif isinstance(target, ast.Attribute) and not require_resolved:
            ref = ("uniquemethod", target.attr)
        if ref is None:
            return
        self.fn.spawn_targets.append(ref)
        self.fn.spawn_lines.append(line)
        self.mod.has_spawn = True
        if self.cls is not None:
            self.cls.owns_spawn = True
            if self.is_ctor:
                sl = self.cls.ctor_spawn_line
                self.cls.ctor_spawn_line = line if sl is None \
                    else min(sl, line)

    def _edge(self, node: ast.Call):
        f = node.func
        g = frozenset(self.guards)
        if isinstance(f, ast.Name):
            if f.id in self.sk.class_names:
                self.fn.calls.append((("class", f.id), g))
            else:
                self.fn.calls.append((("localname", self.fn.key, f.id), g))
            return
        if isinstance(f, ast.Attribute):
            base = f.value
            if isinstance(base, ast.Name):
                if base.id == "self" and self.fn.cls is not None:
                    self.fn.calls.append((("method", self.fn.cls, f.attr), g))
                    return
                if base.id in self.sk.class_names:
                    keys = self.sk.class_names[base.id]
                    if len(keys) == 1:
                        self.fn.calls.append(
                            (("clsmethod", keys[0], f.attr), g))
                        return
            self.fn.calls.append((("uniquemethod", f.attr), g))


# --------------------------------------------------------------------------
# phase C: reachability + verdicts


def _resolve_edge(sk: _Skeleton, ref) -> list:
    kind = ref[0]
    if kind == "method":
        _, cls_key, m = ref
        key = (cls_key[0], f"{cls_key[1]}.{m}")
        return [key] if key in sk.funcs else []
    if kind == "clsmethod":
        _, cls_key, m = ref
        key = (cls_key[0], f"{cls_key[1]}.{m}")
        return [key] if key in sk.funcs else []
    if kind == "class":
        _, name = ref
        out = []
        for cls_key in sk.class_names.get(name, ()):
            for m in ("__init__", "__enter__", "__exit__", "__call__"):
                key = (cls_key[0], f"{cls_key[1]}.{m}")
                if key in sk.funcs:
                    out.append(key)
        return out
    if kind == "localname":
        _, fkey, name = ref
        # nested defs of the calling function shadow module-level ones
        nested = (fkey[0], f"{fkey[1]}.<locals>.{name}")
        if nested in sk.funcs:
            return [nested]
        mod_fn = (fkey[0], name)
        if mod_fn in sk.funcs and sk.funcs[mod_fn].cls is None:
            return [mod_fn]
        return []
    if kind == "uniquemethod":
        _, m = ref
        owners = sk.method_owners.get(m, ())
        if len(owners) == 1:
            ck = next(iter(owners))
            key = (ck[0], f"{ck[1]}.{m}")
            return [key] if key in sk.funcs else []
        return []
    return []


def _closure(sk: _Skeleton, roots: set) -> set:
    seen = set(roots)
    todo = list(roots)
    while todo:
        fkey = todo.pop()
        fn = sk.funcs.get(fkey)
        if fn is None:
            continue
        for ref, _g in fn.calls:
            for nxt in _resolve_edge(sk, ref):
                if nxt not in seen:
                    seen.add(nxt)
                    todo.append(nxt)
    return seen


def _candidates(sk: _Skeleton) -> set:
    """Class keys whose fields are subject to analysis: spawn owners,
    lock/handoff holders, plus classes stored in a candidate's fields."""
    cand = {k for k, c in sk.classes.items()
            if c.owns_spawn or c.locks or c.handoffs}
    changed = True
    while changed:
        changed = False
        for k in list(cand):
            for tn in sk.classes[k].field_types.values():
                keys = sk.class_names.get(tn, ())
                for ck in keys:
                    if ck not in cand:
                        cand.add(ck)
                        changed = True
    return cand


def analyze(sources: dict) -> Result:
    """Run the race detector over ``{relpath: source}``."""
    sk = _Skeleton()
    trees, lines = {}, {}
    for path in sorted(sources):
        try:
            tree = ast.parse(sources[path], filename=path)
        except SyntaxError:
            continue                        # pass 3 reports parse errors
        trees[path] = tree
        lines[path] = sources[path].splitlines()
        sk.collect(path, tree)
    for fn in sk.funcs.values():
        _FuncAnalyzer(sk, fn, lines[fn.key[0]]).run()

    spawn_roots = set()
    for fn in sk.funcs.values():
        for ref in fn.spawn_targets:
            spawn_roots.update(_resolve_edge(sk, ref))
    worker = _closure(sk, spawn_roots)
    main = _closure(sk, set(sk.funcs) - spawn_roots)

    # guard sets carried by every in-package call edge into a function:
    # an access inside `_foo_locked` counts as guarded when *all* call
    # sites hold the owner's lock (and the function is not itself a
    # thread entry point, which would bypass every call site)
    incoming: dict = {}
    for fn in sk.funcs.values():
        for ref, g in fn.calls:
            for callee in _resolve_edge(sk, ref):
                incoming.setdefault(callee, []).append(g)

    def _inherited_guards(fkey) -> frozenset:
        sites = incoming.get(fkey)
        if not sites or fkey in spawn_roots:
            return frozenset()
        return frozenset.intersection(*sites)

    cand = _candidates(sk)
    by_field: dict = {}
    for fn in sk.funcs.values():
        for acc in fn.accesses:
            by_field.setdefault(acc.field, []).append(acc)

    findings, used = [], set()
    for field in sorted(by_field):
        kind = field[0]
        if kind == "attr":
            _, cls_key, name = field
            cls = sk.classes.get(cls_key)
            if cls is None or cls_key not in cand:
                continue
            if name in cls.locks:
                continue                    # the guards themselves
            is_handoff = name in cls.handoffs
            owner_locks = cls.locks
            label = f"{cls_key[1]}.{name}"
            ctor_spawn = cls.ctor_spawn_line
        else:
            _, mpath, name = field
            mod = sk.modules[mpath]
            if not (mod.has_spawn or mod.global_locks):
                continue
            if name in mod.global_locks:
                continue
            is_handoff = name in mod.global_handoffs
            owner_locks = mod.global_locks
            label = name
            ctor_spawn = None

        accs = by_field[field]
        worker_accs = [a for a in accs if a.func in worker]
        main_accs = [a for a in accs if a.func in main]
        if not worker_accs or not main_accs:
            continue
        for acc in accs:
            if not acc.write:
                continue
            if is_handoff and acc.in_ctor_of is not None:
                continue                    # the constructor build
            pre_spawn_publish = (
                acc.in_ctor_of is not None
                and (ctor_spawn is None or acc.line <= ctor_spawn))
            guarded = bool(
                (set(acc.guards) | _inherited_guards(acc.func))
                & owner_locks)
            if not is_handoff and (pre_spawn_publish or guarded):
                continue
            if acc.waiver is not None:
                used.add((acc.path, acc.line))
                if not acc.waiver:
                    findings.append(Finding(
                        THREAD, ERROR, "waiver-missing-reason",
                        f"`# lint: thread-ok` on shared {label} carries "
                        "no reason — every thread waiver must say why "
                        "the unguarded access is safe",
                        field=label, file=acc.path, line=acc.line))
                continue
            other = next((a for a in worker_accs if a.func != acc.func),
                         None) or next(
                (a for a in main_accs if a.func != acc.func), None) \
                or (worker_accs + main_accs)[0]
            if is_handoff:
                findings.append(Finding(
                    THREAD, ERROR, "handoff-rebound",
                    f"handoff object {label} is rebound outside the "
                    f"constructor while also used at "
                    f"{other.path}:{other.line} — threads holding the "
                    "old object never see the new one; mutate in place "
                    "or guard the swap",
                    field=label, file=acc.path, line=acc.line))
            elif acc.in_ctor_of is not None:
                findings.append(Finding(
                    THREAD, ERROR, "post-spawn-publish",
                    f"{label} is written after the constructor spawns "
                    f"its thread (spawn at line {ctor_spawn}); the "
                    f"worker (via {other.path}:{other.line}) can read "
                    "the pre-write value — publish before the spawn "
                    "or guard both sides",
                    field=label, file=acc.path, line=acc.line))
            else:
                findings.append(Finding(
                    THREAD, ERROR, "unguarded-shared-mutation",
                    f"{label} is mutated without holding a lock while "
                    f"also accessed from another thread entry point "
                    f"(conflicting access {other.path}:{other.line}) — "
                    "guard with the owner's lock, make it a handoff "
                    "object, or waive with `# lint: thread-ok <reason>`",
                    field=label, file=acc.path, line=acc.line))
    return Result(findings, used)


# --------------------------------------------------------------------------
# entry points


def lint_source(src: str, path: str = "<string>") -> list:
    """Lint one self-contained module (tests, planted mutations)."""
    return analyze({path: src}).findings


def package_sources(root: str | None = None) -> dict:
    """``{relpath: source}`` for every module under raft_tla_tpu/."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    pkg = os.path.join(root, "raft_tla_tpu")
    out = {}
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for f in sorted(filenames):
            if not f.endswith(".py"):
                continue
            full = os.path.join(dirpath, f)
            with open(full, "r", encoding="utf-8") as fh:
                out[os.path.relpath(full, root)] = fh.read()
    return out


def lint_paths(root: str | None = None) -> list:
    """The whole package, one model (cross-module reachability)."""
    return analyze(package_sources(root)).findings
