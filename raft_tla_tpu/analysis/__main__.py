"""speclint CLI — run the five analysis passes over a model config.

::

    python -m raft_tla_tpu.lint runs/MC3s2v.cfg            # both modes
    python -m raft_tla_tpu.lint runs/MC3s2v.cfg --strict   # warnings fail
    python -m raft_tla_tpu.lint --mode faithful --spec election cfg
    python -m raft_tla_tpu.lint                  # no cfg: passes 1+3+4+5

(``python -m raft_tla_tpu.analysis`` is the same program.)

Exit code: 0 when every pass proves its property (warnings allowed),
1 on any error finding — or on any finding at all under ``--strict``.
"""

from __future__ import annotations

import argparse
import sys

from raft_tla_tpu.analysis import report
from raft_tla_tpu.analysis.report import Finding


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="raft-tla-lint",
        description="static width-safety and spec-consistency analyzer: "
                    "proves the packed encodings cannot silently truncate "
                    "(Pass 1), lints the cfg against the model registries "
                    "(Pass 2), flags tracer-hostile idioms in the "
                    "kernel/engine sources (Pass 3), detects unguarded "
                    "shared state across thread entry points (Pass 4), "
                    "and cross-checks the gate/obs-schema/waiver "
                    "contracts (Pass 5)")
    p.add_argument("cfg", nargs="?", default=None,
                   help="TLC model config (.cfg); omit to run only the "
                        "width and jit passes on default bounds")
    p.add_argument("--mode", choices=("parity", "faithful", "both"),
                   default="both",
                   help="which encoding mode(s) to prove (default: both)")
    p.add_argument("--spec", default="full",
                   help="action-family subset, as in check.py (default: "
                        "full)")
    p.add_argument("--view", default=None,
                   help="CLI state view name (models/views registry) to "
                        "check symmetry/invariant compatibility against")
    p.add_argument("--strict", action="store_true",
                   help="exit 1 on warnings too")
    p.add_argument("--max-term", type=int, default=None, metavar="N")
    p.add_argument("--max-log", type=int, default=None, metavar="N")
    p.add_argument("--max-msgs", type=int, default=None, metavar="N")
    p.add_argument("--max-dup", type=int, default=None, metavar="N")
    p.add_argument("--skip", action="append", default=[],
                   choices=("width", "cfg", "jit", "thread", "contract"),
                   help="skip a pass (repeatable)")
    return p


def _bounds_for(args, cfg, history: bool):
    from raft_tla_tpu.config import Bounds
    kw = {"history": history}
    if cfg is not None:
        kw["n_servers"] = len(cfg.server_names())
        kw["n_values"] = len(cfg.value_names())
    for flag in ("max_term", "max_log", "max_msgs", "max_dup"):
        v = getattr(args, flag)
        if v is not None:
            kw[flag] = v
    return Bounds(**kw)


def run_lint(args) -> tuple[list, int]:
    """All requested passes; returns (findings, exit_code)."""
    from raft_tla_tpu.analysis import (cfglint, contracts, jitlint,
                                       threadlint, widthcheck)
    from raft_tla_tpu.utils.cfgparse import load_cfg

    cfg = None
    if args.cfg is not None:
        try:
            cfg = load_cfg(args.cfg)
        except (OSError, ValueError) as e:
            f = Finding(report.CFG, report.ERROR, "cfg-unreadable", str(e),
                        file=args.cfg)
            return [f], 1

    modes = {"parity": (False,), "faithful": (True,),
             "both": (False, True)}[args.mode]
    findings: list = []
    for history in modes:
        tag = "faithful" if history else "parity"
        try:
            bounds = _bounds_for(args, cfg, history)
        except ValueError as e:
            findings.append(Finding(
                report.WIDTH, report.ERROR, "bounds-invalid",
                f"[{tag}] {e}", file=args.cfg))
            continue
        if "width" not in args.skip:
            for f in widthcheck.check_widths(bounds, args.spec):
                findings.append(_tagged(f, tag))
        if cfg is not None and "cfg" not in args.skip:
            for f in cfglint.lint_cfg(cfg, bounds, spec=args.spec,
                                      view=args.view, path=args.cfg):
                findings.append(_tagged(f, tag))
    if "jit" not in args.skip:
        findings += jitlint.lint_paths()
    if "thread" not in args.skip:
        findings += threadlint.lint_paths()
    if "contract" not in args.skip:
        findings += contracts.lint_paths()
    return findings, report.exit_code(findings, strict=args.strict)


def _tagged(f: Finding, tag: str) -> Finding:
    return Finding(f.pass_, f.severity, f.code, f"[{tag}] {f.message}",
                   transition=f.transition, field=f.field,
                   interval=f.interval, width=f.width, file=f.file,
                   line=f.line)


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    findings, code = run_lint(args)
    target = args.cfg or "(no cfg)"
    print(report.render(
        findings, header=f"speclint: {target} mode={args.mode} "
                         f"spec={args.spec}"))
    return code


if __name__ == "__main__":
    sys.exit(main())
