"""Static analysis layer — the checker that guards the checker.

The runtime engines trust one unproved assumption (SURVEY §4.5): every
transition kernel writes values that fit the per-field bit widths
``ops/bitpack.field_bits`` derives from :class:`~raft_tla_tpu.config.Bounds`.
One overflowing write — a term increment past ``term_cap``, a bitmask past
``n`` bits — is silently truncated by the pack, collides fingerprints, and
turns "exhaustive check passed" into a false negative with no runtime
symptom.  This package closes that hole at build time, before any state is
expanded:

- **Pass 1** (:mod:`.widthcheck`): an interval abstract interpreter over the
  state schema *proves* width-safety per transition — classic abstract
  interpretation (Cousot & Cousot 1977) on the guard/update structure of
  ``ops/kernels``;
- **Pass 2** (:mod:`.cfglint`): diagnostics for the cfg/invariant/view
  surface (unknown names with did-you-mean, vacuous invariants,
  symmetry/view compatibility) — TLC's "check the model before trusting
  the run" philosophy (Yu, Manolios & Lamport);
- **Pass 3** (:mod:`.jitlint`): a stdlib-``ast`` lint over the kernel and
  engine sources for known JAX tracer hazards (Python ``if`` on traced
  values, nondeterministic set iteration, ``int()`` casts of tracers,
  unannotated dtype narrowing).

Entry points: ``python -m raft_tla_tpu.lint`` (standalone CLI),
``check.py --lint`` (Pass 1 at step-build time, warn-only by default),
and the individual ``check_*`` functions for tests and the seeded-mutation
harness (``tests/test_lint_mutations.py``).
"""

from raft_tla_tpu.analysis.report import Finding, render, has_errors  # noqa: F401
