"""Interval abstract domain over the state schema — Pass 1's substrate.

A value interval ``[lo, hi]`` over-approximates the set of values an
``int32`` field element can hold; the transfer functions in
:mod:`.widthcheck` push these through the guard/update structure of
``ops/kernels``.  The domain is the classic one (Cousot & Cousot 1977)
restricted to what the kernels actually compute: add/sub with constants
and intervals, min/max, bitwise-or of non-negative sets, one-bit shifts,
and join (convex union).  Everything is exact integer arithmetic — no
widening is needed because every chain is bounded by a field capacity
and the message-envelope fixpoint (:func:`.widthcheck.message_envelope`)
is monotone over a finite lattice.

Two environments matter:

- :func:`envelope` — the *claimed inductive invariant*: the interval each
  struct field stays inside on every reachable state.  Pass 1 proves it
  closed under every transition (and that it fits the packed widths).
- :func:`expansion_envelope` — the envelope met with the StateConstraint
  (``ops/state.constraint_ok``): the input domain of a transition, because
  TLC semantics only ever *expand* constraint-satisfying states
  (config.py "capacity scheme" docstring).  This meet is exactly why the
  ``+1`` capacities suffice — drop it (see the seeded mutations) and
  Timeout/ClientRequest overflow their fields.
"""

from __future__ import annotations

import dataclasses

from raft_tla_tpu.config import Bounds
from raft_tla_tpu.ops import state as st


@dataclasses.dataclass(frozen=True)
class Interval:
    """Closed integer interval [lo, hi]; lo <= hi always."""

    lo: int
    hi: int

    def __post_init__(self):
        if self.lo > self.hi:
            raise ValueError(f"empty interval [{self.lo}, {self.hi}]")

    # -- arithmetic ----------------------------------------------------------
    def __add__(self, other: "Interval | int") -> "Interval":
        o = _as_iv(other)
        return Interval(self.lo + o.lo, self.hi + o.hi)

    def __sub__(self, other: "Interval | int") -> "Interval":
        o = _as_iv(other)
        return Interval(self.lo - o.hi, self.hi - o.lo)

    def min_(self, other: "Interval | int") -> "Interval":
        o = _as_iv(other)
        return Interval(min(self.lo, o.lo), min(self.hi, o.hi))

    def max_(self, other: "Interval | int") -> "Interval":
        o = _as_iv(other)
        return Interval(max(self.lo, o.lo), max(self.hi, o.hi))

    def join(self, other: "Interval | int") -> "Interval":
        """Convex union — the abstract `jnp.where(cond, a, b)`."""
        o = _as_iv(other)
        return Interval(min(self.lo, o.lo), max(self.hi, o.hi))

    def meet(self, other: "Interval | int") -> "Interval":
        """Intersection (guard refinement); raises on empty."""
        o = _as_iv(other)
        return Interval(max(self.lo, o.lo), min(self.hi, o.hi))

    def or_(self, other: "Interval | int") -> "Interval":
        """Bitwise-or bound for non-negative operands: x|y >= max(x, y)
        and x|y < 2^k whenever both x, y < 2^k."""
        o = _as_iv(other)
        if self.lo < 0 or o.lo < 0:
            raise ValueError("or_ requires non-negative intervals")
        hi = (1 << max(self.hi.bit_length(), o.hi.bit_length())) - 1
        return Interval(max(self.lo, o.lo), max(hi, 0))

    # -- queries -------------------------------------------------------------
    def fits_bits(self, bits: int) -> bool:
        """All values representable as `bits`-wide non-negative ints."""
        return self.lo >= 0 and self.hi <= (1 << bits) - 1

    def subset(self, other: "Interval") -> bool:
        return self.lo >= other.lo and self.hi <= other.hi

    def as_tuple(self) -> tuple:
        return (self.lo, self.hi)


def _as_iv(x) -> Interval:
    return x if isinstance(x, Interval) else Interval(int(x), int(x))


def const(v: int) -> Interval:
    return Interval(int(v), int(v))


BOOL = Interval(0, 1)


def bitmask(n_bits: int) -> Interval:
    """All n-bit masks (the vote-set encoding)."""
    return Interval(0, (1 << n_bits) - 1)


# -- state-schema environments ----------------------------------------------

def envelope(bounds: Bounds) -> dict:
    """The claimed per-field inductive interval, derived from Bounds.

    This is the width contract ``ops/bitpack.field_bits`` encodes,
    written as value sets: Pass 1 proves (a) Init is inside, (b) every
    transition maps the constraint-met envelope back into it, (c) it
    fits the packed widths.  ``allLogs`` is a raw 32-bit mask word
    (sign bit is data) and is tracked as [0, 2^32-1] with uint32
    semantics — see ``ops/bitpack.RAW_FIELDS``.
    """
    from raft_tla_tpu.ops.msgbits import HI_FIELDS, LO_FIELDS
    n = bounds.n_servers
    hi_bits = max(sh + w for sh, w in HI_FIELDS.values())
    # Parity mode strips the mlog field 'g' (always 0), so the packed lo
    # word never reaches its faithful-mode range — mirror field_bits.
    lo_fields = LO_FIELDS if bounds.history else \
        {k: v for k, v in LO_FIELDS.items() if k != "g"}
    lo_bits = max(sh + w for sh, w in lo_fields.values())
    env = {
        "role": Interval(0, 2),
        "term": Interval(1, bounds.term_cap),
        "votedFor": Interval(0, n),                 # 0 = Nil, else id+1
        "commitIndex": Interval(0, bounds.log_cap),
        "logLen": Interval(0, bounds.log_cap),
        "logTerm": Interval(0, bounds.term_cap),    # 0 = padding
        "logVal": Interval(0, bounds.n_values),     # 0 = padding
        "vResp": bitmask(n),
        "vGrant": bitmask(n),
        "nextIndex": Interval(1, bounds.log_cap + 1),
        "matchIndex": Interval(0, bounds.log_cap),
        # The packed message words are checked per-subfield against the
        # shift/width tables; as whole words they span the packed range.
        "msgHi": bitmask(hi_bits),
        "msgLo": bitmask(lo_bits),
        "msgCount": Interval(0, bounds.dup_cap),
    }
    if bounds.history:
        from raft_tla_tpu.ops.loguniv import LogUniverse
        uni = LogUniverse.of(bounds)
        env.update({
            "allLogs": bitmask(32),                   # raw mask words
            "vLog": Interval(0, uni.size),            # rank+1, 0 = absent
            "eTerm": Interval(0, bounds.term_cap),    # 0 = empty slot
            "eLeader": Interval(0, max(n - 1, 0)),
            "eLog": Interval(0, uni.size - 1),
            "eVotes": bitmask(n),
            "eVLog": Interval(0, uni.size),           # rank+1, 0 = absent
        })
    return env


def expansion_envelope(bounds: Bounds) -> dict:
    """envelope ∧ StateConstraint — a transition's input domain.

    Only constraint-satisfying states are ever expanded (TLC CONSTRAINT
    semantics, ``ops/state.constraint_ok``), which tightens exactly the
    three constrained axes; everything else keeps its inductive range.
    """
    env = dict(envelope(bounds))
    env["term"] = env["term"].meet(Interval(1, bounds.max_term))
    env["logLen"] = env["logLen"].meet(Interval(0, bounds.max_log))
    env["msgCount"] = env["msgCount"].meet(Interval(0, bounds.max_dup))
    return env


def init_env(bounds: Bounds) -> dict:
    """Point intervals of the unique Init state (ops/state.init_struct)."""
    import numpy as np
    struct = st.init_struct(bounds, np)
    return {f: Interval(int(a.min()), int(a.max())) if a.size else const(0)
            for f, a in struct.items()}
