"""Pass 5 — runtime-contract lint: gates, obs schema, waiver audit.

The repo carries two families of cross-file contracts that no runtime
test can see end to end:

- **Gates.**  Every ``RAFT_TLA_*`` environment variable is a promise:
  a CLI flag sets it, exactly one resolution helper reads it, a
  ``tools/lint.sh`` smoke block exercises it, the README documents it,
  and it never leaks into the checkpoint identity digest (gates toggle
  *how* a state space is explored, never *which* state space — a gate
  in the digest would make checkpoints unresumable across gate
  settings).  Each leg of that promise lives in a different file, so a
  new gate can silently ship half-wired.  This pass discovers every
  gate name in the sources (string constants merge across implicit
  concatenation, so split help-text literals still count) and checks
  all five legs, with did-you-mean on names that appear exactly once
  within edit distance 2 of an established gate.

- **Obs schema.**  ``obs/events.py`` declares a versioned field set
  per event type; consumers (the campaign supervisor, Perfetto export,
  RESULTS.md tooling) parse by that declaration.  Every emission
  site's *literal* field set must be a subset of the declared fields
  for its event type — a new field can never ship without a schema
  bump.  ``**fields`` splats are invisible to this pass; they are
  covered at runtime by ``validate_event``.

- **Waivers.**  ``# lint: jit-ok`` / ``# lint: thread-ok`` comments
  suppress findings forever, so each must still be *earning* its keep:
  a jit waiver is stale when stripping it and re-linting the file
  produces no finding on that line; a thread waiver is stale when the
  race detector no longer needs it.  Stale waivers are errors — they
  read as "this line is dangerous" over code that no longer is, and
  they would silently mask a *future* regression of a different kind.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize

from raft_tla_tpu.analysis import jitlint, threadlint
from raft_tla_tpu.analysis.report import CONTRACT, ERROR, Finding

GATE_RE = re.compile(r"\bRAFT_TLA_[A-Z0-9][A-Z0-9_]*\b")

WAIVER_KINDS = ("jit-ok", "thread-ok")

_SCHEMA_PATH = "raft_tla_tpu/obs/events.py"
_DIGEST_PATH = "raft_tla_tpu/utils/ckpt.py"
_DIGEST_FUNC = "config_digest"


@dataclasses.dataclass
class Inputs:
    """Everything the contract lint cross-checks, injectable for tests."""
    sources: dict                       # {relpath: python source}
    readme: str = ""
    lint_sh: str = ""
    schema_path: str = _SCHEMA_PATH
    digest_path: str = _DIGEST_PATH


def _edit_distance(a: str, b: str) -> int:
    if abs(len(a) - len(b)) > 2:
        return 3                        # caller only cares about <= 2
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        for j, cb in enumerate(b, 1):
            cur.append(min(prev[j] + 1, cur[-1] + 1,
                           prev[j - 1] + (ca != cb)))
        prev = cur
    return prev[-1]


def _flag_text(gate: str) -> str:
    """``RAFT_TLA_PHASE_TIMERS`` -> ``--phase-timers`` (fallback guess;
    the authoritative flag comes from the parser's add_argument call)."""
    return "--" + gate[len("RAFT_TLA_"):].lower().replace("_", "-")


def _mentions(text: str, gate: str, flags: set) -> bool:
    if re.search(re.escape(gate) + r"\b", text):
        return True
    for fl in flags | {_flag_text(gate)}:
        if re.search(re.escape(fl) + r"(?![a-z0-9-])", text):
            return True
    return False


# --------------------------------------------------------------------------
# gate contract


class _GateScan(ast.NodeVisitor):
    """Per-file AST facts: env-var aliases, environ reads, argparse
    flags, and which gates each ``add_argument`` call mentions."""

    def __init__(self, path: str, aliases: dict):
        self.path = path
        self.aliases = aliases          # shared: ENV_X name -> gate
        self.reads: list = []           # (gate, line)
        self.flag_gates: dict = {}      # gate -> set of option strings

    def visit_Assign(self, node: ast.Assign):
        if isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str) and \
                GATE_RE.fullmatch(node.value.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.aliases[t.id] = node.value.value
        self.generic_visit(node)

    def _gate_of(self, node: ast.AST) -> str | None:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            m = GATE_RE.fullmatch(node.value)
            return m.group(0) if m else None
        if isinstance(node, ast.Name):
            return self.aliases.get(node.id)
        if isinstance(node, ast.Attribute):   # events.ENV_EVENTS
            return self.aliases.get(node.attr)
        return None

    @staticmethod
    def _is_environ(node: ast.AST) -> bool:
        return isinstance(node, ast.Attribute) and node.attr == "environ"

    def visit_Call(self, node: ast.Call):
        f = node.func
        # os.environ.get(GATE, ...)
        if isinstance(f, ast.Attribute) and f.attr == "get" and \
                self._is_environ(f.value) and node.args:
            g = self._gate_of(node.args[0])
            if g:
                self.reads.append((g, node.lineno))
        # p.add_argument("--flag", ..., help="... names the gate ...")
        if isinstance(f, ast.Attribute) and f.attr == "add_argument":
            opts = {a.value for a in node.args
                    if isinstance(a, ast.Constant)
                    and isinstance(a.value, str)
                    and a.value.startswith("--")}
            gates = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Constant) and \
                        isinstance(sub.value, str):
                    gates.update(GATE_RE.findall(sub.value))
            for g in gates:
                self.flag_gates.setdefault(g, set()).update(opts)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript):
        # os.environ[GATE] in Load position only (writes are the CLI
        # side of the contract, not a resolver)
        if self._is_environ(node.value) and \
                isinstance(node.ctx, ast.Load):
            g = self._gate_of(node.slice)
            if g:
                self.reads.append((g, node.lineno))
        self.generic_visit(node)


def _gate_contract(inp: Inputs, trees: dict) -> list:
    findings = []
    # occurrence census over raw text (docstrings, comments, literals)
    occ: dict = {}
    for path in sorted(inp.sources):
        for i, line in enumerate(inp.sources[path].splitlines(), 1):
            for g in GATE_RE.findall(line):
                occ.setdefault(g, []).append((path, i))

    aliases: dict = {}
    scans = []
    for path in sorted(trees):
        sc = _GateScan(path, aliases)
        scans.append(sc)
    for sc in scans:                    # aliases first, then reads/flags
        sc.visit(trees[sc.path])
    for sc in scans:
        sc.reads = []
        sc.flag_gates = {}
        sc.visit(trees[sc.path])

    established = {g for g, sites in occ.items() if len(sites) >= 2}
    gates = []
    for g in sorted(occ):
        if len(occ[g]) == 1:
            near = sorted(e for e in established
                          if 0 < _edit_distance(g, e) <= 2)
            if near:
                path, line = occ[g][0]
                findings.append(Finding(
                    CONTRACT, ERROR, "gate-near-miss",
                    f"{g} appears exactly once and is within edit "
                    f"distance 2 of {near[0]} — did you mean "
                    f"{near[0]}? (a typo'd gate name reads the wrong "
                    "env var and silently never fires)",
                    field=g, file=path, line=line))
                continue
        gates.append(g)

    reads: dict = {}
    flags: dict = {}
    for sc in scans:
        for g, line in sc.reads:
            reads.setdefault(g, []).append((sc.path, line))
        for g, opts in sc.flag_gates.items():
            flags.setdefault(g, set()).update(opts)

    digest_src = _function_source(inp, inp.digest_path, _DIGEST_FUNC)

    for g in gates:
        path, line = occ[g][0]
        r = reads.get(g, [])
        if not r:
            findings.append(Finding(
                CONTRACT, ERROR, "gate-no-resolver",
                f"{g} has no resolution helper — nothing reads it from "
                "os.environ, so setting it does nothing",
                field=g, file=path, line=line))
        elif len(r) > 1:
            sites = ", ".join(f"{p}:{ln}" for p, ln in sorted(r))
            findings.append(Finding(
                CONTRACT, ERROR, "gate-multiple-resolvers",
                f"{g} is resolved in {len(r)} places ({sites}) — "
                "precedence can fork; route every consumer through one "
                "helper",
                field=g, file=r[0][0], line=r[0][1]))
        if g not in flags:
            findings.append(Finding(
                CONTRACT, ERROR, "gate-no-cli-flag",
                f"{g} has no CLI flag — no add_argument call mentions "
                "it, so the gate is env-only and invisible to --help",
                field=g, file=path, line=line))
        gate_flags = flags.get(g, set())
        if not _mentions(inp.lint_sh, g, gate_flags):
            findings.append(Finding(
                CONTRACT, ERROR, "gate-no-smoke",
                f"{g} has no tools/lint.sh smoke block — neither the "
                f"gate nor its flag ({', '.join(sorted(gate_flags)) or _flag_text(g)}) "
                "appears there, so a regression behind the gate ships "
                "unexercised",
                field=g, file=path, line=line))
        if not _mentions(inp.readme, g, gate_flags):
            findings.append(Finding(
                CONTRACT, ERROR, "gate-no-readme",
                f"{g} is not documented in the README (neither the "
                "gate name nor its flag appears)",
                field=g, file=path, line=line))
        if digest_src and re.search(re.escape(g) + r"\b", digest_src):
            findings.append(Finding(
                CONTRACT, ERROR, "gate-in-digest",
                f"{g} appears in {inp.digest_path}:{_DIGEST_FUNC} — "
                "gates toggle how a space is explored, never which "
                "space; a gate in the identity digest makes every "
                "checkpoint unresumable across gate settings",
                field=g, file=inp.digest_path))
    return findings


def _function_source(inp: Inputs, path: str, func: str) -> str:
    src = inp.sources.get(path)
    if src is None:
        return ""
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return ""
    for node in tree.body:
        if isinstance(node, ast.FunctionDef) and node.name == func:
            return ast.get_source_segment(src, node) or ""
    return ""


# --------------------------------------------------------------------------
# obs-schema contract


def _dict_keys(node: ast.AST, named: dict) -> set | None:
    if isinstance(node, ast.Name):
        return named.get(node.id)
    if not isinstance(node, ast.Dict):
        return None
    out = set()
    for k in node.keys:
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            out.add(k.value)
    return out


def parse_schema(schema_src: str) -> tuple:
    """``(allowed, events)`` from obs/events.py's declaration tables:
    ``allowed[event] = _BASE ∪ _REQUIRED[event] ∪ _OPTIONAL[event]``."""
    tree = ast.parse(schema_src)
    named: dict = {}
    req: dict = {}
    opt: dict = {}
    base: set = set()
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        name = node.targets[0].id
        if isinstance(node.value, ast.Dict):
            keys = _dict_keys(node.value, named)
            named[name] = keys
            if name == "_BASE":
                base = keys or set()
            elif name in ("_REQUIRED", "_OPTIONAL"):
                table = req if name == "_REQUIRED" else opt
                for k, v in zip(node.value.keys, node.value.values):
                    if isinstance(k, ast.Constant):
                        table[k.value] = _dict_keys(v, named) or set()
    events = set(req) | set(opt)
    allowed = {ev: base | req.get(ev, set()) | opt.get(ev, set())
               for ev in events}
    return allowed, events


def _obs_contract(inp: Inputs, trees: dict) -> list:
    schema_src = inp.sources.get(inp.schema_path)
    if schema_src is None:
        return []
    allowed, events = parse_schema(schema_src)
    findings = []
    for path in sorted(trees):
        for node in ast.walk(trees[path]):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            ev_arg = None
            if isinstance(f, ast.Name) and f.id == "append_event" and \
                    len(node.args) >= 2:
                ev_arg = node.args[1]
            elif isinstance(f, ast.Attribute) and \
                    f.attr in ("emit", "_emit") and node.args:
                ev_arg = node.args[0]
            if not (isinstance(ev_arg, ast.Constant) and
                    isinstance(ev_arg.value, str)):
                continue
            ev = ev_arg.value
            if ev not in events:
                findings.append(Finding(
                    CONTRACT, ERROR, "obs-unknown-event",
                    f'emission of undeclared event type "{ev}" — not '
                    "in obs/events.py's _REQUIRED/_OPTIONAL tables; "
                    "declare it (with a schema bump if it is new)",
                    field=ev, file=path, line=node.lineno))
                continue
            for kw in node.keywords:
                if kw.arg is None:       # **fields: runtime's job
                    continue
                if kw.arg not in allowed[ev]:
                    findings.append(Finding(
                        CONTRACT, ERROR, "obs-undeclared-field",
                        f'field "{kw.arg}" of event "{ev}" is not in '
                        "the declared schema — a new field must land "
                        "in obs/events.py's tables with a "
                        "SCHEMA_VERSION bump before any site emits it",
                        field=f"{ev}.{kw.arg}", file=path,
                        line=node.lineno))
    return findings


# --------------------------------------------------------------------------
# waiver audit


def _comment_waivers(src: str, path: str) -> list:
    """``(line, kind, comment_text)`` for every ``# lint:`` comment.
    Tokenize-based: strings that merely *mention* a waiver (docstrings,
    the lint passes themselves) are not waivers."""
    out = []
    try:
        toks = tokenize.generate_tokens(io.StringIO(src).readline)
        for tok in toks:
            if tok.type != tokenize.COMMENT or "lint:" not in tok.string:
                continue
            tail = tok.string.split("lint:", 1)[1].strip()
            kind = tail.split()[0].rstrip(":,—-") if tail else ""
            out.append((tok.start[0], kind, tok.string))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass
    return out


def _strip_comment(src: str, line: int) -> str:
    lines = src.splitlines(True)
    i = line - 1
    if 0 <= i < len(lines):
        lines[i] = lines[i].split("#", 1)[0].rstrip() + "\n"
    return "".join(lines)


def _waiver_audit(inp: Inputs) -> list:
    findings = []
    thread_used = threadlint.analyze(inp.sources).used_waivers
    for path in sorted(inp.sources):
        src = inp.sources[path]
        for line, kind, _text in _comment_waivers(src, path):
            if kind not in WAIVER_KINDS:
                findings.append(Finding(
                    CONTRACT, ERROR, "waiver-unknown-kind",
                    f'unknown waiver kind "lint: {kind}" — known kinds '
                    f"are {', '.join(WAIVER_KINDS)}; a misspelled "
                    "waiver suppresses nothing while looking like it "
                    "does",
                    field=kind, file=path, line=line))
                continue
            if kind == "jit-ok":
                stripped = _strip_comment(src, line)
                live = any(f.line == line
                           for f in jitlint.lint_source(stripped, path))
                if not live:
                    findings.append(Finding(
                        CONTRACT, ERROR, "stale-waiver",
                        "`# lint: jit-ok` no longer suppresses "
                        "anything — relinting without it produces no "
                        "finding on this line; remove the waiver",
                        field=kind, file=path, line=line))
            elif kind == "thread-ok":
                if (path, line) not in thread_used:
                    findings.append(Finding(
                        CONTRACT, ERROR, "stale-waiver",
                        "`# lint: thread-ok` no longer suppresses "
                        "anything — the race detector has no finding "
                        "on this line; remove the waiver",
                        field=kind, file=path, line=line))
    return findings


# --------------------------------------------------------------------------
# entry points


def lint_inputs(inp: Inputs) -> list:
    # The lint passes themselves are out of scope for the gate and obs
    # contracts: they *talk about* gates and events (docstring examples,
    # finding codes through their own `_emit` helpers) without producing
    # either.  The waiver audit still covers them.
    scan = Inputs(
        sources={p: s for p, s in inp.sources.items()
                 if not p.startswith("raft_tla_tpu/analysis/")},
        readme=inp.readme, lint_sh=inp.lint_sh,
        schema_path=inp.schema_path, digest_path=inp.digest_path)
    trees = {}
    for path in sorted(scan.sources):
        try:
            trees[path] = ast.parse(scan.sources[path], filename=path)
        except SyntaxError:
            continue                    # pass 3 reports parse errors
    findings = _gate_contract(scan, trees)
    findings += _obs_contract(scan, trees)
    findings += _waiver_audit(inp)
    return findings


def lint_paths(root: str | None = None) -> list:
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))

    def _read(rel: str) -> str:
        p = os.path.join(root, rel)
        if not os.path.exists(p):
            return ""
        with open(p, "r", encoding="utf-8") as fh:
            return fh.read()

    return lint_inputs(Inputs(
        sources=threadlint.package_sources(root),
        readme=_read("README.md"),
        lint_sh=_read(os.path.join("tools", "lint.sh"))))
