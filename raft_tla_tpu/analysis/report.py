"""Findings — the one result type all five analysis passes emit.

A finding is a *claim about the model or its sources*, not a runtime
event: severity ``error`` means the pass could not prove the property it
exists to prove (a width-safety hole, an unresolvable cfg name), severity
``warning`` means a hazard that does not by itself unsound the checker
(a tracer-hostile idiom, a vacuous invariant).  Exit-code policy follows
the split: errors always fail, warnings only under ``--strict`` — so
``python -m raft_tla_tpu.lint runs/MC3s2v.cfg`` exits 0 on a healthy tree
while still printing what it noticed.
"""

from __future__ import annotations

import dataclasses

ERROR = "error"
WARNING = "warning"

# Pass identifiers (the `pass_` field); stable for waiver lists and tests.
WIDTH = "width"      # Pass 1: interval width-safety
CFG = "cfg"          # Pass 2: spec/config lint
JIT = "jit"          # Pass 3: tracer-hazard AST lint
THREAD = "thread"    # Pass 4: static race detector (host threading seams)
CONTRACT = "contract"  # Pass 5: runtime-contract lint (gates, obs schema)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic: which pass, how bad, and where.

    ``transition``/``field``/``interval``/``width`` carry Pass 1's proof
    obligation (the acceptance contract: every overflow is reported with
    all four); ``file``/``line`` locate Pass 2/3 findings in sources.
    """

    pass_: str                      # WIDTH | CFG | JIT | THREAD | CONTRACT
    severity: str                   # ERROR | WARNING
    code: str                       # stable kebab-case id, e.g. "width-overflow"
    message: str
    transition: str | None = None   # action family (Pass 1)
    field: str | None = None        # struct field / packed subfield
    interval: tuple | None = None   # (lo, hi) derived value interval
    width: int | None = None        # allotted bits
    file: str | None = None         # source path (Pass 3) / cfg path (Pass 2)
    line: int | None = None

    def format(self) -> str:
        loc = ""
        if self.file:
            loc = f"{self.file}:{self.line}: " if self.line else f"{self.file}: "
        ctx = []
        if self.transition:
            ctx.append(f"transition={self.transition}")
        if self.field:
            ctx.append(f"field={self.field}")
        if self.interval is not None:
            ctx.append(f"interval=[{self.interval[0]}, {self.interval[1]}]")
        if self.width is not None:
            ctx.append(f"width={self.width}")
        ctx_txt = f" ({', '.join(ctx)})" if ctx else ""
        return f"{loc}{self.severity}[{self.code}]: {self.message}{ctx_txt}"


def has_errors(findings) -> bool:
    return any(f.severity == ERROR for f in findings)


def render(findings, header: str | None = None) -> str:
    lines = [header] if header else []
    lines += [f.format() for f in findings]
    n_err = sum(1 for f in findings if f.severity == ERROR)
    n_warn = len(findings) - n_err
    lines.append(f"{n_err} error(s), {n_warn} warning(s)")
    return "\n".join(lines)


def exit_code(findings, strict: bool = False) -> int:
    if has_errors(findings):
        return 1
    if strict and findings:
        return 1
    return 0
