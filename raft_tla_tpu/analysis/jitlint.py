"""Pass 3 — jit-hazard AST lint over the kernel and engine sources.

Stdlib-``ast`` only (no libcst in the image).  The hazards are the ones
that have actually bitten JAX model-checker kernels:

- ``traced-python-if`` — a Python ``if`` whose test compares elements of
  a traced operand inside a function that manipulates ``jnp``/``lax``
  values: under ``jit`` this either raises ``TracerBoolConversionError``
  or, worse, burns the first call's value into the compiled code.
- ``traced-scalar-cast`` — ``int(...)``/``float(...)`` of a traced
  expression: concretizes the tracer (same failure mode).
- ``set-iteration`` — iterating a set literal / ``set(...)`` call:
  Python set order is salted per process, so any traced computation
  assembled from it compiles a different program per run — a
  nondeterminism source a fingerprint-deduplicating checker cannot
  afford.
- ``narrow-astype`` — ``.astype`` to a sub-32-bit dtype with no width
  justification in a comment on the same line: silent truncation is the
  exact bug class Pass 1 proves away for the packed encodings; ad-hoc
  narrowing must carry its own proof.

Heuristics, not semantics — so every rule is waivable with a
``# lint: jit-ok`` comment on the offending line, and all Pass 3
findings are warnings (exit 0 unless ``--strict``).  Traced-ness is
approximated as "rooted in a parameter of a function whose body
mentions jnp/lax"; tests of ``.shape``/``.ndim``/``len()`` and
``in``/``is`` comparisons are static under jit and never flagged.
"""

from __future__ import annotations

import ast
import os

from raft_tla_tpu.analysis.report import JIT, WARNING, Finding

WAIVER = "lint: jit-ok"

# Default scan set: the whole package.  This used to be a hand-curated
# list of "the jit surface" that new modules had to remember to join;
# every module is in scope now and tests/test_lint.py asserts the walk
# misses nothing (covered_files vs an independent os.walk).
DEFAULT_TARGETS = ("raft_tla_tpu",)

_NARROW_DTYPES = {"int8", "int16", "uint8", "uint16", "bfloat16", "float16",
                  "bool_"}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}


def _mentions_traced(node: ast.AST) -> bool:
    """Does this function's body textually use jnp/lax/jax values?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in ("jnp", "lax", "jax"):
            return True
    return False


def _param_names(fn: ast.AST) -> set:
    a = fn.args
    names = {p.arg for p in a.args + a.posonlyargs + a.kwonlyargs}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    # self/cls are never tracers in this codebase; bounds/xp are static.
    return names - {"self", "cls", "bounds", "xp", "cfg", "config"}


def _root_name(node: ast.AST) -> str | None:
    """The Name at the root of an attribute/subscript/call chain."""
    while True:
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, (ast.Attribute, ast.Subscript, ast.Starred)):
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        else:
            return None


def _is_static_test(node: ast.AST) -> bool:
    """Tests that never touch tracer *values*: shape/ndim/dtype probes,
    len() of containers, identity and membership tests, isinstance."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _STATIC_ATTRS:
            return True
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name) \
                and sub.func.id in ("len", "isinstance", "hasattr",
                                    "callable"):
            return True
        if isinstance(sub, ast.Compare) and any(
                isinstance(op, (ast.In, ast.NotIn, ast.Is, ast.IsNot))
                for op in sub.ops):
            return True
    return False


def _param_subscript_roots(node: ast.AST, params: set) -> set:
    """Parameter names whose *elements* the expression reads (x[i], a
    tracer if x is traced input; a bare `x` name could be a loop bound)."""
    roots = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Subscript):
            r = _root_name(sub.value)
            if r in params:
                roots.add(r)
    return roots


class _FnVisitor(ast.NodeVisitor):
    def __init__(self, path: str, src_lines: list):
        self.path = path
        self.src_lines = src_lines
        self.findings: list = []
        self._fn_stack: list = []

    # -- helpers -------------------------------------------------------------
    def _waived(self, lineno: int) -> bool:
        line = self.src_lines[lineno - 1] if lineno <= len(self.src_lines) \
            else ""
        return WAIVER in line

    def _line_comment(self, lineno: int) -> str:
        line = self.src_lines[lineno - 1] if lineno <= len(self.src_lines) \
            else ""
        idx = line.find("#")
        return line[idx:] if idx >= 0 else ""

    def _emit(self, code: str, message: str, node: ast.AST):
        if self._waived(node.lineno):
            return
        self.findings.append(Finding(
            JIT, WARNING, code, message, file=self.path, line=node.lineno))

    def _in_traced_fn(self) -> bool:
        return bool(self._fn_stack) and self._fn_stack[-1]["traced"]

    def _params(self) -> set:
        return self._fn_stack[-1]["params"] if self._fn_stack else set()

    # -- visitors ------------------------------------------------------------
    def _visit_fn(self, node):
        self._fn_stack.append({
            "traced": _mentions_traced(node),
            "params": _param_names(node),
        })
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_If(self, node: ast.If):
        if self._in_traced_fn() and not _is_static_test(node.test):
            roots = _param_subscript_roots(node.test, self._params())
            if roots:
                self._emit(
                    "traced-python-if",
                    "Python `if` on a value read from traced operand "
                    f"{'/'.join(sorted(roots))}: under jit this raises "
                    "TracerBoolConversionError or bakes in the traced "
                    "value — use jnp.where/lax.cond", node)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        if self._in_traced_fn() and isinstance(node.func, ast.Name) \
                and node.func.id in ("int", "float", "bool") and node.args:
            roots = _param_subscript_roots(node.args[0], self._params())
            if roots:
                self._emit(
                    "traced-scalar-cast",
                    f"{node.func.id}() of a value read from traced operand "
                    f"{'/'.join(sorted(roots))}: concretizes the tracer "
                    "under jit — keep it an array", node)
        self.generic_visit(node)

    def visit_For(self, node: ast.For):
        it = node.iter
        is_set = isinstance(it, ast.Set) or (
            isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
            and it.func.id in ("set", "frozenset"))
        if is_set:
            self._emit(
                "set-iteration",
                "iteration over a set: order is salted per process, so "
                "any program assembled from it differs run to run — "
                "iterate a sorted() or a tuple", node)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute):
        self.generic_visit(node)

    def visit_Constant(self, node):
        pass

    def _check_astype(self, node: ast.Call):
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr == "astype" and node.args):
            return
        arg = node.args[0]
        dtype = None
        if isinstance(arg, ast.Attribute):
            dtype = arg.attr
        elif isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            dtype = arg.value
        if dtype in _NARROW_DTYPES:
            comment = self._line_comment(node.lineno)
            if "bit" not in comment and "width" not in comment \
                    and WAIVER not in comment:
                self._emit(
                    "narrow-astype",
                    f"narrowing .astype({dtype}) without a width comment: "
                    "state a `# <n>-bit ...` justification (or waive) so "
                    "the truncation is provably safe", node)

    def generic_visit(self, node):
        if isinstance(node, ast.Call):
            self._check_astype(node)
        super().generic_visit(node)


def lint_source(src: str, path: str = "<string>") -> list:
    """Lint one source text; returns findings (all warnings)."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding(JIT, WARNING, "syntax-error",
                        f"could not parse: {e.msg}", file=path,
                        line=e.lineno)]
    v = _FnVisitor(path, src.splitlines())
    v.visit(tree)
    return v.findings


def _default_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def covered_files(targets=DEFAULT_TARGETS,
                  root: str | None = None) -> list:
    """Absolute paths the targets resolve to — the lint's actual scan
    set, so coverage can be asserted rather than assumed."""
    if root is None:
        root = _default_root()
    files = []
    for target in targets:
        full = os.path.join(root, target)
        if os.path.isfile(full):
            files.append(full)
        elif os.path.isdir(full):
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"]
                files += [os.path.join(dirpath, f)
                          for f in sorted(filenames)
                          if f.endswith(".py")]
    return sorted(set(files))


def lint_paths(targets=DEFAULT_TARGETS, root: str | None = None) -> list:
    """Lint every .py under the target files/dirs (relative to repo
    root, resolved against this package's parent by default)."""
    if root is None:
        root = _default_root()
    findings = []
    for path in covered_files(targets, root):
        with open(path, "r", encoding="utf-8") as f:
            src = f.read()
        rel = os.path.relpath(path, root)
        findings += lint_source(src, rel)
    return findings
