"""Simulation mode — TLC's ``-simulate``, the TPU way (SURVEY §0: TLC is
the runtime whose capabilities this framework replicates).

Where exhaustive BFS enumerates the bounded state space, simulation mode
samples random *behaviors*: walks from ``Init`` taking uniformly-random
enabled actions, invariants checked on every generated state, up to a depth
bound per behavior, restarting until a behavior quota is met or a violation
is found.  TLC runs one walker; here a **batch of walkers advances in
lockstep inside one jitted segment** — each step vmaps the fused action
expansion (ops/kernels.build_expand) over the whole batch, samples one
enabled lane per walker with ``jax.random``, and records the lane into a
per-walker history ring so a violating walk replays exactly.

Behavior-end rules (TLC semantics):

- **depth bound reached** — behavior complete, walker resets to Init;
- **no enabled action** — with ``check_deadlock`` the run aborts with the
  walk as counterexample (exit 11 at the CLI); otherwise the behavior
  completes and the walker resets;
- **StateConstraint violation** — the successor is still generated and
  invariant-checked (CONSTRAINT gates exploration, not generation), then
  the behavior ends and the walker resets;
- **invariant violation** — the run stops; the trace is reconstructed by
  replaying the recorded lane history through the model's host
  interpreter, so the reported behavior is exact, not approximate.

Determinism: one ``jax.random`` key drives everything; the same seed,
batch size and depth reproduce the same walks bit for bit.

The simulator is model-generic: it drives the registry adapter's
simulation surface (``build_sim_expand`` / ``sim_codec`` /
``jnp_invariants`` / ``jnp_constraint`` / ``host_apply``), so any spec
whose adapter advertises ``"simulate" in engines`` — Raft or a
schema-declared spec like twophase — random-walks through the same
engine.  The host side fetches the carry **once per dispatch** (a single
fused device_get instead of a per-field sync storm) and donates the
walker/history buffers back to the next dispatch; the sharded fleet
engine (``raft_tla_tpu/fleet``) scales the same segment across a device
mesh.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tla_tpu.config import CheckConfig
from raft_tla_tpu.engine import DEADLOCK, Violation

I32 = jnp.int32


@dataclasses.dataclass
class SimResult:
    n_behaviors: int       # completed behaviors (depth/constraint-ended)
    n_states: int          # states generated (not deduplicated)
    max_depth_seen: int
    violation: Optional[Violation]
    wall_s: float

    @property
    def states_per_sec(self) -> float:
        return self.n_states / self.wall_s if self.wall_s > 0 else float("inf")


def _build_sim_segment(config: CheckConfig, walkers: int, depth: int,
                       steps: int, W: int, A: int, model):
    """One jitted dispatch: advance every walker by up to ``steps`` steps."""
    bounds = config.bounds
    n_inv = len(config.invariants)
    expand = model.build_sim_expand(config)
    inv_fns = list(model.jnp_invariants(config))
    con_fn = model.jnp_constraint(bounds)
    _w, pack, unpack = model.sim_codec(bounds)
    BIG = jnp.int32(np.iinfo(np.int32).max)

    def one_step(carry, key, init_vec):
        (vecs, hist, hlen, n_beh, n_st, maxd, viol_w, viol_i, dead_w,
         fail) = carry
        structs = jax.vmap(unpack)(vecs)
        succs, valid, ovf = jax.vmap(expand)(structs)       # [B, A, ...]

        # sample one enabled lane per walker (uniform over enabled), then
        # gather just that lane from the successor tree — packing all A
        # lanes first would do A-fold wasted work in the hot loop.
        logits = jnp.where(valid, 0.0, -jnp.inf)
        lane = jax.random.categorical(key, logits, axis=-1).astype(I32)
        enabled = jnp.any(valid, axis=-1)                   # [B]
        lane = jnp.where(enabled, lane, 0)
        rows = jnp.arange(walkers)
        pick_s = jax.tree.map(lambda x: x[rows, lane], succs)
        pick = jax.vmap(pack)(pick_s)                       # [B, W]
        con_ok = jax.vmap(con_fn)(pick_s)
        # capacity overflow on a taken lane is a soundness bug — loud, never
        # clamped (SURVEY §4.5), like every engine.
        fail = fail | jnp.any(enabled & ovf[rows, lane])
        if inv_fns:
            inv_ok = jnp.stack([jax.vmap(f)(pick_s) for f in inv_fns],
                               axis=-1)                     # [B, nI]
        else:
            inv_ok = jnp.ones((walkers, 0), bool)

        # deadlock: current state has no successor at all
        first_dead = jnp.min(jnp.where(
            ~enabled, jnp.arange(walkers, dtype=I32), BIG))
        new_dead = (first_dead < BIG) & (dead_w < 0) if config.check_deadlock \
            else jnp.bool_(False)
        dead_w = jnp.where(new_dead, first_dead, dead_w)

        # invariant violation among stepped walkers
        bad = enabled & jnp.any(~inv_ok, axis=-1)
        first_bad = jnp.min(jnp.where(bad, jnp.arange(walkers, dtype=I32),
                                      BIG))
        new_viol = (first_bad < BIG) & (viol_w < 0)
        viol_w = jnp.where(new_viol, first_bad, viol_w)
        bidx = jnp.minimum(first_bad, walkers - 1)
        viol_i = jnp.where(
            new_viol,
            jnp.argmax(~inv_ok[bidx], axis=-1).astype(I32) if n_inv
            else jnp.int32(0),
            viol_i)

        # record the step for walkers that moved
        hist = jnp.where(
            (enabled[:, None]) & (jnp.arange(depth)[None, :] == hlen[:, None]),
            lane[:, None], hist)
        hlen2 = jnp.where(enabled, hlen + 1, hlen)
        maxd = jnp.maximum(maxd, jnp.max(hlen2))
        n_st = n_st + jnp.sum(enabled.astype(I32))

        # behavior end: depth bound, constraint-violating successor, or
        # (without check_deadlock) a stuck walker; violating walkers FREEZE
        # so their history stays replayable.
        frozen = (jnp.arange(walkers, dtype=I32) == viol_w) & (viol_w >= 0) \
            | ((jnp.arange(walkers, dtype=I32) == dead_w) & (dead_w >= 0))
        done = (~frozen) & (enabled & (~con_ok | (hlen2 >= depth))
                            | ~enabled)
        n_beh = n_beh + jnp.sum(done.astype(I32))
        init_b = jnp.broadcast_to(init_vec, (walkers, W))
        vecs2 = jnp.where(frozen[:, None], vecs,
                          jnp.where(done[:, None], init_b,
                                    jnp.where(enabled[:, None], pick, vecs)))
        hlen3 = jnp.where(frozen, hlen2, jnp.where(done, 0, hlen2))
        # freeze the violating walker's successor (for completeness we keep
        # the pre-violation vec; the trace replays from history anyway)
        stop = (viol_w >= 0) | (dead_w >= 0)
        stop = stop | fail
        return (vecs2, hist, hlen3, n_beh, n_st, maxd, viol_w, viol_i,
                dead_w, fail), stop

    def segment(key, init_vec, vecs, hist, hlen, n_beh, n_st, maxd):
        viol_w = jnp.int32(-1)
        viol_i = jnp.int32(0)
        dead_w = jnp.int32(-1)
        fail = jnp.bool_(False)
        keys = jax.random.split(key, steps)

        def body(i, carry):
            state, stopped = carry

            def advance(_):
                return one_step(state, keys[i], init_vec)
            return jax.lax.cond(stopped, lambda _: (state, stopped),
                                advance, None)

        carry = ((vecs, hist, hlen, n_beh, n_st, maxd, viol_w, viol_i,
                  dead_w, fail), jnp.bool_(False))
        stfin, _stop = jax.lax.fori_loop(0, steps, body, carry)
        return stfin

    return segment


def resolve_sim_model(config: CheckConfig):
    """The model adapter for a simulation run, or a loud error when the
    spec's adapter has no simulation surface."""
    from raft_tla_tpu.frontend.registry import resolve_model
    model = resolve_model(config.spec)
    if "simulate" not in getattr(model, "engines", ()):
        raise ValueError(
            f"spec {config.spec!r} does not support simulation "
            f"(engines: {', '.join(model.engines)})")
    return model


class Simulator:
    """Batched random-behavior generator for one :class:`CheckConfig`.

    ``fetch`` selects the host-side carry readback: ``"fused"`` (default)
    pulls the whole segment result in one device_get; ``"legacy"`` keeps
    the historical per-field ``bool()``/``int()`` sync storm, retained
    only so ``runs/fleet_ab.py`` can measure the delta honestly.
    """

    def __init__(self, config: CheckConfig, walkers: int = 1024,
                 depth: int = 100, steps_per_dispatch: int = 64,
                 seed: int = 0, fetch: str = "fused"):
        if config.symmetry:
            raise ValueError("simulation mode ignores SYMMETRY; run without")
        if fetch not in ("fused", "legacy"):
            raise ValueError(f"fetch must be 'fused' or 'legacy': {fetch!r}")
        self.config = config
        self.bounds = config.bounds
        self.model = resolve_sim_model(config)
        self.width, _pack, _unpack = self.model.sim_codec(self.bounds)
        self.table = self.model.action_table(self.bounds)
        self.A = len(self.table)
        self.walkers = walkers
        self.depth = depth
        self.steps = steps_per_dispatch
        self.seed = seed
        self.fetch = fetch
        # Donate the walker/history buffers: shapes match the outputs
        # exactly, so off-CPU the dispatch updates them in place instead
        # of holding both generations live.  (CPU has no donation; gate
        # it off there to keep runs warning-free.)
        donate = () if jax.default_backend() == "cpu" else (2, 3, 4)
        self._segment = jax.jit(
            _build_sim_segment(config, walkers, depth, self.steps,
                               self.width, self.A, self.model),
            donate_argnums=donate)

    def run(self, n_behaviors: int,
            init_override=None,
            max_wall_s: float | None = None,
            on_progress=None, events: str | None = None) -> SimResult:
        t0 = time.monotonic()
        # The same telemetry facade the exhaustive engines drive
        # (obs/events.py): one segment record per device dispatch, a
        # run_start/run_end envelope, and the --events JSONL log —
        # replacing the simulator's pre-schema silence.  ``level`` carries
        # the deepest walk seen (the closest analog to a BFS level).
        from raft_tla_tpu.obs import RunTelemetry
        tel = RunTelemetry("simulate", config=self.config,
                           on_progress=on_progress, events=events, t0=t0)
        bounds = self.bounds
        init_py = init_override if init_override is not None \
            else self.model.init_py(bounds)
        init_vec = self.model.to_vec(init_py, bounds)
        tel.run_start()
        for nm in self.config.invariants:
            if not self.model.py_invariant(nm)(init_py, bounds):
                res = SimResult(0, 1, 0,
                                Violation(nm, init_py, [(None, init_py)]),
                                time.monotonic() - t0)
                self._end_telemetry(tel, res, complete=True)
                return res
        iv = jnp.asarray(init_vec, I32)

        key = jax.random.PRNGKey(self.seed)
        vecs = jnp.broadcast_to(jnp.asarray(init_vec, I32),
                                (self.walkers, self.width))
        hist = jnp.zeros((self.walkers, self.depth), I32)
        hlen = jnp.zeros((self.walkers,), I32)
        n_beh = jnp.int32(0)
        n_st = jnp.int32(0)
        maxd = jnp.int32(0)
        while True:
            key, sub = jax.random.split(key)
            (vecs, hist, hlen, n_beh, n_st, maxd, viol_w, viol_i,
             dead_w, fail) = self._segment(sub, iv, vecs, hist, hlen,
                                           n_beh, n_st, maxd)
            if self.fetch == "legacy":
                # the historical per-field sync storm (A/B reference arm)
                failh, nb, nst = bool(fail), int(n_beh), int(n_st)
                mx, vw, vi, dw = (int(maxd), int(viol_w), int(viol_i),
                                  int(dead_w))
            else:
                # one fused device->host fetch per dispatch: every carry
                # scalar materializes in a single blocking transfer.
                failh, nb, nst, mx, vw, vi, dw = (
                    x.item() for x in jax.device_get(
                        (fail, n_beh, n_st, maxd, viol_w, viol_i, dead_w)))
            if failh:
                tel.stop_requested("tensor-encoding overflow",
                                   source="simulate")
                tel.close()
                raise RuntimeError(
                    "simulation aborted: a sampled transition overflowed "
                    "the tensor encoding — bounds reasoning violated "
                    "(config.py capacity scheme)")
            if tel.active:
                tel.segment(nst, mx, nst)
            if vw >= 0 or dw >= 0:
                # If both landed in the same dispatch (different walkers),
                # report the invariant violation — its walker's history is
                # the one we replay, so label and trace must agree.
                w = vw if vw >= 0 else dw
                name = self.config.invariants[vi] if vw >= 0 else DEADLOCK
                trace = self._replay(init_py, np.asarray(hist[w]),
                                     int(hlen[w]))
                res = SimResult(
                    n_behaviors=nb, n_states=nst, max_depth_seen=mx,
                    violation=Violation(name, trace[-1][1], trace),
                    wall_s=time.monotonic() - t0)
                self._end_telemetry(tel, res, complete=True)
                return res
            if nb >= n_behaviors:
                complete = True
                break
            if max_wall_s is not None and \
                    time.monotonic() - t0 > max_wall_s:
                complete = False    # wall-bounded partial run
                break
        res = SimResult(n_behaviors=nb, n_states=nst,
                        max_depth_seen=mx, violation=None,
                        wall_s=time.monotonic() - t0)
        self._end_telemetry(tel, res, complete=complete)
        return res

    def _end_telemetry(self, tel, res: SimResult, complete: bool) -> None:
        """Honest per-field run_end for a statistical run: behaviors,
        sampled transitions and max depth each land in their own field
        (obs schema v3 ``sim`` dict) instead of being aliased through the
        exhaustive-result shape."""
        tel.run_end_sim(
            n_states=res.n_states, n_behaviors=res.n_behaviors,
            max_depth=res.max_depth_seen, wall_s=res.wall_s,
            complete=complete, violation=res.violation,
            sim={"sampled_transitions": res.n_states,
                 "max_depth": res.max_depth_seen,
                 "walkers": self.walkers,
                 "per_invariant": {nm: res.n_states
                                   for nm in self.config.invariants}})
        tel.close()

    def _replay(self, init_py, lanes: np.ndarray, hlen: int) -> list:
        """Rebuild the violating walk exactly through the model's host
        interpreter."""
        chain = [(None, init_py)]
        cur = init_py
        for k in range(hlen):
            a = self.table[int(lanes[k])]
            nxt = self.model.host_apply(cur, a, self.bounds)
            assert nxt is not None, "recorded lane must be enabled on replay"
            chain.append((a.label(), nxt))
            cur = nxt
        return chain


def simulate(config: CheckConfig, n_behaviors: int = 1000, **kw) -> SimResult:
    """One-shot convenience mirroring the engines' ``check``."""
    run_kw = {k: kw.pop(k) for k in ("init_override", "max_wall_s")
              if k in kw}
    return Simulator(config, **kw).run(n_behaviors, **run_kw)
