"""Delayed-duplicate-detection engine — exact dedup on the host (paged v3).

Every prior device engine keeps the EXACT fingerprint set in HBM, which
caps distinct-state capacity at ~2^28 slots (2 GiB single-buffer limit;
the elect5 campaign measured probing degrade as load crossed 0.48 near
130M orbits — RESULTS.md "capacity findings").  This engine removes the
device table from the correctness path entirely, the external-memory
regime TLC itself uses for its `states/` fingerprint set
(`/root/reference/.gitignore:2`):

- **Device: expand + fingerprint only.**  The per-chunk program expands a
  slice of the frontier block, fingerprints the candidates, and pushes a
  *compacted* candidate stream (key, packed row, parent, lane, constraint
  flag) to the host.  The only device state is a **lossy filter table**:
  a bucketized fingerprint cache probed in one gather, inserting with
  overwrite-on-full-bucket instead of FAIL_PROBE.  A filter hit proves
  the key was already streamed (inserts happen only for streamed
  candidates), so hits are dropped on device — that filters the heavy
  recent-duplicate traffic cheaply.  Misses (true new states + evicted
  re-sights) stream to the host.  The filter affects traffic volume only,
  never the verdict: resume even starts it EMPTY.
- **Host: exact dedup in first-occurrence stream order.**  Candidates
  buffer in a pending list; each flush sorts them, keeps each key's first
  occurrence, anti-joins against the sorted master key array
  (`utils/keyset.MasterKeys`), appends the genuinely-new states to the
  native store in stream order, and merges their keys into the master.
  Because the table engines also admit each state at its first occurrence
  in stream order, discovery order — counts, levels, per-action coverage,
  traces — is byte-identical to the oracle and every other engine (the
  parity suite asserts it, including under forced filter eviction).
- **Level-synchronous BFS** keeps counts exact: new states join the next
  level only (frontier blocks stream host→device as in streamed_engine).

Capacity: master keys 8 B/state + packed rows in host RAM (~10^9 states
on this host), no device table in the correctness path — the designed
fix for the elect5 2^28 ceiling (RESULTS.md, runs/northstar_sizing.md).

Violation semantics match refbfs exactly: the candidate stream is
truncated ON DEVICE at the first violating candidate (kept inclusively)
or the first deadlocked row (its successors excluded), so `n_states` and
`n_transitions` stop where the oracle's do.  A violating candidate is
always genuinely new — a previously-seen state with a failing invariant
would have stopped the run at ITS first occurrence — so after a forced
flush the violator is the last appended state (asserted by key).

Checkpoints are fully incremental: rows/links/constraints stream as in
streamed_engine, and the master keys are checkpointed as their
*discovery-order append log* (a width-2 int32 native store) — sorted
back into the master on resume.  Snapshots land at block boundaries with
an empty pending buffer, so resume never re-expands or double-counts.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import time
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tla_tpu.config import CheckConfig
from raft_tla_tpu.device_engine import (
    _EMPTY, BUCKET, FAIL_INDEX, FAIL_LEVEL, FAIL_ROUTE, FAIL_WIDTH,
    aggregate_coverage, decode_fail)
from raft_tla_tpu.engine import DEADLOCK, EngineResult, Violation
from raft_tla_tpu.models import interp, invariants as inv_mod, spec as S
from raft_tla_tpu.obs import RunTelemetry
from raft_tla_tpu.ops import bitpack
from raft_tla_tpu.ops import devdedup
from raft_tla_tpu.ops import kernels
from raft_tla_tpu.ops import state as st
from raft_tla_tpu.ops import symmetry as sym_mod
from raft_tla_tpu.utils import ckpt
from raft_tla_tpu.utils import flushq
from raft_tla_tpu.utils import keyset
from raft_tla_tpu.utils import native
from raft_tla_tpu.utils import pacing
from raft_tla_tpu.utils import prefetch

I32 = jnp.int32
U32 = jnp.uint32

# Discovery-index ceiling.  Round 4 widened the whole id path to int64
# (C++ store links, checkpoint streams, host flush; the DEVICE emits
# block-relative parents that always fit int32 and the host rebases
# them), so the old ~2.13e9 int32 ceiling — which the elect5 campaign
# was measured to hit mid-level-31/32 (VERDICT r3 missing #2) — is
# gone.  The guard remains as a loud absurdity check far past any
# host-RAM-feasible state count.
_IDX_CEIL = 1 << 62


def install_sigint_boundary_stop(eng, stack, boundary="segment") -> None:
    """The runs/campaign_stop.sh contract, shared by the DDD engine
    family: the FIRST SIGINT sets ``eng._sigint``, a flag the engine's
    harvest loop reads next to the deadline check, so the engine stops
    at the next *boundary* (segment for ddd, window for ddd-shard) —
    pending candidates flushed, a snapshot saved when a --checkpoint
    path is configured, and a normal ``complete=False`` EngineResult
    returned (the campaign wrapper then prints its endpoint JSON).
    A SECOND SIGINT restores the previous handler and aborts raw
    (KeyboardInterrupt), for when the graceful path is itself wedged
    behind a dead dispatch.  signal.signal is main-thread-only; off the
    main thread the flag stays False and Ctrl-C keeps its raw meaning.
    The previous handler is restored via ``stack`` on every exit."""
    import signal
    import sys
    import threading
    eng._sigint = False
    if threading.current_thread() is not threading.main_thread():
        return
    prev = signal.getsignal(signal.SIGINT)

    def handler(_signum, _frame):
        if eng._sigint:
            signal.signal(signal.SIGINT, prev)
            raise KeyboardInterrupt
        eng._sigint = True
        print(f"SIGINT: stopping at the next {boundary} boundary "
              "(SIGINT again aborts raw)", file=sys.stderr, flush=True)

    signal.signal(signal.SIGINT, handler)
    stack.callback(signal.signal, signal.SIGINT, prev)


@dataclasses.dataclass(frozen=True)
class DDDCapacities:
    """Static shapes.  ``block``: frontier upload granularity; ``table``:
    lossy filter slots (traffic optimization only — NOT a state-count
    ceiling; keep it SMALL: XLA copies the whole table every chunk
    inside the segment while_loop — gather+scatter on one carry defeats
    its in-place pass — so the filter costs ~45 ns per BYTE of table
    per chunk.  Chip-measured (runs/filter_inengine.out): 2^22 slots
    filter within 0.6% of 2^26's traffic at 9% of the per-chunk cost;
    2^26 was costing 46% of the whole step); ``seg_rows``: device output-buffer rows per segment (a
    segment runs many chunks inside one dispatch and stops early when the
    next chunk might not fit — dispatch round-trips over the deployment
    tunnel cost ~100-300 ms, so per-chunk dispatch is ~10x slower);
    ``flush``: pending candidates per host dedup pass; ``levels``:
    host-side BFS-depth bound; ``route_rows``: >0 switches the chunk
    program to the EP-routed step (kernels.build_step_routed) with that
    many compacted candidate slots per chunk — discovery order is
    engine-identical (the parity suite asserts it), so like ``table``/
    ``seg_rows``/``flush`` it is checkpoint-compatible tuning, not
    digest identity; a chunk with more enabled lanes than slots aborts
    loudly (FAIL_ROUTE)."""

    block: int = 1 << 20
    table: int = 1 << 22
    seg_rows: int = 1 << 19
    flush: int = 1 << 23
    levels: int = 1 << 12
    route_rows: int = 0
    # "full": every state row + trace link retained (traces, liveness
    # exports, reshard).  "frontier": TLC's own campaign regime — RAM
    # holds the master keys only, rows live in disk-backed current+next
    # level files (utils/native.LevelStore), NO trace links (a
    # violation reports the violating state, not a path — TLC -noTrace
    # equivalence).  Lifts both the host-RAM (~76 B/state) and the
    # checkpoint-disk (~68 B/state) ceilings to ~16 B/state, the
    # difference between a ~1.5e9 and a ~7e9 state capacity on this
    # host.  Retention is NOT checkpoint identity (the npz records the
    # format; a full-format snapshot migrates on first frontier resume).
    retention: str = "full"
    # Frontier mode only: retain ALL level files instead of deleting
    # pre-frontier ones — TLC's own disk regime (its states/ dir keeps
    # every level), which restores FULL counterexample traces via
    # backward re-search (frontier_backtrace) at ~rows-stream disk cost
    # (~P*4 B/state).  Checkpoint-compatible tuning, not digest
    # identity: flipping it mid-campaign only changes which files are
    # garbage-collected.
    keep_levels: bool = False

    def __post_init__(self):
        if self.retention not in ("full", "frontier"):
            raise ValueError(f"retention={self.retention!r}")
        for nm in ("block", "table"):
            v = getattr(self, nm)
            if v & (v - 1):
                raise ValueError(f"{nm}={v} must be a power of two")
        if self.table < BUCKET:
            raise ValueError(
                f"table={self.table} must be >= one bucket ({BUCKET})")
        if self.route_rows < 0:
            raise ValueError(f"route_rows={self.route_rows} must be >= 0")


@dataclasses.dataclass(frozen=True)
class _DigestCaps:
    """Checkpoint-identity view of DDDCapacities: only fields that change
    what a snapshot MEANS join the digest.  ``block`` denominates
    ``blocks_done``; ``levels`` bounds the search.  ``table`` (lossy
    filter), ``seg_rows`` and ``flush`` provably cannot affect discovery
    order or any checkpointed byte, so tuning them mid-campaign must not
    orphan a multi-hour snapshot.  Defaults mirror DDDCapacities so
    default-valued fields keep dropping out of the digest (_stable).
    Introducing this class rotated the digest once (the class NAME joins
    the _stable tuple); no snapshot predating it existed outside tests."""

    block: int = 1 << 20
    levels: int = 1 << 12


class FilterCarry(NamedTuple):
    """The only serial device state between segments: the lossy filter
    and the chunk cursor.  Everything else is per-segment output, which
    is what makes the two-deep segment pipeline possible — segment k+1
    depends on k only through this carry, so it can be dispatched before
    k's outputs are harvested."""

    tbl_hi: jax.Array     # [TB, BUCKET] lossy filter (donated through)
    tbl_lo: jax.Array
    c: jax.Array          # chunk cursor within the current block


class SegBufs(NamedTuple):
    """One segment's candidate-stream output buffers (donated; the
    engine ping-pongs two sets so one can transfer/flush on the host
    while the device fills the other)."""

    okey_hi: jax.Array    # [OCAP]
    okey_lo: jax.Array
    orows: jax.Array      # [OCAP, P] bit-packed successor rows
    opar: jax.Array       # [OCAP] parent id, BLOCK-RELATIVE (int32-
                          # safe at any depth; harvest adds block start)
    olane: jax.Array      # [OCAP] action lane
    ocon: jax.Array       # [OCAP] constraint flag


class SegStats(NamedTuple):
    cursor: jax.Array     # streamed rows this segment (output fill)
    n_valid: jax.Array    # transitions counted (truncated at violation)
    fail: jax.Array       # FAIL_WIDTH / FAIL_ROUTE bits
    viol_kind: jax.Array  # 0 none / 1 invariant / 2 deadlock
    viol_inv: jax.Array   # invariant index (kind 1)
    dead_g: jax.Array     # kind 2: dead state's discovery index
    steps: jax.Array      # chunks executed (pacer signal)
    done: jax.Array       # block exhausted
    peak: jax.Array       # max live enabled lanes in any chunk — the
                          # route_rows sizing signal (both step shapes)


class _SegCarry(NamedTuple):
    """Internal while_loop carry (FilterCarry + SegBufs + SegStats
    scalars)."""

    tbl_hi: jax.Array
    tbl_lo: jax.Array
    okey_hi: jax.Array
    okey_lo: jax.Array
    orows: jax.Array
    opar: jax.Array
    olane: jax.Array
    ocon: jax.Array
    cursor: jax.Array
    n_valid: jax.Array
    fail: jax.Array
    viol_kind: jax.Array
    viol_inv: jax.Array
    dead_g: jax.Array
    c: jax.Array
    peak: jax.Array


def save_ddd_snapshot(path, host, constore, keystore, n_states, n_trans,
                      cov, level_ends, blocks_done, P, digest) -> None:
    """ONE definition site for the DDD four-stream snapshot format
    (.rows/.links/.con/.keys + metadata npz) — the single-chip and
    mesh-sharded DDD engines interoperate on it byte-for-byte
    (parallel/ddd_shard_engine.reshard_ddd_checkpoint migrates campaigns
    between them), so the writer must not fork."""
    ckpt.stream_rows_append(path + ".rows", host.read, n_states, P)

    def links_reader(start, n):
        # int64 parents as (lo, hi) int32 words + lane: width-3 rows.
        # (The pre-round-4 format was width-2 int32 (parent, lane);
        # load_ddd_snapshot dual-reads it, and stream_rows_append's
        # width check turns the first post-widening snapshot of an old
        # campaign into one full .links rewrite — the migration.)
        par, lan = host.read_links(start, n)
        pu = par.astype(np.int64).view(np.uint64)
        return np.stack(
            [(pu & np.uint64(0xFFFFFFFF)).astype(np.uint32).view(np.int32),
             (pu >> np.uint64(32)).astype(np.uint32).view(np.int32),
             lan.astype(np.int32)], axis=1)

    ckpt.stream_rows_append(path + ".links", links_reader, n_states, 3)
    ckpt.stream_rows_append(path + ".con", constore.read, n_states, 1)
    ckpt.stream_rows_append(path + ".keys", keystore.read, n_states, 2)
    ckpt.atomic_savez(
        path,
        n_states=np.int64(n_states),
        n_trans=np.uint64(n_trans),
        cov=np.asarray(cov, np.int64),
        level_ends=np.asarray(level_ends, np.int64),
        blocks_done=np.int64(blocks_done),
        config_digest=np.uint64(digest))


def load_ddd_snapshot(path, P, digest):
    """Counterpart reader: rebuilds the native stores from the streams
    (master keys are engine-specific and rebuilt by the caller)."""
    with ckpt.load_npz_checked(path, digest) as z:
        n_states = int(z["n_states"])
        n_trans = int(z["n_trans"])
        cov = np.asarray(z["cov"], np.int64).copy()
        level_ends = [int(x) for x in z["level_ends"]]
        blocks_done = int(z["blocks_done"])
    host = native.make_store(P)
    constore = native.make_store(1)
    keystore = native.make_store(2)
    ckpt.stream_rows_in(path + ".rows", host.append, n_states,
                        expect_width=P)

    def links_in_w3(blk):
        par = (blk[:, 0].view(np.uint32).astype(np.uint64)
               | (blk[:, 1].view(np.uint32).astype(np.uint64)
                  << np.uint64(32))).view(np.int64)
        host.append_links(par, blk[:, 2])

    if ckpt.stream_width(path + ".links") == 2:
        # pre-round-4 snapshot: int32 (parent, lane) — widen on read
        ckpt.stream_rows_in(
            path + ".links",
            lambda blk: host.append_links(blk[:, 0].astype(np.int64),
                                          blk[:, 1]),
            n_states, expect_width=2)
    else:
        ckpt.stream_rows_in(path + ".links", links_in_w3, n_states,
                            expect_width=3)
    ckpt.stream_rows_in(path + ".con", constore.append, n_states,
                        expect_width=1)
    ckpt.stream_rows_in(path + ".keys", keystore.append, n_states,
                        expect_width=2)
    return (host, constore, keystore, n_states, n_trans, cov, level_ends,
            blocks_done)


def save_frontier_snapshot(path, rows_ls, con_ls, keystore, n_states,
                           n_trans, cov, level_ends, blocks_done,
                           digest, keep_levels: bool = False) -> None:
    """Frontier-retention snapshots: the level files and the keys
    stream ARE the store, so a snapshot is three syncs + the metadata
    npz + post-commit cleanup of pre-frontier level files (skipped
    under ``keep_levels``: retained levels feed frontier_backtrace) —
    no stream copying at any state count."""
    rows_ls.sync()
    con_ls.sync()
    keystore.sync()
    ckpt.atomic_savez(
        path,
        n_states=np.int64(n_states),
        n_trans=np.uint64(n_trans),
        cov=np.asarray(cov, np.int64),
        level_ends=np.asarray(level_ends, np.int64),
        blocks_done=np.int64(blocks_done),
        retention=np.bytes_(b"frontier"),
        config_digest=np.uint64(digest))
    if not keep_levels:
        rows_ls.delete_old()
        con_ls.delete_old()


def load_frontier_snapshot(path, P, digest):
    """Open a frontier-format snapshot IN PLACE (no copying); also
    migrates a full-format snapshot (no ``retention`` field in the
    npz): the retained level window is sliced out of the old .rows/.con
    streams into level files, the keys stream is renamed (formats
    coincide), and the old full streams are REMOVED — a 983M-state
    campaign checkpoint shrinks by the dead-prefix ~56 B/state."""
    with ckpt.load_npz_checked(path, digest) as z:
        n_states = int(z["n_states"])
        n_trans = int(z["n_trans"])
        cov = np.asarray(z["cov"], np.int64).copy()
        level_ends = [int(x) for x in z["level_ends"]]
        blocks_done = int(z["blocks_done"])
        is_frontier = "retention" in z.files
    L = len(level_ends)
    lvl_lo = level_ends[-2] if L > 1 else 0
    lvl_hi = level_ends[-1]
    if not is_frontier:
        _migrate_full_to_frontier(path, P, n_states, n_trans, cov,
                                  level_ends, blocks_done, lvl_lo,
                                  lvl_hi, L, digest)
    else:
        # idempotent leftover cleanup: a crash between the migration's
        # npz commit and its stream deletions leaves full streams behind
        for suf in (".rows", ".links", ".con"):
            try:
                os.remove(path + suf)
            except FileNotFoundError:
                pass
    rows_ls = native.LevelStore(path + ".rows", P, L, lvl_lo, lvl_hi)
    con_ls = native.LevelStore(path + ".con", 1, L, lvl_lo, lvl_hi)
    keystore = native.FileStore(path + ".keys", 2, 0)
    if len(keystore) < n_states:
        raise ValueError(
            f"key stream holds {len(keystore)} rows, metadata expects "
            f"{n_states} — torn snapshot")
    # a crash between keystore.sync() and the npz commit leaves the key
    # stream LONGER than the metadata: truncate, or post-resume appends
    # land past a stale gap and every key row misaligns from its state
    keystore.trim(n_states)
    rows_ls.trim_next(n_states)
    con_ls.trim_next(n_states)
    if len(rows_ls.cur) != lvl_hi or len(rows_ls) != n_states:
        raise ValueError(
            f"frontier level files hold [{rows_ls.cur.base}, "
            f"{len(rows_ls.cur)}) + [{rows_ls.nxt.base}, {len(rows_ls)}),"
            f" metadata expects [{lvl_lo}, {lvl_hi}) + {n_states} — "
            "torn snapshot")
    return (rows_ls, con_ls, keystore, n_states, n_trans, cov,
            level_ends, blocks_done)


def _mmap_rows(path: str, width: int):
    """Read-only view of a committed FileStore stream.  Never opens the
    file writable (FileStore's own open truncates to the header count,
    which must not happen to a retained level file)."""
    hdr = np.fromfile(path, np.int64, 2)
    if hdr.shape[0] != 2 or int(hdr[1]) != width:
        raise ValueError(f"{path}: not a width-{width} row stream")
    n = int(hdr[0])
    if n == 0:
        return np.zeros((0, width), np.int32)
    return np.memmap(path, np.int32, mode="r", offset=16,
                     shape=(n, width))


def frontier_backtrace(config, schema, lay, bounds, table, prefix,
                       level_ends, n_states, viol_g, keystore):
    """TLC-equivalent counterexample reconstruction in frontier mode.

    TLC's external-memory regime still produces full error traces: its
    ``states/`` directory retains every BFS level and a violation
    triggers a backward predecessor search over them.  Same algorithm
    here (VERDICT r4 missing #3): re-expand level file L(t-1) through
    the SAME fused step the forward search ran — fingerprints match
    bit-exactly, symmetry/view included — scanning for any predecessor
    of the current target key; repeat down to Init.  BFS level
    minimality makes any such chain a shortest counterexample, exactly
    like the trace links the full-retention mode stores.

    Requires the retained level files of ``DDDCapacities.keep_levels``
    (default off: a campaign-scale rows stream can exceed the disk);
    returns ``[(action_label, py_state), ...]`` from Init to the
    violator, or ``None`` when any needed level file is absent.
    """
    import bisect
    P = schema.P
    K = len(level_ends)

    def file_of(g):     # level file L{i} index holding global row g
        return bisect.bisect_right(level_ends, g) + 1

    def span_of(i):     # global [start, end) of level file L{i}
        lo = level_ends[i - 2] if i >= 2 else 0
        hi = level_ends[i - 1] if i - 1 < K else n_states
        return lo, hi

    tf = file_of(int(viol_g))
    if not all(os.path.exists(f"{prefix}.rowsL{i}")
               and os.path.exists(f"{prefix}.conL{i}")
               for i in range(1, tf + 1)):
        return None

    A = len(table)
    B = config.chunk
    step = kernels.build_step(config.bounds, config.spec, (),
                              config.symmetry, view=config.view)

    @jax.jit
    def match(fbuf, fcon, nrows, tgt_hi, tgt_lo):
        vecs = schema.unpack(fbuf, jnp)
        out = step(vecs)
        act = (jnp.arange(B, dtype=I32) < nrows) & fcon
        hit = (out["valid"] & act[:, None]
               & (out["fp_hi"] == tgt_hi) & (out["fp_lo"] == tgt_lo))
        flat = hit.reshape(-1)
        return jnp.any(flat), jnp.argmax(flat)

    def unpack_state(fi, g):
        lo, _ = span_of(fi)
        rows = _mmap_rows(f"{prefix}.rowsL{fi}", P)
        row = schema.unpack(np.asarray(rows[g - lo]), np)
        return interp.from_struct(st.unpack(row, lay, np), bounds)

    rev = []                      # [(label_into_state, py)] backwards
    tgt_g = int(viol_g)
    while True:
        fi = file_of(tgt_g)
        py = unpack_state(fi, tgt_g)
        if fi == 1:
            rev.append((None, py))
            break
        kw = keystore.read(tgt_g, 1).view(np.uint32)
        tgt_lo, tgt_hi = np.uint32(kw[0, 0]), np.uint32(kw[0, 1])
        plo, phi = span_of(fi - 1)
        rows = _mmap_rows(f"{prefix}.rowsL{fi - 1}", P)
        cons = _mmap_rows(f"{prefix}.conL{fi - 1}", 1)
        hitg = None
        for b in range(plo, phi, B):
            n = min(B, phi - b)
            blk = np.asarray(rows[b - plo:b - plo + n])
            con = np.asarray(cons[b - plo:b - plo + n])[:, 0] != 0
            if n < B:
                blk = np.concatenate(
                    [blk, np.zeros((B - n, P), np.int32)])
                con = np.concatenate([con, np.zeros(B - n, bool)])
            found, idx = match(jnp.asarray(blk), jnp.asarray(con),
                               jnp.int32(n), jnp.uint32(tgt_hi),
                               jnp.uint32(tgt_lo))
            if bool(found):
                idx = int(idx)
                hitg = b + idx // A
                rev.append((table[idx % A].label(), py))
                break
        if hitg is None:
            raise RuntimeError(
                f"frontier backtrace: no predecessor of state {tgt_g} "
                f"in level file L{fi - 1} — level-file corruption or a "
                "kernel/dedup soundness bug")
        tgt_g = hitg
    rev.reverse()
    return rev


def _migrate_full_to_frontier(path, P, n_states, n_trans, cov,
                              level_ends, blocks_done, lvl_lo, lvl_hi,
                              L, digest):
    """One-way, one-time: slice the retained window out of a
    full-format snapshot's streams into level files, verify the copies,
    COMMIT a frontier-format metadata npz, and only then delete the
    full .rows/.links/.con (the keys stream is format-identical and
    stays).  Every crash window re-runs safely: before the npz commit
    the old npz + full streams are intact (level files rewrite from
    scratch); after it, the loader takes the frontier path and removes
    stream leftovers idempotently.

    ``.links`` is deleted FIRST: the frontier format never reads it,
    and at campaign scale that frees the gigabytes the level-file
    slices are about to need (the 983M-orbit checkpoint then migrates
    within ~15 GB of transient headroom instead of ~22).  A crash after
    that point only forecloses resuming this snapshot in FULL retention
    (which the caller just chose to leave); frontier re-migration is
    unaffected."""
    try:
        os.remove(path + ".links")
    except FileNotFoundError:
        pass
    for prefix, width, reader_path in ((".rows", P, path + ".rows"),
                                       (".con", 1, path + ".con")):
        with open(reader_path, "rb") as f:
            have, w = (int(x) for x in np.fromfile(f, np.int64, 2))
            if w != width or have < n_states:
                raise ValueError(
                    f"{reader_path}: width {w} rows {have}, expected "
                    f"width {width} >= {n_states} rows")

            def slice_to(dst_path, base, end):
                fs = native.FileStore(dst_path, width, base, reset=True)
                step = 1 << 20
                for s0 in range(base, end, step):
                    n = min(step, end - s0)
                    f.seek(16 + s0 * width * 4)
                    fs.append(np.fromfile(f, np.int32, n * width)
                              .reshape(n, width))
                fs.sync()
                fs.close()

            slice_to(f"{path}{prefix}L{L}", lvl_lo, lvl_hi)
            slice_to(f"{path}{prefix}L{L + 1}", lvl_hi, n_states)

            # verify BEFORE the source streams are removed below — the
            # full streams are the only copy of the campaign's history
            rng = np.random.default_rng(0)
            for dst, base, end in ((f"{path}{prefix}L{L}", lvl_lo,
                                    lvl_hi),
                                   (f"{path}{prefix}L{L + 1}", lvl_hi,
                                    n_states)):
                fs = native.FileStore(dst, width, base)
                if len(fs) != end:
                    raise RuntimeError(
                        f"migration wrote {len(fs)} != {end} rows to "
                        f"{dst} — full streams left untouched")
                for s0 in ([base, max(base, end - 7)]
                           + [int(x) for x in rng.integers(
                               base, max(end - 7, base + 1), 8)]
                           if end > base else []):
                    n = min(7, end - s0)
                    f.seek(16 + s0 * width * 4)
                    want = np.fromfile(f, np.int32, n * width) \
                        .reshape(n, width)
                    if not np.array_equal(fs.read(s0, n), want):
                        raise RuntimeError(
                            f"migration verification mismatch at row "
                            f"{s0} of {dst} — full streams left "
                            "untouched")
                fs.close()
    ckpt.atomic_savez(
        path,
        n_states=np.int64(n_states),
        n_trans=np.uint64(n_trans),
        cov=np.asarray(cov, np.int64),
        level_ends=np.asarray(level_ends, np.int64),
        blocks_done=np.int64(blocks_done),
        retention=np.bytes_(b"frontier"),
        config_digest=np.uint64(digest))
    for suf in (".rows", ".links", ".con"):
        try:
            os.remove(path + suf)
        except FileNotFoundError:
            pass


def frontier_checkpoint_setup(resume, checkpoint, checkpoint_every_s,
                              cleanup, prefix):
    """The frontier checkpoint-path contract, ONE definition for both
    DDD engines (single-chip + mesh): in-place resume mapping, tmpdir
    creation with cleanup registered on the caller's ExitStack, and the
    resume==checkpoint requirement — which must be enforced BEFORE
    load_checkpoint because the full->frontier migration rewrites the
    RESUME path's files.  Returns (checkpoint, checkpoint_every_s,
    tmpdir); ``tmpdir is not None`` is the ONLY sound gate for deleting
    level files at rotation (nothing can resume a tmpdir run)."""
    tmpdir = None
    if resume and not checkpoint:
        checkpoint = resume              # frontier resumes in place
    if not checkpoint:
        import shutil
        import tempfile
        tmpdir = tempfile.mkdtemp(prefix=prefix,
                                  dir=os.environ.get("TMPDIR", "."))
        cleanup.callback(
            lambda d=tmpdir: shutil.rmtree(d, ignore_errors=True))
        checkpoint_every_s = float("inf")
        checkpoint = os.path.join(tmpdir, "run")
    if resume and os.path.abspath(resume) != os.path.abspath(checkpoint):
        raise ValueError(
            "frontier mode resumes in place: --checkpoint must equal "
            "--resume (the level files are the store)")
    return checkpoint, checkpoint_every_s, tmpdir


# Per-call compacted-insert budget: only streamed keys reach the table
# scatter (typically a few thousand of the N=chunk*A candidates — 3.7k
# at flagship shapes, runs/filter_anatomy.out), and a chunk streaming
# more than this simply drops the excess INSERTS — the key still
# streams to the host, so exactness is untouched and the only cost is
# re-sighted traffic.  Chip-measured (runs/scatter_menu.out +
# runs/filter_inengine.out): TPU scatter cost is per-UPDATE (~80 ns)
# regardless of how few updates really write (mode="drop" masking is
# not free), so compacting 172k masked updates to 16k is the win; a
# combined [TB, BUCKET, 2] table layout that would fix this with one
# row scatter was measured SLOWER in-engine (rank-3 minor-dim-2 layout
# wrecks the probe gather) and rejected.
_S_INS = 1 << 14


def _filter_insert(tbl_hi, tbl_lo, key_hi, key_lo, active):
    """Lossy one-gather filter probe + compacted insert.

    Returns ``(tbl_hi, tbl_lo, stream)`` where ``stream[c]`` is True iff
    candidate c is active, is the first active candidate carrying its key
    in this batch (same two-sort first-occurrence pass as
    device_engine._dedup_insert stage 1), and its key is NOT in the
    filter — bit-identical stream semantics to the rounds-1-3
    implementation (discovery order never depends on filter contents: a
    filter hit proves the key already streamed, so the parity argument
    is insert-policy-independent).

    Inserts: first empty slot, else overwrite the key-hashed slot —
    eviction, the ``_S_INS`` compaction budget, and the in-batch
    (bucket, slot) dedup below only widen the stream (the host dedups
    exactly), they never drop a state.  The hi and lo words scatter
    with IDENTICAL compacted index vectors, and those vectors are made
    DUPLICATE-FREE before the scatters: rounds 1-4 relied on XLA
    applying duplicate-index updates in operand order identically in
    both set() ops (implementation-defined — a drift could fuse a
    fabricated (hiA, loB) "chimera" key that aliases a never-streamed
    candidate and silently drops a state, VERDICT r4 weak #3).  Keeping
    only the first insert per (bucket, slot) per batch removes the
    reliance outright; the loser key simply isn't remembered and may
    re-stream later, which the host dedups.
    """
    BA = key_hi.shape[0]
    TB, Sb = tbl_hi.shape
    bmask = jnp.uint32(TB - 1)
    skh = jnp.where(active, key_hi, _EMPTY)
    skl = jnp.where(active, key_lo, _EMPTY)
    perm = jnp.lexsort((skl, skh))       # stable: ties keep stream order
    ph, pl, pa = key_hi[perm], key_lo[perm], active[perm]
    same_as_prev = jnp.concatenate([
        jnp.zeros((1,), bool),
        (ph[1:] == ph[:-1]) & (pl[1:] == pl[:-1]) & pa[1:] & pa[:-1]])
    first_of_key = jnp.zeros((BA,), bool).at[perm].set(~same_as_prev)
    probe = active & first_of_key

    bidx = (key_lo & bmask).astype(I32)
    row_hi, row_lo = tbl_hi[bidx], tbl_lo[bidx]          # [BA, Sb] gather
    seen = jnp.any((row_hi == key_hi[:, None])
                   & (row_lo == key_lo[:, None]), axis=1)
    stream = probe & ~seen
    slot_empty = (row_hi == _EMPTY) & (row_lo == _EMPTY)
    has_empty = jnp.any(slot_empty, axis=1)
    evict = (key_hi % jnp.uint32(Sb)).astype(I32)
    wslot = jnp.where(has_empty, jnp.argmax(slot_empty, axis=1), evict)

    # compact the streamed inserts (stable: stream-first, batch order),
    # then scatter only S updates instead of BA
    S = min(_S_INS, BA)
    sel = jnp.argsort(~stream, stable=True)[:S]
    ok = stream[sel]
    wb = jnp.where(ok, bidx[sel], TB)            # TB row = dropped
    ws = wslot[sel]
    # in-batch (bucket, slot) dedup: duplicate-free scatter indices have
    # no update-order semantics to rely on (see docstring)
    lin = wb * Sb + ws
    order = jnp.argsort(lin, stable=True)
    dup = jnp.concatenate(
        [jnp.zeros((1,), bool), lin[order][1:] == lin[order][:-1]])
    wb = jnp.where(jnp.zeros((S,), bool).at[order].set(~dup), wb, TB)
    tbl_hi = tbl_hi.at[wb, ws].set(key_hi[sel], mode="drop")
    tbl_lo = tbl_lo.at[wb, ws].set(key_lo[sel], mode="drop")
    return tbl_hi, tbl_lo, stream


def _build_segment(config: CheckConfig, caps: DDDCapacities, A: int,
                   W: int, schema: bitpack.BitSchema):
    """One dispatch = up to ``budget`` chunks via ``lax.while_loop``,
    compacting every chunk's candidate stream into the segment output
    buffers at a running cursor.  The loop stops when the block is done,
    the next chunk might overflow the output buffers, a violation or
    failure is flagged, or the budget is spent."""
    B = config.chunk
    N = B * A
    routed = caps.route_rows > 0
    NK = caps.route_rows if routed else N   # max streamed rows per chunk
    OCAP = caps.seg_rows
    if OCAP < NK:
        raise ValueError(
            f"seg_rows={OCAP} must be >= per-chunk candidate rows = {NK}")
    n_inv = len(config.invariants)
    # Both step flavors share _step_stages, so the orbit-scan variants
    # (prescan ladder, sig-prune coset scan) resolve from their env
    # gates here at build time — set RAFT_TLA_SIGPRUNE before
    # constructing the engine; keys are bit-identical either way.
    if routed:
        step = kernels.build_step_routed(
            config.bounds, config.spec, tuple(config.invariants),
            config.symmetry, k_rows=caps.route_rows, view=config.view)
    else:
        step = kernels.build_step(config.bounds, config.spec,
                                  tuple(config.invariants), config.symmetry,
                                  view=config.view)
    BIG = jnp.int32(np.iinfo(np.int32).max)

    def chunk_body(carry: _SegCarry) -> _SegCarry:
        (tbl_hi, tbl_lo, okey_hi, okey_lo, orows, opar, olane, ocon,
         cursor, n_valid_a, fail, viol_kind, viol_inv, dead_g, c,
         peak) = carry
        r0 = c * B
        rows_b = r0 + jnp.arange(B, dtype=I32)
        row_act = rows_b < block_rows
        bidx = jnp.minimum(rows_b, caps.block - 1)
        vecs = schema.unpack(fbuf[bidx], jnp)
        row_ok = row_act & fcon[bidx]
        out = step(vecs, row_ok) if routed else step(vecs)
        valid = out["valid"] & row_ok[:, None]
        fvalid = valid.reshape(-1)
        iota = jnp.arange(N, dtype=I32)

        # Normalize both step shapes to one candidate stream of NK rows
        # in flat (b*A + a) order: ``src`` = flat source lane, ``order``
        # = flat position for refbfs-exact truncation, ``cand_act`` =
        # live candidate.  Dense: the full N-lane grid.  Routed: the
        # step's compacted slots (already row_ok-masked — only live
        # rows' lanes consume routing budget).
        peak = jnp.maximum(peak, out["n_en"] if routed
                           else jnp.sum(fvalid.astype(I32)))
        if routed:
            cidx = out["cidx"]
            src = jnp.minimum(cidx, N - 1)
            cand_act = out["cvalid"]
            order = cidx
            kh, kl = out["cfp_hi"], out["cfp_lo"]
            inv_ok_rows = out["cinv_ok"]
            ovf_rows = out["overflow"].reshape(-1)[src]
            con_rows = out["ccon_ok"]
            word_rows = out["csvecs"]
            route_ovf = out["route_ovf"]
        else:
            src = iota
            cand_act = fvalid
            order = iota
            kh = out["fp_hi"].reshape(-1)
            kl = out["fp_lo"].reshape(-1)
            inv_ok_rows = out["inv_ok"].reshape(N, n_inv)
            ovf_rows = out["overflow"].reshape(-1)
            con_rows = out["con_ok"].reshape(-1)
            word_rows = out["svecs"].reshape(N, W)
            route_ovf = jnp.bool_(False)

        # refbfs-exact truncation: first invariant violation (violator
        # kept) vs first dead row (its and later rows' candidates cut),
        # ordered the way streamed_engine orders them (flat candidate
        # position vs drow * A)
        inv_bad = cand_act & jnp.any(~inv_ok_rows, axis=-1) if n_inv \
            else jnp.zeros((NK,), bool)
        first_inv = jnp.min(jnp.where(inv_bad, order, BIG))
        if config.check_deadlock:
            dead = row_act & fcon[bidx] & ~jnp.any(out["valid"], axis=1)
            drow = jnp.min(jnp.where(dead, jnp.arange(B, dtype=I32), BIG))
            dpos = jnp.where(drow < BIG // A, drow * A, BIG)
        else:
            drow = BIG
            dpos = BIG
        use_dead = dpos < first_inv
        has_inv = (first_inv < BIG) & ~use_dead
        cut_incl = jnp.where(use_dead, dpos - 1,
                             jnp.where(first_inv < BIG, first_inv, BIG))
        keep = order <= cut_incl
        kvalid = cand_act & keep
        n_valid_a = n_valid_a + jnp.sum(kvalid.astype(I32))
        fail = fail | jnp.any(kvalid & ovf_rows).astype(I32) * FAIL_WIDTH

        tbl_hi, tbl_lo, stream = _filter_insert(tbl_hi, tbl_lo, kh, kl,
                                                kvalid)
        pos = cursor + jnp.cumsum(stream.astype(I32)) - 1
        sl = jnp.where(stream, pos, OCAP)
        svecs = schema.pack(word_rows, jnp)
        okey_hi = okey_hi.at[sl].set(kh, mode="drop")
        okey_lo = okey_lo.at[sl].set(kl, mode="drop")
        orows = orows.at[sl].set(svecs, mode="drop")
        # BLOCK-RELATIVE parent (always fits int32 regardless of how
        # deep the campaign is); the harvest rebases to the global int64
        # discovery index by adding the block start on the host
        opar = opar.at[sl].set(r0 + src // A, mode="drop")
        olane = olane.at[sl].set(src % A, mode="drop")
        ocon = ocon.at[sl].set(con_rows, mode="drop")
        cursor = cursor + jnp.sum(stream.astype(I32))

        viol_kind = jnp.where(use_dead, 2, jnp.where(has_inv, 1, 0)) \
            .astype(I32)
        # A detected invariant violation outranks a routing overflow:
        # compaction keeps the FIRST K enabled lanes in flat order, so
        # every dropped lane lies past the detected violator — beyond
        # the truncation cut the dense engine applies anyway — and the
        # emitted stream is already dense-exact.  A deadlock cut (or no
        # detection at all) may have lost pre-cut candidates: abort.
        fail = fail | (route_ovf & (viol_kind != 1)).astype(I32) \
            * FAIL_ROUTE
        viol_inv_c = jnp.argmax(~inv_ok_rows[
            jnp.argmin(jnp.where(inv_bad, order, BIG))]) \
            if n_inv else jnp.int32(0)
        dead_g = jnp.where(                 # block-relative, as opar
            use_dead, r0 + jnp.minimum(drow, B - 1), dead_g)
        return _SegCarry(tbl_hi, tbl_lo, okey_hi, okey_lo, orows, opar,
                         olane, ocon, cursor, n_valid_a, fail, viol_kind,
                         viol_inv_c.astype(I32), dead_g, c + 1, peak)

    def cond(sc):
        s, carry = sc
        n_chunks = (block_rows + B - 1) // B
        return ((carry.c < n_chunks) & (carry.viol_kind == 0)
                & (carry.fail == 0) & (s < budget)
                & (carry.cursor + NK <= OCAP))

    def body(sc):
        s, carry = sc
        return s + 1, chunk_body(carry)

    def segment(fc, bufs, fbuf_, fcon_, budget_, block_rows_):
        nonlocal fbuf, fcon, budget, block_rows
        fbuf, fcon = fbuf_, fcon_
        budget = budget_
        block_rows = block_rows_
        carry = _SegCarry(
            fc.tbl_hi, fc.tbl_lo, *bufs,
            cursor=jnp.int32(0), n_valid=jnp.int32(0), fail=jnp.int32(0),
            viol_kind=jnp.int32(0), viol_inv=jnp.int32(0),
            dead_g=jnp.int32(-1), c=fc.c, peak=jnp.int32(0))
        steps, carry = jax.lax.while_loop(cond, body,
                                          (jnp.int32(0), carry))
        n_chunks = (block_rows + B - 1) // B
        return (FilterCarry(carry.tbl_hi, carry.tbl_lo, carry.c),
                SegBufs(carry.okey_hi, carry.okey_lo, carry.orows,
                        carry.opar, carry.olane, carry.ocon),
                SegStats(carry.cursor, carry.n_valid, carry.fail,
                         carry.viol_kind, carry.viol_inv, carry.dead_g,
                         steps, carry.c >= n_chunks, carry.peak))

    fbuf = fcon = budget = block_rows = None
    return segment


def _dd_filter(backend):
    """Devdedup export filter for one segment's output buffers: drop
    every lane whose key already streamed this level (ops/devdedup) and
    compact the survivors to the buffer head in stream order, so the
    harvest's existing ``[:ns]`` slices transfer and append only rows
    the master keyset would actually admit.  Jitted with dstate and
    bufs donated — runs in dispatch order, so the set's serial carry
    always reflects exactly the rows streamed before this segment."""
    filt = devdedup.make_filter(backend)

    def apply(dstate, bufs, cursor):
        dstate, _keep, idx, new_n, hits = filt(
            dstate, bufs.okey_hi, bufs.okey_lo, cursor)
        bufs = SegBufs(
            okey_hi=bufs.okey_hi[idx], okey_lo=bufs.okey_lo[idx],
            orows=bufs.orows[idx], opar=bufs.opar[idx],
            olane=bufs.olane[idx], ocon=bufs.ocon[idx])
        return dstate, bufs, new_n, hits

    return apply


class DDDEngine:
    """Exhaustive checker whose exact dedup lives on the host — distinct-
    state capacity is host RAM, with no device fingerprint table in the
    correctness path."""

    SEG_TARGET_S = 8.0
    SEG_CLAMP_S = 25.0
    SEG_MIN, SEG_MAX = 4, 1 << 16

    def __init__(self, config: CheckConfig,
                 caps: DDDCapacities | None = None,
                 seg_chunks: int = 64):
        self.config = config
        self.bounds = config.bounds
        self.lay = st.Layout.of(self.bounds)
        self.table = S.action_table(self.bounds, config.spec)
        self.A = len(self.table)
        self.caps = caps or DDDCapacities()
        if self.caps.block < config.chunk:
            raise ValueError("block must be >= chunk")
        self.seg_chunks = seg_chunks
        self._digest_caps = _DigestCaps(block=self.caps.block,
                                        levels=self.caps.levels)
        self.schema = bitpack.BitSchema(self.bounds)
        # RAFT_TLA_HOSTDEDUP gate: partitioned master keys + background
        # flush worker.  Resolved once at construction (like the
        # sig-prune/megakernel gates) and deliberately NOT part of
        # _DigestCaps — checkpoints are compatible both directions.
        self._host_dedup = keyset.host_dedup_enabled()
        # RAFT_TLA_PREFETCH gate: double-buffered background staging of
        # the next frontier block (utils/prefetch).  Same resolution
        # discipline; also NOT part of _DigestCaps — checkpoints resume
        # across either gate setting.
        self._prefetch = prefetch.prefetch_enabled()
        # RAFT_TLA_DEVDEDUP gate: device-resident exact within-level
        # fingerprint set applied to each segment's output buffers
        # before export (ops/devdedup) — drops rows the master keyset
        # would reject anyway, shrinking d2h export volume by the
        # within-level duplicate rate.  Same resolution discipline;
        # also NOT part of _DigestCaps — a resumed set starts empty and
        # merely re-streams, which the master dedups exactly.
        self._devdedup = devdedup.devdedup_backend()
        self._dd_apply = jax.jit(_dd_filter(self._devdedup),
                                 donate_argnums=(0, 1)) \
            if self._devdedup else None
        # Per-flush, per-partition merge budget: 8x the partition's
        # expected share of one flush covers the amortized LSM movement
        # (flush/parts keys in, each moved ~log2(N/flush) ~ 7 times at
        # campaign scale) while bounding any single flush's spike.
        self._merge_budget = max(1 << 16,
                                 (8 * self.caps.flush)
                                 // keyset.DEFAULT_PARTS)
        self._segment = jax.jit(
            _build_segment(config, self.caps, self.A, self.lay.width,
                           self.schema),
            donate_argnums=(0, 1))

    def _new_master(self):
        return keyset.new_master(self._host_dedup,
                                 merge_budget=self._merge_budget)

    def _init_filter(self) -> FilterCarry:
        TB = self.caps.table // BUCKET
        return FilterCarry(
            tbl_hi=jnp.full((TB, BUCKET), _EMPTY, U32),
            tbl_lo=jnp.full((TB, BUCKET), _EMPTY, U32),
            c=jnp.int32(0))

    def _init_devset(self):
        return jax.device_put(
            devdedup.init_set(self.caps.table, self._devdedup))

    def _make_bufs(self) -> SegBufs:
        OCAP = self.caps.seg_rows
        return SegBufs(
            okey_hi=jnp.zeros((OCAP,), U32),
            okey_lo=jnp.zeros((OCAP,), U32),
            orows=jnp.zeros((OCAP, self.schema.P), I32),
            opar=jnp.zeros((OCAP,), I32),
            olane=jnp.zeros((OCAP,), I32),
            ocon=jnp.zeros((OCAP,), bool))

    # -- host dedup -----------------------------------------------------

    def _flush(self, pend, master, host, constore, keystore, cov) -> int:
        """Exact-dedup the pending candidate stream; append the new
        states in first-occurrence order.  Returns the number appended."""
        if not pend["keys"]:
            return 0
        keys = np.concatenate(pend["keys"])
        new_idx = master.dedup(keys)
        n_new = int(new_idx.size)
        if n_new:
            rows = np.concatenate(pend["rows"])[new_idx]
            lane = np.concatenate(pend["lane"])[new_idx]
            con = np.concatenate(pend["con"])[new_idx]
            host.append(rows)
            if self.caps.retention == "full":
                par = np.concatenate(pend["par"])[new_idx]
                host.append_links(par, lane)
            constore.append(con.astype(np.int32)[:, None])
            nk = keys[new_idx]
            keystore.append(np.stack(
                [(nk & np.uint64(0xFFFFFFFF)).astype(np.uint32),
                 (nk >> np.uint64(32)).astype(np.uint32)],
                axis=1).view(np.int32))
            cov += np.bincount(lane, minlength=self.A)
        for lst in pend.values():
            lst.clear()
        return n_new

    # -- checkpoint / resume --------------------------------------------

    def save_checkpoint(self, path: str, host, constore, keystore,
                        n_states: int, n_trans: int, cov, level_ends,
                        blocks_done: int, init_key) -> None:
        """Block-boundary snapshots with an empty pending buffer; every
        stream (rows/links/constraints/keys) extends incrementally."""
        digest = ckpt.config_digest(self.config, self._digest_caps,
                                    init_key)
        if self.caps.retention == "frontier":
            save_frontier_snapshot(path, host, constore, keystore,
                                   n_states, n_trans, cov, level_ends,
                                   blocks_done, digest,
                                   keep_levels=self.caps.keep_levels)
        else:
            save_ddd_snapshot(path, host, constore, keystore, n_states,
                              n_trans, cov, level_ends, blocks_done,
                              self.schema.P, digest)

    def load_checkpoint(self, path: str, init_key):
        digest = ckpt.config_digest(self.config, self._digest_caps,
                                    init_key)
        load = load_frontier_snapshot \
            if self.caps.retention == "frontier" else load_ddd_snapshot
        (host, constore, keystore, n_states, n_trans, cov, level_ends,
         blocks_done) = load(path, self.schema.P, digest)
        kw = keystore.read(0, n_states).view(np.uint32)
        keys = keyset.pack_keys(kw[:, 1], kw[:, 0])
        # master_from_keys dedupe-checks BEFORE construction: a corrupt
        # log raises the stream-corrupt diagnostic naming the snapshot,
        # not MasterKeys's generic sortedness error; the partitioned
        # build also splits the O(N log N) resume sort across the pool
        master = keyset.master_from_keys(
            keys, source=path, partitioned=self._host_dedup,
            merge_budget=self._merge_budget)
        if len(master) != n_states:
            raise ValueError(
                f"checkpoint key log has {len(master)} distinct keys for "
                f"{n_states} states — stream corrupt")
        return (host, constore, keystore, master, n_states, n_trans, cov,
                level_ends, blocks_done)

    # -- main loop ------------------------------------------------------

    def check(self, init_override: interp.PyState | None = None,
              on_progress=None, checkpoint: str | None = None,
              checkpoint_every_s: float = 600.0,
              resume: str | None = None,
              deadline_s: float | None = None,
              retain_store: bool = False,
              events: str | None = None) -> EngineResult:
        import contextlib
        with contextlib.ExitStack() as stack:
            # bound stack: tmpdir cleanup runs on EVERY exit, including
            # KeyboardInterrupt and unexpected errors (review r4)
            self._install_sigint(stack)
            return self._check_impl(
                init_override, on_progress, checkpoint,
                checkpoint_every_s, resume, deadline_s, retain_store,
                stack, events)

    def _install_sigint(self, stack) -> None:
        install_sigint_boundary_stop(self, stack, boundary="segment")

    def _check_impl(self, init_override, on_progress, checkpoint,
                    checkpoint_every_s, resume, deadline_s,
                    retain_store, _cleanup, events=None) -> EngineResult:
        t0 = time.monotonic()
        tel = RunTelemetry(
            "ddd", config=self.config, caps=self.caps,
            on_progress=on_progress, events=events,
            resumed=resume is not None, n0=1, t0=t0)
        _cleanup.callback(tel.close)
        bounds = self.bounds
        init_py = init_override if init_override is not None \
            else interp.init_state(bounds)
        init_vec = interp.to_vec(init_py, bounds)
        hi0, lo0 = sym_mod.init_fingerprint(self.config, init_py, init_vec)

        for nm in self.config.invariants:
            if not inv_mod.py_invariant(nm)(init_py, bounds):
                from collections import Counter
                res = EngineResult(
                    n_states=1, diameter=0, n_transitions=0,
                    coverage=Counter(),
                    violation=Violation(nm, init_py, [(None, init_py)]),
                    levels=[1], wall_s=time.monotonic() - t0)
                tel.run_end(res)
                return res

        B = self.config.chunk
        N = B * self.A
        frontier = self.caps.retention == "frontier"
        if frontier and retain_store:
            raise ValueError(
                "retain_store (liveness graph export) needs retention="
                "'full' — frontier mode drops pre-frontier rows")
        tmpdir = None
        if frontier:
            # shared contract with DDDShardEngine (ADVICE r4: the two
            # inline copies had started to drift)
            checkpoint, checkpoint_every_s, tmpdir = \
                frontier_checkpoint_setup(resume, checkpoint,
                                          checkpoint_every_s, _cleanup,
                                          prefix="ddd_frontier_")
        # fresh run: any stream files at the checkpoint path belong to
        # some other run — remove before incremental appends trust them
        # (same contract as streamed_engine.check)
        _SUFFIXES = (".rows", ".links", ".con", ".keys")
        if checkpoint and not (resume and os.path.abspath(resume)
                               == os.path.abspath(checkpoint)):
            import glob as _glob
            for suf in _SUFFIXES:
                try:
                    os.remove(checkpoint + suf)
                except FileNotFoundError:
                    pass
            for pat in (".rowsL*", ".conL*"):
                for pth in _glob.glob(checkpoint + pat):
                    try:
                        os.remove(pth)
                    except OSError:
                        pass
        if resume:
            (host, constore, keystore, master, n_states, n_trans, cov,
             level_ends, blocks_done) = self.load_checkpoint(
                resume, (hi0, lo0))
            if checkpoint and os.path.abspath(resume) == \
                    os.path.abspath(checkpoint) and not frontier:
                for suf, w in ((".rows", self.schema.P), (".links", 3),
                               (".con", 1), (".keys", 2)):
                    # a pre-widening .links (width 2) is left alone: the
                    # first post-resume snapshot rewrites it whole
                    ckpt.trim_stream(checkpoint + suf, n_states, w)
        else:
            if frontier:
                # level 1 = the init state alone; next level opens empty
                host = native.LevelStore(checkpoint + ".rows",
                                         self.schema.P, 1, 0, 1,
                                         reset=True)
                constore = native.LevelStore(checkpoint + ".con", 1, 1,
                                             0, 1, reset=True)
                keystore = native.FileStore(checkpoint + ".keys", 2, 0,
                                            reset=True)
            else:
                host = native.make_store(self.schema.P)
                constore = native.make_store(1)
                keystore = native.make_store(2)
            master = self._new_master()
            master.seed(int(keyset.pack_keys(
                np.uint32(hi0)[None], np.uint32(lo0)[None])[0]))
            init_packed = self.schema.pack(
                np.asarray(init_vec, np.int32), np)
            if frontier:
                host.cur.append(init_packed[None, :])
                con0 = interp.constraint_ok(init_py, bounds)
                constore.cur.append(np.asarray([[con0]], np.int32))
            else:
                host.append(init_packed[None, :])
                host.append_links(np.asarray([-1], np.int64),
                                  np.asarray([-1], np.int32))
                con0 = interp.constraint_ok(init_py, bounds)
                constore.append(np.asarray([[con0]], np.int32))
            keystore.append(np.asarray(
                [[np.uint32(lo0), np.uint32(hi0)]],
                np.uint32).view(np.int32))
            n_states = 1
            n_trans = 0
            cov = np.zeros(self.A, np.int64)
            level_ends = [1]
            blocks_done = 0

        fc = self._init_filter()                # filter ≠ correctness:
        dst = self._init_devset() if self._dd_apply else None
        export_rows = 0      # rows actually exported d2h (post-filter)
        dd_hits = 0          # rows the device set dropped pre-export
        bufsets = [self._make_bufs(), self._make_bufs()]
        pend = {"keys": [], "rows": [], "par": [],  # resume starts empty
                "lane": [], "con": []}
        # Background dedup worker (RAFT_TLA_HOSTDEDUP): flushes run on
        # one daemon thread, depth-1 ordered, so flush i's new keys are
        # in the master before flush i+1's dedup starts — cross-flush
        # first-occurrence order is untouched and discovery stays byte-
        # identical.  Every reader of flush-mutated state (block upload,
        # checkpoint, level boundary, terminal/stop paths) drains first.
        worker = flushq.DedupWorker(
            lambda batch: self._flush(batch, master, host, constore,
                                      keystore, cov),
            phases=tel.phases) \
            if self._host_dedup else None
        if worker is not None:
            _cleanup.callback(worker.close)

        def seal(p):
            batch = {k: v[:] for k, v in p.items()}
            for v in p.values():
                v.clear()
            return batch

        def flush_sync():
            """Drain the background queue, then flush the remaining pend
            inline — afterwards master/stores/cov reflect every streamed
            candidate, exactly as in the synchronous engine."""
            nonlocal n_states
            if worker is not None:
                with tel.phases.phase("dedup_wait"):
                    n_states += worker.drain()
            with tel.phases.phase("dedup"):
                n_states += self._flush(pend, master, host, constore,
                                        keystore, cov)
        Fcap = self.caps.block
        # Upload prefetcher (RAFT_TLA_PREFETCH): while the device
        # expands block k, a daemon thread reads block k+1's rows +
        # constraint column and stages them into one of two
        # preallocated buffer sets via device_put, so the block
        # boundary swaps to a resident buffer instead of paying
        # drain→read→pad→h2d.  Safe concurrently with the flush
        # worker: block reads target rows < level_ends[-1], all
        # published before the level began, while in-flight flushes
        # append only rows >= level_ends[-1] (the store concurrency
        # contract, utils/native) — so prefetch-on also drops the
        # upload's unconditional dedup_wait drain.
        prefetcher = None
        if self._prefetch:
            pf_rows = [np.zeros((Fcap, self.schema.P), np.int32),
                       np.zeros((Fcap, self.schema.P), np.int32)]
            pf_con = [np.zeros((Fcap,), bool), np.zeros((Fcap,), bool)]

            def pf_load(start, rows, slot):
                # range-disjointness precondition (utils/prefetch)
                assert start + rows <= level_ends[-1], \
                    (start, rows, level_ends[-1])
                rb, cb = pf_rows[slot], pf_con[slot]
                rb[:rows] = host.read(start, rows)
                cb[:rows] = constore.read(start, rows)[:, 0]
                if rows < Fcap:          # zero pad == the sync path's
                    rb[rows:] = 0        # np.zeros concat, byte-exact
                    cb[rows:] = False
                return jax.block_until_ready(
                    (jax.device_put(rb), jax.device_put(cb)))

            prefetcher = prefetch.BlockPrefetcher(
                pf_load, phases=tel.phases, tracer=tel.trace)
            _cleanup.callback(prefetcher.close)
        viol = None          # (kind, inv_idx, dead_g) once detected
        viol_key = None
        fail = 0
        route_peak = 0       # max live enabled lanes seen in any chunk
        complete = True
        stopped = False
        t_warm = None
        pacer = pacing.SegmentPacer(self.seg_chunks, self.SEG_MIN,
                                    self.SEG_MAX, self.SEG_TARGET_S,
                                    self.SEG_CLAMP_S)
        budget = pacer.budget
        last_ckpt = time.monotonic()
        tel.run_start(n_states=n_states)

        def progress():
            if not tel.active:
                return
            # report the same inclusive count the old stats stream did
            # (ADVICE r4): bare n_states advances only at flushes, which
            # read as a 0-then-spike rate artifact; the tracker anchors
            # its incremental rate on the running max of this count, so a
            # post-flush dip never reads as a negative rate
            n_incl = n_states + sum(len(k) for k in pend["keys"])
            if worker is not None:
                n_incl += worker.inclusive_extra()
            tel.segment(
                n_states=n_states, n_incl=n_incl,
                level=len(level_ends), n_transitions=n_trans,
                coverage=dict(aggregate_coverage(self.table, cov)),
                route_peak=route_peak,
                flush_backlog=worker.backlog() if worker else None,
                upload_wait_ms=round(prefetcher.wait_s * 1e3, 3)
                if prefetcher else None,
                prefetch_hits=prefetcher.hits if prefetcher else None,
                export_rows=export_rows,
                dev_dedup_hits=dd_hits if self._dd_apply else None)

        n_trans_mark = n_trans   # n_trans as of the current block's start
        while not stopped:
            lvl_lo = level_ends[-2] if len(level_ends) > 1 else 0
            lvl_hi = level_ends[-1]
            b0 = lvl_lo + blocks_done * Fcap
            if prefetcher is not None and b0 < lvl_hi:
                # level start: every block address in [lvl_lo, lvl_hi)
                # is known now — warm the first block immediately
                prefetcher.schedule(b0, min(Fcap, lvl_hi - b0))
            for b_start in range(b0, lvl_hi, Fcap):
                b_rows = min(Fcap, lvl_hi - b_start)
                n_trans_mark = n_trans
                if prefetcher is not None:
                    # prefetch-on: NO pre-upload drain — block reads hit
                    # rows below lvl_hi only, published before the level
                    # began; the in-flight flush appends rows >= lvl_hi
                    # (disjoint ranges, utils/native contract).  The
                    # dedup_wait phase now fires only at flush_sync /
                    # checkpoint drains: that asymmetry in the phase
                    # timers is the gate's signature.
                    with tel.phases.phase("upload") as ph:
                        fbuf, fcon = ph.sync(
                            prefetcher.take(b_start, b_rows))
                    nxt = b_start + Fcap
                    if nxt < lvl_hi:
                        prefetcher.schedule(nxt,
                                            min(Fcap, lvl_hi - nxt))
                else:
                    if worker is not None:
                        # without the prefetcher's disjointness
                        # discipline, settle the in-flight flush before
                        # reading the block
                        with tel.phases.phase("dedup_wait"):
                            n_states += worker.drain()
                    with tel.phases.phase("upload") as ph:
                        blk = host.read(b_start, b_rows)
                        con = constore.read(b_start,
                                            b_rows)[:, 0].astype(bool)
                        if b_rows < Fcap:
                            blk = np.concatenate([blk, np.zeros(
                                (Fcap - b_rows, self.schema.P),
                                np.int32)])
                            con = np.concatenate(
                                [con, np.zeros((Fcap - b_rows,), bool)])
                        fbuf, fcon = ph.sync((jnp.asarray(blk),
                                              jnp.asarray(con)))
                fc = fc._replace(c=jnp.int32(0))
                # Two-deep segment pipeline: segment k+1 depends on k only
                # through the filter carry, so it is dispatched BEFORE k's
                # outputs are harvested — the d2h transfer and the host
                # dedup flush overlap device compute (the PP overlap the
                # round-1 verdict called out).  Dispatch order == harvest
                # order == stream order, so every exactness argument is
                # unchanged.  A segment dispatched speculatively after the
                # block's last chunk runs zero chunks (its while_loop cond
                # fails immediately); one harvested AFTER a stop event
                # (violation/failure/deadline) is dropped whole — its work
                # lies beyond the refbfs-exact stop point, and its filter
                # insertions are harmless (the run is over; resume
                # rebuilds the filter empty).
                q = []               # in-flight: (bufset idx, stats, t)
                free = list(range(len(bufsets)))
                block_done = False
                t_last_harvest = time.monotonic()
                while q or not (block_done or stopped):
                    if (not stopped and deadline_s is not None
                            and t_warm is not None
                            and time.monotonic() - t_warm > deadline_s):
                        complete = False
                        stopped = True
                        tel.stop_requested("deadline")
                    if not stopped and self._sigint:
                        complete = False      # graceful-stop contract:
                        stopped = True        # flush+snapshot below
                        tel.stop_requested("sigint")
                    if not (block_done or stopped) and free:
                        idx = free.pop(0)
                        t_disp = time.monotonic()
                        # enabling phase timers blocks on each dispatch —
                        # honest per-phase walls at the cost of the
                        # two-deep overlap (obs/phases.py contract)
                        with tel.phases.phase("expand") as ph:
                            fc, bufsets[idx], stats = self._segment(
                                fc, bufsets[idx], fbuf, fcon,
                                jnp.int32(budget), jnp.int32(b_rows))
                            ph.sync(stats)
                        ncur = dhits = None
                        if self._dd_apply is not None:
                            # applied in dispatch order (== stream
                            # order): the set's serial carry reflects
                            # exactly the rows streamed before this
                            # segment, so drops are provably re-sights
                            with tel.phases.phase("devdedup") as ph:
                                dst, bufsets[idx], ncur, dhits = \
                                    self._dd_apply(dst, bufsets[idx],
                                                   stats.cursor)
                                ph.sync(ncur)
                        q.append((idx, stats, ncur, dhits, t_disp))
                        if len(q) < 2:
                            continue         # keep the pipeline full
                    if not q:                # stop landed with nothing
                        break                # in flight
                    idx, stats, ncur, dhits, t_disp = q.pop(0)
                    # Stats first (tiny); the OCAP-sized buffers transfer
                    # only when the segment streamed anything.  The full-
                    # buffer transfer (vs the old jitted prefix slice) is
                    # deliberate: a slice program would enqueue BEHIND the
                    # in-flight speculative segment on the serial device
                    # queue and stall the harvest until it finishes —
                    # defeating the overlap this pipeline exists for.  At
                    # the 8 s segment target the fixed transfer is a few
                    # percent; zero-stream segments (every block end) now
                    # skip it entirely.
                    with tel.phases.phase("export"):
                        st_h = jax.device_get(stats)
                        # gate on: the harvest slices the POST-filter
                        # cursor — dropped rows never cross d2h at all
                        ns = int(st_h.cursor) if ncur is None \
                            else int(jax.device_get(ncur))
                        nv = int(st_h.n_valid)
                        vk = int(st_h.viol_kind)
                        route_peak = max(route_peak, int(st_h.peak))
                        bufs_h = jax.device_get(bufsets[idx]) \
                            if ns and not stopped else None
                    free.append(idx)
                    if stopped:
                        continue             # drop post-stop segments
                    n_trans += nv
                    fail |= int(st_h.fail)
                    if dhits is not None:
                        dd_hits += int(jax.device_get(dhits))
                    if ns:
                        export_rows += ns
                        # .copy(): a bare slice would pin the whole OCAP
                        # transfer buffer in pend until the next flush
                        pend["keys"].append(keyset.pack_keys(
                            bufs_h.okey_hi[:ns], bufs_h.okey_lo[:ns]))
                        pend["rows"].append(bufs_h.orows[:ns].copy())
                        if not frontier:
                            # rebase block-relative device parents to
                            # global int64 discovery indices (frontier
                            # mode keeps no links — skip the dead copy)
                            pend["par"].append(
                                bufs_h.opar[:ns].astype(np.int64)
                                + b_start)
                        pend["lane"].append(bufs_h.olane[:ns].copy())
                        pend["con"].append(bufs_h.ocon[:ns].copy())
                    if vk or fail:
                        if vk:
                            dg = int(st_h.dead_g)
                            viol = (vk, int(st_h.viol_inv),
                                    dg + b_start if dg >= 0 else dg)
                            if vk == 1:
                                # truncation makes the violator the last
                                # streamed candidate; remember its key to
                                # assert the flushed identity below
                                viol_key = pend["keys"][-1][-1]
                        stopped = True
                        continue
                    now = time.monotonic()
                    if t_warm is None:
                        t_warm = now
                    # own device time ~ since the later of my dispatch
                    # and the previous harvest (queue wait excluded); a
                    # zero-chunk speculative segment (block already done)
                    # is pure transfer time — no pacing signal, and it
                    # would poison the watchdog ratchet
                    if int(st_h.steps) > 0:
                        budget = pacer.update(
                            now - max(t_disp, t_last_harvest),
                            int(st_h.steps))
                    t_last_harvest = now
                    self.seg_chunks = budget
                    block_done = block_done or bool(st_h.done)
                    if sum(len(x) for x in pend["keys"]) >= \
                            self.caps.flush:
                        if worker is not None:
                            # sealed-batch submission: blocks only until
                            # the PREVIOUS flush completes (depth-1);
                            # this one runs while the next segment
                            # computes.  n_states lags by at most one
                            # in-flight flush — the _IDX_CEIL re-check
                            # at every drain point keeps the ceiling
                            # honest.
                            n_pend = sum(len(x) for x in pend["keys"])
                            with tel.phases.phase("dedup_submit"):
                                worker.submit(seal(pend), n_pend)
                            n_states += worker.collect()
                        else:
                            with tel.phases.phase("dedup"):
                                n_states += self._flush(pend, master,
                                                        host, constore,
                                                        keystore, cov)
                        if n_states > _IDX_CEIL:
                            fail = FAIL_INDEX
                            stopped = True
                        progress()
                        # the flush ran while the next segment computed;
                        # re-stamp so its duration never inflates the next
                        # harvest's dt (the pacer ratchet never decays)
                        t_last_harvest = time.monotonic()
                if stopped:
                    break
                blocks_done += 1
                if checkpoint and (time.monotonic() - last_ckpt
                                   >= checkpoint_every_s):
                    flush_sync()
                    with tel.phases.phase("snapshot"):
                        self.save_checkpoint(checkpoint, host, constore,
                                             keystore, n_states, n_trans,
                                             cov, level_ends, blocks_done,
                                             (hi0, lo0))
                    tel.checkpoint(checkpoint, n_states)
                    last_ckpt = time.monotonic()
            if stopped:
                break
            blocks_done = 0
            flush_sync()
            progress()
            if n_states > _IDX_CEIL:
                fail = FAIL_INDEX
                break
            if n_states == level_ends[-1]:       # no new states: done
                break
            level_ends.append(n_states)
            if self._dd_apply is not None:
                # the set is within-level by contract: reset it empty
                # at every boundary so capacity tracks one level's
                # stream, not the whole run (a next-level re-sight of a
                # previous-level state streams and the master drops it,
                # exactly as with the gate off)
                dst = self._init_devset()
            if prefetcher is not None:
                # quiesce before any rotation/teardown below; by now the
                # last take() consumed the final scheduled block, so
                # this is a no-op unless a stop raced the level end
                prefetcher.invalidate()
            if frontier:
                # the just-finished level's rows are dead weight now.
                # With snapshots, the files outlive the rotation until
                # the npz commits (save_frontier_snapshot.delete_old);
                # without (tmpdir mode) there is nothing to resume, so
                # delete immediately or every level accumulates.
                keep = self.caps.keep_levels
                host.rotate(delete_old=tmpdir is not None and not keep)
                constore.rotate(delete_old=tmpdir is not None
                                and not keep)
            if len(level_ends) > self.caps.levels:
                _cleanup.close()
                raise RuntimeError(
                    f"DDD search aborted: {decode_fail(FAIL_LEVEL)} "
                    f"(caps={self.caps}) — grow DDDCapacities and rerun")

        if prefetcher is not None:
            # stop paths (violation/SIGINT/deadline) can leave a
            # prefetch in flight; no store read survives past here, so
            # snapshots, traces and store teardown see a quiet store
            prefetcher.invalidate()
        flush_sync()
        if not complete and checkpoint and not viol and not fail:
            # graceful stop (SIGINT or deadline): same mid-level snapshot
            # shape as the periodic path above (pend flushed first, so
            # re-running the partial block on resume dedups against the
            # master keys) — a deadline stop must be as lossless as a
            # SIGINT one or --deadline silently discards work.  The
            # snapshot records n_trans as of the partial block's START:
            # states dedup on the re-run, transitions do not, so counting
            # any of the partial block here would double them on resume.
            with tel.phases.phase("snapshot"):
                self.save_checkpoint(checkpoint, host, constore, keystore,
                                     n_states, n_trans_mark, cov,
                                     level_ends, blocks_done, (hi0, lo0))
            tel.checkpoint(checkpoint, n_states)
        if fail:
            _cleanup.close()
            raise RuntimeError(
                f"DDD search aborted: {decode_fail(fail)} "
                f"(caps={self.caps}) — grow DDDCapacities and rerun")

        violation = None
        if viol is not None:
            kind, vi, dead_g = viol
            if kind == 1:
                viol_g = n_states - 1    # the violator is always new and
                n_inv = len(self.config.invariants)   # last in the flush
                inv_name = self.config.invariants[min(vi, n_inv - 1)]
                kw = keystore.read(viol_g, 1).view(np.uint32)
                got_key = int(keyset.pack_keys(kw[:, 1], kw[:, 0])[0])
                if got_key != int(viol_key):
                    _cleanup.close()
                    raise RuntimeError(
                        "DDD violator identity mismatch after flush — "
                        "fingerprint collision or dedup-order bug")
            else:
                viol_g = dead_g
                inv_name = DEADLOCK
            if frontier:
                # no trace links in frontier retention; with
                # keep_levels a backward re-search over the retained
                # level files rebuilds the full TLC-equivalent trace,
                # else (-noTrace equivalence) report the state itself
                row = self.schema.unpack(host.read(int(viol_g), 1)[0],
                                         np)
                py = interp.from_struct(st.unpack(row, self.lay, np),
                                        self.bounds)
                host.sync()          # commit cur/nxt for mmap reads
                constore.sync()
                trace = frontier_backtrace(
                    self.config, self.schema, self.lay, self.bounds,
                    self.table, checkpoint, level_ends, n_states,
                    int(viol_g), keystore)
                violation = Violation(invariant=inv_name, state=py,
                                      trace=trace or [(None, py)])
            else:
                chain_idx = host.trace_chain(viol_g)
                chain = []
                for k, g in enumerate(chain_idx):
                    row = self.schema.unpack(host.read(int(g), 1)[0], np)
                    _, lane_g = host.read_links(int(g), 1)
                    py = interp.from_struct(st.unpack(row, self.lay, np),
                                            self.bounds)
                    label = self.table[int(lane_g[0])].label() if k > 0 \
                        else None
                    chain.append((label, py))
                violation = Violation(invariant=inv_name,
                                      state=chain[-1][1], trace=chain)

        levels_arr = [level_ends[0]] + [
            level_ends[k] - level_ends[k - 1]
            for k in range(1, len(level_ends))]
        tail = n_states - level_ends[-1]
        if tail > 0:                 # partial final level (stopped run)
            levels_arr.append(tail)
        coverage = aggregate_coverage(self.table, cov)
        if tmpdir is not None:
            host.close()
            constore.close()
            keystore.close()
        if retain_store:
            # graph exports (models/liveness.ddd_graph) re-expand the
            # stored rows; the caller owns closing these
            self.retained = (host, constore, keystore, n_states)
        else:
            host.close()
            constore.close()
            keystore.close()
        result = EngineResult(
            n_states=n_states, diameter=len(levels_arr) - 1,
            n_transitions=n_trans, coverage=coverage,
            violation=violation, levels=levels_arr,
            wall_s=time.monotonic() - t0, complete=complete)
        tel.run_end(result)
        _cleanup.close()
        return result


def check(config: CheckConfig, caps: DDDCapacities | None = None,
          **kw) -> EngineResult:
    return DDDEngine(config, caps).check(**kw)
