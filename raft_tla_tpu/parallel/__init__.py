from raft_tla_tpu.parallel.shard_engine import (  # noqa: F401
    ShardCapacities, ShardEngine, check, make_mesh, make_slice_mesh,
    reshard_checkpoint)
from raft_tla_tpu.parallel.paged_shard_engine import (  # noqa: F401
    PagedShardCapacities, PagedShardEngine)
from raft_tla_tpu.parallel.cp_expand import (  # noqa: F401
    build_cp_expand, build_cp_step, cp_lane_count, cp_lane_map)
