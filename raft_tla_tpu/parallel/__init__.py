from raft_tla_tpu.parallel.shard_engine import (  # noqa: F401
    ShardCapacities, ShardEngine, check, make_mesh, make_slice_mesh,
    reshard_checkpoint)

# The paged-shard engine (pulls utils.native: a g++ build on first use)
# and the CP expansion load lazily — importing the package stays as
# cheap as the repo's lazy-import layering everywhere else assumes.
_LAZY = {
    "DDDShardCapacities": "ddd_shard_engine",
    "DDDShardEngine": "ddd_shard_engine",
    "reshard_ddd_checkpoint": "ddd_shard_engine",
    "PagedShardCapacities": "paged_shard_engine",
    "PagedShardEngine": "paged_shard_engine",
    "build_cp_expand": "cp_expand",
    "build_cp_step": "cp_expand",
    "cp_lane_count": "cp_expand",
    "cp_lane_map": "cp_expand",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib
        mod = importlib.import_module(
            f"raft_tla_tpu.parallel.{_LAZY[name]}")
        return getattr(mod, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
