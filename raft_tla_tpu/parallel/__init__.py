from raft_tla_tpu.parallel.shard_engine import (  # noqa: F401
    ShardCapacities, ShardEngine, check, make_mesh)
