"""Mesh-sharded delayed-duplicate-detection engine — the scale engine's
multi-chip composition (SURVEY §2.9 DP row, §7.1 step 7; VERDICT r2
missing #1).

The single-chip DDD engine (ddd_engine.py) removed the device
fingerprint-table ceiling by moving exact dedup to host RAM; this module
removes its single-chip ceiling by spreading BOTH the device work and
the host key set over a ``jax.sharding.Mesh``:

- **Device: lockstep expand + owner-routed lossy filtering.**  Each
  frontier window of ``ndev * block`` states splits into contiguous
  per-shard slices; shards expand their slice in lockstep chunks.  Every
  candidate is routed over the mesh to its fingerprint owner
  (``fp_hi % ndev`` — TLC's fingerprint-space partition, the same map as
  shard_engine.py) with one ``all_to_all`` per chunk (two-stage over a
  2-D (dcn, ici) slice mesh), so all duplicates of a key funnel through
  ONE shard's lossy filter and filtering efficiency matches the
  single-chip engine.  As in ddd_engine, the filter affects candidate
  *traffic* only, never the verdict — resume starts it empty.
- **Host: per-shard exact dedup in canonical order.**  Master keys are
  partitioned by the same owner map, so shard streams can never collide
  across partitions and each partition dedups independently
  (utils/keyset.MasterKeys — LSM-tiered, O(log) per flush) at arbitrary
  flush times.  Global discovery order is **(level, window, shard,
  shard-stream position)**: within a window each shard's new states are
  staged, and at the window boundary stagings drain into the single
  global store shard-major.  Every merge point is a deterministic
  function of the search — never of wall-clock flush/segment timing —
  so counts, levels, parent links and traces are reproducible run to
  run and across checkpoint resume, the shard_engine.py determinism
  contract.  On a 1-device mesh the order (and the checkpoint streams)
  coincide with the single-chip DDD engine's exactly (tested).

Totals (n_states, per-level counts, diameter, n_transitions, verdicts)
match refbfs exactly on violation-free runs.  On violating runs the
engine stops at lockstep-chunk granularity and reports a *valid,
deterministic* counterexample that may differ from refbfs's pick, and
counts include the full stopping chunk — the same relaxation as
shard_engine.py (TLC's multi-worker mode shares it).

Capacity: host RAM for keys + rows (as ddd_engine), device HBM holds
only the per-shard lossy filter and transfer buffers — the composition
runs/northstar_sizing.md calls for.  Discovery ids are int64 end-to-end
since round 4 (C++ store links, width-3 checkpoint streams, host
rebasing of window-relative device parents), so neither 10^9- nor
10^10-scale spaces hit an id ceiling (VERDICT r3 missing #2 closed);
the binding limits are host RAM and wall clock.

Checkpoints reuse the single-chip DDD incremental stream format
(.rows/.links/.con/.keys + npz); ``blocks_done`` counts completed
*global* windows and the digest pins the mesh size (the window layout
and owner map depend on it).  ``reshard_ddd_checkpoint`` rewrites a
snapshot for a different mesh size — the streams are order-only history
and move verbatim; only the window accounting and digest change.

Reference: TLC's external-memory fingerprint regime + multi-worker mode
(`/root/reference/.gitignore:1-2`); raft.tla line citations live in
ops/kernels.py next to the action semantics.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_tla_tpu.config import CheckConfig
from raft_tla_tpu.device_engine import (
    _EMPTY, BUCKET, FAIL_INDEX, FAIL_LEVEL, FAIL_ROUTE, FAIL_WIDTH,
    aggregate_coverage, decode_fail)
from raft_tla_tpu.ddd_engine import (
    _filter_insert, _IDX_CEIL, frontier_backtrace,
    frontier_checkpoint_setup, load_ddd_snapshot,
    load_frontier_snapshot, save_ddd_snapshot, save_frontier_snapshot)
from raft_tla_tpu.engine import DEADLOCK, EngineResult, Violation
from raft_tla_tpu.models import interp, invariants as inv_mod, spec as S
from raft_tla_tpu.obs import RunTelemetry
from raft_tla_tpu.ops import bitpack
from raft_tla_tpu.ops import devdedup
from raft_tla_tpu.ops import kernels
from raft_tla_tpu.ops import state as st
from raft_tla_tpu.ops import symmetry as sym_mod
from raft_tla_tpu.parallel.shard_engine import (
    _AXIS, _DCN, _mesh_axes, _shard_map, exchange, make_mesh)
from raft_tla_tpu.utils import ckpt
from raft_tla_tpu.utils import keyset
from raft_tla_tpu.utils import native
from raft_tla_tpu.utils import pacing
from raft_tla_tpu.utils import prefetch

I32 = jnp.int32
U32 = jnp.uint32


@dataclasses.dataclass(frozen=True)
class DDDShardCapacities:
    """Static shapes (per shard where noted).  ``block``: per-shard rows
    of one frontier window (a window is ``ndev * block`` global rows);
    ``table``: per-shard lossy filter slots (traffic only, never a
    ceiling); ``seg_rows``: per-shard output-buffer rows per segment;
    ``flush``: per-shard pending candidates per host dedup pass;
    ``send``: per-destination exchange depth per chunk (None = the safe
    bound ``chunk * A``; smaller trades memory for a loud FAIL_ROUTE);
    ``send2``: stage-B depth on 2-D meshes (None = ``nici * send``)."""

    block: int = 1 << 18
    table: int = 1 << 22
    seg_rows: int = 1 << 19
    flush: int = 1 << 22
    levels: int = 1 << 12
    send: Optional[int] = None
    send2: Optional[int] = None
    # "frontier": the single-chip campaign regime on the mesh — master
    # keys in RAM, rows/constraints in disk-backed current+next level
    # files, no trace links (ddd_engine.DDDCapacities.retention docs).
    # Shares the frontier snapshot format and migration with the
    # single-chip engine.
    retention: str = "full"
    # Retain ALL frontier level files for counterexample backtrace
    # (ddd_engine.DDDCapacities.keep_levels docs); tuning, not digest.
    keep_levels: bool = False
    # CP mode (SURVEY §2.9 CP row): every shard expands the SAME window
    # rows over its lane slice (parallel/cp_expand) instead of its own
    # row slice over all lanes — the bag-scan axis shards, the frontier
    # replicates.  Owner exchange, filters and host dedup are unchanged;
    # discovery order is (chunk, lane-slice shard, slot) and joins the
    # digest.  Pays only when bag lanes dominate the fan-out; see the
    # RESULTS.md measurement before choosing it.
    cp: bool = False

    def __post_init__(self):
        if self.retention not in ("full", "frontier"):
            raise ValueError(f"retention={self.retention!r}")
        # table is bitmask-addressed (power of two); block is only window
        # arithmetic and just needs to be chunk-aligned (engine-checked)
        if self.table & (self.table - 1):
            raise ValueError(f"table={self.table} must be a power of two")
        if self.table < BUCKET:
            raise ValueError(
                f"table={self.table} must be >= one bucket ({BUCKET})")


@dataclasses.dataclass(frozen=True)
class _DigestCaps:
    """Checkpoint-identity view: ``block`` + ``ndev`` fix the window
    layout and owner map, ``levels`` bounds the search; filter/buffer
    sizes are timing-only tuning.  Field names, class name and defaults
    deliberately coincide with ddd_engine._DigestCaps (+ ``ndev``,
    default-omitted at 1), so a single-chip DDD checkpoint with block B
    IS a valid 1-device-mesh checkpoint with block B and vice versa —
    the two engines produce identical discovery order there (tested)."""

    block: int = 1 << 20
    levels: int = 1 << 12
    ndev: int = 1
    cp: bool = False


class MFilter(NamedTuple):
    """Per-shard serial device state between segments: lossy filter +
    the replicated chunk cursor within the current window."""

    tbl_hi: jax.Array     # [dev] [TBd, BUCKET]
    tbl_lo: jax.Array     # [dev]
    c: jax.Array          # replicated scalar


class MBufs(NamedTuple):
    """Per-shard candidate-stream output buffers (donated)."""

    okey_hi: jax.Array    # [dev] [OCAP]
    okey_lo: jax.Array    # [dev]
    orows: jax.Array      # [dev] [OCAP, P]
    opar: jax.Array       # [dev] [OCAP] parent id, WINDOW-RELATIVE
                          # (int32-safe at any depth; harvest adds wbase)
    olane: jax.Array      # [dev] [OCAP]
    ocon: jax.Array       # [dev] [OCAP]


class MStats(NamedTuple):
    cursor: jax.Array     # [dev] [1] streamed rows this segment
    n_valid: jax.Array    # [dev] [1] transitions this segment
    fail: jax.Array       # [dev] [1] FAIL_* bits
    viol_pos: jax.Array   # [dev] [1] buffer slot of first violating
    viol_inv: jax.Array   # [dev] [1]   streamed candidate, -1 if none
    dead_g: jax.Array     # [dev] [1] global id of first dead row, -1
    steps: jax.Array      # replicated: chunks executed (pacer signal)
    done: jax.Array       # replicated: window exhausted (reading it off
                          # stats keeps the host from syncing on the
                          # in-flight carry — the pipeline's precondition)


class _MCarry(NamedTuple):
    tbl_hi: jax.Array
    tbl_lo: jax.Array
    okey_hi: jax.Array
    okey_lo: jax.Array
    orows: jax.Array
    opar: jax.Array
    olane: jax.Array
    ocon: jax.Array
    cursor: jax.Array
    n_valid: jax.Array
    fail: jax.Array
    viol_pos: jax.Array
    viol_inv: jax.Array
    dead_g: jax.Array
    c: jax.Array          # replicated
    halt: jax.Array       # replicated: stop event or buffers full


_SHARDED = ("tbl_hi", "tbl_lo", "okey_hi", "okey_lo", "orows", "opar",
            "olane", "ocon", "cursor", "n_valid", "fail", "viol_pos",
            "viol_inv", "dead_g")


def _carry_specs(axes):
    ax = axes if len(axes) > 1 else axes[0]
    return _MCarry(**{f: P(ax) if f in _SHARDED else P()
                      for f in _MCarry._fields})


def _build_segment(config: CheckConfig, caps: DDDShardCapacities, A: int,
                   W: int, schema: bitpack.BitSchema, ndev: int,
                   nici: int, axes: tuple):
    """One watchdog-safe lockstep slice (<= budget chunks) of the
    window expansion, under shard_map."""
    B = config.chunk
    n_inv = len(config.invariants)
    if n_inv > 29:
        raise ValueError("at most 29 invariants (bit-packed into int32)")
    if caps.cp:
        from raft_tla_tpu.parallel import cp_expand as cpx

        step = cpx.build_cp_step(config.bounds, config.spec,
                                 tuple(config.invariants),
                                 config.symmetry, ndev=ndev,
                                 view=config.view)
        A_loc = cpx.cp_lane_count(config.bounds, config.spec, ndev)
        lane_map = jnp.asarray(cpx.cp_lane_map(config.bounds, config.spec,
                                               ndev))     # [ndev, A_loc]
    else:
        # Orbit-scan variants (prescan, sig-prune) resolve from their
        # env gates at build time — bit-identical keys either way.
        step = kernels.build_step(config.bounds, config.spec,
                                  tuple(config.invariants),
                                  config.symmetry, view=config.view)
        A_loc = A
    BA = B * A_loc
    OCAP = caps.seg_rows
    Csend = caps.send if caps.send is not None else BA
    nslice = ndev // nici
    Csend2 = caps.send2 if caps.send2 is not None else nici * Csend
    NR = nici * Csend if nslice == 1 else nslice * Csend2
    if OCAP < NR:
        raise ValueError(
            f"seg_rows={OCAP} must be >= per-chunk receivable rows {NR} "
            "(shrink send/send2 or grow seg_rows)")
    BIG = jnp.int32(np.iinfo(np.int32).max)

    def owner(key_hi):
        return (key_hi % jnp.uint32(ndev)).astype(I32)

    # Every closure over the per-call window arrays is built INSIDE
    # segment(), fresh per trace.  The shared-nonlocal-cell pattern the
    # single-chip engine uses is a retrace hazard here: a sharding change
    # on fc.c (fresh jnp scalar on the first window call vs the
    # NamedSharding-committed output afterwards) retraces the pjit, and
    # build-time closures would still hold the PREVIOUS trace's shard_map
    # tracers in their cells — UnexpectedTracerError on the first
    # multi-segment window (caught by review; the parity tests' windows
    # all fit one segment).
    def segment(fc: MFilter, bufs: MBufs, fbuf, fcon, fpar, nrows,
                budget, n_chunks):
        def chunk_body(carry: _MCarry) -> _MCarry:
            (tbl_hi, tbl_lo, okey_hi, okey_lo, orows, opar, olane, ocon,
             cursor, n_valid, fail, viol_pos, viol_inv, dead_g, c,
             halt) = carry
            cur, nva, fa = cursor[0], n_valid[0], fail[0]
            vpos, vinv, dg = viol_pos[0], viol_inv[0], dead_g[0]

            # ---- expand my chunk (my row slice, or in CP mode the
            # shared rows over my lane slice) ----
            r0 = c * B
            rows_l = r0 + jnp.arange(B, dtype=I32)
            row_act = rows_l < nrows[0]
            bidx = jnp.minimum(rows_l, caps.block - 1)
            vecs = schema.unpack(fbuf[bidx], jnp)
            row_ok = row_act & fcon[bidx]
            if caps.cp:
                dev = jax.lax.axis_index(_AXIS).astype(I32) \
                    if nslice == 1 else (
                        jax.lax.axis_index(_DCN).astype(I32) * nici
                        + jax.lax.axis_index(_AXIS).astype(I32))
                out = step(vecs, dev)
            else:
                out = step(vecs)
            valid = out["valid"] & row_ok[:, None]
            fvalid = valid.reshape(BA)
            nva = nva + jnp.sum(fvalid.astype(I32))
            fa = fa | jnp.any(fvalid & out["overflow"].reshape(BA)) \
                .astype(I32) * FAIL_WIDTH
            if config.check_deadlock:
                en = jnp.any(out["valid"], axis=1)
                if caps.cp:
                    # a row's enabled lanes are sliced across the mesh
                    en = jax.lax.psum(en.astype(I32), axes) > 0
                dead = row_ok & ~en
                drow = jnp.min(jnp.where(dead, jnp.arange(B, dtype=I32),
                                         BIG))
                dg = jnp.where((drow < BIG) & (dg < 0),
                               fpar[r0 + jnp.minimum(drow, B - 1)], dg)

            # ---- route candidates to their fingerprint owners ----
            fhi = out["fp_hi"].reshape(BA)
            flo = out["fp_lo"].reshape(BA)
            svecs = schema.pack(out["svecs"].reshape(BA, W), jnp)
            par_g = fpar[r0 + jnp.arange(BA, dtype=I32) // A_loc]
            if caps.cp:
                # dense action-table index of each local lane (coverage
                # attribution and trace labels are table-global)
                lane_a = lane_map[dev][jnp.arange(BA, dtype=I32) % A_loc]
            else:
                lane_a = jnp.arange(BA, dtype=I32) % A_loc
            flags = jnp.ones((BA,), I32) | (
                out["con_ok"].reshape(BA).astype(I32) << 1)
            if n_inv:
                iv = out["inv_ok"].reshape(BA, n_inv).astype(I32)
                flags = flags | jnp.sum(
                    iv << (2 + jnp.arange(n_inv, dtype=I32))[None, :],
                    axis=1)

            dest_a = jnp.where(fvalid, owner(fhi) % nici, nici)
            (r_vec, r_hi, r_lo, r_par, r_lane, r_flags), ovf = exchange(
                _AXIS, nici, Csend, dest_a,
                ((svecs, 0, I32), (fhi, _EMPTY, U32), (flo, _EMPTY, U32),
                 (par_g, -1, I32), (lane_a, -1, I32), (flags, 0, I32)))
            fa = fa | ovf.astype(I32) * FAIL_ROUTE
            active = (r_flags & 1) == 1
            if nslice > 1:
                dest_b = jnp.where(active, owner(r_hi) // nici, nslice)
                (r_vec, r_hi, r_lo, r_par, r_lane, r_flags), ovf2 = \
                    exchange(
                        _DCN, nslice, Csend2, dest_b,
                        ((r_vec, 0, I32), (r_hi, _EMPTY, U32),
                         (r_lo, _EMPTY, U32), (r_par, -1, I32),
                         (r_lane, -1, I32), (r_flags, 0, I32)))
                fa = fa | ovf2.astype(I32) * FAIL_ROUTE
                active = (r_flags & 1) == 1

            # ---- owner-side lossy filter; stream to my buffer ----
            tbl_hi, tbl_lo, stream = _filter_insert(tbl_hi, tbl_lo, r_hi,
                                                    r_lo, active)
            pos = cur + jnp.cumsum(stream.astype(I32)) - 1
            sl = jnp.where(stream, pos, OCAP)
            okey_hi = okey_hi.at[sl].set(r_hi, mode="drop")
            okey_lo = okey_lo.at[sl].set(r_lo, mode="drop")
            orows = orows.at[sl].set(r_vec, mode="drop")
            opar = opar.at[sl].set(r_par, mode="drop")
            olane = olane.at[sl].set(r_lane, mode="drop")
            ocon = ocon.at[sl].set(((r_flags >> 1) & 1) == 1, mode="drop")
            cur = cur + jnp.sum(stream.astype(I32))

            # ---- first violating streamed candidate (relaxed stop) ----
            if n_inv:
                bad = stream & ((r_flags >> 2) & ((1 << n_inv) - 1)
                                != (1 << n_inv) - 1)
                first = jnp.min(jnp.where(bad, pos, BIG))
                hit = (first < BIG) & (vpos < 0)
                fidx = jnp.argmin(jnp.where(bad, pos, BIG))
                binv = jnp.argmax(
                    ((r_flags[fidx] >> 2) & (1 << jnp.arange(n_inv))) == 0
                ).astype(I32)
                vpos = jnp.where(hit, first, vpos)
                vinv = jnp.where(hit, binv, vinv)

            # ---- lockstep continue/halt (replicated collectives) ----
            stop_ev = jax.lax.psum(
                ((vpos >= 0) | (dg >= 0) | (fa != 0)).astype(I32),
                axes) > 0
            full = jax.lax.pmax((cur + NR > OCAP).astype(I32), axes) > 0
            return _MCarry(tbl_hi, tbl_lo, okey_hi, okey_lo, orows, opar,
                           olane, ocon, cur[None], nva[None], fa[None],
                           vpos[None], vinv[None], dg[None], c + 1,
                           stop_ev | full)

        def cond(sc):
            s, carry = sc
            return (carry.c < n_chunks) & ~carry.halt & (s < budget)

        def body(sc):
            s, carry = sc
            return s + 1, chunk_body(carry)

        z1 = jnp.zeros((1,), I32)
        carry = _MCarry(
            fc.tbl_hi, fc.tbl_lo, *bufs,
            cursor=z1, n_valid=z1, fail=z1,
            viol_pos=z1 - 1, viol_inv=z1, dead_g=z1 - 1,
            c=fc.c, halt=jnp.bool_(False))
        steps, carry = jax.lax.while_loop(cond, body,
                                          (jnp.int32(0), carry))
        return (MFilter(carry.tbl_hi, carry.tbl_lo, carry.c),
                MBufs(carry.okey_hi, carry.okey_lo, carry.orows,
                      carry.opar, carry.olane, carry.ocon),
                MStats(carry.cursor, carry.n_valid, carry.fail,
                       carry.viol_pos, carry.viol_inv, carry.dead_g,
                       steps, carry.c >= n_chunks))

    return segment


def _dd_filter_shard(backend):
    """Per-shard devdedup export filter (ops/devdedup) for the local
    view under shard_map: drop lanes whose key already streamed from
    THIS shard this level and compact survivors in stream order.  Owner
    routing funnels all duplicates of a key through one shard, but the
    filter does not rely on it — a drop is sound whenever the key
    streamed earlier from the *same* shard, which is exactly what the
    per-shard set records.  ``viol_pos`` is a buffer SLOT, so it is
    remapped through the compaction (the violator itself always
    survives: an equal earlier candidate would have violated first and
    stopped the run at its own segment)."""
    filt = devdedup.make_filter(backend)

    def apply(dstate, bufs, cursor, viol_pos):
        stt = devdedup.DevSet(dstate.hi, dstate.lo, dstate.n[0])
        stt, keep, idx, new_n, hits = filt(
            stt, bufs.okey_hi, bufs.okey_lo, cursor[0])
        nbufs = MBufs(
            okey_hi=bufs.okey_hi[idx], okey_lo=bufs.okey_lo[idx],
            orows=bufs.orows[idx], opar=bufs.opar[idx],
            olane=bufs.olane[idx], ocon=bufs.ocon[idx])
        vp = viol_pos[0]
        kpos = jnp.cumsum(keep.astype(I32))
        nvp = jnp.where(
            vp >= 0, kpos[jnp.clip(vp, 0, keep.shape[0] - 1)] - 1, vp)
        return (devdedup.DevSet(stt.hi, stt.lo, stt.n[None]), nbufs,
                new_n[None], hits[None], nvp[None])

    return apply


class DDDShardEngine:
    """Mesh-wide exhaustive checker with host-exact sharded dedup."""

    SEG_TARGET_S = 8.0
    SEG_CLAMP_S = 25.0
    SEG_MIN, SEG_MAX = 4, 1 << 16

    def __init__(self, config: CheckConfig, mesh: Mesh | None = None,
                 caps: DDDShardCapacities | None = None,
                 seg_chunks: int = 64):
        self.config = config
        self.bounds = config.bounds
        self.lay = st.Layout.of(self.bounds)
        self.table = S.action_table(self.bounds, config.spec)
        self.A = len(self.table)
        self.mesh = mesh if mesh is not None else make_mesh()
        self.ndev = self.mesh.devices.size
        self.caps = caps or DDDShardCapacities()
        if self.caps.block < config.chunk or \
                self.caps.block % config.chunk:
            raise ValueError(
                "block must be a multiple of chunk (chunk-local frontier "
                "indexing assumes whole chunks per window slice)")
        self.seg_chunks = seg_chunks
        self._digest_caps = _DigestCaps(block=self.caps.block,
                                        levels=self.caps.levels,
                                        ndev=self.ndev,
                                        cp=self.caps.cp)
        self.schema = bitpack.BitSchema(self.bounds)
        # RAFT_TLA_HOSTDEDUP: per-shard masters ride the partitioned
        # keyset and the process-shared dedup pool.  Shard ownership is
        # hi mod ndev — orthogonal to the keyset's top-bit partitioning,
        # so every shard splits evenly.  The flush itself stays
        # synchronous here: the canonical (level, window, shard) drain
        # order is fixed at window boundaries, not flush time.
        self._host_dedup = keyset.host_dedup_enabled()
        # RAFT_TLA_PREFETCH: the next window's rows are read and staged
        # by a daemon thread while the devices expand the current one
        # (utils/prefetch).  Flushes stay synchronous and the canonical
        # (level, window, shard) drain order is untouched — the prefetch
        # only reads rows published before the level began, disjoint
        # from anything the window-boundary drain appends.
        self._prefetch = prefetch.prefetch_enabled()
        # RAFT_TLA_DEVDEDUP: per-shard device-resident exact within-
        # level sets filter each segment's output buffers before export
        # (ops/devdedup).  Per-shard drops are sound regardless of key
        # routing (a drop proves the key already streamed from the same
        # shard), and the canonical (level, window, shard) drain order
        # is untouched — the filter only thins each shard's stream.
        # NOT part of the digest: resume across either gate setting.
        self._devdedup = devdedup.devdedup_backend()
        self._merge_budget = max(1 << 16,
                                 (8 * self.caps.flush)
                                 // keyset.DEFAULT_PARTS)
        axes = _mesh_axes(self.mesh)
        nici = self.mesh.shape[_AXIS]
        specs = _carry_specs(axes)
        self._ax = axes if len(axes) > 1 else axes[0]
        fc_specs = MFilter(specs.tbl_hi, specs.tbl_lo, P())
        buf_specs = MBufs(*(getattr(specs, f) for f in MBufs._fields))
        st_specs = MStats(*(getattr(specs, f)
                            for f in MStats._fields[:-2]), P(), P())
        dp = P(self._ax)
        fn = _build_segment(config, self.caps, self.A, self.lay.width,
                            self.schema, self.ndev, nici, axes)
        self._segment = jax.jit(
            _shard_map(fn, mesh=self.mesh,
                          in_specs=(fc_specs, buf_specs, dp, dp, dp, dp,
                                    P(), P()),
                          out_specs=(fc_specs, buf_specs, st_specs),
                          check_vma=False),
            donate_argnums=(0, 1))
        self._dd_apply = None
        if self._devdedup:
            dd_specs = devdedup.DevSet(dp, dp, dp)
            self._dd_apply = jax.jit(
                _shard_map(_dd_filter_shard(self._devdedup),
                           mesh=self.mesh,
                           in_specs=(dd_specs, buf_specs, dp, dp),
                           out_specs=(dd_specs, buf_specs, dp, dp, dp),
                           check_vma=False),
                donate_argnums=(0, 1))
        self._in_shardings = [
            NamedSharding(self.mesh, dp) for _ in range(4)]
        # window staging, lazy-alloc: one buffer set per prefetch slot
        # (slot 0 doubles as the gate-off synchronous path's buffers)
        self._gstage: list = [None, None]

    # -- device-side helpers --------------------------------------------

    def _init_filter(self) -> MFilter:
        TBd = self.caps.table // BUCKET
        sh = NamedSharding(self.mesh, P(self._ax))
        return MFilter(
            tbl_hi=jax.device_put(
                np.full((self.ndev * TBd, BUCKET), _EMPTY, np.uint32), sh),
            tbl_lo=jax.device_put(
                np.full((self.ndev * TBd, BUCKET), _EMPTY, np.uint32), sh),
            c=jnp.int32(0))

    def _init_devset(self):
        one = devdedup.init_set(self.caps.table, self._devdedup)
        nd = self.ndev
        reps = (nd, 1) if one.hi.ndim == 2 else nd
        sh = NamedSharding(self.mesh, P(self._ax))
        return devdedup.DevSet(
            hi=jax.device_put(np.tile(one.hi, reps), sh),
            lo=jax.device_put(np.tile(one.lo, reps), sh),
            n=jax.device_put(np.zeros((nd,), np.int32), sh))

    def _make_bufs(self) -> MBufs:
        OCAP = self.caps.seg_rows
        nd = self.ndev
        sh = NamedSharding(self.mesh, P(self._ax))
        z = lambda shape, dt, fill=0: jax.device_put(  # noqa: E731
            np.full(shape, fill, dt), sh)
        return MBufs(
            okey_hi=z((nd * OCAP,), np.uint32),
            okey_lo=z((nd * OCAP,), np.uint32),
            orows=z((nd * OCAP, self.schema.P), np.int32),
            opar=z((nd * OCAP,), np.int32),
            olane=z((nd * OCAP,), np.int32),
            ocon=z((nd * OCAP,), bool))

    def _upload_window(self, host, constore, wbase: int, wrows: int,
                       slot: int = 0):
        """Sharded upload of one frontier window: shard s expands global
        rows [wbase + s*block, ...); parent ids ride along.  The host
        staging buffers are allocated once per slot (inter-window
        critical path: devices idle during upload) and only their live
        prefix is rewritten — rows past ``wrows`` are masked off by
        ``nrows``, so stale tail contents are never read.  ``slot``
        selects the staging buffer set: the upload prefetcher
        double-buffers so staging window k+1 never scribbles over the
        buffers window k was uploaded from."""
        nd, Fcap = self.ndev, self.caps.block
        if self._gstage[slot] is None:
            self._gstage[slot] = (
                np.zeros((nd * Fcap, self.schema.P), np.int32),
                np.zeros((nd * Fcap,), bool))
        gbuf, gcon = self._gstage[slot]
        if self.caps.cp:
            # CP mode: every shard expands the SAME rows (its lane slice)
            blk = host.read(wbase, wrows)
            con = constore.read(wbase, wrows)[:, 0]
            for s in range(nd):
                gbuf[s * Fcap:s * Fcap + wrows] = blk
                gcon[s * Fcap:s * Fcap + wrows] = con
            # WINDOW-RELATIVE parent ids (fit int32 at any campaign
            # depth); the harvest rebases by adding wbase as int64
            gpar = np.tile(np.arange(Fcap), nd).astype(np.int32)
            nrows = np.full((nd,), wrows, np.int32)
        else:
            gbuf[:wrows] = host.read(wbase, wrows)
            gcon[:wrows] = constore.read(wbase, wrows)[:, 0]
            gpar = np.arange(nd * Fcap, dtype=np.int32)  # window-relative
            nrows = np.clip(wrows - np.arange(nd) * Fcap, 0, Fcap) \
                .astype(np.int32)
        sh = self._in_shardings
        return (jax.device_put(gbuf, sh[0]),
                jax.device_put(gcon, sh[1]),
                jax.device_put(gpar, sh[2]), jax.device_put(nrows, sh[3]),
                int(nrows.max() + self.config.chunk - 1)
                // self.config.chunk)

    # -- host dedup ------------------------------------------------------

    def _flush_shard(self, s, pend, masters, staging) -> int:
        """Exact-dedup shard ``s``'s pending stream into its staging (new
        states await the window-boundary drain).  Order within the shard
        stream is preserved; keys land in the master immediately so later
        flushes anti-join correctly."""
        if not pend[s]["keys"]:
            return 0
        keys = np.concatenate(pend[s]["keys"])
        new_idx = masters[s].dedup(keys)
        n_new = int(new_idx.size)
        if n_new:
            staging[s]["keys"].append(keys[new_idx])
            fields = ("rows", "lane", "con") if not pend[s]["par"] \
                else ("rows", "par", "lane", "con")
            for f in fields:
                staging[s][f].append(np.concatenate(pend[s][f])[new_idx])
        for lst in pend[s].values():
            lst.clear()
        return n_new

    def _drain(self, staging, host, constore, keystore, cov) -> int:
        """Window-boundary drain: append every shard's staged new states
        to the global store in shard order — the canonical merge point
        that fixes global discovery order."""
        n = 0
        for s in range(self.ndev):
            if not staging[s]["keys"]:
                continue
            keys = np.concatenate(staging[s]["keys"])
            rows = np.concatenate(staging[s]["rows"])
            lane = np.concatenate(staging[s]["lane"])
            con = np.concatenate(staging[s]["con"])
            host.append(rows)
            if self.caps.retention == "full":
                par = np.concatenate(staging[s]["par"])
                host.append_links(par, lane)
            constore.append(con.astype(np.int32)[:, None])
            keystore.append(np.stack(
                [(keys & np.uint64(0xFFFFFFFF)).astype(np.uint32),
                 (keys >> np.uint64(32)).astype(np.uint32)],
                axis=1).view(np.int32))
            cov += np.bincount(lane, minlength=self.A)
            n += keys.size
            for lst in staging[s].values():
                lst.clear()
        return n

    # -- checkpoint / resume ---------------------------------------------

    def save_checkpoint(self, path, host, constore, keystore, n_states,
                        n_trans, cov, level_ends, blocks_done,
                        init_key) -> None:
        """Window-boundary snapshots (pending + staging empty); the
        shared ddd_engine snapshot format — see reshard_ddd_checkpoint."""
        digest = ckpt.config_digest(self.config, self._digest_caps,
                                    init_key)
        if self.caps.retention == "frontier":
            save_frontier_snapshot(path, host, constore, keystore,
                                   n_states, n_trans, cov, level_ends,
                                   blocks_done, digest,
                                   keep_levels=self.caps.keep_levels)
        else:
            save_ddd_snapshot(path, host, constore, keystore, n_states,
                              n_trans, cov, level_ends, blocks_done,
                              self.schema.P, digest)

    def load_checkpoint(self, path, init_key):
        digest = ckpt.config_digest(self.config, self._digest_caps,
                                    init_key)
        load = load_frontier_snapshot \
            if self.caps.retention == "frontier" else load_ddd_snapshot
        (host, constore, keystore, n_states, n_trans, cov, level_ends,
         blocks_done) = load(path, self.schema.P, digest)
        masters = self._rebuild_masters(keystore, n_states, source=path)
        return (host, constore, keystore, masters, n_states, n_trans,
                cov, level_ends, blocks_done)

    def _new_master(self):
        return keyset.new_master(self._host_dedup,
                                 merge_budget=self._merge_budget)

    def _rebuild_masters(self, keystore, n_states, source="checkpoint"):
        kw = keystore.read(0, n_states).view(np.uint32)
        keys = keyset.pack_keys(kw[:, 1], kw[:, 0])
        own = (kw[:, 1] % np.uint32(self.ndev)).astype(np.int64)
        # master_from_keys dedupe-checks per shard and (partitioned)
        # sorts per partition on the shared pool, naming the snapshot in
        # the corruption diagnostic
        masters = [
            keyset.master_from_keys(
                keys[own == s], source=source,
                partitioned=self._host_dedup,
                merge_budget=self._merge_budget)
            for s in range(self.ndev)]
        if sum(len(m) for m in masters) != n_states:
            raise ValueError(
                f"checkpoint key log partitions to "
                f"{sum(len(m) for m in masters)} keys for {n_states} "
                "states — stream corrupt")
        return masters

    # -- main loop --------------------------------------------------------

    def check(self, init_override: interp.PyState | None = None,
              on_progress=None, checkpoint: str | None = None,
              checkpoint_every_s: float = 600.0,
              resume: str | None = None,
              events: str | None = None) -> EngineResult:
        import contextlib
        from raft_tla_tpu.ddd_engine import install_sigint_boundary_stop
        with contextlib.ExitStack() as stack:
            install_sigint_boundary_stop(self, stack, boundary="window")
            return self._check_impl(init_override, on_progress,
                                    checkpoint, checkpoint_every_s,
                                    resume, stack, events)

    def _check_impl(self, init_override, on_progress, checkpoint,
                    checkpoint_every_s, resume, _cleanup,
                    events=None) -> EngineResult:
        t0 = time.monotonic()
        tel = RunTelemetry(
            "ddd-shard", config=self.config, caps=self.caps,
            on_progress=on_progress, events=events,
            resumed=resume is not None, n0=1,
            n_devices=self.ndev, t0=t0)
        _cleanup.callback(tel.close)
        bounds = self.bounds
        init_py = init_override if init_override is not None \
            else interp.init_state(bounds)
        init_vec = interp.to_vec(init_py, bounds)
        hi0, lo0 = sym_mod.init_fingerprint(self.config, init_py, init_vec)

        for nm in self.config.invariants:
            if not inv_mod.py_invariant(nm)(init_py, bounds):
                from collections import Counter
                res = EngineResult(
                    n_states=1, diameter=0, n_transitions=0,
                    coverage=Counter(),
                    violation=Violation(nm, init_py, [(None, init_py)]),
                    levels=[1], wall_s=time.monotonic() - t0)
                tel.run_end(res)
                return res

        frontier = self.caps.retention == "frontier"
        tmpdir = None
        if frontier:
            checkpoint, checkpoint_every_s, tmpdir = \
                frontier_checkpoint_setup(resume, checkpoint,
                                          checkpoint_every_s, _cleanup,
                                          "dddshard_frontier_")
        _SUFFIXES = (".rows", ".links", ".con", ".keys")
        if checkpoint and not (resume and os.path.abspath(resume)
                               == os.path.abspath(checkpoint)):
            import glob as _glob
            for suf in _SUFFIXES:
                try:
                    os.remove(checkpoint + suf)
                except FileNotFoundError:
                    pass
            for pat in (".rowsL*", ".conL*"):
                for pth in _glob.glob(checkpoint + pat):
                    try:
                        os.remove(pth)
                    except OSError:
                        pass
        if resume:
            (host, constore, keystore, masters, n_states, n_trans, cov,
             level_ends, blocks_done) = self.load_checkpoint(
                resume, (hi0, lo0))
            if checkpoint and os.path.abspath(resume) == \
                    os.path.abspath(checkpoint) and not frontier:
                for suf, w in ((".rows", self.schema.P), (".links", 3),
                               (".con", 1), (".keys", 2)):
                    ckpt.trim_stream(checkpoint + suf, n_states, w)
        else:
            if frontier:
                host = native.LevelStore(checkpoint + ".rows",
                                         self.schema.P, 1, 0, 1,
                                         reset=True)
                constore = native.LevelStore(checkpoint + ".con", 1, 1,
                                             0, 1, reset=True)
                keystore = native.FileStore(checkpoint + ".keys", 2, 0,
                                            reset=True)
            else:
                host = native.make_store(self.schema.P)
                constore = native.make_store(1)
                keystore = native.make_store(2)
            masters = [self._new_master() for _ in range(self.ndev)]
            k0 = int(keyset.pack_keys(np.uint32(hi0)[None],
                                      np.uint32(lo0)[None])[0])
            masters[int(np.uint32(hi0) % np.uint32(self.ndev))].seed(k0)
            init_row = self.schema.pack(
                np.asarray(init_vec, np.int32), np)[None, :]
            con_row = np.asarray(
                [[interp.constraint_ok(init_py, bounds)]], np.int32)
            if frontier:
                host.cur.append(init_row)
                constore.cur.append(con_row)
            else:
                host.append(init_row)
                host.append_links(np.asarray([-1], np.int64),
                                  np.asarray([-1], np.int32))
                constore.append(con_row)
            keystore.append(np.asarray(
                [[np.uint32(lo0), np.uint32(hi0)]],
                np.uint32).view(np.int32))
            n_states = 1
            n_trans = 0
            cov = np.zeros(self.A, np.int64)
            level_ends = [1]
            blocks_done = 0

        fc = self._init_filter()
        dst = self._init_devset() if self._dd_apply else None
        export_rows = 0      # rows actually exported d2h (post-filter)
        dd_hits = 0          # rows the per-shard device sets dropped
        bufsets = [self._make_bufs(), self._make_bufs()]
        pend = [{"keys": [], "rows": [], "par": [], "lane": [], "con": []}
                for _ in range(self.ndev)]
        staging = [{"keys": [], "rows": [], "par": [], "lane": [],
                    "con": []} for _ in range(self.ndev)]
        # global window rows: row-sharded in DP mode, replicated in CP
        W = self.caps.block if self.caps.cp \
            else self.ndev * self.caps.block
        # Upload prefetcher (RAFT_TLA_PREFETCH): stage window k+1 on a
        # daemon thread while the devices expand window k.  Reads hit
        # rows < level_ends[-1] only — disjoint from everything the
        # window-boundary drain appends (>= level_ends[-1]), the store
        # concurrency contract (utils/native) — and the canonical
        # (level, window, shard) drain order is untouched.
        prefetcher = None
        if self._prefetch:
            def pf_load(wb, wr, slot):
                # range-disjointness precondition (utils/prefetch)
                assert wb + wr <= level_ends[-1], \
                    (wb, wr, level_ends[-1])
                out = self._upload_window(host, constore, wb, wr,
                                          slot=slot)
                jax.block_until_ready(out[:4])
                return out

            prefetcher = prefetch.BlockPrefetcher(
                pf_load, phases=tel.phases, tracer=tel.trace)
            _cleanup.callback(prefetcher.close)
        OCAP = self.caps.seg_rows
        fail = 0
        viol = None        # (kind, inv_idx, key_or_gid) once detected
        stopped = False
        complete = True    # False on a graceful SIGINT window-boundary stop
        pacer = pacing.SegmentPacer(self.seg_chunks, self.SEG_MIN,
                                    self.SEG_MAX, self.SEG_TARGET_S,
                                    self.SEG_CLAMP_S)
        budget = pacer.budget
        last_ckpt = time.monotonic()
        tel.run_start(n_states=n_states)

        def progress():
            if not tel.active:
                return
            # report the same INCLUSIVE count the old stats stream did:
            # bare n_states only advances at window-boundary drains,
            # which would read as 0-0-spike.  Staged counts are exact
            # (post-dedup); pend is the raw harvested stream, so the sum
            # is an upper bound — same contract as the single-chip
            # engine's progress().  The tracker's running-max anchor
            # keeps the post-drain dip from reading as a negative rate.
            n_incl = n_states + sum(
                sum(len(k) for k in st_["keys"]) for st_ in staging) \
                + sum(sum(len(k) for k in p_["keys"]) for p_ in pend)
            tel.segment(
                n_states=n_states, n_incl=n_incl,
                level=len(level_ends), n_transitions=n_trans,
                coverage=dict(aggregate_coverage(self.table, cov)),
                upload_wait_ms=round(prefetcher.wait_s * 1e3, 3)
                if prefetcher else None,
                prefetch_hits=prefetcher.hits if prefetcher else None,
                export_rows=export_rows,
                dev_dedup_hits=dd_hits if self._dd_apply else None)

        while not stopped:
            lvl_lo = level_ends[-2] if len(level_ends) > 1 else 0
            lvl_hi = level_ends[-1]
            w0 = lvl_lo + blocks_done * W
            if prefetcher is not None and w0 < lvl_hi:
                # level start: all window addresses are known — warm the
                # first window immediately
                prefetcher.schedule(w0, min(W, lvl_hi - w0))
            for wbase in range(w0, lvl_hi, W):
                wrows = min(W, lvl_hi - wbase)
                if prefetcher is not None:
                    # hit: swap to the staged, already-resident window;
                    # miss: the loader runs inline, same bytes either way
                    with tel.phases.phase("upload"):
                        fbuf, fcon, fpar, nrows, n_chunks = \
                            prefetcher.take(wbase, wrows)
                    nxtw = wbase + W
                    if nxtw < lvl_hi:
                        prefetcher.schedule(nxtw, min(W, lvl_hi - nxtw))
                else:
                    with tel.phases.phase("upload") as ph:
                        fbuf, fcon, fpar, nrows, n_chunks = \
                            self._upload_window(host, constore, wbase,
                                                wrows)
                        ph.sync((fbuf, fcon, fpar))
                fc = fc._replace(c=jnp.int32(0))
                # Two-deep segment pipeline (the ddd_engine PP overlap):
                # segment k+1 depends on k only through the filter carry,
                # so it is dispatched BEFORE k's stats/buffers are
                # harvested — d2h transfer and host dedup overlap device
                # compute.  Dispatch order == harvest order == stream
                # order, so the canonical-order argument is unchanged; a
                # segment harvested AFTER a stop event is dropped whole
                # (its chunks lie past the chunk-granular stop point),
                # and one dispatched past the window's last chunk runs
                # zero chunks.
                q = []               # in-flight: (bufset idx, stats, t)
                free = list(range(len(bufsets)))
                window_done = False
                t_last_harvest = time.monotonic()
                while q or not (window_done or stopped):
                    if not (window_done or stopped) and free:
                        idx = free.pop(0)
                        t_disp = time.monotonic()
                        # NB: enabling phase timers blocks each dispatch,
                        # trading the two-deep overlap for honest walls
                        with tel.phases.phase("expand") as ph:
                            fc, bufsets[idx], stats = self._segment(
                                fc, bufsets[idx], fbuf, fcon, fpar,
                                nrows, jnp.int32(budget),
                                jnp.int32(n_chunks))
                            ph.sync(stats)
                        ncur = dhits = nvp = None
                        if self._dd_apply is not None:
                            # dispatch order == per-shard stream order,
                            # so each shard's set carry reflects exactly
                            # its rows streamed before this segment
                            with tel.phases.phase("devdedup") as ph:
                                (dst, bufsets[idx], ncur, dhits,
                                 nvp) = self._dd_apply(
                                    dst, bufsets[idx], stats.cursor,
                                    stats.viol_pos)
                                ph.sync(ncur)
                        q.append((idx, stats, ncur, dhits, nvp, t_disp))
                        if len(q) < 2:
                            continue         # keep the pipeline full
                    if not q:
                        break
                    idx, stats, ncur, dhits, nvp, t_disp = q.pop(0)
                    with tel.phases.phase("export"):
                        st_h = jax.device_get(stats)
                        # gate on: harvest the POST-filter cursors —
                        # dropped rows never cross d2h at all
                        cursors = np.asarray(st_h.cursor) \
                            if ncur is None \
                            else np.asarray(jax.device_get(ncur))
                        bufs_h = jax.device_get(bufsets[idx]) \
                            if cursors.sum() and not stopped else None
                    free.append(idx)
                    if stopped:
                        continue             # drop post-stop segments
                    # harvest per shard in shard order
                    for s in range(self.ndev):
                        ns = int(cursors[s])
                        if not ns:
                            continue
                        o = s * OCAP
                        pend[s]["keys"].append(keyset.pack_keys(
                            bufs_h.okey_hi[o:o + ns],
                            bufs_h.okey_lo[o:o + ns]))
                        pend[s]["rows"].append(
                            bufs_h.orows[o:o + ns].copy())
                        if not frontier:
                            pend[s]["par"].append(   # rebase to global
                                bufs_h.opar[o:o + ns].astype(np.int64)
                                + wbase)
                        pend[s]["lane"].append(
                            bufs_h.olane[o:o + ns].copy())
                        pend[s]["con"].append(
                            bufs_h.ocon[o:o + ns].copy())
                    n_trans += int(np.asarray(st_h.n_valid).sum())
                    export_rows += int(cursors.sum())
                    if dhits is not None:
                        dd_hits += int(np.asarray(
                            jax.device_get(dhits)).sum())
                    fail |= int(np.bitwise_or.reduce(
                        np.asarray(st_h.fail)))
                    # gate on: viol_pos remapped through the compaction
                    vpos = np.asarray(st_h.viol_pos) if nvp is None \
                        else np.asarray(jax.device_get(nvp))
                    dgs = np.asarray(st_h.dead_g)
                    if fail:
                        stopped = True
                        continue
                    elif (vpos >= 0).any():
                        s = int(np.nonzero(vpos >= 0)[0][0])
                        viol = (1, int(np.asarray(st_h.viol_inv)[s]),
                                int(keyset.pack_keys(
                                    bufs_h.okey_hi[s * OCAP + vpos[s]]
                                    [None],
                                    bufs_h.okey_lo[s * OCAP + vpos[s]]
                                    [None])[0]))
                        stopped = True
                        continue
                    elif (dgs >= 0).any():
                        s = int(np.nonzero(dgs >= 0)[0][0])
                        viol = (2, 0, int(dgs[s]) + wbase)
                        stopped = True
                        continue
                    now = time.monotonic()
                    # own device time ~ since the later of my dispatch
                    # and the previous harvest (queue wait excluded);
                    # zero-chunk speculative segments carry no signal
                    if int(st_h.steps) > 0:
                        budget = pacer.update(
                            now - max(t_disp, t_last_harvest),
                            int(st_h.steps))
                        self.seg_chunks = budget
                    t_last_harvest = now
                    window_done = window_done or bool(st_h.done)
                    flushed = False
                    for s in range(self.ndev):
                        if sum(len(x) for x in pend[s]["keys"]) >= \
                                self.caps.flush:
                            with tel.phases.phase("dedup"):
                                self._flush_shard(s, pend, masters,
                                                  staging)
                            flushed = True
                    if flushed:
                        # the flush ran while the next segment computed;
                        # re-stamp so its duration never inflates the
                        # next harvest's dt
                        t_last_harvest = time.monotonic()
                    progress()
                if stopped:
                    break
                # window boundary: flush all shards, drain shard-major
                with tel.phases.phase("dedup"):
                    for s in range(self.ndev):
                        self._flush_shard(s, pend, masters, staging)
                    n_states += self._drain(staging, host, constore,
                                            keystore, cov)
                blocks_done += 1
                if n_states > _IDX_CEIL:
                    fail = FAIL_INDEX
                    stopped = True
                    break
                if checkpoint and (time.monotonic() - last_ckpt
                                   >= checkpoint_every_s):
                    with tel.phases.phase("snapshot"):
                        self.save_checkpoint(checkpoint, host, constore,
                                             keystore, n_states, n_trans,
                                             cov, level_ends, blocks_done,
                                             (hi0, lo0))
                    tel.checkpoint(checkpoint, n_states)
                    last_ckpt = time.monotonic()
                if getattr(self, "_sigint", False):
                    # Graceful-stop contract (install_sigint_boundary_
                    # stop): stop at the WINDOW boundary, the only point
                    # where the canonical shard-major stream order is
                    # whole — pend/staging just drained, blocks_done just
                    # advanced, every counter (incl. n_trans: all of this
                    # window's segments are harvested) names exactly the
                    # completed-window prefix.  A mid-window drain would
                    # emit a partial window in shard-major order and
                    # diverge from the uninterrupted stream.
                    complete = False
                    stopped = True
                    tel.stop_requested("sigint")
                    if checkpoint:
                        with tel.phases.phase("snapshot"):
                            self.save_checkpoint(
                                checkpoint, host, constore, keystore,
                                n_states, n_trans, cov, level_ends,
                                blocks_done, (hi0, lo0))
                        tel.checkpoint(checkpoint, n_states)
                    break
            if stopped:
                break
            blocks_done = 0
            if n_states == level_ends[-1]:       # no new states: done
                break
            level_ends.append(n_states)
            if self._dd_apply is not None:
                # within-level sets by contract: reset empty at every
                # boundary (re-sights of previous-level states stream
                # and the per-shard masters drop them, as with gate off)
                dst = self._init_devset()
            if prefetcher is not None:
                # quiesce before rotation (no-op unless a stop raced the
                # level end — the last take() consumed the final window)
                prefetcher.invalidate()
            if self.caps.retention == "frontier":
                # finished level's rows are dead weight (snapshots keep
                # files alive until their npz commits; tmpdir runs have
                # nothing to resume — delete immediately)
                keep = self.caps.keep_levels
                host.rotate(delete_old=tmpdir is not None and not keep)
                constore.rotate(delete_old=tmpdir is not None
                                and not keep)
            progress()
            if len(level_ends) > self.caps.levels:
                raise RuntimeError(
                    f"DDD-shard search aborted: {decode_fail(FAIL_LEVEL)} "
                    f"(caps={self.caps}) — grow capacities and rerun")

        if prefetcher is not None:
            # stop paths can leave a window prefetch in flight; no store
            # read survives past here, so the drain, traces and store
            # teardown below see a quiet store
            prefetcher.invalidate()
        # terminal drain (stopped runs keep everything streamed so far —
        # the relaxed chunk-granular stop, as shard_engine)
        with tel.phases.phase("dedup"):
            for s in range(self.ndev):
                self._flush_shard(s, pend, masters, staging)
            n_states += self._drain(staging, host, constore, keystore,
                                    cov)
        if fail:
            raise RuntimeError(
                f"DDD-shard search aborted: {decode_fail(fail)} "
                f"(caps={self.caps}, ndev={self.ndev}) — grow "
                "capacities and rerun")

        violation = None
        if viol is not None:
            kind, vi, ref = viol
            if kind == 1:
                # the violator's first occurrence was discovered this
                # level; find its global id by key
                lvl_base = level_ends[-1] if len(level_ends) else 0
                kw = keystore.read(lvl_base, n_states - lvl_base) \
                    .view(np.uint32)
                got = keyset.pack_keys(kw[:, 1], kw[:, 0])
                hits = np.nonzero(got == np.uint64(ref))[0]
                if not hits.size:
                    raise RuntimeError(
                        "DDD-shard violator key not found after drain — "
                        "fingerprint collision or dedup-order bug")
                viol_g = lvl_base + int(hits[0])
                n_inv = len(self.config.invariants)
                inv_name = self.config.invariants[min(vi, n_inv - 1)]
            else:
                viol_g = ref
                inv_name = DEADLOCK
            if self.caps.retention == "frontier":
                # no trace links; keep_levels restores the full trace
                # via backward re-search (ddd_engine.frontier_backtrace
                # — the level files are mesh-agnostic global streams),
                # else TLC -noTrace: report the state
                row = self.schema.unpack(host.read(int(viol_g), 1)[0],
                                         np)
                py = interp.from_struct(st.unpack(row, self.lay, np),
                                        self.bounds)
                host.sync()
                constore.sync()
                trace = frontier_backtrace(
                    self.config, self.schema, self.lay, self.bounds,
                    self.table, checkpoint, level_ends, n_states,
                    int(viol_g), keystore)
                violation = Violation(invariant=inv_name, state=py,
                                      trace=trace or [(None, py)])
            else:
                chain_idx = host.trace_chain(viol_g)
                chain = []
                for k, g in enumerate(chain_idx):
                    row = self.schema.unpack(host.read(int(g), 1)[0], np)
                    _, lane_g = host.read_links(int(g), 1)
                    py = interp.from_struct(st.unpack(row, self.lay, np),
                                            self.bounds)
                    label = self.table[int(lane_g[0])].label() if k > 0 \
                        else None
                    chain.append((label, py))
                violation = Violation(invariant=inv_name,
                                      state=chain[-1][1], trace=chain)

        levels_arr = [level_ends[0]] + [
            level_ends[k] - level_ends[k - 1]
            for k in range(1, len(level_ends))]
        tail = n_states - level_ends[-1]
        if tail > 0:
            levels_arr.append(tail)
        coverage = aggregate_coverage(self.table, cov)
        host.close()
        constore.close()
        keystore.close()
        result = EngineResult(
            n_states=n_states, diameter=len(levels_arr) - 1,
            n_transitions=n_trans, coverage=coverage,
            violation=violation, levels=levels_arr,
            wall_s=time.monotonic() - t0, complete=complete)
        tel.run_end(result)
        return result


def check(config: CheckConfig, mesh: Mesh | None = None,
          caps: DDDShardCapacities | None = None, **kw) -> EngineResult:
    return DDDShardEngine(config, mesh, caps).check(**kw)


def reshard_ddd_checkpoint(config: CheckConfig,
                           caps_src: DDDShardCapacities, src_path: str,
                           dst_path: str, ndev_src: int, ndev_dst: int,
                           caps_dst: DDDShardCapacities | None = None,
                           init_override: interp.PyState | None = None,
                           ) -> dict:
    """Rewrite a DDD-shard checkpoint for a different mesh size.

    Unlike the shard engine's resharder, nothing about the *stored*
    search history depends on the mesh: the streams record discovery
    order, which is immutable history, and the per-shard master keys are
    rebuilt from the key stream at load time for whatever mesh resumes.
    Only the window accounting changes — ``blocks_done`` denominates in
    ``ndev * block`` global rows — so the completed-row count must land
    on a destination window boundary (checkpoints are written at window
    boundaries, so for ``ndev_dst * block_dst`` dividing
    ``ndev_src * block_src`` every snapshot qualifies; otherwise let the
    run reach a compatible boundary first).  The single-chip DDD engine
    writes the identical stream format, so this also migrates a
    single-chip campaign onto a mesh: pass the single-chip engine's
    ``block`` inside ``caps_src`` and ``ndev_src=1``.
    """
    caps_dst = caps_dst or caps_src
    init_py = init_override if init_override is not None \
        else interp.init_state(config.bounds)
    init_vec = interp.to_vec(init_py, config.bounds)
    hi0, lo0 = sym_mod.init_fingerprint(config, init_py, init_vec)
    init_key = (hi0, lo0)
    src_digest = ckpt.config_digest(
        config, _DigestCaps(block=caps_src.block, levels=caps_src.levels,
                            ndev=ndev_src, cp=caps_src.cp), init_key)
    with ckpt.load_npz_checked(src_path, src_digest) as z:
        fields = {k: np.asarray(z[k]).copy() for k in
                  ("n_states", "n_trans", "cov", "level_ends",
                   "blocks_done")}
        is_frontier = "retention" in z.files
    rows_done = int(fields["blocks_done"]) * (
        caps_src.block if caps_src.cp else ndev_src * caps_src.block)
    w_dst = caps_dst.block if caps_dst.cp else ndev_dst * caps_dst.block
    # a partial final level window is clamped by the level size; rows
    # actually expanded = min(rows_done, current level rows)
    le = [int(x) for x in fields["level_ends"]]
    lvl_lo = le[-2] if len(le) > 1 else 0
    lvl_rows = le[-1] - lvl_lo
    rows_done = min(rows_done, lvl_rows)
    if rows_done % w_dst and rows_done != lvl_rows:
        raise ValueError(
            f"completed rows {rows_done} of the current level do not "
            f"land on a {w_dst}-row destination window boundary — "
            "resume on the source mesh until they do, or pick a "
            "divisible block size")
    fields["blocks_done"] = np.int64(-(-rows_done // w_dst)
                                     if rows_done == lvl_rows
                                     else rows_done // w_dst)
    n_states = int(fields["n_states"])
    P_ = bitpack.BitSchema(config.bounds).P
    if is_frontier:
        # frontier snapshots: keys + the two live level files move
        # verbatim (they are mesh-independent history, same as the full
        # streams); links don't exist
        le = [int(x) for x in fields["level_ends"]]
        L = len(le)
        lvl_lo = le[-2] if L > 1 else 0
        ckpt.copy_stream(src_path + ".keys", dst_path + ".keys",
                         n_states, 2)
        for prefix, w in ((".rows", P_), (".con", 1)):
            for idx, base, end in ((L, lvl_lo, le[-1]),
                                   (L + 1, le[-1], n_states)):
                ckpt.copy_stream(f"{src_path}{prefix}L{idx}",
                                 f"{dst_path}{prefix}L{idx}",
                                 end - base, w)
    else:
        # .links is width 3 post-int64-widening, width 2 in pre-round-4
        # snapshots; the stream moves verbatim either way (the loader
        # dual-reads both), so copy at the source's own width
        links_w = ckpt.stream_width(src_path + ".links")
        for suf, w in ((".rows", P_),
                       (".links", links_w), (".con", 1), (".keys", 2)):
            ckpt.copy_stream(src_path + suf, dst_path + suf, n_states, w)
    extra = {"retention": np.bytes_(b"frontier")} if is_frontier else {}
    ckpt.atomic_savez(
        dst_path, **fields, **extra,
        config_digest=np.uint64(ckpt.config_digest(
            config, _DigestCaps(block=caps_dst.block,
                                levels=caps_dst.levels, ndev=ndev_dst,
                                cp=caps_dst.cp),
            init_key)))
    return {"ndev_src": ndev_src, "ndev_dst": ndev_dst,
            "n_states": n_states, "rows_done": rows_done,
            "blocks_done_dst": int(fields["blocks_done"])}
