"""CP analog — the per-state message-bag scan partitioned across devices.

SURVEY §2.9's CP row: "partition the per-state message bag scan across
lanes when M is large."  Within one device the scan is already
lane-parallel (each bag slot is an action lane of the dense fan-out,
`models/spec.action_table`).  This module partitions it across MESH
devices: the bag-driven families — ``Receive(m)``, ``DuplicateMessage(m)``,
``DropMessage(m)`` (``raft.tla:461-463``), the only lanes that grow with
the ``MaxMsgSlots`` bound — are sharded by SLOT, so each device expands
the same frontier chunk over ``ceil(S / ndev)`` slots per bag family
while the fixed-size non-bag lanes ride on device 0 (dense compute,
device-masked validity — they are the cheap minority precisely when CP
pays, at large M).

Because exhaustive dedup is keyed on state fingerprints — not on which
device produced a candidate — the per-device partial fan-outs compose
with the FP-prefix ``all_to_all`` dedup exchange exactly like
frontier-sharded (DP) candidates; a CP engine's deterministic stream
order is (device-major, local-lane), exposed by :func:`cp_lane_map`.

Built on the same family kernels and stage pipeline as the dense step
(``ops/kernels``): per-lane values are bit-identical to
``kernels.build_step`` at the mapped dense lane (asserted by
tests/test_cp_expand.py on the virtual 8-device mesh).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from raft_tla_tpu.config import Bounds
from raft_tla_tpu.models import spec as SP
from raft_tla_tpu.ops import kernels
from raft_tla_tpu.ops import state as st

I32 = jnp.int32

_BAG_FAMS = (SP.RECEIVE, SP.DUPLICATE, SP.DROP)


def _split(bounds: Bounds, spec: str):
    """(non-bag instances, bag families present, slots per bag family)."""
    table = SP.action_table(bounds, spec)
    nonbag = [a for a in table if a.family not in _BAG_FAMS]
    bagfams = [f for f in _BAG_FAMS if f in SP.SPECS[spec]]
    return nonbag, bagfams, bounds.msg_cap


def cp_lane_count(bounds: Bounds, spec: str, ndev: int) -> int:
    """Per-device lane count A_local = n_nonbag + n_bagfams * ceil(S/ndev)."""
    nonbag, bagfams, S = _split(bounds, spec)
    return len(nonbag) + len(bagfams) * (-(-S // ndev))


def cp_lane_map(bounds: Bounds, spec: str, ndev: int) -> np.ndarray:
    """``[ndev, A_local]`` dense-table lane index of each local lane, or
    -1 for lanes that are dead on that device (non-bag lanes off device
    0; slot padding past S).  The union of the >=0 entries is exactly
    ``range(len(action_table))``, each exactly once."""
    nonbag, bagfams, S = _split(bounds, spec)
    Sp = -(-S // ndev)
    table = SP.action_table(bounds, spec)
    base = {}
    for g, a in enumerate(table):
        if a.family in _BAG_FAMS and a.slot == 0:
            base[a.family] = g
    out = np.full((ndev, cp_lane_count(bounds, spec, ndev)), -1, np.int32)
    for d in range(ndev):
        for l in range(len(nonbag)):
            if d == 0:
                out[d, l] = l
        for fi, fam in enumerate(bagfams):
            for k in range(Sp):
                slot = d * Sp + k
                if slot < S:
                    out[d, len(nonbag) + fi * Sp + k] = base[fam] + slot
    return out


def build_cp_expand(bounds: Bounds, spec: str = "full", ndev: int = 1):
    """Per-device slice of the action fan-out: ``expand(s, dev) ->
    (succs[A_local, ...], valid[A_local], overflow[A_local])``.

    ``dev`` is the traced device index (``jax.lax.axis_index`` under
    ``shard_map``); bag-family slot arguments are computed from it, so
    one program serves every mesh position.  Canonicalization and the
    faithful-mode allLogs union match ``kernels.build_expand`` exactly.
    """
    nonbag, bagfams, S = _split(bounds, spec)
    Sp = -(-S // ndev)
    groups = kernels.group_instances(nonbag)

    def expand(s, dev):
        succs, valids, ovfs = kernels.grouped_dispatch(bounds, s, groups)
        on_dev0 = dev == 0
        valids = [v & on_dev0 for v in valids]
        ovfs = [o & on_dev0 for o in ovfs]
        slots = dev * Sp + jnp.arange(Sp, dtype=I32)
        in_range = slots < S
        slot_arg = jnp.minimum(slots, S - 1)
        for fam in bagfams:
            kern, _ = kernels._FAMILY_KERNELS[fam]
            out, valid, ovf = jax.vmap(
                lambda sl: kern(bounds, s, sl))(slot_arg)
            succs.append(out)
            valids.append(jnp.broadcast_to(valid, (Sp,)) & in_range)
            ovfs.append(jnp.broadcast_to(ovf, (Sp,)) & in_range)
        return kernels.finish_expand(bounds, s, succs, valids, ovfs)

    return expand


def build_cp_step(bounds: Bounds, spec: str = "full",
                  invariants: tuple = (), symmetry: tuple = (),
                  ndev: int = 1, view: str | None = None):
    """The dense step's CP twin: ``step(vecs[B, W], dev) -> dict`` with
    ``svecs [B, A_local, W]``, ``valid``/``overflow`` ``[B, A_local]``,
    ``fp_hi/fp_lo``, ``inv_ok``, ``con_ok`` — per-lane values
    bit-identical to ``kernels.build_step`` at ``cp_lane_map``'s dense
    index.  Call inside ``shard_map`` with ``dev = lax.axis_index(axis)``.
    """
    stages = kernels._step_stages(bounds, spec, invariants, symmetry, view)
    lay = stages[0]
    expand = build_cp_expand(bounds, spec, ndev)

    def step(vecs, dev):
        structs = jax.vmap(lambda v: st.unpack(v, lay, jnp))(vecs)
        succs, valid, ovf = jax.vmap(
            lambda t: expand(t, dev))(structs)
        svecs = jax.vmap(jax.vmap(lambda t: st.pack(t, jnp)))(succs)
        fp_hi, fp_lo, inv_ok, con_ok = kernels.apply_stages(
            bounds, stages, symmetry, succs, svecs, valid)
        return {"svecs": svecs, "valid": valid, "overflow": ovf,
                "fp_hi": fp_hi, "fp_lo": fp_lo, "inv_ok": inv_ok,
                "con_ok": con_ok}

    return step
