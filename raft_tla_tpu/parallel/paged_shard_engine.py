"""Paged + sharded composition: a mesh whose stores page to host RAM.

VERDICT r1 next#6.  The plain shard engine (shard_engine.py) keeps every
device's full state store in HBM — flagship-scale spaces do not fit.  The
paged single-chip engine (paged_engine.py) keeps only a ring of the live
BFS window in HBM and pages completed rows to a host store.  This module
composes the two, the architecture the north-star run needs:

- **per-device HBM**: a bit-packed ring of the live window (current +
  next BFS level of the states this device owns) plus the device's shard
  of the fingerprint table — nothing else;
- **dedup exchange**: the shard engine's FP-prefix ownership with an
  ``all_to_all`` per chunk, but the routed payload is the *bit-packed*
  row (ops/bitpack.py, ~8x narrower than the unpacked vector the plain
  shard engine routes);
- **host RAM**: one append-only store per device (utils/native.py, the
  C++ path when built) holding every state that device owns, paged out
  between watchdog-safe segments.  Current scope is single-controller
  (every shard addressable from this host — true on one multi-chip host
  and on the virtual CPU mesh); the multi-host extension is per-host
  stores over exactly the locally-addressable shards, and ``_pageout``
  fails loudly if it meets a shard it cannot address;
- **trace links**: per-row ``(parent_device, parent_local_index, lane)``
  — parent chains hop across devices through the per-device host stores.

Segments yield to the host either when the chunk budget is spent or when
ANY device's ring is within half a ring of lapping its unpaged rows (a
``pmax`` pause flag, the multi-device analog of paged_engine's
``pause_at``); the host pages out every device's new rows and redispatches.
Same watchdog/checkpoint architecture as every other engine: donated
carries, adaptive budgets, atomic digest-guarded snapshots (the digest
pins the mesh size — FP ownership depends on it).

Exploration metrics (state counts, levels, diameter, transition totals,
verdicts) match refbfs exactly; violation traces are valid but possibly
different counterexamples, and per-action coverage matches in total, with
the same attribution caveat as shard_engine.py (module docstring there).
"""

from __future__ import annotations

import dataclasses
import functools
import time
from collections import Counter
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_tla_tpu.config import CheckConfig
from raft_tla_tpu.device_engine import (
    _EMPTY, _dedup_insert, BUCKET, FAIL_INDEX, FAIL_LEVEL, FAIL_PROBE,
    FAIL_RING, FAIL_WIDTH, decode_fail, _acc64_add, _acc64_zero, acc64_int,
    aggregate_coverage)
from raft_tla_tpu.engine import DEADLOCK, EngineResult, Violation
from raft_tla_tpu.obs import RunTelemetry
from raft_tla_tpu.models import interp, invariants as inv_mod, spec as S
from raft_tla_tpu.ops import bitpack
from raft_tla_tpu.ops import kernels
from raft_tla_tpu.ops import state as st
from raft_tla_tpu.ops import symmetry as sym_mod
from raft_tla_tpu.parallel.shard_engine import (FAIL_ROUTE, _DCN,
    _mesh_axes, _shard_map, exchange, make_mesh)
from raft_tla_tpu.utils import ckpt, native, pacing

I32 = jnp.int32
U32 = jnp.uint32
_AXIS = "d"


@dataclasses.dataclass(frozen=True)
class PagedShardCapacities:
    """Per-device static shapes.  ``ring`` must hold the device's widest
    live window (current + next level of its ~1/ndev share); ``table``
    slots bound the device's distinct-state share (load factor <= 0.5 for
    sane probing); ``send`` as in ShardCapacities."""

    ring: int = 1 << 20
    table: int = 1 << 22
    levels: int = 512
    send: Optional[int] = None
    send2: Optional[int] = None    # stage-B depth, 2-D meshes (see
    #                                ShardCapacities.send2)


class PSCarry(NamedTuple):
    """Mesh-wide carry; [dev] leaves are sharded over the mesh axis."""

    store: jax.Array     # [dev] [Rcap, P] bit-packed ring, local discovery
    pdev: jax.Array      # [dev] [Rcap] parent's owner device
    pidx: jax.Array      # [dev] [Rcap] parent's local discovery index
    lane: jax.Array      # [dev] [Rcap]
    conflag: jax.Array   # [dev] [Rcap]
    tbl_hi: jax.Array    # [dev] [TBd, BUCKET]
    tbl_lo: jax.Array    # [dev] [TBd, BUCKET]
    n_states: jax.Array  # [dev] [1] local discovery count
    lvl_start: jax.Array  # [dev] [1] local level window (discovery idx)
    lvl_end: jax.Array   # [dev] [1]
    viol_l: jax.Array    # [dev] [1] local discovery idx of violation, -1
    viol_i: jax.Array    # [dev] [1]
    n_trans: jax.Array   # [dev] [2] uint32 limbs
    cov: jax.Array       # [dev] [A]
    fail: jax.Array      # [dev] [1]
    levels: jax.Array    # replicated [Lcap]
    lvl: jax.Array       # replicated scalar
    c: jax.Array         # replicated scalar
    n_chunks: jax.Array  # replicated scalar
    stop: jax.Array      # replicated scalar bool
    yieldf: jax.Array    # replicated scalar bool: ring needs pageout


_SHARDED = ("store", "pdev", "pidx", "lane", "conflag", "tbl_hi", "tbl_lo",
            "n_states", "lvl_start", "lvl_end", "viol_l", "viol_i",
            "n_trans", "cov", "fail")


def _carry_specs(axes=(_AXIS,)):
    ax = axes if len(axes) > 1 else axes[0]
    return PSCarry(**{f: P(ax) if f in _SHARDED else P()
                      for f in PSCarry._fields})


def _build_segment(config: CheckConfig, caps: PagedShardCapacities, A: int,
                   W: int, ndev: int, schema: bitpack.BitSchema,
                   nici: int | None = None, axes: tuple = (_AXIS,)):
    B = config.chunk
    n_inv = len(config.invariants)
    if n_inv > 29:
        raise ValueError("at most 29 invariants (bit-packed int32 flags)")
    # Orbit-scan variants (prescan, sig-prune) resolve from their env
    # gates at build time — bit-identical keys either way.
    step = kernels.build_step(config.bounds, config.spec,
                              tuple(config.invariants), config.symmetry,
                              view=config.view)
    Rcap, Lcap = caps.ring, caps.levels
    rmask = Rcap - 1
    Pw = schema.P
    Csend = caps.send if caps.send is not None else B * A
    nici = ndev if nici is None else nici
    nslice = ndev // nici
    Csend2 = caps.send2 if caps.send2 is not None else nici * Csend
    NR = nici * Csend if nslice == 1 else nslice * Csend2
    BIG = jnp.int32(np.iinfo(np.int32).max)
    # Index-ceiling headroom must cover the worst-case per-chunk append,
    # which here is the full routed-buffer width NR (every sender fills
    # this owner's routing buffer) — not the single-device engine's 2*B*A.
    IDX_CEIL = jnp.int32(np.iinfo(np.int32).max - 2 * NR)

    def owner(key_hi):
        return (key_hi % jnp.uint32(ndev)).astype(I32)

    def chunk_body(carry: PSCarry) -> PSCarry:
        dev = jax.lax.axis_index(_AXIS).astype(I32) if nslice == 1 else (
            jax.lax.axis_index(_DCN).astype(I32) * nici
            + jax.lax.axis_index(_AXIS).astype(I32))
        lvl_start, lvl_end = carry.lvl_start[0], carry.lvl_end[0]
        n_states, fail = carry.n_states[0], carry.fail[0]
        viol_l, viol_i = carry.viol_l[0], carry.viol_i[0]
        store, pdev, pidx, lane = (carry.store, carry.pdev, carry.pidx,
                                   carry.lane)
        conflag, tbl_hi, tbl_lo = carry.conflag, carry.tbl_hi, carry.tbl_lo
        n_trans, cov = carry.n_trans, carry.cov

        # ---- expand my chunk out of the ring ----
        start = lvl_start + carry.c * B
        rows_g = start + jnp.arange(B, dtype=I32)     # local discovery ids
        row_act = rows_g < lvl_end
        ridx = rows_g & rmask
        vecs = schema.unpack(store[ridx], jnp)
        out = step(vecs)
        con_par = conflag[ridx]
        valid = out["valid"] & row_act[:, None] & con_par[:, None]
        n_trans = _acc64_add(n_trans, jnp.sum(valid.astype(I32)))
        fail = fail | jnp.any(valid & out["overflow"]) * FAIL_WIDTH

        # ---- route candidates to their fingerprint owners ----
        BA = B * A
        fhi = out["fp_hi"].reshape(BA)
        flo = out["fp_lo"].reshape(BA)
        fvalid = valid.reshape(BA)
        flat_b = jnp.arange(BA, dtype=I32) // A
        flat_a = jnp.arange(BA, dtype=I32) % A
        flags = jnp.ones((BA,), I32) | (
            out["con_ok"].reshape(BA).astype(I32) << 1)
        if n_inv:
            iv = out["inv_ok"].reshape(BA, n_inv).astype(I32)
            flags = flags | jnp.sum(
                iv << (2 + jnp.arange(n_inv, dtype=I32))[None, :], axis=1)

        # the routed row is BIT-PACKED — the whole point of the composition
        svecs = schema.pack(out["svecs"].reshape(BA, W), jnp)
        # stage A over ICI to the owner's in-slice chip (1-D: the whole
        # exchange); stage B over DCN in aggregated per-slice blocks
        dest_a = jnp.where(fvalid, owner(fhi) % nici, nici)
        (r_vec, r_hi, r_lo, r_pd, r_pi, r_lane, r_flags), ovf = exchange(
            _AXIS, nici, Csend, dest_a,
            ((svecs, 0, I32), (fhi, _EMPTY, U32), (flo, _EMPTY, U32),
             (jnp.full((BA,), 0, I32) + dev, -1, I32),
             (rows_g[flat_b], -1, I32), (flat_a, -1, I32),
             (flags, 0, I32)))
        fail = fail | ovf * FAIL_ROUTE
        active = (r_flags & 1) == 1
        if nslice > 1:
            dest_b = jnp.where(active, owner(r_hi) // nici, nslice)
            (r_vec, r_hi, r_lo, r_pd, r_pi, r_lane,
             r_flags), ovf2 = exchange(
                _DCN, nslice, Csend2, dest_b,
                ((r_vec, 0, I32), (r_hi, _EMPTY, U32),
                 (r_lo, _EMPTY, U32), (r_pd, -1, I32), (r_pi, -1, I32),
                 (r_lane, -1, I32), (r_flags, 0, I32)))
            fail = fail | ovf2 * FAIL_ROUTE
            active = (r_flags & 1) == 1

        # ---- owner-side dedup + ring append ----
        tbl_hi, tbl_lo, is_new, pfail = _dedup_insert(
            tbl_hi, tbl_lo, r_hi, r_lo, active)
        fail = fail | jnp.any(pfail) * FAIL_PROBE
        pos_st = n_states + jnp.cumsum(is_new.astype(I32)) - 1
        n_new = jnp.sum(is_new.astype(I32))
        # Ring-lap guard.  Two live regions must never be overwritten: the
        # level window being expanded (from lvl_start) AND the rows not yet
        # paged to the host (from the paged watermark — a mesh device can
        # receive up to NR appends in ONE chunk under routing skew, far
        # past the between-chunks pause heuristic).  Exact and loud:
        fail = fail | (n_states + n_new
                       - jnp.minimum(lvl_start, paged_wm) > Rcap) * FAIL_RING
        fail = fail | (n_states > IDX_CEIL) * FAIL_INDEX
        ok = is_new & (pos_st - lvl_start < Rcap)
        sl = jnp.where(ok, pos_st & rmask, Rcap)
        store = store.at[sl].set(r_vec, mode="drop")
        pdev = pdev.at[sl].set(r_pd, mode="drop")
        pidx = pidx.at[sl].set(r_pi, mode="drop")
        lane = lane.at[sl].set(r_lane, mode="drop")
        conflag = conflag.at[sl].set(((r_flags >> 1) & 1) == 1, mode="drop")
        cov = cov.at[jnp.where(is_new, r_lane, A)].add(1, mode="drop")
        n_states = n_states + n_new

        # ---- first violation among my new states ----
        if n_inv:
            inv_bits = (r_flags >> 2) & ((1 << n_inv) - 1)
            inv_bad = is_new & (inv_bits != (1 << n_inv) - 1)
        else:
            inv_bad = jnp.zeros_like(is_new)
        first = jnp.min(jnp.where(
            inv_bad, jnp.arange(NR, dtype=I32), BIG))
        new_viol = (first < BIG) & (viol_l < 0)
        fidx = jnp.minimum(first, NR - 1)
        viol_l = jnp.where(new_viol, pos_st[fidx], viol_l)
        if n_inv:
            bad_inv = jnp.argmax(
                ((r_flags[fidx] >> 2) & (1 << jnp.arange(n_inv))) == 0
            ).astype(I32)
        else:
            bad_inv = jnp.int32(0)
        viol_i = jnp.where(new_viol, bad_inv, viol_i)
        if config.check_deadlock:
            # local deadlock check; attribution caveat as in shard_engine
            dead = row_act & con_par & ~jnp.any(out["valid"], axis=1)
            drow = jnp.min(jnp.where(dead, jnp.arange(B, dtype=I32), BIG))
            dl = (drow < BIG) & (viol_l < 0)
            viol_l = jnp.where(
                dl, start + jnp.minimum(drow, B - 1), viol_l)
            viol_i = jnp.where(dl, jnp.int32(n_inv), viol_i)

        stop = (jax.lax.psum((viol_l >= 0).astype(I32), axes) > 0) | \
            (jax.lax.pmax(fail, axes) != 0)
        # a ring nearing its unpaged rows anywhere -> yield for pageout
        yieldf = jax.lax.pmax(
            (n_states >= paged_wm + half).astype(I32), axes) > 0
        return carry._replace(
            store=store, pdev=pdev, pidx=pidx, lane=lane, conflag=conflag,
            tbl_hi=tbl_hi, tbl_lo=tbl_lo,
            n_states=n_states[None], n_trans=n_trans, cov=cov,
            viol_l=viol_l[None], viol_i=viol_i[None], fail=fail[None],
            stop=stop, yieldf=yieldf, c=carry.c + 1)

    def outer_body(sc):
        steps, carry = sc

        def ccond(cc):
            s, inner = cc
            return ((inner.c < inner.n_chunks) & ~inner.stop
                    & ~inner.yieldf & (s < budget))

        def cbody(cc):
            s, inner = cc
            return s + 1, chunk_body(inner)

        steps, carry = jax.lax.while_loop(ccond, cbody, (steps, carry))
        adv = (carry.c >= carry.n_chunks) & ~carry.stop & ~carry.yieldf
        n_new = carry.n_states[0] - carry.lvl_end[0]
        n_new_tot = jax.lax.psum(n_new, axes)
        levels = jnp.where(
            adv,
            carry.levels.at[jnp.minimum(carry.lvl, Lcap - 1)].set(n_new_tot),
            carry.levels)
        fail = carry.fail[0] | (
            adv & (carry.lvl >= Lcap - 1) & (n_new_tot > 0)) * FAIL_LEVEL
        lvl_start = jnp.where(adv, carry.lvl_end[0], carry.lvl_start[0])
        lvl_end = jnp.where(adv, carry.n_states[0], carry.lvl_end[0])
        n_act = lvl_end - lvl_start
        n_chunks = jnp.where(
            adv, jax.lax.pmax((n_act + B - 1) // B, axes), carry.n_chunks)
        stop = carry.stop | (adv & (n_new_tot == 0)) | \
            (jax.lax.pmax(fail, axes) != 0)
        return steps, carry._replace(
            levels=levels, fail=fail[None],
            lvl_start=lvl_start[None], lvl_end=lvl_end[None],
            lvl=jnp.where(adv, carry.lvl + 1, carry.lvl),
            c=jnp.where(adv, 0, carry.c), n_chunks=n_chunks, stop=stop)

    def outer_cond(sc):
        steps, carry = sc
        return (steps < budget) & ~carry.stop & ~carry.yieldf

    def segment(carry: PSCarry, budget_, paged_d):
        nonlocal budget, paged_wm
        budget = budget_
        paged_wm = paged_d[0]      # this device's host-paged watermark
        # fresh segment: the host just paged out, the yield flag resets
        carry = carry._replace(yieldf=jnp.zeros((), bool))
        steps, carry = jax.lax.while_loop(outer_cond, outer_body,
                                          (jnp.int32(0), carry))
        return steps, carry

    budget = paged_wm = None
    half = Rcap // 2
    return segment


class PagedShardEngine:
    """Mesh-sharded exhaustive checker bounded by host RAM per device."""

    SEG_TARGET_S = 8.0
    SEG_CLAMP_S = 25.0
    SEG_MIN, SEG_MAX = 16, 1 << 16
    PAGE_ROWS = 1 << 16          # fixed pageout gather width (one compile)

    def __init__(self, config: CheckConfig, mesh: Mesh | None = None,
                 caps: PagedShardCapacities | None = None,
                 seg_chunks: int = 64):
        self.config = config
        self.bounds = config.bounds
        self.lay = st.Layout.of(self.bounds)
        self.table = S.action_table(self.bounds, config.spec)
        self.A = len(self.table)
        self.mesh = mesh if mesh is not None else make_mesh()
        self.ndev = self.mesh.devices.size
        self.caps = caps or PagedShardCapacities()
        for nm in ("ring", "table"):
            v = getattr(self.caps, nm)
            if v & (v - 1):
                raise ValueError(f"{nm}={v} must be a power of two "
                                 "(bucket/ring masks are bitwise)")
        if self.caps.ring < 2 * config.chunk * self.A:
            raise ValueError(
                f"ring={self.caps.ring} must be >= 2 * chunk * A = "
                f"{2 * config.chunk * self.A} (pageout headroom; worst-"
                "case routing skew is guarded loudly in-kernel)")
        # trace links pack (lane, parent_device) into one int32 word:
        # lane in bits 0..15, device in bits 16..23 (_extract_trace)
        if self.ndev > 1 << 8:
            raise ValueError(f"at most {1 << 8} devices (link-word field)")
        if self.A > 1 << 16:
            raise ValueError("action table exceeds the link-word field")
        self.seg_chunks = seg_chunks
        self.schema = bitpack.BitSchema(self.bounds)
        axes = _mesh_axes(self.mesh)
        nici = self.mesh.shape[_AXIS]
        specs = _carry_specs(axes)
        fn = _build_segment(config, self.caps, self.A, self.lay.width,
                            self.ndev, self.schema, nici=nici, axes=axes)
        paged_spec = P(axes if len(axes) > 1 else axes[0])
        self._segment = jax.jit(_shard_map(
            fn, mesh=self.mesh,
            in_specs=(specs, P(), paged_spec),
            out_specs=(P(), specs),
            check_vma=False), donate_argnums=(0,))
        self._shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), specs)

    def _put(self, carry: PSCarry) -> PSCarry:
        return PSCarry(*(jax.device_put(x, s)
                         for x, s in zip(carry, self._shardings)))

    def _init_carry(self, init_packed, hi0, lo0, con0) -> PSCarry:
        nd, Rcap, A = self.ndev, self.caps.ring, self.A
        Pw, Lcap = self.schema.P, self.caps.levels
        TBd = self.caps.table // BUCKET
        own = int(np.uint32(hi0) % np.uint32(nd))
        store = np.zeros((nd * Rcap, Pw), np.int32)
        store[own * Rcap] = init_packed
        pdev = np.full((nd * Rcap,), -1, np.int32)
        pidx = np.full((nd * Rcap,), -1, np.int32)
        lane = np.full((nd * Rcap,), -1, np.int32)
        conflag = np.zeros((nd * Rcap,), bool)
        conflag[own * Rcap] = con0
        tbl_hi = np.full((nd * TBd, BUCKET), _EMPTY, np.uint32)
        tbl_lo = np.full((nd * TBd, BUCKET), _EMPTY, np.uint32)
        b0 = int(np.uint32(lo0) & np.uint32(TBd - 1))
        tbl_hi[own * TBd + b0, 0] = hi0
        tbl_lo[own * TBd + b0, 0] = lo0
        n0 = np.zeros((nd,), np.int32)
        n0[own] = 1
        return self._put(PSCarry(
            store=store, pdev=pdev, pidx=pidx, lane=lane, conflag=conflag,
            tbl_hi=tbl_hi, tbl_lo=tbl_lo,
            n_states=n0, lvl_start=np.zeros((nd,), np.int32),
            lvl_end=n0.copy(),
            viol_l=np.full((nd,), -1, np.int32),
            viol_i=np.zeros((nd,), np.int32),
            n_trans=np.zeros((nd * 2,), np.uint32),
            cov=np.zeros((nd * A,), np.int32),
            fail=np.zeros((nd,), np.int32),
            levels=np.zeros((Lcap,), np.int32),
            lvl=np.int32(1), c=np.int32(0), n_chunks=np.int32(1),
            stop=np.bool_(False), yieldf=np.bool_(False)))

    # -- pageout --------------------------------------------------------

    def _shard_data(self, arr, d: int):
        """Device d's local block of a [dev]-sharded global array."""
        for sh in arr.addressable_shards:
            # a fully-replicated / single-shard index reads slice(None)
            if (sh.index[0].start or 0) == d * (arr.shape[0] // self.ndev):
                return sh.data
        raise RuntimeError(f"shard {d} not addressable from this host")

    def _pageout(self, carry: PSCarry, hosts: list, paged: list) -> list:
        """Copy each device's rows [paged[d], n_states[d]) from its ring
        into its host store.  Per-device gathers run on the owning device;
        only the gathered block crosses to the host."""
        rmask = self.caps.ring - 1
        n_d = np.asarray(jax.device_get(carry.n_states))
        iota = np.arange(self.PAGE_ROWS, dtype=np.int32)
        for d in range(self.ndev):
            n = int(n_d[d])
            st_d = self._shard_data(carry.store, d)
            pd_d = self._shard_data(carry.pdev, d)
            pi_d = self._shard_data(carry.pidx, d)
            la_d = self._shard_data(carry.lane, d)
            dev_obj = list(st_d.devices())[0]
            while paged[d] < n:
                k = min(n - paged[d], self.PAGE_ROWS)
                gidx = np.minimum(paged[d] + iota, n - 1)
                # the gather runs on the owning device; only the gathered
                # block crosses to the host
                ridx = jax.device_put(jnp.asarray(gidx & rmask), dev_obj)
                rows, pdv, piv, lav = jax.device_get(
                    (st_d[ridx], pd_d[ridx], pi_d[ridx], la_d[ridx]))
                hosts[d].append(rows[:k])
                # lane (bits 0..15) and parent device (16..23) share a word
                hosts[d].append_links(
                    piv[:k], lav[:k] | (pdv[:k].astype(np.int32) << 16))
                paged[d] += k
        return paged

    # -- checkpoint / resume --------------------------------------------

    def save_checkpoint(self, path: str, carry: PSCarry, hosts: list,
                        paged: list, init_key: tuple) -> None:
        for d in range(self.ndev):
            ckpt.stream_rows_out(f"{path}.rows{d}", hosts[d].read,
                                 paged[d], self.schema.P)

            def links_reader(start, n, _d=d):
                par, lan = hosts[_d].read_links(start, n)
                return np.stack([par, lan], axis=1)

            ckpt.stream_rows_out(f"{path}.links{d}", links_reader,
                                 paged[d], 2)
        arrs = jax.device_get(carry)
        ckpt.atomic_savez(
            path,
            **{f"c{i}": np.asarray(x) for i, x in enumerate(arrs)},
            paged=np.asarray(paged, np.int64),
            config_digest=np.uint64(ckpt.config_digest(
                self.config, self.caps, init_key + (self.ndev,))))

    def load_checkpoint(self, path: str, init_key: tuple):
        with ckpt.load_npz_checked(
                path, ckpt.config_digest(
                    self.config, self.caps,
                    init_key + (self.ndev,))) as z:
            carry = PSCarry(*(jnp.asarray(z[f"c{i}"])
                              for i in range(len(PSCarry._fields))))
            paged = [int(x) for x in z["paged"]]
        hosts = [native.make_store(self.schema.P) for _ in range(self.ndev)]
        for d in range(self.ndev):
            ckpt.stream_rows_in(f"{path}.rows{d}", hosts[d].append,
                                paged[d], expect_width=self.schema.P)
            ckpt.stream_rows_in(
                f"{path}.links{d}",
                lambda blk, _d=d: hosts[_d].append_links(
                    blk[:, 0], blk[:, 1]),
                paged[d], expect_width=2)
        return self._put(carry), hosts, paged

    # -- public API -----------------------------------------------------

    def check(self, init_override: interp.PyState | None = None,
              checkpoint: str | None = None,
              checkpoint_every_s: float = 600.0,
              resume: str | None = None,
              on_progress=None, events: str | None = None) -> EngineResult:
        t0 = time.monotonic()
        tel = RunTelemetry(
            "pagedshard", config=self.config, caps=self.caps,
            on_progress=on_progress, events=events,
            resumed=resume is not None,
            n0=1 if resume is None else None,
            n_devices=self.ndev, t0=t0)
        try:
            return self._check_impl(tel, t0, init_override, checkpoint,
                                    checkpoint_every_s, resume)
        finally:
            tel.close()

    def _check_impl(self, tel, t0, init_override, checkpoint,
                    checkpoint_every_s, resume) -> EngineResult:
        bounds = self.bounds
        init_py = init_override if init_override is not None \
            else interp.init_state(bounds)
        init_vec = interp.to_vec(init_py, bounds)
        hi0, lo0 = sym_mod.init_fingerprint(self.config, init_py, init_vec)
        tel.run_start()

        for nm in self.config.invariants:
            if not inv_mod.py_invariant(nm)(init_py, bounds):
                res = EngineResult(
                    n_states=1, diameter=0, n_transitions=0,
                    coverage=Counter(),
                    violation=Violation(nm, init_py, [(None, init_py)]),
                    levels=[1], wall_s=time.monotonic() - t0)
                tel.run_end(res)
                return res

        if resume:
            carry, hosts, paged = self.load_checkpoint(resume, (hi0, lo0))
        else:
            init_packed = self.schema.pack(
                np.asarray(init_vec, np.int32), np)
            carry = self._init_carry(
                init_packed, np.uint32(hi0), np.uint32(lo0),
                bool(interp.constraint_ok(init_py, bounds)))
            hosts = [native.make_store(self.schema.P)
                     for _ in range(self.ndev)]
            paged = [0] * self.ndev

        pacer = pacing.SegmentPacer(self.seg_chunks, self.SEG_MIN,
                                    self.SEG_MAX, self.SEG_TARGET_S,
                                    self.SEG_CLAMP_S)
        budget = pacer.budget
        last_ckpt = time.monotonic()
        while True:
            paged_d = jnp.asarray(np.asarray(paged, np.int32))
            t_seg = time.monotonic()
            with tel.phases.phase("expand") as ph:
                steps_d, carry = self._segment(carry, jnp.int32(budget),
                                               paged_d)
                ph.sync(steps_d)
            with tel.phases.phase("export"):
                paged = self._pageout(carry, hosts, paged)
            if tel.active:
                n_states_d, lvl, n_trans_d, cov_arr = jax.device_get(
                    (carry.n_states, carry.lvl, carry.n_trans, carry.cov))
                tel.segment(
                    n_states=int(np.asarray(n_states_d).sum()),
                    level=int(lvl), n_transitions=acc64_int(n_trans_d),
                    coverage=dict(aggregate_coverage(self.table, cov_arr)))
            if bool(np.asarray(carry.stop)):
                break
            dt = time.monotonic() - t_seg
            executed = max(1, int(np.asarray(steps_d)))
            if checkpoint and (time.monotonic() - last_ckpt
                               >= checkpoint_every_s):
                with tel.phases.phase("snapshot"):
                    self.save_checkpoint(checkpoint, carry, hosts, paged,
                                         (hi0, lo0))
                tel.checkpoint(checkpoint)
                last_ckpt = time.monotonic()
            budget = pacer.update(dt, executed)
            self.seg_chunks = budget

        (n_states_d, viol_ls, viol_is, n_trans_d, fail_d, n_levels,
         levels_dev, cov_arr) = jax.device_get(
             (carry.n_states, carry.viol_l, carry.viol_i, carry.n_trans,
              carry.fail, carry.lvl, carry.levels, carry.cov))
        fail = int(np.bitwise_or.reduce(np.asarray(fail_d)))
        if fail:
            raise RuntimeError(
                f"paged-shard search aborted: {decode_fail(fail)} "
                f"(caps={self.caps}, ndev={self.ndev}) — grow "
                "PagedShardCapacities and rerun")
        n_states = int(np.asarray(n_states_d).sum())
        levels_arr = [1] + [int(x) for x in
                            np.asarray(levels_dev)[:int(n_levels)]
                            if int(x) > 0]
        cov_tot = np.asarray(cov_arr).reshape(self.ndev, self.A).sum(axis=0)
        coverage: Counter = Counter()
        for a, inst in enumerate(self.table):
            if cov_tot[a]:
                coverage[inst.family] += int(cov_tot[a])

        violation = None
        viol_ls = np.asarray(viol_ls)
        viol_devs = np.nonzero(viol_ls >= 0)[0]
        if viol_devs.size:
            d = int(viol_devs[0])
            violation = self._extract_trace(
                hosts, d, int(viol_ls[d]), int(np.asarray(viol_is)[d]))
        for h in hosts:
            h.close()

        result = EngineResult(
            n_states=n_states,
            diameter=len(levels_arr) - 1,
            n_transitions=acc64_int(n_trans_d),
            coverage=coverage,
            violation=violation,
            levels=levels_arr,
            wall_s=time.monotonic() - t0)
        tel.run_end(result)
        return result

    def _extract_trace(self, hosts: list, dev: int, lidx: int,
                       viol_i: int) -> Violation:
        """Walk the parent chain across the per-device host stores."""
        chain = []                     # (dev, local idx) root..violation
        d, li = dev, lidx
        while li >= 0:
            chain.append((d, li))
            par, word = hosts[d].read_links(li, 1)
            li = int(par[0])
            d = (int(word[0]) >> 16) & 0xFF
        chain.reverse()
        out = []
        for k, (cd, cl) in enumerate(chain):
            row = self.schema.unpack(hosts[cd].read(cl, 1)[0], np)
            py = interp.from_struct(st.unpack(row, self.lay, np),
                                    self.bounds)
            if k == 0:
                out.append((None, py))
            else:
                _par, word = hosts[cd].read_links(cl, 1)
                out.append((self.table[int(word[0]) & 0xFFFF].label(), py))
        inv_name = DEADLOCK if viol_i == len(self.config.invariants) \
            else self.config.invariants[viol_i]
        return Violation(invariant=inv_name, state=out[-1][1], trace=out)


def check(config: CheckConfig, mesh: Mesh | None = None,
          caps: PagedShardCapacities | None = None, **kw) -> EngineResult:
    return PagedShardEngine(config, mesh, caps).check(**kw)
