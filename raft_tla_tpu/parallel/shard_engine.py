"""Multi-chip sharded BFS engine — L4 over ICI (SURVEY §7.1 step 7, §2.9).

The reference is single-process (TLC's distributed mode is unused —
SURVEY §2.9); this module is the scale-out design the task demands, built the
TPU way: ``jax.sharding.Mesh`` + ``shard_map`` + XLA collectives, not
NCCL/MPI.  The whole multi-device search is still **one jitted computation**
(the device_engine.py architecture), with three collectives in the hot loop:

- **all_to_all** — fingerprint-prefix dedup exchange (SURVEY §2.9 row SP):
  every chip owns the slice of fingerprint space ``fp_hi % n_dev == d``.
  After a chip expands a chunk of its local frontier, each candidate
  successor is routed to its owner chip, which alone consults/updates its
  local fingerprint table.  Because a state's owner is a pure function of its
  fingerprint, a state is only ever deduplicated in one place — no global
  table, no host round-trips.
- **pmax** — lockstep chunk scheduling: devices run the same number of chunk
  iterations per level (all_to_all requires all participants), idle rows
  masked off.
- **psum** — termination detection (frontier empty everywhere), violation
  broadcast, level histograms, coverage and transition totals.

Data placement per device (all static shapes): its shard of the store
(states it owns, in local discovery order), parent **global ids**
(``dev * n_states_cap + local_idx`` — trace chains cross chips), lane ids,
constraint flags, and the local fingerprint table.  The frontier is a
contiguous store segment per device, exactly as in device_engine.py — BFS is
level-synchronous, and new states append to their owner's store.

Load balance comes from the hash: fingerprints are avalanche-mixed
(ops/fingerprint.py), so each chip owns ~1/n of every level's new states.
This is the checker's DP axis; the per-state action fan-out is its TP axis
(SURVEY §2.9).

Determinism: within a device, candidate order is (sender device, send slot) —
fixed — so parent links and local discovery order are reproducible run to
run.  Global discovery order differs from the single-chip engines (states
interleave across chips), so total counts, per-level counts, transition
counts, verdicts and diameter all match refbfs/DeviceEngine exactly, while
(a) a violation trace may be a *different valid counterexample* than the
single-chip one (still replayable — tested), and (b) per-action coverage
*attribution* can differ when the same new state is producible by several
actions within one level — the first discoverer gets credit, and "first"
depends on interleaving.  Coverage *totals* still equal n_states - 1
(every non-initial state credited exactly once); TLC's own multi-worker
mode has the same attribution nondeterminism.

Differences vs TLC's distributed mode (Java sockets, central fingerprint
server): here dedup is sharded, not centralized, and the exchange is a
single fused collective per chunk on the ICI fabric.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from collections import Counter
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from raft_tla_tpu.config import CheckConfig
from raft_tla_tpu.device_engine import _EMPTY, _dedup_insert, BUCKET
from raft_tla_tpu.engine import DEADLOCK, EngineResult, Violation
from raft_tla_tpu.models import interp, invariants as inv_mod, spec as S
from raft_tla_tpu.ops import fingerprint as fpr
from raft_tla_tpu.ops import kernels
from raft_tla_tpu.ops import state as st
from raft_tla_tpu.ops import symmetry as sym_mod

I32 = jnp.int32
U32 = jnp.uint32
_AXIS = "d"     # the frontier/fingerprint mesh axis (DP, SURVEY §2.9)


@dataclasses.dataclass(frozen=True)
class ShardCapacities:
    """Static shapes of one compiled sharded search (per-device where noted).

    ``send`` is the per-destination routing buffer depth per chunk; ``None``
    means the safe bound ``chunk * A`` (no overflow possible).  Smaller
    values trade memory for a loud abort if one chip's candidates concentrate
    on one owner (hash-uniform, so ~BA/n expected).
    """

    n_states: int = 1 << 17      # store rows per device
    levels: int = 256
    send: Optional[int] = None

    @property
    def table(self) -> int:      # per-device hash slots, load factor <= 0.5
        return 1 << (2 * self.n_states - 1).bit_length()


def make_mesh(n_devices: int | None = None) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` devices (default: all)."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"need {n_devices} devices, have {len(devs)} "
                "(tests: --xla_force_host_platform_device_count)")
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (_AXIS,))


def _build_sharded_search(config: CheckConfig, caps: ShardCapacities,
                          A: int, W: int, ndev: int):
    """The per-device program; run under shard_map over the ``d`` axis."""
    B = config.chunk
    n_inv = len(config.invariants)
    if n_inv > 29:
        raise ValueError("at most 29 invariants (bit-packed into int32 flags)")
    step = kernels.build_step(config.bounds, config.spec,
                              tuple(config.invariants), config.symmetry)
    Ncap, Lcap, Tcap = caps.n_states, caps.levels, caps.table
    Csend = caps.send if caps.send is not None else B * A
    BIG = jnp.int32(np.iinfo(np.int32).max)

    def owner(key_hi):
        """FP-prefix shard map: which device dedups/stores this state."""
        return (key_hi % jnp.uint32(ndev)).astype(I32)

    def chunk_body(carry, c):
        (store, parent, lane, conflag, tbl_hi, tbl_lo, n_states,
         lvl_start, lvl_end, viol_g, viol_i, n_trans, cov, fail, stop) = carry
        dev = jax.lax.axis_index(_AXIS).astype(I32)

        # ---- expand my chunk (rows may be inactive on ragged levels) ----
        start = lvl_start + c * B
        gstart = jnp.clip(start, 0, Ncap - B)
        rows_l = gstart + jnp.arange(B, dtype=I32)
        row_act = (rows_l >= start) & (rows_l < lvl_end)
        vecs = jax.lax.dynamic_slice(store, (gstart, 0), (B, W))
        out = step(vecs)
        con_par = jax.lax.dynamic_slice(conflag, (gstart,), (B,))
        valid = out["valid"] & row_act[:, None] & con_par[:, None]
        n_trans = n_trans + jnp.sum(valid.astype(I32))
        fail = fail | jnp.any(valid & out["overflow"])

        # ---- route candidates to their fingerprint owners ----
        BA = B * A
        fhi = out["fp_hi"].reshape(BA)
        flo = out["fp_lo"].reshape(BA)
        fvalid = valid.reshape(BA)
        dest = jnp.where(fvalid, owner(fhi), ndev)
        oh = (dest[:, None] == jnp.arange(ndev, dtype=I32)[None, :])
        cum = jnp.cumsum(oh.astype(I32), axis=0)
        pos = jnp.take_along_axis(
            cum, jnp.clip(dest, 0, ndev - 1)[:, None], axis=1)[:, 0] - 1
        fail = fail | jnp.any(fvalid & (pos >= Csend))   # routing overflow
        slot = jnp.where(fvalid & (pos < Csend), dest * Csend + pos,
                         ndev * Csend)

        flat_b = jnp.arange(BA, dtype=I32) // A
        flat_a = jnp.arange(BA, dtype=I32) % A
        # flags: bit0 occupied, bit1 con_ok, bits 2.. per-invariant ok
        flags = jnp.ones((BA,), I32) | (
            out["con_ok"].reshape(BA).astype(I32) << 1)
        if n_inv:
            iv = out["inv_ok"].reshape(BA, n_inv).astype(I32)
            flags = flags | jnp.sum(
                iv << (2 + jnp.arange(n_inv, dtype=I32))[None, :], axis=1)

        def scatter(val, fill, dtype):
            buf = jnp.full((ndev * Csend,) + val.shape[1:], fill, dtype)
            return buf.at[slot].set(val.astype(dtype), mode="drop")

        svecs = out["svecs"].reshape(BA, W)
        s_vec = scatter(svecs, 0, I32).reshape(ndev, Csend, W)
        s_hi = scatter(fhi, _EMPTY, U32).reshape(ndev, Csend)
        s_lo = scatter(flo, _EMPTY, U32).reshape(ndev, Csend)
        s_par = scatter(dev * Ncap + gstart + flat_b, -1, I32).reshape(
            ndev, Csend)
        s_lane = scatter(flat_a, -1, I32).reshape(ndev, Csend)
        s_flags = scatter(flags, 0, I32).reshape(ndev, Csend)

        a2a = functools.partial(jax.lax.all_to_all, axis_name=_AXIS,
                                split_axis=0, concat_axis=0, tiled=True)
        r_vec = a2a(s_vec).reshape(ndev * Csend, W)
        r_hi = a2a(s_hi).reshape(ndev * Csend)
        r_lo = a2a(s_lo).reshape(ndev * Csend)
        r_par = a2a(s_par).reshape(ndev * Csend)
        r_lane = a2a(s_lane).reshape(ndev * Csend)
        r_flags = a2a(s_flags).reshape(ndev * Csend)
        active = (r_flags & 1) == 1

        # ---- owner-side dedup + append (same protocol as device_engine) ----
        tbl_hi, tbl_lo, is_new, pfail = _dedup_insert(
            tbl_hi, tbl_lo, r_hi, r_lo, active)
        fail = fail | pfail
        pos_st = n_states + jnp.cumsum(is_new.astype(I32)) - 1
        sl = jnp.where(is_new & (pos_st < Ncap), pos_st, Ncap)
        store = store.at[sl].set(r_vec, mode="drop")
        parent = parent.at[sl].set(r_par, mode="drop")
        lane = lane.at[sl].set(r_lane, mode="drop")
        conflag = conflag.at[sl].set(((r_flags >> 1) & 1) == 1, mode="drop")
        cov = cov.at[jnp.where(is_new, r_lane, A)].add(1, mode="drop")
        n_new = jnp.sum(is_new.astype(I32))
        fail = fail | (n_states + n_new > Ncap)
        n_states = jnp.minimum(n_states + n_new, Ncap)

        # ---- first invariant violation among my new states ----
        if n_inv:
            inv_bits = (r_flags >> 2) & ((1 << n_inv) - 1)
            inv_bad = is_new & (inv_bits != (1 << n_inv) - 1)
        else:
            inv_bad = jnp.zeros_like(is_new)
        first = jnp.min(jnp.where(
            inv_bad, jnp.arange(ndev * Csend, dtype=I32), BIG))
        new_viol = (first < BIG) & (viol_g < 0)
        fidx = jnp.minimum(first, ndev * Csend - 1)
        viol_g = jnp.where(new_viol, dev * Ncap + pos_st[fidx], viol_g)
        if n_inv:
            bad_inv = jnp.argmax(
                ((r_flags[fidx] >> 2) & (1 << jnp.arange(n_inv))) == 0
            ).astype(I32)
        else:
            bad_inv = jnp.int32(0)
        viol_i = jnp.where(new_viol, bad_inv, viol_i)
        if config.check_deadlock:
            # TLC's default deadlock check, device-locally: an expanded row
            # with no enabled action.  Which event is reported first when a
            # deadlock and a violation coexist is interleaving-dependent
            # here, like coverage attribution (module docstring) — either
            # is a correct counterexample.
            dead = row_act & con_par & ~jnp.any(out["valid"], axis=1)
            drow = jnp.min(jnp.where(dead, jnp.arange(B, dtype=I32), BIG))
            dl = (drow < BIG) & (viol_g < 0)
            viol_g = jnp.where(
                dl, dev * Ncap + gstart + jnp.minimum(drow, B - 1), viol_g)
            viol_i = jnp.where(dl, jnp.int32(n_inv), viol_i)

        # replicated stop flag: any device saw a violation or failed
        stop = (jax.lax.psum((viol_g >= 0).astype(I32), _AXIS) > 0) | \
            (jax.lax.pmax(fail.astype(I32), _AXIS) > 0)
        return (store, parent, lane, conflag, tbl_hi, tbl_lo, n_states,
                lvl_start, lvl_end, viol_g, viol_i, n_trans, cov, fail, stop)

    def level_body(carry):
        (store, parent, lane, conflag, tbl_hi, tbl_lo, n_states,
         lvl_start, lvl_end, viol_g, viol_i, n_trans, cov, fail, stop,
         levels, lvl) = carry
        # lockstep chunk count across devices (all_to_all needs everyone)
        n_act = lvl_end - lvl_start
        n_chunks = jax.lax.pmax((n_act + B - 1) // B, _AXIS)

        def ccond(c_carry):
            c, inner = c_carry
            return (c < n_chunks) & ~inner[14]

        def cbody(c_carry):
            c, inner = c_carry
            return c + 1, chunk_body(inner, c)

        inner = (store, parent, lane, conflag, tbl_hi, tbl_lo, n_states,
                 lvl_start, lvl_end, viol_g, viol_i, n_trans, cov, fail,
                 jnp.bool_(False))
        _, inner = jax.lax.while_loop(ccond, cbody, (jnp.int32(0), inner))
        (store, parent, lane, conflag, tbl_hi, tbl_lo, n_states,
         lvl_start, lvl_end, viol_g, viol_i, n_trans, cov, fail,
         stop) = inner
        n_new_tot = jax.lax.psum(n_states - lvl_end, _AXIS)  # replicated
        levels = levels.at[jnp.minimum(lvl, Lcap - 1)].set(n_new_tot)
        fail = fail | ((lvl >= Lcap - 1) & (n_new_tot > 0))
        stop = stop | (jax.lax.pmax(fail.astype(I32), _AXIS) > 0) | \
            (n_new_tot == 0)
        return (store, parent, lane, conflag, tbl_hi, tbl_lo, n_states,
                lvl_end, n_states, viol_g, viol_i, n_trans, cov, fail,
                stop, levels, lvl + 1)

    def level_cond(carry):
        stop = carry[14]
        return ~stop

    def search(init_vec, init_hi, init_lo, init_con):
        """Per-device program.  Scalar inputs are replicated."""
        dev = jax.lax.axis_index(_AXIS).astype(I32)
        mine = owner(init_hi) == dev
        store = jnp.zeros((Ncap, W), I32).at[0].set(
            jnp.where(mine, init_vec, 0))
        parent = jnp.full((Ncap,), -1, I32)
        lane = jnp.full((Ncap,), -1, I32)
        conflag = jnp.zeros((Ncap,), bool).at[0].set(mine & init_con)
        TBd = Tcap // BUCKET
        ib = (init_lo & jnp.uint32(TBd - 1)).astype(I32)
        tbl_hi = jnp.full((TBd, BUCKET), _EMPTY, U32).at[ib, 0].set(
            jnp.where(mine, init_hi, _EMPTY))
        tbl_lo = jnp.full((TBd, BUCKET), _EMPTY, U32).at[ib, 0].set(
            jnp.where(mine, init_lo, _EMPTY))
        levels = jnp.zeros((Lcap,), I32)
        n0 = jnp.where(mine, 1, 0).astype(I32)
        carry = (store, parent, lane, conflag, tbl_hi, tbl_lo,
                 n0, jnp.int32(0), n0,
                 jnp.int32(-1), jnp.int32(0), jnp.int32(0),
                 jnp.zeros((A,), I32), jnp.bool_(False), jnp.bool_(False),
                 levels, jnp.int32(1))
        carry = jax.lax.while_loop(level_cond, level_body, carry)
        (store, parent, lane, conflag, _th, _tl, n_states, _ls, _le,
         viol_g, viol_i, n_trans, cov, fail, _stop, levels, lvl) = carry
        return {
            # sharded outputs (global view is the concatenation over devices)
            "store": store, "parent": parent, "lane": lane,
            "n_states": n_states[None], "viol_g": viol_g[None],
            "viol_i": viol_i[None], "fail": fail[None],
            # replicated outputs
            "n_transitions": jax.lax.psum(n_trans, _AXIS),
            "coverage": jax.lax.psum(cov, _AXIS),
            "levels": levels, "n_levels": lvl,
        }

    return search


class ShardEngine:
    """One compiled multi-device exhaustive checker; reusable across runs."""

    def __init__(self, config: CheckConfig, mesh: Mesh | None = None,
                 caps: ShardCapacities | None = None):
        self.config = config
        self.bounds = config.bounds
        self.lay = st.Layout.of(self.bounds)
        self.table = S.action_table(self.bounds, config.spec)
        self.A = len(self.table)
        self.mesh = mesh if mesh is not None else make_mesh()
        self.ndev = self.mesh.devices.size
        self.caps = caps or ShardCapacities()
        if self.caps.n_states < config.chunk:
            raise ValueError("ShardCapacities.n_states must be >= chunk")
        fn = _build_sharded_search(config, self.caps, self.A,
                                   self.lay.width, self.ndev)
        sharded = {"store": P(_AXIS), "parent": P(_AXIS), "lane": P(_AXIS),
                   "n_states": P(_AXIS), "viol_g": P(_AXIS),
                   "viol_i": P(_AXIS), "fail": P(_AXIS)}
        out_specs = {k: sharded.get(k, P()) for k in (
            "store", "parent", "lane", "n_states", "viol_g", "viol_i",
            "fail", "n_transitions", "coverage", "levels", "n_levels")}
        self._search = jax.jit(jax.shard_map(
            fn, mesh=self.mesh,
            in_specs=(P(), P(), P(), P()),   # replicated init
            out_specs=out_specs, check_vma=False))

    def check(self, init_override: interp.PyState | None = None
              ) -> EngineResult:
        t0 = time.monotonic()
        bounds = self.bounds
        init_py = init_override if init_override is not None \
            else interp.init_state(bounds)
        init_vec = interp.to_vec(init_py, bounds)
        hi0, lo0 = sym_mod.init_fingerprint(self.config, init_py,
                                            init_vec)

        for nm in self.config.invariants:
            if not inv_mod.py_invariant(nm)(init_py, bounds):
                return EngineResult(
                    n_states=1, diameter=0, n_transitions=0,
                    coverage=Counter(),
                    violation=Violation(nm, init_py, [(None, init_py)]),
                    levels=[1], wall_s=time.monotonic() - t0)

        out = self._search(jnp.asarray(init_vec, I32), jnp.uint32(hi0),
                           jnp.uint32(lo0),
                           jnp.bool_(interp.constraint_ok(init_py, bounds)))
        n_states = int(np.asarray(out["n_states"]).sum())
        if bool(np.asarray(out["fail"]).any()):
            raise RuntimeError(
                "sharded search aborted: store/level/probe/routing capacity "
                f"exceeded (caps={self.caps}, ndev={self.ndev}) — grow "
                "ShardCapacities and rerun")
        viol_gs = np.asarray(out["viol_g"])
        viol_devs = np.nonzero(viol_gs >= 0)[0]
        n_levels = int(out["n_levels"])
        levels_arr = [1] + [int(x) for x in
                            np.asarray(out["levels"][:n_levels]) if int(x) > 0]
        if viol_devs.size and len(levels_arr) > 1:
            levels_arr = levels_arr[:-1]    # violating level is partial
        cov_arr = np.asarray(out["coverage"])
        coverage: Counter = Counter()
        for a, inst in enumerate(self.table):
            if cov_arr[a]:
                coverage[inst.family] += int(cov_arr[a])

        violation = None
        if viol_devs.size:
            d = int(viol_devs[0])
            violation = self._extract_trace(
                out, int(viol_gs[d]), int(np.asarray(out["viol_i"])[d]))

        return EngineResult(
            n_states=n_states,
            diameter=len(levels_arr) - 1,
            n_transitions=int(out["n_transitions"]),
            coverage=coverage,
            violation=violation,
            levels=levels_arr,
            wall_s=time.monotonic() - t0)

    def _extract_trace(self, out, viol_g: int, viol_i: int) -> Violation:
        """Walk the cross-device parent chain through the global arrays."""
        parent = np.asarray(out["parent"])   # [ndev * Ncap]
        lane = np.asarray(out["lane"])
        chain_idx = []
        cur = viol_g
        while cur >= 0:
            chain_idx.append(cur)
            cur = int(parent[cur])
        chain_idx.reverse()
        rows = np.asarray(out["store"][jnp.asarray(chain_idx)])
        chain = []
        for k, g in enumerate(chain_idx):
            py = interp.from_struct(
                st.unpack(rows[k], self.lay, np), self.bounds)
            label = self.table[int(lane[g])].label() if k > 0 else None
            chain.append((label, py))
        inv_name = DEADLOCK if viol_i == len(self.config.invariants) \
            else self.config.invariants[viol_i]
        return Violation(invariant=inv_name, state=chain[-1][1], trace=chain)


@functools.lru_cache(maxsize=None)
def _cached_engine(config: CheckConfig, mesh: Mesh,
                   caps: ShardCapacities) -> ShardEngine:
    return ShardEngine(config, mesh, caps)


def check(config: CheckConfig, mesh: Mesh | None = None,
          caps: ShardCapacities | None = None, **kw) -> EngineResult:
    """One-shot convenience mirroring the other engines' ``check``."""
    return _cached_engine(config, mesh if mesh is not None else make_mesh(),
                          caps or ShardCapacities()).check(**kw)
