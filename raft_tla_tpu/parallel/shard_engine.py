"""Multi-chip sharded BFS engine — L4 over ICI (SURVEY §7.1 step 7, §2.9).

The reference is single-process (TLC's distributed mode is unused —
SURVEY §2.9); this module is the scale-out design the task demands, built the
TPU way: ``jax.sharding.Mesh`` + ``shard_map`` + XLA collectives, not
NCCL/MPI.  The multi-device search runs as **watchdog-safe segments** (the
device_engine.py architecture): one jitted program advances the whole mesh by
up to ``budget`` chunk expansions and returns the carry with its buffers
donated back into the next dispatch — so a search of any length survives the
deployment tunnel's ~60 s program watchdog, the host can snapshot the carry
for checkpoint/resume (TLC ``-recover``), and per-segment stats stream out.
Three collectives run in the hot loop:

- **all_to_all** — fingerprint-prefix dedup exchange (SURVEY §2.9 row SP):
  every chip owns the slice of fingerprint space ``fp_hi % n_dev == d``.
  After a chip expands a chunk of its local frontier, each candidate
  successor is routed to its owner chip, which alone consults/updates its
  local fingerprint table.  Because a state's owner is a pure function of its
  fingerprint, a state is only ever deduplicated in one place — no global
  table, no host round-trips.
- **pmax** — lockstep chunk scheduling: devices run the same number of chunk
  iterations per level (all_to_all requires all participants), idle rows
  masked off.
- **psum** — termination detection (frontier empty everywhere), violation
  broadcast, level histograms, coverage and transition totals.

Data placement per device (all static shapes): its shard of the store
(states it owns, in local discovery order), parent **global ids**
(``dev * n_states_cap + local_idx`` — trace chains cross chips), lane ids,
constraint flags, and the local fingerprint table.  The frontier is a
contiguous store segment per device, exactly as in device_engine.py — BFS is
level-synchronous, and new states append to their owner's store.

Load balance comes from the hash: fingerprints are avalanche-mixed
(ops/fingerprint.py), so each chip owns ~1/n of every level's new states.
This is the checker's DP axis; the per-state action fan-out is its TP axis
(SURVEY §2.9).

Determinism: within a device, candidate order is (sender device, send slot) —
fixed — so parent links and local discovery order are reproducible run to
run, and a checkpoint resume replays the identical search.  Global discovery
order differs from the single-chip engines (states interleave across chips),
so total counts, per-level counts, transition counts, verdicts and diameter
all match refbfs/DeviceEngine exactly, while (a) a violation trace may be a
*different valid counterexample* than the single-chip one (still replayable —
tested), and (b) per-action coverage *attribution* can differ when the same
new state is producible by several actions within one level — the first
discoverer gets credit, and "first" depends on interleaving.  Coverage
*totals* still equal n_states - 1 (every non-initial state credited exactly
once); TLC's own multi-worker mode has the same attribution nondeterminism.

Differences vs TLC's distributed mode (Java sockets, central fingerprint
server): here dedup is sharded, not centralized, and the exchange is a
single fused collective per chunk on the ICI fabric.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from collections import Counter
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_tla_tpu.config import CheckConfig
from raft_tla_tpu.device_engine import (
    _EMPTY, _dedup_insert, BUCKET, FAIL_LEVEL, FAIL_PROBE, FAIL_ROUTE,
    FAIL_STORE, FAIL_WIDTH, decode_fail, _acc64_add, acc64_int,
    aggregate_coverage, widen_legacy_n_trans)
from raft_tla_tpu.engine import DEADLOCK, EngineResult, Violation
from raft_tla_tpu.obs import RunTelemetry
from raft_tla_tpu.models import interp, invariants as inv_mod, spec as S
from raft_tla_tpu.ops import fingerprint as fpr
from raft_tla_tpu.ops import kernels
from raft_tla_tpu.ops import state as st
from raft_tla_tpu.ops import symmetry as sym_mod
from raft_tla_tpu.utils import ckpt
from raft_tla_tpu.utils import pacing

I32 = jnp.int32
U32 = jnp.uint32


def _shard_map(fn, *, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` across the promotion boundary: the public name
    (with ``check_vma``) only exists in newer jax; older releases have
    the pre-promotion ``jax.experimental.shard_map`` (``check_rep``)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=check_vma)


_AXIS = "d"     # the frontier/fingerprint mesh axis (DP, SURVEY §2.9)
_DCN = "dcn"    # outer mesh axis for multi-slice scale-out (SURVEY §2.9
#                 comm-backend row: ICI within a slice, DCN across slices)


@dataclasses.dataclass(frozen=True)
class ShardCapacities:
    """Static shapes of one compiled sharded search (per-device where noted).

    ``send`` is the per-destination routing buffer depth per chunk; ``None``
    means the safe bound ``chunk * A`` (no overflow possible).  Smaller
    values trade memory for a loud abort if one chip's candidates
    concentrate on one destination.  Expected occupancy is hash-uniform
    over the STAGE-A destination count: ~BA/ndev on a 1-D mesh but
    ~BA/per_slice on a 2-D mesh (stage A routes within the slice), so a
    ``send`` tuned on a flat mesh must be rescaled by ndev/per_slice when
    moving to a slice mesh.  ``send2`` is the stage-B (cross-slice, 2-D
    only) per-destination-slice depth; ``None`` means the safe bound
    ``per_slice * send``.
    """

    n_states: int = 1 << 17      # store rows per device
    levels: int = 256
    send: Optional[int] = None
    send2: Optional[int] = None

    @property
    def table(self) -> int:      # per-device hash slots, load factor <= 0.5
        return 1 << (2 * self.n_states - 1).bit_length()


def make_mesh(n_devices: int | None = None) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` devices (default: all)."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"need {n_devices} devices, have {len(devs)} "
                "(tests: --xla_force_host_platform_device_count)")
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (_AXIS,))


def make_slice_mesh(n_slices: int, per_slice: int) -> Mesh:
    """A 2-D ``(dcn, ici)`` mesh: ``n_slices`` pod slices of ``per_slice``
    chips.  The outer axis rides DCN, the inner ICI; the hierarchical
    dedup exchange (stage A over ICI, stage B over DCN) keeps cross-slice
    traffic aggregated into per-slice blocks.  On real multi-slice pods
    the device order from ``jax.devices()`` groups by slice already; under
    the virtual CPU mesh the reshape just fixes the flat-id convention
    ``dev = slice * per_slice + chip``."""
    devs = jax.devices()
    if n_slices * per_slice > len(devs):
        raise ValueError(
            f"need {n_slices * per_slice} devices, have {len(devs)} "
            "(tests: --xla_force_host_platform_device_count)")
    grid = np.asarray(devs[:n_slices * per_slice]).reshape(
        n_slices, per_slice)
    return Mesh(grid, (_DCN, _AXIS))


def _mesh_axes(mesh: Mesh) -> tuple:
    """Collective axis names spanning every device of ``mesh``."""
    return (_DCN, _AXIS) if _DCN in mesh.axis_names else (_AXIS,)


class SCarry(NamedTuple):
    """The segment carry — the entire mesh-wide search state.

    Leaves marked [dev] are sharded over the mesh axis (global leading dim
    ``ndev * per-device``; scalars are shape-[1] per device, [ndev] global);
    the rest are replicated lockstep values, identical on every device by
    construction (they only change through psum/pmax results).
    """

    store: jax.Array      # [dev] [Ncap, W] states this device owns
    parent: jax.Array     # [dev] [Ncap] parent GLOBAL id (dev*Ncap + row)
    lane: jax.Array       # [dev] [Ncap]
    conflag: jax.Array    # [dev] [Ncap]
    tbl_hi: jax.Array     # [dev] [TBd, BUCKET]
    tbl_lo: jax.Array     # [dev] [TBd, BUCKET]
    n_states: jax.Array   # [dev] [1]
    lvl_start: jax.Array  # [dev] [1] local level window
    lvl_end: jax.Array    # [dev] [1]
    viol_g: jax.Array     # [dev] [1] first violating GLOBAL id, -1 if none
    viol_i: jax.Array     # [dev] [1] invariant index (n_inv = deadlock)
    n_trans: jax.Array    # [dev] [2] uint32 limbs (64-bit counter)
    cov: jax.Array        # [dev] [A]
    fail: jax.Array       # [dev] [1] FAIL_* bitmask
    levels: jax.Array     # replicated [Lcap] global per-level new states
    lvl: jax.Array        # replicated scalar
    c: jax.Array          # replicated scalar: chunk cursor within level
    n_chunks: jax.Array   # replicated scalar: lockstep chunks this level
    stop: jax.Array       # replicated scalar bool


_SHARDED = ("store", "parent", "lane", "conflag", "tbl_hi", "tbl_lo",
            "n_states", "lvl_start", "lvl_end", "viol_g", "viol_i",
            "n_trans", "cov", "fail")


def _carry_specs(axes=(_AXIS,)):
    ax = axes if len(axes) > 1 else axes[0]
    return SCarry(**{f: P(ax) if f in _SHARDED else P()
                     for f in SCarry._fields})



def exchange(axis_name, n_dest, cap, dest, payload):
    """Count-sort ``payload`` rows into per-destination blocks and
    all_to_all them over one mesh axis (shared by the shard and
    paged-shard engines; the 2-D hierarchical exchange is two calls —
    stage A over ICI, stage B over DCN).  ``dest >= n_dest`` drops the
    row; ``payload`` is a sequence of (values, fill, dtype).  Returns
    (received payload, overflow flag)."""
    oh = (dest[:, None] == jnp.arange(n_dest, dtype=I32)[None, :])
    cum = jnp.cumsum(oh.astype(I32), axis=0)
    pos = jnp.take_along_axis(
        cum, jnp.clip(dest, 0, n_dest - 1)[:, None], axis=1)[:, 0] - 1
    live = dest < n_dest
    overflow = jnp.any(live & (pos >= cap))
    slot = jnp.where(live & (pos < cap), dest * cap + pos, n_dest * cap)
    a2a = functools.partial(jax.lax.all_to_all, axis_name=axis_name,
                            split_axis=0, concat_axis=0, tiled=True)
    outs = []
    for val, fill, dtype in payload:
        buf = jnp.full((n_dest * cap,) + val.shape[1:], fill, dtype)
        buf = buf.at[slot].set(val.astype(dtype), mode="drop")
        outs.append(a2a(buf.reshape((n_dest, cap) + val.shape[1:]))
                    .reshape((n_dest * cap,) + val.shape[1:]))
    return outs, overflow


def _build_segment(config: CheckConfig, caps: ShardCapacities,
                   A: int, W: int, ndev: int, nici: int | None = None,
                   axes: tuple = (_AXIS,)):
    """One watchdog-safe slice of the mesh-wide search (<= budget chunks).

    ``nici`` (2-D meshes): devices per slice; the dedup exchange then runs
    hierarchically — stage A routes candidates over ICI to the owner's
    in-slice index, stage B forwards them over DCN to the owner's slice in
    aggregated per-slice blocks (one DCN message per destination slice per
    chunk instead of per destination chip)."""
    B = config.chunk
    n_inv = len(config.invariants)
    if n_inv > 29:
        raise ValueError("at most 29 invariants (bit-packed into int32 flags)")
    # Orbit-scan variants (prescan, sig-prune) resolve from their env
    # gates at build time; keys stay bit-identical either way, so mixed
    # settings across reshard/resume cannot corrupt the store.
    step = kernels.build_step(config.bounds, config.spec,
                              tuple(config.invariants), config.symmetry,
                              view=config.view)
    Ncap, Lcap = caps.n_states, caps.levels
    Csend = caps.send if caps.send is not None else B * A
    nici = ndev if nici is None else nici
    nslice = ndev // nici
    Csend2 = caps.send2 if caps.send2 is not None else nici * Csend
    NR = nici * Csend if ndev // nici == 1 else (ndev // nici) * Csend2
    BIG = jnp.int32(np.iinfo(np.int32).max)

    def owner(key_hi):
        """FP shard map: FLAT device id ``slice * nici + chip`` that dedups
        and stores this state (slice decomposition does not change it, so
        checkpoints move between 1-D and 2-D meshes of equal size)."""
        return (key_hi % jnp.uint32(ndev)).astype(I32)

    def chunk_body(carry: SCarry) -> SCarry:
        dev = jax.lax.axis_index(_AXIS).astype(I32) if nslice == 1 else (
            jax.lax.axis_index(_DCN).astype(I32) * nici
            + jax.lax.axis_index(_AXIS).astype(I32))
        lvl_start, lvl_end = carry.lvl_start[0], carry.lvl_end[0]
        n_states, fail = carry.n_states[0], carry.fail[0]
        viol_g, viol_i = carry.viol_g[0], carry.viol_i[0]
        store, parent, lane = carry.store, carry.parent, carry.lane
        conflag, tbl_hi, tbl_lo = carry.conflag, carry.tbl_hi, carry.tbl_lo
        n_trans, cov = carry.n_trans, carry.cov

        # ---- expand my chunk (rows may be inactive on ragged levels) ----
        start = lvl_start + carry.c * B
        gstart = jnp.clip(start, 0, Ncap - B)
        rows_l = gstart + jnp.arange(B, dtype=I32)
        row_act = (rows_l >= start) & (rows_l < lvl_end)
        vecs = jax.lax.dynamic_slice(store, (gstart, 0), (B, W))
        out = step(vecs)
        con_par = jax.lax.dynamic_slice(conflag, (gstart,), (B,))
        valid = out["valid"] & row_act[:, None] & con_par[:, None]
        n_trans = _acc64_add(n_trans, jnp.sum(valid.astype(I32)))
        fail = fail | jnp.any(valid & out["overflow"]) * FAIL_WIDTH

        # ---- route candidates to their fingerprint owners ----
        BA = B * A
        fhi = out["fp_hi"].reshape(BA)
        flo = out["fp_lo"].reshape(BA)
        fvalid = valid.reshape(BA)

        flat_b = jnp.arange(BA, dtype=I32) // A
        flat_a = jnp.arange(BA, dtype=I32) % A
        # flags: bit0 occupied, bit1 con_ok, bits 2.. per-invariant ok
        flags = jnp.ones((BA,), I32) | (
            out["con_ok"].reshape(BA).astype(I32) << 1)
        if n_inv:
            iv = out["inv_ok"].reshape(BA, n_inv).astype(I32)
            flags = flags | jnp.sum(
                iv << (2 + jnp.arange(n_inv, dtype=I32))[None, :], axis=1)
        svecs = out["svecs"].reshape(BA, W)
        par_g = dev * Ncap + gstart + flat_b

        # stage A over ICI: route to the owner's in-slice chip index (for
        # 1-D meshes nici == ndev and this IS the whole exchange)
        dest_a = jnp.where(fvalid, owner(fhi) % nici, nici)
        (r_vec, r_hi, r_lo, r_par, r_lane, r_flags), ovf = exchange(
            _AXIS, nici, Csend, dest_a,
            ((svecs, 0, I32), (fhi, _EMPTY, U32), (flo, _EMPTY, U32),
             (par_g, -1, I32), (flat_a, -1, I32), (flags, 0, I32)))
        fail = fail | ovf * FAIL_ROUTE
        active = (r_flags & 1) == 1
        if nslice > 1:
            # stage B over DCN: every active row already sits on the
            # owner's chip index; forward to the owner's slice in one
            # aggregated block per destination slice
            dest_b = jnp.where(active, owner(r_hi) // nici, nslice)
            (r_vec, r_hi, r_lo, r_par, r_lane, r_flags), ovf2 = exchange(
                _DCN, nslice, Csend2, dest_b,
                ((r_vec, 0, I32), (r_hi, _EMPTY, U32),
                 (r_lo, _EMPTY, U32), (r_par, -1, I32),
                 (r_lane, -1, I32), (r_flags, 0, I32)))
            fail = fail | ovf2 * FAIL_ROUTE
            active = (r_flags & 1) == 1

        # ---- owner-side dedup + append (same protocol as device_engine) ----
        tbl_hi, tbl_lo, is_new, pfail = _dedup_insert(
            tbl_hi, tbl_lo, r_hi, r_lo, active)
        fail = fail | jnp.any(pfail) * FAIL_PROBE
        pos_st = n_states + jnp.cumsum(is_new.astype(I32)) - 1
        sl = jnp.where(is_new & (pos_st < Ncap), pos_st, Ncap)
        store = store.at[sl].set(r_vec, mode="drop")
        parent = parent.at[sl].set(r_par, mode="drop")
        lane = lane.at[sl].set(r_lane, mode="drop")
        conflag = conflag.at[sl].set(((r_flags >> 1) & 1) == 1, mode="drop")
        cov = cov.at[jnp.where(is_new, r_lane, A)].add(1, mode="drop")
        n_new = jnp.sum(is_new.astype(I32))
        fail = fail | (n_states + n_new > Ncap) * FAIL_STORE
        n_states = jnp.minimum(n_states + n_new, Ncap)

        # ---- first invariant violation among my new states ----
        if n_inv:
            inv_bits = (r_flags >> 2) & ((1 << n_inv) - 1)
            inv_bad = is_new & (inv_bits != (1 << n_inv) - 1)
        else:
            inv_bad = jnp.zeros_like(is_new)
        first = jnp.min(jnp.where(
            inv_bad, jnp.arange(NR, dtype=I32), BIG))
        new_viol = (first < BIG) & (viol_g < 0)
        fidx = jnp.minimum(first, NR - 1)
        viol_g = jnp.where(new_viol, dev * Ncap + pos_st[fidx], viol_g)
        if n_inv:
            bad_inv = jnp.argmax(
                ((r_flags[fidx] >> 2) & (1 << jnp.arange(n_inv))) == 0
            ).astype(I32)
        else:
            bad_inv = jnp.int32(0)
        viol_i = jnp.where(new_viol, bad_inv, viol_i)
        if config.check_deadlock:
            # TLC's default deadlock check, device-locally: an expanded row
            # with no enabled action.  Which event is reported first when a
            # deadlock and a violation coexist is interleaving-dependent
            # here, like coverage attribution (module docstring) — either
            # is a correct counterexample.
            dead = row_act & con_par & ~jnp.any(out["valid"], axis=1)
            drow = jnp.min(jnp.where(dead, jnp.arange(B, dtype=I32), BIG))
            dl = (drow < BIG) & (viol_g < 0)
            viol_g = jnp.where(
                dl, dev * Ncap + gstart + jnp.minimum(drow, B - 1), viol_g)
            viol_i = jnp.where(dl, jnp.int32(n_inv), viol_i)

        # replicated stop flag: any device saw a violation or failed
        stop = (jax.lax.psum((viol_g >= 0).astype(I32), axes) > 0) | \
            (jax.lax.pmax(fail, axes) != 0)
        return carry._replace(
            store=store, parent=parent, lane=lane, conflag=conflag,
            tbl_hi=tbl_hi, tbl_lo=tbl_lo,
            n_states=n_states[None], n_trans=n_trans, cov=cov,
            viol_g=viol_g[None], viol_i=viol_i[None], fail=fail[None],
            stop=stop, c=carry.c + 1)

    def outer_body(sc):
        """Run chunks until the level is exhausted, the budget runs out, or
        a stop event lands; then (maybe) advance the level window."""
        steps, carry = sc

        def ccond(cc):
            s, inner = cc
            return (inner.c < inner.n_chunks) & ~inner.stop & (s < budget)

        def cbody(cc):
            s, inner = cc
            return s + 1, chunk_body(inner)

        steps, carry = jax.lax.while_loop(ccond, cbody, (steps, carry))
        # Level advance (lockstep: c/n_chunks/stop are replicated).
        adv = (carry.c >= carry.n_chunks) & ~carry.stop
        n_new = carry.n_states[0] - carry.lvl_end[0]
        n_new_tot = jax.lax.psum(n_new, axes)
        levels = jnp.where(
            adv,
            carry.levels.at[jnp.minimum(carry.lvl, Lcap - 1)].set(n_new_tot),
            carry.levels)
        fail = carry.fail[0] | (
            adv & (carry.lvl >= Lcap - 1) & (n_new_tot > 0)) * FAIL_LEVEL
        lvl_start = jnp.where(adv, carry.lvl_end[0], carry.lvl_start[0])
        lvl_end = jnp.where(adv, carry.n_states[0], carry.lvl_end[0])
        n_act = lvl_end - lvl_start
        n_chunks = jnp.where(
            adv, jax.lax.pmax((n_act + B - 1) // B, axes), carry.n_chunks)
        stop = carry.stop | (adv & (n_new_tot == 0)) | \
            (jax.lax.pmax(fail, axes) != 0)
        return steps, carry._replace(
            levels=levels, fail=fail[None],
            lvl_start=lvl_start[None], lvl_end=lvl_end[None],
            lvl=jnp.where(adv, carry.lvl + 1, carry.lvl),
            c=jnp.where(adv, 0, carry.c), n_chunks=n_chunks, stop=stop)

    def outer_cond(sc):
        steps, carry = sc
        return (steps < budget) & ~carry.stop

    def segment(carry: SCarry, budget_):
        nonlocal budget
        budget = budget_
        steps, carry = jax.lax.while_loop(outer_cond, outer_body,
                                          (jnp.int32(0), carry))
        # Executed chunk count (lockstep-replicated) — the host divides the
        # segment wall time by THIS, not the requested budget, so a segment
        # cut short never underestimates per-chunk cost (advisor finding).
        return steps, carry

    budget = None
    return segment


class ShardEngine:
    """Segmented multi-device exhaustive checker; reusable across runs.

    Same watchdog/checkpoint architecture as DeviceEngine: donated carries,
    adaptive segment budgets, atomic digest-guarded snapshots."""

    SEG_TARGET_S = 8.0
    SEG_CLAMP_S = 25.0
    SEG_MIN, SEG_MAX = 16, 1 << 16

    def __init__(self, config: CheckConfig, mesh: Mesh | None = None,
                 caps: ShardCapacities | None = None, seg_chunks: int = 256):
        self.config = config
        self.bounds = config.bounds
        self.lay = st.Layout.of(self.bounds)
        self.table = S.action_table(self.bounds, config.spec)
        self.A = len(self.table)
        self.mesh = mesh if mesh is not None else make_mesh()
        self.ndev = self.mesh.devices.size
        self.caps = caps or ShardCapacities()
        if self.caps.n_states < config.chunk:
            raise ValueError("ShardCapacities.n_states must be >= chunk")
        # Global state ids are int32 ``dev * Ncap + row`` (parent links,
        # viol_g): the address space must fit, or ids on high-numbered
        # devices wrap negative — corrupt traces and a silently missed
        # violation stop.  Fail at construction, not mid-run.
        if self.ndev * self.caps.n_states > 2**31 - 1:
            raise ValueError(
                f"ndev * n_states = {self.ndev} * {self.caps.n_states} "
                "exceeds the int32 global-id space (2^31-1); shrink "
                "ShardCapacities.n_states")
        self.seg_chunks = seg_chunks
        axes = _mesh_axes(self.mesh)
        nici = self.mesh.shape[_AXIS]
        specs = _carry_specs(axes)
        fn = _build_segment(config, self.caps, self.A, self.lay.width,
                            self.ndev, nici=nici, axes=axes)
        self._segment = jax.jit(_shard_map(
            fn, mesh=self.mesh, in_specs=(specs, P()),
            out_specs=(P(), specs),
            check_vma=False), donate_argnums=(0,))
        self._shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), specs)

    # -- carry construction / checkpointing ---------------------------------

    def _init_carry(self, init_vec, hi0, lo0, con0) -> SCarry:
        """Host-built initial carry: Init lives on its fingerprint owner."""
        nd, Ncap, A = self.ndev, self.caps.n_states, self.A
        W, Lcap = self.lay.width, self.caps.levels
        TBd = self.caps.table // BUCKET
        own = int(np.uint32(hi0) % np.uint32(nd))
        store = np.zeros((nd * Ncap, W), np.int32)
        store[own * Ncap] = init_vec
        parent = np.full((nd * Ncap,), -1, np.int32)
        lane = np.full((nd * Ncap,), -1, np.int32)
        conflag = np.zeros((nd * Ncap,), bool)
        conflag[own * Ncap] = con0
        tbl_hi = np.full((nd * TBd, BUCKET), _EMPTY, np.uint32)
        tbl_lo = np.full((nd * TBd, BUCKET), _EMPTY, np.uint32)
        b0 = int(np.uint32(lo0) & np.uint32(TBd - 1))
        tbl_hi[own * TBd + b0, 0] = hi0
        tbl_lo[own * TBd + b0, 0] = lo0
        n0 = np.zeros((nd,), np.int32)
        n0[own] = 1
        carry = SCarry(
            store=store, parent=parent, lane=lane, conflag=conflag,
            tbl_hi=tbl_hi, tbl_lo=tbl_lo,
            n_states=n0, lvl_start=np.zeros((nd,), np.int32),
            lvl_end=n0.copy(),
            viol_g=np.full((nd,), -1, np.int32),
            viol_i=np.zeros((nd,), np.int32),
            n_trans=np.zeros((nd * 2,), np.uint32),
            cov=np.zeros((nd * A,), np.int32),
            fail=np.zeros((nd,), np.int32),
            levels=np.zeros((Lcap,), np.int32),
            lvl=np.int32(1), c=np.int32(0), n_chunks=np.int32(1),
            stop=np.bool_(False))
        return self._put(carry)

    def _put(self, carry: SCarry) -> SCarry:
        return SCarry(*(jax.device_put(x, s)
                        for x, s in zip(carry, self._shardings)))

    def save_checkpoint(self, path: str, carry: SCarry,
                        init_key: tuple) -> None:
        """Atomic digest-guarded snapshot of the mesh-wide carry (the mesh
        size joins the digest key — a checkpoint is only resumable on an
        equal-size mesh, since the FP-ownership map depends on it)."""
        host = jax.device_get(carry)
        ckpt.atomic_savez(
            path,
            **{f"c{i}": np.asarray(x) for i, x in enumerate(host)},
            config_digest=np.uint64(ckpt.config_digest(
                self.config, self.caps, init_key + (self.ndev,))))

    def load_checkpoint(self, path: str, init_key: tuple) -> SCarry:
        with ckpt.load_npz_checked(
                path, ckpt.config_digest(
                    self.config, self.caps,
                    init_key + (self.ndev,))) as z:
            arrs = [z[f"c{i}"] for i in range(len(SCarry._fields))]
        return self._put(SCarry(*widen_legacy_n_trans(
            arrs, SCarry._fields)))

    # -- public API ----------------------------------------------------------

    def check(self, init_override: interp.PyState | None = None,
              checkpoint: str | None = None,
              checkpoint_every_s: float = 600.0,
              resume: str | None = None,
              on_progress=None, events: str | None = None) -> EngineResult:
        t0 = time.monotonic()
        tel = RunTelemetry(
            "shard", config=self.config, caps=self.caps,
            on_progress=on_progress, events=events,
            resumed=resume is not None,
            n0=1 if resume is None else None,
            n_devices=self.ndev, t0=t0)
        try:
            return self._check_impl(tel, t0, init_override, checkpoint,
                                    checkpoint_every_s, resume)
        finally:
            tel.close()

    def _check_impl(self, tel, t0, init_override, checkpoint,
                    checkpoint_every_s, resume) -> EngineResult:
        bounds = self.bounds
        init_py = init_override if init_override is not None \
            else interp.init_state(bounds)
        init_vec = interp.to_vec(init_py, bounds)
        hi0, lo0 = sym_mod.init_fingerprint(self.config, init_py,
                                            init_vec)
        tel.run_start()

        for nm in self.config.invariants:
            if not inv_mod.py_invariant(nm)(init_py, bounds):
                res = EngineResult(
                    n_states=1, diameter=0, n_transitions=0,
                    coverage=Counter(),
                    violation=Violation(nm, init_py, [(None, init_py)]),
                    levels=[1], wall_s=time.monotonic() - t0)
                tel.run_end(res)
                return res

        carry = self.load_checkpoint(resume, (hi0, lo0)) if resume \
            else self._init_carry(
                np.asarray(init_vec, np.int32), np.uint32(hi0),
                np.uint32(lo0), bool(interp.constraint_ok(init_py, bounds)))

        pacer = pacing.SegmentPacer(self.seg_chunks, self.SEG_MIN,
                                    self.SEG_MAX, self.SEG_TARGET_S,
                                    self.SEG_CLAMP_S)
        budget = pacer.budget
        last_ckpt = time.monotonic()
        while True:
            t_seg = time.monotonic()
            with tel.phases.phase("expand") as ph:
                steps_d, carry = self._segment(carry, jnp.int32(budget))
                ph.sync(steps_d)
            if tel.active:
                with tel.phases.phase("export"):
                    n_states_d, lvl, n_trans_d, cov_arr = jax.device_get(
                        (carry.n_states, carry.lvl, carry.n_trans,
                         carry.cov))
                tel.segment(
                    n_states=int(np.asarray(n_states_d).sum()),
                    level=int(lvl), n_transitions=acc64_int(n_trans_d),
                    coverage=dict(aggregate_coverage(self.table, cov_arr)))
            if bool(np.asarray(carry.stop)):
                break
            dt = time.monotonic() - t_seg
            executed = max(1, int(np.asarray(steps_d)))
            if checkpoint and (time.monotonic() - last_ckpt
                               >= checkpoint_every_s):
                with tel.phases.phase("snapshot"):
                    self.save_checkpoint(checkpoint, carry, (hi0, lo0))
                tel.checkpoint(checkpoint)
                last_ckpt = time.monotonic()
            budget = pacer.update(dt, executed)
            self.seg_chunks = budget

        (n_states_d, viol_gs, viol_is, n_trans_d, fail_d, n_levels,
         levels_dev, cov_arr) = jax.device_get(
             (carry.n_states, carry.viol_g, carry.viol_i, carry.n_trans,
              carry.fail, carry.lvl, carry.levels, carry.cov))
        fail = int(np.bitwise_or.reduce(np.asarray(fail_d)))
        if fail:
            raise RuntimeError(
                f"sharded search aborted: {decode_fail(fail)} "
                f"(caps={self.caps}, ndev={self.ndev}) — grow "
                "ShardCapacities and rerun")
        n_states = int(np.asarray(n_states_d).sum())
        viol_gs = np.asarray(viol_gs)
        viol_devs = np.nonzero(viol_gs >= 0)[0]
        # The partially-explored violating level is never recorded (the
        # level window only advances on completed levels), matching refbfs.
        levels_arr = [1] + [int(x) for x in
                            np.asarray(levels_dev)[:int(n_levels)]
                            if int(x) > 0]
        cov_tot = np.asarray(cov_arr).reshape(self.ndev, self.A).sum(axis=0)
        coverage: Counter = Counter()
        for a, inst in enumerate(self.table):
            if cov_tot[a]:
                coverage[inst.family] += int(cov_tot[a])

        violation = None
        if viol_devs.size:
            d = int(viol_devs[0])
            violation = self._extract_trace(
                carry, int(viol_gs[d]), int(np.asarray(viol_is)[d]))

        result = EngineResult(
            n_states=n_states,
            diameter=len(levels_arr) - 1,
            n_transitions=acc64_int(n_trans_d),
            coverage=coverage,
            violation=violation,
            levels=levels_arr,
            wall_s=time.monotonic() - t0)
        tel.run_end(result)
        return result

    def _extract_trace(self, carry: SCarry, viol_g: int,
                       viol_i: int) -> Violation:
        """Walk the cross-device parent chain through the global arrays."""
        parent = np.asarray(carry.parent)   # [ndev * Ncap]
        lane = np.asarray(carry.lane)
        chain_idx = []
        cur = viol_g
        while cur >= 0:
            chain_idx.append(cur)
            cur = int(parent[cur])
        chain_idx.reverse()
        rows = np.asarray(carry.store[jnp.asarray(chain_idx)])
        chain = []
        for k, g in enumerate(chain_idx):
            py = interp.from_struct(
                st.unpack(rows[k], self.lay, np), self.bounds)
            label = self.table[int(lane[g])].label() if k > 0 else None
            chain.append((label, py))
        inv_name = DEADLOCK if viol_i == len(self.config.invariants) \
            else self.config.invariants[viol_i]
        return Violation(invariant=inv_name, state=chain[-1][1], trace=chain)


@functools.lru_cache(maxsize=None)
def _cached_engine(config: CheckConfig, mesh: Mesh,
                   caps: ShardCapacities) -> ShardEngine:
    return ShardEngine(config, mesh, caps)


def check(config: CheckConfig, mesh: Mesh | None = None,
          caps: ShardCapacities | None = None, **kw) -> EngineResult:
    """One-shot convenience mirroring the other engines' ``check``."""
    return _cached_engine(config, mesh if mesh is not None else make_mesh(),
                          caps or ShardCapacities()).check(**kw)


def reshard_checkpoint(config: CheckConfig, caps_src: ShardCapacities,
                       src_path: str, dst_path: str, ndev_dst: int,
                       caps_dst: ShardCapacities | None = None,
                       init_override: interp.PyState | None = None) -> dict:
    """Rewrite a shard-engine checkpoint for a different mesh size.

    A snapshot's FP-ownership map (``owner = fp_hi % ndev``) and its
    global discovery ids (``dev * Ncap + row``) are baked into the saved
    carry, so the digest pins the mesh size — without this loader, a
    pod-size change discards a multi-hour run.  The resharder rebuilds
    the carry host-side from first principles:

    - every stored state's dedup key is **recomputed** from its packed
      row (the fp/orbit pipeline is deterministic, so keys are
      bit-identical to the original run's) and the state moves to its
      new owner ``hi % ndev_dst``;
    - the already-expanded prefix of the current BFS window (``c``
      lockstep chunks) is **promoted into the done region** — expanded
      is expanded, whichever device now holds the row — so mid-level
      snapshots reshard exactly: the new window holds only unexpanded
      rows, ``c`` resets to 0, and level accounting (``levels``, the
      post-window next-level states) is unchanged;
    - parent links are remapped old-gid -> new-gid (traces survive);
    - per-device fingerprint tables are rebuilt by replaying the
      engine's own ``_dedup_insert`` over each new device's keys in
      its new discovery order;
    - counters that only ever report as mesh-wide sums (``n_trans``,
      ``cov``) are totalled onto device 0.

    ``caps_dst`` may also grow ``n_states``/``table`` (rescuing a run
    near FAIL_STORE/FAIL_PROBE); it defaults to ``caps_src``.  Refuses
    runs that already stopped, failed, or found a violation.  Returns a
    summary dict (per-device state counts, window sizes).
    """
    caps_dst = caps_dst or caps_src
    bounds = config.bounds
    lay = st.Layout.of(bounds)
    A = len(S.action_table(bounds, config.spec))
    B = config.chunk
    W = lay.width
    Ncap_s, Ncap_d = caps_src.n_states, caps_dst.n_states
    if ndev_dst * Ncap_d > 2**31 - 1:
        raise ValueError("ndev_dst * n_states exceeds the int32 global-id "
                         "address space")

    init_py = init_override if init_override is not None \
        else interp.init_state(bounds)
    init_vec = interp.to_vec(init_py, bounds)
    hi0, lo0 = sym_mod.init_fingerprint(config, init_py, init_vec)
    init_key = (int(hi0), int(lo0))

    with ckpt.load_npz_verified(src_path) as z:
        arrs = [np.asarray(z[f"c{i}"])
                for i in range(len(SCarry._fields))]
        stored_digest = int(z["config_digest"])
    arrs = widen_legacy_n_trans(arrs, SCarry._fields)
    src = SCarry(*arrs)
    nd_src = src.n_states.shape[0]
    want = ckpt.config_digest(config, caps_src, init_key + (nd_src,))
    if stored_digest != np.uint64(want):
        raise ValueError(
            f"checkpoint digest mismatch: {src_path} was not written by "
            f"this config/caps on a {nd_src}-device mesh")
    if bool(np.asarray(src.stop)):
        raise ValueError("run already complete (stop flag set) — "
                         "nothing to reshard")
    if int(np.bitwise_or.reduce(src.fail)) != 0:
        raise ValueError(f"refusing to reshard a failed run: "
                         f"{decode_fail(int(np.bitwise_or.reduce(src.fail)))}")
    if (src.viol_g >= 0).any():
        raise ValueError("refusing to reshard a run with a recorded "
                         "violation")

    # -- recompute every stored state's dedup key (batched, jitted) --------
    consts_j = jnp.asarray(fpr.lane_constants(W))
    faithful = "allLogs" in lay.shapes
    if config.symmetry:
        # host one-off: the unpruned scan is fine here (sig-prune keys
        # are bit-identical, so either variant reproduces the store)
        orbit = sym_mod.build_orbit_fp(bounds, tuple(config.symmetry),
                                       consts_j, faithful)

        @jax.jit
        def fp_batch(vecs):
            structs = jax.vmap(lambda v: st.unpack(v, lay, jnp))(vecs)
            return orbit(structs)
    else:
        @jax.jit
        def fp_batch(vecs):
            return fpr.fingerprint(vecs, consts_j, jnp)

    # -- live rows in (group, old_dev, row) order, fully vectorized --------
    # group 0: done + expanded window prefix; 1: unexpanded window;
    # 2: next-level states.  Everything below is array-at-a-time so a
    # flagship-scale (10^8-row) rescue stays in numpy, not Python loops.
    store = src.store.reshape(nd_src, Ncap_s, W)
    c_cur = int(np.asarray(src.c))
    devs_l, rows_l, grp_l = [], [], []
    for d in range(nd_src):
        ns_d = int(src.n_states[d])
        ls_d, le_d = int(src.lvl_start[d]), int(src.lvl_end[d])
        ec_d = min(c_cur * B, le_d - ls_d)       # expanded window prefix
        g = np.empty((ns_d,), np.int8)
        g[:ls_d + ec_d] = 0
        g[ls_d + ec_d:le_d] = 1
        g[le_d:] = 2
        devs_l.append(np.full((ns_d,), d, np.int64))
        rows_l.append(np.arange(ns_d, dtype=np.int64))
        grp_l.append(g)
    devs = np.concatenate(devs_l)
    rows = np.concatenate(rows_l)
    grp = np.concatenate(grp_l)
    M = devs.size
    if M == 0:
        raise ValueError("empty checkpoint")
    # concat order is dev-major with ascending rows, so a stable sort on
    # group alone yields (group, dev, row) lexicographic order
    order = np.argsort(grp, kind="stable")
    devs, rows, grp = devs[order], rows[order], grp[order]
    vecs_all = np.ascontiguousarray(store[devs, rows])
    del store, order            # at 10^8-row rescue scale every full-
    #                             store intermediate is multi-GB
    #                             (round-2 advisor finding)

    # fixed-size batches (only the ragged tail padded) — one jit
    # compile, no second full-store copy
    CH = 8192
    keys_hi = np.empty((M,), np.uint32)
    keys_lo = np.empty((M,), np.uint32)
    for o in range(0, M, CH):
        nb = min(CH, M - o)
        chunk = vecs_all[o:o + nb]
        if nb < CH:
            chunk = np.concatenate(
                [chunk, np.zeros((CH - nb, W), np.int32)])
        h, l = fp_batch(jnp.asarray(chunk))
        keys_hi[o:o + nb] = np.asarray(h)[:nb]
        keys_lo[o:o + nb] = np.asarray(l)[:nb]

    # -- assign new owners, preserving sequence order per owner ------------
    owner_of = (keys_hi % np.uint32(ndev_dst)).astype(np.int64)
    counts = np.bincount(owner_of, minlength=ndev_dst)
    ns_new = counts.astype(np.int32)
    if (ns_new > Ncap_d).any():
        raise ValueError(
            f"caps_dst.n_states={Ncap_d} too small: a device would hold "
            f"{int(ns_new.max())} states — grow caps_dst")
    perm = np.argsort(owner_of, kind="stable")   # owner-major, seq order
    offsets = np.cumsum(counts) - counts
    local_idx = np.empty((M,), np.int64)
    local_idx[perm] = np.arange(M) - np.repeat(offsets, counts)
    new_gid = owner_of * Ncap_d + local_idx
    gid_map = np.full((nd_src * Ncap_s,), -1, np.int64)
    gid_map[devs * Ncap_s + rows] = new_gid
    ls_new = np.bincount(owner_of[grp == 0],
                         minlength=ndev_dst).astype(np.int32)
    le_new = ls_new + np.bincount(owner_of[grp == 1],
                                  minlength=ndev_dst).astype(np.int32)

    # -- rebuild the sharded leaves (vectorized scatters) ------------------
    # The src carry's big arrays must actually die before the destination
    # allocations: reshape views alone free nothing while ``src``/``arrs``
    # stay referenced, so the small surviving fields are extracted first
    # and the carry dropped wholesale (round-2 advisor finding).
    par_src = src.parent.reshape(nd_src, Ncap_s)
    lane_src = src.lane.reshape(nd_src, Ncap_s)
    con_src = src.conflag.reshape(nd_src, Ncap_s)
    parent_new = np.full((ndev_dst * Ncap_d,), -1, np.int32)
    lane_new = np.full((ndev_dst * Ncap_d,), -1, np.int32)
    con_new = np.zeros((ndev_dst * Ncap_d,), bool)
    p_old = par_src[devs, rows]
    parent_new[new_gid] = np.where(p_old >= 0, gid_map[np.maximum(p_old, 0)],
                                   -1).astype(np.int32)
    lane_new[new_gid] = lane_src[devs, rows]
    con_new[new_gid] = con_src[devs, rows]
    n_trans_tot = sum(
        acc64_int(src.n_trans.reshape(nd_src, 2)[d]) for d in range(nd_src))
    cov_tot = src.cov.reshape(nd_src, A).sum(axis=0)
    levels_src = np.asarray(src.levels).copy()
    lvl_src = np.asarray(src.lvl).copy()
    del par_src, lane_src, con_src, p_old, gid_map, src, arrs

    store_new = np.zeros((ndev_dst * Ncap_d, W), np.int32)
    store_new[new_gid] = vecs_all
    del vecs_all                 # scattered; free before the table build
    TBd = caps_dst.table // BUCKET
    tbl_hi_new = np.full((ndev_dst * TBd, BUCKET), _EMPTY, np.uint32)
    tbl_lo_new = np.full((ndev_dst * TBd, BUCKET), _EMPTY, np.uint32)
    ins = jax.jit(_dedup_insert)
    for o in range(ndev_dst):
        th = jnp.asarray(tbl_hi_new[o * TBd:(o + 1) * TBd])
        tl = jnp.asarray(tbl_lo_new[o * TBd:(o + 1) * TBd])
        sl = perm[offsets[o]:offsets[o] + counts[o]]  # new local order
        IB = 4096
        for jo in range(0, sl.size, IB):
            s2 = sl[jo:jo + IB]
            kh = np.full((IB,), 0, np.uint32)
            kl = np.full((IB,), 0, np.uint32)
            act = np.zeros((IB,), bool)
            kh[:s2.size] = keys_hi[s2]
            kl[:s2.size] = keys_lo[s2]
            act[:s2.size] = True       # fixed batch shape: one compile
            th, tl, is_new, pf = ins(th, tl, jnp.asarray(kh),
                                     jnp.asarray(kl), jnp.asarray(act))
            if bool(np.asarray(pf).any()) or \
                    not bool(np.asarray(is_new)[:s2.size].all()):
                raise RuntimeError(
                    "table rebuild failed (probe overflow or duplicate "
                    "key) — grow caps_dst.table")
        tbl_hi_new[o * TBd:(o + 1) * TBd] = np.asarray(th)
        tbl_lo_new[o * TBd:(o + 1) * TBd] = np.asarray(tl)

    n_trans_new = np.zeros((ndev_dst * 2,), np.uint32)
    n_trans_new[0] = np.uint32(n_trans_tot & 0xFFFFFFFF)
    n_trans_new[1] = np.uint32(n_trans_tot >> 32)
    cov_new = np.zeros((ndev_dst * A,), np.int32)
    cov_new[:A] = cov_tot

    # the levels array is caps.levels long — resize to caps_dst (the
    # digest is written for caps_dst, so a mismatched length would
    # silently clamp deep-level accounting)
    lvl_cur = int(lvl_src)
    if caps_dst.levels <= lvl_cur + 1:
        raise ValueError(
            f"caps_dst.levels={caps_dst.levels} too small: the run is "
            f"already at BFS level {lvl_cur}")
    levels_new = np.zeros((caps_dst.levels,), np.int32)
    n_keep = min(caps_src.levels, caps_dst.levels)
    levels_new[:n_keep] = levels_src[:n_keep]

    win = (le_new - ls_new).astype(np.int64)
    n_chunks = int(max(1, ((win + B - 1) // B).max()))
    dst = SCarry(
        store=store_new, parent=parent_new, lane=lane_new,
        conflag=con_new, tbl_hi=tbl_hi_new, tbl_lo=tbl_lo_new,
        n_states=ns_new, lvl_start=ls_new, lvl_end=le_new,
        viol_g=np.full((ndev_dst,), -1, np.int32),
        viol_i=np.zeros((ndev_dst,), np.int32),
        n_trans=n_trans_new, cov=cov_new,
        fail=np.zeros((ndev_dst,), np.int32),
        levels=levels_new, lvl=lvl_src,
        c=np.int32(0), n_chunks=np.int32(n_chunks),
        stop=np.bool_(False))
    ckpt.atomic_savez(
        dst_path,
        **{f"c{i}": np.asarray(x) for i, x in enumerate(dst)},
        config_digest=np.uint64(ckpt.config_digest(
            config, caps_dst, init_key + (ndev_dst,))))
    return {"ndev_src": nd_src, "ndev_dst": ndev_dst,
            "n_states": int(ns_new.sum()),
            "per_device": ns_new.tolist(),
            "window": win.tolist(),
            "promoted_expanded": c_cur > 0}
