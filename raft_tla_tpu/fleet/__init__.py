"""Mesh-sharded walker fleets — statistical checking at serving scale.

Promotes simulation mode (``raft_tla_tpu/simulate``) from a
single-device afterthought to a first-class sharded workload:
``FleetSimulator`` shard_maps the jitted walk segment over a 1-D device
mesh (the ``parallel/`` virtual-mesh infrastructure), with per-walker
PRNG streams folded from one root seed so a fixed (seed, walkers,
depth) reproduces the same walks bit for bit at ANY device count, and
one fused device->host fetch per segment.

``scenario`` adds the coverage/steering layer: weighted fault-action
sampling (Restart/Duplicate/Drop intensity sweeps) and the
scenario-matrix runner.
"""

from raft_tla_tpu.fleet.engine import FleetResult, FleetSimulator
from raft_tla_tpu.fleet.scenario import Scenario, fault_matrix, run_matrix

__all__ = ["FleetResult", "FleetSimulator", "Scenario", "fault_matrix",
           "run_matrix"]
