"""Scenario matrices: fault-intensity sweeps over one walker fleet.

A scenario is a named vector of action-family sampling weights.  For
Raft the interesting axis is fault intensity — how often the fleet
injects Restart / DuplicateMessage / DropMessage relative to protocol
progress — and :func:`fault_matrix` builds that sweep.  Weights are
sampling policy only: enabledness (and therefore the reachable state
space and deadlock detection) is untouched, and recorded lanes replay
exactly regardless of how they were sampled.

:func:`run_matrix` reuses ONE compiled :class:`~raft_tla_tpu.fleet.
engine.FleetSimulator` across all scenarios (weights are a traced
input, so no recompilation between cells).
"""

from __future__ import annotations

import dataclasses


# Raft's fault-action families (frontend/raft_schema re-exported via
# models/spec); plain strings so the module imports without jax.
RESTART = "Restart"
DUPLICATE = "DuplicateMessage"
DROP = "DropMessage"
FAULT_FAMILIES = (RESTART, DUPLICATE, DROP)


@dataclasses.dataclass(frozen=True)
class Scenario:
    """One cell of the matrix: a display name plus family->weight."""

    name: str
    fault_weights: dict

    def describe(self) -> str:
        if not self.fault_weights:
            return f"{self.name}: uniform"
        ws = ", ".join(f"{k}={v:g}"
                       for k, v in sorted(self.fault_weights.items()))
        return f"{self.name}: {ws}"


def fault_matrix(intensities=(0.0, 0.5, 2.0),
                 families=FAULT_FAMILIES) -> list:
    """The standard sweep: uniform baseline plus one scenario per fault
    intensity (all fault families scaled together).  ``0.0`` is the
    fault-free arm — fault lanes are never sampled (but still count as
    enabled, so no false deadlocks)."""
    out = [Scenario("uniform", {})]
    for w in intensities:
        if w == 1.0:
            continue         # identical to uniform
        out.append(Scenario(f"faults-x{w:g}", {f: float(w)
                                               for f in families}))
    return out


def run_matrix(sim, scenarios, n_behaviors: int, **run_kw) -> list:
    """Run every scenario on one fleet; returns ``[(scenario, result)]``
    in input order.  The simulator's (seed, walkers, depth) stay fixed
    across cells, so two cells differ only by sampling policy."""
    out = []
    for sc in scenarios:
        res = sim.run(n_behaviors, fault_weights=sc.fault_weights,
                      **run_kw)
        out.append((sc, res))
    return out
