"""The sharded walker-fleet engine: simulation as a mesh workload.

Random-walk checking is embarrassingly parallel — the cheapest path to
"as fast as the hardware allows" on any mesh — so the fleet engine
shard_maps the jitted walk segment over a 1-D device mesh and keeps the
host out of the loop: one fused device->host fetch of a few per-device
scalars per segment, walker/history buffers donated between dispatches.

Device-count invariance (the contract the tests pin):

- every walker owns a PRNG stream derived only from its GLOBAL id and
  the global step index — ``fold_in(fold_in(root, gid), step)`` — never
  from which device hosts it or how many devices exist;
- there is no early stop inside a segment: a violating or deadlocked
  walker freezes individually (its history stays replayable) while the
  rest of the fleet keeps walking, so every counter is a sum of
  per-walker terms, order-independent under resharding;
- the reported violation is the lexicographic minimum over
  (global step, global walker id) of all frozen walkers — computed as a
  per-device minimum plus a host-side merge, which equals the global
  minimum for any partitioning.

Hence the same (seed, walkers, depth, steps_per_dispatch) produces
bit-identical walks, counters and violation traces on 1, 2, or N
devices — the property that makes a fleet result auditable after a
mesh resize.

Steering (off by default): per-action visit counters are aggregated
across the mesh at segment boundaries, and the NEXT segment biases its
categorical lane sampling against over-visited actions with
``logits -= tau * log1p(count / mean_count)``.  Lanes are still
recorded, so exact replay is preserved; ``tau`` is a sampling policy
knob, not a spec change (enabledness is untouched).  Scenario weights
(``fault_weights``) multiply lane probabilities per action family the
same way.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from raft_tla_tpu.config import CheckConfig
from raft_tla_tpu.engine import DEADLOCK, Violation
from raft_tla_tpu.parallel.shard_engine import _AXIS, _shard_map, make_mesh
from raft_tla_tpu.simulate import resolve_sim_model

I32 = jnp.int32
F32 = jnp.float32
BIG = np.iinfo(np.int32).max


@dataclasses.dataclass
class FleetResult:
    """What a fleet run established — statistical, so the confidence
    block (states checked per invariant, coverage entropy) travels with
    the counts instead of masquerading as an exhaustive proof."""

    n_behaviors: int         # completed behaviors across the fleet
    n_states: int            # sampled transitions (states generated)
    max_depth_seen: int
    violation: Optional[Violation]
    wall_s: float
    n_devices: int
    walkers: int
    steer_tau: float
    coverage: dict           # action family -> sampled-transition count
    coverage_entropy: float  # normalized entropy of the action histogram
    device_states: list      # per-device sampled transitions (cumulative)
    walks: Optional[tuple] = None   # (hist, hlen) np arrays on request

    @property
    def states_per_sec(self) -> float:
        return self.n_states / self.wall_s if self.wall_s > 0 else float("inf")

    def confidence(self, invariants=()) -> dict:
        """The run_end ``sim`` payload (obs schema v3)."""
        return {
            "sampled_transitions": self.n_states,
            "max_depth": self.max_depth_seen,
            "walkers": self.walkers,
            "n_devices": self.n_devices,
            "coverage_entropy": round(self.coverage_entropy, 4),
            "steer_tau": self.steer_tau,
            "per_invariant": {nm: self.n_states for nm in invariants},
        }


def _coverage_entropy(counts: np.ndarray) -> float:
    """Normalized Shannon entropy of the per-action visit histogram:
    1.0 = uniform over all A lanes, 0.0 = a single lane (or no data)."""
    total = float(counts.sum())
    if total <= 0 or len(counts) < 2:
        return 0.0
    p = counts[counts > 0] / total
    return float(-(p * np.log(p)).sum() / math.log(len(counts)))


def _build_fleet_segment(config: CheckConfig, model, mesh, walkers: int,
                         depth: int, steps: int, W: int, A: int,
                         steer_tau: float):
    """One sharded dispatch: every device advances its walker shard by
    ``steps`` lockstep steps; returns updated walker shards plus small
    per-device summaries (one host fetch covers them all)."""
    bounds = config.bounds
    n_inv = len(config.invariants)
    expand = model.build_sim_expand(config)
    inv_fns = list(model.jnp_invariants(config))
    con_fn = model.jnp_constraint(bounds)
    _w, pack, unpack = model.sim_codec(bounds)
    ndev = mesh.devices.size
    B = walkers // ndev          # walkers per device
    BIGJ = jnp.int32(BIG)

    def device_seg(root_key, seg_base, cov, wvec, init_vec,
                   vecs, hist, hlen, viol_step, viol_inv, dead_step):
        # local (per-device) shapes: vecs[B, W], hist[B, depth], hlen[B].
        d = jax.lax.axis_index(_AXIS).astype(I32)
        gid = d * B + jnp.arange(B, dtype=I32)      # global walker ids
        # per-walker streams from the one root key: device-layout free
        wkeys = jax.vmap(lambda g: jax.random.fold_in(root_key, g))(gid)

        # static-per-segment sampling policy: scenario weights, then the
        # coverage-steering bias from SEGMENT-START global counts (the
        # same replicated input on every device, so fleets of any shape
        # compute the same logits).
        logw = jnp.where(wvec > 0,
                         jnp.log(jnp.maximum(wvec, 1e-30)), -jnp.inf)
        if steer_tau:            # python float; 0.0 compiles steering out
            r = cov / jnp.maximum(jnp.mean(cov), 1.0)
            logw = logw - F32(steer_tau) * jnp.log1p(r)
        init_b = jnp.broadcast_to(init_vec, (B, W))
        rows = jnp.arange(B)

        def one_step(i, carry):
            (vecs, hist, hlen, viol_step, viol_inv, dead_step,
             d_beh, d_st, maxd, cov_d, fail) = carry
            step_idx = (seg_base + i).astype(I32)
            keys = jax.vmap(
                lambda k: jax.random.fold_in(k, step_idx))(wkeys)
            structs = jax.vmap(unpack)(vecs)
            succs, valid, ovf = jax.vmap(expand)(structs)   # [B, A, ...]
            frozen = (viol_step < BIGJ) | (dead_step < BIGJ)

            logits = jnp.where(valid, logw[None, :], -jnp.inf)
            # weights are sampling policy, not spec: when every weighted
            # lane is disabled but some lane is valid, fall back to
            # uniform-over-valid instead of declaring a false deadlock.
            any_w = jnp.any(jnp.isfinite(logits), axis=-1)
            logits = jnp.where(any_w[:, None], logits,
                               jnp.where(valid, 0.0, -jnp.inf))
            lane = jax.vmap(jax.random.categorical)(keys, logits) \
                .astype(I32)
            enabled = jnp.any(valid, axis=-1)
            lane = jnp.where(enabled, lane, 0)
            live = enabled & ~frozen
            pick_s = jax.tree.map(lambda x: x[rows, lane], succs)
            pick = jax.vmap(pack)(pick_s)
            con_ok = jax.vmap(con_fn)(pick_s)
            # overflow on a taken lane is a soundness bug — loud abort
            fail = fail | jnp.any(live & ovf[rows, lane])
            if inv_fns:
                inv_ok = jnp.stack([jax.vmap(f)(pick_s) for f in inv_fns],
                                   axis=-1)                 # [B, nI]
            else:
                inv_ok = jnp.ones((B, 0), bool)

            # stuck: no enabled action at all on a live walker
            stuck = ~enabled & ~frozen
            if config.check_deadlock:
                new_dead = stuck & (dead_step == BIGJ)
                dead_step = jnp.where(new_dead, step_idx, dead_step)
            # invariant violation: the walker freezes individually (no
            # fleet-wide early stop — statistics stay device-invariant)
            bad = live & jnp.any(~inv_ok, axis=-1)
            new_viol = bad & (viol_step == BIGJ)
            viol_step = jnp.where(new_viol, step_idx, viol_step)
            first_inv = (jnp.argmax(~inv_ok, axis=-1).astype(I32)
                         if n_inv else jnp.zeros((B,), I32))
            viol_inv = jnp.where(new_viol, first_inv, viol_inv)

            hist = jnp.where(
                live[:, None]
                & (jnp.arange(depth)[None, :] == hlen[:, None]),
                lane[:, None], hist)
            hlen2 = jnp.where(live, hlen + 1, hlen)
            maxd = jnp.maximum(maxd, jnp.max(hlen2))
            d_st = d_st + jnp.sum(live.astype(I32))
            cov_d = cov_d.at[lane].add(live.astype(I32))

            # behavior end: depth bound, constraint-violating successor,
            # or (without check_deadlock) a stuck walker; frozen walkers
            # keep their state and history for replay.
            frozen2 = (viol_step < BIGJ) | (dead_step < BIGJ)
            done = ~frozen2 & ((live & (~con_ok | (hlen2 >= depth)))
                               | stuck)
            d_beh = d_beh + jnp.sum(done.astype(I32))
            vecs2 = jnp.where(
                frozen2[:, None], vecs,
                jnp.where(done[:, None], init_b,
                          jnp.where(live[:, None], pick, vecs)))
            hlen3 = jnp.where(frozen2, hlen2, jnp.where(done, 0, hlen2))
            return (vecs2, hist, hlen3, viol_step, viol_inv, dead_step,
                    d_beh, d_st, maxd, cov_d, fail)

        carry = (vecs, hist, hlen, viol_step, viol_inv, dead_step,
                 jnp.int32(0), jnp.int32(0), jnp.int32(0),
                 jnp.zeros((A,), I32), jnp.bool_(False))
        (vecs, hist, hlen, viol_step, viol_inv, dead_step,
         d_beh, d_st, maxd, cov_d, fail) = jax.lax.fori_loop(
            0, steps, one_step, carry)

        # per-device violation winner: min (step, gid) — merged with the
        # other devices' minima on the host into the global minimum
        vmin = jnp.min(viol_step)
        vgid = jnp.min(jnp.where(viol_step == vmin, gid, BIGJ))
        vidx = jnp.argmin(jnp.where(viol_step == vmin, gid, BIGJ))
        vinv = viol_inv[vidx]
        dmin = jnp.min(dead_step)
        dgid = jnp.min(jnp.where(dead_step == dmin, gid, BIGJ))

        one = lambda x: jnp.reshape(x, (1,))        # noqa: E731
        return (vecs, hist, hlen, viol_step, viol_inv, dead_step,
                one(d_beh), one(d_st), one(maxd),
                jnp.reshape(cov_d, (1, A)), one(fail),
                one(vmin), one(vgid), one(vinv), one(dmin), one(dgid))

    shard = P(_AXIS)
    shard2 = P(_AXIS, None)
    repl = P()
    seg = _shard_map(
        device_seg, mesh=mesh,
        in_specs=(repl, repl, repl, repl, repl,
                  shard2, shard2, shard, shard, shard, shard),
        out_specs=(shard2, shard2, shard, shard, shard, shard,
                   shard, shard, shard, shard2, shard,
                   shard, shard, shard, shard, shard))
    # Donate the walker shards (args 5-10): off-CPU each dispatch then
    # reuses the buffers in place.  (CPU has no donation; gate it off
    # there to keep virtual-mesh runs warning-free.)
    donate = () if jax.default_backend() == "cpu" else tuple(range(5, 11))
    return jax.jit(seg, donate_argnums=donate)


class FleetSimulator:
    """Sharded batched random-behavior generator over a device mesh.

    ``walkers`` is the GLOBAL fleet size and must divide evenly over the
    mesh; results are a pure function of (seed, walkers, depth,
    steps_per_dispatch) — never of the mesh shape.  ``steer_tau`` > 0
    turns on coverage steering; ``fault_weights`` maps action-family
    names to sampling weights (missing families weigh 1.0).
    """

    def __init__(self, config: CheckConfig, mesh=None, walkers: int = 1024,
                 depth: int = 100, steps_per_dispatch: int = 64,
                 seed: int = 0, steer_tau: float = 0.0,
                 fault_weights: dict | None = None):
        if config.symmetry:
            raise ValueError("simulation mode ignores SYMMETRY; run without")
        self.config = config
        self.bounds = config.bounds
        self.model = resolve_sim_model(config)
        self.mesh = mesh if mesh is not None else make_mesh(None)
        if tuple(self.mesh.axis_names) != (_AXIS,):
            raise ValueError(
                f"fleet needs a 1-D ({_AXIS!r},) mesh "
                f"(got axes {self.mesh.axis_names}); slice meshes carry "
                "no benefit for independent walkers")
        self.n_devices = self.mesh.devices.size
        if walkers % self.n_devices:
            raise ValueError(
                f"walkers ({walkers}) must divide evenly over the mesh "
                f"({self.n_devices} devices); try "
                f"{walkers - walkers % self.n_devices} or "
                f"{walkers + self.n_devices - walkers % self.n_devices}")
        self.width, _pack, _unpack = self.model.sim_codec(self.bounds)
        self.table = self.model.action_table(self.bounds)
        self.A = len(self.table)
        self.walkers = walkers
        self.depth = depth
        self.steps = steps_per_dispatch
        self.seed = seed
        self.steer_tau = float(steer_tau)
        self.fault_weights = dict(fault_weights or {})
        self._weight_vec(None)       # validate constructor weights loudly
        self._segment = _build_fleet_segment(
            config, self.model, self.mesh, walkers, depth, self.steps,
            self.width, self.A, self.steer_tau)

    def _weight_vec(self, fault_weights: dict | None) -> np.ndarray:
        """Family-weight dict -> per-lane f32 vector, validated loudly."""
        fw = self.fault_weights if fault_weights is None else fault_weights
        fams = {a.family for a in self.table}
        unknown = sorted(set(fw) - fams)
        if unknown:
            raise ValueError(
                f"unknown action families {unknown} for spec "
                f"{self.config.spec!r} (known: {', '.join(sorted(fams))})")
        bad = sorted(f for f, w in fw.items() if w < 0)
        if bad:
            raise ValueError(f"negative fault weights for {bad}")
        return np.asarray([fw.get(a.family, 1.0) for a in self.table],
                          dtype=np.float32)

    def run(self, n_behaviors: int, init_override=None,
            max_wall_s: float | None = None, on_progress=None,
            events: str | None = None, fault_weights: dict | None = None,
            snapshot_walks: bool = False) -> FleetResult:
        t0 = time.monotonic()
        from raft_tla_tpu.obs import RunTelemetry
        tel = RunTelemetry("fleet", config=self.config,
                           on_progress=on_progress, events=events,
                           n_devices=self.n_devices, t0=t0)
        bounds = self.bounds
        init_py = init_override if init_override is not None \
            else self.model.init_py(bounds)
        init_vec = self.model.to_vec(init_py, bounds)
        tel.run_start()
        for nm in self.config.invariants:
            if not self.model.py_invariant(nm)(init_py, bounds):
                res = self._result(
                    0, 1, 0, Violation(nm, init_py, [(None, init_py)]),
                    t0, np.zeros(self.A, np.int64),
                    [0] * self.n_devices)
                self._end(tel, res, complete=True)
                return res

        wvec = jnp.asarray(self._weight_vec(fault_weights))
        root = jax.random.PRNGKey(self.seed)
        iv = jnp.asarray(init_vec, I32)
        vecs = jnp.broadcast_to(iv, (self.walkers, self.width))
        hist = jnp.zeros((self.walkers, self.depth), I32)
        hlen = jnp.zeros((self.walkers,), I32)
        viol_step = jnp.full((self.walkers,), BIG, I32)
        viol_inv = jnp.zeros((self.walkers,), I32)
        dead_step = jnp.full((self.walkers,), BIG, I32)
        cov_total = np.zeros(self.A, np.int64)
        dev_states = [0] * self.n_devices
        nb = nst = mx = 0
        base = 0
        complete = True
        while True:
            seg_t0 = time.monotonic()
            (vecs, hist, hlen, viol_step, viol_inv, dead_step,
             d_beh, d_st, maxd, cov_d, fail,
             vmin, vgid, vinv, dmin, dgid) = self._segment(
                root, jnp.int32(base), jnp.asarray(cov_total, F32),
                wvec, iv, vecs, hist, hlen, viol_step, viol_inv,
                dead_step)
            # ONE device->host fetch per segment: every per-device
            # summary lands in a single blocking transfer.
            (d_beh, d_st, maxd, cov_d, fail,
             vmin, vgid, vinv, dmin, dgid) = jax.device_get(
                (d_beh, d_st, maxd, cov_d, fail,
                 vmin, vgid, vinv, dmin, dgid))
            base += self.steps
            seg_wall = max(time.monotonic() - seg_t0, 1e-9)
            nb += int(d_beh.sum())
            nst += int(d_st.sum())
            mx = max(mx, int(maxd.max()))
            cov_total += cov_d.sum(axis=0).astype(np.int64)
            dev_states = [a + int(b) for a, b in zip(dev_states, d_st)]
            if fail.any():
                tel.stop_requested("tensor-encoding overflow",
                                   source="fleet")
                tel.close()
                raise RuntimeError(
                    "fleet simulation aborted: a sampled transition "
                    "overflowed the tensor encoding — bounds reasoning "
                    "violated (config.py capacity scheme)")
            if tel.active:
                tel.segment(nst, mx, nst,
                            device_rates=[round(float(s) / seg_wall, 1)
                                          for s in d_st])
            if int(vmin.min()) < BIG or int(dmin.min()) < BIG:
                viol = int(vmin.min()) < BIG
                steps_arr = vmin if viol else dmin
                gids_arr = vgid if viol else dgid
                smin = int(steps_arr.min())
                # global lexicographic-min (step, gid) winner
                cand = [(int(gids_arr[i]), i)
                        for i in range(self.n_devices)
                        if int(steps_arr[i]) == smin]
                w, dev = min(cand)
                name = (self.config.invariants[int(vinv[dev])]
                        if viol else DEADLOCK)
                trace = self._replay(init_py, np.asarray(hist[w]),
                                     int(hlen[w]))
                res = self._result(
                    nb, nst, mx,
                    Violation(name, trace[-1][1], trace),
                    t0, cov_total, dev_states)
                if snapshot_walks:
                    res.walks = (np.asarray(hist), np.asarray(hlen))
                self._end(tel, res, complete=True)
                return res
            if nb >= n_behaviors:
                break
            if max_wall_s is not None and \
                    time.monotonic() - t0 > max_wall_s:
                complete = False     # wall-bounded partial run
                break
        res = self._result(nb, nst, mx, None, t0, cov_total, dev_states)
        if snapshot_walks:
            res.walks = (np.asarray(hist), np.asarray(hlen))
        self._end(tel, res, complete=complete)
        return res

    def _result(self, nb, nst, mx, violation, t0, cov_total,
                dev_states) -> FleetResult:
        by_family: dict = {}
        for inst, cnt in zip(self.table, cov_total):
            by_family[inst.family] = by_family.get(inst.family, 0) \
                + int(cnt)
        return FleetResult(
            n_behaviors=nb, n_states=nst, max_depth_seen=mx,
            violation=violation, wall_s=time.monotonic() - t0,
            n_devices=self.n_devices, walkers=self.walkers,
            steer_tau=self.steer_tau, coverage=by_family,
            coverage_entropy=_coverage_entropy(np.asarray(cov_total)),
            device_states=list(dev_states))

    def _end(self, tel, res: FleetResult, complete: bool) -> None:
        tel.run_end_sim(
            n_states=res.n_states, n_behaviors=res.n_behaviors,
            max_depth=res.max_depth_seen, wall_s=res.wall_s,
            complete=complete, violation=res.violation,
            sim=res.confidence(self.config.invariants))
        tel.close()

    def _replay(self, init_py, lanes: np.ndarray, hlen: int) -> list:
        """Rebuild the winning walk exactly through the model's host
        interpreter (same contract as the solo simulator)."""
        chain = [(None, init_py)]
        cur = init_py
        for k in range(hlen):
            a = self.table[int(lanes[k])]
            nxt = self.model.host_apply(cur, a, self.bounds)
            assert nxt is not None, \
                "recorded lane must be enabled on replay"
            chain.append((a.label(), nxt))
            cur = nxt
        return chain
