"""raft_tla_tpu — a TPU-native exhaustive model checker for the Raft TLA+ spec.

Re-architects TLC's explicit-state BFS of ``Spec == Init /\\ [][Next]_vars``
(reference ``raft.tla:469``) as massively data-parallel tensor computation:

- the spec's ``Next`` relation (``raft.tla:454-465``) compiles to a batched,
  jittable successor function over a fixed-width int32 tensor state encoding
  (``ops/state.py``);
- the BFS frontier is vmapped across HBM (``engine.py``);
- 64-bit state fingerprints deduplicate through a two-lane multilinear hash
  (``ops/fingerprint.py``; Pallas kernel in ``ops/pallas_fp.py``);
- the frontier shards over a ``jax.sharding.Mesh`` with ``all_to_all``
  fingerprint routing and ``psum`` termination detection (``parallel/``);
- the checker is driven through the stock ``raft.cfg``
  SPECIFICATION/INVARIANT/CONSTANTS interface (``utils/cfgparse.py``) so stock
  TLC remains the CPU reference oracle (``models/tla_export.py`` emits the
  patched module TLC needs).

The semantic ground truth is the reference spec at ``/root/reference/raft.tla``
(Ongaro's dissertation spec); every kernel cites the lines it implements.
"""

from raft_tla_tpu.config import Bounds, CheckConfig

__version__ = "0.1.0"

__all__ = ["Bounds", "CheckConfig", "__version__"]
