"""``python -m raft_tla_tpu.lint`` — the speclint static analyzer.

Thin alias for :mod:`raft_tla_tpu.analysis.__main__` (the documented
short spelling; also the ``raft-tla-lint`` console script).  See that
module for the pass descriptions and exit-code policy.
"""

from __future__ import annotations

import sys

from raft_tla_tpu.analysis.__main__ import build_argparser, main, run_lint

__all__ = ["build_argparser", "main", "run_lint", "entry"]


def entry() -> None:
    """Console-script entry point (pyproject: raft-tla-lint)."""
    sys.exit(main())


if __name__ == "__main__":
    sys.exit(main())
