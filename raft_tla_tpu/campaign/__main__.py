"""``python -m raft_tla_tpu.campaign`` == ``raft-tla-campaign``."""

from raft_tla_tpu.campaign.cli import entry

if __name__ == "__main__":
    entry()
