"""``raft-tla-campaign`` — the unattended-campaign front.

One command supervises a whole check campaign: admission, child spawns,
health monitoring, lossless preemption, checkpoint verification,
quarantine, mesh resharding, and bounded resume — everything
:class:`~raft_tla_tpu.campaign.supervisor.Supervisor` does, with the
policy knobs as flags.  SIGUSR1 to the supervisor is an external
preemption notice (a scheduler's eviction warning): the child is
stopped losslessly and the campaign resumes on the next allocation.

Exit codes mirror ``raft-tla-check``: 0 verdict-ok, 11 deadlock,
12 violation, 13 liveness, 1 rejected / gave up / error.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys

from raft_tla_tpu.campaign.supervisor import (CampaignPolicy,
                                              CampaignSpec, Supervisor)

_OPTION_FLAGS = ("max_term", "max_log", "max_msgs", "max_dup",
                 "max_elections")


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="raft-tla-campaign",
        description="Preemption-tolerant campaign supervisor: run one "
                    "exhaustive check across any number of child "
                    "process lifetimes, resharding between mesh sizes "
                    "as the allocation changes.")
    p.add_argument("cfg", help="TLC .cfg model config")
    p.add_argument("--spec", default="full",
                   help="compiled spec variant (default: full)")
    p.add_argument("--workdir", required=True, metavar="DIR",
                   help="campaign state directory: checkpoint family, "
                        "run.events, supervisor.events, generations, "
                        "quarantine")
    p.add_argument("--window", type=int, default=1 << 20, metavar="W",
                   help="global frontier window rows — the campaign "
                        "invariant every mesh divides (default 2^20)")
    p.add_argument("--chunk", type=int, default=1024)
    p.add_argument("--levels", type=int, default=256)
    p.add_argument("--cap", type=int, default=1 << 20,
                   help="expected distinct-state total (table sizing)")
    for name in _OPTION_FLAGS:
        p.add_argument("--" + name.replace("_", "-"), type=int,
                       default=None, help=argparse.SUPPRESS)
    p.add_argument("--faithful", action="store_true",
                   help="faithful (full-history) fingerprinting")
    p.add_argument("--symmetry", action="store_true")
    p.add_argument("--deadlock", action="store_true")
    p.add_argument("--mesh-plan", default=None, metavar="N,M,...",
                   help="mesh size per resume attempt, last entry "
                        "repeats (default: probe jax.devices() at "
                        "every spawn)")
    p.add_argument("--checkpoint-every", type=float, default=120.0,
                   metavar="S", help="child snapshot period; 0 = every "
                                     "window boundary (default 120)")
    p.add_argument("--session-wall", type=float, default=None,
                   metavar="S", help="preempt the child losslessly "
                                     "after S seconds of wall clock")
    p.add_argument("--stale-after", type=float, default=None,
                   metavar="S", help="declare the child unhealthy when "
                                     "its event log goes quiet for S "
                                     "seconds (default: 10x segment "
                                     "cadence, clamped to [30s, 1h])")
    p.add_argument("--drift-max", type=float, default=None, metavar="R",
                   help="preempt when a run_start fiducial exceeds R x "
                        "the campaign's first-run baseline")
    p.add_argument("--max-resumes", type=int, default=8,
                   help="bounded unattended retries (default 8)")
    p.add_argument("--grace", type=float, default=20.0, metavar="S",
                   help="SIGINT -> SIGKILL grace window (default 20)")
    p.add_argument("--cpu", action="store_true",
                   help="children run on the CPU backend")
    p.add_argument("--metrics-port", type=int, default=None, metavar="P",
                   help="expose a live OpenMetrics endpoint on "
                        "127.0.0.1:P (0 = ephemeral port; also via "
                        "RAFT_TLA_METRICS) over the workdir's event "
                        "logs, snapshotted into WORKDIR/metrics.events")
    p.add_argument("--json", action="store_true",
                   help="print the final CampaignResult as JSON")
    p.add_argument("--quiet", action="store_true")
    return p


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    options = {}
    for name in _OPTION_FLAGS:
        v = getattr(args, name)
        if v is not None:
            options[name] = v
    for name in ("faithful", "symmetry", "deadlock"):
        if getattr(args, name):
            options[name] = True
    spec = CampaignSpec(cfg_path=args.cfg, spec=args.spec,
                        window=args.window, chunk=args.chunk,
                        levels=args.levels, cap=args.cap,
                        options=options, cpu=args.cpu)
    policy = CampaignPolicy(checkpoint_every_s=args.checkpoint_every,
                            stale_after_s=args.stale_after,
                            session_wall_s=args.session_wall,
                            drift_max=args.drift_max,
                            max_resumes=args.max_resumes,
                            grace_s=args.grace)
    plan = None
    if args.mesh_plan:
        plan = [int(x) for x in args.mesh_plan.split(",")]
    sup = Supervisor(spec, args.workdir, policy=policy, mesh_plan=plan,
                     quiet=args.quiet)
    signal.signal(signal.SIGUSR1,
                  lambda *_: sup.request_preempt("preempt-signal",
                                                 "SIGUSR1"))
    from raft_tla_tpu.obs.metrics import metrics_port
    mport = metrics_port(args.metrics_port)
    mserver = None
    if mport is not None:
        # Reads the campaign's own event logs (run.events /
        # supervisor.events) from the supervising process — the child
        # engines never see the endpoint.
        import os
        from raft_tla_tpu.obs.openmetrics import MetricsServer
        os.makedirs(args.workdir, exist_ok=True)
        mserver = MetricsServer(
            args.workdir, port=mport,
            snapshot_path=os.path.join(args.workdir, "metrics.events"))
        print(f"metrics endpoint: {mserver.url}", flush=True)
    try:
        res = sup.run()
    finally:
        if mserver is not None:
            mserver.close()
    if args.json:
        print(json.dumps(res.__dict__, sort_keys=True))
    elif not args.quiet:
        print(f"campaign {res.outcome}: "
              f"{res.n_states if res.n_states is not None else '?'} "
              f"states across {res.attempts} attempt(s), "
              f"{res.preempts} preempt(s), {res.reshards} reshard(s), "
              f"{len(res.quarantined)} quarantined snapshot(s)")
    return res.exit_code


def entry() -> None:
    """Console-script entry point (pyproject ``raft-tla-campaign``)."""
    sys.exit(main())


if __name__ == "__main__":
    entry()
