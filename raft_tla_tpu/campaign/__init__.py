"""campaign/ — preemption-tolerant campaign supervision.

A campaign outlives any single process: the :class:`Supervisor` spawns
``raft_tla_tpu.check`` children it is allowed to lose, watches their
event logs for unhealth, drives the lossless-stop contract, verifies
every snapshot before resuming it (quarantining corrupt families —
never the same poison twice), reshards between mesh sizes as the
allocation changes, and retries with bounded backoff until the check
reaches a verdict.  :mod:`~raft_tla_tpu.campaign.chaos` is the fault
harness that proves all of it: kill, truncate, shrink, grow — finals
identical to an uninterrupted run.
"""

from raft_tla_tpu.campaign.integrity import (  # noqa: F401
    CheckpointCorrupt,
    snapshot_family,
    verify_snapshot,
)
from raft_tla_tpu.campaign.supervisor import (  # noqa: F401
    CampaignPolicy,
    CampaignResult,
    CampaignSpec,
    HealthMonitor,
    Supervisor,
    fit_mesh,
)
