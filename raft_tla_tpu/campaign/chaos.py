"""Fault injection for the campaign supervisor — the chaos harness.

:class:`ChaosMonkey` plugs into the two seams :class:`Supervisor`
exposes and injects the faults a real campaign meets:

- **kills** — a watcher thread tails the tenant event log and, on the
  k-th ``checkpoint`` event of a chosen attempt, SIGKILLs the child
  (``"kill"``) or races a SIGINT with an almost-immediate SIGKILL
  (``"int-race"``: the graceful path starts but never finishes).
  Keying on checkpoint events makes the kill point deterministic in
  *state space position* — with ``checkpoint_every_s=0`` the engines
  snapshot at every window boundary, so "die after the k-th snapshot"
  is reproducible regardless of wall-clock jitter.
- **truncations** — before the supervisor's pre-resume verify of a
  chosen attempt, truncate one family member (the metadata npz or any
  stream) to a fraction of its size: the torn-snapshot shape a dying
  filesystem leaves behind.  The supervisor must detect it
  (:class:`CheckpointCorrupt`), quarantine it, and restore an earlier
  generation — without operator input.

Every kill point is also *classified* from the surviving snapshot
(``boundary``: the snapshot landed exactly on a completed level end;
``mid-level``: a partial next level is on disk), so a chaos test can
assert it exercised both resume shapes rather than hoping.

``python -m raft_tla_tpu.campaign.chaos`` is the self-contained smoke:
run a toy campaign twice — uninterrupted, then with a SIGKILL mid-run —
and fail unless final ``n_states`` / ``n_transitions`` / verdict are
identical.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
import time

import numpy as np

from raft_tla_tpu.campaign.supervisor import (CampaignPolicy,
                                              CampaignSpec, Supervisor,
                                              _LogTail)


class ChaosMonkey:
    """Deterministic fault schedule for one campaign.

    ``kills``: ``{attempt: (kind, when)}`` — on attempt *a*, fire at a
    ``checkpoint`` event; ``kind`` is ``"kill"`` (SIGKILL) or
    ``"int-race"`` (SIGINT then SIGKILL 50 ms later); ``when`` is an
    int (the n-th checkpoint) or ``"boundary"`` / ``"mid-level"`` (the
    first checkpoint whose state count does / does not sit exactly on
    the last completed level end — the two resume shapes).
    ``truncations``: ``{attempt: suffix}`` — before attempt *a*'s
    verify, truncate family member ``ckpt + suffix`` (``""`` = the
    metadata npz itself).
    """

    def __init__(self, kills: dict | None = None,
                 truncations: dict | None = None):
        self.kills = dict(kills or {})
        self.truncations = dict(truncations or {})
        self._lock = threading.Lock()    # guards `fired` (stalker threads)
        self.fired: list = []            # (attempt, kind, nth)
        self.observed: list = []         # (attempt, n_states, kind)
        self.truncated: list = []        # (attempt, path, new_size)

    # -- Supervisor seams ---------------------------------------------------

    def spawn_hook(self, sup: Supervisor, proc, attempt: int) -> None:
        plan = self.kills.pop(attempt, None)
        if plan is None:
            return
        kind, nth = plan
        t = threading.Thread(target=self._stalk, daemon=True,
                             args=(sup.events_path, proc, attempt,
                                   kind, nth))
        t.start()

    def pre_verify_hook(self, sup: Supervisor, attempt: int) -> None:
        self._observe(sup, attempt)
        suffix = self.truncations.pop(attempt, None)
        if suffix is None:
            return
        path = sup.ckpt + suffix
        size = os.path.getsize(path)
        new = max(1, size // 3)
        with open(path, "r+b") as f:
            f.truncate(new)
        self.truncated.append((attempt, path, new))

    # -- internals ----------------------------------------------------------

    def _stalk(self, events_path: str, proc, attempt: int, kind: str,
               when) -> None:
        tail = _LogTail(events_path)
        tail.seek_end()
        seen = 0
        level_end_n = None
        while proc.poll() is None:
            for e in tail.poll():
                ev = e.get("event")
                if ev == "level_end":
                    level_end_n = e.get("n_states")
                if ev != "checkpoint":
                    continue
                seen += 1
                if isinstance(when, int):
                    hit = seen >= when
                else:
                    at_boundary = (e.get("n_states") is not None
                                   and e.get("n_states") == level_end_n)
                    hit = at_boundary if when == "boundary" \
                        else not at_boundary
                if not hit:
                    continue
                try:
                    if kind == "int-race":
                        proc.send_signal(signal.SIGINT)
                        time.sleep(0.05)
                    proc.kill()
                except ProcessLookupError:
                    pass
                with self._lock:
                    self.fired.append((attempt, kind, seen))
                return
            time.sleep(0.02)

    def _observe(self, sup: Supervisor, attempt: int) -> None:
        """Classify the surviving snapshot's resume shape."""
        try:
            with np.load(sup.ckpt) as z:
                n_states = int(z["n_states"])
                ends = [int(x) for x in np.atleast_1d(z["level_ends"])]
        except Exception:
            return                       # torn npz: the verify will say so
        kind = "boundary" if ends and n_states == ends[-1] else "mid-level"
        self.observed.append((attempt, n_states, kind))

    def kill_kinds(self) -> set:
        """Resume shapes actually exercised (``boundary``/``mid-level``)."""
        return {kind for _, _, kind in self.observed}


def final_record(events_path: str) -> dict | None:
    """The last ``run_end`` of a tenant log — the comparable final."""
    tail = _LogTail(events_path)
    ends = [e for e in tail.poll() if e.get("event") == "run_end"]
    return ends[-1] if ends else None


def run_reference(spec: CampaignSpec, workdir: str,
                  quiet: bool = True) -> dict:
    """One uninterrupted campaign (no chaos, single mesh) — the ground
    truth the chaos run must match byte-for-byte on finals."""
    sup = Supervisor(spec, workdir,
                     policy=CampaignPolicy(checkpoint_every_s=0.0,
                                           max_resumes=0),
                     mesh_plan=[1], quiet=quiet)
    res = sup.run()
    if res.outcome not in ("ok", "deadlock", "violation", "liveness"):
        raise RuntimeError(
            f"reference campaign did not finish: {res.outcome} "
            f"({res.detail})")
    end = final_record(sup.events_path)
    return {"outcome": res.outcome, "n_states": end["n_states"],
            "n_transitions": end["n_transitions"]}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m raft_tla_tpu.campaign.chaos",
        description="Chaos smoke: a toy campaign survives a SIGKILL "
                    "mid-run and lands on finals identical to an "
                    "uninterrupted run.")
    p.add_argument("cfg", help="TLC .cfg of a small model")
    p.add_argument("--workdir", required=True)
    p.add_argument("--spec", default="full")
    p.add_argument("--window", type=int, default=128)
    p.add_argument("--chunk", type=int, default=32)
    p.add_argument("--cap", type=int, default=1 << 14)
    p.add_argument("--max-term", type=int, default=None)
    p.add_argument("--max-log", type=int, default=None)
    p.add_argument("--max-msgs", type=int, default=None)
    p.add_argument("--kill-after", type=int, default=2, metavar="K",
                   help="SIGKILL after the K-th checkpoint event "
                        "(default 2)")
    p.add_argument("--mesh-plan", default="1",
                   help="comma-separated ndev per attempt (default 1)")
    p.add_argument("--cpu", action="store_true",
                   help="children run on the CPU backend")
    args = p.parse_args(argv)

    options = {k: getattr(args, k)
               for k in ("max_term", "max_log", "max_msgs")
               if getattr(args, k) is not None}
    spec = CampaignSpec(cfg_path=args.cfg, spec=args.spec,
                        window=args.window, chunk=args.chunk,
                        cap=args.cap, options=options, cpu=args.cpu)
    ref = run_reference(spec, os.path.join(args.workdir, "ref"))
    print(f"reference: {ref['outcome']}, {ref['n_states']:,} states, "
          f"{ref['n_transitions']:,} transitions")

    monkey = ChaosMonkey(kills={0: ("kill", args.kill_after)})
    plan = [int(x) for x in args.mesh_plan.split(",")]
    sup = Supervisor(spec, os.path.join(args.workdir, "chaos"),
                     policy=CampaignPolicy(checkpoint_every_s=0.0,
                                           backoff_base_s=0.0,
                                           grace_s=5.0, poll_s=0.05),
                     mesh_plan=plan, spawn_hook=monkey.spawn_hook,
                     pre_verify_hook=monkey.pre_verify_hook, quiet=False)
    res = sup.run()
    end = final_record(sup.events_path)
    got = {"outcome": res.outcome,
           "n_states": end["n_states"] if end else None,
           "n_transitions": end["n_transitions"] if end else None}
    print(f"chaos: {got['outcome']} after {res.attempts} attempt(s), "
          f"kills fired {monkey.fired}, kill points {monkey.observed}")
    if not monkey.fired:
        print("FAIL: the kill never fired (run too short for "
              f"--kill-after {args.kill_after}?)", file=sys.stderr)
        return 1
    if got != ref:
        print(f"FAIL: finals diverge: chaos {got} != reference {ref}",
              file=sys.stderr)
        return 1
    print("chaos smoke OK: finals identical to the uninterrupted run")
    return 0


if __name__ == "__main__":
    sys.exit(main())
