"""Structural snapshot verification — the supervisor's gate before any
resume.

:func:`verify_snapshot` answers one question without loading a single
state row into memory: *is this DDD snapshot family internally
consistent enough that a resume could be lossless?*  It checks the
metadata npz (content digest via :func:`ckpt.load_npz_verified`), then
every stream file's header against its on-disk size and against the
metadata's ``n_states`` — the exact torn-snapshot shapes a SIGKILL
mid-``atomic_savez`` or a truncated copy leaves behind.  Everything is
host-side file inspection (headers are 16 bytes); a multi-GB campaign
checkpoint verifies in milliseconds.

It deliberately does NOT check the config digest: that is the *caller's*
identity claim, and the engines re-check it on resume anyway
(``ckpt.load_npz_checked``).  Integrity and identity are different
failures — a corrupt snapshot gets quarantined, a digest mismatch means
the operator pointed the campaign at the wrong model.

No jax import anywhere in this module: the supervisor process must stay
a pure host-side process so it never competes with its child for the
accelerator.
"""

from __future__ import annotations

import os

import numpy as np

from raft_tla_tpu.utils import ckpt
from raft_tla_tpu.utils.ckpt import CheckpointCorrupt

# full-retention stream suffixes with their fixed widths (None = model
# dependent: .rows is the packed row width P, .links is 3, or 2 in
# pre-round-4 snapshots)
_FULL_STREAMS = ((".rows", None), (".links", (2, 3)), (".con", (1,)),
                 (".keys", (2,)))

_HDR_BYTES = 16                          # int64[2] = [n_rows, width]
_META_KEYS = ("n_states", "n_trans", "level_ends", "blocks_done",
              "config_digest")


def _check_stream(path: str, min_rows: int, widths=None) -> tuple:
    """One stream file: header readable, width sane, row count covers
    ``min_rows``, and the file is long enough to actually hold what the
    header claims.  Returns ``(n_rows, width)``."""
    if not os.path.exists(path):
        raise CheckpointCorrupt(
            f"checkpoint stream {path} is missing — incomplete snapshot "
            "family")
    size = os.path.getsize(path)
    if size < _HDR_BYTES:
        raise CheckpointCorrupt(
            f"checkpoint stream {path}: truncated header "
            f"({size} bytes) — torn snapshot")
    with open(path, "rb") as f:
        hdr = np.fromfile(f, dtype=np.int64, count=2)
    n_rows, width = int(hdr[0]), int(hdr[1])
    if width < 1 or n_rows < 0:
        raise CheckpointCorrupt(
            f"checkpoint stream {path}: nonsense header "
            f"[{n_rows}, {width}] — torn snapshot")
    if widths is not None and width not in widths:
        raise CheckpointCorrupt(
            f"checkpoint stream {path}: row width {width}, expected "
            f"{' or '.join(str(w) for w in widths)}")
    if n_rows < min_rows:
        raise CheckpointCorrupt(
            f"checkpoint stream {path} holds {n_rows} rows, metadata "
            f"expects {min_rows} — torn snapshot")
    if size < _HDR_BYTES + n_rows * width * 4:
        raise CheckpointCorrupt(
            f"checkpoint stream {path} is {size} bytes but its header "
            f"claims {n_rows} x {width} int32 rows — truncated file")
    return n_rows, width


def verify_snapshot(path: str, row_width: int | None = None) -> dict:
    """Verify one DDD snapshot family (full or frontier retention).

    Raises :class:`CheckpointCorrupt` on any structural damage, plain
    ``FileNotFoundError`` when the metadata npz itself is absent (no
    snapshot is not a *corrupt* snapshot).  Returns a summary dict
    (``n_states``, ``levels``, ``blocks_done``, ``retention``) for
    supervisor bookkeeping.

    ``row_width`` (the packed state row width P), when known, pins the
    ``.rows`` stream width; without it the width is only sanity-checked
    against the file size.
    """
    with ckpt.load_npz_verified(path) as z:
        names = set(z.files)
        missing = [k for k in _META_KEYS if k not in names]
        if missing:
            raise CheckpointCorrupt(
                f"checkpoint {path} is missing metadata field(s) "
                f"{missing} — torn snapshot")
        n_states = int(z["n_states"])
        level_ends = [int(x) for x in np.atleast_1d(z["level_ends"])]
        blocks_done = int(z["blocks_done"])
        frontier = "retention" in names
    if n_states < 0 or blocks_done < 0:
        raise CheckpointCorrupt(
            f"checkpoint {path}: negative counters (n_states={n_states}, "
            f"blocks_done={blocks_done}) — torn snapshot")
    if any(b > a for a, b in zip(level_ends, [0] + level_ends[:-1])):
        raise CheckpointCorrupt(
            f"checkpoint {path}: level_ends not monotone — torn snapshot")
    if level_ends and level_ends[-1] > n_states:
        raise CheckpointCorrupt(
            f"checkpoint {path}: last level end {level_ends[-1]} exceeds "
            f"n_states {n_states} — torn snapshot")

    rows_w = (row_width,) if row_width is not None else None
    if frontier:
        if not level_ends:
            raise CheckpointCorrupt(
                f"checkpoint {path}: frontier retention with no completed "
                "levels — torn snapshot")
        L = len(level_ends)
        lvl_lo = level_ends[-2] if L > 1 else 0
        lvl_hi = level_ends[-1]
        _check_stream(path + ".keys", n_states, (2,))
        # the frontier window lives in per-level stream files; the
        # loader trims overhang, so >= is the right relation here too
        _check_stream(f"{path}.rowsL{L}", lvl_hi - lvl_lo, rows_w)
        _check_stream(f"{path}.conL{L}", lvl_hi - lvl_lo, (1,))
        if n_states > lvl_hi:
            _check_stream(f"{path}.rowsL{L + 1}", n_states - lvl_hi,
                          rows_w)
            _check_stream(f"{path}.conL{L + 1}", n_states - lvl_hi, (1,))
    else:
        for suf, widths in _FULL_STREAMS:
            if suf == ".rows" and rows_w is not None:
                widths = rows_w
            _check_stream(path + suf, n_states, widths)
    return {"path": path, "n_states": n_states,
            "levels": len(level_ends), "blocks_done": blocks_done,
            "retention": "frontier" if frontier else "full"}


def snapshot_family(path: str) -> list:
    """Every on-disk member of the snapshot family rooted at ``path``
    (the metadata npz plus its ``.rows``/``.links``/``.con``/``.keys``
    and frontier ``.rowsL<k>``/``.conL<k>`` streams).  Used whole-sale:
    quarantine moves, generation copies, and fresh-start deletion all
    operate on the family, never on individual members."""
    import glob as _glob

    out = [path] if os.path.exists(path) else []
    for p in sorted(_glob.glob(_glob.escape(path) + ".*")):
        if p.endswith(".tmp"):
            continue                     # torn atomic_savez temp — not ours
        out.append(p)
    return out
