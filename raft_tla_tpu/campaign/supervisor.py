"""Preemption-tolerant campaign supervisor.

A *campaign* is one exhaustive check too big (or too preemptible) to
finish in one process lifetime.  The :class:`Supervisor` runs it as a
child process it is allowed to lose:

- **watch** — tail the tenant's own event log (the one obs/ already
  writes) and declare the child unhealthy on heartbeat staleness,
  fiducial drift, a session wall-clock policy, or an external
  preemption notice (:meth:`Supervisor.request_preempt`, SIGUSR1 from
  the CLI);
- **stop losslessly** — append ``stop_requested`` to the tenant log
  (the same contract ``campaign_stop.sh`` documents), send SIGINT, and
  give the child a grace window to flush a boundary snapshot before
  SIGKILL;
- **verify, quarantine, reshard, resume** — structurally verify the
  snapshot family (:mod:`raft_tla_tpu.campaign.integrity`) before every
  resume; a corrupt family is moved to ``quarantine/`` (never resumed
  twice) and the newest good *generation copy* restored in its place;
  when the mesh the scheduler hands back differs from the one the
  snapshot was written for, rewrite it in place via
  :func:`~raft_tla_tpu.parallel.ddd_shard_engine.reshard_ddd_checkpoint`
  (the global window ``W`` is the campaign invariant: every mesh runs
  ``block = W // ndev``, so window boundaries are shared and any
  snapshot reshards to any planned mesh);
- **retry bounded** — exponential backoff between resume attempts,
  reset whenever an attempt makes state-count progress, hard-capped at
  ``max_resumes``.

The supervisor's own actions are an event log too
(``supervisor.events``: schema-v2 ``preempt`` / ``reshard`` /
``resume_attempt`` lines), so ``raft-tla-monitor`` renders the
campaign's control history with the same tooling as the run itself.

Admission is the serve/ gate (:func:`raft_tla_tpu.serve.jobs.admit`):
a campaign that would be rejected as a service job — width-unsafe,
vacuous, property-carrying — is rejected before the first child spawn,
for the same reasons.

The child is always a fresh ``python -m raft_tla_tpu.check`` process
(``--engine ddd`` at one device, ``--engine ddd-shard --devices N``
otherwise): re-spawning re-probes the mesh, so a grown or shrunk
allocation is discovered exactly where it matters.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import shutil
import signal
import subprocess
import sys
import time

from raft_tla_tpu.campaign.integrity import (CheckpointCorrupt,
                                             snapshot_family,
                                             verify_snapshot)
from raft_tla_tpu.obs import append_event
from raft_tla_tpu.obs.collect import LogTail as _LogTail
from raft_tla_tpu.obs.history import _DRIFT_EXEMPT, fiducial_drift

# check.py's exit contract (mirrored, not imported: the supervisor must
# not pay the check-CLI import just to read four integers)
EXIT_OK, EXIT_DEADLOCK, EXIT_VIOLATION, EXIT_LIVENESS = 0, 11, 12, 13
EXIT_STOPPED = 14
_TERMINAL = {EXIT_OK: "ok", EXIT_DEADLOCK: "deadlock",
             EXIT_VIOLATION: "violation", EXIT_LIVENESS: "liveness"}


@dataclasses.dataclass(frozen=True)
class CampaignPolicy:
    """The supervisor's health + retry policy — everything that decides
    *when* to preempt and *whether* to resume, none of it about the
    model being checked."""

    checkpoint_every_s: float = 120.0    # child's --checkpoint-every
    stale_after_s: float | None = None   # None: 10x segment cadence,
    #                                      clamped to [30s, 1h] (the
    #                                      obs/monitor auto threshold)
    session_wall_s: float | None = None  # preempt the child past this
    #                                      wall (also the child's own
    #                                      --deadline at ndev == 1)
    drift_max: float | None = None       # fiducial ratio vs. the
    #                                      campaign's first-run baseline
    max_resumes: int = 8                 # bounded unattended retries
    backoff_base_s: float = 0.5
    backoff_cap_s: float = 30.0
    backoff_jitter_seed: int | None = None
    #   decorrelated-jitter RNG seed; None derives one from the pid so
    #   co-located supervisors never retry in lockstep, an explicit int
    #   makes the whole delay sequence reproducible (tests pin it)
    grace_s: float = 20.0                # SIGINT -> SIGKILL window
    poll_s: float = 0.25                 # supervisor loop period
    retain_generations: int = 2          # known-good snapshot copies


@dataclasses.dataclass(frozen=True)
class CampaignSpec:
    """What to check and at what shape.  ``window`` is the campaign's
    global frontier window W — the one number that must survive every
    reshard (each mesh runs ``block = W // ndev``)."""

    cfg_path: str
    spec: str = "full"
    window: int = 1 << 20
    chunk: int = 1024
    levels: int = 256
    cap: int = 1 << 20
    options: dict = dataclasses.field(default_factory=dict)
    #   extra JobOptions fields (max_term, faithful, ...) — forwarded
    #   both to admission and to the child CLI
    cpu: bool = False                    # children run --cpu (tests /
    #                                      virtual-mesh campaigns)
    extra_args: tuple = ()               # raw extra child CLI args


@dataclasses.dataclass
class CampaignResult:
    outcome: str          # ok|deadlock|violation|liveness|gave-up|
    #                       rejected|error
    exit_code: int
    n_states: int | None
    n_transitions: int | None
    attempts: int         # child spawns, total
    preempts: int
    reshards: int
    quarantined: list     # (path, reason) pairs
    events_path: str = ""
    checkpoint: str = ""
    detail: str = ""


# _LogTail and _DRIFT_EXEMPT began life here; they now live in
# obs/collect.py (shared with the metrics aggregator) and
# obs/history.py (shared with raft-tla-regress) respectively, and are
# re-imported above so the serve/chaos tails and the pinned-sequence
# tests keep their import sites.


def _median(xs: list) -> float:
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


class DecorrelatedBackoff:
    """Seedable decorrelated-jitter retry delays (the AWS-architecture
    variant: ``next = min(cap, uniform(base, prev * 3))``).

    Pure exponential backoff retries co-located supervisors (and the
    serve worker pool's respawns) in lockstep — every failed host wakes
    at the same instants and thunders the shared allocation together.
    Decorrelated jitter spreads the wakeups while keeping the same mean
    growth; seeding it makes the *whole sequence* deterministic, so the
    anti-herd behavior itself is testable (and two supervisors seeded
    differently provably diverge).  ``seed=None`` derives one from the
    pid: distinct processes get distinct sequences by default.
    """

    def __init__(self, base_s: float, cap_s: float,
                 seed: int | None = None):
        self.base_s = base_s
        self.cap_s = cap_s
        if seed is None:
            seed = os.getpid()
        self._seed = seed
        self._rng = random.Random(seed)
        self._prev = base_s

    def reset(self) -> None:
        """Progress was made: the next failure backs off from base
        again (the RNG stream keeps advancing — only the window resets)."""
        self._prev = self.base_s

    def next(self) -> float:
        self._prev = min(self.cap_s,
                         self._rng.uniform(self.base_s, self._prev * 3.0))
        return self._prev


class HealthMonitor:
    """Pure health-decision logic for one child attempt.

    Feed it the attempt's parsed events (:meth:`observe`); ask
    :meth:`verdict` whether the child should be preempted and why.
    No I/O, injectable clock — unit-testable without a process tree.
    """

    def __init__(self, policy: CampaignPolicy, clock=time.time,
                 fiducial_baseline: dict | None = None):
        self.policy = policy
        self.clock = clock
        self.spawned_at: float | None = None
        self.fiducial_baseline = fiducial_baseline
        self.fiducials_seen: dict | None = None
        self._last_ts: float | None = None
        self._seg_ts: list = []

    def observe(self, events: list) -> None:
        for e in events:
            ts = e.get("ts")
            if isinstance(ts, (int, float)):
                self._last_ts = ts
                if e.get("event") == "segment":
                    self._seg_ts.append(ts)
                    del self._seg_ts[:-10]
            if e.get("event") == "run_start" and e.get("fiducials"):
                self.fiducials_seen = dict(e["fiducials"])

    def last_event_age(self, now: float) -> float | None:
        anchor = self._last_ts if self._last_ts is not None \
            else self.spawned_at
        return None if anchor is None else max(0.0, now - anchor)

    def stale_threshold(self) -> float:
        """Explicit policy wins; otherwise 10x the observed segment
        cadence clamped to [30s, 1h] (same rule as obs/monitor), else a
        flat 300s before the first cadence sample exists."""
        if self.policy.stale_after_s is not None:
            return self.policy.stale_after_s
        gaps = [b - a for a, b in zip(self._seg_ts, self._seg_ts[1:])
                if b >= a]
        if not gaps:
            return 300.0
        return min(3600.0, max(30.0, 10.0 * _median(gaps)))

    def _drift(self) -> tuple | None:
        base, cur = self.fiducial_baseline, self.fiducials_seen
        if not self.policy.drift_max or not base or not cur:
            return None
        # The one drift policy (shared with raft-tla-regress): first
        # offending key in sorted order, one-sided growth ratio,
        # _DRIFT_EXEMPT honored.
        return fiducial_drift(base, cur, self.policy.drift_max)

    def verdict(self) -> tuple | None:
        """None = healthy, else ``(reason, detail)`` with reason one of
        ``session-wall`` / ``fiducial-drift`` / ``heartbeat-stale``."""
        now = self.clock()
        wall = self.policy.session_wall_s
        if wall is not None and self.spawned_at is not None \
                and now - self.spawned_at > wall:
            return ("session-wall",
                    f"child past {wall:.0f}s session budget")
        drift = self._drift()
        if drift is not None:
            key, ratio = drift
            return ("fiducial-drift",
                    f"{key} {ratio:.2f}x vs campaign baseline "
                    f"(threshold {self.policy.drift_max:.2f}x)")
        age = self.last_event_age(now)
        if age is not None and age > self.stale_threshold():
            return ("heartbeat-stale",
                    f"last event {age:.0f}s ago "
                    f"(threshold {self.stale_threshold():.0f}s)")
        return None


def fit_mesh(ndev_avail: int, window: int, chunk: int) -> int:
    """Largest usable mesh size <= what the runtime offers: ndev must
    divide the campaign window W into chunk-aligned per-device blocks.
    Always succeeds at 1 (window is chunk-aligned by construction)."""
    for nd in range(max(1, ndev_avail), 0, -1):
        if window % nd == 0 and (window // nd) % chunk == 0:
            return nd
    return 1


class Supervisor:
    """Drive one campaign to a verdict across any number of child
    lifetimes.  See the module docstring for the loop contract.

    ``mesh_plan``: None (probe ``jax.devices()`` each spawn), a list of
    mesh sizes indexed by attempt (last entry repeats — the test
    harness's deterministic reshard schedule), or a callable
    ``attempt -> ndev``.

    ``spawn_hook(sup, proc, attempt)`` / ``pre_verify_hook(sup,
    attempt)`` are the chaos seams: fault injection attaches here, the
    production path never notices.
    """

    def __init__(self, spec: CampaignSpec, workdir: str,
                 policy: CampaignPolicy | None = None, mesh_plan=None,
                 spawn_hook=None, pre_verify_hook=None,
                 quiet: bool = False, clock=time.time, sleep=time.sleep):
        if spec.window % spec.chunk:
            raise ValueError(
                f"campaign window {spec.window} is not a multiple of "
                f"chunk {spec.chunk}")
        self.spec = spec
        self.policy = policy or CampaignPolicy()
        self.workdir = workdir
        self.mesh_plan = mesh_plan
        self.spawn_hook = spawn_hook
        self.pre_verify_hook = pre_verify_hook
        self.quiet = quiet
        self.clock = clock
        self.sleep = sleep
        os.makedirs(workdir, exist_ok=True)
        self.ckpt = os.path.join(workdir, "campaign.ckpt")
        self.events_path = os.path.join(workdir, "run.events")
        self.sup_events = os.path.join(workdir, "supervisor.events")
        self.quarantine_dir = os.path.join(workdir, "quarantine")
        self.gen_dir = os.path.join(workdir, "gen")
        self._state_path = os.path.join(workdir, "campaign.json")
        self._state = self._load_state()
        self._external: tuple | None = None
        self.config = None
        self.quarantined: list = []
        self._jitter = DecorrelatedBackoff(
            self.policy.backoff_base_s, self.policy.backoff_cap_s,
            seed=self.policy.backoff_jitter_seed)
        self._last_backoff_s = 0.0
        # v8 tracing (RAFT_TLA_TRACE, inherited by the child): child
        # attempt lifetimes and preempt->exit drains become spans in
        # supervisor.events; the anchored run_start puts the supervisor
        # on the same wall axis as the child's engine spans.  Gated so
        # untraced supervisor logs stay byte-compatible with v2 readers.
        from raft_tla_tpu.obs.trace import (NULL_TRACER,
                                            anchored_run_start,
                                            trace_enabled, tracer_for)
        self.tracer = NULL_TRACER
        if trace_enabled():
            anchored_run_start(self.sup_events, "campaign")
            self.tracer = tracer_for(self.sup_events)

    # ---------------------------------------------------------------- util

    def _say(self, msg: str) -> None:
        if not self.quiet:
            print(f"[campaign] {msg}", flush=True)

    def _load_state(self) -> dict:
        try:
            with open(self._state_path, "r", encoding="utf-8") as f:
                d = json.load(f)
            return d if isinstance(d, dict) else {}
        except (OSError, ValueError):
            return {}

    def _save_state(self, **updates) -> None:
        """Supervisor restart journal: the snapshot's mesh format lives
        here (``ndev``) — the one fact a fresh supervisor cannot re-probe
        from the family itself without trying every digest."""
        self._state.update(updates)
        tmp = self._state_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self._state, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._state_path)

    def request_preempt(self, reason: str = "external-preempt",
                        detail: str = "") -> None:
        """External preemption notice (scheduler eviction, SIGUSR1):
        the next supervisor poll drives the lossless-stop contract."""
        self._external = (reason, detail)

    # ----------------------------------------------------------- admission

    def _admit(self):
        from raft_tla_tpu.serve.jobs import CheckJob, admit
        job = CheckJob.from_dict(
            {"id": "campaign", "cfg": self.spec.cfg_path,
             "spec": self.spec.spec, "chunk": self.spec.chunk,
             **self.spec.options})
        adm = admit(job)
        if adm.admitted and adm.properties:
            return adm, ("property-unsupported: liveness needs a "
                         "dedicated exhaustive run (raft-tla-check "
                         "--property); campaigns check invariants")
        if not adm.admitted:
            return adm, "; ".join(adm.findings_text()) or adm.reason
        return adm, None

    # ---------------------------------------------------------------- mesh

    def _mesh_for(self, attempt: int) -> int:
        plan = self.mesh_plan
        if plan is None:
            import jax
            nd = fit_mesh(len(jax.devices()), self.spec.window,
                          self.spec.chunk)
        elif callable(plan):
            nd = int(plan(attempt))
        else:
            nd = int(plan[min(attempt, len(plan) - 1)])
        if nd < 1 or self.spec.window % nd \
                or (self.spec.window // nd) % self.spec.chunk:
            raise ValueError(
                f"mesh plan ndev={nd} does not divide window "
                f"{self.spec.window} into chunk-aligned "
                f"({self.spec.chunk}) blocks")
        return nd

    def _reshard(self, ndev_src: int, ndev_dst: int) -> dict:
        from raft_tla_tpu.parallel.ddd_shard_engine import (
            DDDShardCapacities, reshard_ddd_checkpoint)
        W = self.spec.window
        caps_src = DDDShardCapacities(block=W // ndev_src,
                                      levels=self.spec.levels)
        caps_dst = DDDShardCapacities(block=W // ndev_dst,
                                      levels=self.spec.levels)
        dst = os.path.join(self.workdir, "reshard_tmp")
        for p in snapshot_family(dst):
            os.remove(p)                 # a crashed earlier reshard
        info = reshard_ddd_checkpoint(self.config, caps_src, self.ckpt,
                                      dst, ndev_src, ndev_dst, caps_dst)
        # swap the rewritten family over the live one, member by member;
        # stale members with no rewritten counterpart must go too
        new_sufs = {p[len(dst):] for p in snapshot_family(dst)}
        for p in snapshot_family(self.ckpt):
            if p[len(self.ckpt):] not in new_sufs:
                os.remove(p)
        for suf in new_sufs:
            os.replace(dst + suf, self.ckpt + suf)
        # the family on disk is now ndev_dst-format; journal that before
        # anything else can crash, or the next resume reshards from the
        # wrong source shape
        self._save_state(ndev=ndev_dst)
        append_event(self.sup_events, "reshard", ndev_src=ndev_src,
                     ndev_dst=ndev_dst, n_states=int(info["n_states"]),
                     path=self.ckpt)
        self._say(f"resharded {ndev_src} -> {ndev_dst} devices at "
                  f"{info['n_states']:,} states")
        return info

    # ----------------------------------------- verify / quarantine / gens

    def _generations(self) -> list:
        try:
            names = sorted(n for n in os.listdir(self.gen_dir)
                           if n.startswith("g"))
        except OSError:
            return []
        return [os.path.join(self.gen_dir, n) for n in names]

    def _copy_family(self, dst_dir: str) -> None:
        os.makedirs(dst_dir, exist_ok=True)
        for p in snapshot_family(self.ckpt):
            shutil.copy2(p, os.path.join(dst_dir, os.path.basename(p)))

    def _maybe_save_generation(self, info: dict) -> None:
        """Keep ``retain_generations`` known-good copies of the verified
        family, deduped on state count — the fallback when a later
        snapshot turns out torn."""
        gens = self._generations()
        last_meta = {}
        if gens:
            try:
                with open(os.path.join(gens[-1], "meta.json"),
                          encoding="utf-8") as f:
                    last_meta = json.load(f)
            except (OSError, ValueError):
                pass
        if last_meta.get("n_states") == info["n_states"] \
                and last_meta.get("ndev") == self._state.get("ndev"):
            return                       # no progress since last copy
        seq = self._state.get("gen_seq", 0)
        gdir = os.path.join(self.gen_dir, f"g{seq:06d}")
        self._copy_family(gdir)
        with open(os.path.join(gdir, "meta.json"), "w",
                  encoding="utf-8") as f:
            json.dump({"n_states": info["n_states"],
                       "ndev": self._state.get("ndev")}, f)
        self._save_state(gen_seq=seq + 1)
        for old in self._generations()[:-self.policy.retain_generations]:
            shutil.rmtree(old, ignore_errors=True)

    def _quarantine(self, what: str, reason: str, members: list) -> None:
        seq = self._state.get("quarantine_seq", 0)
        qdir = os.path.join(self.quarantine_dir, f"q{seq:06d}-{what}")
        os.makedirs(qdir, exist_ok=True)
        for p in members:
            os.replace(p, os.path.join(qdir, os.path.basename(p)))
        with open(os.path.join(qdir, "reason.txt"), "w",
                  encoding="utf-8") as f:
            f.write(reason + "\n")
        self._save_state(quarantine_seq=seq + 1)
        self.quarantined.append((qdir, reason))
        self._say(f"quarantined {what} -> {qdir}: {reason}")

    def _verify_or_recover(self, attempt: int) -> bool:
        """True = the live family is verified and resumable.  A corrupt
        family is quarantined (poison guarantee: it is *moved*, so the
        same bytes are never resumed twice) and the newest good
        generation restored; with none left, fall back to fresh start.
        """
        try:
            info = verify_snapshot(self.ckpt)
        except FileNotFoundError:
            return False
        except CheckpointCorrupt as e:
            self._quarantine("live", str(e), snapshot_family(self.ckpt))
        else:
            self._maybe_save_generation(info)
            return True
        for gdir in reversed(self._generations()):
            for n in os.listdir(gdir):
                if n != "meta.json":
                    shutil.copy2(os.path.join(gdir, n),
                                 os.path.join(self.workdir, n))
            try:
                info = verify_snapshot(self.ckpt)
            except CheckpointCorrupt as e:
                members = [p for p in snapshot_family(self.ckpt)]
                self._quarantine(os.path.basename(gdir), str(e), members)
                shutil.rmtree(gdir, ignore_errors=True)
                continue
            try:
                with open(os.path.join(gdir, "meta.json"),
                          encoding="utf-8") as f:
                    self._save_state(ndev=json.load(f).get("ndev"))
            except (OSError, ValueError):
                pass
            self._say(f"restored generation {os.path.basename(gdir)} at "
                      f"{info['n_states']:,} states")
            return True
        self._say("no good generation left; campaign restarts fresh")
        return False

    # --------------------------------------------------------------- child

    def _child_argv(self, ndev: int, resume: bool) -> list:
        spec = self.spec
        argv = [sys.executable, "-m", "raft_tla_tpu.check", spec.cfg_path,
                "--spec", spec.spec, "--chunk", str(spec.chunk),
                "--levels", str(spec.levels), "--cap", str(spec.cap),
                "--block", str(spec.window // ndev),
                "--checkpoint", self.ckpt,
                "--checkpoint-every", str(self.policy.checkpoint_every_s),
                "--events", self.events_path, "--no-trace"]
        if ndev > 1:
            argv += ["--engine", "ddd-shard", "--devices", str(ndev)]
        else:
            argv += ["--engine", "ddd"]
            if self.policy.session_wall_s is not None:
                # belt to the supervisor's suspenders: the single-chip
                # engine stops itself losslessly at the deadline even if
                # the supervisor dies with it
                argv += ["--deadline", str(self.policy.session_wall_s)]
        if resume:
            argv += ["--resume", self.ckpt]
        if spec.cpu:
            argv += ["--cpu"]
        for k, v in sorted(spec.options.items()):
            flag = "--" + k.replace("_", "-")
            if isinstance(v, bool):
                if v:
                    argv.append(flag)
            else:
                argv += [flag, str(v)]
        argv += list(spec.extra_args)
        return argv

    def _preempt(self, proc, reason: str, detail: str,
                 hm: HealthMonitor) -> None:
        extra = {}
        if detail:
            extra["detail"] = detail
        age = hm.last_event_age(self.clock())
        if age is not None:
            extra["stale_s"] = round(age, 3)
        append_event(self.sup_events, "preempt", reason=reason,
                     pid=proc.pid, **extra)
        # the documented lossless-stop contract: the notice lands in the
        # tenant's log first, so the run's own history attributes the stop
        append_event(self.events_path, "stop_requested",
                     reason=f"supervisor: {reason}", source="supervisor",
                     pid=proc.pid)
        self._say(f"preempting pid {proc.pid}: {reason}"
                  + (f" ({detail})" if detail else ""))
        try:
            proc.send_signal(signal.SIGINT)
        except ProcessLookupError:
            pass

    def _attempt(self, attempt: int, ndev: int, resume: bool) -> tuple:
        """One child lifetime: spawn, tail, health-check, (maybe)
        preempt, reap.  Returns ``(returncode, events, preempted)``."""
        argv = self._child_argv(ndev, resume)
        out_path = os.path.join(self.workdir, f"attempt{attempt:03d}.out")
        hm = HealthMonitor(self.policy, clock=self.clock,
                           fiducial_baseline=self._state.get("fiducials"))
        tail = _LogTail(self.events_path)
        tail.seek_end()                  # only this attempt's heartbeat
        with open(out_path, "ab") as out:
            proc = subprocess.Popen(argv, stdout=out,
                                    stderr=subprocess.STDOUT)
        t0_mono = time.monotonic()       # attempt span start (tracing)
        drain_mono = None                # preempt signal sent (drain span)
        hm.spawned_at = self.clock()
        self._say(f"attempt {attempt}: pid {proc.pid}, ndev {ndev}, "
                  + ("resume" if resume else "fresh start"))
        if self.spawn_hook:
            self.spawn_hook(self, proc, attempt)
        events: list = []
        preempted_at = None
        killed = False
        while True:
            rc = proc.poll()
            evs = tail.poll()
            events.extend(evs)
            hm.observe(evs)
            if rc is not None:
                break
            if preempted_at is None:
                bad = self._external or hm.verdict()
                self._external = None
                if bad:
                    self._preempt(proc, bad[0], bad[1], hm)
                    preempted_at = self.clock()
                    drain_mono = time.monotonic()
            elif not killed and \
                    self.clock() - preempted_at > self.policy.grace_s:
                self._say(f"grace window ({self.policy.grace_s:.0f}s) "
                          "expired; SIGKILL")
                try:
                    proc.kill()
                except ProcessLookupError:
                    pass
                killed = True
            self.sleep(self.policy.poll_s)
        events.extend(tail.poll())       # drain the post-exit flush
        if self.tracer.enabled:
            now_mono = time.monotonic()
            self.tracer.emit_span(
                "attempt", t0_mono, now_mono - t0_mono,
                thread="children", attempt=attempt, pid=proc.pid,
                ndev=ndev, exit_code=rc,
                preempted=preempted_at is not None)
            if drain_mono is not None:
                # preempt-signal -> child-exit: the lossless-stop drain
                # (SIGKILL included when the grace window expired).
                self.tracer.emit_span(
                    "preempt_drain", drain_mono, now_mono - drain_mono,
                    thread="children", attempt=attempt, killed=killed)
        if hm.fiducials_seen and not self._state.get("fiducials"):
            self._save_state(fiducials=hm.fiducials_seen)
        return rc, events, preempted_at is not None

    @staticmethod
    def _classify(rc: int, events: list) -> tuple:
        """(outcome-or-None, last run_end event-or-None): None outcome
        means the attempt is recoverable (stopped or crashed)."""
        ends = [e for e in events if e.get("event") == "run_end"]
        end = ends[-1] if ends else None
        if rc in _TERMINAL:
            if rc == EXIT_OK and end is None:
                # exited clean with no run_end in the log: torn log or
                # impostor exit — treat as a crash, the checkpoint decides
                return None, None
            return _TERMINAL[rc], end
        return None, end                 # stopped (14) or crashed

    @staticmethod
    def _progress(events: list) -> int:
        n = -1
        for e in events:
            if e.get("event") in ("segment", "checkpoint", "run_end"):
                n = max(n, int(e.get("n_states", -1)))
        return n

    # ----------------------------------------------------------------- run

    def run(self) -> CampaignResult:
        adm, reject = self._admit()
        if reject is not None:
            self._say(f"rejected at admission: {reject}")
            return CampaignResult("rejected", 1, None, None, 0, 0, 0,
                                  [], self.events_path, self.ckpt,
                                  detail=reject)
        self.config = adm.config
        attempt = int(self._state.get("attempt", 0))
        spawns = preempts = reshards = 0
        backoff_k = 0
        progress_mark = -1
        last_end = None
        last_rc = 1
        while True:
            resume = False
            if os.path.exists(self.ckpt) or snapshot_family(self.ckpt):
                if self.pre_verify_hook:
                    self.pre_verify_hook(self, attempt)
                resume = self._verify_or_recover(attempt)
                if not resume:
                    # fresh start: no partial family may shadow it
                    for p in snapshot_family(self.ckpt):
                        os.remove(p)
            ndev = self._mesh_for(attempt)
            ndev_have = self._state.get("ndev")
            if resume and ndev_have is not None and ndev != ndev_have:
                try:
                    self._reshard(ndev_have, ndev)
                except CheckpointCorrupt as e:
                    # damage the structural pass could not see; same
                    # poison contract — quarantine, re-enter recovery
                    self._quarantine("live", str(e),
                                     snapshot_family(self.ckpt))
                    continue
                reshards += 1
            self._save_state(ndev=ndev, attempt=attempt)
            if spawns:
                extra = {"path": self.ckpt, "ndev": ndev}
                if backoff_k:
                    extra["backoff_s"] = round(self._last_backoff_s, 3)
                if self.quarantined:
                    extra["quarantined"] = self.quarantined[-1][0]
                append_event(self.sup_events, "resume_attempt",
                             attempt=attempt, **extra)
            rc, events, preempted = self._attempt(attempt, ndev, resume)
            spawns += 1
            preempts += int(preempted)
            last_rc = rc
            outcome, end = self._classify(rc, events)
            last_end = end or last_end
            if outcome is not None:
                self._say(f"campaign verdict: {outcome} after "
                          f"{spawns} attempt(s)")
                return self._result(outcome, rc, last_end, spawns,
                                    preempts, reshards)
            if rc == 1 and not events and not resume \
                    and not os.path.exists(self.ckpt):
                # died before emitting a single event on a fresh start:
                # argv/config error, a retry re-runs the same failure
                return self._result(
                    "error", rc, last_end, spawns, preempts, reshards,
                    detail=f"child exited {rc} before its run started "
                           f"(see attempt{attempt:03d}.out)")
            n_now = self._progress(events)
            if n_now > progress_mark:
                progress_mark = n_now
                backoff_k = 0
            else:
                backoff_k += 1
            attempt += 1
            if spawns > self.policy.max_resumes:
                self._say(f"giving up after {spawns} attempt(s) "
                          f"(max_resumes={self.policy.max_resumes})")
                return self._result("gave-up", last_rc, last_end, spawns,
                                    preempts, reshards)
            delay = self._backoff(backoff_k)
            if delay > 0:
                self._say(f"retrying in {delay:.1f}s "
                          f"(attempt {attempt}, rc {rc})")
                self.sleep(delay)

    def _backoff(self, k: int) -> float:
        """Delay before retry ``k`` of the current no-progress streak:
        0 resets the jitter window (progress was made), k >= 1 draws the
        next decorrelated-jitter delay.  Stateful — call once per retry
        decision; the drawn value is kept in ``_last_backoff_s`` for the
        resume_attempt event."""
        if k <= 0:
            self._jitter.reset()
            self._last_backoff_s = 0.0
            return 0.0
        self._last_backoff_s = self._jitter.next()
        return self._last_backoff_s

    def _result(self, outcome: str, rc: int, end, spawns: int,
                preempts: int, reshards: int,
                detail: str = "") -> CampaignResult:
        code = {"ok": EXIT_OK, "deadlock": EXIT_DEADLOCK,
                "violation": EXIT_VIOLATION,
                "liveness": EXIT_LIVENESS}.get(outcome, 1)
        return CampaignResult(
            outcome, code,
            int(end["n_states"]) if end else None,
            int(end["n_transitions"]) if end else None,
            spawns, preempts, reshards, list(self.quarantined),
            self.events_path, self.ckpt, detail=detail)
