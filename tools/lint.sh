#!/usr/bin/env bash
# Pre-push gate: the speclint static analyzer plus a pytest collection
# sanity pass.  Fast (no model checking, no kernel compiles beyond the
# analyzer's own imports) — run it before every push:
#
#     tools/lint.sh            # both encoding modes, flagship cfg
#     tools/lint.sh --strict   # warnings fail too
#
# Exits nonzero if the analyzer reports an error (or, with --strict, any
# finding), or if the smoke-marked test set no longer collects.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== speclint (width + cfg + jit passes, parity & faithful) =="
python -m raft_tla_tpu.lint runs/MC3s2v.cfg "$@"

echo "== pytest smoke collection =="
python -m pytest tests/ -m smoke --collect-only -q -p no:cacheprovider \
    --continue-on-collection-errors | tail -2

echo "== obs smoke (event schema conformance) =="
python -m pytest tests/test_obs.py -m smoke -q -p no:cacheprovider | tail -2
