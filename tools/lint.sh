#!/usr/bin/env bash
# Pre-push gate: the speclint static analyzer (Passes 1-5) plus smoke
# runs of every gated subsystem.  Fast (no model checking beyond toy
# configs, no kernel compiles beyond the analyzer's own imports) — run
# it before every push:
#
#     tools/lint.sh            # both encoding modes, flagship cfg
#     tools/lint.sh --strict   # warnings fail too
#
# Exits nonzero if the analyzer reports an error (or, with --strict, any
# finding), or if any smoke block fails.  Every block is named: the
# summary table at the end shows one line per block, and a mid-script
# failure prints "FAILED in block: <name>" so it cannot be misread.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

SERVE_TMP=""
BLOCK_NAMES=()
BLOCK_STATUS=()
CURRENT_BLOCK=""

begin() {
    # close the previous block as ok (a failure never reaches the next
    # begin under set -e), then open the named one
    if [ -n "$CURRENT_BLOCK" ]; then
        BLOCK_NAMES+=("$CURRENT_BLOCK"); BLOCK_STATUS+=("ok")
    fi
    CURRENT_BLOCK="$1"
    echo "== $2 =="
}

on_exit() {
    rc=$?
    [ -n "$SERVE_TMP" ] && rm -rf "$SERVE_TMP"
    if [ -n "$CURRENT_BLOCK" ]; then
        BLOCK_NAMES+=("$CURRENT_BLOCK")
        if [ "$rc" -eq 0 ]; then BLOCK_STATUS+=("ok")
        else BLOCK_STATUS+=("FAIL"); fi
    fi
    echo
    echo "== lint.sh summary =="
    for ((i = 0; i < ${#BLOCK_NAMES[@]}; i++)); do
        printf '  %-14s %s\n' "${BLOCK_NAMES[$i]}" "${BLOCK_STATUS[$i]}"
    done
    if [ "$rc" -ne 0 ]; then
        echo "FAILED in block: $CURRENT_BLOCK (exit $rc)"
    else
        echo "all ${#BLOCK_NAMES[@]} blocks ok"
    fi
    exit "$rc"
}
trap on_exit EXIT

begin speclint "speclint (width + cfg + jit + thread + contract, parity & faithful)"
python -m raft_tla_tpu.lint runs/MC3s2v.cfg "$@"

begin collect "pytest smoke collection"
python -m pytest tests/ -m smoke --collect-only -q -p no:cacheprovider \
    --continue-on-collection-errors | tail -2

begin obs "obs smoke (event schema conformance)"
python -m pytest tests/test_obs.py -m smoke -q -p no:cacheprovider | tail -2

begin serve "serve smoke (2-job toy manifest end-to-end, CPU)"
SERVE_TMP="$(mktemp -d)"
cat > "$SERVE_TMP/toy.cfg" <<'CFG'
SPECIFICATION Spec
INVARIANT NoTwoLeaders
CONSTANTS
    Server = {s1, s2}
    Value = {v1}
    Follower = "Follower"
    Candidate = "Candidate"
    Leader = "Leader"
    Nil = "Nil"
    RequestVoteRequest = "RequestVoteRequest"
    RequestVoteResponse = "RequestVoteResponse"
    AppendEntriesRequest = "AppendEntriesRequest"
    AppendEntriesResponse = "AppendEntriesResponse"
CFG
cat > "$SERVE_TMP/manifest.jsonl" <<'MANIFEST'
{"id": "smoke-a", "cfg": "toy.cfg", "spec": "election", "max_term": 2, "max_log": 0, "max_msgs": 2}
{"id": "smoke-b", "cfg": "toy.cfg", "spec": "election", "max_term": 2, "max_log": 0, "max_msgs": 2}
MANIFEST
python -m raft_tla_tpu.serve "$SERVE_TMP/manifest.jsonl" \
    --out "$SERVE_TMP/out" --chunk 256 --cpu --quiet
python - "$SERVE_TMP/out" <<'PY'
import json, sys
out = sys.argv[1]
recs = [json.loads(l) for l in open(f"{out}/results.jsonl")]
assert len(recs) == 2 and all(r["status"] == "completed" for r in recs), recs
assert all(r["n_states"] == 3014 for r in recs), recs
from raft_tla_tpu.obs import validate_event
for r in recs:
    events = [json.loads(l) for l in open(r["events"])]
    assert not [e for d in events for e in validate_event(d)]
    assert events[-1]["event"] == "run_end" and events[-1]["outcome"] == "ok"
print(f"serve smoke ok: 2 jobs x {recs[0]['n_states']} states, "
      "per-tenant event logs valid")
PY

begin serve-daemon "serve daemon smoke (watch-dir intake -> SIGINT drain, CPU)"
mkdir -p "$SERVE_TMP/queue"
python -m raft_tla_tpu.serve "$SERVE_TMP/queue" --watch \
    --out "$SERVE_TMP/dout" --chunk 64 --poll 0.2 --cpu --quiet &
DAEMON_PID=$!
cat > "$SERVE_TMP/queue/001-watched.json" <<'JOB'
{"id": "watched", "cfg": "../toy.cfg", "spec": "election", "max_term": 2, "max_log": 0, "max_msgs": 1}
JOB
for _ in $(seq 1 600); do
    grep -q '"job_id": "watched"' "$SERVE_TMP/dout/results.jsonl" \
        2>/dev/null && break
    kill -0 "$DAEMON_PID" 2>/dev/null || { echo "daemon died early"; exit 1; }
    sleep 0.3
done
kill -INT "$DAEMON_PID"
wait "$DAEMON_PID" || { echo "daemon SIGINT drain exited nonzero"; exit 1; }
python - "$SERVE_TMP/dout" <<'PY'
import json, sys
recs = [json.loads(l) for l in open(f"{sys.argv[1]}/results.jsonl")]
(rec,) = [r for r in recs if r["job_id"] == "watched"]
assert rec["status"] == "completed" and rec["n_states"] == 524, rec
print("serve daemon smoke ok: watch intake served, SIGINT drained clean")
PY

begin serve-chaos "serve-chaos smoke (worker pool + mid-dispatch SIGKILL, CPU)"
# The pool's acceptance bar in miniature: solo reference pass, then the
# supervised worker pool with the first worker SIGKILLed after 2 segment
# events — requeued jobs re-run losslessly and every final results
# record and tenant event log must be canonically identical to solo.
python -m raft_tla_tpu.serve.chaos "$SERVE_TMP/toy.cfg" \
    --workdir "$SERVE_TMP/serve-chaos" --jobs 4 --workers 2 \
    --chunk 256 --max-msgs 1 --kill-after-segments 2 --cpu --quiet \
    | tail -1

begin frontend "frontend smoke (two-phase commit through the spec compiler, CPU)"
cat > "$SERVE_TMP/2pc.cfg" <<'CFG'
SPECIFICATION Spec
CONSTANT RM = {r1, r2}
INVARIANT TCConsistent
CFG
python -m raft_tla_tpu.check "$SERVE_TMP/2pc.cfg" \
    --spec twophase --engine host --chunk 256 --cpu \
    | tee "$SERVE_TMP/2pc.out" | tail -2
grep -q "^56 distinct states found" "$SERVE_TMP/2pc.out" \
    || { echo "frontend smoke FAILED: expected 56 states"; exit 1; }

begin megakernel "megakernel smoke (toy cfg, staged whole-step Pallas, CPU)"
# Gate forced ON: off-TPU this runs the kernel in Pallas interpret
# mode (ops/pallas_compat.resolve), so the block walks the real
# pallas_call staging path end-to-end inside a real engine.
python -m raft_tla_tpu.check "$SERVE_TMP/toy.cfg" \
    --spec election --max-term 2 --max-log 0 --max-msgs 2 \
    --chunk 256 --megakernel on --cpu --no-lint --no-trace \
    | tee "$SERVE_TMP/megakernel.out" | tail -2
grep -q "^3014 distinct states found" "$SERVE_TMP/megakernel.out" \
    || { echo "megakernel smoke FAILED: expected 3014 states"; exit 1; }

begin host-dedup "host-dedup smoke (ddd engine, background partitioned flush, CPU)"
# Gate forced ON: the toy cfg runs end-to-end through the ddd engine
# with partitioned master keys and the depth-1 background flush worker,
# then again with the gate OFF — the result lines (counts, diameter,
# transitions; wall stripped) must be byte-identical.
python -m raft_tla_tpu.check "$SERVE_TMP/toy.cfg" \
    --spec election --max-term 2 --max-log 0 --max-msgs 2 \
    --engine ddd --chunk 32 --host-dedup on --cpu --no-lint --no-trace \
    | tee "$SERVE_TMP/hostdedup_on.out" | tail -2
grep -q "^3014 distinct states found" "$SERVE_TMP/hostdedup_on.out" \
    || { echo "host-dedup smoke FAILED: expected 3014 states"; exit 1; }
python -m raft_tla_tpu.check "$SERVE_TMP/toy.cfg" \
    --spec election --max-term 2 --max-log 0 --max-msgs 2 \
    --engine ddd --chunk 32 --host-dedup off --cpu --no-lint --no-trace \
    > "$SERVE_TMP/hostdedup_off.out"
on_line="$(grep '^3014 distinct states found' "$SERVE_TMP/hostdedup_on.out" \
    | sed 's/, [0-9.]*s.*//')"
off_line="$(grep '^3014 distinct states found' "$SERVE_TMP/hostdedup_off.out" \
    | sed 's/, [0-9.]*s.*//')"
[ "$on_line" = "$off_line" ] \
    || { echo "host-dedup smoke FAILED: on/off result lines differ"; \
         echo "  on:  $on_line"; echo "  off: $off_line"; exit 1; }
echo "host-dedup smoke ok: on/off byte-identical ($on_line)"

begin prefetch "prefetch smoke (ddd engine, double-buffered upload staging, CPU)"
# Gate forced ON: the toy cfg runs end-to-end through the ddd engine
# with block uploads served from the background prefetch thread, then
# again with the gate OFF — the result lines (counts, diameter,
# transitions; wall stripped) must be byte-identical.
python -m raft_tla_tpu.check "$SERVE_TMP/toy.cfg" \
    --spec election --max-term 2 --max-log 0 --max-msgs 2 \
    --engine ddd --chunk 32 --prefetch on --cpu --no-lint --no-trace \
    | tee "$SERVE_TMP/prefetch_on.out" | tail -2
grep -q "^3014 distinct states found" "$SERVE_TMP/prefetch_on.out" \
    || { echo "prefetch smoke FAILED: expected 3014 states"; exit 1; }
python -m raft_tla_tpu.check "$SERVE_TMP/toy.cfg" \
    --spec election --max-term 2 --max-log 0 --max-msgs 2 \
    --engine ddd --chunk 32 --prefetch off --cpu --no-lint --no-trace \
    > "$SERVE_TMP/prefetch_off.out"
on_line="$(grep '^3014 distinct states found' "$SERVE_TMP/prefetch_on.out" \
    | sed 's/, [0-9.]*s.*//')"
off_line="$(grep '^3014 distinct states found' "$SERVE_TMP/prefetch_off.out" \
    | sed 's/, [0-9.]*s.*//')"
[ "$on_line" = "$off_line" ] \
    || { echo "prefetch smoke FAILED: on/off result lines differ"; \
         echo "  on:  $on_line"; echo "  off: $off_line"; exit 1; }
echo "prefetch smoke ok: on/off byte-identical ($on_line)"

begin device-dedup "device-dedup smoke (ddd engine, HBM within-level exact set, CPU)"
# Gate forced ON (hash backend): the toy cfg runs end-to-end through
# the ddd engine with the device-resident within-level fingerprint set
# filtering segment exports, then again with the gate OFF — the result
# lines (counts, diameter, transitions; wall stripped) must be
# byte-identical (the widening contract: the set only drops rows the
# host master keyset would reject anyway).
python -m raft_tla_tpu.check "$SERVE_TMP/toy.cfg" \
    --spec election --max-term 2 --max-log 0 --max-msgs 2 \
    --engine ddd --chunk 32 --device-dedup on --cpu --no-lint --no-trace \
    | tee "$SERVE_TMP/devdedup_on.out" | tail -2
grep -q "^3014 distinct states found" "$SERVE_TMP/devdedup_on.out" \
    || { echo "device-dedup smoke FAILED: expected 3014 states"; exit 1; }
python -m raft_tla_tpu.check "$SERVE_TMP/toy.cfg" \
    --spec election --max-term 2 --max-log 0 --max-msgs 2 \
    --engine ddd --chunk 32 --device-dedup off --cpu --no-lint --no-trace \
    > "$SERVE_TMP/devdedup_off.out"
on_line="$(grep '^3014 distinct states found' "$SERVE_TMP/devdedup_on.out" \
    | sed 's/, [0-9.]*s.*//')"
off_line="$(grep '^3014 distinct states found' "$SERVE_TMP/devdedup_off.out" \
    | sed 's/, [0-9.]*s.*//')"
[ "$on_line" = "$off_line" ] \
    || { echo "device-dedup smoke FAILED: on/off result lines differ"; \
         echo "  on:  $on_line"; echo "  off: $off_line"; exit 1; }
echo "device-dedup smoke ok: on/off byte-identical ($on_line)"

begin gates "gates smoke (--sig-prune/--prescan/--phase-timers/--compile-cache, CPU)"
# The four remaining RAFT_TLA_* gates exercised in one identity check:
# every gate forced away from its auto default (the phase-timer sync
# path, both kernel-policy gates, the persistent compile cache), then a
# default run — the result lines (wall stripped) must be byte-identical,
# and the compile cache directory must actually be populated.
python -m raft_tla_tpu.check "$SERVE_TMP/toy.cfg" \
    --spec election --max-term 2 --max-log 0 --max-msgs 2 \
    --engine ddd --chunk 32 --sig-prune on --prescan on \
    --phase-timers --compile-cache "$SERVE_TMP/jaxcache" \
    --cpu --no-lint --no-trace \
    | tee "$SERVE_TMP/gates_on.out" | tail -2
grep -q "^3014 distinct states found" "$SERVE_TMP/gates_on.out" \
    || { echo "gates smoke FAILED: expected 3014 states"; exit 1; }
[ -d "$SERVE_TMP/jaxcache" ] && [ -n "$(ls -A "$SERVE_TMP/jaxcache")" ] \
    || { echo "gates smoke FAILED: compile cache dir empty"; exit 1; }
python -m raft_tla_tpu.check "$SERVE_TMP/toy.cfg" \
    --spec election --max-term 2 --max-log 0 --max-msgs 2 \
    --engine ddd --chunk 32 --sig-prune off --prescan off \
    --cpu --no-lint --no-trace \
    > "$SERVE_TMP/gates_off.out"
on_line="$(grep '^3014 distinct states found' "$SERVE_TMP/gates_on.out" \
    | sed 's/, [0-9.]*s.*//')"
off_line="$(grep '^3014 distinct states found' "$SERVE_TMP/gates_off.out" \
    | sed 's/, [0-9.]*s.*//')"
[ "$on_line" = "$off_line" ] \
    || { echo "gates smoke FAILED: on/off result lines differ"; \
         echo "  on:  $on_line"; echo "  off: $off_line"; exit 1; }
echo "gates smoke ok: on/off byte-identical ($on_line)"

begin trace "trace smoke (v8 spans -> collect -> Perfetto -> report, CPU)"
# Tracing forced ON: the toy cfg runs through the ddd engine with span
# emission into the event log, the trace CLI must collect, export and
# attribute it — then the same run with tracing OFF must produce a
# byte-identical result line (the off-path discipline in one grep).
python -m raft_tla_tpu.check "$SERVE_TMP/toy.cfg" \
    --spec election --max-term 2 --max-log 0 --max-msgs 2 \
    --engine ddd --chunk 32 --host-dedup on --prefetch on \
    --events "$SERVE_TMP/trace.events" --trace \
    --cpu --no-lint --no-trace \
    | tee "$SERVE_TMP/trace_on.out" | tail -2
grep -q "^3014 distinct states found" "$SERVE_TMP/trace_on.out" \
    || { echo "trace smoke FAILED: expected 3014 states"; exit 1; }
grep -q '"event": "span"' "$SERVE_TMP/trace.events" \
    || { echo "trace smoke FAILED: no span events in the log"; exit 1; }
python -m raft_tla_tpu.obs.tracecli collect "$SERVE_TMP/trace.events"
python -m raft_tla_tpu.obs.tracecli export "$SERVE_TMP/trace.events" \
    -o "$SERVE_TMP/trace.json"
python -c "import json,sys; d=json.load(open(sys.argv[1])); \
    assert any(e['ph'] == 'X' for e in d['traceEvents']), 'no spans'" \
    "$SERVE_TMP/trace.json"
python -m raft_tla_tpu.obs.tracecli report "$SERVE_TMP/trace.events" \
    > "$SERVE_TMP/trace_report.out"
head -8 "$SERVE_TMP/trace_report.out"
python -m raft_tla_tpu.check "$SERVE_TMP/toy.cfg" \
    --spec election --max-term 2 --max-log 0 --max-msgs 2 \
    --engine ddd --chunk 32 --host-dedup on --prefetch on \
    --cpu --no-lint --no-trace \
    > "$SERVE_TMP/trace_off.out"
on_line="$(grep '^3014 distinct states found' "$SERVE_TMP/trace_on.out" \
    | sed 's/, [0-9.]*s.*//')"
off_line="$(grep '^3014 distinct states found' "$SERVE_TMP/trace_off.out" \
    | sed 's/, [0-9.]*s.*//')"
[ "$on_line" = "$off_line" ] \
    || { echo "trace smoke FAILED: on/off result lines differ"; \
         echo "  on:  $on_line"; echo "  off: $off_line"; exit 1; }
echo "trace smoke ok: on/off byte-identical ($on_line)"

begin campaign-chaos "chaos smoke (campaign SIGKILL + reshard 1->2->1, CPU)"
# The campaign supervisor's acceptance loop in miniature: reference run,
# then SIGKILL after the 2nd checkpoint, auto-reshard across a 1->2->1
# virtual-mesh plan, unattended resume — finals must be identical.
python -m raft_tla_tpu.campaign.chaos "$SERVE_TMP/toy.cfg" \
    --workdir "$SERVE_TMP/campaign" --spec election \
    --max-term 2 --max-log 0 --max-msgs 2 \
    --window 128 --chunk 32 --kill-after 2 --mesh-plan 1,2,1 --cpu \
    | tail -3

begin fleet "fleet smoke (sharded walker fleet, 2 virtual devices, CPU)"
# Deterministic seed: the same cfg at the same seed must report the same
# behavior/state counts every run, on any mesh (the fleet's
# device-count-invariance contract in one grep).
python -m raft_tla_tpu.check "$SERVE_TMP/toy.cfg" \
    --engine ref --spec election --max-term 2 --max-log 0 --max-msgs 2 \
    --simulate 200 --depth 20 --walkers 64 --seed 5 \
    --fleet --devices 2 --cpu \
    | tee "$SERVE_TMP/fleet.out" | tail -4
grep -q "^Fleet: 2 devices x 32 walkers" "$SERVE_TMP/fleet.out" \
    || { echo "fleet smoke FAILED: no fleet summary"; exit 1; }
grep -q "behaviors generated" "$SERVE_TMP/fleet.out" \
    || { echo "fleet smoke FAILED: no behaviors line"; exit 1; }

begin metrics "metrics smoke (OpenMetrics endpoint + v10 snapshot, gate on/off identity, CPU)"
# Gate forced ON (--metrics-port 0 = ephemeral port; RAFT_TLA_METRICS
# is the equivalent process-wide switch): the toy manifest runs
# one-pass with the endpoint up, and every stable result field must be
# identical to the gate-off serve block's records — the endpoint is a
# pure log reader.  Then the watch daemon with a 2-worker pool: scrape
# the live endpoint (per-tenant p99 latency summary, queue depth, pool
# worker counters), SIGINT drain, and the replayable
# OUT/metrics.events snapshot log must validate as schema v10.
python -m raft_tla_tpu.serve "$SERVE_TMP/manifest.jsonl" \
    --out "$SERVE_TMP/mout" --chunk 256 --metrics-port 0 --cpu --quiet \
    | tee "$SERVE_TMP/metrics_serve.out"
grep -q "^metrics endpoint: http://127.0.0.1:" \
    "$SERVE_TMP/metrics_serve.out" \
    || { echo "metrics smoke FAILED: no endpoint line"; exit 1; }
python - "$SERVE_TMP/out" "$SERVE_TMP/mout" <<'PY'
import json, sys
VOLATILE = ("admission_s", "wall_s", "states_per_sec", "events")
def canon(out):
    recs = [json.loads(l) for l in open(f"{out}/results.jsonl")]
    return sorted(
        json.dumps({k: v for k, v in r.items() if k not in VOLATILE},
                   sort_keys=True) for r in recs)
off, on = canon(sys.argv[1]), canon(sys.argv[2])
assert off == on, f"gate on/off result records differ:\n{off}\n{on}"
print("metrics one-pass ok: gate on/off result records identical")
PY
mkdir -p "$SERVE_TMP/mqueue"
python -m raft_tla_tpu.serve "$SERVE_TMP/mqueue" --watch --workers 2 \
    --out "$SERVE_TMP/mdout" --chunk 64 --poll 0.2 --metrics-port 0 \
    --cpu --quiet > "$SERVE_TMP/mdaemon.out" &
MDAEMON_PID=$!
cat > "$SERVE_TMP/mqueue/001-mjob.json" <<'JOB'
{"id": "mjob", "cfg": "../toy.cfg", "spec": "election", "max_term": 2, "max_log": 0, "max_msgs": 1}
JOB
for _ in $(seq 1 600); do
    grep -q '"job_id": "mjob"' "$SERVE_TMP/mdout/results.jsonl" \
        2>/dev/null && break
    kill -0 "$MDAEMON_PID" 2>/dev/null \
        || { echo "metrics daemon died early"; exit 1; }
    sleep 0.3
done
MPORT="$(sed -n \
    's|^metrics endpoint: http://127.0.0.1:\([0-9]*\)/metrics$|\1|p' \
    "$SERVE_TMP/mdaemon.out")"
[ -n "$MPORT" ] \
    || { echo "metrics smoke FAILED: no port in daemon output"; exit 1; }
python - "$MPORT" <<'PY'
import sys, urllib.request
body = urllib.request.urlopen(
    f"http://127.0.0.1:{sys.argv[1]}/metrics", timeout=10).read().decode()
assert 'raft_tla_latency_seconds{tenant="mjob",quantile="0.99"}' in body, \
    body
assert "raft_tla_queue_depth" in body, body
assert "raft_tla_workers_spawned_total" in body, body
print("metrics scrape ok: per-tenant p99 latency + queue depth + "
      "pool counters served")
PY
kill -INT "$MDAEMON_PID"
wait "$MDAEMON_PID" \
    || { echo "metrics daemon SIGINT drain exited nonzero"; exit 1; }
python - "$SERVE_TMP/mdout/metrics.events" <<'PY'
import json, sys
from raft_tla_tpu.obs import validate_event
evs = [json.loads(l) for l in open(sys.argv[1])]
assert evs and all(e["event"] == "metrics_snapshot" for e in evs), evs
assert not [err for e in evs for err in validate_event(e)]
print(f"metrics snapshot ok: {len(evs)} schema-v10 snapshot(s) "
      "replayable from the log alone")
PY

begin regress "regress smoke (history ingest -> drift verdicts -> A/B reproduction)"
# The cross-run sentinel end-to-end (--history PATH; RAFT_TLA_HISTORY
# is the equivalent): the recorded BENCH drivers seed the store, the
# same-config round passes clean (exit 0), a planted 10x wall
# regression exits 4, and the recorded devdedup A/B reproduces its
# RESULTS.md refutation verdict mechanically.
python -m raft_tla_tpu.obs.regress ingest BENCH_r0*.json \
    --history "$SERVE_TMP/history.jsonl"
python -m raft_tla_tpu.obs.regress check BENCH_r05.json \
    --history "$SERVE_TMP/history.jsonl" \
    || { echo "regress smoke FAILED: clean re-run did not exit 0"; exit 1; }
python - "$SERVE_TMP/slow.json" <<'PY'
import json, sys
doc = json.load(open("BENCH_r05.json"))
for k, v in list(doc["parsed"].items()):
    if isinstance(v, (int, float)) and not isinstance(v, bool) \
        and ("wall" in k or k.endswith("_ms")):
        doc["parsed"][k] = v * 10.0
json.dump(doc, open(sys.argv[1], "w"))
PY
rc=0
python -m raft_tla_tpu.obs.regress check "$SERVE_TMP/slow.json" \
    --history "$SERVE_TMP/history.jsonl" || rc=$?
[ "$rc" -eq 4 ] \
    || { echo "regress smoke FAILED: planted drift exit $rc != 4"; exit 1; }
rc=0
python -m raft_tla_tpu.obs.regress ab runs/devdedup_ab.out || rc=$?
[ "$rc" -eq 4 ] \
    || { echo "regress smoke FAILED: devdedup ab exit $rc != 4"; exit 1; }
echo "regress smoke ok: clean pass, planted drift caught (exit 4), devdedup refutation reproduced"
