"""Trace layer: spans, clock alignment, Perfetto export, attribution.

The contract under test is PR 17's tentpole: with ``--trace`` on, every
process in a run (engines, scheduler, pool supervisor) emits schema-v8
``span`` events into its own log, each log carries a wall/monotonic
anchor, and the collector merges them onto ONE wall axis with the skew
bounded by the recorded anchor error; with tracing off (the default),
every instrumentation site touches one shared null handle and the logs
are byte-compatible with v7 consumers.
"""

import json
import os
import threading
import time

import pytest

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.obs import collect as obs_collect
from raft_tla_tpu.obs import perfetto as obs_perfetto
from raft_tla_tpu.obs.events import append_event, validate_event
from raft_tla_tpu.obs.phases import PhaseTimers
from raft_tla_tpu.obs.trace import (NULL_TRACER, SpanTracer, clock_anchor,
                                    trace_enabled, tracer_for)

CFG = CheckConfig(
    bounds=Bounds(n_servers=2, n_values=1, max_term=2, max_log=0,
                  max_msgs=2),
    spec="election", invariants=("NoTwoLeaders",), chunk=32)
N_TOY = 3014


# --------------------------------------------------------------------------
# span model


def _capture_tracer():
    rows = []
    tr = SpanTracer(lambda event, **f: rows.append({"event": event, **f}))
    return tr, rows


def test_span_nesting_parent_ids_and_set():
    tr, rows = _capture_tracer()
    with tr.span("outer", a=1):
        assert tr.current_id() == 1
        with tr.span("inner") as sp:
            assert tr.current_id() == 2
            sp.set(rows=256)
    assert tr.current_id() is None
    # inner emitted first (exit order), parented to outer
    inner, outer = rows
    assert inner["name"] == "inner" and inner["parent_id"] == 1
    assert inner["args"] == {"rows": 256}
    assert outer["name"] == "outer" and "parent_id" not in outer
    assert outer["args"] == {"a": 1}
    assert outer["t0"] <= inner["t0"]
    assert inner["dur"] <= outer["dur"]


def test_span_thread_attribution_is_per_thread():
    tr, rows = _capture_tracer()

    def work():
        with tr.span("bg"):
            # a fresh thread has its own stack: no parent inherited
            # from the main thread's open span
            assert tr.current_id() is not None

    with tr.span("main_work"):
        t = threading.Thread(target=work, name="bg-thread")
        t.start()
        t.join()
    by = {r["name"]: r for r in rows}
    assert by["bg"]["thread"] == "bg-thread"
    assert "parent_id" not in by["bg"]
    assert by["main_work"]["thread"] == threading.current_thread().name


def test_manual_spans_ride_synthetic_tracks():
    tr, rows = _capture_tracer()
    t0 = time.monotonic()
    tr.emit_span("ticket", t0, 0.5, thread="tickets", bin="b0")
    tr.emit_span("worker", t0, -1.0, thread="workers")  # clamped
    assert rows[0]["thread"] == "tickets"
    assert rows[0]["args"] == {"bin": "b0"}
    assert rows[1]["dur"] == 0.0
    assert rows[0]["span_id"] != rows[1]["span_id"]


def test_spans_validate_at_schema_v8(tmp_path):
    log = str(tmp_path / "t.events")
    tr = tracer_for(log)
    with tr.span("expand", rows=4):
        pass
    d = json.loads(open(log).read())
    assert d["event"] == "span" and validate_event(d) == []


# --------------------------------------------------------------------------
# off path


def test_off_path_is_one_shared_handle():
    assert not trace_enabled("")
    assert not trace_enabled("off")
    assert trace_enabled("1") and trace_enabled("on")
    s1 = NULL_TRACER.span("a", x=1)
    s2 = NULL_TRACER.span("b")
    assert s1 is s2                      # no per-call allocation
    with s1 as sp:
        assert sp.set(y=2) is sp
    assert NULL_TRACER.current_id() is None
    NULL_TRACER.emit_span("x", 0.0, 1.0)  # no-op, nothing to observe


def _ok_result():
    from types import SimpleNamespace
    return SimpleNamespace(n_states=1, n_transitions=1, complete=True,
                           violation=None, diameter=1, levels=[1],
                           wall_s=0.1)


def test_untraced_run_emits_no_spans_and_null_tracer(tmp_path,
                                                     monkeypatch):
    monkeypatch.delenv("RAFT_TLA_TRACE", raising=False)
    from raft_tla_tpu.obs.events import RunTelemetry
    tel = RunTelemetry("ddd", config=CFG,
                       events=str(tmp_path / "off.events"))
    assert tel.trace is NULL_TRACER
    tel.run_start()
    with tel.phases.phase("expand"):
        pass
    tel.run_end(_ok_result())
    tel.close()
    evs = [json.loads(l) for l in open(tmp_path / "off.events")]
    assert [e["event"] for e in evs] == ["run_start", "run_end"]
    # the anchor rides run_start unconditionally (it is cheap and makes
    # ANY log alignable); host context only when traced
    assert "anchor" in evs[0] and "host" not in evs[0]


def test_traced_telemetry_attaches_tracer(tmp_path, monkeypatch):
    monkeypatch.setenv("RAFT_TLA_TRACE", "1")
    from raft_tla_tpu.obs.events import RunTelemetry
    tel = RunTelemetry("ddd", config=CFG,
                       events=str(tmp_path / "on.events"))
    assert tel.trace.enabled and tel.phases.tracer is tel.trace
    tel.run_start()
    with tel.phases.phase("expand"):
        pass
    tel.run_end(_ok_result())
    tel.close()
    evs = [json.loads(l) for l in open(tmp_path / "on.events")]
    assert [e["event"] for e in evs] \
        == ["run_start", "span", "run_end"]
    assert "host" in evs[0]
    assert evs[1]["name"] == "expand"
    assert all(validate_event(e) == [] for e in evs)


# --------------------------------------------------------------------------
# PhaseTimers thread attribution (the v8 bugfix)


def test_phase_timers_background_thread_buckets():
    """Work timed on a non-owner thread lands in its own
    ``{phase}@{thread}`` bucket instead of silently racing the owner's
    accumulator — and the snapshot drains both."""
    pt = PhaseTimers(enabled=True)

    def work():
        with pt.phase("dedup"):
            time.sleep(0.01)

    with pt.phase("dedup"):
        time.sleep(0.01)
    t = threading.Thread(target=work, name="raft-tla-flush")
    t.start()
    t.join()
    snap = pt.snapshot()
    assert set(snap) == {"dedup", "dedup@raft-tla-flush"}
    assert snap["dedup"] > 0 and snap["dedup@raft-tla-flush"] > 0


def test_phase_timers_trace_only_emits_spans_without_sync():
    """A tracer on a DISABLED PhaseTimers still opens spans (trace-only
    mode) but never syncs or accumulates — dispatch pipelining stays
    intact and ``phase_s`` stays empty.  With both layers off the
    handle is the shared null singleton."""
    pt = PhaseTimers(enabled=False)
    tr, rows = _capture_tracer()
    pt.tracer = tr
    with pt.phase("expand") as ph:
        # sync() marks a value to block on — with timers disabled the
        # exit path must never touch it (no jax sync in trace-only mode)
        ph.sync(object())
    assert [r["name"] for r in rows] == ["expand"]
    assert pt.snapshot() == {}
    pt.tracer = NULL_TRACER
    assert pt.phase("expand") is pt.phase("upload")  # shared null handle


# --------------------------------------------------------------------------
# collector: clock alignment


def _synthetic_log(path, engine, pid, wall0, mono0, spans,
                   err_s=1e-6):
    """A minimal anchored log: run_start + spans with process-local
    monotonic t0 values (mono0 + offset)."""
    append_event(path, "run_start", engine=engine, universe={},
                 spec="", invariants=[], resumed=False, pid=pid,
                 anchor={"wall": wall0, "mono": mono0, "err_s": err_s},
                 host={"nproc": 1})
    for i, (name, off, dur, thread) in enumerate(spans, 1):
        append_event(path, "span", name=name, span_id=i,
                     t0=mono0 + off, dur=dur, thread=thread)


def test_two_process_clock_alignment(tmp_path):
    """Two processes whose monotonic clocks started at wildly different
    points record the SAME wall-time story; the collector aligns them
    through their anchors to within the recorded error bound."""
    a = str(tmp_path / "a.events")
    b = str(tmp_path / "b.events")
    wall = 1_700_000_000.0
    # process a: mono started 50s ago; process b: 9000s ago
    _synthetic_log(a, "ddd", 100, wall, 50.0,
                   [("expand", 1.0, 0.5, "MainThread")])
    _synthetic_log(b, "sched", 200, wall, 9000.0,
                   [("dispatch", 1.0, 0.5, "MainThread")])
    col = obs_collect.collect([a, b])
    assert len(col["processes"]) == 2
    sa, sb = col["spans"]
    # both spans happened at wall+1.0 despite disjoint monotonic bases
    assert abs(sa["ts"] - (wall + 1.0)) <= 1e-6
    assert abs(sa["ts"] - sb["ts"]) <= 2 * col["skew_bound_s"] + 1e-9
    assert col["skew_bound_s"] == 1e-6


def test_collector_anchorless_fallback_and_mixed_versions(tmp_path):
    """A log with no anchor (pre-v8 producer) degrades to the span's
    append stamp minus duration — still placed, flagged unanchored —
    and non-span/v7 rows in the mix are passed through as instants."""
    log = str(tmp_path / "old.events")
    append_event(log, "run_start", engine="ddd", universe={},
                 spec="", invariants=[], resumed=False, pid=7)
    append_event(log, "span", name="expand", span_id=1, t0=123.0,
                 dur=0.25, thread="MainThread")
    append_event(log, "worker_spawn", worker="w0", pid=9)
    d = [json.loads(l) for l in open(log)]
    col = obs_collect.collect([log])
    (proc,) = col["processes"]
    assert proc["anchored"] is False and proc["skew_bound_s"] is None
    (span,) = col["spans"]
    assert abs(span["ts"] - (d[1]["ts"] - 0.25)) <= 1e-9
    assert [i["name"] for i in col["instants"]] == ["worker_spawn"]


# --------------------------------------------------------------------------
# Perfetto export


def test_perfetto_export_structure(tmp_path):
    a = str(tmp_path / "a.events")
    wall = 1_700_000_000.0
    _synthetic_log(a, "ddd", 100, wall, 50.0,
                   [("expand", 1.0, 0.5, "MainThread"),
                    ("prefetch", 1.1, 0.2, "raft-tla-prefetch")])
    append_event(a, "segment", wall_s=2.0, n_states=10, level=1,
                 n_transitions=20, dedup_hit_rate=0.5,
                 states_per_sec=5.0, inc_states_per_sec=5.0,
                 since_resume=False)
    append_event(a, "run_end", outcome="ok", n_states=10,
                 n_transitions=20, complete=True)
    col = obs_collect.collect([a])
    out = str(tmp_path / "trace.json")
    n = obs_perfetto.export(col, out)
    doc = json.loads(open(out).read())
    evs = doc["traceEvents"]
    assert len(evs) == n
    meta = [e for e in evs if e["ph"] == "M"]
    names = {e["name"]: e for e in meta}
    assert "process_name" in names
    tthreads = {e["args"]["name"]: e["tid"] for e in meta
                if e["name"] == "thread_name"}
    assert tthreads["MainThread"] == 1          # main track first
    assert tthreads["raft-tla-prefetch"] == 2
    xs = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert xs["expand"]["dur"] == 0.5e6
    # rebased to t_min: the earliest stamp in the collection is 0
    assert min(e["ts"] for e in evs if e["ph"] != "M") == 0.0
    assert [e for e in evs if e["ph"] == "C"]   # the rate counter
    assert [e for e in evs if e["ph"] == "i"]   # run_end instant


# --------------------------------------------------------------------------
# end-to-end: traced engine run, report attribution, CLI


@pytest.mark.smoke
def test_traced_ddd_run_report_attribution(tmp_path, monkeypatch):
    """The acceptance bar on one process: a traced toy ddd run (host
    dedup + prefetch on) collects into a timeline whose main thread is
    >= 95% attributed to named phases, with the prefetch thread on its
    own track — and the traced result equals the untraced oracle."""
    monkeypatch.setenv("RAFT_TLA_TRACE", "1")
    monkeypatch.setenv("RAFT_TLA_HOSTDEDUP", "on")
    monkeypatch.setenv("RAFT_TLA_PREFETCH", "on")
    from raft_tla_tpu.ddd_engine import DDDCapacities, DDDEngine
    log = str(tmp_path / "ddd.events")
    eng = DDDEngine(CFG, DDDCapacities(block=256, table=1 << 14,
                                       flush=1 << 10, levels=64))
    res = eng.check(events=log)
    assert res.n_states == N_TOY
    evs = [json.loads(l) for l in open(log)]
    assert all(validate_event(e) == [] for e in evs)
    spans = [e for e in evs if e["event"] == "span"]
    assert {s["name"] for s in spans} >= {"expand", "upload", "dedup"}
    assert "raft-tla-prefetch" in {s["thread"] for s in spans}

    col = obs_collect.collect(obs_collect.find_logs(str(tmp_path)))
    rep = obs_collect.report(col)
    (proc,) = rep["processes"]
    main = proc["threads"]["MainThread"]
    assert main["attributed_frac"] >= 0.95
    assert abs(main["attributed_frac"] + main["gap_frac"] - 1.0) < 1e-9
    assert proc["levels"], "level_end marks should yield critical path"
    text = obs_collect.render_report(rep)
    assert "MainThread" in text and "expand" in text

    # the CLI over the same directory: collect, export, report
    from raft_tla_tpu.obs.tracecli import main as trace_main
    out = str(tmp_path / "trace.json")
    assert trace_main(["export", str(tmp_path), "-o", out]) == 0
    doc = json.loads(open(out).read())
    assert any(e["ph"] == "X" for e in doc["traceEvents"])
    assert trace_main(["collect", str(tmp_path)]) == 0
    assert trace_main(["report", str(tmp_path), "--json"]) == 0


@pytest.mark.smoke
def test_pool_run_merges_into_one_timeline(tmp_path, monkeypatch):
    """The multi-process acceptance bar: a traced --workers 2 pool run
    leaves logs that collect into ONE timeline — pool supervisor with
    worker-lifetime spans, each worker's scheduler with dispatch/
    harvest/ticket spans, each tenant engine with phase spans — all
    anchored, distinct pids, Perfetto-exportable."""
    monkeypatch.setenv("RAFT_TLA_TRACE", "1")
    from test_cli import write_cfg

    from raft_tla_tpu.serve.jobs import CheckJob, JobOptions
    from raft_tla_tpu.serve.pool import run_pool
    from raft_tla_tpu.serve.supervise import PoolPolicy
    cfg = write_cfg(tmp_path / "toy.cfg")
    opts = JobOptions(spec="election", max_term=2, max_log=0, max_msgs=1)
    opts_sym = JobOptions(spec="election", max_term=2, max_log=0,
                          max_msgs=1, symmetry=True)
    jobs = [CheckJob("j0", opts, cfg_path=str(cfg)),
            CheckJob("j1", opts_sym, cfg_path=str(cfg))]
    out = str(tmp_path / "out")
    recs = run_pool(jobs, out, workers=2, chunk=256, cpu=True,
                    quiet=True,
                    policy=PoolPolicy(poll_s=0.02, backoff_base_s=0.05,
                                      backoff_cap_s=0.2,
                                      backoff_jitter_seed=7))
    assert all(r["status"] == "completed" for r in recs)

    logs = obs_collect.find_logs(out)
    assert any(p.endswith("pool.events") for p in logs)
    assert sum("sched-" in os.path.basename(p) for p in logs) == 2
    col = obs_collect.collect(logs)
    by_engine = {}
    for p in col["processes"]:
        by_engine.setdefault(p["engine"], []).append(p)
    assert len(by_engine["pool"]) == 1
    assert len(by_engine["sched"]) == 2
    assert len(by_engine["serve"]) == 2          # tenant logs
    assert all(p["anchored"] for p in col["processes"])
    assert col["skew_bound_s"] is not None
    # >= 3 distinct OS processes: the supervisor + 2 workers (each
    # worker contributes a sched row AND its tenant rows, same os_pid)
    assert len({p["os_pid"] for p in col["processes"]}) >= 3
    sched_os = {p["os_pid"] for p in by_engine["sched"]}
    serve_os = {p["os_pid"] for p in by_engine["serve"]}
    assert serve_os <= sched_os         # tenants ran inside the workers

    sup = by_engine["pool"][0]
    sup_spans = [s for s in col["spans"] if s["pid"] == sup["pid"]]
    assert {s["name"] for s in sup_spans} >= {"worker"}
    assert {s["thread"] for s in sup_spans} == {"workers"}
    sched_spans = [s for s in col["spans"]
                   if s["pid"] in {p["pid"] for p in by_engine["sched"]}]
    assert {s["name"] for s in sched_spans} >= {"dispatch", "harvest",
                                                "ticket", "compile"}
    assert "tickets" in {s["thread"] for s in sched_spans}

    rep = obs_collect.report(col)
    assert len(rep["processes"]) == len(col["processes"])
    out_json = str(tmp_path / "pool_trace.json")
    n = obs_perfetto.export(col, out_json)
    assert n > 0
    doc = json.loads(open(out_json).read())
    pids = {e["pid"] for e in doc["traceEvents"]}
    assert len(pids) >= 3                        # distinct tracks
