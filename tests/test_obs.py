"""Cross-engine run-event conformance + obs unit tests.

The conformance tests are the contract the obs/ package exists for:
every engine family emits the SAME versioned event schema, so one
monitor (and one campaign-projection client) reads all of them.  Each
engine runs the tiny election universe, the resulting log is validated
line by line against the strict schema, and the final ``run_end`` count
must agree with the ``EngineResult`` — and across engines.
"""

import json
import subprocess
import sys
import time

import pytest

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.obs import monitor
from raft_tla_tpu.obs.events import (
    SCHEMA_VERSION, EventLog, ProgressTracker, append_event, validate_event)
from raft_tla_tpu.obs.phases import PhaseTimers

CFG = CheckConfig(
    bounds=Bounds(n_servers=2, n_values=1, max_term=2, max_log=0,
                  max_msgs=2),
    spec="election", invariants=("NoTwoLeaders",), chunk=32)
N_TOY = 3014            # distinct states of the toy universe (oracle)


def _read_log(path):
    with open(path) as fh:
        return [json.loads(line) for line in fh]


def _assert_conformant(evs, engine):
    """The schema contract: valid lines, run_start first, run_end last,
    segments carrying the shared ProgressRecord fields."""
    errs = [(e["event"], err) for e in evs for err in validate_event(e)]
    assert not errs, errs[:5]
    assert evs[0]["event"] == "run_start"
    assert evs[0]["engine"] == engine
    assert evs[0]["universe"] == {"servers": 2, "values": 1}
    assert evs[-1]["event"] == "run_end"
    assert evs[-1]["outcome"] == "ok" and evs[-1]["complete"]
    segs = [e for e in evs if e["event"] == "segment"]
    assert segs, f"{engine}: no segment events"
    for s in segs:
        assert s["v"] == SCHEMA_VERSION
        assert s["since_resume"] is True
        # per-invariant evaluation counts (TLC -coverage 1 analogue):
        # every generated state was checked against every invariant
        assert s["inv_evals"] == {"NoTwoLeaders": s["n_transitions"]}
    # level_end events appear whenever a level transition is observed
    # between segments (always for the ddd family, pacing-dependent for
    # table engines whose budget can cross several levels per segment)
    ends = [e["level"] for e in evs if e["event"] == "level_end"]
    assert ends == sorted(ends)
    return evs[-1]["n_states"]


def _run_engine(name, events, on_progress=None):
    if name == "device":
        from raft_tla_tpu.device_engine import Capacities, DeviceEngine
        eng = DeviceEngine(CFG, Capacities(n_states=1 << 15, levels=64))
    elif name == "paged":
        from raft_tla_tpu.paged_engine import PagedCapacities, PagedEngine
        eng = PagedEngine(CFG, PagedCapacities(ring=16384, table=1 << 15,
                                               levels=64))
    elif name == "streamed":
        from raft_tla_tpu.streamed_engine import (StreamedCapacities,
                                                  StreamedEngine)
        eng = StreamedEngine(CFG, StreamedCapacities(
            block=256, ring=4096, table=1 << 14, levels=64))
    elif name == "ddd":
        from raft_tla_tpu.ddd_engine import DDDCapacities, DDDEngine
        eng = DDDEngine(CFG, DDDCapacities(block=256, table=1 << 14,
                                           flush=1 << 10, levels=64))
    elif name == "shard":
        from raft_tla_tpu.parallel import (ShardCapacities, ShardEngine,
                                           make_mesh)
        eng = ShardEngine(CFG, make_mesh(8),
                          ShardCapacities(n_states=1 << 12, levels=64))
    elif name == "pagedshard":
        from raft_tla_tpu.parallel.paged_shard_engine import (
            PagedShardCapacities, PagedShardEngine)
        from raft_tla_tpu.parallel.shard_engine import make_mesh
        eng = PagedShardEngine(CFG, make_mesh(8), PagedShardCapacities(
            ring=4096, table=1 << 12, levels=64))
    else:
        from raft_tla_tpu.parallel.ddd_shard_engine import (
            DDDShardCapacities, DDDShardEngine)
        eng = DDDShardEngine(CFG, caps=DDDShardCapacities(
            block=256, table=1 << 14, flush=1 << 10, levels=64))
    return eng.check(events=events, on_progress=on_progress)


@pytest.mark.smoke
@pytest.mark.parametrize("engine", ["device", "paged", "streamed", "ddd"])
def test_event_conformance_single_device(engine, tmp_path):
    path = str(tmp_path / f"{engine}.events")
    lines = []
    res = _run_engine(engine, path, on_progress=lines.append)
    evs = _read_log(path)
    n = _assert_conformant(evs, engine)
    assert n == res.n_states == N_TOY
    if engine in ("streamed", "ddd"):  # boundary-exact level accounting
        assert [e["level"] for e in evs if e["event"] == "level_end"]
    # on_progress receives the same records the log's segments carry
    segs = [e for e in evs if e["event"] == "segment"]
    assert len(lines) == len(segs)
    for cb, seg in zip(lines, segs):
        assert cb["n_states"] == seg["n_states"]
        assert cb["inc_states_per_sec"] == seg["inc_states_per_sec"]


@pytest.mark.smoke
@pytest.mark.slow
@pytest.mark.parametrize("engine", ["shard", "pagedshard", "ddd-shard"])
def test_event_conformance_sharded(engine, tmp_path):
    path = str(tmp_path / "shard.events")
    res = _run_engine(engine, path)
    evs = _read_log(path)
    n = _assert_conformant(evs, engine)
    assert n == res.n_states == N_TOY
    assert evs[0]["n_devices"] >= 1


# --------------------------------------------------------------------------
# schema unit tests


def test_validate_rejects_unknowns_and_type_drift():
    ok = {"v": 1, "event": "level_end", "ts": 0.0, "level": 3,
          "n_states": 10}
    assert validate_event(ok) == []
    assert validate_event({**ok, "event": "levelend"})      # unknown event
    assert validate_event({**ok, "extra": 1})               # unknown field
    assert validate_event({**ok, "level": "3"})             # type drift
    assert validate_event({**ok, "level": True})            # bool is not int
    assert validate_event({**ok, "v": 2}) == []             # v2 superset
    assert validate_event({**ok, "v": 3}) == []             # v3 superset
    assert validate_event({**ok, "v": 4}) == []             # v4 superset
    assert validate_event({**ok, "v": 5}) == []             # v5 superset
    assert validate_event({**ok, "v": 6}) == []             # v6 superset
    assert validate_event({**ok, "v": 7}) == []             # v7 superset
    assert validate_event({**ok, "v": 8}) == []             # v8 superset
    assert validate_event({**ok, "v": 9}) == []             # v9 superset
    assert validate_event({**ok, "v": 10}) == []            # v10 superset
    assert validate_event({**ok, "v": 11})                  # future version
    assert validate_event({"v": 1, "event": "level_end", "ts": 0.0,
                           "level": 3})                     # missing field


def test_validate_v2_supervisor_events():
    ok = {"v": 2, "event": "preempt", "ts": 0.0, "reason": "stale"}
    assert validate_event(ok) == []
    assert validate_event({**ok, "stale_s": 12.5, "pid": 7}) == []
    assert validate_event({**ok, "v": 1})      # v2-only type on a v1 line
    assert validate_event({"v": 2, "event": "reshard", "ts": 0.0,
                           "ndev_src": 8, "ndev_dst": 2,
                           "n_states": 3014}) == []
    assert validate_event({"v": 2, "event": "reshard", "ts": 0.0,
                           "ndev_src": 8})     # missing ndev_dst
    assert validate_event({"v": 2, "event": "resume_attempt", "ts": 0.0,
                           "attempt": 1, "backoff_s": 0.5,
                           "quarantined": "x.ckpt"}) == []
    assert validate_event({"v": 2, "event": "resume_attempt", "ts": 0.0,
                           "attempt": 1, "surprise": 1})    # unknown field


def test_validate_v4_serve_segment_fields():
    """The serve scheduler's per-bin attribution (``bin``/``inflight``
    on segment events) exists only from schema v4 — field-gated exactly
    like the v3 fleet fields, so a v3 consumer never sees them."""
    seg = {"v": 4, "event": "segment", "ts": 0.0, "wall_s": 0.1,
           "n_states": 10, "level": 1, "n_transitions": 20,
           "dedup_hit_rate": 0.5, "since_resume": False,
           "states_per_sec": 100.0, "inc_states_per_sec": 100.0,
           "bin": "bin0", "inflight": 2}
    assert validate_event(seg) == []
    errs = validate_event({**seg, "v": 3})   # v4-only fields, v3 line
    assert errs and all("requires schema version >= 4" in e
                        for e in errs)
    assert validate_event({**seg, "bin": 0})         # type drift
    assert validate_event({**seg, "inflight": 1.5})  # type drift


def test_validate_v5_hostdedup_segment_field():
    """The ddd background host-dedup attribution (``flush_backlog`` on
    segment events) exists only from schema v5 — field-gated exactly
    like the v3/v4 fields, so a v4 consumer never sees it."""
    seg = {"v": 5, "event": "segment", "ts": 0.0, "wall_s": 0.1,
           "n_states": 10, "level": 1, "n_transitions": 20,
           "dedup_hit_rate": 0.5, "since_resume": False,
           "states_per_sec": 100.0, "inc_states_per_sec": 100.0,
           "flush_backlog": 1}
    assert validate_event(seg) == []
    errs = validate_event({**seg, "v": 4})   # v5-only field, v4 line
    assert errs and all("requires schema version >= 5" in e
                        for e in errs)
    assert validate_event({**seg, "flush_backlog": 0.5})  # type drift
    assert validate_event({**seg, "flush_backlog": True})  # bool ≠ int


def test_validate_v7_pool_supervision_events():
    """The serve worker-pool lifecycle (worker_spawn / worker_lost /
    job_retry / quarantine) exists only from schema v7 — event-type
    gated exactly like the v2 campaign-supervisor types, so a v6
    consumer never sees them."""
    spawn = {"v": 7, "event": "worker_spawn", "ts": 0.0, "worker": "w0",
             "pid": 1234}
    assert validate_event(spawn) == []
    assert validate_event({**spawn, "jobs": ["a", "b"], "bins": 1,
                           "chunk": 256, "respawn": True,
                           "attempt": 2}) == []
    errs = validate_event({**spawn, "v": 6})  # v7-only type on a v6 line
    assert errs and all("requires schema version >= 7" in e for e in errs)
    assert validate_event({**spawn, "chunk": "256"})      # type drift
    assert validate_event({"v": 7, "event": "worker_spawn", "ts": 0.0,
                           "worker": "w0"})               # missing pid

    lost = {"v": 7, "event": "worker_lost", "ts": 0.0, "worker": "w0",
            "kind": "killed"}
    assert validate_event(lost) == []
    assert validate_event({**lost, "pid": 9, "exit_code": -9,
                           "jobs": ["a"], "detail": "signal 9"}) == []
    assert validate_event({**lost, "v": 1})
    assert validate_event({"v": 7, "event": "worker_lost", "ts": 0.0,
                           "worker": "w0"})               # missing kind

    retry = {"v": 7, "event": "job_retry", "ts": 0.0, "job_id": "a",
             "attempt": 1}
    assert validate_event(retry) == []
    assert validate_event({**retry, "worker": "w1", "backoff_s": 0.7,
                           "reason": "killed"}) == []
    assert validate_event({**retry, "attempt": True})     # bool ≠ int

    quar = {"v": 7, "event": "quarantine", "ts": 0.0, "job_id": "a",
            "reason": "poison-job"}
    assert validate_event(quar) == []
    assert validate_event({**quar, "deaths": 3, "worker": "w2",
                           "detail": "killed its worker 3x"}) == []
    assert validate_event({**quar, "v": 6})
    assert validate_event({**quar, "surprise": 1})        # unknown field


def test_validate_v8_span_events():
    """Trace spans (obs/trace.py) exist only from schema v8 — event-type
    gated like the v7 pool lifecycle; the ``run_start`` clock anchor and
    host context are field-gated like the v3..v6 additions, so a v7
    consumer never sees any of it."""
    span = {"v": 8, "event": "span", "ts": 0.0, "name": "expand",
            "span_id": 3, "t0": 12.25, "dur": 0.125,
            "thread": "MainThread"}
    assert validate_event(span) == []
    assert validate_event({**span, "parent_id": 1,
                           "args": {"rows": 256}}) == []
    errs = validate_event({**span, "v": 7})  # v8-only type on a v7 line
    assert errs and all("requires schema version >= 8" in e for e in errs)
    assert validate_event({**span, "span_id": "3"})       # type drift
    assert validate_event({**span, "span_id": True})      # bool ≠ int
    assert validate_event({**span, "dur": "fast"})        # type drift
    assert validate_event({**span, "surprise": 1})        # unknown field
    assert validate_event({"v": 8, "event": "span", "ts": 0.0,
                           "name": "expand", "span_id": 3,
                           "t0": 1.0, "dur": 0.1})        # missing thread

    start = {"v": 8, "event": "run_start", "ts": 0.0, "engine": "ddd",
             "universe": {}, "spec": "election", "invariants": [],
             "resumed": False,
             "anchor": {"wall": 1.0, "mono": 2.0, "err_s": 1e-6},
             "host": {"nproc": 4}}
    assert validate_event(start) == []
    errs = validate_event({**start, "v": 7})  # v8-only fields, v7 line
    assert errs and all("requires schema version >= 8" in e for e in errs)
    assert validate_event({**start, "anchor": [1.0]})     # type drift


def test_validate_v9_devdedup_segment_fields():
    """The ddd device-dedup attribution (``export_rows`` /
    ``dev_dedup_hits`` on segment events) exists only from schema v9 —
    field-gated exactly like the v5 ``flush_backlog``, so a v8 consumer
    never sees it."""
    seg = {"v": 9, "event": "segment", "ts": 0.0, "wall_s": 0.1,
           "n_states": 10, "level": 1, "n_transitions": 20,
           "dedup_hit_rate": 0.5, "since_resume": False,
           "states_per_sec": 100.0, "inc_states_per_sec": 100.0,
           "export_rows": 8, "dev_dedup_hits": 2}
    assert validate_event(seg) == []
    # the off arm of an A/B emits export_rows without dev_dedup_hits
    off = dict(seg)
    del off["dev_dedup_hits"]
    assert validate_event(off) == []
    errs = validate_event({**seg, "v": 8})   # v9-only fields, v8 line
    assert errs and all("requires schema version >= 9" in e
                        for e in errs)
    assert validate_event({**seg, "export_rows": 0.5})     # type drift
    assert validate_event({**seg, "dev_dedup_hits": True})  # bool ≠ int


def test_validate_v10_metrics_snapshot():
    """The metrics layer's periodic exposition dump (one flat dict of
    series, written by obs/openmetrics.py's snapshot loop) exists only
    from schema v10 — event-type gated exactly like the v7/v8 types, so
    a v9 consumer never sees it."""
    snap = {"v": 10, "event": "metrics_snapshot", "ts": 0.0,
            "metrics": {"raft_tla_queue_depth": 2.0,
                        'raft_tla_latency_seconds{tenant="a",'
                        'quantile="0.99"}': 1.5}}
    assert validate_event(snap) == []
    assert validate_event({**snap, "port": 9108, "root": "/tmp/x"}) == []
    errs = validate_event({**snap, "v": 9})  # v10-only type on a v9 line
    assert errs and all("requires schema version >= 10" in e for e in errs)
    assert validate_event({**snap, "metrics": [1, 2]})    # type drift
    assert validate_event({**snap, "port": "9108"})       # type drift
    assert validate_event({**snap, "surprise": 1})        # unknown field
    assert validate_event({"v": 10, "event": "metrics_snapshot",
                           "ts": 0.0})                    # missing metrics


def test_monitor_pool_attribution_rows(tmp_path):
    """A pool.events supervision log (no segments at all) renders a
    pool-lifecycle heartbeat; a tenant log with pool events alongside
    segments gets the pool row appended."""
    from raft_tla_tpu.obs.monitor import heartbeat, load_stream, summarize

    p = str(tmp_path / "pool.events")
    append_event(p, "worker_spawn", worker="w0", pid=11,
                 jobs=["a", "b"], chunk=256)
    append_event(p, "worker_lost", worker="w0", kind="killed",
                 exit_code=-9, jobs=["b"])
    append_event(p, "job_retry", job_id="b", attempt=1, worker="w1",
                 backoff_s=0.4)
    append_event(p, "worker_spawn", worker="w1", pid=12, respawn=True)
    append_event(p, "quarantine", job_id="b", reason="poison-job",
                 deaths=3)
    s = summarize(load_stream(p))
    assert s["pool_only"] and s["pool"]["spawns"] == 2
    assert s["pool"]["losses"] == 1 and s["pool"]["retries"] == 1
    assert s["pool"]["last_loss_kind"] == "killed"
    assert s["pool"]["quarantined"] == ["b"]
    line = heartbeat(s)
    assert "2 spawn(s)" in line and "1 lost" in line
    assert "last loss: killed" in line and "QUARANTINED b" in line
    # an empty/eventless stream still reports "no segments yet"
    q = str(tmp_path / "empty.events")
    open(q, "w").close()
    assert heartbeat(summarize(load_stream(q))) == "obs: no segments yet"


def test_append_event_validates(tmp_path):
    p = str(tmp_path / "x.events")
    append_event(p, "stop_requested", reason="clean-stop", source="test")
    with pytest.raises(ValueError):
        append_event(p, "stop_requested", source="test")  # missing reason
    with pytest.raises(ValueError):
        append_event(p, "no_such_event", reason="x")
    evs = _read_log(p)
    assert len(evs) == 1 and validate_event(evs[0]) == []


def test_tracker_incremental_rate_immune_to_resume():
    """Satellite (a): cumulative states/s inflated after a resume
    (prior-process states over this-process wall); the incremental rate
    and the since_resume tag carry the honest signal."""
    tr = ProgressTracker(t0=time.monotonic() - 100.0,  # 100s in already
                         n0=1, resumed=True)
    tr.anchor(1_000_000)                  # checkpoint-restored count
    rec = tr.record(n_states=1_000_050, level=7, n_transitions=2_000_000)
    assert rec.since_resume is False      # cumulative fields span processes
    assert rec.states_per_sec > 5_000     # the inflated wart, tagged...
    assert rec.inc_states_per_sec < 10    # ...while inc stays honest
    # rollback-monotone anchor: an inclusive count below the running max
    # never yields a negative rate
    rec2 = tr.record(n_states=999_000, level=7, n_transitions=2_000_001,
                     n_incl=999_500)
    assert rec2.inc_states_per_sec == 0.0


def test_tracker_unknown_baseline_first_record_anchors():
    tr = ProgressTracker(t0=time.monotonic() - 10.0,
                         n0=None)             # table-engine resume
    rec = tr.record(n_states=500, level=3, n_transitions=900)
    assert rec.inc_states_per_sec == 0.0      # anchor, not a fabricated rate
    rec2 = tr.record(n_states=700, level=3, n_transitions=1300)
    assert rec2.inc_states_per_sec > 0.0


def test_event_log_round_trips(tmp_path):
    p = str(tmp_path / "log.events")
    log = EventLog(p)
    for k in range(100):
        log.emit("level_end", level=k, n_states=k * 10)
    log.close()
    evs = _read_log(p)
    assert [e["level"] for e in evs] == list(range(100))
    assert all(validate_event(e) == [] for e in evs)
    log.close()                                   # idempotent


def test_concurrent_event_log_writers_do_not_corrupt(tmp_path):
    """Serving-mode write pattern: two jobs' EventLogs appending to
    separate logs concurrently, plus an external one-shot emitter
    (``python -m raft_tla_tpu.obs emit``) interleaving whole lines into
    one of them mid-run.  Every line must still parse and validate —
    append-mode line-at-a-time writes never interleave partial lines."""
    import threading

    pa = str(tmp_path / "a.events")
    pb = str(tmp_path / "b.events")
    la, lb = EventLog(pa), EventLog(pb)
    n_each = 400

    def pump(log, tag):
        for k in range(n_each):
            log.emit("level_end", level=k, n_states=k * 10 + tag)

    ta = threading.Thread(target=pump, args=(la, 1))
    tb = threading.Thread(target=pump, args=(lb, 2))
    ta.start(), tb.start()
    # External one-shot emitters racing the live background writer on
    # log A (the campaign_stop.sh pattern, now also the service's
    # rejected-tenant path).
    for k in range(3):
        r = subprocess.run(
            [sys.executable, "-m", "raft_tla_tpu.obs", "emit", pa,
             "stop_requested", "--reason", f"external-{k}",
             "--source", "test"],
            capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
    ta.join(), tb.join()
    la.close(), lb.close()

    evs_a, evs_b = _read_log(pa), _read_log(pb)     # json.loads = no torn lines
    for evs in (evs_a, evs_b):
        assert all(validate_event(e) == [] for e in evs)
    # nothing lost, nothing duplicated, no cross-log bleed
    assert len(evs_a) == n_each + 3
    assert len(evs_b) == n_each
    lv_a = [e["level"] for e in evs_a if e["event"] == "level_end"]
    assert sorted(lv_a) == list(range(n_each))
    assert [e["n_states"] % 10 for e in evs_a
            if e["event"] == "level_end"] == [1] * n_each
    assert [e["n_states"] % 10 for e in evs_b] == [2] * n_each
    exts = [e for e in evs_a if e["event"] == "stop_requested"]
    assert sorted(e["reason"] for e in exts) == [
        f"external-{k}" for k in range(3)]


def test_phase_timers_disabled_is_inert_enabled_accumulates():
    off = PhaseTimers(enabled=False)
    with off.phase("expand") as ph:
        assert ph.sync(123) == 123                # pass-through
    assert off.snapshot() == {}
    on = PhaseTimers(enabled=True)
    with on.phase("expand") as ph:
        ph.sync((1, 2))
    with on.phase("expand"):
        pass
    snap = on.snapshot()
    assert set(snap) == {"expand"} and snap["expand"] >= 0.0
    assert on.snapshot() == {}                    # snapshot(reset=True)


# --------------------------------------------------------------------------
# monitor


def test_load_stream_lifts_legacy_and_rebases_walls():
    stream = monitor.load_stream("runs/elect5ddd_r5a.stats")
    assert stream["legacy"] and not stream["invalid"]
    segs = stream["segments"]
    assert segs
    cum = [s["cum_wall_s"] for s in segs]
    assert cum == sorted(cum)                     # one monotone clock
    ns = [s["n_states"] for s in segs]
    assert ns == sorted(ns)                       # rollbacks dropped
    hb = monitor.heartbeat(monitor.summarize(stream))
    assert hb.startswith("L") and "inc" in hb


def test_monitor_reads_v1_log_end_to_end(tmp_path):
    p = str(tmp_path / "run.events")
    _run_engine("ddd", p)
    stream = monitor.load_stream(p)
    assert not stream["legacy"] and not stream["invalid"]
    s = monitor.summarize(stream)
    assert s["status"] == "ok" and s["n_states"] == N_TOY
    assert s["level_sizes"]                       # from level_end events
    assert sum(s["level_sizes"].values()) <= N_TOY
    assert "ok" in monitor.heartbeat(s)
    assert monitor.main([p]) == 0                 # CLI one-shot


def test_obs_emit_cli_interleaves_with_log(tmp_path):
    p = str(tmp_path / "x.events")
    append_event(p, "checkpoint", path="ck.npz", n_states=5)
    r = subprocess.run(
        [sys.executable, "-m", "raft_tla_tpu.obs", "emit", p,
         "stop_requested", "--reason", "clean-stop",
         "--source", "campaign_stop.sh", "--pid", "42"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    evs = _read_log(p)
    assert [e["event"] for e in evs] == ["checkpoint", "stop_requested"]
    assert evs[-1]["pid"] == 42
    bad = subprocess.run(
        [sys.executable, "-m", "raft_tla_tpu.obs", "emit", p, "bogus"],
        capture_output=True, text=True)
    assert bad.returncode != 0 and len(_read_log(p)) == 2


# -- monitor end-state attribution (campaign supervision satellite) ---------
# One test per status path in monitor.summarize: the supervisor's
# health verdicts and the operator's heartbeat must agree on what a
# quiet log means.


def _seg(path, ts, n_states, level=1):
    append_event(path, "segment", ts=ts, wall_s=ts, n_states=n_states,
                 level=level, n_transitions=2 * n_states,
                 dedup_hit_rate=0.5, states_per_sec=10.0,
                 inc_states_per_sec=10.0, since_resume=True)


def _summary(path, now, stale_after_s=None):
    return monitor.summarize(monitor.load_stream(path), now=now,
                             stale_after_s=stale_after_s)


def test_monitor_attribution_run_end_wins(tmp_path):
    p = str(tmp_path / "e")
    _seg(p, 10.0, 100)
    append_event(p, "run_end", ts=11.0, n_states=3014,
                 n_transitions=5274, complete=True, outcome="ok")
    # a finished run is never "presumed-crashed", however old the log
    s = _summary(p, now=11.0 + 9999.0)
    assert s["status"] == "ok"


def test_monitor_attribution_presumed_crashed(tmp_path):
    p = str(tmp_path / "e")
    # 5s cadence -> auto threshold 10x = 50s (clamped to [30s, 1h])
    for t in range(0, 30, 5):
        _seg(p, float(t), 10 * (t + 1))
    assert _summary(p, now=25.0 + 49.0)["status"] == "live"
    s = _summary(p, now=25.0 + 51.0)
    assert s["stale"] is True
    assert s["status"].startswith("presumed-crashed (last event 51s ago")
    assert "cadence ~5s" in s["status"]


def test_monitor_attribution_explicit_threshold_overrides(tmp_path):
    p = str(tmp_path / "e")
    for t in range(0, 30, 5):
        _seg(p, float(t), 10 * (t + 1))
    # 49s of silence: live under the cadence rule, crashed at 10s policy
    assert _summary(p, now=74.0)["status"] == "live"
    s = _summary(p, now=74.0, stale_after_s=10.0)
    assert s["status"].startswith("presumed-crashed")


def test_monitor_attribution_stop_requested_live(tmp_path):
    p = str(tmp_path / "e")
    _seg(p, 10.0, 100)
    append_event(p, "stop_requested", ts=11.0, reason="preempt",
                 source="supervisor")
    s = _summary(p, now=12.0)
    assert s["status"] == "live (stop requested (preempt))"


def test_monitor_attribution_violation_live(tmp_path):
    p = str(tmp_path / "e")
    _seg(p, 10.0, 100)
    append_event(p, "violation", ts=11.0, invariant="NoTwoLeaders")
    s = _summary(p, now=12.0)
    assert s["status"] == "live (VIOLATION NoTwoLeaders)"


def test_monitor_attribution_timestampless_is_unjudged(tmp_path):
    p = str(tmp_path / "e")
    with open(p, "w") as fh:        # legacy .stats line: no ts anywhere
        fh.write(json.dumps({"n_states": 100, "wall_s": 1.0,
                             "level": 1}) + "\n")
    s = _summary(p, now=9999.0)
    assert s["stale"] is None
    assert s["status"] == "live?"   # no timestamps: no crash verdict
