"""Pass 4 (threadlint) — planted-race suite and clean-tree assertions.

Mirrors test_lint_mutations.py's discipline for the race detector: every
planted bug class must be caught (0 false negatives), and the matching
disciplined shape must NOT be flagged (0 false positives), so the pass
can gate the tree without crying wolf.  The centerpiece is the PR 17
phases.py off-owner race: the exact pre-fix shape (a worker-thread
``__exit__`` mutating the timers' dict through a local alias, no lock)
must produce a finding, and the shipped post-fix shape must not.
"""

from __future__ import annotations

import pytest

from raft_tla_tpu.analysis import threadlint
from raft_tla_tpu.analysis.report import ERROR, THREAD

pytestmark = pytest.mark.smoke


def _codes(findings):
    return sorted(f.code for f in findings)


def _lint(src):
    return threadlint.lint_source(src, "planted.py")


# a minimal spawning worker used by several mutations; SAFE as written:
# everything shared is either lock-guarded or published before spawn
SAFE_WORKER = '''
import threading

class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = []
        self._done = 0
        self._closed = False
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            with self._lock:
                if self._closed:
                    return
                self._done += 1

    def close(self):
        with self._lock:
            self._closed = True
'''


def test_clean_worker_no_findings():
    assert _lint(SAFE_WORKER) == []


# -- bug class 1: dropped lock ------------------------------------------------

def test_dropped_lock_is_caught():
    mutated = SAFE_WORKER.replace(
        "        with self._lock:\n"
        "            self._closed = True",
        "        self._closed = True")
    findings = _lint(mutated)
    assert _codes(findings) == ["unguarded-shared-mutation"]
    f = findings[0]
    assert f.pass_ == THREAD and f.severity == ERROR
    assert "Worker._closed" in f.message
    # both access sites cited: the mutation location + the other side
    assert f.line is not None and "planted.py:" in f.message


def test_dropped_lock_worker_side_is_caught():
    mutated = SAFE_WORKER.replace(
        "            with self._lock:\n"
        "                if self._closed:\n"
        "                    return\n"
        "                self._done += 1",
        "            if self._closed:\n"
        "                return\n"
        "            self._done += 1")
    findings = _lint(mutated)
    assert "unguarded-shared-mutation" in _codes(findings)


# -- bug class 2: post-spawn publish -----------------------------------------

def test_post_spawn_publish_is_caught():
    src = SAFE_WORKER.replace(
        "        self._closed = False\n"
        "        self._thread = threading.Thread",
        "        self._thread = threading.Thread")
    src = src.replace(
        "        self._thread.start()",
        "        self._thread.start()\n"
        "        self._closed = False")
    findings = _lint(src)
    assert _codes(findings) == ["post-spawn-publish"]
    assert "spawn" in findings[0].message


def test_publish_before_spawn_is_clean():
    # ctor writes above the Thread(...) line are the main thread's half
    # of the handshake — never flagged
    assert _lint(SAFE_WORKER) == []


# -- bug class 3: the PR 17 off-owner alias race ------------------------------

# the exact pre-fix obs/phases.py shape: _Phase.__exit__ runs on
# whatever thread executes the `with timers.phase(...)` block and
# mutates the owner's dict through a local alias, with no lock anywhere
PRE_FIX_PHASES = '''
import threading, time

class PhaseTimers:
    def __init__(self, enabled=True):
        self.enabled = enabled
        self._acc = {}
        self._owner = threading.get_ident()

    def phase(self, name):
        return _Phase(self, name)

    def snapshot(self):
        out = dict(self._acc)
        self._acc = {}
        return out

class _Phase:
    def __init__(self, timers, name):
        self._timers = timers
        self._name = name

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        acc = self._timers._acc
        acc[self._name] = acc.get(self._name, 0.0) + (
            time.monotonic() - self._t0)
        return False

class FlushWorker:
    def __init__(self):
        self._phases = PhaseTimers()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            with self._phases.phase("dedup"):
                pass
'''


def test_pr17_off_owner_race_is_caught():
    findings = _lint(PRE_FIX_PHASES)
    assert findings, "the PR 17 pre-fix shape must be a finding"
    assert all(f.code == "unguarded-shared-mutation" for f in findings)
    assert any("PhaseTimers._acc" in f.message for f in findings)
    # the alias-mutation line inside __exit__ is one of the cited sites
    exit_mutation = [f for f in findings if f.line in (29, 30)]
    assert exit_mutation, [f.line for f in findings]


def test_pr17_post_fix_shape_is_clean():
    # the shipped fix: PhaseTimers grows a lock, __exit__ and snapshot
    # both take it
    fixed = PRE_FIX_PHASES.replace(
        "        self._acc = {}\n"
        "        self._owner",
        "        self._acc = {}\n"
        "        self._lock = threading.Lock()\n"
        "        self._owner")
    fixed = fixed.replace(
        "        acc = self._timers._acc\n"
        "        acc[self._name] = acc.get(self._name, 0.0) + (\n"
        "            time.monotonic() - self._t0)",
        "        timers = self._timers\n"
        "        with timers._lock:\n"
        "            acc = timers._acc\n"
        "            acc[self._name] = acc.get(self._name, 0.0) + (\n"
        "                time.monotonic() - self._t0)")
    fixed = fixed.replace(
        "        out = dict(self._acc)\n"
        "        self._acc = {}\n"
        "        return out",
        "        with self._lock:\n"
        "            out = dict(self._acc)\n"
        "            self._acc = {}\n"
        "        return out")
    assert _lint(fixed) == []


def test_real_phases_module_is_clean():
    import os
    import raft_tla_tpu.obs.phases as phases_mod
    path = phases_mod.__file__
    with open(path) as fh:
        src = fh.read()
    # self-contained module lint: the shipped fix must satisfy the pass
    assert threadlint.lint_source(src, os.path.basename(path)) == []


# -- bug class 4: handoff rebound --------------------------------------------

def test_handoff_rebound_is_caught():
    src = '''
import threading, queue

class Pump:
    def __init__(self):
        self._q = queue.Queue()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            self._q.get()

    def reset(self):
        self._q = queue.Queue()
'''
    findings = _lint(src)
    assert _codes(findings) == ["handoff-rebound"]
    assert "Pump._q" in findings[0].message


def test_handoff_use_is_clean():
    src = '''
import threading, queue

class Pump:
    def __init__(self):
        self._q = queue.Queue()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            self._q.get()

    def put(self, item):
        self._q.put(item)
'''
    assert _lint(src) == []


# -- bug class 5: waiver present but reason missing ---------------------------

def test_waiver_without_reason_is_caught():
    mutated = SAFE_WORKER.replace(
        "        with self._lock:\n"
        "            self._closed = True",
        "        self._closed = True  # lint: thread-ok")
    findings = _lint(mutated)
    assert _codes(findings) == ["waiver-missing-reason"]


def test_waiver_with_reason_suppresses():
    mutated = SAFE_WORKER.replace(
        "        with self._lock:\n"
        "            self._closed = True",
        "        self._closed = True  # lint: thread-ok benign flag, "
        "worst case one extra loop pass")
    findings = _lint(mutated)
    assert findings == []


def test_used_waiver_lines_are_exported():
    mutated = SAFE_WORKER.replace(
        "        with self._lock:\n"
        "            self._closed = True",
        "        self._closed = True  # lint: thread-ok benign")
    res = threadlint.analyze({"planted.py": mutated})
    assert res.findings == []
    assert any(line for (path, line) in res.used_waivers
               if path == "planted.py")


# -- bug class 6: unguarded module-global from a thread -----------------------

def test_global_mutation_race_is_caught():
    src = '''
import threading

COUNTS = {}

def worker():
    COUNTS["n"] = COUNTS.get("n", 0) + 1

def start():
    t = threading.Thread(target=worker, daemon=True)
    t.start()
    COUNTS["m"] = 0
'''
    findings = _lint(src)
    assert "unguarded-shared-mutation" in _codes(findings)
    assert any("COUNTS" in f.message for f in findings)


def test_global_behind_lock_is_clean():
    src = '''
import threading

_LOCK = threading.Lock()
COUNTS = {}

def worker():
    with _LOCK:
        COUNTS["n"] = COUNTS.get("n", 0) + 1

def start():
    t = threading.Thread(target=worker, daemon=True)
    t.start()
    with _LOCK:
        COUNTS["m"] = 0
'''
    assert _lint(src) == []


# -- bug class 7: executor submit target races --------------------------------

def test_executor_submit_race_is_caught():
    src = '''
import threading
from concurrent.futures import ThreadPoolExecutor

class Batcher:
    def __init__(self):
        self._pool = ThreadPoolExecutor(2)
        self._results = []

    def _job(self, x):
        self._results.append(x)

    def submit(self, x):
        self._pool.submit(self._job, x)
        n = len(self._results)
        self._results = []
        return n
'''
    findings = _lint(src)
    assert "unguarded-shared-mutation" in _codes(findings)
    assert any("Batcher._results" in f.message for f in findings)


# -- FP guards: the shapes the real tree relies on ---------------------------

def test_caller_held_lock_propagates():
    # the prefetch.py `_reraise_locked` convention: the helper's every
    # call site holds the cv — the helper's own mutation is guarded
    src = '''
import threading

class Prefetcher:
    def __init__(self):
        self._cv = threading.Condition()
        self._exc = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        with self._cv:
            self._exc = self._exc or ValueError()

    def _reraise_locked(self):
        exc, self._exc = self._exc, None
        if exc is not None:
            raise exc

    def take(self):
        with self._cv:
            self._reraise_locked()
'''
    assert _lint(src) == []


def test_thread_confined_handle_class_is_clean():
    # a per-call handle class with no lock/handoff/spawn of its own and
    # not stored in any spawning class's field stays out of scope
    src = '''
class Span:
    def __init__(self, name):
        self.name = name
        self.dur = 0.0

    def close(self, dur):
        self.dur = dur

def run_all(items):
    spans = [Span(i) for i in items]
    for s in spans:
        s.close(1.0)
    return spans
'''
    assert _lint(src) == []


def test_nested_def_spawn_target_is_modeled():
    # the serve/chaos.py shape: a nested def passed to Thread(target=...)
    src = '''
import threading

class Stalker:
    def __init__(self):
        self.kills = []

    def arm(self):
        def run():
            self.kills.append(1)
        t = threading.Thread(target=run, daemon=True)
        t.start()
        self.kills.append(0)
'''
    findings = _lint(src)
    assert "unguarded-shared-mutation" in _codes(findings)


# -- the whole tree -----------------------------------------------------------

def test_threadlint_repo_is_clean():
    """Every real finding is fixed or waived — the pass gates the tree."""
    assert threadlint.lint_paths() == []


def test_campaign_chaos_fix_regression():
    """PR 19's real finding: ChaosMonkey._stalk appends to ``fired``
    from a stalker thread; the fix guards it with the monkey's lock.
    Reverting the guard must re-surface the finding."""
    import os
    import raft_tla_tpu.campaign.chaos as chaos_mod
    with open(chaos_mod.__file__) as fh:
        src = fh.read()
    guarded = ("                with self._lock:\n"
               "                    self.fired.append((attempt, kind, "
               "seen))")
    assert guarded in src, "the shipped fix changed shape; update test"
    assert threadlint.lint_source(src, "campaign/chaos.py") == []
    reverted = src.replace(
        guarded,
        "                self.fired.append((attempt, kind, seen))")
    findings = threadlint.lint_source(reverted, "campaign/chaos.py")
    assert any(f.code == "unguarded-shared-mutation"
               and "fired" in f.message for f in findings)


def test_chaosmonkey_fired_is_lock_guarded_at_runtime():
    """Behavioral half of the regression test: concurrent recorders
    through the shipped lock lose no entries."""
    import threading as th
    from raft_tla_tpu.campaign.chaos import ChaosMonkey
    monkey = ChaosMonkey()
    def record(a):
        for i in range(100):
            with monkey._lock:
                monkey.fired.append((a, "kill", i))
    threads = [th.Thread(target=record, args=(a,)) for a in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(monkey.fired) == 400
