"""Interpreter unit tests — one per guard/effect branch (SURVEY §4.1).

Covers the corner semantics called out in SURVEY §2.5/§2.6: self-vote via the
network, UpdateTerm leaving the message in flight, candidate step-down keeping
the message, truncate-one-off-the-tail, commitIndex decrease on stale
requests, and the nextIndex floor.
"""

import numpy as np

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.models import interp, refbfs, spec as S
from raft_tla_tpu.ops import msgbits as mb

B = Bounds(n_servers=3, n_values=2, max_term=3, max_log=2, max_msgs=4)
N = B.n_servers


def bag(*items):
    d = {}
    for m in items:
        d[m] = d.get(m, 0) + 1
    return tuple(sorted(d.items()))


def test_init_matches_spec():
    s = interp.init_state(B)
    assert s.term == (1, 1, 1)
    assert s.role == (S.FOLLOWER,) * 3
    assert s.nextIndex == ((1, 1, 1),) * 3
    assert s.msgs == ()


def test_timeout_no_self_vote():
    """Timeout (raft.tla:178-187): votedFor stays Nil; self-vote is by message."""
    s = interp.init_state(B)
    t = interp.timeout(s, 0)
    assert t.role[0] == S.CANDIDATE and t.term[0] == 2
    assert t.votedFor[0] == S.NIL
    # leader cannot time out
    lead = s._replace(role=(S.LEADER, 0, 0))
    assert interp.timeout(lead, 0) is None


def test_request_vote_self_allowed():
    """RequestVote quantifies over all pairs incl. i=j (raft.tla:456)."""
    s = interp.timeout(interp.init_state(B), 0)
    t = interp.request_vote(s, 0, 0)
    assert t is not None
    ((hi, _lo), cnt), = t.msgs
    assert mb.mtype(hi) == S.M_RVREQ and mb.src(hi) == 0 and mb.dst(hi) == 0
    assert cnt == 1
    # repeated send of identical message bumps multiplicity (WithMessage :106-110)
    t2 = interp.request_vote(t, 0, 0)
    assert t2.msgs[0][1] == 2


def test_update_term_keeps_message():
    """UpdateTerm (raft.tla:406-412): message NOT consumed, reprocessed later."""
    s = interp.init_state(B)
    m = mb.rv_request(3, 0, 0, 1, 0)
    s = s._replace(msgs=bag(m))
    t = interp.receive(s, 0)
    assert t.term[0] == 3 and t.role[0] == S.FOLLOWER
    assert t.msgs == s.msgs
    # Re-receive now dispatches the RV request handler (grant, term equal).
    t2 = interp.receive(t, 0)
    assert t2.votedFor[0] == 2  # voted for server 1 (id+1 encoding)
    (mm, cnt), = t2.msgs
    assert mb.mtype(mm[0]) == S.M_RVRESP and mb.fa(mm[0]) == 1


def test_vote_denied_when_log_stale():
    """logOk (raft.tla:285-287): deny when candidate's log is behind."""
    s = interp.init_state(B)
    s = s._replace(log=(((1, 1),), (), ()))  # server 0 has one entry
    m = mb.rv_request(1, 0, 0, 1, 0)         # candidate 1, empty log, term 1
    s = s._replace(msgs=bag(m))
    t = interp.receive(s, 0)
    (mm, _), = t.msgs
    assert mb.mtype(mm[0]) == S.M_RVRESP and mb.fa(mm[0]) == 0  # not granted
    assert t.votedFor[0] == S.NIL


def test_vote_response_tally_and_stale_drop():
    s = interp.timeout(interp.init_state(B), 0)  # candidate, term 2
    granted = mb.rv_response(2, 1, 1, 0)
    stale = mb.rv_response(1, 1, 2, 0)
    s = s._replace(msgs=bag(granted, stale))
    slot_granted = [k for k, (m, _) in enumerate(s.msgs) if m == granted][0]
    t = interp.receive(s, slot_granted)
    assert t.vResp[0] & (1 << 1) and t.vGrant[0] & (1 << 1)
    slot_stale = [k for k, (m, _) in enumerate(t.msgs) if m == stale][0]
    u = interp.receive(t, slot_stale)  # DropStaleResponse (raft.tla:415-418)
    assert all(m != stale for m, _ in u.msgs)
    assert u.vResp == t.vResp and u.vGrant == t.vGrant


def test_become_leader_quorum():
    s = interp.timeout(interp.init_state(B), 0)
    s = s._replace(vGrant=(0b011, 0, 0))  # votes from 0 and 1: quorum of 3
    t = interp.become_leader(s, 0, N)
    assert t.role[0] == S.LEADER
    assert t.nextIndex[0] == (1, 1, 1)  # Len(log)+1 (raft.tla:233-234)
    s2 = s._replace(vGrant=(0b001, 0, 0))
    assert interp.become_leader(s2, 0, N) is None


def test_candidate_step_down_keeps_message():
    """HandleAppendEntriesRequest branch b (raft.tla:346-350)."""
    s = interp.init_state(B)
    s = s._replace(role=(S.CANDIDATE, S.LEADER, S.FOLLOWER), term=(2, 2, 1))
    m = mb.ae_request(2, 0, 0, 0, 0, 0, 0, 1, 0)  # heartbeat leader 1 -> 0
    s = s._replace(msgs=bag(m))
    t = interp.receive(s, 0)
    assert t.role[0] == S.FOLLOWER
    assert t.msgs == s.msgs  # kept for reprocessing


def test_append_then_done_then_commit_decrease():
    """Accept branches (raft.tla:356-388) incl. commitIndex decrease."""
    s = interp.init_state(B)
    s = s._replace(role=(S.FOLLOWER, S.LEADER, S.FOLLOWER), term=(2, 2, 1),
                   log=((), ((2, 1),), ()))
    m = mb.ae_request(2, 0, 0, 1, 2, 1, 0, 1, 0)
    s = s._replace(msgs=bag(m))
    t = interp.receive(s, 0)          # no conflict: append (raft.tla:383-388)
    assert t.log[0] == ((2, 1),)
    assert t.msgs == s.msgs           # message kept
    u = interp.receive(t, 0)          # already done: reply (raft.tla:356-374)
    assert u.commitIndex[0] == 0
    (mm, _), = u.msgs
    assert mb.mtype(mm[0]) == S.M_AERESP and mb.fa(mm[0]) == 1
    assert mb.fb(mm[0]) == 1          # mmatchIndex = prevLogIndex + Len(entries)
    # commitIndex decrease: set commit to 1, then receive stale dup with mcommit 0
    v = u._replace(commitIndex=(1, 0, 0), msgs=bag(m))
    w = interp.receive(v, 0)
    assert w.commitIndex[0] == 0      # decreased (raft.tla:361-365)


def test_conflict_truncates_tail():
    """Conflict removes ONE entry off the tail, not at index (raft.tla:375-382)."""
    s = interp.init_state(B)
    s = s._replace(role=(S.FOLLOWER, S.LEADER, S.FOLLOWER), term=(3, 3, 1),
                   log=(((1, 1), (1, 2)), ((3, 2),), ()))
    m = mb.ae_request(3, 0, 0, 1, 3, 2, 0, 1, 0)  # entry term 3 conflicts @1
    s = s._replace(msgs=bag(m))
    t = interp.receive(s, 0)
    assert t.log[0] == ((1, 1),)      # tail entry removed
    assert t.msgs == s.msgs           # kept: multi-step convergence loop


def test_reject_stale_term():
    s = interp.init_state(B)
    s = s._replace(term=(3, 1, 1))
    m = mb.ae_request(1, 0, 0, 0, 0, 0, 0, 1, 0)
    s = s._replace(msgs=bag(m))
    t = interp.receive(s, 0)
    (mm, _), = t.msgs
    assert mb.mtype(mm[0]) == S.M_AERESP
    assert mb.fa(mm[0]) == 0 and mb.mterm(mm[0]) == 3


def test_ae_response_next_index_floor():
    """HandleAppendEntriesResponse failure path: Max(nextIndex-1, 1) (:399-400)."""
    s = interp.init_state(B)
    s = s._replace(role=(S.LEADER, 0, 0), term=(2, 2, 1))
    fail = mb.ae_response(2, 0, 0, 1, 0)
    s = s._replace(msgs=bag(fail))
    t = interp.receive(s, 0)
    assert t.nextIndex[0][1] == 1     # floor holds at 1
    ok = mb.ae_response(2, 1, 2, 1, 0)
    u = t._replace(msgs=bag(ok))
    v = interp.receive(u, 0)
    assert v.nextIndex[0][1] == 3 and v.matchIndex[0][1] == 2


def test_advance_commit_current_term_restriction():
    """AdvanceCommitIndex (raft.tla:268-270): only current-term entries commit."""
    s = interp.init_state(B)
    s = s._replace(role=(S.LEADER, 0, 0), term=(2, 1, 1),
                   log=(((1, 1),), (), ()),
                   matchIndex=((0, 1, 1), (0,) * 3, (0,) * 3))
    t = interp.advance_commit_index(s, 0, N)
    assert t.commitIndex[0] == 0      # term-1 entry, leader at term 2
    s2 = s._replace(log=(((2, 1),), (), ()))
    t2 = interp.advance_commit_index(s2, 0, N)
    assert t2.commitIndex[0] == 1


def test_restart_keeps_stable_storage():
    s = interp.init_state(B)
    s = s._replace(role=(S.LEADER, 0, 0), term=(3, 1, 1), votedFor=(1, 0, 0),
                   log=(((2, 1),), (), ()), commitIndex=(1, 0, 0),
                   vGrant=(0b111, 0, 0), nextIndex=((2, 2, 2),) + ((1,) * 3,) * 2)
    t = interp.restart(s, 0, N)
    assert t.role[0] == S.FOLLOWER
    assert t.term[0] == 3 and t.votedFor[0] == 1 and t.log[0] == ((2, 1),)
    assert t.commitIndex[0] == 0 and t.vGrant[0] == 0
    assert t.nextIndex[0] == (1, 1, 1) and t.matchIndex[0] == (0, 0, 0)


def test_duplicate_and_drop():
    s = interp.init_state(B)
    m = mb.rv_request(1, 0, 0, 0, 1)
    s = s._replace(msgs=bag(m))
    d = interp.duplicate_message(s, 0)
    assert d.msgs[0][1] == 2
    e = interp.drop_message(d, 0)
    assert e.msgs == s.msgs
    f = interp.drop_message(e, 0)
    assert f.msgs == ()
    assert interp.drop_message(f, 0) is None  # empty bag: no slot


def test_bfs_election_tiny():
    """Exhaustive election-only run, 2 servers: spot-check determinism."""
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=("NoTwoLeaders",))
    r1 = refbfs.check(cfg)
    r2 = refbfs.check(cfg)
    assert r1.violation is None
    assert r1.n_states == r2.n_states and r1.diameter == r2.diameter
    assert r1.n_states > 10


def test_bfs_naive_invariant_violated_with_trace():
    """The naive reading is falsified and yields a replayable trace (§0.1).

    A deposed leader keeps state = Leader until it observes the higher term
    (raft.tla:406-412), so two simultaneous leaders in different terms are
    reachable.  The violation region is ~18 steps deep, beyond the
    pure-Python oracle's reach, so exploration starts from a crafted
    mid-election state: s1 leads term 2; s3 campaigns in term 3 with s2's
    vote still in flight.
    """
    bounds = Bounds(n_servers=3, n_values=1, max_term=3, max_log=0,
                    max_msgs=4, max_dup=1)
    cfg = CheckConfig(bounds=bounds, spec="election",
                      invariants=("NaiveNoTwoLeaders",))
    start = interp.init_state(bounds)._replace(
        role=(S.LEADER, S.FOLLOWER, S.CANDIDATE),
        term=(2, 3, 3),
        votedFor=(1, 3, 0),
        vGrant=(0b011, 0, 0b100),
        msgs=bag(mb.rv_response(3, 1, 1, 2)),  # s2's grant to s3, in flight
    )
    r = refbfs.check(cfg, init_override=start)
    assert r.violation is not None
    trace = r.violation.trace
    assert trace[0][0] is None and trace[0][1] == start
    # each step is a real successor of its predecessor
    for (_lbl, prev), (_label, cur) in zip(trace, trace[1:]):
        succs = [t for _i, t in interp.successors(prev, bounds,
                                                  spec="election")]
        assert cur in succs
    # final state has two simultaneous leaders, in different terms
    final = trace[-1][1]
    leaders = [i for i, x in enumerate(final.role) if x == S.LEADER]
    assert len(leaders) >= 2
    assert len({final.term[i] for i in leaders}) == len(leaders)
    # ...but ElectionSafety holds throughout this run
    r2 = refbfs.check(CheckConfig(bounds=bounds, spec="election",
                                  invariants=("NoTwoLeaders",)),
                      init_override=start)
    assert r2.violation is None
