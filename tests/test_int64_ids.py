"""int64 discovery ids (VERDICT r3 missing #2 / next #2).

The elect5 campaign's space is confirmed > 2^31 orbits, so parents /
trace links / checkpoint streams must carry 64-bit discovery indices
end-to-end.  These tests exercise the widened path with synthetic
>2^31 ids — no 2-billion-state run needed — plus the pre-round-4
width-2 .links migration and both HostStore implementations.

TLC's own fingerprint set is 64-bit with a disk-backed queue
(/root/reference/.gitignore:1-2), so the reference runtime has no such
ceiling; after this widening neither do the DDD engines.
"""

import numpy as np
import pytest

from raft_tla_tpu.utils import ckpt, native

BIG = (1 << 31) + 12345          # a parent id past the int32 ceiling


@pytest.mark.parametrize("mk", [native.make_store, native.PyHostStore],
                         ids=["native", "numpy"])
def test_links_roundtrip_past_int32(mk):
    st = mk(2)
    par = np.asarray([-1, BIG, (1 << 40) + 7], np.int64)
    lane = np.asarray([3, 5, 9], np.int32)
    st.append_links(par, lane)
    p, l = st.read_links(0, 3)
    assert p.dtype == np.int64
    assert p.tolist() == par.tolist()
    assert l.tolist() == lane.tolist()
    st.close()


@pytest.mark.parametrize("mk", [native.make_store, native.PyHostStore],
                         ids=["native", "numpy"])
def test_trace_chain_via_int64_parent_values(mk):
    # A 4-link chain whose PARENT VALUES would overflow int32 if the
    # store truncated them: 3 -> 2 -> 1 -> 0 with the root at -1, but
    # stored with parent ids reconstructed from int64 round-trips.
    st = mk(1)
    par = np.asarray([-1, 0, 1, 2], np.int64)
    lane = np.asarray([-1, 4, 2, 7], np.int32)
    st.append_links(par, lane)
    chain = st.trace_chain(3)
    assert chain.tolist() == [0, 1, 2, 3]
    st.close()


def test_ddd_snapshot_links_roundtrip_past_int32(tmp_path):
    """save_ddd_snapshot / load_ddd_snapshot carry >2^31 parents through
    the width-3 (par_lo, par_hi, lane) int32 stream bit-exactly."""
    from raft_tla_tpu.ddd_engine import load_ddd_snapshot, \
        save_ddd_snapshot

    P = 3
    n = 4
    host = native.make_store(P)
    constore = native.make_store(1)
    keystore = native.make_store(2)
    rng = np.random.default_rng(0)
    host.append(rng.integers(0, 100, (n, P)).astype(np.int32))
    par = np.asarray([-1, BIG, (1 << 35) + 3, 2], np.int64)
    lane = np.asarray([-1, 1, 2, 3], np.int32)
    host.append_links(par, lane)
    constore.append(np.ones((n, 1), np.int32))
    # distinct keys (the loader rebuilds+validates the master from them)
    keys = np.arange(1, n + 1, dtype=np.uint64) * np.uint64(0x9E3779B9)
    keystore.append(np.stack(
        [(keys & np.uint64(0xFFFFFFFF)).astype(np.uint32),
         (keys >> np.uint64(32)).astype(np.uint32)], axis=1)
        .view(np.int32))

    path = str(tmp_path / "snap")
    save_ddd_snapshot(path, host, constore, keystore, n, 7,
                      np.zeros(5, np.int64), [1, n], 0, P, digest=99)
    with open(path + ".links", "rb") as f:
        assert int(np.fromfile(f, np.int64, 2)[1]) == 3   # width-3 now
    h2, c2, k2, n2, t2, cov2, le2, bd2 = load_ddd_snapshot(path, P, 99)
    p2, l2 = h2.read_links(0, n)
    assert p2.tolist() == par.tolist()
    assert l2.tolist() == lane.tolist()
    assert (h2.read(0, n) == host.read(0, n)).all()
    for s in (host, constore, keystore, h2, c2, k2):
        s.close()


def test_ddd_snapshot_migrates_old_width2_links(tmp_path):
    """A pre-round-4 snapshot (.links width 2, int32 parents) loads via
    the dual-read path; saving again rewrites it width 3."""
    from raft_tla_tpu.ddd_engine import load_ddd_snapshot, \
        save_ddd_snapshot

    P = 2
    n = 3
    rng = np.random.default_rng(1)
    rows = rng.integers(0, 100, (n, P)).astype(np.int32)
    par32 = np.asarray([-1, 0, 1], np.int32)
    lane = np.asarray([-1, 2, 5], np.int32)
    con = np.ones((n, 1), np.int32)
    keys = np.arange(1, n + 1, dtype=np.uint64) * np.uint64(0x61C88647)
    kw = np.stack([(keys & np.uint64(0xFFFFFFFF)).astype(np.uint32),
                   (keys >> np.uint64(32)).astype(np.uint32)],
                  axis=1).view(np.int32)

    path = str(tmp_path / "old")
    ckpt.stream_rows_out(path + ".rows", lambda s, k: rows[s:s + k], n, P)
    ckpt.stream_rows_out(
        path + ".links",
        lambda s, k: np.stack([par32, lane], axis=1)[s:s + k], n, 2)
    ckpt.stream_rows_out(path + ".con", lambda s, k: con[s:s + k], n, 1)
    ckpt.stream_rows_out(path + ".keys", lambda s, k: kw[s:s + k], n, 2)
    ckpt.atomic_savez(path, n_states=np.int64(n), n_trans=np.uint64(2),
                      cov=np.zeros(4, np.int64),
                      level_ends=np.asarray([1, n], np.int64),
                      blocks_done=np.int64(0),
                      config_digest=np.uint64(7))

    h2, c2, k2, n2, *_ = load_ddd_snapshot(path, P, 7)
    p2, l2 = h2.read_links(0, n)
    assert p2.dtype == np.int64
    assert p2.tolist() == par32.tolist()
    assert l2.tolist() == lane.tolist()

    # re-save: the width change forces one full .links rewrite to w3
    save_ddd_snapshot(path, h2, c2, k2, n, 2, np.zeros(4, np.int64),
                      [1, n], 0, P, digest=7)
    with open(path + ".links", "rb") as f:
        assert int(np.fromfile(f, np.int64, 2)[1]) == 3
    h3, c3, k3, *_ = load_ddd_snapshot(path, P, 7)
    p3, l3 = h3.read_links(0, n)
    assert p3.tolist() == par32.tolist()
    for s in (h2, c2, k2, h3, c3, k3):
        s.close()
