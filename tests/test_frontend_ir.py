"""frontend/raft_ir + widthgen: Raft as the IR compiler's first client.

Two parity claims, each pinned bit-for-bit:

- **Pass-1 twins**: ``widthgen.transfer_of`` derives the speclint
  interval twins from the same ActionDefs the runtime kernels compile
  from; they must equal the hand-written ``widthcheck.TRANSFERS``
  output-for-output (writes, sends, AND the message-envelope fixpoint),
  so the hand table and the kernels can only drift together.
- **Runtime step**: the IR-compiled kernel table produces the same
  states, fingerprints, invariant verdicts, and traces as the hand
  kernels — at the step level (every output lane), the engine level
  (the 3014-state toy), and on violation/deadlock traces.

Heavy arms (the 583506-state from-init violation, the symmetry orbit
sweeps) are marked slow; tier-1 keeps the seeded-violation and toy-bound
arms only.
"""

import numpy as np
import pytest

from raft_tla_tpu import engine
from raft_tla_tpu.analysis import intervals as iv
from raft_tla_tpu.analysis import widthcheck as wc
from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.frontend import raft_ir
from raft_tla_tpu.models import interp
from raft_tla_tpu.models import spec as S
from raft_tla_tpu.ops import kernels
from raft_tla_tpu.ops import msgbits as mb

TOY = Bounds(n_servers=2, n_values=1, max_term=2, max_log=1, max_msgs=2)

TWIN_BOUNDS = [
    Bounds(),
    Bounds(n_servers=2, n_values=1, max_term=2, max_log=1, max_msgs=2),
    Bounds(n_servers=5, n_values=2, max_term=4, max_log=2, max_msgs=3,
           max_dup=2),
]


# -- Pass-1 twin equality -----------------------------------------------------

@pytest.mark.parametrize("bounds", TWIN_BOUNDS,
                         ids=["default", "toy", "wide"])
def test_generated_twins_equal_hand(bounds):
    env = iv.expansion_envelope(bounds)
    gen = raft_ir.transfers()
    assert set(gen) == set(wc.TRANSFERS)
    # the envelope fixpoint must agree BEFORE the per-family comparison:
    # it feeds every Receive twin
    menv_hand = wc.message_envelope(bounds, env, wc.TRANSFERS)
    menv_gen = wc.message_envelope(bounds, env, gen)
    assert menv_hand == menv_gen
    for fam in wc.TRANSFERS:
        hand = wc.TRANSFERS[fam](bounds, env, menv_hand)
        made = gen[fam](bounds, env, menv_hand)
        assert made.writes == hand.writes, fam
        assert made.sends == hand.sends, fam


@pytest.mark.parametrize("spec", ["full", "election", "replication"])
def test_check_widths_clean_with_generated_twins(spec):
    for bounds in TWIN_BOUNDS:
        assert wc.check_widths(bounds, spec,
                               transfers=raft_ir.transfers()) == [], spec


# -- step-level bit identity --------------------------------------------------

def test_step_bit_identical_on_toy_frontiers():
    """Every output lane of the fused step — packed successors, valid/
    overflow masks, both fingerprint words, invariant verdicts,
    constraint flags — over two BFS levels from Init."""
    import jax
    invs = ("NoTwoLeaders",)
    hand = jax.jit(kernels.build_step(TOY, "election", invariants=invs))
    made = jax.jit(kernels.build_step(
        TOY, "election", invariants=invs,
        family_kernels=raft_ir.family_kernels(TOY)))
    B = 16                     # fixed batch: one compile spans both levels
    init = np.asarray(interp.to_vec(interp.init_state(TOY), TOY))
    vecs = np.tile(init, (B, 1))
    for level in range(2):
        out_h = {k: np.asarray(v) for k, v in hand(vecs).items()}
        out_m = {k: np.asarray(v) for k, v in made(vecs).items()}
        assert set(out_h) == set(out_m)
        for key in out_h:
            assert np.array_equal(out_h[key], out_m[key]), (level, key)
        keep = out_h["valid"] & ~out_h["overflow"]
        nxt = np.unique(out_h["svecs"][keep], axis=0)
        assert 0 < len(nxt) <= B
        # pad back to B with repeats of the first successor
        vecs = np.concatenate([nxt, np.tile(nxt[:1], (B - len(nxt), 1))])


# -- engine-level parity ------------------------------------------------------

def _pair(spec_bounds, **cfg_kw):
    res = {}
    for spec in ("election", "ir-election"):
        cfg = CheckConfig(bounds=spec_bounds, spec=spec, **cfg_kw)
        res[spec] = engine.check(cfg)
    return res["election"], res["ir-election"]


def test_engine_ir_equals_hand_on_toy():
    hand, made = _pair(TOY, invariants=("NoTwoLeaders",), chunk=256)
    assert (hand.n_states, hand.diameter, hand.n_transitions) == (
        made.n_states, made.diameter, made.n_transitions)
    assert hand.coverage == made.coverage
    # the anchor itself, so a joint drift cannot hide
    assert (hand.n_states, hand.diameter, hand.n_transitions) == \
        (3014, 17, 5274)


def bag(*ms):
    return tuple(sorted((m, 1) for m in ms))


@pytest.mark.slow
def test_violation_trace_identical_seeded():
    """Hand and IR reconstruct the SAME NaiveNoTwoLeaders counterexample
    (labels and full states), from the cheap seeded start."""
    bounds = Bounds(n_servers=3, n_values=1, max_term=3, max_log=0,
                    max_msgs=4, max_dup=1)
    start = interp.init_state(bounds)._replace(
        role=(S.LEADER, S.FOLLOWER, S.CANDIDATE),
        term=(2, 3, 3), votedFor=(1, 3, 0),
        vGrant=(0b011, 0, 0b100),
        msgs=bag(mb.rv_response(3, 1, 1, 2)))
    out = {}
    for spec in ("election", "ir-election"):
        cfg = CheckConfig(bounds=bounds, spec=spec,
                          invariants=("NaiveNoTwoLeaders",), chunk=256)
        out[spec] = engine.check(cfg, init_override=start)
    v_h, v_m = out["election"].violation, out["ir-election"].violation
    assert v_h is not None and v_m is not None
    assert v_h.invariant == v_m.invariant == "NaiveNoTwoLeaders"
    assert v_h.state == v_m.state
    assert v_h.trace == v_m.trace


def test_deadlock_trace_identical():
    """Replication from default Init deadlocks immediately (no client
    request has happened, no AE is sendable) — both compilers must
    report the same deadlock state and trace."""
    out = {}
    for spec in ("replication", "ir-replication"):
        cfg = CheckConfig(bounds=TOY, spec=spec, invariants=(),
                          check_deadlock=True, chunk=256)
        out[spec] = engine.check(cfg)
    v_h, v_m = out["replication"].violation, out["ir-replication"].violation
    assert v_h is not None and v_m is not None
    assert v_h.invariant == v_m.invariant
    assert v_h.trace == v_m.trace
    assert len(v_h.trace) == 1              # Init itself is the deadlock


# -- heavy arms ---------------------------------------------------------------

@pytest.mark.slow
def test_violation_trace_identical_from_init():
    """The full from-init search (583506 states) ends in the same
    19-state counterexample under both compilers."""
    bounds = Bounds(n_servers=3, n_values=1, max_term=3, max_log=0,
                    max_msgs=1)
    out = {}
    for spec in ("election", "ir-election"):
        cfg = CheckConfig(bounds=bounds, spec=spec,
                          invariants=("NaiveNoTwoLeaders",), chunk=256)
        out[spec] = engine.check(cfg)
    r_h, r_m = out["election"], out["ir-election"]
    assert r_h.n_states == r_m.n_states == 583506
    v_h, v_m = r_h.violation, r_m.violation
    assert v_h is not None and v_m is not None
    assert len(v_h.trace) == 19
    assert v_h.trace == v_m.trace


_SYM_ARMS = [
    # (bounds, |G|): Server orbit sizes 3! / 4! / 5!.  The |G|=120 arm
    # runs at max_term=1 (a near-degenerate 2-state space) — it probes
    # the 120-permutation orbit canonicalization, not search depth.
    (Bounds(n_servers=3, n_values=1, max_term=2, max_log=0, max_msgs=1), 6),
    (Bounds(n_servers=4, n_values=1, max_term=2, max_log=0, max_msgs=1), 24),
    (Bounds(n_servers=5, n_values=1, max_term=1, max_log=0, max_msgs=1),
     120),
]


@pytest.mark.slow
@pytest.mark.parametrize("bounds,order", _SYM_ARMS,
                         ids=["G6", "G24", "G120"])
def test_engine_ir_equals_hand_under_symmetry(bounds, order):
    import math
    assert math.factorial(bounds.n_servers) == order
    hand, made = _pair(bounds, invariants=("NoTwoLeaders",),
                       symmetry=("Server",), chunk=256)
    assert (hand.n_states, hand.diameter, hand.n_transitions) == (
        made.n_states, made.diameter, made.n_transitions)
    assert hand.violation is None and made.violation is None
