"""CP lane sharding (parallel/cp_expand.py): the per-state bag-scan
fan-out partitioned across mesh devices.

Gates: every dense action lane is owned by exactly one (device, local
lane); under shard_map on the virtual 8-device mesh each owned lane's
(valid, overflow, svec, fingerprint, invariant, constraint) values are
bit-identical to the dense step's at the mapped index; dead lanes
(non-bag off device 0, slot padding) are never valid; and the partition
covers awkward shapes (S not divisible by ndev, ndev > S).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from raft_tla_tpu.config import Bounds
from raft_tla_tpu.models import interp, spec as SP
from raft_tla_tpu.ops import kernels
from raft_tla_tpu.parallel.cp_expand import (
    build_cp_step, cp_lane_count, cp_lane_map)
from raft_tla_tpu.parallel.shard_engine import make_mesh, _AXIS, _shard_map

from test_state import random_pystate

# a bag-heavy universe: S = msg_cap large enough that the bag lanes
# dominate the table — CP's operating regime
B5 = Bounds(n_servers=2, n_values=1, max_term=2, max_log=0, max_msgs=5)


def test_lane_map_is_a_partition():
    for bounds, spec, ndev in ((B5, "full", 8), (B5, "full", 4),
                               (B5, "election", 3),
                               (B5, "full", 16)):   # ndev > S
        m = cp_lane_map(bounds, spec, ndev)
        A = len(SP.action_table(bounds, spec))
        assert m.shape == (ndev, cp_lane_count(bounds, spec, ndev))
        owned = m[m >= 0]
        assert sorted(owned.tolist()) == list(range(A))


def _run_cp(bounds, spec, invs, sym, vecs, ndev):
    mesh = make_mesh(ndev)
    step = build_cp_step(bounds, spec, invs, sym, ndev=ndev)

    def shard_fn(v):
        return step(v, jax.lax.axis_index(_AXIS))

    out = jax.jit(_shard_map(
        shard_fn, mesh=mesh, in_specs=P(), out_specs=P(_AXIS)))(vecs)
    return {k: np.asarray(v) for k, v in out.items()}


@pytest.mark.slow      # virtual-mesh test (see test_shard_engine)
def test_cp_step_matches_dense_per_lane():
    rng = np.random.default_rng(23)
    states = [random_pystate(rng, B5) for _ in range(8)]
    vecs = jnp.asarray(np.stack([interp.to_vec(s, B5) for s in states]))
    invs = ("NoTwoLeaders",)
    for sym in ((), ("Server",)):
        dense = {k: np.asarray(v) for k, v in jax.jit(
            kernels.build_step(B5, "full", invs, sym))(vecs).items()}
        ndev = 8
        got = _run_cp(B5, "full", invs, sym, vecs, ndev)
        lanes = cp_lane_map(B5, "full", ndev)     # [ndev, A_local]
        Al = lanes.shape[1]
        Bc = len(states)
        # out_specs stacks the device axis first: [ndev * Bc, A_local]
        for d in range(ndev):
            seg = {k: v[d * Bc:(d + 1) * Bc] for k, v in got.items()}
            for l in range(Al):
                g = lanes[d, l]
                if g < 0:
                    assert not seg["valid"][:, l].any()
                    continue
                np.testing.assert_array_equal(seg["valid"][:, l],
                                              dense["valid"][:, g])
                np.testing.assert_array_equal(seg["overflow"][:, l],
                                              dense["overflow"][:, g])
                np.testing.assert_array_equal(seg["svecs"][:, l],
                                              dense["svecs"][:, g])
                np.testing.assert_array_equal(seg["fp_hi"][:, l],
                                              dense["fp_hi"][:, g])
                np.testing.assert_array_equal(seg["fp_lo"][:, l],
                                              dense["fp_lo"][:, g])
                np.testing.assert_array_equal(seg["inv_ok"][:, l],
                                              dense["inv_ok"][:, g])
                np.testing.assert_array_equal(seg["con_ok"][:, l],
                                              dense["con_ok"][:, g])


@pytest.mark.slow      # virtual-mesh test (see test_shard_engine)
def test_cp_step_faithful_mode():
    """History fields (allLogs union) ride the CP expansion too."""
    bounds = Bounds(n_servers=2, n_values=1, max_term=2, max_log=1,
                    max_msgs=3, history=True, max_elections=4)
    rng = np.random.default_rng(29)
    states = [random_pystate(rng, bounds) for _ in range(4)]
    vecs = jnp.asarray(np.stack([interp.to_vec(s, bounds)
                                 for s in states]))
    dense = {k: np.asarray(v) for k, v in jax.jit(
        kernels.build_step(bounds, "full", ()))(vecs).items()}
    ndev = 4
    got = _run_cp(bounds, "full", (), (), vecs, ndev)
    lanes = cp_lane_map(bounds, "full", ndev)
    Bc = len(states)
    for d in range(ndev):
        seg_v = got["valid"][d * Bc:(d + 1) * Bc]
        seg_s = got["svecs"][d * Bc:(d + 1) * Bc]
        for l in range(lanes.shape[1]):
            g = lanes[d, l]
            if g < 0:
                assert not seg_v[:, l].any()
                continue
            np.testing.assert_array_equal(seg_v[:, l],
                                          dense["valid"][:, g])
            np.testing.assert_array_equal(seg_s[:, l],
                                          dense["svecs"][:, g])
