"""Multi-tenant serve/: admission gate, lane-packed executor, service.

Admission must reject width-unsafe and vacuous configs with speclint
findings attached — before any device work.  The batch executor must
produce per-lane counts byte-identical to solo ``engine.Engine`` runs
(completing lanes) and identical verdicts/traces (violation/deadlock
lanes).  The service front must leave one valid SCHEMA_VERSION=1 event
log per tenant that the monitor renders unchanged.
"""

import json
import os

import pytest

from test_cli import write_cfg

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.engine import DEADLOCK, Engine
from raft_tla_tpu.models import interp, spec as S
from raft_tla_tpu.ops import msgbits as mb
from raft_tla_tpu.serve import CheckJob, JobOptions, admit
from raft_tla_tpu.serve.batch import BatchExecutor, bin_key
from raft_tla_tpu.serve.service import load_jobs, run_service

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FLAGSHIP_CFG = os.path.join(REPO, "runs", "MC3s2v.cfg")

# The 3014-state toy universe (known: diameter 17, 5274 transitions).
TOY_BOUNDS = Bounds(n_servers=2, n_values=1, max_term=2, max_log=0,
                    max_msgs=2)
TOY = CheckConfig(bounds=TOY_BOUNDS, spec="election",
                  invariants=("NoTwoLeaders",), chunk=256)

_CONSTANTS = """CONSTANTS
    Server = {%s}
    Value = {v1}
    Follower = "Follower"
    Candidate = "Candidate"
    Leader = "Leader"
    Nil = "Nil"
    RequestVoteRequest = "RequestVoteRequest"
    RequestVoteResponse = "RequestVoteResponse"
    AppendEntriesRequest = "AppendEntriesRequest"
    AppendEntriesResponse = "AppendEntriesResponse"
"""

TOY_OPTS = JobOptions(spec="election", max_term=2, max_log=0, max_msgs=2)


def _no_device(monkeypatch):
    """Poison the step builder: admission must never reach the kernels."""
    from raft_tla_tpu.ops import kernels

    def boom(*a, **kw):                              # pragma: no cover
        raise AssertionError("admission performed device work")
    monkeypatch.setattr(kernels, "build_step", boom)


# --------------------------------------------------------------------------
# admission


def test_admission_rejects_width_unsafe(tmp_path, monkeypatch):
    _no_device(monkeypatch)
    wide = write_cfg(tmp_path / "wide.cfg",
                     servers=", ".join(f"s{i}" for i in range(1, 16)))
    adm = admit(CheckJob("wide", TOY_OPTS, cfg_path=str(wide)))
    assert not adm.admitted and adm.reason == "width-unsafe"
    assert adm.config is None
    codes = {f.code for f in adm.findings}
    assert "bounds-invalid" in codes
    assert adm.findings_text() and all(isinstance(t, str)
                                       for t in adm.findings_text())


def test_admission_rejects_vacuous(tmp_path, monkeypatch):
    _no_device(monkeypatch)
    # LogMatching under the log-free election subset checks nothing:
    # a CLI warning, but the service must not bill device time for it.
    text = ("SPECIFICATION Spec\nINVARIANT LogMatching\n"
            + _CONSTANTS % "s1, s2")
    adm = admit(CheckJob("vac", TOY_OPTS, cfg_text=text))
    assert not adm.admitted and adm.reason == "vacuous"
    assert any(f.code == "invariant-vacuous" for f in adm.findings)


def test_admission_rejects_unreadable(tmp_path, monkeypatch):
    _no_device(monkeypatch)
    adm = admit(CheckJob("ghost", TOY_OPTS,
                         cfg_path=str(tmp_path / "missing.cfg")))
    assert not adm.admitted and adm.reason == "cfg-unreadable"


def test_admission_rejects_unknown_invariant(monkeypatch):
    _no_device(monkeypatch)
    text = ("SPECIFICATION Spec\nINVARIANT NoTwoLeadres\n"
            + _CONSTANTS % "s1, s2")
    adm = admit(CheckJob("typo", TOY_OPTS, cfg_text=text))
    assert not adm.admitted and adm.reason == "cfg-invalid"
    assert any(f.severity == "error" for f in adm.findings)


def test_admission_admits_flagship_cfg(monkeypatch):
    _no_device(monkeypatch)
    adm = admit(CheckJob("mc3s2v",
                         JobOptions(spec="full", max_term=2, max_log=1),
                         cfg_path=FLAGSHIP_CFG))
    assert adm.admitted and adm.reason is None
    cc = adm.config
    assert cc.bounds.n_servers == 3 and cc.bounds.n_values == 2
    assert cc.symmetry == ("Server",)
    assert "NoTwoLeaders" in cc.invariants
    assert adm.properties == ()


def test_job_digest_covers_text_and_options(tmp_path):
    toy = write_cfg(tmp_path / "toy.cfg")
    by_path = CheckJob("a", TOY_OPTS, cfg_path=str(toy))
    by_text = CheckJob("b", TOY_OPTS,
                       cfg_text=(tmp_path / "toy.cfg").read_text())
    # Same model: same digest regardless of id or path-vs-inline ...
    assert by_path.digest() == by_text.digest()
    # ... different options: different digest.
    other = CheckJob("a", JobOptions(spec="election", max_term=3,
                                     max_log=0, max_msgs=2),
                     cfg_path=str(toy))
    assert other.digest() != by_path.digest()


def test_job_from_dict_rejects_unknown_keys():
    with pytest.raises(ValueError, match="unknown option"):
        CheckJob.from_dict({"id": "x", "cfg_text": "", "max_trem": 3})
    with pytest.raises(ValueError, match="no 'id'"):
        CheckJob.from_dict({"cfg_text": ""})


# --------------------------------------------------------------------------
# lane-packed batch executor


def bag(*ms):
    return tuple(sorted((m, 1) for m in ms))


VB = Bounds(n_servers=3, n_values=1, max_term=3, max_log=0, max_msgs=4)
VIOL = CheckConfig(bounds=VB, spec="election",
                   invariants=("NaiveNoTwoLeaders",), chunk=256)
DEAD = CheckConfig(bounds=Bounds(n_servers=1, n_values=1, max_term=2,
                                 max_log=0, max_msgs=2),
                   spec="election", invariants=(), check_deadlock=True,
                   chunk=256)
TOY_SYM = CheckConfig(bounds=TOY_BOUNDS, spec="election",
                      invariants=("NoTwoLeaders",), symmetry=("Server",),
                      chunk=256)


def seeded_start():
    """Two steps from a NaiveNoTwoLeaders violation (engine-test seed)."""
    return interp.init_state(VB)._replace(
        role=(S.LEADER, S.FOLLOWER, S.CANDIDATE),
        term=(2, 3, 3), votedFor=(1, 3, 0),
        vGrant=(0b011, 0, 0b100), msgs=bag(mb.rv_response(3, 1, 1, 2)))


def assert_counts_equal(res, ref):
    assert res.n_states == ref.n_states
    assert res.diameter == ref.diameter
    assert res.n_transitions == ref.n_transitions
    assert list(res.levels) == list(ref.levels)
    assert dict(res.coverage) == dict(ref.coverage)
    assert res.complete and ref.complete


def test_bin_key_ignores_chunk():
    rechunked = CheckConfig(bounds=TOY_BOUNDS, spec="election",
                            invariants=("NoTwoLeaders",), chunk=64)
    assert bin_key(TOY) == bin_key(rechunked)
    assert bin_key(TOY) != bin_key(TOY_SYM)


def test_batch_lanes_match_solo_runs():
    """One executor, four bins (toy x2 shares one): every completing
    lane's counts byte-identical to a solo Engine of the same cfg, and
    violation/deadlock lanes reach the solo verdict and trace."""
    ex = BatchExecutor(chunk=256)
    out = ex.run([("toy-a", TOY), ("toy-b", TOY), ("sym", TOY_SYM),
                  ("dead", DEAD), ("viol", VIOL)],
                 init_overrides={"viol": seeded_start()})
    assert set(out) == {"toy-a", "toy-b", "sym", "dead", "viol"}

    solo_toy = Engine(TOY).check()
    assert solo_toy.n_states == 3014 and solo_toy.n_transitions == 5274
    for jid in ("toy-a", "toy-b"):
        assert out[jid].status == "completed"
        assert_counts_equal(out[jid].result, solo_toy)

    solo_sym = Engine(TOY_SYM).check()
    assert out["sym"].status == "completed"
    assert_counts_equal(out["sym"].result, solo_sym)
    assert solo_sym.n_states < solo_toy.n_states     # symmetry quotient

    solo_dead = Engine(DEAD).check()
    assert out["dead"].status == "deadlock"
    v = out["dead"].result.violation
    assert v.invariant == DEADLOCK == solo_dead.violation.invariant
    assert v.trace == solo_dead.violation.trace

    solo_viol = Engine(VIOL).check(init_override=seeded_start())
    assert out["viol"].status == "violation"
    v = out["viol"].result.violation
    assert v.invariant == "NaiveNoTwoLeaders"
    assert v.trace == solo_viol.violation.trace
    assert v.state == solo_viol.violation.state


def test_batch_duplicate_job_id_rejected():
    with pytest.raises(ValueError, match="duplicate job id"):
        BatchExecutor(chunk=64).run([("a", TOY), ("a", TOY)])


def test_batch_max_states_stops_one_lane_only():
    """A lane blowing its cap is stopped with attribution; its bin-mates
    (and other bins) keep running to their verdicts."""
    out = BatchExecutor(chunk=128, max_states=200).run(
        [("big", TOY), ("dead", DEAD)])
    assert out["big"].status == "stopped"
    assert "exceeded 200" in out["big"].error
    assert not out["big"].result.complete
    assert out["dead"].status == "deadlock"


# --------------------------------------------------------------------------
# service front


def _toy_manifest_line(jid, **extra):
    d = {"id": jid, "cfg": "toy.cfg", "spec": "election", "max_term": 2,
         "max_log": 0, "max_msgs": 2}
    d.update(extra)
    return json.dumps(d)


def _write_service_inputs(tmp_path):
    write_cfg(tmp_path / "toy.cfg")
    write_cfg(tmp_path / "wide.cfg",
              servers=", ".join(f"s{i}" for i in range(1, 16)))
    return tmp_path / "manifest.jsonl"


@pytest.mark.smoke
def test_service_end_to_end(tmp_path):
    from raft_tla_tpu.obs import validate_event
    from raft_tla_tpu.obs import monitor

    manifest = _write_service_inputs(tmp_path)
    vac_text = ("SPECIFICATION Spec\nINVARIANT LogMatching\n"
                + _CONSTANTS % "s1, s2")
    manifest.write_text("\n".join([
        "# comment lines and blanks are skipped",
        "",
        _toy_manifest_line("good-a"),
        _toy_manifest_line("good-b"),
        _toy_manifest_line("wide", cfg="wide.cfg"),
        json.dumps({"id": "vac", "cfg_text": vac_text, "spec": "election",
                    "max_term": 2, "max_log": 0, "max_msgs": 2}),
        _toy_manifest_line("live", properties=["EventuallyLeader"]),
    ]) + "\n")

    out_dir = tmp_path / "out"
    records = run_service(load_jobs(str(manifest)), str(out_dir),
                          chunk=256, quiet=True)
    by_id = {r["job_id"]: r for r in records}
    assert set(by_id) == {"good-a", "good-b", "wide", "vac", "live"}

    # Verdicts + tenant isolation: identical jobs share a digest, the
    # results file is the same records the call returned.
    assert by_id["good-a"]["status"] == "completed"
    assert by_id["good-a"]["n_states"] == 3014
    assert by_id["good-a"]["digest"] == by_id["good-b"]["digest"]
    assert by_id["wide"]["status"] == "rejected"
    assert by_id["wide"]["reason"] == "width-unsafe"
    assert by_id["wide"]["findings"]            # lint payload attached
    assert by_id["vac"]["reason"] == "vacuous"
    assert by_id["live"]["reason"] == "property-unsupported"
    on_disk = [json.loads(l)
               for l in (out_dir / "results.jsonl").read_text().splitlines()]
    assert {r["job_id"] for r in on_disk} == set(by_id)

    # One conformant event log per tenant; the monitor renders each with
    # the right end-state attribution, no serve-specific handling.
    for jid, want in [("good-a", "ok"), ("good-b", "ok"),
                      ("wide", "rejected"), ("vac", "rejected"),
                      ("live", "rejected")]:
        path = by_id[jid]["events"]
        events = [json.loads(l) for l in open(path)]
        assert not [e for d in events for e in validate_event(d)], jid
        assert events[0]["event"] == "run_start"
        assert events[-1]["event"] == "run_end"
        hb = monitor.heartbeat(monitor.summarize(monitor.load_stream(path)))
        assert want in hb, (jid, hb)


def test_service_stopped_lane_attribution(tmp_path):
    from raft_tla_tpu.obs import monitor

    manifest = _write_service_inputs(tmp_path)
    manifest.write_text(_toy_manifest_line("capped") + "\n")
    records = run_service(load_jobs(str(manifest)), str(tmp_path / "out"),
                          chunk=128, max_states=200, quiet=True)
    (rec,) = records
    assert rec["status"] == "stopped" and "exceeded 200" in rec["error"]
    hb = monitor.heartbeat(monitor.summarize(
        monitor.load_stream(rec["events"])))
    assert "stopped" in hb, hb


def test_load_jobs_queue_dir_and_errors(tmp_path):
    write_cfg(tmp_path / "toy.cfg")
    qdir = tmp_path / "queue"
    qdir.mkdir()
    # Queue convention: filename stem is the default id, sorted order.
    (qdir / "010-beta.json").write_text(json.dumps(
        {"cfg": str(tmp_path / "toy.cfg"), "spec": "election"}))
    (qdir / "005-alpha.json").write_text(json.dumps(
        {"cfg": "toy.cfg", "spec": "election"}))
    (qdir / "toy.cfg").write_text((tmp_path / "toy.cfg").read_text())
    jobs = load_jobs(str(qdir))
    assert [j.job_id for j in jobs] == ["005-alpha", "010-beta"]
    # Relative cfg resolved against the queue dir itself.
    assert jobs[0].cfg_path == str(qdir / "toy.cfg")

    m = tmp_path / "bad.jsonl"
    m.write_text(_toy_manifest_line("a") + "\n" + _toy_manifest_line("a")
                 + "\n")
    with pytest.raises(ValueError, match="duplicate job id"):
        load_jobs(str(m))
    m.write_text(_toy_manifest_line("../evil") + "\n")
    with pytest.raises(ValueError, match="not path-safe"):
        load_jobs(str(m))
    m.write_text(_toy_manifest_line("a", max_trem=3) + "\n")
    with pytest.raises(ValueError, match="unknown option"):
        load_jobs(str(m))
    m.write_text("{not json\n")
    with pytest.raises(ValueError, match="not JSON"):
        load_jobs(str(m))
    empty = tmp_path / "empty-queue"
    empty.mkdir()
    with pytest.raises(ValueError, match="no \\*.json jobs"):
        load_jobs(str(empty))


def test_load_jobs_queue_skips_partial_writes(tmp_path):
    """Queue-dir intake races a producer mid-write: the torn file is
    retried once, then skipped with attribution — never poisons the
    scan (campaign supervision satellite)."""
    write_cfg(tmp_path / "toy.cfg")
    qdir = tmp_path / "queue"
    qdir.mkdir()
    (qdir / "001-good.json").write_text(json.dumps(
        {"cfg": str(tmp_path / "toy.cfg"), "spec": "election"}))
    (qdir / "002-torn.json").write_text('{"cfg": "toy.cfg", "spe')
    skipped = []
    jobs = load_jobs(str(qdir), skipped=skipped)
    assert [j.job_id for j in jobs] == ["001-good"]
    assert [name for name, _ in skipped] == ["002-torn.json"]
    assert skipped[0][1]                 # the parse error is attributed

    # every job file unreadable: that is not a race, it is a dead queue
    bad = tmp_path / "dead-queue"
    bad.mkdir()
    (bad / "x.json").write_text("{")
    with pytest.raises(ValueError, match="unreadable"):
        load_jobs(str(bad))
