"""Frontier-retention DDD mode (the TLC-regime campaign mode).

Retention changes WHERE rows live (disk level files, no trace links),
never WHAT is discovered: counts, levels, coverage and verdicts must
be identical to full retention, checkpoints must resume in place, and
a full-format snapshot must migrate on first frontier resume.
"""

import glob
import os

import pytest

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.ddd_engine import DDDCapacities, DDDEngine
from raft_tla_tpu.models import refbfs

ELECTION = CheckConfig(
    bounds=Bounds(n_servers=2, n_values=1, max_term=2, max_log=0,
                  max_msgs=2),
    spec="election", invariants=("NoTwoLeaders",), chunk=256)

FULL = CheckConfig(
    bounds=Bounds(n_servers=2, n_values=2, max_term=2, max_log=1,
                  max_msgs=2, max_dup=1),
    spec="full",
    invariants=("NoTwoLeaders", "LogMatching", "CommittedWithinLog"),
    chunk=256)


def _caps(**kw):
    base = dict(block=1 << 12, table=1 << 10, seg_rows=1 << 15,
                flush=1 << 12, levels=64, retention="frontier")
    base.update(kw)
    return DDDCapacities(**base)


def assert_totals(got, ref):
    assert got.n_states == ref.n_states
    assert got.diameter == ref.diameter
    assert got.n_transitions == ref.n_transitions
    assert got.levels == ref.levels
    assert got.coverage == ref.coverage


def test_frontier_parity_election():
    ref = refbfs.check(ELECTION)
    got = DDDEngine(ELECTION, _caps()).check()
    assert_totals(got, ref)
    assert got.violation is None


def test_frontier_parity_full_spec():
    ref = refbfs.check(FULL)
    got = DDDEngine(FULL, _caps()).check()
    assert_totals(got, ref)


def test_frontier_violation_reports_state_without_trace():
    # 3 servers: a deposed leader coexists with a new-term leader (at 2
    # servers quorum forces the step-down first, Naive is unreachable)
    cfg = CheckConfig(
        bounds=Bounds(n_servers=3, n_values=1, max_term=3, max_log=0,
                      max_msgs=1),
        spec="election", invariants=("NaiveNoTwoLeaders",), chunk=256)
    ref = refbfs.check(cfg)
    assert ref.violation is not None
    got = DDDEngine(cfg, _caps()).check()
    assert got.violation is not None
    assert got.violation.invariant == "NaiveNoTwoLeaders"
    # the same violating state the full-retention engine stops at;
    # only the path is absent (TLC -noTrace equivalence)
    full = DDDEngine(cfg, _caps(retention="full")).check()
    assert got.violation.state == full.violation.state
    assert len(got.violation.trace) == 1
    assert got.n_states == full.n_states


def test_frontier_deadlock():
    cfg = CheckConfig(
        bounds=Bounds(n_servers=1, n_values=1, max_term=2, max_log=0,
                      max_msgs=2),
        spec="election", invariants=(), check_deadlock=True, chunk=64)
    ref = refbfs.check(cfg)
    got = DDDEngine(cfg, _caps(block=1 << 8)).check()
    assert got.violation is not None
    assert got.violation.invariant == ref.violation.invariant
    assert got.n_states == ref.n_states


def test_frontier_checkpoint_resume(tmp_path):
    ck = str(tmp_path / "f.ckpt")
    ref = refbfs.check(FULL)
    eng = DDDEngine(FULL, _caps())
    part = eng.check(checkpoint=ck, checkpoint_every_s=0.0,
                     deadline_s=1.0)
    assert not part.complete
    assert part.n_states < ref.n_states
    assert os.path.exists(ck)       # at least one boundary snapshot
    got = DDDEngine(FULL, _caps()).check(resume=ck, checkpoint=ck,
                                         checkpoint_every_s=0.0)
    assert_totals(got, ref)
    # pre-frontier level files were cleaned at snapshots
    idxs = sorted(int(p.rsplit("L", 1)[1])
                  for p in glob.glob(ck + ".rowsL*"))
    assert len(idxs) <= 3


def test_full_snapshot_migrates_to_frontier(tmp_path):
    """A full-format checkpoint (the elect5 campaign's situation)
    resumes under retention='frontier': the retained window slices out
    of the old streams, the dead prefix and .links are removed."""
    ck = str(tmp_path / "m.ckpt")
    ref = refbfs.check(FULL)
    full_caps = _caps(retention="full")
    part = DDDEngine(FULL, full_caps).check(
        checkpoint=ck, checkpoint_every_s=0.0, deadline_s=1.0)
    assert not part.complete
    assert os.path.exists(ck + ".rows") and os.path.exists(ck + ".links")
    got = DDDEngine(FULL, _caps()).check(resume=ck, checkpoint=ck,
                                         checkpoint_every_s=0.0)
    assert_totals(got, ref)
    assert not os.path.exists(ck + ".rows")       # migrated + removed
    assert not os.path.exists(ck + ".links")


def test_frontier_rejects_retain_store():
    with pytest.raises(ValueError, match="retain_store"):
        DDDEngine(ELECTION, _caps()).check(retain_store=True)


def test_filestore_torn_append_discarded(tmp_path):
    """Rows appended after the last sync() are discarded on reopen —
    the crash contract snapshots rely on."""
    from raft_tla_tpu.utils import native

    p = str(tmp_path / "s.stream")
    fs = native.FileStore(p, 3, base=5, reset=True)
    fs.append([[1, 2, 3], [4, 5, 6]])
    fs.sync()                        # commits rows 5..6
    fs.append([[7, 8, 9]])           # torn: never synced
    fs._f.flush()                    # bytes on disk, header not updated
    fs.close()

    fs2 = native.FileStore(p, 3, base=5)
    assert len(fs2) == 7             # base 5 + 2 committed rows
    assert fs2.read(5, 2).tolist() == [[1, 2, 3], [4, 5, 6]]
    # appends continue exactly at the committed point
    fs2.append([[9, 9, 9]])
    fs2.sync()
    assert fs2.read(7, 1).tolist() == [[9, 9, 9]]
    fs2.close()


def test_levelstore_rotation_and_trim(tmp_path):
    from raft_tla_tpu.utils import native

    ls = native.LevelStore(str(tmp_path / "r"), 2, 1, 0, 1, reset=True)
    ls.cur.append([[0, 0]])                  # the init row
    ls.append([[1, 1], [2, 2]])              # level 2 discoveries
    ls.sync()
    ls.rotate()                              # level boundary
    assert ls.cur.base == 1 and len(ls.cur) == 3
    assert ls.nxt.base == 3
    ls.append([[3, 3], [4, 4]])
    ls.trim_next(4)                          # npz said only 4 states
    assert len(ls) == 4
    assert ls.read(3, 1).tolist() == [[3, 3]]
    assert ls.read(1, 2).tolist() == [[1, 1], [2, 2]]   # cur routing
    ls.close()


# -- mesh (ddd-shard) frontier mode ----------------------------------------

def _mesh_caps(**kw):
    from raft_tla_tpu.parallel.ddd_shard_engine import DDDShardCapacities

    base = dict(block=256, table=1 << 10, seg_rows=1 << 16,
                flush=1 << 10, levels=64, retention="frontier")
    base.update(kw)
    return DDDShardCapacities(**base)


@pytest.mark.slow      # virtual-mesh test (see test_shard_engine)
def test_mesh_frontier_parity_8dev():
    from raft_tla_tpu.parallel.ddd_shard_engine import DDDShardEngine
    from raft_tla_tpu.parallel.shard_engine import make_mesh

    ref = refbfs.check(ELECTION)
    got = DDDShardEngine(ELECTION, make_mesh(8), _mesh_caps()).check()
    assert got.n_states == ref.n_states
    assert got.diameter == ref.diameter
    assert got.n_transitions == ref.n_transitions
    assert got.levels == ref.levels


@pytest.mark.slow      # virtual-mesh test (see test_shard_engine)
def test_mesh_frontier_checkpoint_resume_and_reshard(tmp_path):
    """Mesh frontier: snapshot, resume in place, and reshard the
    frontier snapshot 8 -> 2 (keys + level files move verbatim)."""
    from raft_tla_tpu.parallel.ddd_shard_engine import (
        DDDShardEngine, reshard_ddd_checkpoint)
    from raft_tla_tpu.parallel.shard_engine import make_mesh

    ck = str(tmp_path / "m.ckpt")
    ck2 = str(tmp_path / "m2.ckpt")
    ref = refbfs.check(FULL)
    DDDShardEngine(FULL, make_mesh(8), _mesh_caps()).check(
        checkpoint=ck, checkpoint_every_s=0.0)
    got = DDDShardEngine(FULL, make_mesh(8), _mesh_caps()).check(
        resume=ck)
    assert got.n_states == ref.n_states
    assert got.diameter == ref.diameter

    caps2 = _mesh_caps(block=1024, seg_rows=1 << 16)
    reshard_ddd_checkpoint(FULL, _mesh_caps(), ck, ck2, ndev_src=8,
                           ndev_dst=2, caps_dst=caps2)
    from raft_tla_tpu.parallel.shard_engine import make_mesh as mm
    got2 = DDDShardEngine(FULL, mm(2), caps2).check(resume=ck2)
    assert got2.n_states == ref.n_states
    assert got2.diameter == ref.diameter
    assert got2.n_transitions == ref.n_transitions

# -- keep_levels: TLC's states/-dir regime -> full traces ----------------

VIOL_CFG = CheckConfig(
    bounds=Bounds(n_servers=3, n_values=1, max_term=3, max_log=0,
                  max_msgs=1),
    spec="election", invariants=("NaiveNoTwoLeaders",), chunk=256)


def _assert_replayable(trace, cfg):
    """Every edge of the reconstructed trace must be a real interpreter
    transition with the claimed action label (no-symmetry configs)."""
    from raft_tla_tpu.models import interp, spec as S
    table = S.action_table(cfg.bounds, cfg.spec)
    assert trace[0][0] is None
    assert trace[0][1] == interp.init_state(cfg.bounds)
    for (_, prev), (label, cur) in zip(trace, trace[1:]):
        succ = [(table[i].label(), n)
                for i, n in interp.successors(prev, cfg.bounds, table,
                                              cfg.spec)]
        assert (label, cur) in succ


def test_frontier_keep_levels_full_violation_trace():
    got = DDDEngine(VIOL_CFG, _caps(keep_levels=True)).check()
    assert got.violation is not None
    full = DDDEngine(VIOL_CFG, _caps(retention="full")).check()
    # same violating endpoint, same (shortest) trace length as the
    # link-following full-retention trace, every edge replayable
    assert got.violation.state == full.violation.state
    assert len(got.violation.trace) == len(full.violation.trace)
    assert got.violation.trace[-1][1] == got.violation.state
    _assert_replayable(got.violation.trace, VIOL_CFG)


def test_frontier_keep_levels_trace_with_checkpointing(tmp_path):
    # snapshots must not garbage-collect the retained level files
    ck = str(tmp_path / "run")
    got = DDDEngine(VIOL_CFG, _caps(keep_levels=True)).check(
        checkpoint=ck, checkpoint_every_s=0.0)
    assert got.violation is not None
    assert len(got.violation.trace) > 1
    _assert_replayable(got.violation.trace, VIOL_CFG)
    # every level file from L1 up survives on disk
    n_levels = len(glob.glob(ck + ".rowsL*"))
    assert n_levels >= len(got.violation.trace)


def test_frontier_keep_levels_deadlock_trace():
    cfg = CheckConfig(
        bounds=Bounds(n_servers=1, n_values=1, max_term=2, max_log=0,
                      max_msgs=2),
        spec="election", invariants=(), check_deadlock=True, chunk=64)
    got = DDDEngine(cfg, _caps(block=1 << 8, keep_levels=True)).check()
    ref = refbfs.check(cfg)
    assert got.violation is not None
    assert got.violation.invariant == ref.violation.invariant
    assert len(got.violation.trace) == len(ref.violation.trace)
    _assert_replayable(got.violation.trace, cfg)


@pytest.mark.slow      # virtual-mesh test (see test_shard_engine)
def test_frontier_keep_levels_shard_trace():
    from raft_tla_tpu.parallel.ddd_shard_engine import (
        DDDShardCapacities, DDDShardEngine)
    from raft_tla_tpu.parallel.shard_engine import make_mesh
    caps = DDDShardCapacities(block=256, table=1 << 14,
                              seg_rows=1 << 14, flush=1 << 12,
                              levels=64, retention="frontier",
                              keep_levels=True)
    got = DDDShardEngine(VIOL_CFG, make_mesh(2), caps).check()
    assert got.violation is not None
    full = DDDEngine(VIOL_CFG, _caps(retention="full")).check()
    assert len(got.violation.trace) == len(full.violation.trace)
    assert got.violation.trace[-1][1] == got.violation.state
    _assert_replayable(got.violation.trace, VIOL_CFG)


def test_frontier_keep_levels_trace_composes_with_symmetry():
    cfg = CheckConfig(
        bounds=VIOL_CFG.bounds, spec="election",
        invariants=("NaiveNoTwoLeaders",), symmetry=("Server",),
        chunk=256)
    got = DDDEngine(cfg, _caps(keep_levels=True)).check()
    full = DDDEngine(cfg, _caps(retention="full")).check()
    assert got.violation is not None and full.violation is not None
    # states are canonical orbit representatives; the trace matches the
    # full-retention link trace in endpoint and (shortest) length
    assert got.violation.state == full.violation.state
    assert len(got.violation.trace) == len(full.violation.trace)
    assert got.violation.trace[-1][1] == got.violation.state
    assert got.violation.trace[0][0] is None
    assert all(lbl is not None for lbl, _ in got.violation.trace[1:])
