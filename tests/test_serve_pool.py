"""Fault-isolated serving: worker pool, supervision, chaos parity.

The pool's contract is run_service's contract survived: SIGKILL a
worker mid-dispatch and every job still completes with results records
and tenant event logs canonically identical to an unsupervised solo
pass; ride a poison job and the pool bisects to it, quarantines it in
<= K worker deaths, and never runs it again; OOM a worker and it
respawns at half dispatch width without blaming anyone.  Plus the
crash-safety satellites: torn results tails, restart dedup, per-job
wall budgets, and the _LogTail live-log behaviors the supervisor
leans on.
"""

import json
import os
import threading
import time

import pytest

from test_cli import write_cfg

from raft_tla_tpu.campaign.supervisor import _LogTail
from raft_tla_tpu.obs import append_event
from raft_tla_tpu.serve import supervise
from raft_tla_tpu.serve.chaos import (PoolChaos, canon_events,
                                      canon_record, last_records)
from raft_tla_tpu.serve.jobs import CheckJob, JobOptions, admit
from raft_tla_tpu.serve.pool import _partition, run_pool
from raft_tla_tpu.serve.service import (read_results, record_is_terminal,
                                        run_daemon, run_service)
from raft_tla_tpu.serve.supervise import PoolPolicy, classify_death

# 524-state election universe (max_msgs=1): the cheapest real check,
# ~2s per worker process on CPU — pool tests spawn several.
OPTS = JobOptions(spec="election", max_term=2, max_log=0, max_msgs=1)
OPTS_SYM = JobOptions(spec="election", max_term=2, max_log=0,
                      max_msgs=1, symmetry=True)

FAST = PoolPolicy(poll_s=0.02, backoff_base_s=0.05, backoff_cap_s=0.2,
                  backoff_jitter_seed=7)


def _jobs(cfg, ids, alternate=True):
    """Jobs over one cfg; ``alternate`` flips symmetry on odd indices
    so the batch spans two step-signature bins."""
    return [CheckJob(j, OPTS_SYM if alternate and i % 2 else OPTS,
                     cfg_path=str(cfg))
            for i, j in enumerate(ids)]


# --------------------------------------------------------------------------
# host-only units: death classification, partitioning, budgets, torn tails


def test_classify_death_kinds():
    assert classify_death(-9)[0] == "killed"
    assert classify_death(-11)[0] == "segfault"
    assert classify_death(-15)[0] == "signal"
    assert classify_death(1)[0] == "crashed"
    assert classify_death(2, "usage: ...")[0] == "crashed"
    # the output scan wins over the returncode — an uncaught
    # MemoryError exits 1, a TPU RESOURCE_EXHAUSTED dies on a signal
    assert classify_death(1, "MemoryError: ...")[0] == "oom"
    assert classify_death(-6, "RESOURCE_EXHAUSTED: hbm")[0] == "oom"
    assert classify_death(134, "std::bad_alloc")[0] == "oom"


def test_partition_keeps_bins_together_and_splits_when_needed(tmp_path):
    cfg = write_cfg(tmp_path / "toy.cfg")
    jobs = _jobs(cfg, ["a", "b", "c", "d"])      # 2 bins x 2 jobs
    admitted = [(j, admit(j), {}) for j in jobs]
    assert all(a.admitted for _, a, _ in admitted)
    groups = _partition(admitted, workers=2)
    assert sorted(sorted(pj.job_id for pj in g) for g in groups) \
        == [["a", "c"], ["b", "d"]]              # bin-mates share a worker
    # fewer bins than workers: the single bin splits so the pool is
    # actually a pool (fault isolation over compile sharing)
    solo_bin = [(j, admit(j), {})
                for j in _jobs(cfg, ["x", "y", "z"], alternate=False)]
    groups = _partition(solo_bin, workers=2)
    assert len(groups) == 2
    assert sorted(len(g) for g in groups) == [1, 2]


def test_budget_invalid_rejected_at_admission(tmp_path):
    cfg = write_cfg(tmp_path / "toy.cfg")
    for bad in (0, -5, "3s", True):
        job = CheckJob("b", JobOptions(spec="election", max_term=2,
                                       max_log=0, max_msgs=1,
                                       wall_s=bad),
                       cfg_path=str(cfg))
        adm = admit(job)
        assert not adm.admitted and adm.reason == "budget-invalid"
        assert any("wall_s" in t for t in adm.findings_text())


def test_read_results_tolerates_torn_tail(tmp_path):
    out = tmp_path / "out"
    out.mkdir()
    good = {"job_id": "a", "status": "completed", "digest": "d1"}
    with open(out / "results.jsonl", "w") as f:
        f.write(json.dumps(good) + "\n")
        f.write("garbage not json\n")
        f.write(json.dumps({"no_job_id": True}) + "\n")
        f.write('{"job_id": "torn", "status": "comp')   # SIGKILL here
    recs = read_results(str(out))
    assert recs == [good]
    assert read_results(str(tmp_path / "missing")) == []


def test_record_is_terminal_statuses():
    for st in ("completed", "violation", "deadlock", "rejected",
               "quarantined"):
        assert record_is_terminal({"status": st})
    assert not record_is_terminal({"status": "stopped"})
    assert not record_is_terminal({"status": "stopped",
                                   "error": "stop requested (drain)"})
    assert record_is_terminal({"status": "stopped",
                               "error": "budget-exceeded: wall 1.2s"})
    assert record_is_terminal({"status": "stopped",
                               "error": "state count exceeded 10"})


# --------------------------------------------------------------------------
# _LogTail over a live serve tenant log (satellite: the supervisor's
# eyes must survive torn lines, truncation/rotation, and a concurrent
# writer thread)


def test_logtail_live_torn_line_and_rotation(tmp_path):
    path = str(tmp_path / "t.events")
    tail = _LogTail(path)
    assert tail.poll() == []                     # not created yet
    line1 = json.dumps({"event": "segment", "n_states": 10}) + "\n"
    line2 = json.dumps({"event": "segment", "n_states": 20}) + "\n"
    with open(path, "a") as f:
        f.write(line1)
        f.flush()
        assert [e["n_states"] for e in tail.poll()] == [10]
        f.write(line2[:9])                       # torn mid-line
        f.flush()
        assert tail.poll() == []                 # buffered, not garbled
        f.write(line2[9:])
        f.flush()
        assert [e["n_states"] for e in tail.poll()] == [20]
    # rotation: requeue moves the log aside and a fresh (shorter) one
    # appears — the tail must re-anchor, not sleep at a stale offset
    os.replace(path, path + ".retry1")
    with open(path, "w") as f:
        f.write(json.dumps({"event": "run_start", "attempt": 2}) + "\n")
    assert [e["event"] for e in tail.poll()] == ["run_start"]


def test_logtail_concurrent_writer_thread(tmp_path):
    """A live serve-style log: a writer thread appends real validated
    events while the supervisor-side tail polls — every event arrives
    exactly once, in order."""
    path = str(tmp_path / "live.events")
    n = 60

    def writer():
        for i in range(n):
            append_event(path, "segment", wall_s=0.01 * i, n_states=i,
                         level=i, n_transitions=i, dedup_hit_rate=0.0,
                         states_per_sec=1.0, inc_states_per_sec=1.0,
                         since_resume=False)
    t = threading.Thread(target=writer)
    t.start()
    tail = _LogTail(path)
    seen = []
    deadline = time.monotonic() + 20.0
    while len(seen) < n and time.monotonic() < deadline:
        seen.extend(e["n_states"] for e in tail.poll()
                    if e.get("event") == "segment")
        time.sleep(0.002)
    t.join()
    seen.extend(e["n_states"] for e in tail.poll()
                if e.get("event") == "segment")
    assert seen == list(range(n))


# --------------------------------------------------------------------------
# pool end-to-end: parity under SIGKILL, poison quarantine, OOM
# degradation, drain, budgets, restart dedup


def test_pool_parity_under_worker_sigkill(tmp_path):
    """The acceptance bar: SIGKILL a worker mid-dispatch; every job
    still completes and both the results records and tenant event logs
    are canonically identical to an unsupervised solo run_service."""
    cfg = write_cfg(tmp_path / "toy.cfg")
    jobs = _jobs(cfg, ["j0", "j1", "j2", "j3"])
    ref = {r["job_id"]: r
           for r in run_service(jobs, str(tmp_path / "ref"),
                                chunk=256, quiet=True)}
    chaos = PoolChaos(kill_after_events=2)
    recs = run_pool(jobs, str(tmp_path / "pool"), workers=2, chunk=256,
                    cpu=True, quiet=True, policy=FAST,
                    spawn_hook=chaos.spawn_hook)
    assert chaos.kills and chaos.kills[0][1] == "kill-after-events"
    by = {r["job_id"]: r for r in recs}
    for job in jobs:
        jid = job.job_id
        assert by[jid]["status"] == "completed"
        assert canon_record(ref[jid]) == canon_record(by[jid])
        assert canon_events(str(tmp_path / "ref" / f"{jid}.events")) \
            == canon_events(str(tmp_path / "pool" / f"{jid}.events"))
    # supervision telemetry: a spawn per worker, one loss, retries
    pool_events = [json.loads(l) for l in
                   open(tmp_path / "pool" / "pool.events")]
    kinds = [e["event"] for e in pool_events]
    assert kinds.count("worker_lost") >= 1
    assert "job_retry" in kinds and "quarantine" not in kinds


def test_pool_poison_bisection_quarantine(tmp_path):
    """A job that kills every worker it rides is bisected to, blamed,
    and quarantined after <= K deaths — with attributed quarantine
    records — while its innocent cellmates complete normally."""
    cfg = write_cfg(tmp_path / "toy.cfg")
    jobs = _jobs(cfg, ["i0", "poison", "i2"], alternate=False)  # one bin
    out = str(tmp_path / "out")
    K = 2
    chaos = PoolChaos(poison="poison")
    recs = run_pool(jobs, out, workers=2, chunk=256, cpu=True,
                    quiet=True,
                    policy=PoolPolicy(poll_s=0.02, backoff_base_s=0.05,
                                      backoff_cap_s=0.2,
                                      backoff_jitter_seed=7,
                                      max_job_deaths=K),
                    spawn_hook=chaos.spawn_hook)
    by = {r["job_id"]: r for r in recs}
    assert by["poison"]["status"] == "quarantined"
    assert by["poison"]["reason"] == "poison-job"
    assert by["poison"]["deaths"] <= K
    assert record_is_terminal(by["poison"])      # never re-run, ever
    assert by["i0"]["status"] == by["i2"]["status"] == "completed"
    assert by["i0"]["n_states"] == by["i2"]["n_states"] == 524
    # the poison died exactly K times and was never dispatched after
    # its quarantine
    assert len(chaos.kills) == K
    pool_events = [json.loads(l) for l in open(os.path.join(
        out, "pool.events"))]
    q = [e for e in pool_events if e["event"] == "quarantine"]
    assert len(q) == 1 and q[0]["job_id"] == "poison"
    spawns_with_poison = [e for e in pool_events
                          if e["event"] == "worker_spawn"
                          and "poison" in e["jobs"]]
    assert len(spawns_with_poison) == K
    q_idx = pool_events.index(q[0])
    assert all(pool_events.index(e) < q_idx for e in spawns_with_poison)
    # tenant-log attribution: the quarantined tenant's log ends with
    # an explicit stop + quarantined outcome, not silence
    ev = [json.loads(l) for l in open(os.path.join(out,
                                                   "poison.events"))]
    assert ev[-1]["event"] == "run_end"
    assert ev[-1]["outcome"] == "quarantined"
    assert any(e["event"] == "stop_requested"
               and "quarantined" in e["reason"] for e in ev)


def test_pool_oom_respawns_with_halved_chunk(tmp_path, monkeypatch):
    """An OOM-classified death takes no blame: the same group respawns
    at half dispatch width and completes."""
    cfg = write_cfg(tmp_path / "toy.cfg")
    jobs = _jobs(cfg, ["a", "b"], alternate=False)
    out = str(tmp_path / "out")
    monkeypatch.setattr(supervise, "classify_death",
                        lambda rc, out_text="": ("oom", "simulated"))
    killed = []

    def hook(w):
        if not killed:
            killed.append(w.wid)
            w.proc.kill()
    # max_job_deaths=1 proves no blame was assigned: one blamed death
    # would quarantine immediately
    recs = run_pool(jobs, out, workers=1, chunk=256, cpu=True,
                    quiet=True,
                    policy=PoolPolicy(poll_s=0.02, backoff_base_s=0.05,
                                      backoff_cap_s=0.2,
                                      backoff_jitter_seed=7,
                                      max_job_deaths=1, min_chunk=32),
                    spawn_hook=hook)
    by = {r["job_id"]: r for r in recs}
    assert by["a"]["status"] == by["b"]["status"] == "completed"
    pool_events = [json.loads(l) for l in open(os.path.join(
        out, "pool.events"))]
    spawns = [e for e in pool_events if e["event"] == "worker_spawn"]
    assert [e["chunk"] for e in spawns] == [256, 128]    # degraded
    assert sorted(spawns[0]["jobs"]) == sorted(spawns[1]["jobs"])
    assert not [e for e in pool_events if e["event"] == "quarantine"]
    retries = [e for e in pool_events if e["event"] == "job_retry"]
    assert retries and all(e["reason"] == "oom" for e in retries)


def test_pool_drain_attributes_undispatched_jobs(tmp_path):
    """stop() truthy before any spawn: no workers start, every admitted
    job gets an attributed stopped record and a non-silent event log."""
    cfg = write_cfg(tmp_path / "toy.cfg")
    jobs = _jobs(cfg, ["a", "b"])
    out = str(tmp_path / "out")
    recs = run_pool(jobs, out, workers=2, cpu=True, quiet=True,
                    policy=FAST, stop=lambda: True)
    assert len(recs) == 2
    for r in recs:
        assert r["status"] == "stopped"
        assert "never reached a worker" in r["error"]
        assert not record_is_terminal(r)         # a restart may retry
        ev = [json.loads(l) for l in open(r["events"])]
        assert ev[-1]["event"] == "run_end"
        assert ev[-1]["outcome"] == "stopped"


def test_pool_gives_up_when_respawn_budget_exhausts(tmp_path,
                                                    monkeypatch):
    """A systematically dying fleet must exhaust the bounded respawn
    budget and stop with attribution, not retry forever."""
    cfg = write_cfg(tmp_path / "toy.cfg")
    jobs = _jobs(cfg, ["a"], alternate=False)
    out = str(tmp_path / "out")

    def hook(w):                                 # every worker dies
        w.proc.kill()
    recs = run_pool(jobs, out, workers=1, chunk=256, cpu=True,
                    quiet=True,
                    policy=PoolPolicy(poll_s=0.02, backoff_base_s=0.02,
                                      backoff_cap_s=0.05,
                                      backoff_jitter_seed=7,
                                      max_job_deaths=99,
                                      max_respawns=2),
                    spawn_hook=hook)
    assert recs[0]["status"] == "stopped"
    assert "pool gave up" in recs[0]["error"]
    pool_events = [json.loads(l) for l in open(os.path.join(
        out, "pool.events"))]
    spawns = [e for e in pool_events if e["event"] == "worker_spawn"]
    assert len(spawns) == 3                      # initial + 2 respawns


def test_wall_budget_stops_lane_losslessly(tmp_path):
    """wall_s -> a terminal budget-exceeded stop at a level boundary;
    the cellmate lane is untouched."""
    cfg = write_cfg(tmp_path / "toy.cfg")
    jobs = [CheckJob("fast", OPTS, cfg_path=str(cfg)),
            CheckJob("capped", JobOptions(spec="election", max_term=2,
                                          max_log=0, max_msgs=1,
                                          wall_s=1e-4),
                     cfg_path=str(cfg))]
    recs = run_service(jobs, str(tmp_path / "out"), chunk=256,
                       quiet=True)
    by = {r["job_id"]: r for r in recs}
    assert by["fast"]["status"] == "completed"
    assert by["fast"]["n_states"] == 524
    assert by["capped"]["status"] == "stopped"
    assert by["capped"]["error"].startswith("budget-exceeded")
    assert record_is_terminal(by["capped"])      # restart will NOT rerun


def test_daemon_restart_skips_terminal_digests(tmp_path):
    """Daemon restart dedup: a queue job whose content digest already
    has a terminal record is not re-run (and not re-billed)."""
    q = tmp_path / "q"
    q.mkdir()
    write_cfg(q / "toy.cfg")
    (q / "001-a.json").write_text(json.dumps(
        {"id": "a", "cfg": "toy.cfg", "spec": "election",
         "max_term": 2, "max_log": 0, "max_msgs": 1}))
    out = str(tmp_path / "out")
    assert run_daemon(str(q), out, chunk=256, quiet=True, poll_s=0.05,
                      max_idle_polls=2) == 0
    first = read_results(out)
    assert [r["status"] for r in first] == ["completed"]
    # restart: same queue, same digest -> zero new records
    assert run_daemon(str(q), out, chunk=256, quiet=True, poll_s=0.05,
                      max_idle_polls=2) == 0
    assert read_results(out) == first
