"""report.py — rendering, counts, and exit-code policy.

The Finding type is the one contract all five passes share, so its
formatting and the error/warning exit split get their own tests: every
pass's output goes through format()/render(), and the CLI's exit code
is exactly exit_code(findings, strict).
"""

from __future__ import annotations

import pytest

from raft_tla_tpu.analysis.report import (
    CFG, CONTRACT, ERROR, JIT, THREAD, WARNING, WIDTH, Finding,
    exit_code, has_errors, render)

pytestmark = pytest.mark.smoke


def test_pass_ids_are_stable():
    # waiver lists and the CLI's --skip choices key off these strings
    assert (WIDTH, CFG, JIT, THREAD, CONTRACT) == (
        "width", "cfg", "jit", "thread", "contract")


def test_format_width_proof_fields():
    f = Finding(WIDTH, ERROR, "width-overflow", "votes can exceed field",
                transition="HandleRequestVoteResponse", field="votes",
                interval=(0, 9), width=3)
    txt = f.format()
    assert txt.startswith("error[width-overflow]: votes can exceed field")
    # the acceptance contract: all four proof obligations in one line
    assert "transition=HandleRequestVoteResponse" in txt
    assert "field=votes" in txt
    assert "interval=[0, 9]" in txt
    assert "width=3" in txt


def test_format_source_location():
    f = Finding(THREAD, ERROR, "unguarded-shared-mutation", "race",
                file="raft_tla_tpu/obs/phases.py", line=42)
    assert f.format() == ("raft_tla_tpu/obs/phases.py:42: "
                          "error[unguarded-shared-mutation]: race")


def test_format_file_without_line():
    f = Finding(CONTRACT, ERROR, "gate-in-digest", "gate leaked",
                file="raft_tla_tpu/utils/ckpt.py")
    assert f.format().startswith("raft_tla_tpu/utils/ckpt.py: error")


def test_format_no_location_no_context():
    f = Finding(CFG, WARNING, "vacuous-invariant", "always true")
    assert f.format() == "warning[vacuous-invariant]: always true"
    assert "(" not in f.format()


def test_render_counts_and_header():
    findings = [
        Finding(JIT, WARNING, "traced-python-if", "hazard", file="a.py",
                line=1),
        Finding(THREAD, ERROR, "unguarded-shared-mutation", "race",
                file="b.py", line=2),
        Finding(CONTRACT, ERROR, "gate-no-smoke", "unwired gate"),
    ]
    out = render(findings, header="speclint: toy.cfg")
    lines = out.splitlines()
    assert lines[0] == "speclint: toy.cfg"
    assert len(lines) == 5                      # header + 3 findings + tally
    assert lines[-1] == "2 error(s), 1 warning(s)"


def test_render_empty_is_just_the_tally():
    assert render([]) == "0 error(s), 0 warning(s)"
    assert render([], header="h") == "h\n0 error(s), 0 warning(s)"


def test_has_errors():
    warn = Finding(JIT, WARNING, "set-iteration", "w")
    err = Finding(WIDTH, ERROR, "width-overflow", "e")
    assert not has_errors([])
    assert not has_errors([warn])
    assert has_errors([warn, err])


def test_exit_code_policy():
    warn = Finding(JIT, WARNING, "set-iteration", "w")
    err = Finding(CONTRACT, ERROR, "stale-waiver", "e")
    # errors always fail
    assert exit_code([err]) == 1
    assert exit_code([err], strict=True) == 1
    # warnings fail only under --strict
    assert exit_code([warn]) == 0
    assert exit_code([warn], strict=True) == 1
    # clean is clean either way
    assert exit_code([]) == 0
    assert exit_code([], strict=True) == 0


def test_findings_are_frozen_and_hashable():
    # passes dedupe and set-ify findings; the dataclass must stay frozen
    f = Finding(CFG, ERROR, "unknown-name", "x")
    with pytest.raises(Exception):
        f.severity = WARNING
    assert len({f, Finding(CFG, ERROR, "unknown-name", "x")}) == 1
