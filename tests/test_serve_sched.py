"""Pipelined dispatch scheduler (serve/sched.py).

The async scheduler must change WHEN work runs, never WHAT it computes:
depth=2 interleaving with async compiles must leave every completing
lane's results and per-tenant event stream identical to the depth=1
sequential baseline (the PR 6 Engine-verbatim invariant, extended).
Fair-share packing must bound starvation under oversubscription, the
background compile must actually run off-thread, and the daemon's
drain hook must give every accepted lane an attributed terminal record.
"""

import json
import os
import signal
import subprocess
import sys
import time
import types

import pytest

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.engine import Engine
from raft_tla_tpu.serve import CheckJob, JobOptions
from raft_tla_tpu.serve.batch import BatchExecutor
from raft_tla_tpu.serve.sched import DispatchScheduler
from raft_tla_tpu.serve.service import run_service

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(**kw):
    b = dict(n_servers=2, n_values=1, max_term=2, max_log=0, max_msgs=2)
    sym = kw.pop("symmetry", ())
    b.update(kw)
    return CheckConfig(bounds=Bounds(**b), spec="election",
                       invariants=("NoTwoLeaders",), symmetry=sym,
                       chunk=256)


TOY_M1 = _cfg(max_msgs=1)               # 524 states
TOY_M1S = _cfg(max_msgs=1, symmetry=("Server",))
TOY = _cfg()                            # 3,014 states
TOY_SYM = _cfg(symmetry=("Server",))


# --------------------------------------------------------------------------
# interleaved vs sequential: byte-identical tenant artifacts

_CFG_TEXT = """SPECIFICATION Spec
INVARIANT NoTwoLeaders
CONSTANTS
    Server = {s1, s2}
    Value = {v1}
    Follower = "Follower"
    Candidate = "Candidate"
    Leader = "Leader"
    Nil = "Nil"
    RequestVoteRequest = "RequestVoteRequest"
    RequestVoteResponse = "RequestVoteResponse"
    AppendEntriesRequest = "AppendEntriesRequest"
    AppendEntriesResponse = "AppendEntriesResponse"
"""

# 16 jobs over 4 step-signature bins, all completing (all-completing is
# what makes full byte-parity well-defined: a lane that *violates* mid-
# pipeline changes later slice boundaries — its guarantee is verdict and
# trace, covered by test_serve.py).
_MANIFEST = ([(f"m1-{i}", dict(max_msgs=1)) for i in range(6)]
             + [(f"m1s-{i}", dict(max_msgs=1, symmetry=True))
                for i in range(4)]
             + [(f"m2-{i}", dict()) for i in range(4)]
             + [(f"m2s-{i}", dict(symmetry=True)) for i in range(2)])

# Everything that varies run-to-run without changing WHAT was computed:
# wall-clock, rates, and the pipeline-occupancy annotation itself.
_VOLATILE = frozenset({"ts", "wall_s", "states_per_sec",
                       "inc_states_per_sec", "admission_s", "inflight",
                       "phase_s", "pid", "git_sha", "anchor"})


def _jobs():
    return [CheckJob(jid, JobOptions(spec="election", max_term=2,
                                     max_log=0,
                                     max_msgs=kw.get("max_msgs", 2),
                                     symmetry=kw.get("symmetry", False)),
                     cfg_text=_CFG_TEXT)
            for jid, kw in _MANIFEST]


def _scrub(d):
    return {k: v for k, v in d.items() if k not in _VOLATILE}


@pytest.mark.smoke
def test_interleaved_matches_sequential_byte_for_byte(tmp_path):
    """The tentpole invariant: depth=2 + async compiles vs the depth=1
    sequential baseline on the 16-job/4-bin manifest — every tenant's
    results.jsonl record and full event stream identical modulo
    timing-only fields."""
    out_seq = run_service(_jobs(), str(tmp_path / "seq"), chunk=256,
                          quiet=True, depth=1, compile_async=False)
    out_int = run_service(_jobs(), str(tmp_path / "int"), chunk=256,
                          quiet=True, depth=2, compile_async=True)
    seq = {r["job_id"]: r for r in out_seq}
    inter = {r["job_id"]: r for r in out_int}
    assert set(seq) == set(inter) == {jid for jid, _ in _MANIFEST}
    for jid in seq:
        a, b = dict(seq[jid]), dict(inter[jid])
        ea, eb = a.pop("events"), b.pop("events")
        assert _scrub(a) == _scrub(b), jid
        assert a["status"] == "completed", jid
        evs_a = [_scrub(json.loads(l)) for l in open(ea)]
        evs_b = [_scrub(json.loads(l)) for l in open(eb)]
        assert evs_a == evs_b, jid

    # and the depth=2 arm really pipelined + compiled off-thread
    # (scheduler stats ride on the records only via the event logs, so
    # re-run one executor directly to read them; chunk 64 makes the
    # 3,014-state levels span several dispatches, so the speculative
    # same-bin path must fill the pipeline)
    ex = BatchExecutor(chunk=64, depth=2, compile_async=True)
    out = ex.run([("a", TOY), ("b", TOY_SYM)])
    assert all(oc.status == "completed" for oc in out.values())
    assert ex.last_stats["peak_inflight"] >= 2
    assert ex.last_stats["async_compiles"] == 2


def test_executor_parity_vs_solo_all_depths():
    """Counts parity vs solo Engine at depth 1, 2 and 3 — the per-lane
    chunk semantics must be depth-invariant, not just depth-2-correct."""
    solo = {jid: Engine(cfg).check()
            for jid, cfg in [("a", TOY_M1), ("s", TOY_M1S)]}
    for depth in (1, 2, 3):
        out = BatchExecutor(chunk=128, depth=depth).run(
            [("a", TOY_M1), ("s", TOY_M1S)])
        for jid, ref in solo.items():
            got = out[jid].result
            assert out[jid].status == "completed", (depth, jid)
            assert got.n_states == ref.n_states, (depth, jid)
            assert got.diameter == ref.diameter, (depth, jid)
            assert got.n_transitions == ref.n_transitions, (depth, jid)
            assert list(got.levels) == list(ref.levels), (depth, jid)
            assert dict(got.coverage) == dict(ref.coverage), (depth, jid)


def test_depth_validation():
    with pytest.raises(ValueError, match="depth"):
        DispatchScheduler(chunk=64, depth=0)


# --------------------------------------------------------------------------
# fair-share deficit round robin: starvation bound


class _StubLane:
    def __init__(self, jid, pending):
        self.job_id = jid
        self._pending = pending

    def pending_rows(self):
        return self._pending


def _drive(chunk, lanes, dispatches):
    """Run _plan_takes repeatedly, applying takes; returns per-dispatch
    served-lane sets."""
    sched = DispatchScheduler(chunk=chunk, depth=1, compile_async=False)
    st = types.SimpleNamespace(rr=0, deficit={})
    served = []
    for _ in range(dispatches):
        live = [ln for ln in lanes if ln.pending_rows() > 0]
        if not live:
            break
        plan = sched._plan_takes(st, live)
        assert sum(t for _ln, t in plan) <= chunk
        for ln, t in plan:
            assert 0 < t <= ln.pending_rows()
            ln._pending -= t
        served.append({ln.job_id for ln, _t in plan})
    return served


def test_drr_starvation_bound_oversubscribed():
    """16 lanes on an 4-row chunk: every pending lane must ride within
    any ceil(n/B) = 4 consecutive dispatches, and every dispatch must
    be full (work-conserving) while work remains."""
    B, n = 4, 16
    lanes = [_StubLane(f"l{i}", 40) for i in range(n)]
    served = _drive(B, lanes, 40)
    window = -(-n // B)
    for w0 in range(len(served) - window + 1):
        rode = set().union(*served[w0:w0 + window])
        assert rode == {f"l{i}" for i in range(n)}, \
            f"lane starved in window starting at dispatch {w0}"
    # full chunks while every lane still had pending rows
    assert all(len(s) == B for s in served[:n // B * 2])


def test_drr_undersubscribed_every_lane_every_dispatch():
    """B >= n: every pending lane rides every dispatch and leftover
    space backfills to the deeper frontiers (chunk stays full)."""
    B = 64
    lanes = [_StubLane("big", 1000), _StubLane("small", 3),
             _StubLane("mid", 100)]
    served = _drive(B, lanes, 1)
    assert served[0] == {"big", "small", "mid"}
    # 3 quantum-21 grants cover small's 3 rows; backfill fills the rest
    taken = 1000 + 3 + 100 - sum(ln.pending_rows() for ln in lanes)
    assert taken == B


def test_drr_skips_exhausted_lane_without_deficit_leak():
    """A lane with no pending rows accrues no deficit and is skipped;
    when it refills it gets the normal quantum, not a hoarded burst."""
    sched = DispatchScheduler(chunk=8, depth=1, compile_async=False)
    st = types.SimpleNamespace(rr=0, deficit={})
    idle = _StubLane("idle", 0)
    busy = _StubLane("busy", 100)
    for _ in range(5):
        plan = sched._plan_takes(st, [busy])
        for ln, t in plan:
            ln._pending -= t
    assert st.deficit.get("idle", 0) == 0
    idle._pending = 100
    plan = dict((ln.job_id, t)
                for ln, t in sched._plan_takes(st, [idle, busy]))
    assert plan["idle"] <= 8              # quantum+backfill, no hoard


# --------------------------------------------------------------------------
# daemon drain: every accepted lane reaches an attributed record


def test_executor_stop_drains_with_attribution(tmp_path):
    """The daemon's stop hook: a stop signal that turns on mid-run must
    leave every lane either completed or failed with the drain
    attribution — never silent."""
    calls = {"n": 0}

    def stop():
        calls["n"] += 1
        return calls["n"] > 4            # trip after a few dispatches

    ex = BatchExecutor(chunk=64, depth=2, stop=stop)
    out = ex.run([("a", TOY), ("b", TOY_SYM)])
    assert set(out) == {"a", "b"}
    for oc in out.values():
        assert oc.status in ("completed", "stopped")
        if oc.status == "stopped":
            assert "stop requested (drain)" in oc.error
            assert oc.result.complete is False
    assert any(oc.status == "stopped" for oc in out.values())


@pytest.mark.smoke
def test_daemon_watch_sigint_drain(tmp_path):
    """End-to-end daemon: file intake from a watched queue dir, results
    appear while the daemon stays up, SIGINT exits 0 (lossless drain),
    and a duplicate job id is rejected without touching the original
    tenant's artifacts."""
    qdir, out = tmp_path / "q", tmp_path / "out"
    qdir.mkdir()
    job = {"id": "watched", "cfg_text": _CFG_TEXT, "spec": "election",
           "max_term": 2, "max_log": 0, "max_msgs": 1}
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    proc = subprocess.Popen(
        [sys.executable, "-m", "raft_tla_tpu.serve", str(qdir),
         "--watch", "--out", str(out), "--chunk", "64", "--poll", "0.2",
         "--cpu", "--quiet"],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    try:
        (qdir / "001-a.json").write_text(json.dumps(job))

        def records():
            p = out / "results.jsonl"
            if not p.exists():
                return []
            return [json.loads(l) for l in p.read_text().splitlines()]

        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            if any(r["job_id"] == "watched" for r in records()):
                break
            assert proc.poll() is None, proc.communicate()
            time.sleep(0.3)
        recs = {r["job_id"]: r for r in records()}
        assert recs["watched"]["status"] == "completed"
        assert recs["watched"]["n_states"] == 524

        # duplicate id in a NEW file: rejected, original artifacts intact
        (qdir / "002-dup.json").write_text(json.dumps(job))
        while time.monotonic() < deadline:
            recs = [r for r in records() if r["job_id"] == "watched"]
            if len(recs) == 2:
                break
            time.sleep(0.3)
        dups = [r for r in records()
                if r["job_id"] == "watched" and r["status"] == "rejected"]
        assert dups and dups[0]["reason"] == "duplicate-id"
        done = [r for r in records()
                if r["job_id"] == "watched" and r["status"] == "completed"]
        assert len(done) == 1            # the original record, untouched

        proc.send_signal(signal.SIGINT)
        code = proc.wait(timeout=60)
        assert code == 0, proc.communicate()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
