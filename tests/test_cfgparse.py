"""The cfg parser must accept the reference's raft.cfg byte-for-byte."""

import pathlib

import pytest

from raft_tla_tpu.utils.cfgparse import parse_cfg, load_cfg

REF_CFG = pathlib.Path("/root/reference/raft.cfg")


def test_reference_cfg_parses():
    cfg = load_cfg(str(REF_CFG))
    assert cfg.specification == "Spec"
    assert cfg.invariants == ["NoTwoLeaders"]
    assert cfg.server_names() == ["s1", "s2", "s3"]
    assert cfg.value_names() == ["v1", "v2"]
    # Model values (raft.cfg:8-15)
    assert cfg.constants["Follower"] == "Follower"
    assert cfg.constants["Nil"] == "Nil"
    assert cfg.constants["AppendEntriesResponse"] == "AppendEntriesResponse"


def test_constraint_and_plural_stanzas():
    cfg = parse_cfg(
        """
SPECIFICATION Spec
INVARIANTS A B
CONSTRAINT StateConstraint
CONSTANTS
    Server = {s1, s2}
    Nil = Nil
"""
    )
    assert cfg.invariants == ["A", "B"]
    assert cfg.constraints == ["StateConstraint"]
    assert cfg.server_names() == ["s1", "s2"]


def test_comments_stripped():
    cfg = parse_cfg("CONSTANTS\n  Server = {a, b, c} \\* three nodes\n")
    assert cfg.server_names() == ["a", "b", "c"]


def test_junk_rejected():
    with pytest.raises(ValueError):
        parse_cfg("NOT_A_STANZA foo\n")
