"""Paged + sharded composition engine (parallel/paged_shard_engine.py).

The VERDICT r1 next#6 gates: exploration-metric parity with the oracle on
the virtual 8-device mesh, and a space whose live BFS window OVERFLOWS a
single device's ring completing on the mesh (each device holds ~1/ndev of
every level).
"""

import numpy as np
import pytest

# needs the virtual multi-device mesh — the slowest compiles on
# this 1-core host, excluded from the time-boxed tier-1 window
# (-m 'not slow'); the shard family stays exercised via -m smoke.
pytestmark = pytest.mark.slow

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.models import interp, refbfs
from raft_tla_tpu.parallel.paged_shard_engine import (
    PagedShardCapacities, PagedShardEngine)
from raft_tla_tpu.parallel.shard_engine import make_mesh

CFG = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                max_log=0, max_msgs=2),
                  spec="election", invariants=("NoTwoLeaders",), chunk=32)
CAPS = PagedShardCapacities(ring=4096, table=1 << 14, levels=64)


def test_parity_with_oracle_8dev():
    ref = refbfs.check(CFG)
    got = PagedShardEngine(CFG, make_mesh(8), CAPS).check()
    assert got.n_states == ref.n_states == 3014
    assert got.diameter == ref.diameter == 17
    assert got.levels == ref.levels
    assert got.n_transitions == ref.n_transitions
    # attribution is interleaving-dependent; totals must match exactly
    assert sum(got.coverage.values()) == sum(ref.coverage.values())
    assert got.violation is None


def test_mesh_size_invariance():
    base = PagedShardEngine(CFG, make_mesh(1), CAPS).check()
    for n in (2, 8):
        r = PagedShardEngine(CFG, make_mesh(n), CAPS).check()
        assert r.n_states == base.n_states, n
        assert r.levels == base.levels, n
        assert r.n_transitions == base.n_transitions, n


def test_window_overflowing_single_ring_completes_on_mesh():
    """The composition's reason to exist: the 3-server election space's
    widest level pair does not fit a 8192-row ring on one device
    (FAIL_RING, loudly), but the 8-device mesh holds ~1/8 per device and
    completes with oracle-exact counts."""
    cfg = CheckConfig(bounds=Bounds(n_servers=3, n_values=1, max_term=2,
                                    max_log=0, max_msgs=1),
                      spec="election",
                      invariants=("NoTwoLeaders",), chunk=64)
    caps = PagedShardCapacities(ring=8192, table=1 << 17, levels=64)
    with pytest.raises(RuntimeError, match="ring"):
        PagedShardEngine(cfg, make_mesh(1), caps).check()
    got = PagedShardEngine(cfg, make_mesh(8), caps).check()
    assert got.n_states == 142538
    assert got.diameter == 31


def test_violation_trace_replays():
    """Seeded NaiveNoTwoLeaders violation (same seed as the shard-engine
    test): the trace walks the per-device host stores across devices and
    must replay through the interpreter."""
    from raft_tla_tpu.models import invariants as inv_mod
    from raft_tla_tpu.models import spec as S
    from raft_tla_tpu.ops import msgbits as mb

    bounds = Bounds(n_servers=3, n_values=1, max_term=3, max_log=0,
                    max_msgs=4, max_dup=1)
    cfg = CheckConfig(bounds=bounds, spec="election",
                      invariants=("NaiveNoTwoLeaders",), chunk=256)
    start = interp.init_state(bounds)._replace(
        role=(S.LEADER, S.FOLLOWER, S.CANDIDATE),
        term=(2, 3, 3),
        votedFor=(1, 3, 0),
        vGrant=(0b011, 0, 0b100),
        msgs=tuple(sorted((m, 1) for m in
                          (mb.rv_response(3, 1, 1, 2),))),
    )
    caps = PagedShardCapacities(ring=1 << 16, table=1 << 17, levels=64)
    got = PagedShardEngine(cfg, make_mesh(8), caps).check(
        init_override=start)
    assert got.violation is not None
    assert got.violation.invariant == "NaiveNoTwoLeaders"
    trace = got.violation.trace
    assert trace[0][0] is None and trace[0][1] == start
    for (_l, prev), (_label, cur) in zip(trace, trace[1:]):
        succs = [t for _i, t in interp.successors(prev, bounds,
                                                  spec="election")]
        assert cur in succs
    assert not inv_mod.py_invariant("NaiveNoTwoLeaders")(
        got.violation.state, bounds)


def test_checkpoint_resume_bit_exact(tmp_path):
    ck = str(tmp_path / "ps.ckpt")

    def eng():
        e = PagedShardEngine(CFG, make_mesh(8), CAPS, seg_chunks=8)
        e.SEG_MAX = 8
        return e

    straight = eng().check()
    res = eng().check(checkpoint=ck, checkpoint_every_s=0.0)
    assert res.n_states == straight.n_states
    resumed = eng().check(resume=ck)
    assert resumed.n_states == straight.n_states
    assert resumed.levels == straight.levels
    assert resumed.n_transitions == straight.n_transitions
    assert resumed.violation is None
    # mesh size is pinned by the digest (FP ownership depends on it)
    with pytest.raises(ValueError, match="checkpoint"):
        e4 = PagedShardEngine(CFG, make_mesh(4), CAPS, seg_chunks=8)
        e4.check(resume=ck)


def test_symmetry_composes():
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=("NoTwoLeaders",),
                      symmetry=("Server",), chunk=32)
    ref = refbfs.check(cfg)
    got = PagedShardEngine(cfg, make_mesh(8), CAPS).check()
    assert got.n_states == ref.n_states == 1514     # orbits, not states
    assert got.diameter == ref.diameter


def test_slice_mesh_2x4_parity():
    """2-D (dcn, ici) mesh with the hierarchical two-stage bit-packed
    exchange: identical exploration metrics to the oracle."""
    from raft_tla_tpu.config import Bounds, CheckConfig
    from raft_tla_tpu.models import refbfs
    from raft_tla_tpu.parallel.paged_shard_engine import (
        PagedShardCapacities, PagedShardEngine)
    from raft_tla_tpu.parallel.shard_engine import make_slice_mesh

    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=("NoTwoLeaders",),
                      chunk=64)
    ref = refbfs.check(cfg)
    got = PagedShardEngine(cfg, make_slice_mesh(2, 4), PagedShardCapacities(
        ring=4096, table=1 << 14, levels=64)).check()
    assert got.n_states == ref.n_states
    assert got.diameter == ref.diameter
    assert got.levels == ref.levels
    assert got.n_transitions == ref.n_transitions
    assert sum(got.coverage.values()) == sum(ref.coverage.values())
    assert got.violation is None
