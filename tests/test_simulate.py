"""Simulation mode (TLC -simulate): batched random behaviors on device.

Random walks from Init with invariants checked on every generated state;
violating walks replay exactly through the reference interpreter.
"""

import pytest

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.engine import DEADLOCK
from raft_tla_tpu.models import interp, spec as S
from raft_tla_tpu.ops import msgbits as mb
from raft_tla_tpu.simulate import Simulator


def bag(*ms):
    return tuple(sorted((m, 1) for m in ms))


B3 = Bounds(n_servers=3, n_values=1, max_term=3, max_log=0, max_msgs=4)
CV = CheckConfig(bounds=B3, spec="election",
                 invariants=("NaiveNoTwoLeaders",))


def seeded_start():
    """Two steps from a NaiveNoTwoLeaders violation (engine-test seed)."""
    return interp.init_state(B3)._replace(
        role=(S.LEADER, S.FOLLOWER, S.CANDIDATE),
        term=(2, 3, 3), votedFor=(1, 3, 0),
        vGrant=(0b011, 0, 0b100), msgs=bag(mb.rv_response(3, 1, 1, 2)))


def test_clean_run_counts_behaviors():
    cc = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                   max_log=1, max_msgs=2),
                     spec="full", invariants=("NoTwoLeaders",))
    sim = Simulator(cc, walkers=128, depth=40, steps_per_dispatch=32, seed=1)
    r = sim.run(500)
    assert r.violation is None
    assert r.n_behaviors >= 500
    assert r.n_states >= r.n_behaviors          # every behavior took steps
    assert 0 < r.max_depth_seen <= 40


def test_finds_violation_and_trace_replays():
    sim = Simulator(CV, walkers=256, depth=20, steps_per_dispatch=16, seed=3)
    r = sim.run(100000, init_override=seeded_start())
    assert r.violation is not None
    assert r.violation.invariant == "NaiveNoTwoLeaders"
    tab = S.action_table(B3, "election")
    cur = r.violation.trace[0][1]
    for label, nxt in r.violation.trace[1:]:
        assert nxt in {t for _a, t in interp.successors(cur, B3, tab)}, label
        cur = nxt
    assert sum(1 for x in cur.role if x == S.LEADER) >= 2
    assert cur == r.violation.state


def test_same_seed_same_walks():
    mk = lambda: Simulator(CV, walkers=64, depth=16,        # noqa: E731
                           steps_per_dispatch=8, seed=7)
    r1 = mk().run(2000, init_override=seeded_start())
    r2 = mk().run(2000, init_override=seeded_start())
    assert r1.violation is not None and r2.violation is not None
    assert r1.violation.trace == r2.violation.trace
    assert (r1.n_behaviors, r1.n_states) == (r2.n_behaviors, r2.n_states)


def test_simulation_deadlock():
    """1-server election: every walk runs into the sole-leader dead end."""
    cd = CheckConfig(bounds=Bounds(n_servers=1, n_values=1, max_term=2,
                                   max_log=0, max_msgs=1),
                     spec="election", invariants=(), check_deadlock=True)
    r = Simulator(cd, walkers=32, depth=30, steps_per_dispatch=16,
                  seed=0).run(1000)
    assert r.violation is not None and r.violation.invariant == DEADLOCK
    # the trace ends at a state with no successors
    tab = S.action_table(cd.bounds, "election")
    assert not list(interp.successors(r.violation.state, cd.bounds, tab))


def test_without_deadlock_flag_walks_reset():
    cd = CheckConfig(bounds=Bounds(n_servers=1, n_values=1, max_term=2,
                                   max_log=0, max_msgs=1),
                     spec="election", invariants=())
    r = Simulator(cd, walkers=32, depth=30, steps_per_dispatch=16,
                  seed=0).run(200)
    assert r.violation is None and r.n_behaviors >= 200


def test_symmetry_rejected():
    with pytest.raises(ValueError, match="SYMMETRY"):
        Simulator(CheckConfig(bounds=B3, spec="election", invariants=(),
                              symmetry=("Server",)))


def test_cli_simulate(tmp_path):
    from test_cli import run_cli, write_cfg
    from raft_tla_tpu import check as cli
    cfg = write_cfg(tmp_path / "s.cfg")
    code, out = run_cli(cfg, "--engine", "ref", "--spec", "election",
                        "--max-term", "2", "--max-log", "0",
                        "--max-msgs", "2", "--simulate", "300",
                        "--depth", "25", "--walkers", "64", "--seed", "5")
    assert code == cli.EXIT_OK
    assert "behaviors generated" in out and "not exhaustive" in out


def test_simulation_composes_with_faithful_mode():
    """build_expand carries the history fields, so random walks generate
    and invariant-check faithful states unchanged."""
    bh = Bounds(n_servers=2, n_values=1, max_term=2, max_log=1, max_msgs=2,
                history=True, max_elections=4)
    cc = CheckConfig(bounds=bh, spec="full",
                     invariants=("NoTwoLeaders", "ElectionSafetyHist",
                                 "AllLogsPrefixClosed"))
    r = Simulator(cc, walkers=64, depth=30, steps_per_dispatch=16,
                  seed=2).run(300)
    assert r.violation is None and r.n_behaviors >= 300


def test_simulation_emits_event_log(tmp_path):
    """simulate.py speaks RunTelemetry: a conformant SCHEMA_VERSION=1
    log with per-dispatch segments and an outcome-attributed run_end."""
    import json

    from raft_tla_tpu.obs import validate_event

    path = str(tmp_path / "sim.events")
    cc = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                   max_log=1, max_msgs=2),
                     spec="full", invariants=("NoTwoLeaders",))
    r = Simulator(cc, walkers=128, depth=40, steps_per_dispatch=32,
                  seed=1).run(500, events=path)
    assert r.violation is None
    events = [json.loads(l) for l in open(path)]
    assert not [e for d in events for e in validate_event(d)]
    assert events[0]["event"] == "run_start"
    assert events[0]["engine"] == "simulate"
    assert sum(1 for d in events if d["event"] == "segment") >= 1
    assert events[-1]["event"] == "run_end"
    assert events[-1]["outcome"] == "ok" and events[-1]["complete"]


def test_fused_fetch_matches_legacy():
    """The single fused device_get per dispatch (the sync-storm fix)
    changes transfer count only — never results."""
    mk = lambda fetch: Simulator(CV, walkers=64, depth=16,   # noqa: E731
                                 steps_per_dispatch=8, seed=7,
                                 fetch=fetch)
    rf = mk("fused").run(2000, init_override=seeded_start())
    rl = mk("legacy").run(2000, init_override=seeded_start())
    assert rf.violation is not None
    assert rf.violation.trace == rl.violation.trace
    assert (rf.n_behaviors, rf.n_states, rf.max_depth_seen) == \
        (rl.n_behaviors, rl.n_states, rl.max_depth_seen)


def test_simulate_rejects_unknown_fetch():
    with pytest.raises(ValueError, match="fetch"):
        Simulator(CV, fetch="eager")


def test_twophase_simulation():
    """--simulate is spec-generic now: the twophase model drives the
    same walker engine through its sim surface (satellite of ISSUE 11),
    and violating walks replay through its host interpreter."""
    cc = CheckConfig(bounds=Bounds(n_servers=2, n_values=1),
                     spec="twophase", invariants=("TCConsistent",))
    r = Simulator(cc, walkers=64, depth=20, steps_per_dispatch=10,
                  seed=3).run(200)
    assert r.violation is None and r.n_behaviors >= 200

    bad = CheckConfig(bounds=Bounds(n_servers=2, n_values=1),
                      spec="twophase", invariants=("~(msgCommit = 1)",))
    rv = Simulator(bad, walkers=64, depth=20, steps_per_dispatch=10,
                   seed=3).run(200)
    assert rv.violation is not None
    assert rv.violation.trace[-1][1] == rv.violation.state


def test_cli_twophase_simulate(tmp_path):
    from test_cli import run_cli
    from raft_tla_tpu import check as cli
    cfg = tmp_path / "2pc.cfg"
    cfg.write_text("SPECIFICATION Spec\nCONSTANT RM = {r1, r2}\n"
                   "INVARIANT TCConsistent\n")
    code, out = run_cli(str(cfg), "--engine", "host", "--spec",
                        "twophase", "--simulate", "100", "--depth", "20",
                        "--walkers", "32", "--seed", "3")
    assert code == cli.EXIT_OK
    assert "behaviors generated" in out and "not exhaustive" in out


def test_cli_simulate_rejects_properties(tmp_path):
    from test_cli import run_cli, write_cfg
    from raft_tla_tpu import check as cli
    cfg = write_cfg(tmp_path / "p.cfg", extra="PROPERTY EventuallyLeader\n")
    code, _ = run_cli(cfg, "--engine", "ref", "--spec", "election",
                      "--max-term", "2", "--max-log", "0",
                      "--max-msgs", "2", "--simulate", "10")
    assert code == cli.EXIT_ERROR
