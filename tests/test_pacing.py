"""Shared segment-pacing controller (utils/pacing.py) — the policy every
segmented engine inlined before it was extracted."""

from raft_tla_tpu.utils.pacing import SegmentPacer


def mk(**kw):
    args = dict(seg_chunks=64, lo=16, hi=1 << 16, target_s=8.0,
                clamp_s=25.0)
    args.update(kw)
    return SegmentPacer(**args)


def test_first_dispatch_excluded():
    p = mk()
    assert p.update(40.0, 64) == 64          # compile-carrying: no signal
    assert p.worst_s_per_chunk == 0.0


def test_scales_toward_target():
    p = mk()
    p.update(1.0, 64)                        # first: ignored
    assert p.update(1.0, 64) == 128          # 8x under target -> 2x cap
    assert p.update(32.0, 128) == 32         # 4x over target -> 0.25x floor


def test_watchdog_clamp_uses_worst_chunk_cost():
    p = mk()
    p.update(0.1, 64)
    p.update(8.0, 16)                        # 0.5 s/chunk observed
    # whatever the target scaling wants, 25 s / 0.5 s = 50 chunks max
    assert p.budget <= 50
    p.update(0.1, 64)                        # cheap tail would ramp...
    assert p.budget <= 50                    # ...but the ratchet holds


def test_short_dispatches_carry_no_signal():
    p = mk()
    p.update(1.0, 64)
    b = p.update(1.0, 64)
    assert p.update(0.01, 64) == b


def test_floor_and_zero_budget_guard():
    p = mk(seg_chunks=0)
    assert p.budget == 1                     # never spins forever
    p.update(1.0, 1)
    p.update(100.0, 1)                       # huge chunk cost
    assert p.budget == 16                    # lo floor wins
