"""CLI (check.py), TLC export (models/tla_export.py), and trace rendering.

The CLI is the checker's L6 layer (SURVEY §1): stock cfg in, TLC-style
report out, TLC-compatible exit codes.  No JVM exists here, so the TLC
artifacts are validated structurally and by cfgparse round-trip
(tla_export module docstring).
"""

import io
import re
from contextlib import redirect_stdout

import pytest

from raft_tla_tpu import check as cli
from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.models import refbfs, spec as S, tla_export
from raft_tla_tpu.models import interp
from raft_tla_tpu.ops import msgbits as mb
from raft_tla_tpu.utils import render
from raft_tla_tpu.utils.cfgparse import parse_cfg

REF_CFG = "/root/reference/raft.cfg"



_CFG_CONSTANTS = (
    "CONSTANTS\n"
    "    Server = {%s}\n    Value = {v1}\n"
    '    Follower = "Follower"\n    Candidate = "Candidate"\n'
    '    Leader = "Leader"\n    Nil = "Nil"\n'
    '    RequestVoteRequest = "RequestVoteRequest"\n'
    '    RequestVoteResponse = "RequestVoteResponse"\n'
    '    AppendEntriesRequest = "AppendEntriesRequest"\n'
    '    AppendEntriesResponse = "AppendEntriesResponse"\n')


def write_cfg(path, servers="s1, s2", extra=""):
    path.write_text("SPECIFICATION Spec\nINVARIANT NoTwoLeaders\n"
                    + extra + _CFG_CONSTANTS % servers)
    return str(path)


def run_cli(*argv):
    buf = io.StringIO()
    with redirect_stdout(buf):
        code = cli.main(list(argv))
    return code, buf.getvalue()


def test_cli_ref_engine_pass():
    code, out = run_cli(REF_CFG, "--engine", "ref", "--spec", "election",
                        "--max-term", "2", "--max-log", "0",
                        "--max-msgs", "1", "--coverage")
    assert code == cli.EXIT_OK
    assert "No error has been found" in out
    m = re.search(r"(\d+) distinct states found, diameter (\d+)", out)
    assert m, out
    # same numbers the engines' parity tests pin for this config
    cc = CheckConfig(bounds=Bounds(n_servers=3, n_values=2, max_term=2,
                                   max_log=0, max_msgs=1),
                     spec="election", invariants=("NoTwoLeaders",))
    ref = refbfs.check(cc)
    assert (int(m.group(1)), int(m.group(2))) == (ref.n_states, ref.diameter)
    assert "BecomeLeader" in out          # --coverage section


def test_cli_device_engine_pass():
    code, out = run_cli(REF_CFG, "--engine", "device", "--cpu",
                        "--spec", "election", "--max-term", "2",
                        "--max-log", "0", "--max-msgs", "1",
                        "--cap", str(1 << 18), "--chunk", "256")
    assert code == cli.EXIT_OK and "No error has been found" in out


def test_cli_bad_cfg_and_bad_invariant(tmp_path):
    code, _ = run_cli(str(tmp_path / "missing.cfg"))
    assert code == cli.EXIT_ERROR
    bad = tmp_path / "bad.cfg"
    bad.write_text("SPECIFICATION Spec\nINVARIANT NoSuchThing\nCONSTANTS\n"
                   "    Server = {s1}\n    Value = {v1}\n")
    code, _ = run_cli(str(bad))
    assert code == cli.EXIT_ERROR


def test_cli_capacity_error_is_loud(tmp_path):
    code, _ = run_cli(REF_CFG, "--engine", "device", "--cpu",
                      "--spec", "election", "--max-term", "2",
                      "--max-log", "0", "--max-msgs", "1",
                      "--cap", "512", "--chunk", "64")
    assert code == cli.EXIT_ERROR


def bag(*ms):
    return tuple(sorted((m, 1) for m in ms))


@pytest.fixture(scope="module")
def seeded_violation():
    """The seeded NaiveNoTwoLeaders violation from the engine tests."""
    bounds = Bounds(n_servers=3, n_values=1, max_term=3, max_log=0,
                    max_msgs=4, max_dup=1)
    cfg = CheckConfig(bounds=bounds, spec="election",
                      invariants=("NaiveNoTwoLeaders",), chunk=256)
    start = interp.init_state(bounds)._replace(
        role=(S.LEADER, S.FOLLOWER, S.CANDIDATE),
        term=(2, 3, 3), votedFor=(1, 3, 0),
        vGrant=(0b011, 0, 0b100),
        msgs=bag(mb.rv_response(3, 1, 1, 2)))
    res = refbfs.check(cfg, init_override=start)
    assert res.violation is not None
    return res.violation, bounds


def test_render_trace_tlc_style(seeded_violation):
    violation, bounds = seeded_violation
    text = render.render_trace(violation, bounds)
    assert "Error: Invariant NaiveNoTwoLeaders is violated." in text
    assert "State 1: <Initial predicate>" in text
    # every subsequent step names its action
    n_states = len(violation.trace)
    for k in range(2, n_states + 1):
        assert f"State {k}: <" in text
    # TLA-style variable conjunctions with reference variable names
    for var in ("messages", "currentTerm", "state", "votedFor", "log",
                "commitIndex", "votesResponded", "votesGranted",
                "nextIndex", "matchIndex"):
        assert f"/\\ {var} = " in text
    # the final state really shows two leaders
    assert text.count("Leader") >= 2


def test_render_messages_have_schema_fields(seeded_violation):
    violation, bounds = seeded_violation
    text = render.render_trace(violation, bounds)
    assert "mtype |-> RequestVoteResponse" in text
    assert "mvoteGranted |-> TRUE" in text


def test_tla_export_structure(tmp_path):
    bounds = Bounds(n_servers=3, n_values=2, max_term=3, max_log=2,
                    max_msgs=4, max_dup=1)
    tla, cfgp = tla_export.export(str(tmp_path), bounds,
                                  ("NoTwoLeaders", "LogMatching"))
    mod = open(tla).read()
    assert mod.startswith("---------------------------- MODULE MCraft ")
    assert "EXTENDS raft" in mod
    assert "NoTwoLeaders ==" in mod and "LogMatching ==" in mod
    assert "currentTerm[i] <= 3" in mod and "Len(log[i]) <= 2" in mod
    assert "Cardinality(DOMAIN messages) <= 4" in mod
    assert "ParityView" in mod and "StripMsg" in mod
    assert mod.rstrip().endswith("=" * 77)

    # cfg round-trips through our own byte-compatible parser
    cfg = parse_cfg(open(cfgp).read())
    assert cfg.specification == "Spec"
    assert cfg.invariants == ["NoTwoLeaders", "LogMatching"]
    assert cfg.constraints == ["StateConstraint"]
    assert cfg.server_names() == ["s1", "s2", "s3"]
    assert cfg.value_names() == ["v1", "v2"]
    assert cfg.constants["Follower"] == "Follower"


def test_tla_export_unknown_invariant(tmp_path):
    with pytest.raises(ValueError, match="no TLA\\+ export"):
        tla_export.emit_module(Bounds(), ("NotAnInvariant",))


def test_cli_liveness_property_stanza(tmp_path):
    """cfg PROPERTY stanza drives liveness; refuted -> TLC exit 13."""
    cfgp = write_cfg(tmp_path / "live.cfg",
                     extra="PROPERTY EventuallyLeader\n")
    code, out = run_cli(cfgp, "--engine", "ref", "--spec", "full",
                        "--max-term", "2", "--max-log", "1",
                        "--max-msgs", "2", "--wf", "Next", "--no-trace")
    assert code == 13
    assert "Property EventuallyLeader is violated" in out
    # satisfied on the election subset under the same fairness
    code2, out2 = run_cli(cfgp, "--engine", "ref", "--spec",
                          "election", "--max-term", "2", "--max-log", "0",
                          "--max-msgs", "2", "--wf", "Next")
    assert code2 == cli.EXIT_OK
    assert "Property EventuallyLeader is satisfied" in out2


def test_cli_symmetry_flag(tmp_path):
    cfgp = write_cfg(tmp_path / "sym.cfg")
    args = (cfgp, "--engine", "ref", "--spec", "election",
            "--max-term", "2", "--max-log", "0", "--max-msgs", "2")
    code, out = run_cli(*args, "--symmetry")
    assert code == cli.EXIT_OK
    assert "Symmetry: Server permutations" in out
    m = re.search(r"(\d+) distinct states found", out)
    assert m, out
    assert int(m.group(1)) == 1514          # orbits of the 3014-state space


def test_cli_faithful_mode(tmp_path):
    """--faithful carries history state; *Hist invariants resolve; the TLC
    twin drops the ParityView (TLC fingerprints full states, as we do)."""
    cfg = write_cfg(tmp_path / "h.cfg",
                    extra="INVARIANTS ElectionSafetyHist "
                          "AllLogsPrefixClosed\n")
    out_tlc = tmp_path / "tlc"
    code, out = run_cli(cfg, "--engine", "ref", "--faithful",
                        "--max-term", "2", "--max-log", "1",
                        "--max-msgs", "2", "--emit-tlc", str(out_tlc))
    assert code == cli.EXIT_OK
    assert "Faithful mode" in out
    # 2s/1v full-spec faithful count (vs 48041-state... parity run is v=1:
    # both pinned by refbfs in tests/test_history.py)
    m = re.search(r"(\d+) distinct states found, diameter (\d+)", out)
    assert m and int(m.group(2)) == 32
    mod = open(out_tlc / "MCraft.tla").read()
    assert "ElectionSafetyHist ==" in mod and "AllLogsPrefixClosed ==" in mod
    assert "ParityView" not in mod
    cfgp = parse_cfg(open(out_tlc / "MCraft.cfg").read())
    assert cfgp.view is None
    assert cfgp.constraints == ["StateConstraint"]


def test_cli_faithful_required_for_hist_invariants(tmp_path):
    cfg = write_cfg(tmp_path / "h2.cfg",
                    extra="INVARIANT ElectionSafetyHist\n")
    code, _out = run_cli(cfg, "--engine", "ref")
    assert code == cli.EXIT_ERROR


def test_cli_faithful_rejects_parity_view(tmp_path):
    """A parity-emitted cfg (VIEW ParityView) contradicts --faithful."""
    cfg = write_cfg(tmp_path / "v.cfg", extra="VIEW ParityView\n")
    tiny = ("--spec", "election", "--max-term", "2", "--max-log", "0",
            "--max-msgs", "1")
    code, _ = run_cli(cfg, "--engine", "ref", *tiny)   # parity: accepted
    assert code == cli.EXIT_OK
    code, _ = run_cli(cfg, "--engine", "ref", "--faithful", *tiny)
    assert code == cli.EXIT_ERROR


def test_cli_init_next_stanzas(tmp_path):
    """INIT/NEXT-style configs: the spec's own operator names pass, any
    other name is rejected (it would silently run a different model)."""
    tiny = ("--spec", "election", "--max-term", "2", "--max-log", "0",
            "--max-msgs", "1")
    template = open(write_cfg(tmp_path / "t.cfg")).read()
    (tmp_path / "a.cfg").write_text(
        template.replace("SPECIFICATION Spec", "INIT Init\nNEXT Next"))
    code, _ = run_cli(str(tmp_path / "a.cfg"), "--engine", "ref", *tiny)
    assert code == cli.EXIT_OK
    (tmp_path / "b.cfg").write_text(
        template.replace("SPECIFICATION Spec", "NEXT LiveNext"))
    code, _ = run_cli(str(tmp_path / "b.cfg"), "--engine", "ref", *tiny)
    assert code == cli.EXIT_ERROR


def test_cli_streamed_and_pagedshard_engines(tmp_path):
    """The two round-2 engines run end-to-end from the CLI with the
    standard report and exit code."""
    cfg = write_cfg(tmp_path / "e.cfg")
    code, out = run_cli(cfg, "--engine", "streamed", "--spec", "election",
                        "--max-term", "2", "--max-log", "0",
                        "--max-msgs", "2", "--chunk", "64",
                        "--cap", "65536", "--ring", "8192")
    assert code == 0 and "3014 distinct states" in out
    code, out = run_cli(cfg, "--engine", "pagedshard", "--spec",
                        "election", "--max-term", "2", "--max-log", "0",
                        "--max-msgs", "2", "--chunk", "64",
                        "--cap", "65536", "--devices", "8")
    assert code == 0 and "3014 distinct states" in out


def test_cli_ddd_engine(tmp_path):
    """The DDD engine runs end-to-end from the CLI with the standard
    report and exit code."""
    cfg = write_cfg(tmp_path / "e.cfg")
    code, out = run_cli(cfg, "--engine", "ddd", "--spec", "election",
                        "--max-term", "2", "--max-log", "0",
                        "--max-msgs", "2", "--chunk", "64",
                        "--cap", "65536")
    assert code == 0 and "3014 distinct states" in out


def test_cli_ddd_routed(tmp_path):
    """--route K drives the EP-routed step from the CLI; counts match
    the dense run."""
    cfg = write_cfg(tmp_path / "e.cfg")
    code, out = run_cli(cfg, "--engine", "ddd", "--spec", "election",
                        "--max-term", "2", "--max-log", "0",
                        "--max-msgs", "2", "--chunk", "64",
                        "--cap", "65536", "--route", "704")
    assert code == 0 and "3014 distinct states" in out


def test_cli_reshard(tmp_path):
    """--reshard-to rewrites a shard checkpoint for a new mesh size from
    the CLI; the resumed search finishes with identical counts."""
    cfg = write_cfg(tmp_path / "e.cfg")
    ck2 = str(tmp_path / "m2.ckpt")
    code, out = run_cli(cfg, "--engine", "shard", "--spec", "election",
                        "--max-term", "2", "--max-log", "0",
                        "--max-msgs", "2", "--chunk", "64",
                        "--cap", "4096", "--levels", "64",
                        "--devices", "2", "--checkpoint", ck2,
                        "--checkpoint-every", "0", "--seg-chunks", "8")
    assert code == 0 and "3014 distinct states" in out
    ck4 = str(tmp_path / "m4.ckpt")
    code, out = run_cli(cfg, "--engine", "shard", "--spec", "election",
                        "--max-term", "2", "--max-log", "0",
                        "--max-msgs", "2", "--chunk", "64",
                        "--cap", "4096", "--levels", "64",
                        "--reshard-to", "4", "--resume", ck2,
                        "--checkpoint", ck4)
    assert code == 0 and "resharded 2 -> 4 devices" in out
    code, out = run_cli(cfg, "--engine", "shard", "--spec", "election",
                        "--max-term", "2", "--max-log", "0",
                        "--max-msgs", "2", "--chunk", "64",
                        "--cap", "4096", "--levels", "64",
                        "--devices", "4", "--resume", ck4)
    assert code == 0 and "3014 distinct states" in out
    # misuse is a clean error, not a traceback
    code, _ = run_cli(cfg, "--engine", "shard", "--reshard-to", "4")
    assert code != 0
