"""Delayed-duplicate-detection engine (ddd_engine.py).

The engine exists because the exact device fingerprint table caps
distinct-state capacity at ~2^28 slots (the elect5 campaign measured into
that ceiling — RESULTS.md "capacity findings"); its gates: oracle-exact
parity with blocks/chunks small enough to cycle many times, IDENTICAL
results under forced filter-table eviction (the lossy filter must never
change a verdict or a count), refbfs-exact violation/deadlock stops,
trace replay, and block-boundary checkpoint/resume with exact counters.
"""

import numpy as np
import pytest

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.ddd_engine import DDDCapacities, DDDEngine
from raft_tla_tpu.models import interp, refbfs

# smoke tier: cross-section for mid-round changes (pytest -m smoke)
pytestmark = pytest.mark.smoke

CFG = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                max_log=0, max_msgs=2),
                  spec="election", invariants=("NoTwoLeaders",), chunk=32)
CAPS = DDDCapacities(block=256, table=1 << 14, flush=1 << 10, levels=64)


def test_parity_with_oracle_tiny_blocks_and_flushes():
    ref = refbfs.check(CFG)
    got = DDDEngine(CFG, CAPS).check()
    assert got.n_states == ref.n_states == 3014
    assert got.diameter == ref.diameter == 17
    assert got.levels == ref.levels
    assert got.n_transitions == ref.n_transitions
    assert got.coverage == ref.coverage      # identical discovery order
    assert got.violation is None and got.complete


def test_parity_under_forced_eviction():
    """A 128-slot filter on a 3014-state space evicts constantly; the
    host dedup must absorb every false-new re-sight — identical counts,
    levels, coverage, discovery order."""
    ref = refbfs.check(CFG)
    caps = DDDCapacities(block=256, table=1 << 7, flush=1 << 9, levels=64)
    got = DDDEngine(CFG, caps).check()
    assert got.n_states == ref.n_states
    assert got.levels == ref.levels
    assert got.n_transitions == ref.n_transitions
    assert got.coverage == ref.coverage


def test_capacity_past_device_table_scale():
    """The filter table is NOT a state-count ceiling: a space 8x larger
    than the filter completes exactly (the table engines would
    FAIL_PROBE here)."""
    cfg = CheckConfig(bounds=Bounds(n_servers=3, n_values=1, max_term=2,
                                    max_log=0, max_msgs=1),
                      spec="election", invariants=("NoTwoLeaders",),
                      chunk=64)
    caps = DDDCapacities(block=1 << 13, table=1 << 14, flush=1 << 14,
                         levels=64)
    got = DDDEngine(cfg, caps).check()
    assert got.n_states == 142538
    assert got.diameter == 31
    assert got.complete


@pytest.mark.parametrize("prefetch", ["on", "off"])
@pytest.mark.parametrize("host_dedup", ["on", "off"])
def test_violation_trace_replays_and_stops_exactly(host_dedup, prefetch,
                                                   monkeypatch):
    monkeypatch.setenv("RAFT_TLA_HOSTDEDUP", host_dedup)
    monkeypatch.setenv("RAFT_TLA_PREFETCH", prefetch)
    from raft_tla_tpu.models import invariants as inv_mod
    from raft_tla_tpu.models import spec as S
    from raft_tla_tpu.ops import msgbits as mb

    bounds = Bounds(n_servers=3, n_values=1, max_term=3, max_log=0,
                    max_msgs=4, max_dup=1)
    cfg = CheckConfig(bounds=bounds, spec="election",
                      invariants=("NaiveNoTwoLeaders",), chunk=64)
    start = interp.init_state(bounds)._replace(
        role=(S.LEADER, S.FOLLOWER, S.CANDIDATE),
        term=(2, 3, 3),
        votedFor=(1, 3, 0),
        vGrant=(0b011, 0, 0b100),
        msgs=tuple(sorted((m, 1) for m in
                          (mb.rv_response(3, 1, 1, 2),))),
    )
    ref = refbfs.check(cfg, init_override=start)
    caps = DDDCapacities(block=1 << 12, table=1 << 17, flush=1 << 12,
                         levels=64)
    got = DDDEngine(cfg, caps).check(init_override=start)
    assert got.violation is not None
    assert got.violation.invariant == "NaiveNoTwoLeaders"
    # device-side stream truncation makes the stop refbfs-exact
    assert got.n_states == ref.n_states
    trace = got.violation.trace
    assert trace[0][0] is None and trace[0][1] == start
    for (_l, prev), (_label, cur) in zip(trace, trace[1:]):
        succs = [t for _i, t in interp.successors(prev, bounds,
                                                  spec="election")]
        assert cur in succs
    assert not inv_mod.py_invariant("NaiveNoTwoLeaders")(
        got.violation.state, bounds)


def test_checkpoint_resume_bit_exact(tmp_path):
    ck = str(tmp_path / "ddd.ckpt")
    straight = DDDEngine(CFG, CAPS).check()
    res = DDDEngine(CFG, CAPS).check(checkpoint=ck,
                                     checkpoint_every_s=0.0)
    assert res.n_states == straight.n_states
    resumed = DDDEngine(CFG, CAPS).check(resume=ck)
    assert resumed.n_states == straight.n_states
    assert resumed.levels == straight.levels
    assert resumed.n_transitions == straight.n_transitions
    assert resumed.coverage == straight.coverage
    assert resumed.violation is None

    other = DDDEngine(CFG, DDDCapacities(block=512, table=1 << 14,
                                         flush=1 << 10, levels=64))
    with pytest.raises(ValueError, match="checkpoint"):
        other.check(resume=ck)


def test_symmetry_composes():
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=("NoTwoLeaders",),
                      symmetry=("Server",), chunk=32)
    ref = refbfs.check(cfg)
    got = DDDEngine(cfg, CAPS).check()
    assert got.n_states == ref.n_states == 1514
    assert got.diameter == ref.diameter
    assert got.coverage == ref.coverage


@pytest.mark.parametrize("prefetch", ["on", "off"])
@pytest.mark.parametrize("host_dedup", ["on", "off"])
def test_deadlock_detected(host_dedup, prefetch, monkeypatch):
    monkeypatch.setenv("RAFT_TLA_HOSTDEDUP", host_dedup)
    monkeypatch.setenv("RAFT_TLA_PREFETCH", prefetch)
    cfg = CheckConfig(bounds=Bounds(n_servers=1, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=(), chunk=16,
                      check_deadlock=True)
    ref = refbfs.check(cfg)
    caps = DDDCapacities(block=64, table=1 << 12, flush=1 << 8, levels=64)
    got = DDDEngine(cfg, caps).check()
    assert ref.violation is not None and got.violation is not None
    assert got.violation.invariant == ref.violation.invariant  # DEADLOCK
    assert got.n_states == ref.n_states


def test_faithful_mode_parity():
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=1, max_msgs=2, history=True,
                                    max_elections=4),
                      spec="full",
                      invariants=("NoTwoLeaders", "ElectionSafetyHist",
                                  "AllLogsPrefixClosed"), chunk=512)
    ref = refbfs.check(cfg)
    assert (ref.n_states, ref.diameter) == (53398, 32)
    caps = DDDCapacities(block=1 << 13, table=1 << 18, flush=1 << 15,
                         levels=64)
    got = DDDEngine(cfg, caps).check()
    assert (got.n_states, got.diameter) == (ref.n_states, ref.diameter)
    assert got.levels == ref.levels
    assert got.coverage == ref.coverage
    assert got.violation is None


def test_masterkeys_unit():
    from raft_tla_tpu.utils.keyset import MasterKeys

    m = MasterKeys()
    m.seed(7)
    keys = np.array([9, 3, 9, 7, 3, 11], np.uint64)
    new = m.dedup(keys)
    # first occurrences of 9, 3, 11 (7 already present), stream order
    assert new.tolist() == [0, 1, 5]
    assert len(m) == 4
    assert m.contains(np.array([3, 4, 7, 9, 11], np.uint64)).tolist() == \
        [True, False, True, True, True]
    # second flush: all duplicates
    assert m.dedup(keys).size == 0
    # strictly-new flush merges in order
    assert m.dedup(np.array([2, 1, 2], np.uint64)).tolist() == [0, 1]
    assert m.array.tolist() == [1, 2, 3, 7, 9, 11]


def test_masterkeys_tiers_randomized():
    """LSM tiers must be observationally identical to a flat set: dedup
    indices per flush, contains, len, and the materialized array all
    match a reference dict over many random overlapping flushes."""
    from raft_tla_tpu.utils.keyset import MasterKeys, _RATIO

    rng = np.random.default_rng(20260731)
    m = MasterKeys()
    seen: set[int] = set()
    for _ in range(40):
        flush = rng.integers(0, 5000, size=rng.integers(1, 4000),
                             dtype=np.uint64)
        # reference first-occurrence semantics
        want, batch_seen = [], set()
        for i, k in enumerate(flush.tolist()):
            if k not in seen and k not in batch_seen:
                want.append(i)
                batch_seen.add(k)
        got = m.dedup(flush)
        assert got.tolist() == want
        seen |= batch_seen
        assert len(m) == len(seen)
        # geometric tier invariant: every older run > _RATIO x newer
        runs = m._runs
        assert all(runs[i].size > _RATIO * runs[i + 1].size
                   for i in range(len(runs) - 1))
        # runs stay mutually disjoint and individually sorted
        for r in runs:
            assert np.all(r[1:] > r[:-1])
    probe = np.arange(5000, dtype=np.uint64)
    assert m.contains(probe).tolist() == [k in seen for k in range(5000)]
    assert m.array.tolist() == sorted(seen)
    # tier count stays logarithmic
    assert m.n_runs <= 16


def test_masterkeys_resume_constructor():
    """The checkpoint-resume path hands a single sorted array; behavior
    must match a set grown flush-by-flush."""
    from raft_tla_tpu.utils.keyset import MasterKeys

    base = np.sort(np.unique(
        np.random.default_rng(7).integers(0, 10**6, 5000, dtype=np.uint64)))
    m = MasterKeys(base)
    assert len(m) == base.size and m.n_runs == 1
    flush = np.concatenate([base[:100], base[:100] + np.uint64(10**7)])
    new = m.dedup(flush)
    assert new.tolist() == list(range(100, 200))
    assert len(m) == base.size + 100
    bad = base.copy()
    bad[10] = bad[9]
    import pytest
    with pytest.raises(ValueError):
        MasterKeys(bad)


# -- RAFT_TLA_HOSTDEDUP gate (partitioned + background host dedup) ----------


@pytest.mark.parametrize("host_dedup", ["on", "off"])
def test_host_dedup_oracle_parity_both_arms(host_dedup, monkeypatch):
    """Explicit both-arm parity (the rest of this file runs under the
    auto policy): partitioned master keys + depth-1 background flush
    must not move a single byte of discovery."""
    monkeypatch.setenv("RAFT_TLA_HOSTDEDUP", host_dedup)
    ref = refbfs.check(CFG)
    got = DDDEngine(CFG, CAPS).check()
    assert got.n_states == ref.n_states == 3014
    assert got.levels == ref.levels
    assert got.n_transitions == ref.n_transitions
    assert got.coverage == ref.coverage
    assert got.violation is None and got.complete


def test_host_dedup_checkpoint_cross_gate(tmp_path, monkeypatch):
    """Checkpoints are gate-agnostic (the master set is rebuilt from the
    key log, and the gate is deliberately not part of the digest):
    written under either arm, resumable under the other, byte-identical
    finals both ways."""
    straight = DDDEngine(CFG, CAPS).check()
    for write, read in (("on", "off"), ("off", "on")):
        ck = str(tmp_path / f"ddd_{write}.ckpt")
        monkeypatch.setenv("RAFT_TLA_HOSTDEDUP", write)
        mid = DDDEngine(CFG, CAPS).check(checkpoint=ck,
                                         checkpoint_every_s=0.0)
        assert mid.n_states == straight.n_states
        monkeypatch.setenv("RAFT_TLA_HOSTDEDUP", read)
        resumed = DDDEngine(CFG, CAPS).check(resume=ck)
        assert resumed.n_states == straight.n_states, (write, read)
        assert resumed.levels == straight.levels
        assert resumed.n_transitions == straight.n_transitions
        assert resumed.coverage == straight.coverage
        assert resumed.violation is None


def test_host_dedup_lossless_deadline_stop_with_pending_flush(
        tmp_path, monkeypatch):
    """The lossless-stop contract under the async flush: a deadline
    lands while sealed batches may be in flight on the background
    worker; the stop path drains the queue before the snapshot, so
    resume completes byte-identical to an uninterrupted run."""
    monkeypatch.setenv("RAFT_TLA_HOSTDEDUP", "on")
    cfg = CheckConfig(bounds=Bounds(n_servers=3, n_values=1, max_term=2,
                                    max_log=0, max_msgs=1),
                      spec="election", invariants=("NoTwoLeaders",),
                      chunk=64)
    caps = DDDCapacities(block=256, table=1 << 14, flush=1 << 9, levels=64)
    straight = DDDEngine(cfg, caps).check()
    ck = str(tmp_path / "dl.ckpt")
    got = DDDEngine(cfg, caps).check(deadline_s=0.5, checkpoint=ck,
                                     checkpoint_every_s=3600.0)
    assert not got.complete
    assert got.n_states < straight.n_states
    resumed = DDDEngine(cfg, caps).check(resume=ck)
    assert resumed.complete
    assert resumed.n_states == straight.n_states
    assert resumed.levels == straight.levels
    assert resumed.n_transitions == straight.n_transitions
    assert resumed.coverage == straight.coverage


def test_deadline_stops_cleanly():
    """A deadline expiry — including one landing between blocks with an
    empty pipeline — returns complete=False instead of crashing, and the
    partial counts stay self-consistent."""
    cfg = CheckConfig(bounds=Bounds(n_servers=3, n_values=1, max_term=2,
                                    max_log=0, max_msgs=1),
                      spec="election", invariants=("NoTwoLeaders",),
                      chunk=64)
    caps = DDDCapacities(block=256, table=1 << 14, flush=1 << 9, levels=64)
    got = DDDEngine(cfg, caps).check(deadline_s=0.5)
    assert not got.complete
    assert 1 <= got.n_states < 142538
    assert got.violation is None


# -- RAFT_TLA_PREFETCH gate (double-buffered upload prefetch) ---------------


@pytest.mark.parametrize("retention", ["full", "frontier"])
@pytest.mark.parametrize("prefetch", ["on", "off"])
def test_prefetch_oracle_parity_both_arms(prefetch, retention,
                                          monkeypatch):
    """Explicit both-arm parity in both retention modes: swapping block
    uploads to prefetched, double-buffered staging must not move a
    single byte of discovery (hits and misses read the same rows)."""
    monkeypatch.setenv("RAFT_TLA_PREFETCH", prefetch)
    ref = refbfs.check(CFG)
    caps = DDDCapacities(block=256, table=1 << 14, flush=1 << 10,
                         levels=64, retention=retention)
    got = DDDEngine(CFG, caps).check()
    assert got.n_states == ref.n_states == 3014
    assert got.levels == ref.levels
    assert got.n_transitions == ref.n_transitions
    assert got.coverage == ref.coverage
    assert got.violation is None and got.complete


def test_prefetch_checkpoint_cross_gate(tmp_path, monkeypatch):
    """Checkpoints are prefetch-agnostic (the gate is deliberately not
    part of the digest): written under either arm, resumable under the
    other, byte-identical finals both ways."""
    straight = DDDEngine(CFG, CAPS).check()
    for write, read in (("on", "off"), ("off", "on")):
        ck = str(tmp_path / f"ddd_pf_{write}.ckpt")
        monkeypatch.setenv("RAFT_TLA_PREFETCH", write)
        mid = DDDEngine(CFG, CAPS).check(checkpoint=ck,
                                         checkpoint_every_s=0.0)
        assert mid.n_states == straight.n_states
        monkeypatch.setenv("RAFT_TLA_PREFETCH", read)
        resumed = DDDEngine(CFG, CAPS).check(resume=ck)
        assert resumed.n_states == straight.n_states, (write, read)
        assert resumed.levels == straight.levels
        assert resumed.n_transitions == straight.n_transitions
        assert resumed.coverage == straight.coverage
        assert resumed.violation is None


def test_prefetch_lossless_deadline_stop_with_prefetch_in_flight(
        tmp_path, monkeypatch):
    """The lossless-stop contract with BOTH background threads live: a
    deadline lands while a flush may be in flight on the dedup worker
    AND a block prefetch may be staged or in flight; the stop path
    invalidates the prefetch and drains the queue before the snapshot,
    so resume completes byte-identical to an uninterrupted run."""
    monkeypatch.setenv("RAFT_TLA_HOSTDEDUP", "on")
    monkeypatch.setenv("RAFT_TLA_PREFETCH", "on")
    cfg = CheckConfig(bounds=Bounds(n_servers=3, n_values=1, max_term=2,
                                    max_log=0, max_msgs=1),
                      spec="election", invariants=("NoTwoLeaders",),
                      chunk=64)
    caps = DDDCapacities(block=256, table=1 << 14, flush=1 << 9, levels=64)
    straight = DDDEngine(cfg, caps).check()
    ck = str(tmp_path / "pf_dl.ckpt")
    got = DDDEngine(cfg, caps).check(deadline_s=0.5, checkpoint=ck,
                                     checkpoint_every_s=3600.0)
    assert not got.complete
    assert got.n_states < straight.n_states
    resumed = DDDEngine(cfg, caps).check(resume=ck)
    assert resumed.complete
    assert resumed.n_states == straight.n_states
    assert resumed.levels == straight.levels
    assert resumed.n_transitions == straight.n_transitions
    assert resumed.coverage == straight.coverage


# -- EP-routed step (DDDCapacities.route_rows; SURVEY §2.9 EP row) ----------

import dataclasses


def _routed(caps, k):
    return dataclasses.replace(caps, route_rows=k)


def _n_lanes(cfg):
    from raft_tla_tpu.models import spec as S
    return cfg.chunk * len(S.action_table(cfg.bounds, cfg.spec))


def test_routed_parity_with_dense():
    """route_rows changes only where per-candidate work runs — counts,
    levels, coverage and discovery order are byte-identical.  K = N/2
    makes the slots genuinely contested (the realistic operating point:
    fewer slots than lanes, no overflow), not just a stable re-ordering
    of the full grid."""
    dense = DDDEngine(CFG, CAPS).check()
    for k in (_n_lanes(CFG), _n_lanes(CFG) // 2):
        got = DDDEngine(CFG, _routed(CAPS, k)).check()
        for f in ("n_states", "diameter", "levels", "n_transitions",
                  "coverage", "complete"):
            assert getattr(got, f) == getattr(dense, f), (k, f)
        assert got.violation is None


def test_routed_violation_truncation_exact():
    from raft_tla_tpu.models import spec as S
    from raft_tla_tpu.ops import msgbits as mb

    bounds = Bounds(n_servers=3, n_values=1, max_term=3, max_log=0,
                    max_msgs=4, max_dup=1)
    cfg = CheckConfig(bounds=bounds, spec="election",
                      invariants=("NaiveNoTwoLeaders",), chunk=64)
    start = interp.init_state(bounds)._replace(
        role=(S.LEADER, S.FOLLOWER, S.CANDIDATE),
        term=(2, 3, 3),
        votedFor=(1, 3, 0),
        vGrant=(0b011, 0, 0b100),
        msgs=tuple(sorted((m, 1) for m in
                          (mb.rv_response(3, 1, 1, 2),))),
    )
    caps = DDDCapacities(block=1 << 12, table=1 << 17, flush=1 << 12,
                         levels=64)
    ref = DDDEngine(cfg, caps).check(init_override=start)
    got = DDDEngine(cfg, _routed(caps, _n_lanes(cfg))) \
        .check(init_override=start)
    assert got.violation is not None
    assert got.violation.invariant == ref.violation.invariant
    assert got.n_states == ref.n_states          # refbfs-exact stop
    assert got.n_transitions == ref.n_transitions
    assert got.violation.trace == ref.violation.trace


def test_routed_deadlock_and_symmetry():
    cfg = CheckConfig(bounds=Bounds(n_servers=1, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=(), chunk=16,
                      check_deadlock=True)
    caps = DDDCapacities(block=64, table=1 << 12, flush=1 << 8, levels=64)
    ref = DDDEngine(cfg, caps).check()
    got = DDDEngine(cfg, _routed(caps, _n_lanes(cfg))).check()
    assert got.violation is not None
    assert got.violation.invariant == ref.violation.invariant
    assert got.n_states == ref.n_states

    sym = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=("NoTwoLeaders",),
                      symmetry=("Server",), chunk=32)
    got = DDDEngine(sym, _routed(CAPS, _n_lanes(sym))).check()
    assert got.n_states == 1514      # refbfs-verified orbit count


def test_routed_faithful_mode():
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2, history=True,
                                    max_elections=4),
                      spec="election",
                      invariants=("NoTwoLeaders", "ElectionSafetyHist"),
                      chunk=64)
    caps = DDDCapacities(block=512, table=1 << 14, flush=1 << 11,
                         levels=64)
    dense = DDDEngine(cfg, caps).check()
    got = DDDEngine(cfg, _routed(caps, _n_lanes(cfg))).check()
    for f in ("n_states", "diameter", "levels", "n_transitions",
              "coverage"):
        assert getattr(got, f) == getattr(dense, f), f


def test_routed_checkpoint_crosses_step_switch(tmp_path):
    """route_rows stays out of the checkpoint digest: a dense snapshot
    resumes on the routed step (and vice versa) with identical results —
    the mid-campaign tuning DDDCapacities promises."""
    straight = DDDEngine(CFG, CAPS).check()
    ck = str(tmp_path / "ddd_route.ckpt")
    DDDEngine(CFG, CAPS).check(checkpoint=ck, checkpoint_every_s=0.0)
    resumed = DDDEngine(CFG, _routed(CAPS, _n_lanes(CFG))) \
        .check(resume=ck)
    assert resumed.n_states == straight.n_states
    assert resumed.n_transitions == straight.n_transitions
    assert resumed.coverage == straight.coverage


def test_routed_budget_overflow_aborts_loudly():
    with pytest.raises(RuntimeError, match="routing budget"):
        DDDEngine(CFG, _routed(CAPS, 8)).check()


def test_routed_violation_never_masked_by_budget():
    """Sweeping route_rows across the seeded-violation universe: every
    budget either aborts loudly (FAIL_ROUTE — candidates before the cut
    may be lost) or reports EXACTLY the dense engine's violation with
    dense-exact counts; a detected invariant violation outranks a
    routing overflow (the dropped lanes provably lie past the cut)."""
    from raft_tla_tpu.models import spec as S
    from raft_tla_tpu.ops import msgbits as mb

    bounds = Bounds(n_servers=3, n_values=1, max_term=3, max_log=0,
                    max_msgs=4, max_dup=1)
    cfg = CheckConfig(bounds=bounds, spec="election",
                      invariants=("NaiveNoTwoLeaders",), chunk=64)
    start = interp.init_state(bounds)._replace(
        role=(S.LEADER, S.FOLLOWER, S.CANDIDATE),
        term=(2, 3, 3),
        votedFor=(1, 3, 0),
        vGrant=(0b011, 0, 0b100),
        msgs=tuple(sorted((m, 1) for m in
                          (mb.rv_response(3, 1, 1, 2),))),
    )
    caps = DDDCapacities(block=1 << 12, table=1 << 17, flush=1 << 12,
                         levels=64)
    ref = DDDEngine(cfg, caps).check(init_override=start)
    n_lanes = _n_lanes(cfg)
    reported = 0
    for k in (n_lanes // 16, n_lanes // 8, n_lanes // 4,
              n_lanes // 2, n_lanes):
        try:
            got = DDDEngine(cfg, _routed(caps, k)) \
                .check(init_override=start)
        except RuntimeError as e:
            assert "routing budget" in str(e)
            continue
        assert got.violation is not None
        assert got.violation.invariant == ref.violation.invariant
        assert got.n_states == ref.n_states
        assert got.n_transitions == ref.n_transitions
        assert got.violation.trace == ref.violation.trace
        reported += 1
    assert reported >= 1          # the sweep must exercise the report path
