"""Engine ≡ oracle: the TPU BFS engine must reproduce refbfs exactly.

SURVEY §4.3 (integration oracle): identical spec+cfg+constraint ⇒ equal
distinct-state counts, equal diameter, equal per-level counts, equal
per-action coverage, equal invariant verdicts, and replayable traces on
seeded violations.
"""

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu import engine
from raft_tla_tpu.models import interp, refbfs, spec as S
from raft_tla_tpu.ops import msgbits as mb


def bag(*ms):
    return tuple(sorted((m, 1) for m in ms))


def assert_parity(cfg, **kw):
    ref = refbfs.check(cfg, **kw)
    got = engine.check(cfg, **kw)
    assert got.n_states == ref.n_states
    assert got.diameter == ref.diameter
    assert got.levels == ref.levels
    assert got.n_transitions == ref.n_transitions
    assert got.coverage == ref.coverage
    assert (got.violation is None) == (ref.violation is None)
    return ref, got


def test_election_2server_parity():
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=("NoTwoLeaders",), chunk=64)
    ref, got = assert_parity(cfg)
    assert got.violation is None and got.n_states > 10


def test_election_3server_parity():
    cfg = CheckConfig(bounds=Bounds(n_servers=3, n_values=1, max_term=2,
                                    max_log=0, max_msgs=1),
                      spec="election",
                      invariants=("NoTwoLeaders", "CommittedWithinLog"),
                      chunk=1024)
    ref, got = assert_parity(cfg)
    assert got.violation is None and got.n_states > 1000


def test_full_spec_small_parity():
    """Full Next (all 10 families) on a tiny universe, vs the oracle."""
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=1, max_msgs=2),
                      spec="full",
                      invariants=("NoTwoLeaders", "LogMatching",
                                  "CommittedWithinLog"),
                      chunk=128)
    ref, got = assert_parity(cfg)
    assert got.violation is None
    # faults + crash-recovery are genuinely exercised
    for fam in (S.RESTART, S.DUPLICATE, S.DROP):
        assert got.coverage[fam] > 0


def test_replication_parity_from_leader():
    """Replication sub-spec from a preset single-leader state (config #3)."""
    bounds = Bounds(n_servers=3, n_values=1, max_term=2, max_log=1,
                    max_msgs=2)
    start = interp.init_state(bounds)._replace(
        role=(S.LEADER, S.FOLLOWER, S.FOLLOWER),
        term=(2, 2, 2), votedFor=(1, 1, 1))
    cfg = CheckConfig(bounds=bounds, spec="replication",
                      invariants=("LogMatching", "CommittedWithinLog"),
                      chunk=256)
    ref, got = assert_parity(cfg, init_override=start)
    assert got.violation is None and got.n_states > 100
    assert got.coverage[S.ADVANCECOMMIT] > 0


def test_engine_finds_naive_violation_with_replayable_trace():
    """Seeded violation (SURVEY §0 defect 1): the naive two-leaders reading
    is falsified; the engine's reconstructed trace must replay step by step
    through the interpreter and end in a genuinely violating state."""
    bounds = Bounds(n_servers=3, n_values=1, max_term=3, max_log=0,
                    max_msgs=4, max_dup=1)
    cfg = CheckConfig(bounds=bounds, spec="election",
                      invariants=("NaiveNoTwoLeaders",), chunk=256)
    start = interp.init_state(bounds)._replace(
        role=(S.LEADER, S.FOLLOWER, S.CANDIDATE),
        term=(2, 3, 3),
        votedFor=(1, 3, 0),
        vGrant=(0b011, 0, 0b100),
        msgs=bag(mb.rv_response(3, 1, 1, 2)),
    )
    ref = refbfs.check(cfg, init_override=start)
    got = engine.check(cfg, init_override=start)
    assert got.violation is not None
    # full stats parity with the oracle even on the violation run
    assert got.n_states == ref.n_states
    assert got.levels == ref.levels
    assert got.coverage == ref.coverage
    trace = got.violation.trace
    assert trace[0][0] is None and trace[0][1] == start
    for (_l, prev), (label, cur) in zip(trace, trace[1:]):
        succs = [t for _i, t in interp.successors(prev, bounds,
                                                  spec="election")]
        assert cur in succs
    leaders = [i for i, x in enumerate(trace[-1][1].role) if x == S.LEADER]
    assert len(leaders) >= 2
    # ...and the engine agrees ElectionSafety holds on the same run
    ok = engine.check(CheckConfig(bounds=bounds, spec="election",
                                  invariants=("NoTwoLeaders",), chunk=256),
                      init_override=start)
    assert ok.violation is None


def test_chunk_size_does_not_change_result():
    cfg1 = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                     max_log=0, max_msgs=2),
                       spec="election", invariants=("NoTwoLeaders",), chunk=8)
    cfg2 = CheckConfig(bounds=cfg1.bounds, spec=cfg1.spec,
                       invariants=cfg1.invariants, chunk=512)
    r1 = engine.check(cfg1)
    r2 = engine.check(cfg2)
    assert r1.n_states == r2.n_states
    assert r1.levels == r2.levels
    assert r1.coverage == r2.coverage
