"""Server-permutation symmetry reduction (TLC SYMMETRY analog).

Correctness anchors: the orbit key is permutation-invariant; the
symmetry-reduced oracle count equals the brute-force orbit count of the
full space; the device engine under symmetry reproduces the reduced oracle
exactly; violations still surface with replayable traces.
"""

import itertools

import numpy as np
import pytest

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.device_engine import Capacities, DeviceEngine
from raft_tla_tpu.models import interp, refbfs, spec as S
from raft_tla_tpu.ops import msgbits as mb
from raft_tla_tpu.ops import symmetry as sym

B2 = Bounds(n_servers=2, n_values=1, max_term=2, max_log=0, max_msgs=2)
B3 = Bounds(n_servers=3, n_values=1, max_term=2, max_log=0, max_msgs=1)


def bag(*ms):
    return tuple(sorted((m, 1) for m in ms))


def permute_py_state(s, p, bounds):
    """Reference permutation on the PyState view (independent impl)."""
    n = bounds.n_servers
    inv = [p.index(k) for k in range(n)]

    def vf(v):
        return 0 if v == 0 else p[v - 1] + 1

    def mask(m):
        out = 0
        for j in range(n):
            out |= ((m >> j) & 1) << p[j]
        return out

    msgs = []
    for (hi, lo), cnt in s.msgs:
        hi2 = mb.pack_hi(mb.mtype(hi), mb.mterm(hi), mb.fa(hi), mb.fb(hi),
                         p[mb.src(hi)], p[mb.dst(hi)])
        msgs.append(((hi2, lo), cnt))
    return s._replace(
        role=tuple(s.role[inv[k]] for k in range(n)),
        term=tuple(s.term[inv[k]] for k in range(n)),
        votedFor=tuple(vf(s.votedFor[inv[k]]) for k in range(n)),
        commitIndex=tuple(s.commitIndex[inv[k]] for k in range(n)),
        log=tuple(s.log[inv[k]] for k in range(n)),
        vResp=tuple(mask(s.vResp[inv[k]]) for k in range(n)),
        vGrant=tuple(mask(s.vGrant[inv[k]]) for k in range(n)),
        nextIndex=tuple(tuple(s.nextIndex[inv[k]][inv[j]] for j in range(n))
                        for k in range(n)),
        matchIndex=tuple(tuple(s.matchIndex[inv[k]][inv[j]]
                               for j in range(n)) for k in range(n)),
        msgs=tuple(sorted(msgs)))


def reachable_states(bounds, spec):
    table = S.action_table(bounds, spec)
    seen = {interp.init_state(bounds)}
    frontier = list(seen)
    while frontier:
        nxt = []
        for s in frontier:
            if not interp.constraint_ok(s, bounds):
                continue
            for _a, t in interp.successors(s, bounds, table):
                if t not in seen:
                    seen.add(t)
                    nxt.append(t)
        frontier = nxt
    return seen


def test_orbit_key_is_permutation_invariant():
    states = list(reachable_states(B3, "election"))[:300]
    perms = list(itertools.permutations(range(3)))
    for s in states[:60]:
        keys = {sym.py_orbit_fingerprint(permute_py_state(s, p, B3), B3)
                for p in perms}
        assert len(keys) == 1


def test_oracle_orbit_count_matches_brute_force():
    cfg = CheckConfig(bounds=B2, spec="election", invariants=(),
                      symmetry=("Server",))
    reduced = refbfs.check(cfg)
    full = reachable_states(B2, "election")
    orbits = {sym.py_orbit_fingerprint(s, B2) for s in full}
    assert reduced.n_states == len(orbits) == 1514
    assert len(full) == 3014


def test_device_engine_symmetry_parity():
    cfg = CheckConfig(bounds=B3, spec="election",
                      invariants=("NoTwoLeaders",), symmetry=("Server",),
                      chunk=256)
    ref = refbfs.check(cfg)
    got = DeviceEngine(cfg, Capacities(n_states=1 << 16, levels=64)).check()
    assert got.n_states == ref.n_states
    assert got.diameter == ref.diameter
    assert got.levels == ref.levels
    assert got.n_transitions == ref.n_transitions
    assert got.coverage == ref.coverage
    assert got.violation is None
    # sanity: it actually reduced (full space is 142538 with 2 values /
    # this config's unreduced count is strictly larger)
    unred = refbfs.check(CheckConfig(bounds=B3, spec="election",
                                     invariants=("NoTwoLeaders",)))
    assert ref.n_states < unred.n_states


def test_symmetry_violation_trace_replayable():
    bounds = Bounds(n_servers=3, n_values=1, max_term=3, max_log=0,
                    max_msgs=4, max_dup=1)
    cfg = CheckConfig(bounds=bounds, spec="election",
                      invariants=("NaiveNoTwoLeaders",),
                      symmetry=("Server",), chunk=256)
    start = interp.init_state(bounds)._replace(
        role=(S.LEADER, S.FOLLOWER, S.CANDIDATE),
        term=(2, 3, 3), votedFor=(1, 3, 0),
        vGrant=(0b011, 0, 0b100),
        msgs=bag(mb.rv_response(3, 1, 1, 2)))
    ref = refbfs.check(cfg, init_override=start)
    got = DeviceEngine(cfg, Capacities(n_states=1 << 15, levels=64)
                       ).check(init_override=start)
    assert ref.violation is not None and got.violation is not None
    assert got.violation.state == ref.violation.state
    trace = got.violation.trace
    for (_l, prev), (_label, cur) in zip(trace, trace[1:]):
        succs = [t for _i, t in interp.successors(prev, bounds,
                                                  spec="election")]
        assert cur in succs


def test_too_many_servers_is_loud():
    with pytest.raises(ValueError, match="symmetry"):
        sym.permutations(Bounds(n_servers=7, n_values=1, max_term=2,
                                max_log=0, max_msgs=1))


def test_host_engine_symmetry_parity():
    """Regression: the host-dedup engine must apply the same orbit keys
    (it once silently skipped the reduction while printing the banner)."""
    from raft_tla_tpu import engine
    cfg = CheckConfig(bounds=B2, spec="election", invariants=(),
                      symmetry=("Server",), chunk=64)
    ref = refbfs.check(cfg)
    got = engine.check(cfg)
    assert got.n_states == ref.n_states == 1514
    assert got.levels == ref.levels


def test_value_symmetry_orbit_counts():
    """Value permutations (TLC Permutations(Value)) quotient further:
    values enter only through ClientRequest and flow inertly, so
    Server x Value orbits < Server orbits < raw states, same diameter."""
    bp = Bounds(n_servers=2, n_values=2, max_term=2, max_log=1, max_msgs=2)

    def run(axes):
        return refbfs.check(CheckConfig(bounds=bp, spec="full",
                                        invariants=(), symmetry=axes))
    base, s_only, v_only, sv = (run(()), run(("Server",)), run(("Value",)),
                                run(("Server", "Value")))
    assert base.n_states == 74897
    assert (s_only.n_states, v_only.n_states, sv.n_states) == \
        (37472, 50515, 25281)
    assert base.diameter == s_only.diameter == v_only.diameter == sv.diameter


def test_value_symmetry_engine_parity():
    from raft_tla_tpu import engine
    bp = Bounds(n_servers=2, n_values=2, max_term=2, max_log=1, max_msgs=2)
    cfg = CheckConfig(bounds=bp, spec="full", invariants=("NoTwoLeaders",),
                      symmetry=("Server", "Value"), chunk=512)
    ref = refbfs.check(cfg)
    got = engine.check(cfg)
    assert (got.n_states, got.diameter) == (ref.n_states, ref.diameter)
    assert got.coverage == ref.coverage and got.violation is None


def test_value_symmetry_faithful_mode():
    """Rank-table remaps + bitwise allLogs permutation: faithful spaces
    quotient under Server x Value too, engines in exact agreement."""
    from raft_tla_tpu import engine
    bh = Bounds(n_servers=2, n_values=2, max_term=2, max_log=1, max_msgs=2,
                history=True, max_elections=4)
    cf = CheckConfig(bounds=bh, spec="full",
                     invariants=("NoTwoLeaders", "ElectionSafetyHist"),
                     symmetry=("Server", "Value"), chunk=512)
    ref = refbfs.check(cf)
    got = engine.check(cf)
    assert (ref.n_states, ref.diameter) == (28121, 32)  # of 84572 states
    assert (got.n_states, got.diameter) == (28121, 32)
    assert ref.violation is None and got.violation is None


def test_scan_orbit_fp_bit_identical_to_loop():
    """The scan-compiled orbit pass (build_orbit_fp — ONE transform
    iterated over the group) must produce bit-identical (hi, lo) keys to
    the reference unrolled loop (orbit_fingerprint): checkpointed runs
    resume across the upgrade only if the keys are unchanged."""
    import jax
    import jax.numpy as jnp
    from raft_tla_tpu.ops import fingerprint as fpr
    from raft_tla_tpu.ops import state as st

    def drive(bounds, axes, spec="full", depth=4):
        lay = st.Layout.of(bounds)
        consts = fpr.lane_constants(lay.width)
        # a bag of reachable states: BFS prefix via the interpreter
        frontier = [interp.init_state(bounds)]
        seen = list(frontier)
        for _ in range(depth):
            nxt = []
            for s in frontier:
                nxt += [t for _i, t in interp.successors(s, bounds,
                                                         spec=spec)]
            frontier = nxt[:40]
            seen += frontier
        vecs = np.stack([interp.to_vec(s, bounds) for s in seen])
        structs = jax.vmap(lambda v: st.unpack(v, lay, jnp))(
            jnp.asarray(vecs))
        fn = sym.build_orbit_fp(bounds, axes, jnp.asarray(consts),
                                "allLogs" in lay.shapes)
        hi_s, lo_s = jax.jit(fn)(structs)
        for k, s in enumerate(seen):
            struct = st.unpack(vecs[k], lay, np)
            hi_l, lo_l = sym.orbit_fingerprint(struct, bounds, consts,
                                               np, axes)
            assert (int(hi_s[k]), int(lo_s[k])) == (int(hi_l), int(lo_l)), \
                (axes, k, s)

    b = Bounds(n_servers=3, n_values=2, max_term=2, max_log=1, max_msgs=2)
    drive(b, ("Server",))
    drive(b, ("Value",))
    drive(b, ("Server", "Value"))
    bh = Bounds(n_servers=2, n_values=2, max_term=2, max_log=1, max_msgs=2,
                history=True, max_elections=4)
    drive(bh, ("Server", "Value"))
