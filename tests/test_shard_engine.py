"""Sharded multi-device engine ≡ oracle (SURVEY §4.3-§4.4).

Runs on the 8-device virtual CPU mesh (conftest.py) — the checker's
"multi-node without a cluster" story.  Exploration metrics (state counts,
per-level counts, diameter, transition counts, verdicts) must match refbfs
exactly; per-action coverage matches in total (attribution is interleaving-
dependent — see shard_engine.py module docstring).
"""

import pytest

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.models import interp, refbfs, spec as S
from raft_tla_tpu.ops import msgbits as mb
from raft_tla_tpu.parallel import ShardCapacities, ShardEngine, make_mesh

# smoke tier: cross-section for mid-round changes (pytest -m smoke)
pytestmark = [pytest.mark.smoke, pytest.mark.slow]

CAPS = ShardCapacities(n_states=1 << 12, levels=64)


def bag(*ms):
    return tuple(sorted((m, 1) for m in ms))


def assert_parity(cfg, ndev=8, caps=CAPS, **kw):
    ref = refbfs.check(cfg, **kw)
    got = ShardEngine(cfg, make_mesh(ndev), caps).check(**kw)
    assert got.n_states == ref.n_states
    assert got.diameter == ref.diameter
    assert got.levels == ref.levels
    assert got.n_transitions == ref.n_transitions
    assert sum(got.coverage.values()) == sum(ref.coverage.values())
    assert (got.violation is None) == (ref.violation is None)
    return ref, got


def test_election_2server_parity_8dev():
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=("NoTwoLeaders",), chunk=64)
    _, got = assert_parity(cfg)
    assert got.violation is None and got.n_states > 1000


def test_full_spec_small_parity_8dev():
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=1, max_msgs=2),
                      spec="full",
                      invariants=("NoTwoLeaders", "LogMatching",
                                  "CommittedWithinLog"),
                      chunk=128)
    _, got = assert_parity(cfg, caps=ShardCapacities(n_states=1 << 14,
                                                     levels=64))
    assert got.violation is None
    for fam in (S.RESTART, S.DUPLICATE, S.DROP):
        assert got.coverage[fam] > 0


def test_ndev_invariance():
    """1-, 2- and 8-chip meshes explore the identical state graph."""
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=("NoTwoLeaders",), chunk=32)
    runs = {n: ShardEngine(cfg, make_mesh(n), CAPS).check()
            for n in (1, 2, 8)}
    base = runs[1]
    for n, r in runs.items():
        assert r.n_states == base.n_states, n
        assert r.levels == base.levels, n
        assert r.n_transitions == base.n_transitions, n


def test_violation_trace_replayable_8dev():
    """Seeded NaiveNoTwoLeaders violation: the cross-chip trace must replay.

    The trace may be a different counterexample than refbfs's (discovery
    interleaving), but it must start at Init, follow real transitions, and
    end in a state violating the same invariant.
    """
    bounds = Bounds(n_servers=3, n_values=1, max_term=3, max_log=0,
                    max_msgs=4, max_dup=1)
    cfg = CheckConfig(bounds=bounds, spec="election",
                      invariants=("NaiveNoTwoLeaders",), chunk=256)
    start = interp.init_state(bounds)._replace(
        role=(S.LEADER, S.FOLLOWER, S.CANDIDATE),
        term=(2, 3, 3),
        votedFor=(1, 3, 0),
        vGrant=(0b011, 0, 0b100),
        msgs=bag(mb.rv_response(3, 1, 1, 2)),
    )
    got = ShardEngine(cfg, make_mesh(8), CAPS).check(init_override=start)
    assert got.violation is not None
    assert got.violation.invariant == "NaiveNoTwoLeaders"
    trace = got.violation.trace
    assert trace[0][0] is None and trace[0][1] == start
    for (_l, prev), (_label, cur) in zip(trace, trace[1:]):
        succs = [t for _i, t in interp.successors(prev, bounds,
                                                  spec="election")]
        assert cur in succs
    from raft_tla_tpu.models import invariants as inv_mod
    assert not inv_mod.py_invariant("NaiveNoTwoLeaders")(
        got.violation.state, bounds)


def test_routing_overflow_is_loud():
    """A send buffer too small for one owner's share must abort, not clamp."""
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=(), chunk=64)
    caps = ShardCapacities(n_states=1 << 12, levels=64, send=1)
    with pytest.raises(RuntimeError, match="routing budget"):
        ShardEngine(cfg, make_mesh(8), caps).check()


def test_slice_mesh_2x4_parity():
    """2-D (dcn, ici) mesh with the hierarchical two-stage exchange
    explores the identical state graph: same counts, levels, transitions,
    verdicts as the oracle and (by test_ndev_invariance) the 1-D mesh."""
    from raft_tla_tpu.parallel.shard_engine import make_slice_mesh

    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=("NoTwoLeaders",),
                      chunk=64)
    ref = refbfs.check(cfg)
    got = ShardEngine(cfg, make_slice_mesh(2, 4), CAPS).check()
    assert got.n_states == ref.n_states
    assert got.diameter == ref.diameter
    assert got.levels == ref.levels
    assert got.n_transitions == ref.n_transitions
    assert sum(got.coverage.values()) == sum(ref.coverage.values())
    assert got.violation is None


def test_slice_mesh_checkpoint_portable_from_1d(tmp_path):
    """FP ownership is by FLAT device id, so a 1-D 8-mesh checkpoint
    resumes on a 2x4 slice mesh (same total size) and finishes with
    identical counts."""
    from raft_tla_tpu.parallel.shard_engine import make_slice_mesh

    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=("NoTwoLeaders",),
                      chunk=64)
    straight = ShardEngine(cfg, make_mesh(8), CAPS).check()
    ck = str(tmp_path / "flat.ckpt")
    ShardEngine(cfg, make_mesh(8), CAPS, seg_chunks=8).check(
        checkpoint=ck, checkpoint_every_s=0.0)
    got = ShardEngine(cfg, make_slice_mesh(2, 4), CAPS).check(resume=ck)
    assert got.n_states == straight.n_states
    assert got.levels == straight.levels
    assert got.n_transitions == straight.n_transitions


def test_reshard_checkpoint_across_mesh_sizes(tmp_path):
    """A mid-run 2-device snapshot resharded to 4, 1, and (with grown
    caps) 8 devices resumes with oracle-exact results — a pod-size
    change no longer discards a run.  Also exercises the mid-level
    promotion (expanded window prefix moves to the done region)."""
    from raft_tla_tpu.parallel.shard_engine import reshard_checkpoint

    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=("NoTwoLeaders",),
                      chunk=64)
    ref = refbfs.check(cfg)
    ck = str(tmp_path / "m2.ckpt")
    ShardEngine(cfg, make_mesh(2), CAPS, seg_chunks=8).check(
        checkpoint=ck, checkpoint_every_s=0.0)
    for nd in (4, 1):
        out = str(tmp_path / f"m{nd}.ckpt")
        info = reshard_checkpoint(cfg, CAPS, ck, out, nd)
        assert info["ndev_src"] == 2 and info["ndev_dst"] == nd
        got = ShardEngine(cfg, make_mesh(nd), CAPS).check(resume=out)
        assert got.n_states == ref.n_states
        assert got.levels == ref.levels
        assert got.n_transitions == ref.n_transitions
        assert sum(got.coverage.values()) == sum(ref.coverage.values())
        assert got.violation is None
    big = ShardCapacities(n_states=1 << 13, levels=96)  # grown store AND
    out = str(tmp_path / "m8big.ckpt")                  # levels array
    reshard_checkpoint(cfg, CAPS, ck, out, 8, caps_dst=big)
    got = ShardEngine(cfg, make_mesh(8), big).check(resume=out)
    assert got.n_states == ref.n_states
    assert got.levels == ref.levels


def test_reshard_symmetric_run(tmp_path):
    """Resharding recomputes ORBIT keys when the run has SYMMETRY; the
    resumed orbit counts must stay exact."""
    from raft_tla_tpu.parallel.shard_engine import reshard_checkpoint

    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=("NoTwoLeaders",),
                      symmetry=("Server",), chunk=64)
    ref = refbfs.check(cfg)
    assert ref.n_states == 1514
    ck = str(tmp_path / "sym2.ckpt")
    ShardEngine(cfg, make_mesh(2), CAPS, seg_chunks=8).check(
        checkpoint=ck, checkpoint_every_s=0.0)
    out = str(tmp_path / "sym8.ckpt")
    reshard_checkpoint(cfg, CAPS, ck, out, 8)
    got = ShardEngine(cfg, make_mesh(8), CAPS).check(resume=out)
    assert got.n_states == ref.n_states
    assert got.levels == ref.levels
    assert got.n_transitions == ref.n_transitions


def test_reshard_refuses_finished_and_wrong_digest(tmp_path):
    from raft_tla_tpu.parallel.shard_engine import reshard_checkpoint

    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=("NoTwoLeaders",),
                      chunk=64)
    ck = str(tmp_path / "m2.ckpt")
    ShardEngine(cfg, make_mesh(2), CAPS, seg_chunks=8).check(
        checkpoint=ck, checkpoint_every_s=0.0)
    other = CheckConfig(bounds=cfg.bounds, spec="election",
                        invariants=(), chunk=64)
    with pytest.raises(ValueError, match="digest"):
        reshard_checkpoint(other, CAPS, ck, str(tmp_path / "x.ckpt"), 4)
    tiny = ShardCapacities(n_states=1 << 4, levels=64)
    with pytest.raises(ValueError, match="n_states"):
        reshard_checkpoint(cfg, CAPS, ck, str(tmp_path / "y.ckpt"), 1,
                           caps_dst=tiny)
