"""frontend/twophase: the second bundled spec, end to end.

The acceptance bar from the frontend PR: a protocol that is NOT Raft,
declared entirely as frontend schema + IR, checked through the same
engine/serve/obs stack, with every count pinned against an independent
NumPy BFS oracle (``twophase.reference_check``) at two bound settings —
and the n=3 state count (288) agreeing with TLC's published figure for
the TwoPhase module at RM cardinality 3.
"""

import json

import numpy as np
import pytest

from raft_tla_tpu import engine
from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.frontend import twophase as tp
from raft_tla_tpu.frontend.registry import TwoPhaseModel, resolve_model
from raft_tla_tpu.frontend.schema import Field, Schema, check_schema
from raft_tla_tpu.serve import CheckJob, JobOptions, admit
from raft_tla_tpu.serve.batch import BatchExecutor
from raft_tla_tpu.serve.service import load_jobs, run_service

# Pinned oracle outputs (independently BFS'd; 288 at n=3 matches TLC).
ORACLE = {1: (12, 4, 19), 2: (56, 7, 153), 3: (288, 10, 1145)}

CFG_2PC = ("SPECIFICATION Spec\n"
           "CONSTANT RM = {r1, r2}\n"
           "INVARIANT TCConsistent\n")


def _config(n, invariants=("TCConsistent",), **kw):
    return CheckConfig(bounds=Bounds(n_servers=n, n_values=1),
                       spec="twophase", invariants=invariants,
                       chunk=256, **kw)


# -- oracle and engine parity -------------------------------------------------

@pytest.mark.parametrize("n", [1, 2, 3])
def test_reference_oracle_pinned(n):
    ref = tp.reference_check(n)
    assert (ref.n_states, ref.diameter, ref.n_transitions) == ORACLE[n]
    assert ref.consistent


@pytest.mark.parametrize("n", [2, 3])
def test_engine_matches_oracle(n):
    ref = tp.reference_check(n)
    got = engine.check(_config(n))
    assert got.violation is None
    assert got.n_states == ref.n_states
    assert got.diameter == ref.diameter
    assert got.n_transitions == ref.n_transitions


def test_never_deadlocks():
    # Terminal states keep self-successors (verdict messages redeliver),
    # so TLC's -deadlock analog finds nothing anywhere in the space.
    got = engine.check(_config(2, check_deadlock=True))
    assert got.violation is None
    assert got.n_states == ORACLE[2][0]


# -- codec and schema ---------------------------------------------------------

@pytest.mark.parametrize("n", [1, 2, 3])
def test_state_codec_roundtrip(n):
    b = Bounds(n_servers=n, n_values=1)
    lay = tp.SCHEMA.layout(b)
    assert lay.width == 3 * n + 3
    init = tp.init_state(b)
    vec = tp.to_vec(init, b)
    assert vec.shape == (lay.width,)
    assert tp.from_vec(vec, b) == init
    # pack/unpack consistent with the codec: struct fields mirror TPState
    struct = lay.unpack(vec, np)
    assert list(struct["rmState"]) == list(init.rmState)
    assert int(struct["tmState"][0]) == init.tmState
    # a non-init state round-trips too
    s = init._replace(rmState=(tp.PREPARED,) * n,
                      tmPrepared=(1,) * n, msgPrepared=(1,) * n)
    assert tp.from_vec(tp.to_vec(s, b), b) == s


def test_check_schema_rejects_invalid():
    bad = Schema("bad", (
        Field("x", ("n",), lo=0, hi=2, init=0),
        Field("y", (), lo=5, hi=2, init=5),          # hi < lo
    ))
    codes = [f.code for f in check_schema(bad, Bounds(n_servers=2))]
    assert codes                                     # at least one finding
    assert any("schema" in c for c in codes)
    assert check_schema(tp.SCHEMA, Bounds(n_servers=3)) == []


# -- violations and rendering -------------------------------------------------

def test_expression_invariant_violation_trace():
    """`~any(rmState = 2)` ("no RM ever commits") is falsifiable; the
    trace renders TLC-style through the twophase renderer."""
    got = engine.check(_config(2, invariants=("~any(rmState = 2)",)))
    assert got.violation is not None
    assert got.violation.invariant == "~any(rmState = 2)"
    model = resolve_model("twophase")
    text = model.render_trace(got.violation, Bounds(n_servers=2, n_values=1))
    assert "Invariant ~any(rmState = 2) is violated" in text
    assert "State 1: <Initial predicate>" in text
    assert "rmState" in text and "tmState" in text
    # the final state must actually falsify the predicate
    assert tp.COMMITTED in got.violation.state.rmState


def test_tc_consistent_holds_everywhere():
    ref = tp.reference_check(2)
    assert ref.consistent
    assert engine.check(_config(2)).violation is None


# -- serve: admission, batching, service --------------------------------------

def test_admission_admits_twophase():
    adm = admit(CheckJob("2pc", JobOptions(spec="twophase"),
                         cfg_text=CFG_2PC))
    assert adm.admitted and adm.reason is None
    assert adm.config.spec == "twophase"
    assert adm.config.bounds.n_servers == 2
    assert adm.config.invariants == ("TCConsistent",)


def test_admission_rejects_unknown_spec():
    adm = admit(CheckJob("typo", JobOptions(spec="twophse"),
                         cfg_text=CFG_2PC))
    assert not adm.admitted and adm.reason == "spec-unknown"
    [f] = [f for f in adm.findings if f.code == "spec-unknown"]
    assert "did you mean: twophase" in f.message


def test_admission_rejects_bad_expression():
    bad = CFG_2PC.replace("TCConsistent", "all(bogus = 1)")
    adm = admit(CheckJob("bad", JobOptions(spec="twophase"), cfg_text=bad))
    assert not adm.admitted and adm.reason == "cfg-invalid"


def test_admission_rejects_unsupported_stanzas():
    for extra, frag in [("SYMMETRY Server\n", "symmetry"),
                        ("PROPERTY EventuallyLeader\n", "propert")]:
        adm = admit(CheckJob("x", JobOptions(spec="twophase"),
                             cfg_text=CFG_2PC + extra))
        assert not adm.admitted and adm.reason == "cfg-invalid", extra
        assert any(frag in f.message for f in adm.findings), extra


def test_batch_mixed_raft_and_twophase():
    """One executor, raft and twophase tenants in separate bins; each
    lane's counts equal its solo run."""
    raft_cfg = CheckConfig(
        bounds=Bounds(n_servers=2, n_values=1, max_term=2, max_log=0,
                      max_msgs=2),
        spec="election", invariants=("NoTwoLeaders",), chunk=256)
    out = BatchExecutor(chunk=256).run(
        [("raft", raft_cfg), ("2pc-a", _config(2)), ("2pc-b", _config(3))])
    assert out["raft"].status == "completed"
    assert out["raft"].result.n_states == 3014
    for jid, n in (("2pc-a", 2), ("2pc-b", 3)):
        assert out[jid].status == "completed"
        assert out[jid].result.n_states == ORACLE[n][0]
        assert out[jid].result.n_transitions == ORACLE[n][2]


def test_service_end_to_end_twophase(tmp_path):
    from raft_tla_tpu.obs import monitor, validate_event

    (tmp_path / "2pc.cfg").write_text(CFG_2PC)
    manifest = tmp_path / "manifest.jsonl"
    manifest.write_text(json.dumps(
        {"id": "2pc", "cfg": "2pc.cfg", "spec": "twophase"}) + "\n")
    out_dir = tmp_path / "out"
    records = run_service(load_jobs(str(manifest)), str(out_dir),
                          chunk=256, quiet=True)
    [rec] = records
    assert rec["status"] == "completed"
    assert rec["n_states"] == ORACLE[2][0]
    events = [json.loads(l) for l in open(rec["events"])]
    assert not [e for d in events for e in validate_event(d)]
    assert events[0]["event"] == "run_start"
    assert events[0]["spec"] == "twophase"
    assert events[-1]["event"] == "run_end"
    hb = monitor.heartbeat(monitor.summarize(
        monitor.load_stream(rec["events"])))
    assert "ok" in hb


# -- CLI-facing model surface -------------------------------------------------

def test_model_engine_gate():
    model = resolve_model("twophase")
    assert model.engines == ("host", "simulate")
    assert not model.is_raft


def test_emit_tla(tmp_path):
    model = TwoPhaseModel()
    paths = model.emit_tla(str(tmp_path), Bounds(n_servers=3, n_values=1),
                           invariants=("TCConsistent",))
    texts = {p.rsplit("/", 1)[-1]: open(p).read() for p in paths}
    assert set(texts) == {"MC2pc.tla", "MC2pc.cfg"}
    cfg = texts["MC2pc.cfg"]
    assert "SPECIFICATION Spec" in cfg
    assert "RM = {r1, r2, r3}" in cfg
    assert "INVARIANT" in cfg and "TCConsistent" in cfg
    tla = texts["MC2pc.tla"]
    assert "MODULE MC2pc" in tla
    assert "TCConsistent" in tla
    # expression invariants have no TLA name to emit — refuse loudly
    with pytest.raises(ValueError, match="expression"):
        model.emit_tla(str(tmp_path), Bounds(n_servers=2, n_values=1),
                       invariants=("all(rmState <= 3)",))
