"""Kernel/interpreter differential — SURVEY §4.2.

Every successor lane of the batched JAX kernel must agree with the reference
interpreter: same enabledness, same canonical successor state, on (a) random
bounded states (including unreachable corners like same-term leaders) and
(b) exact reachable prefixes from Init.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from raft_tla_tpu.config import Bounds
from raft_tla_tpu.models import interp, spec as SP
from raft_tla_tpu.ops import kernels, state as st

from test_state import random_pystate

B3 = Bounds(n_servers=3, n_values=2, max_term=3, max_log=2, max_msgs=4)


def _diff_on_states(states, bounds, spec="full"):
    table = SP.action_table(bounds, spec)
    expand = jax.jit(jax.vmap(kernels.build_expand(bounds, spec)))
    structs = [interp.to_struct(s, bounds) for s in states]
    batch = jax.tree.map(lambda *xs: jnp.asarray(np.stack(xs)), *structs)
    succs, valid, ovf = expand(batch)
    succs = jax.tree.map(np.asarray, succs)
    valid = np.asarray(valid)
    ovf = np.asarray(ovf)

    for bi, s in enumerate(states):
        # The +1 capacity scheme guarantees representability only one step
        # past the constraint: overflow must never fire on states the engine
        # would actually expand (constraint-satisfying ones).  Faithful mode
        # is the exception: elections capacity is not constraint-governed
        # (config.py), so its genuineness is checked per-lane below instead.
        if interp.constraint_ok(s, bounds) and not bounds.history:
            assert not ovf[bi].any(), f"overflow on expandable state {s}"
        got_by_lane = {}
        for ai in range(len(table)):
            if valid[bi, ai] and not ovf[bi, ai]:
                lane = jax.tree.map(lambda x: x[bi, ai], succs)
                got_by_lane[ai] = interp.from_struct(lane, bounds)
        want_by_lane = dict(interp.successors(s, bounds, table))
        for ai in range(len(table)):
            if valid[bi, ai] and ovf[bi, ai]:
                # Lane flagged unrepresentable: the interpreter successor must
                # genuinely exceed tensor capacity (bag, log, or — in
                # faithful mode — elections slots).
                t = want_by_lane.pop(ai)
                assert len(t.msgs) > bounds.msg_cap or \
                    any(len(l) > bounds.log_cap for l in t.log) or \
                    (t.elections is not None
                     and len(t.elections) > bounds.max_elections)
        assert set(got_by_lane) == set(want_by_lane), (
            f"state {bi}: enabled lanes differ\n"
            f"kernel-only: {[table[a].label() for a in set(got_by_lane) - set(want_by_lane)]}\n"
            f"interp-only: {[table[a].label() for a in set(want_by_lane) - set(got_by_lane)]}\n"
            f"state: {s}")
        for ai, got in got_by_lane.items():
            assert got == want_by_lane[ai], (
                f"state {bi} lane {table[ai].label()}:\n"
                f"kernel: {got}\ninterp: {want_by_lane[ai]}\nfrom:   {s}")


def test_differential_random_states():
    rng = np.random.default_rng(7)
    states = [random_pystate(rng, B3) for _ in range(200)]
    _diff_on_states(states, B3)


def test_differential_reachable_prefix():
    bounds = Bounds(n_servers=3, n_values=1, max_term=2, max_log=1,
                    max_msgs=2)
    seen = {interp.init_state(bounds)}
    frontier = list(seen)
    for _level in range(4):
        nxt = []
        for s in frontier:
            if not interp.constraint_ok(s, bounds):
                continue
            for _a, t in interp.successors(s, bounds):
                if t not in seen:
                    seen.add(t)
                    nxt.append(t)
        frontier = nxt
    states = sorted(seen, key=lambda s: interp.to_vec(s, bounds).tobytes())
    _diff_on_states(states[:400], bounds)


def test_differential_election_spec():
    bounds = Bounds(n_servers=3, n_values=1, max_term=3, max_log=0,
                    max_msgs=3)
    rng = np.random.default_rng(11)
    states = [random_pystate(rng, bounds) for _ in range(100)]
    _diff_on_states(states, bounds, spec="election")


def test_step_outputs_consistent():
    """build_step: fingerprints/invariants/constraints agree with host."""
    from raft_tla_tpu.ops import fingerprint as fpr
    from raft_tla_tpu.models import invariants as inv_mod

    bounds = B3
    lay = st.Layout.of(bounds)
    rng = np.random.default_rng(13)
    states = [random_pystate(rng, bounds) for _ in range(32)]
    vecs = np.stack([interp.to_vec(s, bounds) for s in states])
    step = jax.jit(kernels.build_step(bounds, "full",
                                      ("NoTwoLeaders", "LogMatching")))
    out = {k: np.asarray(v) for k, v in step(jnp.asarray(vecs)).items()}

    consts = fpr.lane_constants(lay.width)
    h1, h2 = fpr.fingerprint(out["svecs"], consts, np)
    np.testing.assert_array_equal(h1, out["fp_hi"])
    np.testing.assert_array_equal(h2, out["fp_lo"])

    es = inv_mod.py_invariant("NoTwoLeaders")
    lm = inv_mod.py_invariant("LogMatching")
    for bi in range(len(states)):
        for ai in range(out["valid"].shape[1]):
            if not out["valid"][bi, ai] or out["overflow"][bi, ai]:
                continue
            t = interp.from_struct(
                st.unpack(out["svecs"][bi, ai], lay, np), bounds)
            assert out["inv_ok"][bi, ai, 0] == es(t, bounds)
            assert out["inv_ok"][bi, ai, 1] == lm(t, bounds)
            assert out["con_ok"][bi, ai] == interp.constraint_ok(t, bounds)


def test_differential_5server_north_star_universe():
    """The north-star universe (BASELINE config #4: 5 servers, 2 values,
    default bounds): the 90-lane action table and kernels must agree with
    the interpreter on random bounded states, incl. the wider
    bitmask/quorum arithmetic and every message slot."""
    bounds = Bounds(n_servers=5, n_values=2, max_term=3, max_log=2,
                    max_msgs=4)
    table = SP.action_table(bounds, "full")
    assert len(table) == 5 + 5 + 25 + 5 + 10 + 5 + 20 + 3 * bounds.msg_cap
    rng = np.random.default_rng(21)
    states = [random_pystate(rng, bounds) for _ in range(24)]
    states.append(interp.init_state(bounds))
    _diff_on_states(states, bounds, "full")


def test_routed_step_matches_dense():
    """build_step_routed (EP routing, SURVEY §2.9): the compacted stream
    is exactly the dense step's valid lanes, in flat order, with
    identical per-candidate values — and the budget overflow is loud."""
    bounds = B3
    rng = np.random.default_rng(17)
    states = [random_pystate(rng, bounds) for _ in range(16)]
    vecs = jnp.asarray(np.stack([interp.to_vec(s, bounds) for s in states]))
    invs = ("NoTwoLeaders", "LogMatching")
    for sym in ((), ("Server",)):
        dense = jax.jit(kernels.build_step(bounds, "full", invs,
                                           sym))(vecs)
        A = dense["valid"].shape[1]
        N = len(states) * A
        routed = jax.jit(kernels.build_step_routed(
            bounds, "full", invs, sym, k_rows=N))(vecs)
        np.testing.assert_array_equal(dense["valid"], routed["valid"])
        np.testing.assert_array_equal(dense["overflow"],
                                      routed["overflow"])
        fvalid = np.asarray(dense["valid"]).reshape(-1)
        en = np.flatnonzero(fvalid)
        cidx = np.asarray(routed["cidx"])
        assert np.asarray(routed["cvalid"]).sum() == en.size
        np.testing.assert_array_equal(cidx[:en.size], en)
        assert (cidx[en.size:] == N).all()
        assert not bool(routed["route_ovf"])
        W = dense["svecs"].shape[-1]
        np.testing.assert_array_equal(
            np.asarray(routed["csvecs"])[:en.size],
            np.asarray(dense["svecs"]).reshape(N, W)[en])
        for dk, rk in (("fp_hi", "cfp_hi"), ("fp_lo", "cfp_lo"),
                       ("con_ok", "ccon_ok")):
            np.testing.assert_array_equal(
                np.asarray(routed[rk])[:en.size],
                np.asarray(dense[dk]).reshape(N)[en])
        np.testing.assert_array_equal(
            np.asarray(routed["cinv_ok"])[:en.size],
            np.asarray(dense["inv_ok"]).reshape(N, len(invs))[en])
    # a budget below the enabled count must flag, never silently drop
    tight = jax.jit(kernels.build_step_routed(
        bounds, "full", invs, k_rows=max(1, en.size // 2)))(vecs)
    assert bool(tight["route_ovf"])
    # row_ok: dead rows (stale padding / constraint-excluded parents)
    # must not consume routing slots — only live rows' lanes compact
    row_ok = np.arange(len(states)) % 2 == 0
    masked = jax.jit(kernels.build_step_routed(
        bounds, "full", invs, k_rows=N))(vecs, jnp.asarray(row_ok))
    np.testing.assert_array_equal(masked["valid"], dense["valid"])
    live = fvalid & np.repeat(row_ok, A)
    en_live = np.flatnonzero(live)
    assert np.asarray(masked["cvalid"]).sum() == en_live.size
    np.testing.assert_array_equal(
        np.asarray(masked["cidx"])[:en_live.size], en_live)
