"""Third-oracle cross-checks (VERDICT r1 weak #8: single-oracle risk).

``tests/independent_oracle.py`` is a from-scratch transcription of
``/root/reference/raft.tla`` with a different state representation from
``models/interp.py``; these tests pin the two against each other (and
against the hand-derived worksheet, ``runs/worksheet_levels.md``) so a
shared misreading of the spec would have to be made twice, independently,
to survive.
"""

import sys
from collections import Counter
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))

import independent_oracle as oracle

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.models import refbfs

import pytest
# smoke tier: cross-section for mid-round changes (pytest -m smoke)
pytestmark = pytest.mark.smoke

# Hand-derived in runs/worksheet_levels.md, action family by action family
# from raft.tla:155-465 with explicit set-counting: levels 0-4 of the
# reference raft.cfg universe under the t2/l1/m2 constraint.  Levels 5-7
# are the machine-side extension (dual-interpreter identity; worksheet
# "Level 5" section).
WORKSHEET_LEVELS = [1, 3, 18, 76, 279]
DEEP_LEVELS = [1, 3, 18, 76, 279, 921, 2488, 5373]

# Level 4's 27 hand-derived families and their sizes (worksheet "Level
# 4" section, same order of magnitude grouping).
WORKSHEET_L4_FAMILIES = sorted(
    [45, 36, 30, 18, 18, 12, 12] + [9] * 5 + [6] * 6 + [3] * 9,
    reverse=True)

# Level 5's 51 signature families (machine-pinned; the worksheet's
# level-5 section documents the partition and the derived structural
# facts — the full family-by-family prose derivation stops at level 4).
# Every size is divisible by 3: no level-5 state is fixed by the
# 3-cycle server rotation (worksheet derivation sketch).
WORKSHEET_L5_FAMILIES = sorted(
    [90, 90, 78, 72, 60, 36, 30, 30, 27, 27, 24, 21] + [18] * 3
    + [12] * 10 + [9] * 7 + [6] * 14 + [3] * 5, reverse=True)

_BOUNDS = Bounds(n_servers=3, n_values=2, max_term=2, max_log=1,
                 max_msgs=2)


def _bfs_frontiers(init, succ, con, depth):
    """Level-synchronous BFS (TLC CONSTRAINT semantics: CV states are
    counted, never expanded); returns (per-level counts, last frontier).
    One definition for every loop in this file — the level-count and
    partition tests must never desynchronize on expansion semantics."""
    seen, frontier, levels = {init}, [init], [1]
    for _ in range(depth):
        nxt = []
        for s in frontier:
            if not con(s):
                continue
            for t in succ(s):
                if t not in seen:
                    seen.add(t)
                    nxt.append(t)
        levels.append(len(nxt))
        frontier = nxt
    return levels, frontier


def _pkg_frontiers(b, depth):
    from raft_tla_tpu.models import interp

    return _bfs_frontiers(
        interp.init_state(b),
        lambda s: (t for _i, t in interp.successors(s, b, spec="full")),
        lambda s: interp.constraint_ok(s, b), depth)


def _ora_frontiers(depth):
    return _bfs_frontiers(
        oracle.init_state(3),
        lambda s: oracle.successors(s, 3, 2),
        lambda s: oracle.constraint_ok(s, 2, 1, 2, 1), depth)


# The signature separating the worksheet's families: per-server
# (role, term, votedFor?, votes?) multiset, bag size, bag-count
# multiset, CV flag.  ONE definition per interpreter, shared by the
# level-4 and level-5 partition tests (they must pin the same
# signature or the anchors silently diverge).
def _sig_pkg(s):
    from raft_tla_tpu.models import interp

    per = tuple(sorted(
        (r, t, vf != 0, (vr | vg) != 0)
        for r, t, vf, vr, vg in zip(s.role, s.term, s.votedFor,
                                    s.vResp, s.vGrant)))
    return (per, len(s.msgs),
            tuple(sorted(c for _m, c in s.msgs)),
            not interp.constraint_ok(s, _BOUNDS))


_ROLE_CODE = {oracle.FOLLOWER: 0, oracle.CANDIDATE: 1, oracle.LEADER: 2}


def _sig_ora(s):
    per = tuple(sorted(
        (_ROLE_CODE[r], t, vf is not None, bool(vr or vg))
        for r, t, vf, vr, vg in zip(s.role, s.currentTerm, s.votedFor,
                                    s.votesResponded, s.votesGranted)))
    return (per, len(s.messages),
            tuple(sorted(c for _m, c in s.messages)),
            not oracle.constraint_ok(s, 2, 1, 2, 1))


def _assert_partition_identity(depth, expected_sizes):
    """Both interpreters' depth-``depth`` frontiers, partitioned by the
    shared signature: sizes must match the pinned list and the two
    partitions must be identical class by class, not just in size."""
    _levels, frontier = _pkg_frontiers(_BOUNDS, depth)
    cp = Counter(_sig_pkg(s) for s in frontier)
    assert sorted(cp.values(), reverse=True) == expected_sizes
    _olevels, ofrontier = _ora_frontiers(depth)
    co = Counter(_sig_ora(s) for s in ofrontier)
    assert co == cp


def test_worksheet_level4_partition():
    _assert_partition_identity(4, WORKSHEET_L4_FAMILIES)


def test_worksheet_level5_partition():
    """Level 5 (921 states): the machine-side extension of the anchor
    one level past the prose derivation (VERDICT r4 next #8)."""
    _assert_partition_identity(5, WORKSHEET_L5_FAMILIES)


def test_deep_level_agreement_to_seven():
    """Per-level counts agree between the two interpreters through
    level 7 (5,373 states on the frontier), with the hand-derived
    worksheet prefix — a shared misreading of the spec would have to
    reproduce 8 exact level counts twice."""
    levels, _ = _pkg_frontiers(_BOUNDS, 7)
    mini = oracle.bfs(n=3, values=2, max_term=2, max_log=1, max_msgs=2,
                      max_levels=7)
    assert levels == mini == DEEP_LEVELS
    assert DEEP_LEVELS[:5] == WORKSHEET_LEVELS


def test_full_2s1v_space_matches_package_oracle():
    """The complete 2-server/1-value bounded space: the independent
    interpreter, the package oracle, and the round-1 measured number
    (RESULTS.md: 48,041 states, diameter 32) must all agree."""
    mini = oracle.bfs(n=2, values=1, max_term=2, max_log=1, max_msgs=2)
    cfg = CheckConfig(
        bounds=Bounds(n_servers=2, n_values=1, max_term=2, max_log=1,
                      max_msgs=2),
        spec="full", invariants=())
    ref = refbfs.check(cfg)
    assert sum(mini) == ref.n_states == 48041
    assert len(mini) - 1 == ref.diameter == 32
    assert mini == ref.levels
