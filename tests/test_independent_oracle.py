"""Third-oracle cross-checks (VERDICT r1 weak #8: single-oracle risk).

``tests/independent_oracle.py`` is a from-scratch transcription of
``/root/reference/raft.tla`` with a different state representation from
``models/interp.py``; these tests pin the two against each other (and
against the hand-derived worksheet, ``runs/worksheet_levels.md``) so a
shared misreading of the spec would have to be made twice, independently,
to survive.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))

import independent_oracle as oracle

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.models import refbfs


# Hand-derived in runs/worksheet_levels.md, action family by action family
# from raft.tla:155-465 with explicit set-counting: levels 0-4 of the
# reference raft.cfg universe under the t2/l1/m2 constraint.
WORKSHEET_LEVELS = [1, 3, 18, 76, 279]

# Level 4's 27 hand-derived families and their sizes (worksheet "Level
# 4" section, same order of magnitude grouping).
WORKSHEET_L4_FAMILIES = sorted(
    [45, 36, 30, 18, 18, 12, 12] + [9] * 5 + [6] * 6 + [3] * 9,
    reverse=True)


def _bfs_frontiers(init, succ, con, depth):
    """Level-synchronous BFS (TLC CONSTRAINT semantics: CV states are
    counted, never expanded); returns (per-level counts, last frontier).
    One definition for every loop in this file — the level-count and
    partition tests must never desynchronize on expansion semantics."""
    seen, frontier, levels = {init}, [init], [1]
    for _ in range(depth):
        nxt = []
        for s in frontier:
            if not con(s):
                continue
            for t in succ(s):
                if t not in seen:
                    seen.add(t)
                    nxt.append(t)
        levels.append(len(nxt))
        frontier = nxt
    return levels, frontier


def _pkg_frontiers(b, depth):
    from raft_tla_tpu.models import interp

    return _bfs_frontiers(
        interp.init_state(b),
        lambda s: (t for _i, t in interp.successors(s, b, spec="full")),
        lambda s: interp.constraint_ok(s, b), depth)


def _ora_frontiers(depth):
    return _bfs_frontiers(
        oracle.init_state(3),
        lambda s: oracle.successors(s, 3, 2),
        lambda s: oracle.constraint_ok(s, 2, 1, 2, 1), depth)


def test_worksheet_levels_all_three_implementations():
    b = Bounds(n_servers=3, n_values=2, max_term=2, max_log=1, max_msgs=2)
    levels, _ = _pkg_frontiers(b, 5)
    # the independent transcription
    mini = oracle.bfs(n=3, values=2, max_term=2, max_log=1, max_msgs=2,
                      max_levels=5)
    assert levels[:5] == WORKSHEET_LEVELS
    assert mini[:5] == WORKSHEET_LEVELS
    # beyond the hand-derived prefix the two interpreters must still agree
    assert levels[5] == mini[5]


def test_worksheet_level4_partition():
    """The worksheet's 27 level-4 families (hand-derived counts) must
    partition the actual level-4 states of BOTH interpreters — and the
    two partitions must be identical class by class, not just in size.
    The signature (per-server (role, term, votedFor?, votes?) multiset,
    bag shape, CV flag) separates exactly the worksheet's families."""
    from collections import Counter

    from raft_tla_tpu.models import interp

    b = Bounds(n_servers=3, n_values=2, max_term=2, max_log=1, max_msgs=2)
    _levels, frontier = _pkg_frontiers(b, 4)

    def sig_pkg(s):
        per = tuple(sorted(
            (r, t, vf != 0, (vr | vg) != 0)
            for r, t, vf, vr, vg in zip(s.role, s.term, s.votedFor,
                                        s.vResp, s.vGrant)))
        return (per, len(s.msgs),
                tuple(sorted(c for _m, c in s.msgs)),
                not interp.constraint_ok(s, b))

    cp = Counter(sig_pkg(s) for s in frontier)
    assert sorted(cp.values(), reverse=True) == WORKSHEET_L4_FAMILIES

    role_code = {oracle.FOLLOWER: 0, oracle.CANDIDATE: 1,
                 oracle.LEADER: 2}
    _olevels, ofrontier = _ora_frontiers(4)

    def sig_ora(s):
        per = tuple(sorted(
            (role_code[r], t, vf is not None, bool(vr or vg))
            for r, t, vf, vr, vg in zip(s.role, s.currentTerm,
                                        s.votedFor, s.votesResponded,
                                        s.votesGranted)))
        return (per, len(s.messages),
                tuple(sorted(c for _m, c in s.messages)),
                not oracle.constraint_ok(s, 2, 1, 2, 1))

    co = Counter(sig_ora(s) for s in ofrontier)
    assert co == cp


def test_full_2s1v_space_matches_package_oracle():
    """The complete 2-server/1-value bounded space: the independent
    interpreter, the package oracle, and the round-1 measured number
    (RESULTS.md: 48,041 states, diameter 32) must all agree."""
    mini = oracle.bfs(n=2, values=1, max_term=2, max_log=1, max_msgs=2)
    cfg = CheckConfig(
        bounds=Bounds(n_servers=2, n_values=1, max_term=2, max_log=1,
                      max_msgs=2),
        spec="full", invariants=())
    ref = refbfs.check(cfg)
    assert sum(mini) == ref.n_states == 48041
    assert len(mini) - 1 == ref.diameter == 32
    assert mini == ref.levels
