"""Third-oracle cross-checks (VERDICT r1 weak #8: single-oracle risk).

``tests/independent_oracle.py`` is a from-scratch transcription of
``/root/reference/raft.tla`` with a different state representation from
``models/interp.py``; these tests pin the two against each other (and
against the hand-derived worksheet, ``runs/worksheet_levels.md``) so a
shared misreading of the spec would have to be made twice, independently,
to survive.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))

import independent_oracle as oracle

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.models import refbfs


# Hand-derived in runs/worksheet_levels.md, action family by action family
# from raft.tla:155-465 with explicit set-counting: levels 0-3 of the
# reference raft.cfg universe under the t2/l1/m2 constraint.
WORKSHEET_LEVELS = [1, 3, 18, 76]


def test_worksheet_levels_all_three_implementations():
    b = Bounds(n_servers=3, n_values=2, max_term=2, max_log=1, max_msgs=2)
    # the package oracle
    from raft_tla_tpu.models import interp
    init = interp.init_state(b)
    seen, frontier, levels = {init}, [init], [1]
    for _ in range(4):
        nxt = []
        for s in frontier:
            if not interp.constraint_ok(s, b):
                continue
            for _i, t in interp.successors(s, b, spec="full"):
                if t not in seen:
                    seen.add(t)
                    nxt.append(t)
        levels.append(len(nxt))
        frontier = nxt
    # the independent transcription
    mini = oracle.bfs(n=3, values=2, max_term=2, max_log=1, max_msgs=2,
                      max_levels=4)
    assert levels[:4] == WORKSHEET_LEVELS
    assert mini[:4] == WORKSHEET_LEVELS
    # beyond the hand-derived prefix the two interpreters must still agree
    assert levels[4] == mini[4]


def test_full_2s1v_space_matches_package_oracle():
    """The complete 2-server/1-value bounded space: the independent
    interpreter, the package oracle, and the round-1 measured number
    (RESULTS.md: 48,041 states, diameter 32) must all agree."""
    mini = oracle.bfs(n=2, values=1, max_term=2, max_log=1, max_msgs=2)
    cfg = CheckConfig(
        bounds=Bounds(n_servers=2, n_values=1, max_term=2, max_log=1,
                      max_msgs=2),
        spec="full", invariants=())
    ref = refbfs.check(cfg)
    assert sum(mini) == ref.n_states == 48041
    assert len(mini) - 1 == ref.diameter == 32
    assert mini == ref.levels
