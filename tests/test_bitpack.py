"""Bit-packed rows: lossless round-trip on every representable state.

Packing is storage only — fingerprints, kernels, and the interpreter all
work on the W-form — so the single correctness property is that
``unpack(pack(v)) == v`` for every vector whose elements fit their
bounds-derived field capacities, including the extreme corners.
"""

import numpy as np
import pytest

from raft_tla_tpu.config import Bounds
from raft_tla_tpu.models import interp
from raft_tla_tpu.ops import bitpack, state as st

BOUNDS = [
    Bounds(),                                                    # defaults
    Bounds(n_servers=2, n_values=1, max_term=2, max_log=0, max_msgs=2),
    Bounds(n_servers=3, n_values=2, max_term=2, max_log=1, max_msgs=2),
    Bounds(n_servers=5, n_values=2, max_term=3, max_log=2, max_msgs=4),
    Bounds(n_servers=14, n_values=15, max_term=62, max_log=2, max_msgs=3),
]


def _max_per_position(schema: bitpack.BitSchema) -> np.ndarray:
    return (1 << schema.bits.astype(np.int64)) - 1


@pytest.mark.parametrize("bounds", BOUNDS)
def test_roundtrip_random_and_corners(bounds):
    schema = bitpack.BitSchema(bounds)
    assert schema.P < schema.W                  # it actually compresses
    rng = np.random.default_rng(3)
    mx = _max_per_position(schema)
    vec = rng.integers(0, mx + 1, size=(256, schema.W)).astype(np.int32)
    vec[0] = 0                                  # all-min corner
    vec[1] = mx                                 # all-max corner
    out = schema.unpack(schema.pack(vec, np), np)
    np.testing.assert_array_equal(out, vec)


def test_roundtrip_jnp_matches_numpy():
    import jax.numpy as jnp
    bounds = Bounds(n_servers=3, n_values=2, max_term=2, max_log=1,
                    max_msgs=2)
    schema = bitpack.BitSchema(bounds)
    rng = np.random.default_rng(4)
    vec = rng.integers(0, _max_per_position(schema) + 1,
                       size=(64, schema.W)).astype(np.int32)
    packed_np = schema.pack(vec, np)
    packed_j = np.asarray(schema.pack(jnp.asarray(vec), jnp))
    np.testing.assert_array_equal(packed_np, packed_j)
    np.testing.assert_array_equal(
        np.asarray(schema.unpack(jnp.asarray(packed_np), jnp)), vec)


def test_roundtrip_all_reachable_states():
    """Every state of a real exhaustive run survives the round-trip."""
    bounds = Bounds(n_servers=2, n_values=1, max_term=2, max_log=1,
                    max_msgs=2)
    schema = bitpack.BitSchema(bounds)
    # Walk BFS levels by hand with TLC CONSTRAINT gating (states violating
    # the constraint are representable but never expanded) — the exact
    # domain the engines pack.
    frontier = [interp.init_state(bounds)]
    seen = set(frontier)
    for _ in range(4):
        nxt = []
        for s in frontier:
            if not interp.constraint_ok(s, bounds):
                continue
            for _i, t in interp.successors(s, bounds, spec="full"):
                if t not in seen:
                    seen.add(t)
                    nxt.append(t)
        frontier = nxt
    vecs = np.stack([interp.to_vec(s, bounds) for s in seen]).astype(np.int32)
    out = schema.unpack(schema.pack(vecs, np), np)
    np.testing.assert_array_equal(out, vecs)


# Satellite of the speclint PR: property-style coverage of field_bits at
# the exact capacity edge.  n and max_log sweep the packing-sensitive
# axes (votedFor/vResp widths track n; index widths track log_cap); the
# faithful rows use bounds small enough for the 1024-entry log universe.
EDGE_BOUNDS = [
    Bounds(n_servers=3, max_log=2, history=False),
    Bounds(n_servers=3, max_log=3, history=False),
    Bounds(n_servers=5, max_log=2, history=False),
    Bounds(n_servers=5, max_log=3, history=False),
    Bounds(n_servers=3, n_values=1, max_term=2, max_log=2, history=True),
    Bounds(n_servers=5, n_values=1, max_term=2, max_log=3, history=True),
]


@pytest.mark.parametrize("bounds", EDGE_BOUNDS)
def test_exact_maxima_roundtrip(bounds):
    """Every position at exactly its field maximum survives pack→unpack —
    the widths field_bits allots really hold their extreme value."""
    schema = bitpack.BitSchema(bounds)
    mx = _max_per_position(schema)
    # One vector per position: that position at max, others at 0; plus
    # the all-max corner (cross-field carry/straddle interactions).
    vecs = np.zeros((schema.W + 1, schema.W), dtype=np.int64)
    np.fill_diagonal(vecs[:schema.W], mx)
    vecs[schema.W] = mx
    vecs = vecs.astype(np.int32)
    out = schema.unpack(schema.pack(vecs, np), np)
    np.testing.assert_array_equal(out, vecs)


@pytest.mark.parametrize("bounds", EDGE_BOUNDS)
def test_one_past_maximum_truncates(bounds):
    """One past the maximum is NOT representable: pack masks it and the
    round-trip visibly differs — the truncation the static analyzer
    (analysis/widthcheck) proves no kernel can trigger."""
    schema = bitpack.BitSchema(bounds)
    for w in range(schema.W):
        bits = int(schema.bits[w])
        if bits >= 31:
            continue                     # 1<<31 overflows int32: raw field
        vec = np.zeros((1, schema.W), dtype=np.int32)
        vec[0, w] = np.int32(1 << bits)
        out = schema.unpack(schema.pack(vec, np), np)
        assert out[0, w] == 0, f"position {w} did not truncate"
        assert not np.array_equal(out, vec)


@pytest.mark.parametrize("bounds", EDGE_BOUNDS)
def test_width_table_consistent(bounds):
    """width_table is the analyzer's contract: it must agree with the
    BitSchema actually used to pack rows."""
    table = bitpack.width_table(bounds)
    schema = bitpack.BitSchema(bounds)
    lay = st.Layout.of(bounds)
    assert table["total_bits"] == schema.total_bits
    assert table["packed_words"] == schema.P
    assert table["flat_words"] == lay.width
    assert set(table["bits"]) == set(lay.fields)
    for f in table["raw"]:
        assert table["bits"][f] == 32


def test_density_on_flagship_layout():
    bounds = Bounds(n_servers=3, n_values=2, max_term=2, max_log=1,
                    max_msgs=2)
    schema = bitpack.BitSchema(bounds)
    assert schema.W == 60
    assert schema.P * 4 <= 60               # >= 4x denser than the W-form
