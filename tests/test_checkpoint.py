"""Checkpoint/resume: the search is a pure function of the carry, so a
resumed run must be bit-exact with an uninterrupted one (SURVEY §5 —
TLC's ``states/`` + ``-recover`` analog)."""

import numpy as np
import pytest

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.device_engine import Capacities, DeviceEngine

CFG = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                max_log=0, max_msgs=2),
                  spec="election", invariants=("NoTwoLeaders",), chunk=32)
CAPS = Capacities(n_states=1 << 13, levels=64)


def test_checkpoint_resume_bit_exact(tmp_path):
    ckpt = str(tmp_path / "search.ckpt")
    eng = DeviceEngine(CFG, CAPS, seg_chunks=8)
    eng.SEG_MAX = 8                      # force many segments on a small space
    straight = eng.check()
    # checkpoint_every_s=0: a snapshot after every segment; the file left
    # behind is a mid-search carry from just before the final segments.
    eng2 = DeviceEngine(CFG, CAPS, seg_chunks=8)
    eng2.SEG_MAX = 8
    res = eng2.check(checkpoint=ckpt, checkpoint_every_s=0.0)
    assert res.n_states == straight.n_states

    eng3 = DeviceEngine(CFG, CAPS, seg_chunks=8)
    eng3.SEG_MAX = 8
    resumed = eng3.check(resume=ckpt)
    assert resumed.n_states == straight.n_states
    assert resumed.diameter == straight.diameter
    assert resumed.levels == straight.levels
    assert resumed.n_transitions == straight.n_transitions
    assert resumed.coverage == straight.coverage
    assert resumed.violation is None


def test_checkpoint_shape_mismatch_is_loud(tmp_path):
    ckpt = str(tmp_path / "search.ckpt")
    eng = DeviceEngine(CFG, CAPS, seg_chunks=8)
    eng.SEG_MAX = 8
    eng.check(checkpoint=ckpt, checkpoint_every_s=0.0)
    other = DeviceEngine(CFG, Capacities(n_states=1 << 14, levels=64))
    with pytest.raises(ValueError, match="checkpoint"):
        other.check(resume=ckpt)


def test_checkpoint_file_is_atomic_npz(tmp_path):
    ckpt = str(tmp_path / "search.ckpt")
    eng = DeviceEngine(CFG, CAPS, seg_chunks=8)
    eng.SEG_MAX = 8
    eng.check(checkpoint=ckpt, checkpoint_every_s=0.0)
    with np.load(ckpt) as z:
        assert int(z["width"]) == eng.lay.width
        assert z["c0"].shape == (CAPS.n_states, eng.lay.width)
    assert not (tmp_path / "search.ckpt.tmp").exists()


def test_paged_checkpoint_resume_bit_exact(tmp_path):
    from raft_tla_tpu.paged_engine import PagedCapacities, PagedEngine
    ckpt = str(tmp_path / "paged.ckpt")
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=("NoTwoLeaders",),
                      chunk=16)
    caps = PagedCapacities(ring=2048, table=1 << 13, levels=64)
    eng = PagedEngine(cfg, caps, seg_chunks=8)
    eng.SEG_MAX = 8
    straight = eng.check()
    eng2 = PagedEngine(cfg, caps, seg_chunks=8)
    eng2.SEG_MAX = 8
    eng2.check(checkpoint=ckpt, checkpoint_every_s=0.0)
    eng3 = PagedEngine(cfg, caps, seg_chunks=8)
    eng3.SEG_MAX = 8
    resumed = eng3.check(resume=ckpt)
    assert resumed.n_states == straight.n_states == 3014
    assert resumed.levels == straight.levels
    assert resumed.coverage == straight.coverage
    assert resumed.n_transitions == straight.n_transitions

    other = PagedEngine(cfg, PagedCapacities(ring=4096, table=1 << 13,
                                             levels=64))
    with pytest.raises(ValueError, match="checkpoint"):
        other.check(resume=ckpt)


def test_stream_rows_width_mismatch_rejected(tmp_path):
    """A packed-row layout change must refuse to resume old streams: the
    config digest does not cover the bit-pack schema (review finding)."""
    import numpy as np
    from raft_tla_tpu.utils import ckpt
    p = str(tmp_path / "s.rows")
    ckpt.stream_rows_out(p, lambda st, n: np.zeros((n, 3), np.int32), 5, 3)
    got = []
    ckpt.stream_rows_in(p, got.append, 5, expect_width=3)
    assert sum(b.shape[0] for b in got) == 5
    with pytest.raises(ValueError, match="row width"):
        ckpt.stream_rows_in(p, got.append, 5, expect_width=4)


@pytest.mark.slow      # virtual-mesh test (see test_shard_engine)
def test_shard_checkpoint_resume_bit_exact(tmp_path):
    """Same carry-purity argument on the 8-device mesh: a snapshot taken
    mid-search resumes to the identical result (and a different mesh size
    is rejected — the FP-ownership map depends on it)."""
    from raft_tla_tpu.parallel.shard_engine import (ShardCapacities,
                                                    ShardEngine, make_mesh)
    ck = str(tmp_path / "shard.ckpt")
    caps = ShardCapacities(n_states=1 << 12, levels=64)

    def eng(n=8):
        e = ShardEngine(CFG, make_mesh(n), caps, seg_chunks=8)
        e.SEG_MAX = 8
        return e

    straight = eng().check()
    res = eng().check(checkpoint=ck, checkpoint_every_s=0.0)
    assert res.n_states == straight.n_states
    resumed = eng().check(resume=ck)
    assert resumed.n_states == straight.n_states
    assert resumed.diameter == straight.diameter
    assert resumed.levels == straight.levels
    assert resumed.n_transitions == straight.n_transitions
    assert resumed.coverage == straight.coverage
    assert resumed.violation is None

    with pytest.raises(ValueError, match="checkpoint"):
        eng(4).check(resume=ck)


def test_digest_covers_deadlock_toggle():
    """Resuming a non-deadlock checkpoint under --deadlock would silently
    skip dead states in the explored region (review finding); the digest
    must split on the toggle — but stay stable when it is off (default
    omission keeps old checkpoints valid)."""
    import dataclasses
    from raft_tla_tpu.utils import ckpt
    base = ckpt.config_digest(CFG, CAPS, (1, 2))
    on = ckpt.config_digest(dataclasses.replace(CFG, check_deadlock=True),
                            CAPS, (1, 2))
    assert base != on


def test_stream_rows_append_incremental(tmp_path):
    """Append-only snapshot streams: extending in place must be byte-
    equivalent to a full rewrite, survive a torn append (garbage past the
    header count), cap at an older header, and reject nothing silently."""
    from raft_tla_tpu.utils import ckpt

    data = np.arange(20 * 3, dtype=np.int32).reshape(20, 3)

    def reader(start, n):
        return data[start:start + n]

    p = str(tmp_path / "s.rows")
    # fresh append == full write
    ckpt.stream_rows_append(p, reader, 8, 3)
    got = []
    ckpt.stream_rows_in(p, got.append, 8, expect_width=3)
    assert np.array_equal(np.concatenate(got), data[:8])
    # incremental extension
    ckpt.stream_rows_append(p, reader, 15, 3)
    got = []
    ckpt.stream_rows_in(p, got.append, 15, expect_width=3)
    assert np.array_equal(np.concatenate(got), data[:15])
    # torn append: garbage beyond the header count is dropped on the
    # next snapshot (truncate-to-header before appending)
    with open(p, "ab") as f:
        np.full((7,), -999, np.int32).tofile(f)
    ckpt.stream_rows_append(p, reader, 18, 3)
    got = []
    ckpt.stream_rows_in(p, got.append, 18, expect_width=3)
    assert np.array_equal(np.concatenate(got), data[:18])
    # width change falls back to a full rewrite
    data2 = np.arange(6 * 4, dtype=np.int32).reshape(6, 4)
    ckpt.stream_rows_append(p, lambda s, n: data2[s:s + n], 6, 4)
    got = []
    ckpt.stream_rows_in(p, got.append, 6, expect_width=4)
    assert np.array_equal(np.concatenate(got), data2)


def test_stream_append_shrink_and_stale_protection(tmp_path):
    """The shrink path (end below the current header) and the engine's
    stale-stream hygiene: a fresh run pointed at an existing checkpoint
    path must not inherit another run's stream prefix."""
    from raft_tla_tpu.utils import ckpt
    data = np.arange(20 * 3, dtype=np.int32).reshape(20, 3)

    def reader(start, n):
        return data[start:start + n]

    p = str(tmp_path / "s.rows")
    ckpt.stream_rows_append(p, reader, 15, 3)
    # shrink: trusted prefix capped below the header (resume from an
    # older npz), then re-extended — rows must be the reader's, readable
    ckpt.trim_stream(p, 10, 3)
    got = []
    ckpt.stream_rows_in(p, got.append, 10, expect_width=3)
    assert np.array_equal(np.concatenate(got), data[:10])
    ckpt.stream_rows_append(p, reader, 12, 3)
    got = []
    ckpt.stream_rows_in(p, got.append, 12, expect_width=3)
    assert np.array_equal(np.concatenate(got), data[:12])
    # append with end below header: file caps at end
    ckpt.stream_rows_append(p, reader, 5, 3)
    got = []
    ckpt.stream_rows_in(p, got.append, 5, expect_width=3)
    assert np.array_equal(np.concatenate(got), data[:5])

    # a FRESH StreamedEngine run pointed at a path holding another run's
    # streams must rewrite them from scratch (not append-reuse)
    from raft_tla_tpu.config import Bounds, CheckConfig
    from raft_tla_tpu.streamed_engine import (StreamedCapacities,
                                              StreamedEngine)
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=("NoTwoLeaders",),
                      chunk=32)
    caps = StreamedCapacities(block=256, ring=4096, table=1 << 14,
                              levels=64)
    ck = str(tmp_path / "fresh.ckpt")
    # plant a bogus stream at the checkpoint path
    ckpt.stream_rows_out(ck + ".rows", lambda s, n: np.full(
        (n, StreamedEngine(cfg, caps).schema.P), -7, np.int32), 100,
        StreamedEngine(cfg, caps).schema.P)
    eng = StreamedEngine(cfg, caps, seg_chunks=8)
    eng.SEG_MAX = 8
    straight = eng.check(checkpoint=ck, checkpoint_every_s=0.0)
    eng2 = StreamedEngine(cfg, caps, seg_chunks=8)
    resumed = eng2.check(resume=ck)
    assert resumed.n_states == straight.n_states == 3014
    assert resumed.levels == straight.levels


# -- content-digest seal (campaign supervision satellite) -------------------
# atomic_savez embeds a sha over every array; load_npz_verified checks
# it — the integrity/identity split the campaign supervisor relies on
# (CheckpointCorrupt -> quarantine, ValueError -> operator error).


def test_content_digest_round_trip_and_atomicity(tmp_path):
    import os

    from raft_tla_tpu.utils import ckpt as C

    p = str(tmp_path / "s.npz")
    C.atomic_savez(p, a=np.arange(5), config_digest=np.uint64(3))
    assert not os.path.exists(p + ".tmp")        # rename committed
    with C.load_npz_verified(p) as z:
        assert "content_sha" in z.files
        np.testing.assert_array_equal(z["a"], np.arange(5))
    with C.load_npz_checked(p, 3) as z:          # identity also OK
        np.testing.assert_array_equal(z["a"], np.arange(5))


def test_truncated_npz_is_checkpoint_corrupt(tmp_path):
    import os

    from raft_tla_tpu.utils import ckpt as C

    p = str(tmp_path / "s.npz")
    C.atomic_savez(p, a=np.arange(100), config_digest=np.uint64(3))
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 2)
    with pytest.raises(C.CheckpointCorrupt, match="s.npz"):
        C.load_npz_verified(p)


def test_content_digest_mismatch_is_checkpoint_corrupt(tmp_path):
    from raft_tla_tpu.utils import ckpt as C

    p = str(tmp_path / "s.npz")
    # intact zip, lying seal: bit-rot the digest can see but zip can't
    np.savez(p, a=np.arange(5), config_digest=np.uint64(3),
             content_sha="0" * 64)
    with pytest.raises(C.CheckpointCorrupt, match="content digest"):
        C.load_npz_verified(p)


def test_legacy_snapshot_without_seal_still_loads(tmp_path):
    from raft_tla_tpu.utils import ckpt as C

    p = str(tmp_path / "s.npz")
    np.savez(p, a=np.arange(5), config_digest=np.uint64(3))
    with C.load_npz_verified(p) as z:            # pre-seal format
        np.testing.assert_array_equal(z["a"], np.arange(5))


def test_config_digest_mismatch_is_value_error_not_corrupt(tmp_path):
    from raft_tla_tpu.utils import ckpt as C

    p = str(tmp_path / "s.npz")
    C.atomic_savez(p, a=np.arange(5), config_digest=np.uint64(3))
    with pytest.raises(ValueError, match="different model config") \
            as exc:
        C.load_npz_checked(p, 4)
    assert not isinstance(exc.value, C.CheckpointCorrupt)
