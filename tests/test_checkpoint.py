"""Checkpoint/resume: the search is a pure function of the carry, so a
resumed run must be bit-exact with an uninterrupted one (SURVEY §5 —
TLC's ``states/`` + ``-recover`` analog)."""

import numpy as np
import pytest

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.device_engine import Capacities, DeviceEngine

CFG = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                max_log=0, max_msgs=2),
                  spec="election", invariants=("NoTwoLeaders",), chunk=32)
CAPS = Capacities(n_states=1 << 13, levels=64)


def test_checkpoint_resume_bit_exact(tmp_path):
    ckpt = str(tmp_path / "search.ckpt")
    eng = DeviceEngine(CFG, CAPS, seg_chunks=8)
    eng.SEG_MAX = 8                      # force many segments on a small space
    straight = eng.check()
    # checkpoint_every_s=0: a snapshot after every segment; the file left
    # behind is a mid-search carry from just before the final segments.
    eng2 = DeviceEngine(CFG, CAPS, seg_chunks=8)
    eng2.SEG_MAX = 8
    res = eng2.check(checkpoint=ckpt, checkpoint_every_s=0.0)
    assert res.n_states == straight.n_states

    eng3 = DeviceEngine(CFG, CAPS, seg_chunks=8)
    eng3.SEG_MAX = 8
    resumed = eng3.check(resume=ckpt)
    assert resumed.n_states == straight.n_states
    assert resumed.diameter == straight.diameter
    assert resumed.levels == straight.levels
    assert resumed.n_transitions == straight.n_transitions
    assert resumed.coverage == straight.coverage
    assert resumed.violation is None


def test_checkpoint_shape_mismatch_is_loud(tmp_path):
    ckpt = str(tmp_path / "search.ckpt")
    eng = DeviceEngine(CFG, CAPS, seg_chunks=8)
    eng.SEG_MAX = 8
    eng.check(checkpoint=ckpt, checkpoint_every_s=0.0)
    other = DeviceEngine(CFG, Capacities(n_states=1 << 14, levels=64))
    with pytest.raises(ValueError, match="checkpoint"):
        other.check(resume=ckpt)


def test_checkpoint_file_is_atomic_npz(tmp_path):
    ckpt = str(tmp_path / "search.ckpt")
    eng = DeviceEngine(CFG, CAPS, seg_chunks=8)
    eng.SEG_MAX = 8
    eng.check(checkpoint=ckpt, checkpoint_every_s=0.0)
    with np.load(ckpt) as z:
        assert int(z["width"]) == eng.lay.width
        assert z["c0"].shape == (CAPS.n_states, eng.lay.width)
    assert not (tmp_path / "search.ckpt.tmp").exists()


def test_paged_checkpoint_resume_bit_exact(tmp_path):
    from raft_tla_tpu.paged_engine import PagedCapacities, PagedEngine
    ckpt = str(tmp_path / "paged.ckpt")
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=("NoTwoLeaders",),
                      chunk=16)
    caps = PagedCapacities(ring=2048, table=1 << 13, levels=64)
    eng = PagedEngine(cfg, caps, seg_chunks=8)
    eng.SEG_MAX = 8
    straight = eng.check()
    eng2 = PagedEngine(cfg, caps, seg_chunks=8)
    eng2.SEG_MAX = 8
    eng2.check(checkpoint=ckpt, checkpoint_every_s=0.0)
    eng3 = PagedEngine(cfg, caps, seg_chunks=8)
    eng3.SEG_MAX = 8
    resumed = eng3.check(resume=ckpt)
    assert resumed.n_states == straight.n_states == 3014
    assert resumed.levels == straight.levels
    assert resumed.coverage == straight.coverage
    assert resumed.n_transitions == straight.n_transitions

    other = PagedEngine(cfg, PagedCapacities(ring=4096, table=1 << 13,
                                             levels=64))
    with pytest.raises(ValueError, match="checkpoint"):
        other.check(resume=ckpt)


def test_stream_rows_width_mismatch_rejected(tmp_path):
    """A packed-row layout change must refuse to resume old streams: the
    config digest does not cover the bit-pack schema (review finding)."""
    import numpy as np
    from raft_tla_tpu.utils import ckpt
    p = str(tmp_path / "s.rows")
    ckpt.stream_rows_out(p, lambda st, n: np.zeros((n, 3), np.int32), 5, 3)
    got = []
    ckpt.stream_rows_in(p, got.append, 5, expect_width=3)
    assert sum(b.shape[0] for b in got) == 5
    with pytest.raises(ValueError, match="row width"):
        ckpt.stream_rows_in(p, got.append, 5, expect_width=4)


def test_shard_checkpoint_resume_bit_exact(tmp_path):
    """Same carry-purity argument on the 8-device mesh: a snapshot taken
    mid-search resumes to the identical result (and a different mesh size
    is rejected — the FP-ownership map depends on it)."""
    from raft_tla_tpu.parallel.shard_engine import (ShardCapacities,
                                                    ShardEngine, make_mesh)
    ck = str(tmp_path / "shard.ckpt")
    caps = ShardCapacities(n_states=1 << 12, levels=64)

    def eng(n=8):
        e = ShardEngine(CFG, make_mesh(n), caps, seg_chunks=8)
        e.SEG_MAX = 8
        return e

    straight = eng().check()
    res = eng().check(checkpoint=ck, checkpoint_every_s=0.0)
    assert res.n_states == straight.n_states
    resumed = eng().check(resume=ck)
    assert resumed.n_states == straight.n_states
    assert resumed.diameter == straight.diameter
    assert resumed.levels == straight.levels
    assert resumed.n_transitions == straight.n_transitions
    assert resumed.coverage == straight.coverage
    assert resumed.violation is None

    with pytest.raises(ValueError, match="checkpoint"):
        eng(4).check(resume=ck)


def test_digest_covers_deadlock_toggle():
    """Resuming a non-deadlock checkpoint under --deadlock would silently
    skip dead states in the explored region (review finding); the digest
    must split on the toggle — but stay stable when it is off (default
    omission keeps old checkpoints valid)."""
    import dataclasses
    from raft_tla_tpu.utils import ckpt
    base = ckpt.config_digest(CFG, CAPS, (1, 2))
    on = ckpt.config_digest(dataclasses.replace(CFG, check_deadlock=True),
                            CAPS, (1, 2))
    assert base != on
