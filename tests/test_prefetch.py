"""Upload-prefetch layer (utils/prefetch.py).

The `BlockPrefetcher` sits between the DDD harvest loops and the host
stores; its gates are protocol-level: hits return exactly what the
loader produced for the requested range, misses fall back to a
synchronous load on the caller's thread, invalidation discards staged
AND in-flight work before returning (so stop paths and frontier
rotations never race a store read), stale generations are dropped, and
worker exceptions surface on the main thread — never silently.
"""

import threading
import time

import pytest

from raft_tla_tpu.utils import prefetch
from raft_tla_tpu.utils.prefetch import BlockPrefetcher, prefetch_enabled

pytestmark = pytest.mark.smoke


# -- gate resolution --------------------------------------------------------


def test_gate_forced_arms():
    assert prefetch_enabled("on") is True
    assert prefetch_enabled("off") is False
    assert prefetch_enabled(" ON ") is True     # trimmed, case-folded
    assert prefetch_enabled("OFF") is False


def test_gate_auto_follows_cpu_count(monkeypatch):
    import os
    monkeypatch.setattr(os, "cpu_count", lambda: 1)
    assert prefetch_enabled("auto") is False
    monkeypatch.setattr(os, "cpu_count", lambda: 4)
    assert prefetch_enabled("auto") is True
    monkeypatch.setattr(os, "cpu_count", lambda: None)
    assert prefetch_enabled("auto") is False


def test_gate_reads_env(monkeypatch):
    monkeypatch.setenv(prefetch.ENV_PREFETCH, "on")
    assert prefetch_enabled() is True
    monkeypatch.setenv(prefetch.ENV_PREFETCH, "off")
    assert prefetch_enabled() is False


# -- hit / miss protocol ----------------------------------------------------


def _tracking_loader(calls):
    def loader(start, rows, slot):
        calls.append((start, rows, slot, threading.current_thread().name))
        return ("data", start, rows)
    return loader


def test_scheduled_take_is_a_hit():
    calls = []
    pf = BlockPrefetcher(_tracking_loader(calls))
    try:
        pf.schedule(0, 256)
        assert pf.take(0, 256) == ("data", 0, 256)
        assert pf.hits == 1 and pf.misses == 0
        # the hit ran on the worker thread, not the caller
        assert calls == [(0, 256, 0, "raft-tla-prefetch")]
    finally:
        pf.close()


def test_unscheduled_take_is_a_miss_on_caller_thread():
    calls = []
    pf = BlockPrefetcher(_tracking_loader(calls))
    try:
        assert pf.take(512, 128) == ("data", 512, 128)
        assert pf.hits == 0 and pf.misses == 1
        assert calls[0][:2] == (512, 128)
        assert calls[0][3] == threading.current_thread().name
    finally:
        pf.close()


def test_range_mismatch_is_a_miss():
    """A take whose range doesn't match the staged result must reload
    synchronously — the engine gets the bytes it asked for, always."""
    calls = []
    pf = BlockPrefetcher(_tracking_loader(calls))
    try:
        pf.schedule(0, 256)
        assert pf.take(0, 200) == ("data", 0, 200)   # shrunk block
        assert pf.hits == 0 and pf.misses == 1
    finally:
        pf.close()


def test_slots_round_robin():
    calls = []
    pf = BlockPrefetcher(_tracking_loader(calls), slots=2)
    try:
        for i in range(4):
            pf.schedule(i * 256, 256)
            pf.take(i * 256, 256)
        assert [c[2] for c in calls] == [0, 1, 0, 1]
        assert pf.hits == 4
    finally:
        pf.close()


# -- invalidation (stop events, level boundaries) ---------------------------


def test_invalidate_discards_staged_result():
    calls = []
    pf = BlockPrefetcher(_tracking_loader(calls))
    try:
        pf.schedule(0, 256)
        pf.invalidate()                       # level boundary / stop
        assert pf.take(0, 256) == ("data", 0, 256)
        assert pf.hits == 0 and pf.misses == 1
    finally:
        pf.close()


def test_invalidate_waits_for_in_flight_worker():
    """invalidate() must not return while the loader is mid-read: a
    frontier rotation after it returns would otherwise race the store."""
    entered = threading.Event()
    release = threading.Event()
    done = []

    def loader(start, rows, slot):
        entered.set()
        release.wait(timeout=10.0)
        done.append(time.perf_counter())
        return "late"

    pf = BlockPrefetcher(loader)
    try:
        pf.schedule(0, 256)
        assert entered.wait(timeout=10.0)
        t = threading.Timer(0.05, release.set)
        t.start()
        pf.invalidate()                       # must block until loader exits
        assert done, "invalidate returned while the loader was in flight"
        # and the stale result was dropped: next take is a miss
        calls = []
        pf._loader = _tracking_loader(calls)
        assert pf.take(0, 256) == ("data", 0, 256)
        assert pf.misses == 1
        t.cancel()
    finally:
        release.set()
        pf.close()


def test_invalidate_never_raises_after_worker_error():
    def boom(start, rows, slot):
        raise ValueError("store exploded")

    pf = BlockPrefetcher(boom)
    try:
        pf.schedule(0, 256)
        deadline = time.perf_counter() + 10.0
        while pf._exc is None and time.perf_counter() < deadline:
            time.sleep(0.005)
        pf.invalidate()                       # stop paths: must not raise
        with pytest.raises(RuntimeError, match="upload prefetch failed"):
            pf.schedule(256, 256)
    finally:
        pf.close()


# -- worker exceptions ------------------------------------------------------


def test_worker_exception_reraises_at_take():
    def boom(start, rows, slot):
        raise ValueError("store exploded")

    pf = BlockPrefetcher(boom)
    try:
        pf.schedule(0, 256)
        with pytest.raises(RuntimeError, match="upload prefetch failed"):
            pf.take(0, 256)
    finally:
        pf.close()


# -- close ------------------------------------------------------------------


def test_close_is_idempotent_and_schedule_after_close_raises():
    pf = BlockPrefetcher(_tracking_loader([]))
    pf.close()
    pf.close()
    assert not pf._t.is_alive()
    with pytest.raises(RuntimeError, match="closed"):
        pf.schedule(0, 256)
