"""Device-resident engine ≡ host engine ≡ oracle (SURVEY §4.3).

The full-jit search (device_engine.py) must reproduce refbfs exactly:
distinct-state counts, diameter, per-level counts, per-action coverage,
transition counts, invariant verdicts, and replayable counterexample traces.
"""

import pytest

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu import device_engine
from raft_tla_tpu.device_engine import Capacities, DeviceEngine
from raft_tla_tpu.models import interp, refbfs, spec as S
from raft_tla_tpu.ops import msgbits as mb

# smoke tier: cross-section for mid-round changes (pytest -m smoke)
pytestmark = pytest.mark.smoke

CAPS = Capacities(n_states=1 << 15, levels=64)


def bag(*ms):
    return tuple(sorted((m, 1) for m in ms))


def assert_parity(cfg, caps=CAPS, **kw):
    ref = refbfs.check(cfg, **kw)
    got = DeviceEngine(cfg, caps).check(**kw)
    assert got.n_states == ref.n_states
    assert got.diameter == ref.diameter
    assert got.levels == ref.levels
    assert got.n_transitions == ref.n_transitions
    assert got.coverage == ref.coverage
    assert (got.violation is None) == (ref.violation is None)
    return ref, got


def test_election_2server_parity():
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=("NoTwoLeaders",), chunk=64)
    _, got = assert_parity(cfg)
    assert got.violation is None and got.n_states > 10


def test_election_3server_parity():
    cfg = CheckConfig(bounds=Bounds(n_servers=3, n_values=1, max_term=2,
                                    max_log=0, max_msgs=1),
                      spec="election",
                      invariants=("NoTwoLeaders", "CommittedWithinLog"),
                      chunk=1024)
    _, got = assert_parity(cfg, caps=Capacities(n_states=1 << 18, levels=64))
    assert got.violation is None and got.n_states > 1000


def test_full_spec_small_parity():
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=1, max_msgs=2),
                      spec="full",
                      invariants=("NoTwoLeaders", "LogMatching",
                                  "CommittedWithinLog"),
                      chunk=128)
    _, got = assert_parity(cfg, caps=Capacities(n_states=1 << 16, levels=64))
    assert got.violation is None
    for fam in (S.RESTART, S.DUPLICATE, S.DROP):
        assert got.coverage[fam] > 0


def test_replication_parity_from_leader():
    bounds = Bounds(n_servers=3, n_values=1, max_term=2, max_log=1,
                    max_msgs=2)
    start = interp.init_state(bounds)._replace(
        role=(S.LEADER, S.FOLLOWER, S.FOLLOWER),
        term=(2, 2, 2), votedFor=(1, 1, 1))
    cfg = CheckConfig(bounds=bounds, spec="replication",
                      invariants=("LogMatching", "CommittedWithinLog"),
                      chunk=256)
    _, got = assert_parity(cfg, init_override=start)
    assert got.violation is None and got.coverage[S.ADVANCECOMMIT] > 0


def test_violation_trace_replayable():
    """Seeded NaiveNoTwoLeaders violation: the device trace must replay."""
    bounds = Bounds(n_servers=3, n_values=1, max_term=3, max_log=0,
                    max_msgs=4, max_dup=1)
    cfg = CheckConfig(bounds=bounds, spec="election",
                      invariants=("NaiveNoTwoLeaders",), chunk=256)
    start = interp.init_state(bounds)._replace(
        role=(S.LEADER, S.FOLLOWER, S.CANDIDATE),
        term=(2, 3, 3),
        votedFor=(1, 3, 0),
        vGrant=(0b011, 0, 0b100),
        msgs=bag(mb.rv_response(3, 1, 1, 2)),
    )
    ref = refbfs.check(cfg, init_override=start)
    got = DeviceEngine(cfg, CAPS).check(init_override=start)
    assert got.violation is not None and ref.violation is not None
    # same invariant, same first-in-discovery-order violating state
    assert got.violation.invariant == "NaiveNoTwoLeaders"
    assert got.violation.state == ref.violation.state
    assert len(got.violation.trace) == len(ref.violation.trace)
    # violation-run stats agree with the oracle too
    assert got.levels == ref.levels
    assert got.diameter == ref.diameter
    trace = got.violation.trace
    assert trace[0][0] is None and trace[0][1] == start
    for (_l, prev), (_label, cur) in zip(trace, trace[1:]):
        succs = [t for _i, t in interp.successors(prev, bounds,
                                                  spec="election")]
        assert cur in succs


def test_chunk_size_invariance():
    b = Bounds(n_servers=2, n_values=1, max_term=2, max_log=0, max_msgs=2)
    r = {}
    for chunk in (16, 256):
        cfg = CheckConfig(bounds=b, spec="election",
                          invariants=("NoTwoLeaders",), chunk=chunk)
        r[chunk] = DeviceEngine(cfg, CAPS).check()
    assert r[16].n_states == r[256].n_states
    assert r[16].levels == r[256].levels
    assert r[16].coverage == r[256].coverage


def test_store_overflow_is_loud():
    cfg = CheckConfig(bounds=Bounds(n_servers=3, n_values=1, max_term=2,
                                    max_log=0, max_msgs=1),
                      spec="election", invariants=(), chunk=64)
    with pytest.raises(RuntimeError, match="capacity"):
        DeviceEngine(cfg, Capacities(n_states=256, levels=64)).check()


def test_transition_counter_64bit():
    """Run counters must survive past 2^31 (VERDICT r1 weak #3): JAX's
    default x64-disabled mode narrows int64 silently, so the engines carry
    two uint32 limbs with explicit carry propagation."""
    import jax.numpy as jnp
    import numpy as np
    from raft_tla_tpu.device_engine import (
        _acc64_add, _acc64_zero, acc64_int, widen_legacy_n_trans, Carry)

    z = _acc64_zero()
    assert z.dtype == jnp.uint32 and z.shape == (2,)
    # limb carry across the 2^32 boundary
    acc = jnp.asarray(np.array([0xFFFFFFFF, 0], np.uint32))
    acc = _acc64_add(acc, jnp.int32(1))
    assert acc64_int(acc) == 1 << 32
    acc = _acc64_add(acc, jnp.int32(2**31 - 1))
    assert acc64_int(acc) == (1 << 32) + 2**31 - 1
    # legacy checkpoint migration: scalar int32 (device/paged carries)
    i = Carry._fields.index("n_trans")
    arrs = [None] * len(Carry._fields)
    arrs[i] = np.int32(123)
    out = widen_legacy_n_trans(list(arrs), Carry._fields)
    assert out[i].dtype == np.uint32 and out[i].shape == (2,)
    assert acc64_int(out[i]) == 123
    # legacy per-device vector (shard carries): [v_d] -> flat [v_d, 0] limbs
    arrs[i] = np.array([5, 7], np.int32)
    out = widen_legacy_n_trans(list(arrs), Carry._fields)
    assert out[i].shape == (4,) and acc64_int(out[i]) == 12
    # already-widened checkpoints pass through untouched
    out2 = widen_legacy_n_trans(list(out), Carry._fields)
    assert out2[i] is out[i]


def test_engine_carry_uses_limb_counter(tmp_path):
    """The saved checkpoint (= the live carry) must hold the two-limb
    uint32 transition counter, not an int32 scalar."""
    import numpy as np
    from raft_tla_tpu.device_engine import Carry
    from raft_tla_tpu.models import interp
    from raft_tla_tpu.ops import symmetry as sym_mod

    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=("NoTwoLeaders",), chunk=64)
    eng = DeviceEngine(cfg, CAPS)
    init_py = interp.init_state(cfg.bounds)
    init_vec = interp.to_vec(init_py, cfg.bounds)
    hi0, lo0 = sym_mod.init_fingerprint(cfg, init_py, init_vec)
    import jax.numpy as jnp
    carry = eng._init(jnp.asarray(np.asarray(init_vec, np.int32)),
                      jnp.uint32(hi0), jnp.uint32(lo0), jnp.bool_(True))
    p = str(tmp_path / "c.npz")
    eng.save_checkpoint(p, carry, (hi0, lo0))
    i = Carry._fields.index("n_trans")
    with np.load(p) as z:
        a = z[f"c{i}"]
    assert a.dtype == np.uint32 and a.shape == (2,)
