"""Device-resident engine ≡ host engine ≡ oracle (SURVEY §4.3).

The full-jit search (device_engine.py) must reproduce refbfs exactly:
distinct-state counts, diameter, per-level counts, per-action coverage,
transition counts, invariant verdicts, and replayable counterexample traces.
"""

import pytest

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu import device_engine
from raft_tla_tpu.device_engine import Capacities, DeviceEngine
from raft_tla_tpu.models import interp, refbfs, spec as S
from raft_tla_tpu.ops import msgbits as mb

CAPS = Capacities(n_states=1 << 15, levels=64)


def bag(*ms):
    return tuple(sorted((m, 1) for m in ms))


def assert_parity(cfg, caps=CAPS, **kw):
    ref = refbfs.check(cfg, **kw)
    got = DeviceEngine(cfg, caps).check(**kw)
    assert got.n_states == ref.n_states
    assert got.diameter == ref.diameter
    assert got.levels == ref.levels
    assert got.n_transitions == ref.n_transitions
    assert got.coverage == ref.coverage
    assert (got.violation is None) == (ref.violation is None)
    return ref, got


def test_election_2server_parity():
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=("NoTwoLeaders",), chunk=64)
    _, got = assert_parity(cfg)
    assert got.violation is None and got.n_states > 10


def test_election_3server_parity():
    cfg = CheckConfig(bounds=Bounds(n_servers=3, n_values=1, max_term=2,
                                    max_log=0, max_msgs=1),
                      spec="election",
                      invariants=("NoTwoLeaders", "CommittedWithinLog"),
                      chunk=1024)
    _, got = assert_parity(cfg, caps=Capacities(n_states=1 << 18, levels=64))
    assert got.violation is None and got.n_states > 1000


def test_full_spec_small_parity():
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=1, max_msgs=2),
                      spec="full",
                      invariants=("NoTwoLeaders", "LogMatching",
                                  "CommittedWithinLog"),
                      chunk=128)
    _, got = assert_parity(cfg, caps=Capacities(n_states=1 << 16, levels=64))
    assert got.violation is None
    for fam in (S.RESTART, S.DUPLICATE, S.DROP):
        assert got.coverage[fam] > 0


def test_replication_parity_from_leader():
    bounds = Bounds(n_servers=3, n_values=1, max_term=2, max_log=1,
                    max_msgs=2)
    start = interp.init_state(bounds)._replace(
        role=(S.LEADER, S.FOLLOWER, S.FOLLOWER),
        term=(2, 2, 2), votedFor=(1, 1, 1))
    cfg = CheckConfig(bounds=bounds, spec="replication",
                      invariants=("LogMatching", "CommittedWithinLog"),
                      chunk=256)
    _, got = assert_parity(cfg, init_override=start)
    assert got.violation is None and got.coverage[S.ADVANCECOMMIT] > 0


def test_violation_trace_replayable():
    """Seeded NaiveNoTwoLeaders violation: the device trace must replay."""
    bounds = Bounds(n_servers=3, n_values=1, max_term=3, max_log=0,
                    max_msgs=4, max_dup=1)
    cfg = CheckConfig(bounds=bounds, spec="election",
                      invariants=("NaiveNoTwoLeaders",), chunk=256)
    start = interp.init_state(bounds)._replace(
        role=(S.LEADER, S.FOLLOWER, S.CANDIDATE),
        term=(2, 3, 3),
        votedFor=(1, 3, 0),
        vGrant=(0b011, 0, 0b100),
        msgs=bag(mb.rv_response(3, 1, 1, 2)),
    )
    ref = refbfs.check(cfg, init_override=start)
    got = DeviceEngine(cfg, CAPS).check(init_override=start)
    assert got.violation is not None and ref.violation is not None
    # same invariant, same first-in-discovery-order violating state
    assert got.violation.invariant == "NaiveNoTwoLeaders"
    assert got.violation.state == ref.violation.state
    assert len(got.violation.trace) == len(ref.violation.trace)
    # violation-run stats agree with the oracle too
    assert got.levels == ref.levels
    assert got.diameter == ref.diameter
    trace = got.violation.trace
    assert trace[0][0] is None and trace[0][1] == start
    for (_l, prev), (_label, cur) in zip(trace, trace[1:]):
        succs = [t for _i, t in interp.successors(prev, bounds,
                                                  spec="election")]
        assert cur in succs


def test_chunk_size_invariance():
    b = Bounds(n_servers=2, n_values=1, max_term=2, max_log=0, max_msgs=2)
    r = {}
    for chunk in (16, 256):
        cfg = CheckConfig(bounds=b, spec="election",
                          invariants=("NoTwoLeaders",), chunk=chunk)
        r[chunk] = DeviceEngine(cfg, CAPS).check()
    assert r[16].n_states == r[256].n_states
    assert r[16].levels == r[256].levels
    assert r[16].coverage == r[256].coverage


def test_store_overflow_is_loud():
    cfg = CheckConfig(bounds=Bounds(n_servers=3, n_values=1, max_term=2,
                                    max_log=0, max_msgs=1),
                      spec="election", invariants=(), chunk=64)
    with pytest.raises(RuntimeError, match="capacity"):
        DeviceEngine(cfg, Capacities(n_states=256, levels=64)).check()
