"""Cfg-driven temporal properties (VERDICT r4 missing #4 / next #6).

TLC checks arbitrary PROPERTY formulas; the checker now routes the
three decidable-by-lasso shapes — ``<>P``, ``[]<>P``, ``P ~> Q`` over
the registered predicate set — from a cfg PROPERTY stanza (or
``--property``) through models/liveness, on both the list path and the
CSR fast path, and emits the matching temporal formula + fairness twin
spec in the --emit-tlc artifact.
"""

import subprocess
import sys

import pytest

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.models import liveness, spec as S

ELECTION = CheckConfig(
    bounds=Bounds(n_servers=2, n_values=1, max_term=2, max_log=0,
                  max_msgs=2),
    spec="election", invariants=(), chunk=256)

FULL = CheckConfig(
    bounds=Bounds(n_servers=2, n_values=1, max_term=2, max_log=0,
                  max_msgs=2),
    spec="full", invariants=(), chunk=256)


def test_parse_property_shapes():
    cases = [
        ("<>SomeLeader", liveness.EVENTUALLY, ("SomeLeader",)),
        ("[]<>SomeLeader", liveness.INFINITELY_OFTEN, ("SomeLeader",)),
        ("SomeCandidate ~> SomeLeader", liveness.LEADS_TO,
         ("SomeCandidate", "SomeLeader")),
        ("SomeCandidate~>SomeLeader", liveness.LEADS_TO,
         ("SomeCandidate", "SomeLeader")),
        ("EventuallyLeader", liveness.EVENTUALLY, ("SomeLeader",)),
        ("InfinitelyOftenLeader", liveness.INFINITELY_OFTEN,
         ("SomeLeader",)),
    ]
    for text, form, preds in cases:
        ps = liveness.parse_property(text)
        assert (ps.form, ps.pred_names) == (form, preds), text


def test_parse_property_rejects():
    for bad in ("<>NoSuchPred", "Bogus", "~> SomeLeader",
                "SomeLeader ~>", "[]SomeLeader", "<>",
                "SomeLeader ~> NoSuchPred"):
        with pytest.raises(ValueError):
            liveness.parse_property(bad)


def test_formula_equals_named_property():
    g = liveness.explore_graph(ELECTION)
    for formula, named in (("<>SomeLeader", "EventuallyLeader"),
                           ("[]<>SomeLeader", "InfinitelyOftenLeader")):
        for wf in ((), ("Next",)):
            rf = liveness.check(ELECTION, formula, wf=wf, graph=g)
            rn = liveness.check(ELECTION, named, wf=wf, graph=g)
            assert rf.holds == rn.holds
            assert rf.n_sccs_checked == rn.n_sccs_checked


def test_leads_to_verdicts_and_lasso():
    g = liveness.explore_graph(FULL)
    # Candidate ~> Leader holds under WF(Next)? No: the crash-loop
    # (Restart forever) is a fair lasso that never elects.
    r = liveness.check(FULL, "SomeCandidate ~> SomeLeader",
                       wf=("Next",), graph=g)
    assert not r.holds
    v = r.violation
    # the P occurrence is on the prefix; the cycle never satisfies Q
    assert any(any(x == S.CANDIDATE for x in s.role)
               for _l, s in v.prefix)
    assert all(not any(x == S.LEADER for x in s.role)
               for _l, s in v.cycle)
    # stuttering refutes it with no fairness at all
    r0 = liveness.check(FULL, "SomeCandidate ~> SomeLeader", wf=(),
                        graph=g)
    assert not r0.holds
    # vacuous holds: a predicate that never fires on this spec
    rv = liveness.check(ELECTION, "SomeCommit ~> SomeLeader",
                        wf=(), graph=liveness.explore_graph(ELECTION))
    assert rv.holds


def test_leads_to_list_vs_csr_parity():
    g_int = liveness.explore_graph(ELECTION)
    g_ddd = liveness.ddd_graph(ELECTION)
    for prop in ("SomeCandidate ~> SomeLeader", "<>SomeCommit",
                 "[]<>SomeLeader"):
        for wf in ((), ("Next",), ("Timeout", "BecomeLeader")):
            ri = liveness.check(ELECTION, prop, wf=wf, graph=g_int)
            rd = liveness.check(ELECTION, prop, wf=wf, graph=g_ddd)
            assert ri.holds == rd.holds, (prop, wf)


def test_cfg_property_formula_end_to_end(tmp_path):
    """TLC-grammar cfg stanza -> checker verdict, through the CLI."""
    cfg = tmp_path / "m.cfg"
    cfg.write_text(
        "CONSTANTS\n"
        "    Server = {s1, s2}\n"
        "    Value = {v1}\n"
        "    Nil = Nil\n"
        "PROPERTY SomeCandidate ~> SomeLeader\n")
    out = subprocess.run(
        [sys.executable, "-m", "raft_tla_tpu.check", "--cpu", str(cfg),
         "--spec", "full", "--max-term", "2", "--max-log", "0",
         "--max-msgs", "2", "--engine", "ref", "--wf", "Next"],
        capture_output=True, text=True, timeout=900)
    assert "SomeCandidate ~> SomeLeader" in out.stdout
    assert "is violated" in out.stdout          # crash-loop refutes
    assert out.returncode == 13                 # TLC liveness exit code


def test_emit_tlc_temporal_twin(tmp_path):
    from raft_tla_tpu.models import tla_export
    tla, cfgp = tla_export.export(
        str(tmp_path), ELECTION.bounds, (), spec="election",
        properties=("SomeCandidate ~> SomeLeader", "EventuallyLeader"),
        wf=("Next",))
    module = open(tla).read()
    cfg = open(cfgp).read()
    assert ("TemporalProp1 == (\\E i \\in Server : state[i] = "
            "Candidate) ~> (\\E i \\in Server : state[i] = Leader)"
            in module)
    assert ("EventuallyLeader == <>(\\E i \\in Server : state[i] = "
            "Leader)" in module)
    assert "FairSpec == ElectionSpec /\\ WF_vars(ElectionNext)" in module
    assert "SPECIFICATION FairSpec" in cfg
    assert "PROPERTY TemporalProp1" in cfg
    assert "PROPERTY EventuallyLeader" in cfg
    # stock TLC rejects VIEW for temporal checking: the twin omits it
    assert "VIEW" not in cfg
    # family fairness spells out the existential closure
    module2 = tla_export.emit_module(
        FULL.bounds, (), spec="full", properties=("<>SomeLeader",),
        wf=("Timeout", "RequestVote"))
    assert ("FairSpec == Spec /\\ WF_vars(\\E i \\in Server : "
            "Timeout(i)) /\\ WF_vars(\\E i, j \\in Server : "
            "RequestVote(i, j))" in module2)


def test_view_quotient_liveness_parity():
    """Registered (exact bisimulation) views compose with liveness
    (VERDICT r4 missing #5 groundwork): verdicts on the deadvotes
    quotient must equal the unviewed graph's for every shape, while the
    quotient is measurably smaller."""
    import dataclasses

    viewed = dataclasses.replace(FULL, view="deadvotes")
    g_plain = liveness.explore_graph(FULL)
    g_view = liveness.ddd_graph(viewed)
    assert len(g_view[0]) < len(g_plain[0])     # real collapse (1.6x)
    for prop in ("<>SomeLeader", "[]<>SomeLeader",
                 "SomeCandidate ~> SomeLeader"):
        for wf in ((), ("Next",), ("Timeout", "BecomeLeader")):
            rp = liveness.check(FULL, prop, wf=wf, graph=g_plain)
            rv = liveness.check(viewed, prop, wf=wf, graph=g_view)
            assert rp.holds == rv.holds, (prop, wf, rp.holds, rv.holds)
    g_view[0].close()


def test_view_liveness_cli(tmp_path):
    cfg = tmp_path / "m.cfg"
    cfg.write_text(
        "CONSTANTS\n"
        "    Server = {s1, s2}\n"
        "    Value = {v1}\n"
        "    Nil = Nil\n"
        "PROPERTY SomeCandidate ~> SomeLeader\n")
    out = subprocess.run(
        [sys.executable, "-m", "raft_tla_tpu.check", "--cpu", str(cfg),
         "--spec", "full", "--max-term", "2", "--max-log", "0",
         "--max-msgs", "2", "--engine", "ddd", "--view", "deadvotes",
         "--wf", "Next"],
        capture_output=True, text=True, timeout=900)
    assert "is violated" in out.stdout
    assert out.returncode == 13
