"""Live metrics layer (ISSUE 20 tentpole): the mergeable log-bucketed
histogram's error bound, the registry/exposition round trip, the
streaming aggregator's event reductions, and the OpenMetrics endpoint
end to end (scrape + replayable schema-v10 snapshot).

The histogram tests are the load-bearing ones: every latency quantile
the endpoint reports rides on ``LogHistogram``'s guarantee that any
quantile answered from geometric bucket midpoints is within
``sqrt(gamma) - 1`` (~9.05%) of the exact sample quantile — checked
here against NumPy's ``inverted_cdf`` (the same rank convention) on
adversarial distributions, plus exact associativity of ``merge`` (the
fleet roll-up property).
"""

import json
import urllib.request

import numpy as np
import pytest

from raft_tla_tpu.obs.events import append_event, validate_event
from raft_tla_tpu.obs.metrics import (_GAMMA, ENV_METRICS, LogHistogram,
                                      MetricsAggregator, MetricsRegistry,
                                      metrics_port)
from raft_tla_tpu.obs.openmetrics import MetricsServer, render

# The documented bound: bucket base 2**(1/4), midpoint answers are
# within sqrt(gamma) - 1 of the exact sample quantile.
_BOUND = _GAMMA ** 0.5 - 1.0
_QS = (0.5, 0.95, 0.99)


def _exact(xs, q):
    return float(np.quantile(np.asarray(xs), q, method="inverted_cdf"))


# --------------------------------------------------------------------------
# histogram


@pytest.mark.parametrize("dist", ["lognormal", "exponential", "uniform",
                                  "tiny", "bimodal"])
def test_histogram_quantile_error_bound(dist):
    """Relative error vs the exact inverted-CDF sample quantile stays
    under sqrt(gamma)-1 on heavy-tailed, light, sub-1.0 (negative
    bucket indices) and bimodal data."""
    rng = np.random.default_rng(7)
    xs = {
        "lognormal": rng.lognormal(0.0, 2.0, 5000),
        "exponential": rng.exponential(3.0, 5000),
        "uniform": rng.uniform(10.0, 1000.0, 5000),
        "tiny": rng.uniform(1e-6, 1e-3, 5000),      # all buckets < 0
        "bimodal": np.concatenate([rng.normal(1.0, 0.01, 2500),
                                   rng.normal(1e4, 1.0, 2500)]).clip(1e-9),
    }[dist]
    h = LogHistogram()
    for v in xs:
        h.add(float(v))
    for q in _QS:
        exact = _exact(xs, q)
        got = h.quantile(q)
        assert abs(got - exact) / exact <= _BOUND, (dist, q, got, exact)


def test_histogram_empty_one_sample_and_clamp():
    h = LogHistogram()
    assert h.quantile(0.5) is None                # empty: no answer
    h.add(2.5)
    for q in _QS:
        assert h.quantile(q) == 2.5               # one sample is exact
    z = LogHistogram()
    z.add(0.0)                                    # same-ts latency rounds to 0
    assert 0.0 <= z.quantile(0.99) <= 1e-300      # clamp bucket, ~0
    assert z.n == 1 and z.total == 0.0


def test_histogram_merge_is_exactly_associative():
    rng = np.random.default_rng(11)
    parts = [rng.lognormal(0.0, 1.5, 700) for _ in range(3)]
    a, b, c = (LogHistogram() for _ in range(3))
    for h, xs in zip((a, b, c), parts):
        for v in xs:
            h.add(float(v))
    left = a.merge(b).merge(c)
    right = a.merge(b.merge(c))
    assert left.counts == right.counts            # dict-sum: exact
    assert left.n == right.n == sum(len(p) for p in parts)
    assert left.total == right.total
    assert left.vmin == right.vmin and left.vmax == right.vmax
    for q in _QS:
        assert left.quantile(q) == right.quantile(q)
    # and the merge equals one histogram over the concatenation
    whole = LogHistogram()
    for xs in parts:
        for v in xs:
            whole.add(float(v))
    assert whole.counts == left.counts


def test_histogram_dict_round_trip():
    h = LogHistogram()
    for v in (0.25, 1.0, 7.5, 1e4):
        h.add(v)
    rt = LogHistogram.from_dict(json.loads(json.dumps(h.to_dict())))
    assert rt.counts == h.counts and rt.n == h.n
    for q in _QS:
        assert rt.quantile(q) == h.quantile(q)


# --------------------------------------------------------------------------
# gate resolver / registry / exposition


def test_metrics_port_gate_resolution(monkeypatch):
    monkeypatch.delenv(ENV_METRICS, raising=False)
    assert metrics_port(None) is None             # off by default
    assert metrics_port(9108) == 9108             # explicit wins
    assert metrics_port(0) == 0                   # 0 = ephemeral, still on
    monkeypatch.setenv(ENV_METRICS, "9200")
    assert metrics_port(None) == 9200
    assert metrics_port(9108) == 9108             # explicit beats env
    monkeypatch.setenv(ENV_METRICS, "not-a-port")
    assert metrics_port(None) is None             # unparseable = off


def test_registry_snapshot_and_render():
    reg = MetricsRegistry()
    reg.inc("raft_tla_events", 1, event="segment")
    reg.inc("raft_tla_events", 2, event="segment")
    reg.set_gauge("raft_tla_queue_depth", 3)
    reg.observe("raft_tla_latency_seconds", 2.5, tenant="a")
    snap = reg.snapshot()
    assert snap['raft_tla_events_total{event="segment"}'] == 3
    assert snap["raft_tla_queue_depth"] == 3
    # labels sorted, quantile appended last; one sample is exact
    assert snap['raft_tla_latency_seconds{tenant="a",quantile="0.99"}'] \
        == 2.5
    assert snap['raft_tla_latency_seconds_count{tenant="a"}'] == 1
    # every snapshot key is a legal metrics_snapshot payload
    ev = {"v": 10, "event": "metrics_snapshot", "ts": 0.0, "metrics": snap}
    assert validate_event(ev) == []
    text = render(reg)
    assert "# TYPE raft_tla_events_total counter" in text
    assert "# TYPE raft_tla_queue_depth gauge" in text
    assert "# TYPE raft_tla_latency_seconds summary" in text
    assert 'raft_tla_latency_seconds{tenant="a",quantile="0.5"} 2.5' in text
    assert 'raft_tla_latency_seconds_sum{tenant="a"} 2.5' in text


# --------------------------------------------------------------------------
# aggregator (streaming reducer over event logs)


def _tenant_log(path, t0, t_end=None, inflight=None):
    append_event(path, "run_start", ts=t0, engine="device",
                 universe={"servers": 2, "values": 1}, spec="election",
                 invariants=["NoTwoLeaders"], resumed=False)
    seg = dict(ts=t0 + 1.0, wall_s=1.0, n_states=100, level=2,
               n_transitions=200, dedup_hit_rate=0.5, since_resume=True,
               states_per_sec=100.0, inc_states_per_sec=100.0,
               flush_backlog=4)
    if inflight is not None:
        seg.update(bin="b0", inflight=inflight)
    append_event(path, "segment", **seg)
    if t_end is not None:
        append_event(path, "run_end", ts=t_end, n_states=100,
                     n_transitions=200, complete=True, outcome="ok")


def test_aggregator_latency_queue_and_gauges(tmp_path):
    _tenant_log(str(tmp_path / "job-a.events"), 100.0, t_end=102.5,
                inflight=2)
    _tenant_log(str(tmp_path / "job-b.events"), 200.0)   # still running
    agg = MetricsAggregator(str(tmp_path))
    agg.poll()
    snap = agg.registry.snapshot()
    # admission (run_start ts) -> terminal (run_end ts) = 2.5 s, exact
    assert snap['raft_tla_latency_seconds{tenant="job-a",'
                'quantile="0.99"}'] == 2.5
    assert snap['raft_tla_latency_seconds{quantile="0.99"}'] == 2.5
    assert snap["raft_tla_queue_depth"] == 1             # job-b un-ended
    assert snap['raft_tla_inflight{bin="b0",tenant="job-a"}'] == 2
    assert snap['raft_tla_flush_backlog{tenant="job-b"}'] == 4
    assert snap['raft_tla_inc_states_per_sec{tenant="job-a"}'] == 100.0
    assert snap['raft_tla_runs_ended_total{outcome="ok",'
                'tenant="job-a"}'] == 1
    # incremental: a second poll with no new bytes changes nothing
    before = dict(snap)
    agg.poll()
    assert agg.registry.snapshot() == before
    # ...and a run_end appended later closes job-b's latency + queue
    append_event(str(tmp_path / "job-b.events"), "run_end", ts=204.0,
                 n_states=100, n_transitions=200, complete=True,
                 outcome="ok")
    agg.poll()
    snap = agg.registry.snapshot()
    assert snap["raft_tla_queue_depth"] == 0
    assert snap['raft_tla_latency_seconds{tenant="job-b",'
                'quantile="0.5"}'] == 4.0


def test_aggregator_pool_lifecycle_and_snapshot_immunity(tmp_path):
    p = str(tmp_path / "pool.events")
    append_event(p, "worker_spawn", ts=1.0, worker="w0", pid=11)
    append_event(p, "worker_spawn", ts=2.0, worker="w1", pid=12)
    append_event(p, "worker_lost", ts=3.0, worker="w0", kind="killed")
    append_event(p, "job_retry", ts=4.0, job_id="a", attempt=1)
    append_event(p, "quarantine", ts=5.0, job_id="a", reason="poison-job")
    # a metrics_snapshot in the swept root must NOT feed back
    append_event(str(tmp_path / "metrics.events"), "metrics_snapshot",
                 ts=6.0, metrics={"raft_tla_queue_depth": 99.0})
    agg = MetricsAggregator(str(tmp_path))
    agg.poll()
    snap = agg.registry.snapshot()
    assert snap["raft_tla_workers_spawned_total"] == 2
    assert snap['raft_tla_workers_lost_total{kind="killed"}'] == 1
    assert snap["raft_tla_workers_live"] == 1
    assert snap["raft_tla_job_retries_total"] == 1
    assert snap["raft_tla_quarantines_total"] == 1
    assert snap["raft_tla_queue_depth"] == 0             # not 99: no feedback


# --------------------------------------------------------------------------
# endpoint end to end


def test_metrics_server_scrape_and_snapshot(tmp_path):
    _tenant_log(str(tmp_path / "smoke-a.events"), 10.0, t_end=12.5,
                inflight=2)
    snap_path = str(tmp_path / "metrics.events")
    server = MetricsServer(str(tmp_path), port=0, snapshot_path=snap_path,
                           interval_s=3600.0)      # snapshots on close only
    try:
        assert server.url == f"http://127.0.0.1:{server.port}/metrics"
        with urllib.request.urlopen(server.url, timeout=10) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            body = resp.read().decode("utf-8")
        assert 'raft_tla_latency_seconds{tenant="smoke-a",' \
               'quantile="0.99"} 2.5' in body
        assert "raft_tla_queue_depth 0" in body
        assert 'raft_tla_inflight{bin="b0",tenant="smoke-a"} 2' in body
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/nope", timeout=10)
    finally:
        server.close()
    server.close()                                 # idempotent
    with open(snap_path) as fh:
        evs = [json.loads(line) for line in fh]
    assert evs, "close() must leave a final snapshot"
    for e in evs:
        assert validate_event(e) == [], e
        assert e["event"] == "metrics_snapshot"
        assert e["port"] == server.port
    assert evs[-1]["metrics"]['raft_tla_latency_seconds'
                              '{tenant="smoke-a",quantile="0.99"}'] == 2.5


# --------------------------------------------------------------------------
# monitor rendering of snapshots (satellite: fleet metrics rows)


def test_monitor_renders_metrics_snapshot_rows(tmp_path):
    from raft_tla_tpu.obs import monitor

    p = str(tmp_path / "metrics.events")
    append_event(p, "metrics_snapshot", ts=1.0, metrics={
        'raft_tla_latency_seconds{tenant="job-a",quantile="0.99"}': 1.5,
        'raft_tla_latency_seconds{tenant="job-b",quantile="0.99"}': 0.25,
        'raft_tla_latency_seconds{quantile="0.99"}': 1.5,
        "raft_tla_queue_depth": 2.0})
    s = monitor.summarize(monitor.load_stream(p))
    assert s["metrics_only"] and s["metrics_ts"] == 1.0
    line = monitor.heartbeat(s)
    assert "p99 latency job-a: 1,500 ms" in line
    assert "p99 latency job-b: 250 ms" in line
    assert "queue depth: 2 jobs" in line
    assert "metrics endpoint: stale" in line       # ts=1.0 is ancient
    # fleet view: the snapshot rows ride under the aggregate line
    _tenant_log(str(tmp_path / "job-a.events"), 5.0, t_end=6.5)
    rows, totals = monitor.fleet_view(str(tmp_path))
    assert totals["metrics"] is not None
    assert totals["n_states"] == 100               # snapshot not double-counted
    text = monitor._fleet_lines(rows, totals)
    assert "p99 latency job-a" in text and "queue depth: 2 jobs" in text
