"""Deadlock checking (TLC's default check; CLI --deadlock, exit 11).

A deadlock is a reachable, expanded state with no successor at all
(stuttering excluded; CONSTRAINT gates exploration, not enabledness).
The full ``Next`` can never deadlock — ``Restart`` is always enabled
(raft.tla:167-175, an unconditioned disjunct raft.tla:454) — so the
interesting cases are sub-specs:

- 1-server election: the server elects itself (quorum of one), consumes
  the vote round-trip, and the sole Leader with an empty bag has no
  enabled action.
- replication sub-spec from Init: no leader exists and every disjunct
  needs one, so Init itself deadlocks.
"""

import numpy as np
import pytest

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.models import interp, refbfs, spec as S
from raft_tla_tpu import engine
from raft_tla_tpu.engine import DEADLOCK

B1 = Bounds(n_servers=1, n_values=1, max_term=2, max_log=0, max_msgs=1)
CFG1 = CheckConfig(bounds=B1, spec="election", invariants=("NoTwoLeaders",),
                   chunk=64, check_deadlock=True)


def _assert_deadlock(res, ref):
    assert res.violation is not None
    assert res.violation.invariant == DEADLOCK
    assert (res.n_states, res.diameter) == (ref.n_states, ref.diameter)
    assert res.violation.state == ref.violation.state
    assert len(res.violation.trace) == len(ref.violation.trace)


def test_refbfs_finds_election_deadlock():
    ref = refbfs.check(CFG1)
    assert ref.violation is not None and ref.violation.invariant == DEADLOCK
    final = ref.violation.state
    assert final.role == (S.LEADER,) and final.msgs == ()
    # the trace replays action by action through the interpreter
    cur = ref.violation.trace[0][1]
    table = S.action_table(B1, "election")
    for _label, nxt in ref.violation.trace[1:]:
        assert nxt in {t for _a, t in interp.successors(cur, B1, table)}
        cur = nxt
    # and the final state genuinely has no successors
    assert not list(interp.successors(cur, B1, table))


def test_refbfs_no_deadlock_when_flag_off():
    ref = refbfs.check(CheckConfig(bounds=B1, spec="election",
                                   invariants=("NoTwoLeaders",), chunk=64))
    assert ref.violation is None


def test_host_engine_deadlock_parity():
    ref = refbfs.check(CFG1)
    _assert_deadlock(engine.check(CFG1), ref)


def test_device_engine_deadlock_parity():
    from raft_tla_tpu.device_engine import Capacities, DeviceEngine
    ref = refbfs.check(CFG1)
    got = DeviceEngine(CFG1, Capacities(n_states=1 << 12, levels=32)).check()
    _assert_deadlock(got, ref)


def test_paged_engine_deadlock_parity():
    from raft_tla_tpu.paged_engine import PagedCapacities, PagedEngine
    ref = refbfs.check(CFG1)
    got = PagedEngine(CFG1, PagedCapacities(
        ring=1 << 14, table=1 << 13, levels=64)).check()
    _assert_deadlock(got, ref)


@pytest.mark.slow      # virtual-mesh test (see test_shard_engine)
def test_shard_engine_deadlock():
    """Like violation traces, deadlock reporting in the sharded engine is
    interleaving-dependent in its level accounting (module docstring); the
    verdict, state count and deadlocked state itself must still agree."""
    from raft_tla_tpu.parallel.shard_engine import (ShardCapacities,
                                                    ShardEngine, make_mesh)
    ref = refbfs.check(CFG1)
    got = ShardEngine(CFG1, make_mesh(2),
                      ShardCapacities(n_states=1 << 12, levels=32)).check()
    assert got.violation is not None
    assert got.violation.invariant == DEADLOCK
    assert got.n_states == ref.n_states
    assert got.violation.state == ref.violation.state


def test_replication_spec_init_deadlocks_immediately():
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=1, max_msgs=2),
                      spec="replication", invariants=(), chunk=64,
                      check_deadlock=True)
    ref = refbfs.check(cfg)
    assert ref.violation is not None and ref.violation.invariant == DEADLOCK
    assert ref.n_states == 1 and len(ref.violation.trace) == 1
    got = engine.check(cfg)
    assert got.violation is not None and got.violation.invariant == DEADLOCK
    assert got.n_states == 1


def test_full_spec_cannot_deadlock():
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=1),
                      spec="full", invariants=(), chunk=128,
                      check_deadlock=True)
    assert refbfs.check(cfg).violation is None


def test_cli_deadlock_exit_code(tmp_path):
    from test_cli import run_cli, write_cfg
    from raft_tla_tpu import check as cli
    cfg = write_cfg(tmp_path / "d.cfg", servers="s1")
    code, out = run_cli(cfg, "--engine", "ref", "--spec", "election",
                        "--deadlock", "--max-term", "2", "--max-log", "0",
                        "--max-msgs", "1", "--no-trace")
    assert code == cli.EXIT_DEADLOCK == 11
    assert "Deadlock reached." in out
    # with the trace enabled, the TLC-style header names the deadlock too
    code, out = run_cli(cfg, "--engine", "ref", "--spec", "election",
                        "--deadlock", "--max-term", "2", "--max-log", "0",
                        "--max-msgs", "1")
    assert code == 11 and "Error: Deadlock reached." in out
    assert "State 1: <Initial predicate>" in out
