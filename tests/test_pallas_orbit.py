"""Pallas orbit-fingerprint kernel ≡ the scan-compiled reference
(ops/pallas_orbit.py vs ops/symmetry.build_orbit_fp), lane-for-lane.

Runs the kernel in interpret mode on CPU (the pallas_fp.py pattern); the
same program compiles for TPU, where it replaces the scan path in
kernels.build_step when enabled.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tla_tpu.config import Bounds
from raft_tla_tpu.ops import fingerprint as fpr
from raft_tla_tpu.ops import msgbits as mb
from raft_tla_tpu.ops import pallas_orbit
from raft_tla_tpu.ops import state as st
from raft_tla_tpu.ops import symmetry as sym

# CI (CPU interpret mode) covers 2 and 3 servers — every code path, two
# layouts.  The 5-server instance (120 unrolled permutations) takes ~1 h
# in interpret mode, so it is exercised COMPILED on the real chip by
# runs/pallas_orbit_chip.py instead (bit-identity + throughput), which
# must be re-run whenever this kernel changes.
BOUNDS = (
    Bounds(n_servers=2, n_values=1, max_term=2, max_log=0, max_msgs=2),
    Bounds(n_servers=3, n_values=2, max_term=2, max_log=1, max_msgs=2,
           max_dup=1),
)


def random_struct(bounds, N, rng):
    """Domain-respecting random states (not necessarily reachable — the
    orbit key is defined on the whole encoding domain)."""
    lay = st.Layout.of(bounds)
    n, L, S = lay.n, lay.L, lay.S
    occ = rng.integers(0, 2, (N, S)).astype(bool)
    hi = rng.integers(0, 1 << 29, (N, S), dtype=np.int64).astype(np.int32)
    lo = rng.integers(0, 1 << 31, (N, S), dtype=np.int64).astype(np.int32)
    ct = rng.integers(1, max(2, bounds.max_dup + 1), (N, S))
    return {
        "role": rng.integers(0, 3, (N, n)).astype(np.int32),
        "term": rng.integers(0, bounds.max_term + 1, (N, n)).astype(
            np.int32),
        "votedFor": rng.integers(0, n + 1, (N, n)).astype(np.int32),
        "commitIndex": rng.integers(0, L + 1, (N, n)).astype(np.int32),
        "logLen": rng.integers(0, L + 1, (N, n)).astype(np.int32),
        "logTerm": rng.integers(0, bounds.max_term + 1,
                                (N, n, L)).astype(np.int32),
        "logVal": rng.integers(0, bounds.n_values + 1,
                               (N, n, L)).astype(np.int32),
        "vResp": rng.integers(0, 1 << n, (N, n)).astype(np.int32),
        "vGrant": rng.integers(0, 1 << n, (N, n)).astype(np.int32),
        "nextIndex": rng.integers(1, L + 2, (N, n, n)).astype(np.int32),
        "matchIndex": rng.integers(0, L + 1, (N, n, n)).astype(np.int32),
        "msgHi": np.where(occ, hi, 0).astype(np.int32),
        "msgLo": np.where(occ, lo, 0).astype(np.int32),
        "msgCount": np.where(occ, ct, 0).astype(np.int32),
    }


def pack_batch(struct, lay):
    return np.concatenate(
        [np.asarray(struct[f]).reshape(len(struct["role"]), -1)
         for f in lay.fields], axis=1).astype(np.int32)


@pytest.mark.parametrize("bounds", BOUNDS,
                         ids=[f"{b.n_servers}s" for b in BOUNDS])
def test_bit_identical_to_scan_reference(bounds):
    rng = np.random.default_rng(7)
    N = 96 if bounds.n_servers == 5 else 256
    struct = random_struct(bounds, N, rng)
    lay = st.Layout.of(bounds)
    consts = jnp.asarray(fpr.lane_constants(lay.width))
    ref_fn = sym.build_orbit_fp(bounds, ("Server",), consts, False)
    ref_hi, ref_lo = jax.jit(ref_fn)(
        {k: jnp.asarray(v) for k, v in struct.items()})
    fn = pallas_orbit.build_orbit_fp(bounds, ("Server",), False,
                                     interpret=True)
    got_hi, got_lo = fn(jnp.asarray(pack_batch(struct, lay)))
    np.testing.assert_array_equal(np.asarray(got_hi), np.asarray(ref_hi))
    np.testing.assert_array_equal(np.asarray(got_lo), np.asarray(ref_lo))


def test_unsupported_configs_fall_back():
    b = BOUNDS[0]
    assert pallas_orbit.build_orbit_fp(b, ("Server", "Value"), False) \
        is None
    assert pallas_orbit.build_orbit_fp(b, ("Server",), True) is None


def test_matches_oracle_single_state():
    """Also anchor against the pure-Python per-state oracle key."""
    from raft_tla_tpu.models import interp

    bounds = BOUNDS[1]
    lay = st.Layout.of(bounds)
    py = interp.init_state(bounds)
    vec = np.asarray(interp.to_vec(py, bounds), np.int32)
    hi, lo = sym.py_orbit_fingerprint(py, bounds, ("Server",))
    fn = pallas_orbit.build_orbit_fp(bounds, ("Server",), False,
                                     interpret=True)
    got_hi, got_lo = fn(jnp.asarray(vec[None, :]))
    assert int(got_hi[0]) == int(np.uint32(hi))
    assert int(got_lo[0]) == int(np.uint32(lo))
