"""Paged engine ≡ oracle, with rings small enough to wrap many times.

The paged engine's correctness risks are all in the ring/pageout machinery:
frontier reads after wraparound, pause-before-overwrite, host trace
reconstruction.  Tiny rings force every one of those paths.
"""

import pytest

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.models import interp, refbfs, spec as S
from raft_tla_tpu.ops import msgbits as mb
from raft_tla_tpu.paged_engine import PagedCapacities, PagedEngine
from raft_tla_tpu.utils import native


def bag(*ms):
    return tuple(sorted((m, 1) for m in ms))


def assert_parity(cfg, caps, **kw):
    ref = refbfs.check(cfg, **kw)
    got = PagedEngine(cfg, caps).check(**kw)
    assert got.n_states == ref.n_states
    assert got.diameter == ref.diameter
    assert got.levels == ref.levels
    assert got.n_transitions == ref.n_transitions
    assert got.coverage == ref.coverage
    assert (got.violation is None) == (ref.violation is None)
    return ref, got


def test_election_2server_ring_wraps():
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=("NoTwoLeaders",), chunk=16)
    # 3014 states through a 2048-row ring: wraps and pages repeatedly.
    caps = PagedCapacities(ring=2048, table=1 << 13, levels=64)
    _, got = assert_parity(cfg, caps)
    assert got.violation is None and got.n_states == 3014


def test_full_2server_ring_wraps():
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=1, max_msgs=2),
                      spec="full",
                      invariants=("NoTwoLeaders", "LogMatching",
                                  "CommittedWithinLog"),
                      chunk=16)
    # max adjacent-level pair in this space is 8122 rows; 16384 still forces
    # several ring wraps over the 48041-state run.
    caps = PagedCapacities(ring=16384, table=1 << 17, levels=64)
    _, got = assert_parity(cfg, caps)
    assert got.violation is None
    for fam in (S.RESTART, S.DUPLICATE, S.DROP):
        assert got.coverage[fam] > 0


def test_violation_trace_reconstructs_from_host_store():
    bounds = Bounds(n_servers=3, n_values=1, max_term=3, max_log=0,
                    max_msgs=4, max_dup=1)
    cfg = CheckConfig(bounds=bounds, spec="election",
                      invariants=("NaiveNoTwoLeaders",), chunk=16)
    start = interp.init_state(bounds)._replace(
        role=(S.LEADER, S.FOLLOWER, S.CANDIDATE),
        term=(2, 3, 3), votedFor=(1, 3, 0),
        vGrant=(0b011, 0, 0b100),
        msgs=bag(mb.rv_response(3, 1, 1, 2)))
    ref = refbfs.check(cfg, init_override=start)
    caps = PagedCapacities(ring=2048, table=1 << 13, levels=64)
    got = PagedEngine(cfg, caps).check(init_override=start)
    assert got.violation is not None and ref.violation is not None
    assert got.violation.invariant == "NaiveNoTwoLeaders"
    assert got.violation.state == ref.violation.state
    assert len(got.violation.trace) == len(ref.violation.trace)
    trace = got.violation.trace
    assert trace[0][0] is None and trace[0][1] == start
    for (_l, prev), (_label, cur) in zip(trace, trace[1:]):
        succs = [t for _i, t in interp.successors(prev, bounds,
                                                  spec="election")]
        assert cur in succs


def test_ring_too_small_for_frontier_is_loud():
    # The 3-server election frontier outgrows a 1024-row ring quickly.
    cfg = CheckConfig(bounds=Bounds(n_servers=3, n_values=1, max_term=2,
                                    max_log=0, max_msgs=1),
                      spec="election", invariants=(), chunk=16)
    caps = PagedCapacities(ring=1024, table=1 << 19, levels=64)
    with pytest.raises(RuntimeError, match="ring"):
        PagedEngine(cfg, caps).check()


def test_ring_must_cover_chunk_fanout():
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=(), chunk=1024)
    with pytest.raises(ValueError, match="ring"):
        PagedEngine(cfg, PagedCapacities(ring=2048, table=1 << 13))


def test_matches_device_engine_discovery_order():
    """Same discovery order ⇒ same first violation as DeviceEngine."""
    from raft_tla_tpu.device_engine import Capacities, DeviceEngine
    bounds = Bounds(n_servers=3, n_values=1, max_term=3, max_log=0,
                    max_msgs=4, max_dup=1)
    cfg = CheckConfig(bounds=bounds, spec="election",
                      invariants=("NaiveNoTwoLeaders",), chunk=32)
    start = interp.init_state(bounds)._replace(
        role=(S.LEADER, S.FOLLOWER, S.CANDIDATE),
        term=(2, 3, 3), votedFor=(1, 3, 0),
        vGrant=(0b011, 0, 0b100),
        msgs=bag(mb.rv_response(3, 1, 1, 2)))
    dev = DeviceEngine(cfg, Capacities(n_states=1 << 15, levels=64)
                       ).check(init_override=start)
    pag = PagedEngine(cfg, PagedCapacities(ring=4096, table=1 << 15,
                                           levels=64)
                      ).check(init_override=start)
    assert [l for l, _ in pag.violation.trace] == \
        [l for l, _ in dev.violation.trace]


def test_deadline_partial_run_and_live_coverage():
    """deadline_s time-boxes the search (bench's north-star probe): the
    partial result is marked complete=False, and the --stats stream
    carries live per-action coverage (TLC -coverage 1 analog)."""
    from raft_tla_tpu.config import Bounds, CheckConfig
    from raft_tla_tpu.paged_engine import PagedCapacities, PagedEngine

    cfg = CheckConfig(bounds=Bounds(n_servers=3, n_values=1, max_term=2,
                                    max_log=0, max_msgs=1),
                      spec="election", invariants=("NoTwoLeaders",),
                      chunk=64)
    caps = PagedCapacities(ring=1 << 16, table=1 << 18, levels=64)
    full = PagedEngine(cfg, caps).check()
    assert full.complete and full.n_states == 142538

    stats: list = []
    eng = PagedEngine(cfg, caps, seg_chunks=4)
    eng.SEG_MAX = 4                     # many tiny segments
    part = eng.check(deadline_s=0.0, on_progress=stats.append)
    assert not part.complete
    assert 0 < part.n_states < full.n_states
    assert stats and "coverage" in stats[-1]
    cov = stats[-1]["coverage"]
    assert sum(cov.values()) == part.n_states - 1   # every non-Init credited
    assert "Timeout" in cov
