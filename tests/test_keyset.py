"""Partitioned host dedup (utils/keyset.py, utils/flushq.py).

The partitioned master key set must be *observationally identical* to
the flat one — same first-occurrence new-index vectors flush for flush,
same contains/len/array — under any partition count, adversarial
duplicate patterns, empty partitions and all-duplicate flushes; that
equivalence is what lets the ddd engines swap implementations under the
RAFT_TLA_HOSTDEDUP gate without touching a single byte of discovery
order.  The budgeted compaction must bound per-flush merge data
movement and carry an interrupted merge's cursor across flushes to the
same final set.
"""

import os

import numpy as np
import pytest

from raft_tla_tpu.utils import flushq, keyset
from raft_tla_tpu.utils.keyset import (
    MasterKeys, PartitionedMasterKeys, master_from_keys)

pytestmark = pytest.mark.smoke


def _streams(rng, n_flushes=40):
    """Adversarial flush streams: tiny key pools (heavy duplicates),
    full-range uniform, everything jammed into one partition (63 empty),
    all-duplicate and empty flushes."""
    for it in range(n_flushes):
        n = int(rng.integers(0, 400))
        mode = it % 5
        if mode == 0:
            yield rng.integers(0, 40, n).astype(np.uint64)
        elif mode == 1:
            yield rng.integers(0, 2 ** 63, n, dtype=np.int64).astype(np.uint64)
        elif mode == 2:  # top bits fixed: one partition takes it all
            yield (np.uint64(0x7) << np.uint64(61)) \
                | rng.integers(0, 500, n).astype(np.uint64)
        elif mode == 3 and n:  # all duplicates of one key
            yield np.full(n, rng.integers(0, 2 ** 62), np.uint64)
        else:
            yield np.empty(0, np.uint64)


@pytest.mark.parametrize("parts", [1, 2, 4, 16, 64])
@pytest.mark.parametrize("budget", [None, 64, 4096])
def test_partitioned_equivalence(parts, budget):
    rng = np.random.default_rng(parts * 1000 + (budget or 0))
    flat = MasterKeys()
    part = PartitionedMasterKeys(parts=parts, merge_budget=budget)
    for flush in _streams(rng):
        got = part.dedup(flush.copy())
        want = flat.dedup(flush.copy())
        assert np.array_equal(got, want)
        assert len(flat) == len(part)
    assert np.array_equal(flat.array, part.array)
    probe = rng.integers(0, 2 ** 63, 2000, dtype=np.int64).astype(np.uint64)
    assert np.array_equal(flat.contains(probe), part.contains(probe))


def test_budget_bounds_merge_movement_and_carries_cursor():
    """A merge bigger than the budget must (a) never move more than the
    budget in one flush and (b) resume mid-merge across flushes until
    complete — with probes correct the whole way (both source runs stay
    visible until the spliced result replaces them)."""
    rng = np.random.default_rng(7)
    budget = 256
    flat = MasterKeys()
    part = PartitionedMasterKeys(parts=2, merge_budget=budget)
    saw_pending = False
    for _ in range(300):
        flush = rng.integers(0, 2 ** 63, 200, dtype=np.int64) \
            .astype(np.uint64)
        assert np.array_equal(part.dedup(flush.copy()),
                              flat.dedup(flush.copy()))
        assert part.last_flush_moved <= budget
        if part.pending_merges:
            saw_pending = True
            # mid-merge probes must still see every admitted key
            probe = flat.array[:: max(1, len(flat) // 97)]
            assert bool(np.all(part.contains(probe)))
    assert saw_pending, "budget never forced a carried merge cursor"
    # let later flushes finish the carried merges; final set identical
    for _ in range(200):
        flush = rng.integers(0, 2 ** 63, 200, dtype=np.int64) \
            .astype(np.uint64)
        part.dedup(flush.copy())
        flat.dedup(flush.copy())
    assert np.array_equal(flat.array, part.array)


def test_unbudgeted_partition_matches_flat_tier_structure():
    """With no budget, each partition compacts exactly like the flat
    geometric policy — the run-count bound (O(log N)) holds per
    partition."""
    rng = np.random.default_rng(11)
    part = PartitionedMasterKeys(parts=4, merge_budget=None)
    for _ in range(200):
        part.dedup(rng.integers(0, 2 ** 63, 500, dtype=np.int64)
                   .astype(np.uint64))
    assert part.pending_merges == 0
    assert part.n_runs <= 20
    for p in part._p:
        for a, b in zip(p.runs, p.runs[1:]):
            assert a.size > keyset._RATIO * b.size
            assert bool(np.all(a[1:] > a[:-1]))


def test_parts_must_be_power_of_two():
    with pytest.raises(ValueError):
        PartitionedMasterKeys(parts=3)
    with pytest.raises(ValueError):
        PartitionedMasterKeys(parts=0)


def test_constructor_rejects_unsorted_base():
    bad = np.asarray([3, 2, 5], np.uint64)
    with pytest.raises(ValueError, match="strictly sorted"):
        PartitionedMasterKeys(bad)
    ok = np.asarray([2, 3, 5], np.uint64)
    m = PartitionedMasterKeys(ok, parts=16)
    assert len(m) == 3 and np.array_equal(m.array, ok)


@pytest.mark.parametrize("partitioned", [False, True])
def test_master_from_keys_resume_build(partitioned):
    """The checkpoint-resume factory: unsorted unique log -> same set
    either arm; a duplicated key raises the stream-corrupt diagnostic
    naming the snapshot (NOT the constructor's sortedness error)."""
    rng = np.random.default_rng(3)
    keys = np.unique(rng.integers(0, 2 ** 63, 5000, dtype=np.int64)
                     .astype(np.uint64))
    rng.shuffle(keys)
    m = master_from_keys(keys, source="/tmp/snap.ckpt",
                         partitioned=partitioned)
    assert len(m) == keys.size
    assert np.array_equal(m.array, np.sort(keys))
    bad = np.concatenate([keys, keys[:1]])
    with pytest.raises(ValueError) as ei:
        master_from_keys(bad, source="/tmp/snap.ckpt",
                         partitioned=partitioned)
    assert "stream corrupt" in str(ei.value)
    assert "/tmp/snap.ckpt" in str(ei.value)
    assert "strictly sorted" not in str(ei.value)


def test_host_dedup_gate_resolution():
    assert keyset.host_dedup_enabled("on") is True
    assert keyset.host_dedup_enabled("off") is False
    # measured policy: auto = ON iff the host has >= 2 cores (the
    # partitioned path costs 0.72x in-engine single-threaded)
    auto_expect = (os.cpu_count() or 1) >= 2
    assert keyset.host_dedup_enabled("auto") is auto_expect
    assert keyset.host_dedup_enabled("AUTO") is auto_expect


def test_dedup_worker_ordered_depth1_and_exceptions():
    """flushq.DedupWorker: batches run in submission order, depth-1
    (submit i+1 blocks until i completes), drain settles everything,
    and a worker exception re-raises on the main thread."""
    seen = []

    def fn(batch):
        seen.append(batch)
        return batch

    w = flushq.DedupWorker(fn)
    for i in range(10):
        w.submit(i, n_keys=5)
    assert w.drain() == sum(range(10))
    assert seen == list(range(10))        # strict submission order
    assert w.backlog() == 0 and w.inclusive_extra() == 0
    w.close()

    def boom(batch):
        raise RuntimeError("kaboom")

    w2 = flushq.DedupWorker(boom)
    w2.submit(0, n_keys=1)
    with pytest.raises(RuntimeError, match="background dedup flush"):
        for _ in range(3):
            w2.submit(1, n_keys=1)
            w2.drain()
    w2.close()
