"""Test harness config: force an 8-device virtual CPU mesh.

The checker's "multi-node without a cluster" story (SURVEY §4.4): real TPU
pods are not available under test, so JAX's host-platform device emulation
exercises the sharded dedup/all-to-all paths single-host.  Must run before
jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
