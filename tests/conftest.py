"""Test harness config: force an 8-device virtual CPU mesh.

The checker's "multi-node without a cluster" story (SURVEY §4.4): real TPU
pods are not available under test, so JAX's host-platform device emulation
exercises the sharded dedup/all-to-all paths single-host.  Must run before
jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# The deployment image pre-imports jax from a sitecustomize hook with
# JAX_PLATFORMS pinned to the real-TPU plugin, so the env var above is read
# too late — override through the live config instead (backends initialize
# lazily, so this still wins as long as no test touched a device yet).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
