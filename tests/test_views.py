"""The deadvotes VIEW (models/views.py) — TLC VIEW analog.

The soundness of the quotient rests on view-equivalence being a
bisimulation; ``test_deadvotes_bisimulation`` checks that mechanically
against THIS implementation's action semantics (not just the raft.tla
reading): states differing only in non-Candidate vote sets must enable
identical actions, produce view-identical successors, and agree on
every registered invariant and the constraint.  The remaining tests
pin the quotient's exactness (same verdicts, violations still found)
and the engine/oracle/digest plumbing.
"""

import numpy as np
import pytest

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.models import interp, invariants as inv_mod, refbfs
from raft_tla_tpu.models import spec as S
from raft_tla_tpu.models.views import py_view
from raft_tla_tpu.ops import msgbits as mb

BOUNDS = Bounds(n_servers=3, n_values=1, max_term=2, max_log=0,
                max_msgs=1)
CFG = CheckConfig(bounds=BOUNDS, spec="election",
                  invariants=("NoTwoLeaders",), chunk=64,
                  view="deadvotes")
PLAIN = CheckConfig(bounds=BOUNDS, spec="election",
                    invariants=("NoTwoLeaders",), chunk=64)


def bag(*ms):
    return tuple(sorted((m, 1) for m in ms))


def test_unknown_view_rejected():
    with pytest.raises(ValueError, match="unknown view"):
        CheckConfig(bounds=BOUNDS, view="nope")


def _bisim_walk(bounds, spec, inv_names, min_checked, seed=7):
    """For reachable states s, scrambling the dead vote sets must not
    change: enabled action lanes, viewed successors per lane, any
    registered invariant, or the constraint."""
    rng = np.random.default_rng(seed)
    view = py_view("deadvotes")
    full_mask = (1 << bounds.n_servers) - 1
    invs = [inv_mod.py_invariant(nm) for nm in inv_names]

    # sample reachable states by random walk
    states = [interp.init_state(bounds)]
    cur = states[0]
    for _ in range(400):
        succ = list(interp.successors(cur, bounds, spec=spec))
        if not succ:
            cur = states[0]
            continue
        cur = succ[rng.integers(len(succ))][1]
        states.append(cur)

    # every walk state plus each state's one-step successors: the walk
    # saturates into all-Candidate regions fast, so the successor fringe
    # supplies most of the states that still have a non-Candidate
    fringe = [t for s in states[::8]
              for _a, t in interp.successors(s, bounds, spec=spec)]
    checked = 0
    for s in states + fringe:
        dead = [i for i, r in enumerate(s.role) if r != S.CANDIDATE]
        if not dead:
            continue
        vr, vg = list(s.vResp), list(s.vGrant)
        for i in dead:
            vr[i] = int(rng.integers(full_mask + 1))
            vg[i] = int(rng.integers(full_mask + 1))
        s2 = s._replace(vResp=tuple(vr), vGrant=tuple(vg))
        assert view(s, bounds) == view(s2, bounds)
        su1 = list(interp.successors(s, bounds, spec=spec))
        su2 = list(interp.successors(s2, bounds, spec=spec))
        assert [a for a, _ in su1] == [a for a, _ in su2]
        for (a1, t1), (a2, t2) in zip(su1, su2):
            assert view(t1, bounds) == view(t2, bounds), (a1, s)
        for f in invs:
            assert f(s, bounds) == f(s2, bounds)
        assert interp.constraint_ok(s, bounds) == \
            interp.constraint_ok(s2, bounds)
        checked += 1
    assert checked >= min_checked     # the walk must exercise dead sets


def test_deadvotes_bisimulation():
    _bisim_walk(BOUNDS, "election",
                ("NoTwoLeaders", "ElectionSafety", "NaiveNoTwoLeaders"),
                min_checked=40)


def test_deadvotes_bisimulation_full_spec():
    """The soundness claim covers every full-spec action (Restart,
    Duplicate/Drop, AppendEntries, ClientRequest, AdvanceCommitIndex
    included), not just the election subset."""
    _bisim_walk(Bounds(n_servers=3, n_values=1, max_term=2, max_log=1,
                       max_msgs=2, max_dup=1), "full",
                ("NoTwoLeaders", "LogMatching", "CommittedWithinLog"),
                min_checked=40)


def test_deadvotes_bisimulation_faithful():
    """Faithful mode: history variables (elections/allLogs/voterLog)
    join state identity; the view must stay a bisimulation there too
    (the elections record is only written by BecomeLeader — a Candidate,
    where the view is the identity)."""
    _bisim_walk(Bounds(n_servers=2, n_values=1, max_term=2, max_log=1,
                       max_msgs=2, history=True, max_elections=4), "full",
                ("NoTwoLeaders", "ElectionSafetyHist"),
                min_checked=15)


def test_refbfs_quotient_is_smaller_and_safe():
    plain = refbfs.check(PLAIN)
    viewed = refbfs.check(CFG)
    assert viewed.violation is None and plain.violation is None
    assert viewed.n_states < plain.n_states
    assert viewed.diameter <= plain.diameter
    # the quotient must still reach every viewed state: counts are
    # reproducible constants worth pinning (3s election t2/m1; the
    # measured reduction is ~9.4% here — RESULTS.md "deadvotes VIEW")
    assert plain.n_states == 142538
    assert viewed.n_states == 129134


def test_violation_still_found_under_view():
    bounds = Bounds(n_servers=3, n_values=1, max_term=3, max_log=0,
                    max_msgs=4, max_dup=1)
    start = interp.init_state(bounds)._replace(
        role=(S.LEADER, S.FOLLOWER, S.CANDIDATE),
        term=(2, 3, 3),
        votedFor=(1, 3, 0),
        vGrant=(0b011, 0, 0b100),
        msgs=bag(mb.rv_response(3, 1, 1, 2)),
    )
    for view in (None, "deadvotes"):
        cfg = CheckConfig(bounds=bounds, spec="election",
                          invariants=("NaiveNoTwoLeaders",), chunk=64,
                          view=view)
        got = refbfs.check(cfg, init_override=start)
        assert got.violation is not None
        assert got.violation.invariant == "NaiveNoTwoLeaders"
        assert not inv_mod.py_invariant("NaiveNoTwoLeaders")(
            got.violation.state, bounds)


def test_engine_parity_under_view():
    """Device pipeline (jnp view) == oracle (py view), exact discovery
    order: counts, levels, coverage."""
    from raft_tla_tpu.ddd_engine import DDDCapacities, DDDEngine

    ref = refbfs.check(CFG)
    caps = DDDCapacities(block=1 << 12, table=1 << 14, flush=1 << 12,
                         levels=64)
    got = DDDEngine(CFG, caps).check()
    assert got.n_states == ref.n_states
    assert got.levels == ref.levels
    assert got.n_transitions == ref.n_transitions
    assert got.coverage == ref.coverage


def test_view_composes_with_symmetry():
    cfg_sv = CheckConfig(bounds=BOUNDS, spec="election",
                         invariants=("NoTwoLeaders",), chunk=64,
                         symmetry=("Server",), view="deadvotes")
    cfg_s = CheckConfig(bounds=BOUNDS, spec="election",
                        invariants=("NoTwoLeaders",), chunk=64,
                        symmetry=("Server",))
    ref_sv = refbfs.check(cfg_sv)
    ref_s = refbfs.check(cfg_s)
    assert ref_sv.n_states < ref_s.n_states
    assert ref_sv.violation is None

    from raft_tla_tpu.engine import Engine
    got = Engine(cfg_sv).check()
    assert got.n_states == ref_sv.n_states
    assert got.coverage == ref_sv.coverage


def test_view_joins_checkpoint_digest(tmp_path):
    from raft_tla_tpu.ddd_engine import DDDCapacities, DDDEngine

    caps = DDDCapacities(block=1 << 12, table=1 << 14, flush=1 << 12,
                         levels=64)
    ck = str(tmp_path / "v.ckpt")
    DDDEngine(PLAIN, caps).check(checkpoint=ck, checkpoint_every_s=0.0)
    with pytest.raises(ValueError, match="different model"):
        DDDEngine(CFG, caps).check(resume=ck)


def test_tlc_export_carries_view():
    """--emit-tlc under a view must emit a MATCHING TLC VIEW — a twin
    artifact that silently explored the unquotiented space would
    disagree with the run's printed totals."""
    from raft_tla_tpu.models import tla_export

    t = tla_export.emit_module(BOUNDS, ("NoTwoLeaders",), True, False,
                               "deadvotes")
    assert "DeadVotes(votesResponded)" in t
    assert "DeadVotes(votesGranted)" in t
    c = tla_export.emit_cfg(BOUNDS, ("NoTwoLeaders",), True, False,
                            "deadvotes")
    assert "VIEW ParityView" in c
    # faithful mode keeps history vars in the identity, masks votes only
    fb = Bounds(n_servers=2, n_values=1, max_term=2, max_log=1,
                max_msgs=2, history=True, max_elections=4)
    t2 = tla_export.emit_module(fb, ("NoTwoLeaders",), False, False,
                                "deadvotes")
    assert "DeadVotesView" in t2 and "voterLog" in t2
    assert "VIEW DeadVotesView" in tla_export.emit_cfg(
        fb, ("NoTwoLeaders",), False, False, "deadvotes")


@pytest.mark.slow      # virtual-mesh test (see test_shard_engine)
def test_mesh_engine_under_view():
    from raft_tla_tpu.parallel.ddd_shard_engine import (
        DDDShardCapacities, DDDShardEngine)
    from raft_tla_tpu.parallel.shard_engine import make_mesh

    ref = refbfs.check(CFG)
    caps = DDDShardCapacities(block=1 << 12, table=1 << 12,
                              seg_rows=1 << 15, flush=1 << 12, levels=64)
    got = DDDShardEngine(CFG, make_mesh(8), caps).check()
    assert got.n_states == ref.n_states
    assert got.levels == ref.levels
    assert got.n_transitions == ref.n_transitions

def test_predicates_view_invariant():
    """The PREDICATES registry's obligation #2 (models/liveness.py):
    every registered temporal predicate must read only view-preserved
    fields, for every registered view — pred(s) == pred(view(s)) over a
    reachable full-spec corpus.  A future predicate that reads vote
    sets (legal for symmetry, unsound under deadvotes) fails here
    loudly instead of silently mis-evaluating on the quotient."""
    from raft_tla_tpu.models import interp, liveness, views

    b = Bounds(n_servers=2, n_values=1, max_term=2, max_log=0,
               max_msgs=2)
    cfg = CheckConfig(bounds=b, spec="full", invariants=())
    # reachable corpus: the whole bounded 2-server full-spec space
    seen = {interp.init_state(b)}
    frontier = [interp.init_state(b)]
    while frontier:
        nxt = []
        for s in frontier:
            if not interp.constraint_ok(s, b):
                continue
            for _i, t in interp.successors(s, b, spec="full"):
                if t not in seen:
                    seen.add(t)
                    nxt.append(t)
        frontier = nxt
    assert len(seen) > 20000            # the corpus is the real space
    for vname in views.REGISTRY:
        vw = views.py_view(vname)
        # the view must move SOME state or the check is vacuous
        assert any(vw(s, b) != s for s in seen)
        for pname, (pred, _struct, _tla) in liveness.PREDICATES.items():
            bad = [s for s in seen if pred(s, b) != pred(vw(s, b), b)]
            assert not bad, (vname, pname, bad[:1])
