"""Faithful mode (SURVEY §7.0.3b): history variables as real state.

Covers the bounded-log universe (ops/loguniv.py), the history encodings in
the tensor schema, lane-exact kernel/interpreter differentials with history
on, engine parity, and the history-based invariants — including a seeded
ElectionSafetyHist violation that only history can see (the state-level
NoTwoLeaders reading holds while the history records two leaders for one
term... which cannot happen in Raft, so the seeded case uses a doctored
initial state).
"""

import numpy as np
import pytest

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.models import interp, invariants as inv_mod, refbfs
from raft_tla_tpu.ops import state as st
from raft_tla_tpu.ops.loguniv import LogUniverse

from test_state import random_pystate
from test_kernels import _diff_on_states

BH = Bounds(n_servers=2, n_values=2, max_term=2, max_log=1, max_msgs=2,
            history=True, max_elections=4)


def test_universe_roundtrip_and_prefix():
    uni = LogUniverse.of(BH)
    assert uni.size == 43            # R=6 (3 terms x 2 values), lengths 0..2
    for r in range(uni.size):
        t = uni.tuple_of_id(r)
        assert uni.id_of_tuple(t) == r
        if t:
            assert uni.id_of_tuple(t[:-1]) == int(uni.prefix_id(np.asarray(r), np))
    # empty log is rank 0 (parity-mode messages encode g = 0)
    assert uni.id_of_tuple(()) == 0


def test_universe_vectorized_matches_scalar():
    uni = LogUniverse.of(BH)
    rng = np.random.default_rng(7)
    for _ in range(100):
        ln = int(rng.integers(0, uni.L + 1))
        log = tuple((int(rng.integers(1, uni.T + 1)),
                     int(rng.integers(1, uni.V + 1))) for _ in range(ln))
        lt = np.zeros(uni.L, np.int32)
        lv = np.zeros(uni.L, np.int32)
        for k, (t, v) in enumerate(log):
            lt[k], lv[k] = t, v
        assert int(uni.log_id(lt, lv, np.int32(ln), np)) == uni.id_of_tuple(log)
        et, ev, eln = uni.decode(np.asarray(uni.id_of_tuple(log)), np)
        assert int(eln) == ln
        assert tuple((int(et[..., k]), int(ev[..., k]))
                     for k in range(ln)) == log


def test_layout_and_struct_roundtrip():
    lay = st.Layout.of(BH)
    assert lay.history and lay.E == 4 and lay.Wa == 2
    rng = np.random.default_rng(3)
    for _ in range(50):
        s = random_pystate(rng, BH)
        assert interp.from_struct(interp.to_struct(s, BH), BH) == s


def test_config_gates():
    with pytest.raises(ValueError, match="faithful"):
        CheckConfig(invariants=("ElectionSafetyHist",))
    with pytest.raises(ValueError, match="universe"):
        Bounds(history=True, max_term=6, max_log=4, n_values=2)


def test_differential_random_history_states():
    rng = np.random.default_rng(11)
    states = [random_pystate(rng, BH) for _ in range(48)]
    _diff_on_states(states, BH)


def test_differential_reachable_history_prefix():
    cc = CheckConfig(bounds=BH, spec="full", invariants=())
    frontier = [interp.init_state(BH)]
    seen = set(frontier)
    for _lvl in range(3):
        nxt = []
        for s in frontier:
            for _ai, t in interp.successors(s, BH):
                if t not in seen and interp.constraint_ok(s, BH):
                    seen.add(t)
                    nxt.append(t)
        frontier = nxt[:64]
    _diff_on_states(list(seen)[:128], BH)
    assert cc.bounds.history


def test_faithful_refines_parity_full_spec():
    """History splits parity-equal states (e.g. post-crash states differing
    only in what was ever elected); counts must only grow."""
    bp = Bounds(n_servers=2, n_values=1, max_term=2, max_log=1, max_msgs=2)
    bh = Bounds(n_servers=2, n_values=1, max_term=2, max_log=1, max_msgs=2,
                history=True, max_elections=4)
    rp = refbfs.check(CheckConfig(bounds=bp, spec="full",
                                  invariants=("NoTwoLeaders",)))
    rh = refbfs.check(CheckConfig(
        bounds=bh, spec="full",
        invariants=("NoTwoLeaders", "ElectionSafetyHist",
                    "LeaderCompletenessHist", "AllLogsPrefixClosed")))
    assert rh.violation is None
    assert rh.n_states > rp.n_states        # 53398 vs 48041
    assert rh.diameter == rp.diameter == 32


def test_engine_parity_faithful():
    """Device-path BFS (engine.py, per-chunk jit) must agree with the
    interpreter BFS exactly in faithful mode."""
    from raft_tla_tpu import engine
    cc = CheckConfig(bounds=BH, spec="election",
                     invariants=("NoTwoLeaders", "ElectionSafetyHist"),
                     chunk=256)
    r_ref = refbfs.check(cc)
    r_eng = engine.check(cc)
    assert (r_eng.n_states, r_eng.diameter) == (r_ref.n_states, r_ref.diameter)
    assert r_eng.violation is None and r_ref.violation is None
    assert r_eng.coverage == r_ref.coverage


def test_election_safety_hist_seeded_violation():
    """Two same-term elections with different leaders in the history: the
    state-level NoTwoLeaders reading cannot see it (neither is in office),
    but ElectionSafetyHist must flag it — on both predicate faces."""
    n = BH.n_servers
    s = interp.init_state(BH)
    bad = s._replace(elections=tuple(sorted(
        [(2, 0, (), 0b11, ((), ())), (2, 1, (), 0b11, ((), ()))],
        key=interp._election_key)))
    assert inv_mod.py_invariant("NoTwoLeaders")(bad, BH)
    assert not inv_mod.py_invariant("ElectionSafetyHist")(bad, BH)
    import jax.numpy as jnp
    struct = {k: jnp.asarray(v) for k, v in interp.to_struct(bad, BH).items()}
    assert not bool(inv_mod.jnp_invariant("ElectionSafetyHist", BH)(struct))
    assert bool(inv_mod.jnp_invariant("LeaderCompletenessHist", BH)(struct))


def test_all_logs_prefix_closed_seeded():
    s = interp.init_state(BH)
    # ((1,1),(1,2)) present without its prefix ((1,1),)
    bad = s._replace(allLogs=tuple(sorted([(), ((1, 1), (1, 2))],
                                          key=interp._log_key)))
    ok = s._replace(allLogs=tuple(sorted([(), ((1, 1),), ((1, 1), (1, 2))],
                                         key=interp._log_key)))
    assert not inv_mod.py_invariant("AllLogsPrefixClosed")(bad, BH)
    assert inv_mod.py_invariant("AllLogsPrefixClosed")(ok, BH)
    import jax.numpy as jnp
    for s_, want in ((bad, False), (ok, True)):
        struct = {k: jnp.asarray(v)
                  for k, v in interp.to_struct(s_, BH).items()}
        assert bool(inv_mod.jnp_invariant("AllLogsPrefixClosed", BH)(struct)) \
            is want


def test_leader_completeness_hist_seeded_violation():
    """A committed entry missing from a later-term election's elog."""
    s = interp.init_state(BH)
    ent = (1, 1)
    bad = s._replace(
        log=((ent,), ()), commitIndex=(1, 0), term=(1, 1),
        elections=((2, 1, (), 0b11, ((), ())),))
    assert not inv_mod.py_invariant("LeaderCompletenessHist")(bad, BH)
    good = bad._replace(elections=((2, 1, (ent,), 0b11, ((), ())),))
    assert inv_mod.py_invariant("LeaderCompletenessHist")(good, BH)
    import jax.numpy as jnp
    for s_, want in ((bad, False), (good, True)):
        struct = {k: jnp.asarray(v)
                  for k, v in interp.to_struct(s_, BH).items()}
        assert bool(inv_mod.jnp_invariant(
            "LeaderCompletenessHist", BH)(struct)) is want


def test_liveness_composes_with_faithful_mode():
    """The liveness graph builds on interp.successors, so history state
    flows through: EventuallyLeader holds under WF(Next) on the faithful
    election universe and is stutter-refuted with no fairness, exactly as
    in parity mode."""
    from raft_tla_tpu.models import liveness
    ch = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                   max_log=0, max_msgs=2, history=True,
                                   max_elections=4),
                     spec="election", invariants=())
    g = liveness.explore_graph(ch)
    assert liveness.check(ch, "EventuallyLeader", wf=("Next",),
                          graph=g).holds
    refuted = liveness.check(ch, "EventuallyLeader", wf=(), graph=g)
    assert not refuted.holds and refuted.violation is not None


def test_symmetry_composes_with_faithful_mode():
    """History is Server-equivariant (log ranks carry no server ids;
    voterLog/eLeader/eVotes/eVLog permute), so SYMMETRY quotients faithful
    spaces too.  On the election universe faithful equals parity state for
    state, so the orbit count must be the known parity figure."""
    from raft_tla_tpu import engine
    bh = Bounds(n_servers=2, n_values=1, max_term=2, max_log=0, max_msgs=2,
                history=True, max_elections=4)
    cc = CheckConfig(bounds=bh, spec="election",
                     invariants=("NoTwoLeaders",), symmetry=("Server",),
                     chunk=256)
    r = refbfs.check(cc)
    assert (r.n_states, r.diameter) == (1514, 17)     # 3014 states / 2 = ...
    e = engine.check(cc)
    assert (e.n_states, e.diameter) == (1514, 17)
    assert e.coverage == r.coverage

    bf = Bounds(n_servers=2, n_values=1, max_term=2, max_log=1, max_msgs=2,
                history=True, max_elections=4)
    cf = CheckConfig(bounds=bf, spec="full",
                     invariants=("NoTwoLeaders", "ElectionSafetyHist"),
                     symmetry=("Server",), chunk=512)
    rf = refbfs.check(cf)
    assert (rf.n_states, rf.diameter) == (26723, 32)  # orbits of the 53398
    assert rf.violation is None
    ef = engine.check(cf)
    assert (ef.n_states, ef.diameter) == (26723, 32)


def test_device_and_paged_engines_faithful_parity():
    """The flagship engines run faithful mode too: HBM store rows and the
    paged engine's bit-packed rows both carry the history fields."""
    from raft_tla_tpu.device_engine import Capacities, DeviceEngine
    from raft_tla_tpu.paged_engine import PagedCapacities, PagedEngine
    cc = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                   max_log=1, max_msgs=2, history=True,
                                   max_elections=4),
                     spec="full",
                     invariants=("NoTwoLeaders", "ElectionSafetyHist",
                                 "AllLogsPrefixClosed"), chunk=512)
    ref = refbfs.check(cc)
    assert (ref.n_states, ref.diameter) == (53398, 32)
    dev = DeviceEngine(cc, Capacities(n_states=1 << 16, levels=64)).check()
    assert (dev.n_states, dev.diameter) == (ref.n_states, ref.diameter)
    assert dev.levels == ref.levels and dev.coverage == ref.coverage
    pag = PagedEngine(cc, PagedCapacities(ring=1 << 16, table=1 << 18,
                                          levels=64)).check()
    assert (pag.n_states, pag.diameter) == (ref.n_states, ref.diameter)
    assert pag.levels == ref.levels and pag.coverage == ref.coverage


def test_bitpack_roundtrip_history_fields():
    """Bit-packed rows preserve every faithful-mode field exactly,
    including the 32-bit allLogs words (sign bit included)."""
    from raft_tla_tpu.ops import bitpack
    rng = np.random.default_rng(5)
    sch = bitpack.BitSchema(BH)
    vecs = np.stack([
        interp.to_vec(random_pystate(rng, BH), BH) for _ in range(64)])
    # force sign-bit patterns into the allLogs words
    lay = st.Layout.of(BH)
    off = sum(int(np.prod(lay.shapes[f])) for f in st.STATE_FIELDS)
    vecs[0, off] = -2147483648
    vecs[1, off] = -1
    packed = sch.pack(vecs, np)
    assert packed.shape[-1] == sch.P < vecs.shape[-1]
    assert (sch.unpack(packed, np) == vecs).all()
