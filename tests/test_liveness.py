"""Liveness under weak fairness (models/liveness.py) + LeaderCompleteness.

Ground truths worth stating:
- The reference Spec has NO fairness (raft.tla:469), so with ``wf=()``
  every eventuality is refuted by pure stuttering at Init.
- Under WF(Next), the bounded election-only graph is a DAG whose fair
  behaviors all elect a leader — the property holds.
- Under WF(Next), the full spec is refuted by a crash-loop lasso
  (Restart of a pristine follower is a self-loop that "takes a step").
- Every reported lasso must replay: each consecutive pair is a real
  transition of the interpreter, and the cycle closes.
"""

import pytest

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.models import interp, invariants, liveness, refbfs
from raft_tla_tpu.models import spec as S

B2 = Bounds(n_servers=2, n_values=1, max_term=2, max_log=0, max_msgs=2)
ELECTION = CheckConfig(bounds=B2, spec="election", invariants=())
FULL = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                 max_log=1, max_msgs=2),
                   spec="full", invariants=())


def replay_lasso(v, config):
    """Assert prefix+cycle is a real behavior and the cycle closes."""
    bounds = config.bounds
    table = S.action_table(bounds, config.spec)
    seq = v.prefix + v.cycle
    for (_, prev), (label, cur) in zip(seq, seq[1:]):
        if label == "<stutter>":
            assert cur == prev
            continue
        succs = [t for _i, t in interp.successors(prev, bounds, table)]
        assert cur in succs, label
    first_cycle, last = v.cycle[0][1], v.cycle[-1][1]
    if first_cycle != last:   # non-stutter cycle must close
        succs = [t for _i, t in interp.successors(last, bounds, table)]
        assert first_cycle in succs


def test_no_fairness_stutters_at_init():
    r = liveness.check(ELECTION, "EventuallyLeader", wf=())
    assert not r.holds
    assert len(r.violation.prefix) == 1          # stutter right at Init
    assert r.violation.cycle == [("<stutter>", r.violation.prefix[0][1])]


def test_election_holds_under_wf_next():
    r = liveness.check(ELECTION, "EventuallyLeader", wf=("Next",))
    assert r.holds and r.violation is None
    assert r.n_states == 3014                    # full graph was explored


def test_full_spec_crash_loop_refutes_election_liveness():
    r = liveness.check(FULL, "EventuallyLeader", wf=("Next",))
    assert not r.holds
    replay_lasso(r.violation, FULL)
    # no state in the lasso has a leader
    for _l, s in r.violation.prefix + r.violation.cycle:
        assert all(role != S.LEADER for role in s.role)


def test_per_family_fairness_rules_out_crash_loop():
    """WF on every family: a cycle must take-or-disable each one; Timeout
    strictly increases terms so no bounded cycle takes it, and it is
    enabled at every leaderless in-bound state — the bounded model
    therefore satisfies the property (the unbounded dueling-candidates
    lasso needs unbounded terms, which the CONSTRAINT excludes)."""
    fams = tuple(S.SPECS["full"])
    r = liveness.check(FULL, "EventuallyLeader", wf=fams)
    assert r.holds


def test_infinitely_often_variant():
    r = liveness.check(FULL, "InfinitelyOftenLeader", wf=("Next",))
    assert not r.holds
    replay_lasso(r.violation, FULL)
    # the cycle avoids leaders; the prefix is unconstrained
    for _l, s in r.violation.cycle:
        assert all(role != S.LEADER for role in s.role)


def test_eventually_commit_refuted_by_stutterless_churn():
    r = liveness.check(FULL, "EventuallyCommit", wf=("Next",))
    assert not r.holds
    replay_lasso(r.violation, FULL)
    for _l, s in r.violation.prefix + r.violation.cycle:
        assert all(ci == 0 for ci in s.commitIndex)


def test_unknown_wf_family_is_loud():
    with pytest.raises(ValueError, match="unknown WF"):
        liveness.check(ELECTION, "EventuallyLeader", wf=("NotAFamily",))


# -- LeaderCompleteness (safety side of BASELINE config #5) ------------------

def test_leader_completeness_holds_on_replication():
    bounds = Bounds(n_servers=3, n_values=1, max_term=2, max_log=1,
                    max_msgs=2)
    start = interp.init_state(bounds)._replace(
        role=(S.LEADER, S.FOLLOWER, S.FOLLOWER),
        term=(2, 2, 2), votedFor=(1, 1, 1))
    cfg = CheckConfig(bounds=bounds, spec="replication",
                      invariants=("LeaderCompleteness", "LogMatching"))
    r = refbfs.check(cfg, init_override=start)
    assert r.violation is None and r.n_states > 100


def test_leader_completeness_spares_stale_intermediate_leader():
    """Reachable Raft scenario (verified against the interpreter during
    review): s2 was elected leader in term 3 BEFORE s1's term-4 commit;
    Fig. 3 only covers leaders of terms later than the COMMIT term (4), so
    s2 need not hold the entry.  A formulation comparing against the
    entry's term (2) would wrongly flag this state."""
    bounds = Bounds(n_servers=3, n_values=2, max_term=4, max_log=2,
                    max_msgs=2)
    s = interp.init_state(bounds)._replace(
        role=(S.LEADER, S.LEADER, S.FOLLOWER),
        term=(4, 3, 4),
        log=(((2, 1), (4, 1)), (), ((2, 1), (4, 1))),
        commitIndex=(2, 0, 0))
    assert invariants.py_invariant("LeaderCompleteness")(s, bounds)
    # same verdict on the device side
    import jax.numpy as jnp
    import numpy as np
    from raft_tla_tpu.ops import state as st
    struct = st.unpack(interp.to_vec(s, bounds), st.Layout.of(bounds), np)
    dev = invariants.jnp_invariant("LeaderCompleteness", bounds)
    assert bool(dev({k: jnp.asarray(v) for k, v in struct.items()}))


def test_leader_completeness_py_jnp_agree():
    import jax.numpy as jnp
    import numpy as np
    from raft_tla_tpu.ops import state as st

    bounds = Bounds(n_servers=3, n_values=2, max_term=3, max_log=2,
                    max_msgs=2)
    py = invariants.py_invariant("LeaderCompleteness")
    dev = invariants.jnp_invariant("LeaderCompleteness", bounds)
    # crafted: s1 leader term 3 missing s2's committed entry -> violated
    bad = interp.init_state(bounds)._replace(
        role=(S.LEADER, S.FOLLOWER, S.FOLLOWER),
        term=(3, 2, 2), log=((), ((1, 1),), ((1, 1),)),
        commitIndex=(0, 1, 1))
    good = bad._replace(log=(((1, 1),), ((1, 1),), ((1, 1),)))
    for s, want in ((bad, False), (good, True)):
        assert py(s, bounds) is want
        struct = st.unpack(interp.to_vec(s, bounds), st.Layout.of(bounds),
                           np)
        got = bool(dev({k: jnp.asarray(v) for k, v in struct.items()}))
        assert got is want


# -- engine-built graphs (models/liveness.engine_graph) ----------------------

def _graphs_equal_verdicts(config, props_wf):
    """engine_graph and explore_graph must yield identical verdicts,
    state/edge counts, and (where refuted) replayable lassos."""
    g_int = liveness.explore_graph(config)
    g_eng = liveness.engine_graph(config)
    assert len(g_eng[0]) == len(g_int[0])                   # states
    assert sum(map(len, g_eng[1])) == sum(map(len, g_int[1]))  # edges
    for prop, wf in props_wf:
        ri = liveness.check(config, prop, wf=wf, graph=g_int)
        re = liveness.check(config, prop, wf=wf, graph=g_eng)
        assert ri.holds == re.holds, (prop, wf)
        assert (ri.n_states, ri.n_edges) == (re.n_states, re.n_edges)
        if not re.holds:
            replay_lasso(re.violation, config)


def test_engine_graph_matches_interpreter_election():
    _graphs_equal_verdicts(ELECTION, [
        ("EventuallyLeader", ("Next",)),
        ("EventuallyLeader", ()),
    ])


def test_engine_graph_matches_interpreter_full_spec():
    _graphs_equal_verdicts(FULL, [
        ("EventuallyLeader", ("Next",)),
        ("EventuallyCommit", ("Next",)),
    ])


def test_engine_graph_rejects_symmetry():
    cfg = CheckConfig(bounds=B2, spec="election", invariants=(),
                      symmetry=("Server",))
    with pytest.raises(ValueError, match="SYMMETRY"):
        liveness.engine_graph(cfg)


def test_engine_graph_at_scale_3server_election():
    """VERDICT r1 next#8's 'done' gate: an EventuallyLeader verdict on the
    142,538-state 3-server election universe from an engine-built graph.
    (The interpreter path needs tens of minutes here; the engine graph
    builds in about a minute even on the CPU test backend.)"""
    cfg = CheckConfig(
        bounds=Bounds(n_servers=3, n_values=1, max_term=2, max_log=0,
                      max_msgs=1),
        spec="election", invariants=(), chunk=1024)
    from raft_tla_tpu.device_engine import Capacities
    graph = liveness.engine_graph(cfg, Capacities(n_states=1 << 18,
                                                  levels=64))
    assert len(graph[0]) == 142538
    r = liveness.check(cfg, "EventuallyLeader", wf=("Next",), graph=graph)
    assert r.n_states == 142538
    assert r.holds and r.violation is None


# -- DDD-store graphs (models/liveness.ddd_graph) ----------------------------

def _ddd_caps():
    from raft_tla_tpu.ddd_engine import DDDCapacities
    return DDDCapacities(block=1 << 12, table=1 << 14, flush=1 << 12,
                         levels=64)


def test_ddd_graph_matches_interpreter_election():
    g_int = liveness.explore_graph(ELECTION)
    g_ddd = liveness.ddd_graph(ELECTION, _ddd_caps())
    assert len(g_ddd[0]) == len(g_int[0])
    assert sum(map(len, g_ddd[1])) == sum(map(len, g_int[1]))
    for prop, wf in [("EventuallyLeader", ("Next",)),
                     ("EventuallyLeader", ())]:
        ri = liveness.check(ELECTION, prop, wf=wf, graph=g_int)
        rd = liveness.check(ELECTION, prop, wf=wf, graph=g_ddd)
        assert ri.holds == rd.holds, (prop, wf)
        assert (ri.n_states, ri.n_edges) == (rd.n_states, rd.n_edges)
        if not rd.holds:
            replay_lasso(rd.violation, ELECTION)
    g_ddd[0].close()


def test_ddd_graph_states_view_mask_matches_predicates():
    g = liveness.ddd_graph(FULL, _ddd_caps())
    states = g[0]
    for prop, (_form, pred) in liveness.PROPERTIES.items():
        got = states.mask(prop)
        want = [pred(states[u], FULL.bounds) for u in range(len(states))]
        assert got.tolist() == want, prop
    states.close()


def test_ddd_graph_symmetry_quotient_verdicts_match_raw():
    """The orbit-quotient fair-lasso check must agree with the raw-graph
    verdict (the bisimulation argument in ddd_graph's docstring, checked
    empirically): same holds/refuted for every property and fairness
    mix, on a space where the quotient is ~half the raw graph."""
    raw = CheckConfig(bounds=B2, spec="election", invariants=())
    sym = CheckConfig(bounds=B2, spec="election", invariants=(),
                      symmetry=("Server",))
    g_raw = liveness.explore_graph(raw)
    g_sym = liveness.ddd_graph(sym, _ddd_caps())
    assert len(g_sym[0]) < len(g_raw[0])
    for prop, wf in [("EventuallyLeader", ("Next",)),
                     ("EventuallyLeader", ("Timeout",)),
                     ("EventuallyLeader", ()),
                     ("InfinitelyOftenLeader", ("Next",))]:
        rr = liveness.check(raw, prop, wf=wf, graph=g_raw)
        rs = liveness.check(sym, prop, wf=wf, graph=g_sym)
        assert rr.holds == rs.holds, (prop, wf)
    g_sym[0].close()


def test_ddd_graph_full_spec_crash_loop():
    g = liveness.ddd_graph(FULL, _ddd_caps())
    r = liveness.check(FULL, "EventuallyLeader", wf=("Next",), graph=g)
    assert not r.holds            # Restart churn refutes it
    g[0].close()


def test_csr_path_absent_family_refutes_not_crashes():
    """WF of a family valid in ALL_FAMILIES but absent from the spec
    subset (e.g. ClientRequest under the election spec): everywhere-
    disabled, so any eventuality refutes by stuttering — CSR and list
    paths must agree (a review found the CSR path raising ValueError)."""
    g_csr = liveness.ddd_graph(ELECTION, _ddd_caps())
    g_list = liveness.explore_graph(ELECTION)
    r_csr = liveness.check(ELECTION, "EventuallyLeader",
                           wf=("ClientRequest",), graph=g_csr)
    r_list = liveness.check(ELECTION, "EventuallyLeader",
                            wf=("ClientRequest",), graph=g_list)
    assert r_csr.holds == r_list.holds is False
    g_csr[0].close()
