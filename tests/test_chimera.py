"""Filter-table chimera guard (VERDICT r4 weak #3 / next-round #4).

The DDD filter inserts streamed (hi, lo) key words with two scatters
sharing one compacted index vector.  Rounds 1-4 relied on XLA applying
duplicate-index updates in operand order identically in both ops; a
compiler drift could have fused a fabricated (hiA, loB) "chimera" key
aliasing a never-streamed candidate — silent state loss, the one
failure an exhaustive checker must never have.  Round 5 removed the
reliance (``_filter_insert`` dedups (bucket, slot) within each batch so
the scatter indices are duplicate-free); these tests construct the
adversarial colliding-keys case directly and would fail loudly if the
dedup regressed AND the backend's duplicate-update order ever drifted
between the two ops — plus a differential engine run under a
collision-slammed tiny table (ADVICE r4, ddd_engine.py:379 item).
"""

import jax.numpy as jnp
import numpy as np

from raft_tla_tpu import Bounds, CheckConfig
from raft_tla_tpu.ddd_engine import _EMPTY, DDDCapacities, DDDEngine, \
    _filter_insert
from raft_tla_tpu.models import refbfs

import pytest
# smoke tier: cross-section for mid-round changes (pytest -m smoke)
pytestmark = pytest.mark.smoke

U32 = jnp.uint32


def _table_pairs(tbl_hi, tbl_lo):
    """All non-empty (hi, lo) pairs currently in the table."""
    hi = np.asarray(tbl_hi).ravel()
    lo = np.asarray(tbl_lo).ravel()
    live = ~((hi == np.uint32(_EMPTY)) & (lo == np.uint32(_EMPTY)))
    return set(zip(hi[live].tolist(), lo[live].tolist()))


def test_two_keys_same_bucket_slot_both_stream_no_chimera():
    """The literal adversarial case from the VERDICT: two distinct keys
    colliding on one (bucket, slot) in one batch.  Both must stream and
    the table must contain only genuine inserted keys afterwards."""
    TB, Sb, BA = 4, 2, 8
    tbl_hi = jnp.full((TB, Sb), _EMPTY, U32)
    tbl_lo = jnp.full((TB, Sb), _EMPTY, U32)
    # same bucket (lo & 3 == 1), same evict slot (hi % 2 == 0); the
    # shared gather sees the same empty row, so both pick slot 0.
    A = (0xAAAA0000, 0x00000001)
    B = (0xBBBB0000, 0x00000005)
    key_hi = jnp.zeros((BA,), U32).at[0].set(A[0]).at[1].set(B[0])
    key_lo = jnp.zeros((BA,), U32).at[0].set(A[1]).at[1].set(B[1])
    active = jnp.arange(BA) < 2
    tbl_hi, tbl_lo, stream = _filter_insert(
        tbl_hi, tbl_lo, key_hi, key_lo, active)
    assert bool(stream[0]) and bool(stream[1])      # both stream
    pairs = _table_pairs(tbl_hi, tbl_lo)
    assert pairs <= {A, B}, f"fabricated key in table: {pairs - {A, B}}"
    assert len(pairs) == 1          # in-batch (bucket,slot) dedup kept one


def test_many_colliding_keys_never_fabricate():
    """Randomized slam: hundreds of distinct keys forced into very few
    buckets across several batches.  Every table entry must always be a
    key that was actually presented, and every first-sighting of a key
    not already in the table must stream."""
    rng = np.random.default_rng(7)
    TB, Sb, BA = 2, 2, 64
    tbl_hi = jnp.full((TB, Sb), _EMPTY, U32)
    tbl_lo = jnp.full((TB, Sb), _EMPTY, U32)
    presented = set()
    for _ in range(6):
        hi = rng.integers(1, 1 << 32, BA, dtype=np.uint32)
        lo = rng.integers(1, 1 << 32, BA, dtype=np.uint32)
        active = rng.random(BA) < 0.9
        before = _table_pairs(tbl_hi, tbl_lo)
        tbl_hi, tbl_lo, stream = _filter_insert(
            tbl_hi, tbl_lo, jnp.asarray(hi), jnp.asarray(lo),
            jnp.asarray(active))
        stream = np.asarray(stream)
        seen_batch = set()
        for c in range(BA):
            k = (int(hi[c]), int(lo[c]))
            if not active[c]:
                assert not stream[c]
                continue
            first = k not in seen_batch
            seen_batch.add(k)
            if first and k not in before:
                assert stream[c], f"new key {k} failed to stream"
            presented.add(k)
        pairs = _table_pairs(tbl_hi, tbl_lo)
        assert pairs <= presented, \
            f"fabricated keys: {pairs - presented}"


def test_collision_slammed_table_engine_parity():
    """Differential guard (ADVICE r4): a single-bucket filter table
    forces (bucket, slot) collisions in essentially every batch;
    exploration metrics must still exactly match the pure oracle."""
    cfg = CheckConfig(
        bounds=Bounds(n_servers=2, n_values=1, max_term=2, max_log=0,
                      max_msgs=2),
        spec="election", invariants=("NoTwoLeaders",), chunk=128)
    caps = DDDCapacities(block=256, table=8, flush=1 << 9, levels=64)
    r = DDDEngine(cfg, caps).check()
    o = refbfs.check(cfg)
    assert r.violation is None and o.violation is None
    assert (r.n_states, r.diameter) == (o.n_states, o.diameter)
