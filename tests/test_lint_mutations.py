"""Seeded-mutation harness: the analyzer has NO false negatives on the
overflow/hazard bug classes it exists to catch.

Each test plants one deliberate bug — a kernel-level width regression
(via the analyzer's injectable transfers/tables, the same seams the real
kernels feed through) or a tracer-hostile source idiom — and asserts the
analyzer flags it.  Width findings must carry the full proof context:
transition, field, derived interval, and allotted width (the acceptance
contract).  A mutation the analyzer misses is a failing test.
"""

import pytest

from raft_tla_tpu.analysis import intervals as iv, jitlint, report
from raft_tla_tpu.analysis import widthcheck as wc
from raft_tla_tpu.config import Bounds
from raft_tla_tpu.ops import bitpack, msgbits


def _assert_proof_fields(finding):
    """Every width finding reports transition, field, interval, width."""
    assert finding.pass_ == report.WIDTH
    assert finding.severity == report.ERROR
    assert finding.field is not None
    assert finding.interval is not None
    assert finding.width is not None


# -- mutation 1: Timeout increments term twice (cap clamp lost) --------------

def test_mutation_timeout_double_increment():
    mut = dict(wc.TRANSFERS)

    def bad_timeout(bounds, env, menv):
        r = wc.t_timeout(bounds, env, menv)
        writes = dict(r.writes)
        writes["term"] = env["term"] + 2          # skips a term
        return wc.TransferResult(writes, r.sends)

    mut["Timeout"] = bad_timeout
    fs = wc.check_widths(Bounds(), transfers=mut)
    hits = [f for f in fs if f.transition == "Timeout" and f.field == "term"]
    assert hits, fs
    for f in hits:
        _assert_proof_fields(f)
        assert f.transition == "Timeout"
    # term_cap = max_term + 1 = 4: [1,3] + 2 escapes the [1,4] envelope.
    assert any(f.interval == (3, 5) for f in hits)


# -- mutation 2: expansion without the constraint meet (capacity scheme) -----

def test_mutation_unconstrained_expansion():
    """Dropping the StateConstraint meet is the whole +1 capacity scheme
    failing: Timeout, ClientRequest, and Duplicate must ALL overflow."""
    fs = wc.check_widths(Bounds(), expansion_env=iv.envelope(Bounds()))
    by_transition = {(f.transition, f.field) for f in fs}
    assert ("Timeout", "term") in by_transition
    assert ("ClientRequest", "logLen") in by_transition
    assert ("DuplicateMessage", "msgCount") in by_transition
    overflow = [f for f in fs if f.code == "width-overflow"]
    assert any(f.transition == "ClientRequest" and f.field == "logLen"
               for f in overflow)
    for f in fs:
        if f.code in ("width-overflow", "envelope-escape"):
            _assert_proof_fields(f)


# -- mutation 3: msgLo 'g' widened one bit (spill past bit 31) ---------------

def test_mutation_msglo_widened_spills():
    lo = dict(msgbits.LO_FIELDS)
    sh, w = lo["g"]
    lo["g"] = (sh, w + 1)                        # 17 + 15 = 32 > 31
    fs = wc.check_widths(Bounds(history=True), lo_fields=lo)
    [f] = [f for f in fs if f.code == "msg-table-spill"]
    assert f.field == "msgLo.g" and f.severity == report.ERROR


def test_mutation_overlapping_subfields():
    hi = dict(msgbits.HI_FIELDS)
    sh, w = hi["mterm"]
    hi["mterm"] = (sh, w + 4)                    # grows into field 'a'
    fs = wc.check_widths(Bounds(), hi_fields=hi)
    assert any(f.code == "msg-table-overlap" for f in fs), fs


# -- mutation 4: record subfield slot narrowed (creation-site overflow) ------

def test_mutation_mterm_slot_narrowed():
    hi = dict(msgbits.HI_FIELDS)
    sh, _w = hi["mterm"]
    hi["mterm"] = (sh, 1)                        # terms reach 3 > 1 bit
    fs = wc.check_widths(Bounds(), hi_fields=hi)
    hits = [f for f in fs if f.code == "msg-subfield-overflow"
            and f.field.endswith(".mterm")]
    assert hits, fs
    for f in hits:
        _assert_proof_fields(f)
        assert f.transition is not None          # names the creating action
    assert any("RequestVote" in f.transition for f in hits)


# -- mutation 5: flat field width narrowed by one bit ------------------------

def test_mutation_field_bits_narrowed():
    fb = dict(bitpack.field_bits(Bounds()))
    fb["term"] -= 1
    fs = wc.check_widths(Bounds(), field_bits_table=fb)
    codes = {f.code for f in fs}
    assert "envelope-width" in codes             # envelope no longer fits
    overflow = [f for f in fs if f.code == "width-overflow"
                and f.field == "term"]
    assert overflow, fs
    for f in overflow:
        _assert_proof_fields(f)
        assert f.width == fb["term"]


# -- mutation 6: kernel/twin write-set drift ---------------------------------

def test_mutation_transfer_drift_detected():
    mut = dict(wc.TRANSFERS)

    def sneaky_advance(bounds, env, menv):
        r = wc.t_advance_commit(bounds, env, menv)
        writes = dict(r.writes)
        writes["matchIndex"] = env["matchIndex"]   # undeclared write
        return wc.TransferResult(writes, r.sends)

    mut["AdvanceCommitIndex"] = sneaky_advance
    fs = wc.check_widths(Bounds(), transfers=mut)
    [f] = [f for f in fs if f.code == "transfer-drift"]
    assert f.transition == "AdvanceCommitIndex"
    assert f.field == "matchIndex"


# -- mutation 7: parity mode leaks mlog into a packed record -----------------

def test_mutation_parity_mlog_leak():
    mut = dict(wc.TRANSFERS)

    def leaky_rv(bounds, env, menv):
        r = wc.t_request_vote(bounds, env, menv)
        rec = r.sends[0]
        fields = dict(rec.fields)
        fields["g"] = iv.Interval(0, 5)           # history data in parity
        rec2 = wc.MsgRecord(rec.mtype, fields)
        return wc.TransferResult(r.writes, (rec2,))

    mut["RequestVote"] = leaky_rv
    fs = wc.check_widths(Bounds(history=False), transfers=mut)
    [f] = [f for f in fs if f.code == "parity-mlog-nonzero"]
    assert f.transition == "RequestVote"
    assert f.interval == (0, 5)


# -- mutation 8: tracer-hostile idioms planted in source ---------------------

HAZARD_SNIPPETS = {
    "traced-python-if": (
        "import jax.numpy as jnp\n"
        "def k_receive(bounds, s, slot):\n"
        "    if s['msgHi'][slot] > 0:\n"         # traced guard
        "        return jnp.ones(())\n"
        "    return jnp.zeros(())\n"),
    "traced-scalar-cast": (
        "import jax.numpy as jnp\n"
        "def k_timeout(s, i):\n"
        "    t = int(s['term'][i]) + 1\n"        # concretizes the tracer
        "    return jnp.asarray(t)\n"),
    "set-iteration": (
        "def build_table():\n"
        "    rows = []\n"
        "    for fam in {'Restart', 'Timeout'}:\n"   # salted order
        "        rows.append(fam)\n"
        "    return rows\n"),
    "narrow-astype": (
        "import jax.numpy as jnp\n"
        "def pack_rows(v):\n"
        "    return v.astype(jnp.int16)\n"),     # no width justification
}


@pytest.mark.parametrize("code", sorted(HAZARD_SNIPPETS))
def test_mutation_jit_hazards_flagged(code):
    fs = jitlint.lint_source(HAZARD_SNIPPETS[code], f"mut_{code}.py")
    assert [f.code for f in fs] == [code]
    [f] = fs
    assert f.severity == report.WARNING
    assert f.file == f"mut_{code}.py" and f.line is not None


def test_no_mutation_no_findings():
    """Control: with nothing planted, the analyzer stays silent — the
    mutations above are detected, not hallucinated."""
    assert wc.check_widths(Bounds()) == []
    assert wc.check_widths(Bounds(history=True)) == []
