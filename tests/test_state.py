"""State schema: pack/unpack round-trip, canonicalization, fingerprints."""

import numpy as np
import pytest

from raft_tla_tpu.config import Bounds
from raft_tla_tpu.models import interp
from raft_tla_tpu.ops import fingerprint as fp
from raft_tla_tpu.ops import msgbits as mb
from raft_tla_tpu.ops import state as st

B = Bounds(n_servers=3, n_values=2, max_term=3, max_log=2, max_msgs=4)


def _random_log(rng, bounds: Bounds) -> tuple:
    ln = rng.integers(0, bounds.log_cap + 1)
    return tuple(
        (int(rng.integers(1, bounds.term_cap + 1)),
         int(rng.integers(1, bounds.n_values + 1))) for _ in range(ln))


def random_pystate(rng, bounds: Bounds) -> interp.PyState:
    """Arbitrary bounded (not necessarily reachable) state, canonical."""
    n, V = bounds.n_servers, bounds.n_values
    logs = [_random_log(rng, bounds) for _ in range(n)]
    hist = {}

    def rank(log):              # parity mode: mlog stripped (g = 0)
        return 0
    if bounds.history:
        from raft_tla_tpu.ops.loguniv import LogUniverse
        uni = LogUniverse.of(bounds)

        def rank(log):          # noqa: F811 — faithful: mlog joins identity
            return uni.id_of_tuple(log)
        all_logs = {_random_log(rng, bounds)
                    for _ in range(rng.integers(0, 5))}
        vlog = tuple(tuple(
            _random_log(rng, bounds) if rng.integers(0, 2) else None
            for _j in range(n)) for _i in range(n))
        recs = set()
        for _ in range(rng.integers(0, bounds.max_elections + 1)):
            recs.add((int(rng.integers(1, bounds.term_cap + 1)),
                      int(rng.integers(0, n)),
                      _random_log(rng, bounds),
                      int(rng.integers(0, 2 ** n)),
                      tuple(_random_log(rng, bounds)
                            if rng.integers(0, 2) else None
                            for _j in range(n))))
        hist = dict(
            allLogs=tuple(sorted(all_logs, key=interp._log_key)),
            vLog=vlog,
            elections=tuple(sorted(recs, key=interp._election_key)))
    msgs = {}
    for _ in range(rng.integers(0, bounds.msg_cap + 1)):
        mt = int(rng.integers(1, 5))
        term = int(rng.integers(1, bounds.term_cap + 1))
        i, j = int(rng.integers(0, n)), int(rng.integers(0, n))
        if mt == 1:
            m = mb.rv_request(term, int(rng.integers(0, bounds.term_cap + 1)),
                              int(rng.integers(0, bounds.log_cap + 1)), i, j)
        elif mt == 2:
            m = mb.rv_response(term, int(rng.integers(0, 2)), i, j,
                               rank(_random_log(rng, bounds)))
        elif mt == 3:
            ne = int(rng.integers(0, 2))
            m = mb.ae_request(term, int(rng.integers(0, bounds.log_cap + 1)),
                              int(rng.integers(0, bounds.term_cap + 1)),
                              ne, ne * int(rng.integers(1, bounds.term_cap + 1)),
                              ne * int(rng.integers(1, V + 1)),
                              int(rng.integers(0, bounds.log_cap + 1)), i, j,
                              rank(_random_log(rng, bounds)))
        else:
            m = mb.ae_response(term, int(rng.integers(0, 2)),
                               int(rng.integers(0, bounds.log_cap + 1)), i, j)
        msgs[m] = int(rng.integers(1, bounds.dup_cap + 1))
    return interp.PyState(
        **hist,
        role=tuple(int(x) for x in rng.integers(0, 3, n)),
        term=tuple(int(x) for x in rng.integers(1, bounds.term_cap + 1, n)),
        votedFor=tuple(int(x) for x in rng.integers(0, n + 1, n)),
        commitIndex=tuple(int(rng.integers(0, len(l) + 1)) for l in logs),
        log=tuple(logs),
        vResp=tuple(int(x) for x in rng.integers(0, 2**n, n)),
        vGrant=tuple(int(x) for x in rng.integers(0, 2**n, n)),
        # nextIndex[i][j] <= Len(log[i]) + 1: beyond that, AppendEntries'
        # log[i][prevLogIndex] (raft.tla:209) is an undefined partial-function
        # application (TLC would error); reachable states always satisfy it.
        nextIndex=tuple(tuple(int(x) for x in rng.integers(1, len(logs[i]) + 2, n))
                        for i in range(n)),
        matchIndex=tuple(tuple(int(x) for x in rng.integers(0, bounds.log_cap + 1, n))
                         for _ in range(n)),
        msgs=tuple(sorted(msgs.items())),
    )


def test_msgbits_roundtrip():
    hi, lo = mb.ae_request(5, 3, 2, 1, 4, 2, 1, 2, 0)
    assert mb.mtype(hi) == 3
    assert mb.mterm(hi) == 5
    assert mb.fa(hi) == 3 and mb.fb(hi) == 2
    assert mb.src(hi) == 2 and mb.dst(hi) == 0
    assert mb.fc(lo) == 1 and mb.fd(lo) == 4 and mb.fe(lo) == 2 and mb.ff(lo) == 1


def test_layout_width():
    lay = st.Layout.of(B)
    n, L, S = lay.n, lay.L, lay.S
    assert lay.width == 7 * n + 2 * n * L + 2 * n * n + 3 * S


def test_pystate_struct_vec_roundtrip():
    rng = np.random.default_rng(0)
    for _ in range(50):
        s = random_pystate(rng, B)
        struct = interp.to_struct(s, B)
        assert interp.from_struct(struct, B) == s
        vec = st.pack(struct, np)
        assert vec.shape == (st.Layout.of(B).width,)
        back = st.unpack(vec, st.Layout.of(B), np)
        assert interp.from_struct(back, B) == s


def test_canonicalize_is_sort_invariant():
    rng = np.random.default_rng(1)
    s = random_pystate(rng, B)
    while len(s.msgs) < 2:
        s = random_pystate(rng, B)
    struct = interp.to_struct(s, B)
    # scramble slot order (including moving empties to the front)
    perm = rng.permutation(st.Layout.of(B).S)
    scrambled = dict(struct)
    for f in ("msgHi", "msgLo", "msgCount"):
        scrambled[f] = struct[f][perm]
    canon = st.canonicalize(scrambled, np)
    np.testing.assert_array_equal(canon["msgHi"], struct["msgHi"])
    np.testing.assert_array_equal(canon["msgLo"], struct["msgLo"])
    np.testing.assert_array_equal(canon["msgCount"], struct["msgCount"])


def test_init_struct_matches_interp():
    want = interp.to_struct(interp.init_state(B), B)
    got = st.init_struct(B, np)
    for f in st.STATE_FIELDS:
        np.testing.assert_array_equal(got[f], want[f], err_msg=f)


def test_fingerprint_np_jnp_bit_identical():
    import jax.numpy as jnp
    lay = st.Layout.of(B)
    consts = fp.lane_constants(lay.width)
    rng = np.random.default_rng(2)
    vecs = np.stack([interp.to_vec(random_pystate(rng, B), B)
                     for _ in range(64)])
    h1n, h2n = fp.fingerprint(vecs, consts, np)
    h1j, h2j = fp.fingerprint(jnp.asarray(vecs), jnp.asarray(consts), jnp)
    np.testing.assert_array_equal(h1n, np.asarray(h1j))
    np.testing.assert_array_equal(h2n, np.asarray(h2j))
    # distinct states should fingerprint distinctly (64 random states)
    u = fp.to_u64(h1n, h2n)
    assert len(np.unique(u)) == len(np.unique(vecs, axis=0))


def test_constraint_ok_agrees():
    rng = np.random.default_rng(3)
    for _ in range(100):
        s = random_pystate(rng, B)
        assert bool(st.constraint_ok(interp.to_struct(s, B), B, np)) == \
            interp.constraint_ok(s, B)


def test_bounds_validation():
    with pytest.raises(ValueError):
        Bounds(n_servers=20)
    with pytest.raises(ValueError):
        Bounds(max_term=64)
