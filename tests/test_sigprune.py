"""Signature-refinement orbit-scan pruning (ops/symmetry sig-prune).

The pruned scan removes only PROVABLE duplicate orbit members (one
permutation per coset of the verified stabilizer subgroup), so its min —
the dedup key — must be bit-identical to the full scan.  Anchors:

- mask unit semantics: the coset-representative keep mask keeps exactly
  |G| / prod(class sizes!) permutations, identity always among them;
- pruned vs full bit-identity on reachable states at |G| = 6, 24, 120,
  composed with Value symmetry, VIEW folding, and faithful/history mode;
- the two adversarial poles: an all-servers-identical state (every
  transposition verifies — maximal pruning, still bit-identical) and an
  all-distinct state (nothing verifies — the mask must keep the WHOLE
  group; pruning by signature classes alone would unsoundly scan just
  the identity there);
- engine-level parity: a DDD run with the gate forced on reproduces the
  gate-off orbit count, diameter and coverage exactly.
"""

import math

import numpy as np
import pytest

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.models import interp
from raft_tla_tpu.ops import fingerprint as fpr
from raft_tla_tpu.ops import state as st
from raft_tla_tpu.ops import symmetry as sym

pytestmark = pytest.mark.smoke

B3 = Bounds(n_servers=3, n_values=2, max_term=2, max_log=1, max_msgs=2)
B4 = Bounds(n_servers=4, n_values=1, max_term=2, max_log=0, max_msgs=2)
B5 = Bounds(n_servers=5, n_values=1, max_term=2, max_log=0, max_msgs=2)
BH = Bounds(n_servers=2, n_values=2, max_term=2, max_log=1, max_msgs=2,
            history=True, max_elections=4)


def _reach_structs(bounds, spec, depth, cap=300, lane_cap=60):
    """BFS-prefix bag of reachable states as a batched device struct."""
    import jax
    import jax.numpy as jnp

    lay = st.Layout.of(bounds)
    frontier = [interp.init_state(bounds)]
    seen = list(frontier)
    for _ in range(depth):
        nxt = []
        for s in frontier:
            nxt += [t for _i, t in interp.successors(s, bounds, spec=spec)]
        frontier = nxt[:lane_cap]
        seen += frontier
    vecs = np.stack([interp.to_vec(s, bounds) for s in seen[:cap]])
    structs = jax.vmap(lambda v: st.unpack(v, lay, jnp))(jnp.asarray(vecs))
    return structs, vecs, lay


def _assert_pruned_matches_full(bounds, axes, spec, depth=3):
    import jax
    import jax.numpy as jnp

    structs, _vecs, lay = _reach_structs(bounds, spec, depth)
    consts = jnp.asarray(fpr.lane_constants(lay.width))
    faithful = "allLogs" in lay.shapes
    full = jax.jit(sym.build_orbit_fp(bounds, axes, consts, faithful))
    pruned = jax.jit(sym.build_orbit_fp(bounds, axes, consts, faithful,
                                        prune=True))
    fh, fl = full(structs)
    ph, pl = pruned(structs)
    assert bool(jnp.all(fh == ph) & jnp.all(fl == pl)), (bounds, axes)
    return fh, fl


# -- mask unit tests ---------------------------------------------------------

def test_transposition_pair_table():
    pairs = sym._transposition_pairs(B4)
    perms = sym.permutations(B4)
    assert len(pairs) == 6
    for a, b, pi in pairs:
        p = perms[pi]
        assert p[a] == b and p[b] == a
        assert all(p[j] == j for j in range(4) if j not in (a, b))


def test_pair_less_lut_is_coset_representative_condition():
    perms = sym.permutations(B4)
    pairs = sym._transposition_pairs(B4)
    less = sym._pair_less_lut(perms, pairs)
    assert less.shape == (24, len(pairs))
    for k, p in enumerate(perms):
        for c, (a, b, _pi) in enumerate(pairs):
            assert less[k, c] == (p[a] < p[b])


@pytest.mark.parametrize("classes", [
    ((0, 1, 2, 3),),                  # all interchangeable -> 1 kept
    ((0, 1), (2, 3)),                 # two pairs -> 24/(2!*2!) = 6 kept
    ((0, 1, 2), (3,)),                # triple + singleton -> 4 kept
    ((0,), (1,), (2,), (3,)),         # all distinct -> whole group kept
])
def test_keep_mask_counts_cosets(classes):
    """kept = one permutation per coset of prod(Sym(class)) — the count
    is the multinomial |G| / prod(|class|!), identity always kept."""
    perms = sym.permutations(B4)
    pairs = sym._transposition_pairs(B4)
    less = sym._pair_less_lut(perms, pairs)
    eq = np.zeros((len(pairs),), bool)
    for c, (a, b, _pi) in enumerate(pairs):
        eq[c] = any(a in cl and b in cl for cl in classes)
    keep = ~((eq[None, :] & ~less).any(axis=1))
    want = math.factorial(4)
    for cl in classes:
        want //= math.factorial(len(cl))
    assert keep.sum() == want
    assert keep[0]                    # itertools order: index 0 = identity


def test_server_sig_is_permutation_covariant():
    """sig(pi(s))[pi[i]] == sig(s)[i]: the prefilter may only ever skip
    probes that provably cannot verify."""
    import jax
    import jax.numpy as jnp

    structs, vecs, lay = _reach_structs(B3, "full", 3, cap=64)
    sig = np.asarray(sym._server_sig(structs, jnp))
    for p in sym.permutations(B3):
        permuted = [sym.permute_struct(st.unpack(v, lay, np), p, B3, np)
                    for v in vecs]
        batch = {k: np.stack([d[k] for d in permuted])
                 for k in permuted[0]}
        sig2 = np.asarray(sym._server_sig(
            {k: jnp.asarray(v) for k, v in batch.items()}, jnp))
        assert (sig2[:, list(p)] == sig).all(), p


# -- bit-identity differentials ---------------------------------------------

def test_pruned_bit_identical_g6():
    _assert_pruned_matches_full(B3, ("Server",), "full")


def test_pruned_bit_identical_g6_value_composed():
    _assert_pruned_matches_full(B3, ("Server", "Value"), "full")


def test_pruned_bit_identical_g24():
    _assert_pruned_matches_full(B4, ("Server",), "election")


def test_pruned_bit_identical_g120():
    _assert_pruned_matches_full(B5, ("Server",), "election")


def test_pruned_bit_identical_faithful_history():
    _assert_pruned_matches_full(BH, ("Server", "Value"), "full")


def test_pruned_bit_identical_with_view():
    """Composition with VIEW folding: the engines feed the orbit scan the
    VIEWED struct; pruning must hold on those too."""
    import jax
    import jax.numpy as jnp
    from raft_tla_tpu.models import views

    structs, _vecs, lay = _reach_structs(B3, "full", 3)
    viewer = views.jnp_view("deadvotes", B3)
    viewed = jax.vmap(viewer)(structs)
    consts = jnp.asarray(fpr.lane_constants(lay.width))
    full = jax.jit(sym.build_orbit_fp(B3, ("Server",), consts, False))
    pruned = jax.jit(sym.build_orbit_fp(B3, ("Server",), consts, False,
                                        prune=True))
    fh, fl = full(viewed)
    ph, pl = pruned(viewed)
    assert bool(jnp.all(fh == ph) & jnp.all(fl == pl))


def test_pruned_matches_oracle():
    """Triangulation: pruned scan vs the NumPy unrolled-loop oracle."""
    import jax
    import jax.numpy as jnp

    structs, vecs, lay = _reach_structs(B3, "full", 3, cap=48)
    consts = fpr.lane_constants(lay.width)
    pruned = jax.jit(sym.build_orbit_fp(B3, ("Server", "Value"),
                                        jnp.asarray(consts), False,
                                        prune=True))
    ph, pl = pruned(structs)
    for k in range(vecs.shape[0]):
        struct = st.unpack(vecs[k], lay, np)
        hi, lo = sym.orbit_fingerprint(struct, B3, consts, np,
                                       ("Server", "Value"))
        assert (int(ph[k]), int(pl[k])) == (int(hi), int(lo)), k


# -- adversarial poles -------------------------------------------------------

def test_adversarial_all_servers_identical():
    """Every transposition verifies: maximal pruning (1 kept server perm
    out of |G|), and the key still matches the full scan bit for bit."""
    import jax
    import jax.numpy as jnp

    lay = st.Layout.of(B5)
    vec = interp.to_vec(interp.init_state(B5), B5)
    structs = jax.vmap(lambda v: st.unpack(v, lay, jnp))(
        jnp.asarray(np.stack([vec] * 4)))
    consts = jnp.asarray(fpr.lane_constants(lay.width))
    full = sym.build_orbit_fp(B5, ("Server",), consts, False)
    pruned = sym.build_orbit_fp(B5, ("Server",), consts, False, prune=True)
    fh, fl = full(structs)
    ph, pl = pruned(structs)
    assert bool(jnp.all(fh == ph) & jnp.all(fl == pl))
    # and the mask really is maximal: all pairs verify -> 1 kept perm
    pairs = sym._transposition_pairs(B5)
    less = sym._pair_less_lut(sym.permutations(B5), pairs)
    keep = ~((np.ones((len(pairs),), bool)[None, :] & ~less).any(axis=1))
    assert keep.sum() == 1


def test_adversarial_all_distinct_keeps_whole_group():
    """No transposition verifies: the mask must keep ALL |G| permutations
    — this is the state where partition-only pruning would be unsound
    (it would scan just the identity and miss the true orbit min)."""
    import jax
    import jax.numpy as jnp

    # distinct roles/terms per server: no pair is interchangeable
    s = interp.init_state(B3)
    s = s._replace(role=(0, 1, 2), term=(1, 2, 2), votedFor=(0, 2, 3))
    lay = st.Layout.of(B3)
    vec = interp.to_vec(s, B3)
    structs = jax.vmap(lambda v: st.unpack(v, lay, jnp))(
        jnp.asarray(vec[None, :]))
    consts = jnp.asarray(fpr.lane_constants(lay.width))
    sig = np.asarray(sym._server_sig(structs, jnp))[0]
    assert len(set(sig.tolist())) == 3          # prefilter sees 3 classes
    full = sym.build_orbit_fp(B3, ("Server",), consts, False)
    pruned = sym.build_orbit_fp(B3, ("Server",), consts, False, prune=True)
    fh, fl = full(structs)
    ph, pl = pruned(structs)
    assert (int(fh[0]), int(fl[0])) == (int(ph[0]), int(pl[0]))
    # the full min must differ from the identity-only "min" for at least
    # one such state — guard that the test is actually adversarial
    packed = jnp.asarray(vec[None, :])
    ih, il = fpr.fingerprint(packed, consts, jnp)
    assert (int(ih[0]), int(il[0])) != (int(fh[0]), int(fl[0]))


# -- engine-level parity -----------------------------------------------------

def test_ddd_engine_gate_on_off_parity(monkeypatch):
    from raft_tla_tpu.ddd_engine import DDDCapacities, DDDEngine

    cfg = CheckConfig(bounds=Bounds(n_servers=3, n_values=1, max_term=2,
                                    max_log=0, max_msgs=1),
                      spec="election", invariants=("NoTwoLeaders",),
                      symmetry=("Server",), chunk=256)
    caps = DDDCapacities(block=1 << 12, table=1 << 14, flush=1 << 14,
                         levels=32)
    results = {}
    for mode in ("off", "on"):
        monkeypatch.setenv("RAFT_TLA_SIGPRUNE", mode)
        r = DDDEngine(cfg, caps).check()
        results[mode] = (r.n_states, r.diameter, r.levels, r.n_transitions,
                         r.coverage, r.violation is None)
    assert results["on"] == results["off"]
