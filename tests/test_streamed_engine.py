"""Host-streamed frontier engine (streamed_engine.py).

The engine exists because level windows outgrow any legal HBM ring (the
elect5 runs FAIL_RING'd at ring 2^25 — runs/elect5v2.stats); its gates:
oracle-exact parity with blocks/rings small enough to cycle many times,
completion of a space whose live window exceeds the ring, trace replay,
and block-boundary checkpoint/resume with exact counters.
"""

import numpy as np
import pytest

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.models import interp, refbfs
from raft_tla_tpu.streamed_engine import StreamedCapacities, StreamedEngine

CFG = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                max_log=0, max_msgs=2),
                  spec="election", invariants=("NoTwoLeaders",), chunk=32)
CAPS = StreamedCapacities(block=256, ring=4096, table=1 << 14, levels=64)


def test_parity_with_oracle_tiny_block_and_ring():
    ref = refbfs.check(CFG)
    got = StreamedEngine(CFG, CAPS).check()
    assert got.n_states == ref.n_states == 3014
    assert got.diameter == ref.diameter == 17
    assert got.levels == ref.levels
    assert got.n_transitions == ref.n_transitions
    assert got.coverage == ref.coverage      # identical discovery order
    assert got.violation is None and got.complete


def test_window_past_any_ring_completes():
    """The 3-server election space's widest level pair (~45k rows) exceeds
    a 4096-row ring many times over — the paged engine would FAIL_RING;
    the streamed engine only buffers appends in the ring and completes."""
    cfg = CheckConfig(bounds=Bounds(n_servers=3, n_values=1, max_term=2,
                                    max_log=0, max_msgs=1),
                      spec="election", invariants=("NoTwoLeaders",),
                      chunk=64)
    caps = StreamedCapacities(block=1 << 13, ring=4096, table=1 << 19,
                              levels=64)
    got = StreamedEngine(cfg, caps).check()
    assert got.n_states == 142538
    assert got.diameter == 31
    assert got.complete


def test_violation_trace_replays():
    from raft_tla_tpu.models import invariants as inv_mod
    from raft_tla_tpu.models import spec as S
    from raft_tla_tpu.ops import msgbits as mb

    bounds = Bounds(n_servers=3, n_values=1, max_term=3, max_log=0,
                    max_msgs=4, max_dup=1)
    cfg = CheckConfig(bounds=bounds, spec="election",
                      invariants=("NaiveNoTwoLeaders",), chunk=64)
    start = interp.init_state(bounds)._replace(
        role=(S.LEADER, S.FOLLOWER, S.CANDIDATE),
        term=(2, 3, 3),
        votedFor=(1, 3, 0),
        vGrant=(0b011, 0, 0b100),
        msgs=tuple(sorted((m, 1) for m in
                          (mb.rv_response(3, 1, 1, 2),))),
    )
    caps = StreamedCapacities(block=1 << 12, ring=1 << 13, table=1 << 17,
                              levels=64)
    got = StreamedEngine(cfg, caps).check(init_override=start)
    assert got.violation is not None
    assert got.violation.invariant == "NaiveNoTwoLeaders"
    trace = got.violation.trace
    assert trace[0][0] is None and trace[0][1] == start
    for (_l, prev), (_label, cur) in zip(trace, trace[1:]):
        succs = [t for _i, t in interp.successors(prev, bounds,
                                                  spec="election")]
        assert cur in succs
    assert not inv_mod.py_invariant("NaiveNoTwoLeaders")(
        got.violation.state, bounds)


def test_checkpoint_resume_bit_exact(tmp_path):
    ck = str(tmp_path / "streamed.ckpt")

    def eng():
        e = StreamedEngine(CFG, CAPS, seg_chunks=8)
        e.SEG_MAX = 8
        return e

    straight = eng().check()
    res = eng().check(checkpoint=ck, checkpoint_every_s=0.0)
    assert res.n_states == straight.n_states
    resumed = eng().check(resume=ck)
    assert resumed.n_states == straight.n_states
    assert resumed.levels == straight.levels
    assert resumed.n_transitions == straight.n_transitions
    assert resumed.coverage == straight.coverage
    assert resumed.violation is None

    other = StreamedEngine(CFG, StreamedCapacities(
        block=512, ring=4096, table=1 << 14, levels=64))
    with pytest.raises(ValueError, match="checkpoint"):
        other.check(resume=ck)


def test_symmetry_composes():
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=("NoTwoLeaders",),
                      symmetry=("Server",), chunk=32)
    ref = refbfs.check(cfg)
    got = StreamedEngine(cfg, CAPS).check()
    assert got.n_states == ref.n_states == 1514
    assert got.diameter == ref.diameter
    assert got.coverage == ref.coverage


def test_deadlock_detected():
    cfg = CheckConfig(bounds=Bounds(n_servers=1, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=(), chunk=16,
                      check_deadlock=True)
    ref = refbfs.check(cfg)
    caps = StreamedCapacities(block=64, ring=2048, table=1 << 12,
                              levels=64)
    got = StreamedEngine(cfg, caps).check()
    assert ref.violation is not None and got.violation is not None
    assert got.violation.invariant == ref.violation.invariant  # DEADLOCK
    assert got.n_states == ref.n_states


def test_faithful_mode_parity():
    """Faithful mode (history variables as real fingerprinted state) on
    the streamed engine: packed history rows survive the host round-trip
    (store -> frontier re-upload) bit-exactly."""
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=1, max_msgs=2, history=True,
                                    max_elections=4),
                      spec="full",
                      invariants=("NoTwoLeaders", "ElectionSafetyHist",
                                  "AllLogsPrefixClosed"), chunk=512)
    ref = refbfs.check(cfg)
    assert (ref.n_states, ref.diameter) == (53398, 32)
    caps = StreamedCapacities(block=1 << 13, ring=1 << 15, table=1 << 18,
                              levels=64)
    got = StreamedEngine(cfg, caps).check()
    assert (got.n_states, got.diameter) == (ref.n_states, ref.diameter)
    assert got.levels == ref.levels
    assert got.coverage == ref.coverage
    assert got.violation is None
