"""Mesh-sharded walker fleets (fleet/): device-count-invariant results,
coverage steering, fault-weight scenarios, and fleet telemetry.

The load-bearing contract: a fixed (seed, walkers, depth,
steps_per_dispatch) reproduces the SAME walks bit for bit at any device
count — sharding is a throughput decision, never a semantics decision.
"""

import numpy as np
import pytest

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.fleet import FleetSimulator, Scenario, fault_matrix, \
    run_matrix
from raft_tla_tpu.fleet.scenario import FAULT_FAMILIES
from raft_tla_tpu.models import interp, spec as S
from raft_tla_tpu.ops import msgbits as mb
from raft_tla_tpu.parallel.shard_engine import make_mesh

B3 = Bounds(n_servers=3, n_values=1, max_term=3, max_log=0, max_msgs=4)
CV = CheckConfig(bounds=B3, spec="election",
                 invariants=("NaiveNoTwoLeaders",))
CLEAN = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                  max_log=1, max_msgs=2),
                    spec="full", invariants=("NoTwoLeaders",))


def bag(*ms):
    return tuple(sorted((m, 1) for m in ms))


def seeded_start():
    """Two steps from a NaiveNoTwoLeaders violation (engine-test seed)."""
    return interp.init_state(B3)._replace(
        role=(S.LEADER, S.FOLLOWER, S.CANDIDATE),
        term=(2, 3, 3), votedFor=(1, 3, 0),
        vGrant=(0b011, 0, 0b100), msgs=bag(mb.rv_response(3, 1, 1, 2)))


def fleet(config, ndev, **kw):
    kw.setdefault("walkers", 64)
    kw.setdefault("depth", 24)
    kw.setdefault("steps_per_dispatch", 12)
    kw.setdefault("seed", 11)
    return FleetSimulator(config, mesh=make_mesh(ndev), **kw)


def test_device_count_invariance_bit_for_bit():
    """Same (seed, walkers, depth) -> identical walks at 1 vs 2 devices,
    down to the recorded per-walker lane histories."""
    r1 = fleet(CLEAN, 1).run(300, snapshot_walks=True)
    r2 = fleet(CLEAN, 2).run(300, snapshot_walks=True)
    assert (r1.n_behaviors, r1.n_states, r1.max_depth_seen) == \
        (r2.n_behaviors, r2.n_states, r2.max_depth_seen)
    assert r1.coverage == r2.coverage
    assert r1.coverage_entropy == r2.coverage_entropy
    assert np.array_equal(r1.walks[0], r2.walks[0])     # lane histories
    assert np.array_equal(r1.walks[1], r2.walks[1])     # walk lengths
    assert r1.device_states == [r1.n_states]
    assert sum(r2.device_states) == r2.n_states and \
        len(r2.device_states) == 2


@pytest.mark.slow
def test_violation_parity_and_replay_across_meshes():
    traces = []
    for nd in (1, 2):
        r = fleet(CV, nd, walkers=128, depth=20, steps_per_dispatch=10,
                  seed=3).run(100000, init_override=seeded_start())
        assert r.violation is not None
        assert r.violation.invariant == "NaiveNoTwoLeaders"
        traces.append(r.violation.trace)
    assert traces[0] == traces[1]
    tab = S.action_table(B3, "election")
    cur = traces[0][0][1]
    for label, nxt in traces[0][1:]:
        assert nxt in {t for _a, t in interp.successors(cur, B3, tab)}, \
            label
        cur = nxt
    assert sum(1 for x in cur.role if x == S.LEADER) >= 2


@pytest.mark.slow
def test_steering_shifts_coverage():
    """Coverage steering flattens the per-action histogram: normalized
    entropy rises, while the run still checks the same invariants over
    the same universe."""
    base = fleet(CV, 2, walkers=128, seed=5).run(400)
    steered = fleet(CV, 2, walkers=128, seed=5, steer_tau=2.0).run(400)
    assert steered.coverage_entropy > base.coverage_entropy
    assert steered.violation is None and base.violation is None
    assert sum(steered.coverage.values()) > 0


@pytest.mark.slow
def test_fault_weight_matrix_shifts_sampling():
    """One compiled fleet sweeps the fault-intensity matrix (weights are
    a traced input): weight 0 starves the fault lanes, weight 2 feeds
    them — without touching enabledness."""
    cc = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                   max_log=1, max_msgs=2, max_dup=1),
                     spec="full", invariants=("NoTwoLeaders",))
    sim = fleet(cc, 2, walkers=128, depth=30, steps_per_dispatch=15)
    cells = run_matrix(sim, fault_matrix((0.0, 2.0)), 300)
    by_name = {sc.name: r for sc, r in cells}
    visits = lambda r: sum(r.coverage.get(f, 0) for f in FAULT_FAMILIES)
    assert visits(by_name["faults-x0"]) == 0
    assert visits(by_name["faults-x2"]) > visits(by_name["uniform"]) > 0


@pytest.mark.slow
def test_zero_weight_never_false_deadlocks():
    """When weight-0 lanes are the only enabled ones the sampler falls
    back to uniform-over-valid: from Raft init only Timeout is enabled,
    and starving it must not strand the fleet."""
    r = fleet(CV, 2, fault_weights={"Timeout": 0.0}).run(100)
    assert r.violation is None and r.n_behaviors >= 100
    assert r.coverage["Timeout"] > 0          # fallback sampled it


def test_fleet_rejects_bad_shapes_and_weights():
    with pytest.raises(ValueError, match="divide evenly"):
        fleet(CV, 2, walkers=63)
    with pytest.raises(ValueError, match="unknown action families"):
        fleet(CV, 1, fault_weights={"Restart": 1.0})   # not in election
    with pytest.raises(ValueError, match="negative"):
        fleet(CV, 1, fault_weights={"Timeout": -1.0})
    with pytest.raises(ValueError, match="SYMMETRY"):
        FleetSimulator(CheckConfig(bounds=B3, spec="election",
                                   invariants=(), symmetry=("Server",)))


def test_twophase_fleet_violation_replays():
    cc = CheckConfig(bounds=Bounds(n_servers=2, n_values=1),
                     spec="twophase", invariants=("~(msgCommit = 1)",))
    r = fleet(cc, 2, depth=20).run(200)
    assert r.violation is not None
    assert r.violation.invariant == "~(msgCommit = 1)"
    assert r.violation.trace[-1][1] == r.violation.state
    assert len(r.violation.trace) >= 5        # prepare/prepare/rcv/commit
    from raft_tla_tpu.frontend import resolve_model
    txt = resolve_model("twophase").render_trace(r.violation, cc.bounds)
    assert "TMCommit" in txt and "Initial predicate" in txt


def test_fleet_emits_conformant_events(tmp_path):
    """fleet speaks RunTelemetry v3: per-device segment rates and a
    run_end carrying the statistical-confidence payload."""
    import json

    from raft_tla_tpu.obs import validate_event

    path = str(tmp_path / "fleet.events")
    r = fleet(CLEAN, 2).run(300, events=path)
    assert r.violation is None
    events = [json.loads(l) for l in open(path)]
    assert not [e for d in events for e in validate_event(d)]
    assert events[0]["event"] == "run_start"
    assert events[0]["engine"] == "fleet"
    segs = [d for d in events if d["event"] == "segment"]
    assert segs and all(len(d["device_rates"]) == 2 for d in segs)
    end = events[-1]
    assert end["event"] == "run_end" and end["outcome"] == "ok"
    sim = end["sim"]
    assert sim["behaviors"] == r.n_behaviors
    assert sim["sampled_transitions"] == r.n_states
    assert sim["n_devices"] == 2 and sim["walkers"] == 64
    assert sim["per_invariant"] == {"NoTwoLeaders": r.n_states}
    # the run_end payload IS the result's confidence report
    conf = r.confidence(CLEAN.invariants)
    assert sim == {**conf, "behaviors": r.n_behaviors}
    assert 0.0 <= conf["coverage_entropy"] <= 1.0
    assert r.states_per_sec > 0


def test_scenario_matrix_helpers():
    ms = fault_matrix((0.0, 0.5, 1.0, 2.0))
    assert [s.name for s in ms] == ["uniform", "faults-x0", "faults-x0.5",
                                    "faults-x2"]      # x1 == uniform
    assert ms[0].describe() == "uniform: uniform"
    assert "Restart=2" in ms[-1].describe()
    assert Scenario("x", {"Restart": 0.5}).fault_weights == \
        {"Restart": 0.5}


def test_cli_fleet_smoke(tmp_path):
    from test_cli import run_cli, write_cfg
    from raft_tla_tpu import check as cli
    cfg = write_cfg(tmp_path / "f.cfg")
    code, out = run_cli(cfg, "--engine", "ref", "--spec", "election",
                        "--max-term", "2", "--max-log", "0",
                        "--max-msgs", "2", "--simulate", "200",
                        "--depth", "20", "--walkers", "64", "--seed", "5",
                        "--fleet", "--devices", "2")
    assert code == cli.EXIT_OK
    assert "behaviors generated" in out and "not exhaustive" in out
    assert "Fleet: 2 devices x 32 walkers" in out
    assert "held on" in out                 # confidence lines


def test_cli_fleet_flag_validation(tmp_path):
    from test_cli import run_cli, write_cfg
    cfg = write_cfg(tmp_path / "v.cfg")
    for extra in (["--fleet"],                          # no --simulate
                  ["--simulate", "10", "--steer", "1"],  # steer sans fleet
                  ["--simulate", "10", "--fault-weights", "Restart=2"]):
        with pytest.raises(SystemExit):
            run_cli(cfg, "--engine", "ref", "--spec", "election",
                    "--max-term", "2", "--max-log", "0",
                    "--max-msgs", "2", *extra)
