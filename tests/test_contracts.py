"""Pass 5 (contracts) — planted contract-break suite and clean-tree
assertions.

Everything runs against injectable ``contracts.Inputs`` fixtures: a
minimal fully-wired gate (env alias, resolver, CLI flag, smoke line,
README line, digest-free) plus a tiny obs schema and two emitters.
Each planted bug is a single-edit mutation of that clean base, and the
clean base itself must produce zero findings (0 FP) so every finding in
the mutation tests is attributable to the planted edit (0 FN).
"""

from __future__ import annotations

import pytest

from raft_tla_tpu.analysis import contracts
from raft_tla_tpu.analysis.report import CONTRACT, ERROR

pytestmark = pytest.mark.smoke


GATE_MOD = '''
import os

ENV_FROBBLE = "RAFT_TLA_FROBBLE"

def frobble_enabled(explicit=None):
    """The one resolution point for the FROBBLE gate."""
    return explicit or os.environ.get(ENV_FROBBLE) or None

def add_args(p):
    p.add_argument("--frobble", choices=("auto", "on", "off"),
                   help="sets RAFT_TLA_FROBBLE for the whole run")
'''

SCHEMA_MOD = '''
_BASE = {"v": int, "event": str, "ts": float}

_SEGMENT_REQUIRED = {"states": int}

_REQUIRED = {
    "run-start": {"spec": str},
    "segment": _SEGMENT_REQUIRED,
}

_OPTIONAL = {
    "segment": {"wall_s": float},
}

SCHEMA_VERSION = 1
'''

EMIT_MOD = '''
def emit_run(path, append_event):
    append_event(path, "run-start", spec="full")

def emit_seg(tel):
    tel.emit("segment", states=3, wall_s=0.5)
'''

DIGEST_MOD = '''
import hashlib

def config_digest(config, caps, init_key):
    blob = repr((config, caps, init_key)).encode()
    return hashlib.sha256(blob).hexdigest()
'''

README = ("The `--frobble` flag (env `RAFT_TLA_FROBBLE`) toggles "
          "frobbling for the run.\n")

LINT_SH = "python -m raft_tla_tpu.check --frobble on runs/toy.cfg\n"


def _inputs(sources=None, readme=README, lint_sh=LINT_SH):
    base = {
        "gates.py": GATE_MOD,
        "emit.py": EMIT_MOD,
        "obs_events.py": SCHEMA_MOD,
        "ckpt.py": DIGEST_MOD,
    }
    if sources:
        base.update(sources)
    return contracts.Inputs(sources=base, readme=readme, lint_sh=lint_sh,
                            schema_path="obs_events.py",
                            digest_path="ckpt.py")


def _codes(findings):
    return sorted(f.code for f in findings)


def test_clean_base_no_findings():
    assert contracts.lint_inputs(_inputs()) == []


# -- gate contract: planted breaks, one leg at a time ------------------------

def test_gate_no_cli_flag():
    mod = GATE_MOD.replace(
        '''def add_args(p):
    p.add_argument("--frobble", choices=("auto", "on", "off"),
                   help="sets RAFT_TLA_FROBBLE for the whole run")
''', "")
    findings = contracts.lint_inputs(_inputs({"gates.py": mod}))
    assert _codes(findings) == ["gate-no-cli-flag"]
    f = findings[0]
    assert f.pass_ == CONTRACT and f.severity == ERROR
    assert "RAFT_TLA_FROBBLE" in f.message


def test_gate_no_smoke():
    findings = contracts.lint_inputs(_inputs(lint_sh=""))
    assert _codes(findings) == ["gate-no-smoke"]
    assert "lint.sh" in findings[0].message


def test_smoke_by_flag_counts():
    # the smoke block may exercise the flag rather than the env name
    findings = contracts.lint_inputs(_inputs(
        lint_sh="run --frobble off x.cfg\n"))
    assert findings == []


def test_gate_no_readme():
    findings = contracts.lint_inputs(_inputs(readme=""))
    assert _codes(findings) == ["gate-no-readme"]


def test_gate_no_resolver():
    mod = GATE_MOD.replace(
        "    return explicit or os.environ.get(ENV_FROBBLE) or None",
        "    return explicit")
    findings = contracts.lint_inputs(_inputs({"gates.py": mod}))
    assert _codes(findings) == ["gate-no-resolver"]
    assert "nothing reads it" in findings[0].message


def test_gate_multiple_resolvers():
    extra = '''
import os

def sneaky_read():
    return os.environ.get("RAFT_TLA_FROBBLE")
'''
    findings = contracts.lint_inputs(_inputs({"extra.py": extra}))
    assert _codes(findings) == ["gate-multiple-resolvers"]
    # both resolution sites are cited
    assert "extra.py" in findings[0].message
    assert "gates.py" in findings[0].message


def test_gate_in_digest():
    mod = DIGEST_MOD.replace(
        "    blob = repr((config, caps, init_key)).encode()",
        '    tag = "RAFT_TLA_FROBBLE"\n'
        "    blob = repr((config, caps, init_key, tag)).encode()")
    findings = contracts.lint_inputs(_inputs({"ckpt.py": mod}))
    assert _codes(findings) == ["gate-in-digest"]
    assert "unresumable" in findings[0].message


def test_gate_near_miss_did_you_mean():
    typo = '''
import os

def oops():
    return os.environ.get("RAFT_TLA_FROBLE")
'''
    findings = contracts.lint_inputs(_inputs({"typo.py": typo}))
    assert _codes(findings) == ["gate-near-miss"]
    f = findings[0]
    assert "RAFT_TLA_FROBBLE" in f.message and "did you mean" in f.message
    assert f.file == "typo.py"


def test_env_subscript_read_counts_as_resolver():
    mod = GATE_MOD.replace(
        "    return explicit or os.environ.get(ENV_FROBBLE) or None",
        "    return explicit or os.environ[ENV_FROBBLE]")
    assert contracts.lint_inputs(_inputs({"gates.py": mod})) == []


# -- obs-schema contract ------------------------------------------------------

def test_obs_field_without_schema_bump():
    mod = EMIT_MOD.replace(
        'tel.emit("segment", states=3, wall_s=0.5)',
        'tel.emit("segment", states=3, wall_s=0.5, queue_depth=2)')
    findings = contracts.lint_inputs(_inputs({"emit.py": mod}))
    assert _codes(findings) == ["obs-undeclared-field"]
    f = findings[0]
    assert f.field == "segment.queue_depth"
    assert "SCHEMA_VERSION bump" in f.message


def test_obs_unknown_event():
    mod = EMIT_MOD + '''
def emit_warp(path, append_event):
    append_event(path, "warp-start", x=1)
'''
    findings = contracts.lint_inputs(_inputs({"emit.py": mod}))
    assert _codes(findings) == ["obs-unknown-event"]
    assert findings[0].field == "warp-start"


def test_obs_splat_is_runtime_territory():
    # **fields splats are validate_event's job, not the static pass's
    mod = EMIT_MOD + '''
def emit_any(tel, fields):
    tel.emit("segment", **fields)
'''
    assert contracts.lint_inputs(_inputs({"emit.py": mod})) == []


def test_parse_schema_resolves_named_tables():
    allowed, events = contracts.parse_schema(SCHEMA_MOD)
    assert events == {"run-start", "segment"}
    # _SEGMENT_REQUIRED indirection resolved, _BASE unioned in
    assert allowed["segment"] == {"v", "event", "ts", "states", "wall_s"}
    assert allowed["run-start"] == {"v", "event", "ts", "spec"}


# -- waiver audit -------------------------------------------------------------

def test_stale_jit_waiver():
    mod = "def f():\n    x = 1  # lint: jit-ok long gone\n    return x\n"
    findings = contracts.lint_inputs(_inputs({"w.py": mod}))
    assert _codes(findings) == ["stale-waiver"]
    assert "jit-ok" in findings[0].message


def test_live_jit_waiver_is_kept():
    mod = '''
import jax.numpy as jnp

def f(x):
    if x[0] > 0:  # lint: jit-ok planted hazard for the waiver audit
        return jnp.sum(x)
    return x
'''
    assert contracts.lint_inputs(_inputs({"w.py": mod})) == []


def test_stale_thread_waiver():
    mod = ("def f():\n"
           "    y = 2  # lint: thread-ok nothing races here anymore\n"
           "    return y\n")
    findings = contracts.lint_inputs(_inputs({"w.py": mod}))
    assert _codes(findings) == ["stale-waiver"]
    assert "thread-ok" in findings[0].message


def test_live_thread_waiver_is_kept():
    mod = '''
import threading

class W:
    def __init__(self):
        self.flag = False
        t = threading.Thread(target=self.run, daemon=True)
        t.start()

    def run(self):
        self.flag = True  # lint: thread-ok benign one-way latch

    def done(self):
        return self.flag
'''
    assert contracts.lint_inputs(_inputs({"w.py": mod})) == []


def test_waiver_unknown_kind():
    mod = "def f():\n    x = 1  # lint: threads-ok typo'd kind\n"
    findings = contracts.lint_inputs(_inputs({"w.py": mod}))
    assert _codes(findings) == ["waiver-unknown-kind"]
    assert "threads-ok" in findings[0].message


def test_docstring_mention_is_not_a_waiver():
    mod = '\'\'\'This module documents the `# lint: jit-ok` syntax.\'\'\'\n'
    assert contracts.lint_inputs(_inputs({"w.py": mod})) == []


# -- the whole tree -----------------------------------------------------------

def test_contracts_repo_is_clean():
    """Every gate fully wired, every emission in schema, every waiver
    live — the pass gates the tree."""
    assert contracts.lint_paths() == []
