"""Cross-run regression sentinel (ISSUE 20): the shared drift policy
(obs/history.py — the campaign supervisor's health-watch comparison,
factored out), the history store's median baselines, ingest of the
recorded BENCH artifacts, and the ``raft-tla-regress`` CLI verdicts —
including the mechanical reproduction of the RESULTS.md devdedup
0.44x warm-rate refutation from ``runs/devdedup_ab.out``.
"""

import json
import os

import pytest

from raft_tla_tpu.obs.events import append_event
from raft_tla_tpu.obs.history import (_DRIFT_EXEMPT, HistoryStore,
                                      append_bench, bench_record,
                                      drift_report, fiducial_drift,
                                      history_path, ingest_file,
                                      run_record)
from raft_tla_tpu.obs.regress import (EXIT_DRIFT, EXIT_NO_BASELINE,
                                      EXIT_OK, EXIT_USAGE, main)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH_FILES = sorted(
    os.path.join(REPO, f) for f in os.listdir(REPO)
    if f.startswith("BENCH_r") and f.endswith(".json"))


# --------------------------------------------------------------------------
# the shared drift policy


def test_fiducial_drift_supervisor_semantics():
    """Exactly the HealthMonitor comparison: first offending key in
    sorted order, one-sided growth, exempt set honored."""
    base = {"synthetic_step_ms": 10.0, "copy_64mb_ms": 20.0,
            "trace_emit_overhead_us": 0.2}
    assert fiducial_drift(base, dict(base), 1.5) is None
    # shrinking is not drift (one-sided: degradation only)
    assert fiducial_drift(base, {"synthetic_step_ms": 1.0}, 1.5) is None
    key, ratio = fiducial_drift(
        base, {"synthetic_step_ms": 40.0, "copy_64mb_ms": 100.0}, 1.5)
    assert key == "copy_64mb_ms" and ratio == 5.0    # sorted order: c < s
    # the exempt timing pin never triggers, however wild
    assert "trace_emit_overhead_us" in _DRIFT_EXEMPT
    assert fiducial_drift(base, {"trace_emit_overhead_us": 99.0},
                          1.5) is None
    # degenerate inputs: no policy, no baseline, no current
    assert fiducial_drift(base, {"synthetic_step_ms": 99.0}, 0) is None
    assert fiducial_drift({}, {"x": 9.0}, 1.5) is None
    assert fiducial_drift({"x": 1.0}, {}, 1.5) is None
    # non-numeric / non-positive baselines never divide
    assert fiducial_drift({"x": "fast", "y": 0.0},
                          {"x": "slow", "y": 9.0}, 1.5) is None


def test_drift_report_rate_inversion():
    """Rate-type keys (states/s, warm rates) compare inverted so >1 is
    a regression for walls and rates alike under one tolerance."""
    base = {"wall_s": 100.0, "states_per_sec": 1000.0,
            "dedup_hit_rate": 0.8, "n_states": 3014}
    cur = {"wall_s": 120.0, "states_per_sec": 400.0,
           "dedup_hit_rate": 0.8, "n_states": 3014}
    rep = drift_report(base, cur, 1.5)
    assert not rep["ok"]
    assert rep["keys"]["wall_s"]["ratio"] == 1.2          # current/baseline
    assert not rep["keys"]["wall_s"]["drift"]
    assert rep["keys"]["states_per_sec"]["ratio"] == 2.5  # baseline/current
    assert rep["keys"]["states_per_sec"]["rate"]
    assert rep["keys"]["states_per_sec"]["drift"]
    assert rep["worst"] == ("states_per_sec", 2.5)
    # a faster run is ratio < 1 on both conventions: clean
    fast = {"wall_s": 50.0, "states_per_sec": 2000.0,
            "dedup_hit_rate": 0.9, "n_states": 3014}
    assert drift_report(base, fast, 1.5)["ok"]


# --------------------------------------------------------------------------
# records / store / ingest


def test_bench_record_keyed_by_metric_identity():
    parsed = {"metric": "orbits_per_sec", "unit": "1/s", "value": 100.0}
    a = bench_record(parsed, ts=1.0)
    b = bench_record({**parsed, "value": 120.0}, ts=2.0)
    c = bench_record({**parsed, "metric": "renamed"}, ts=3.0)
    assert a["key"] == b["key"] != c["key"]     # renamed metric: new key
    assert a["key"].startswith("bench:")
    assert bench_record({"metric": "m", "unit": "u"}) is None  # no numbers


def test_run_record_from_event_log(tmp_path):
    p = str(tmp_path / "run.events")
    append_event(p, "run_start", ts=10.0, engine="device",
                 universe={"servers": 3, "values": 2}, spec="election",
                 invariants=["NoTwoLeaders"], resumed=False,
                 fiducials={"synthetic_step_ms": 12.0})
    append_event(p, "run_end", ts=110.0, n_states=1000,
                 n_transitions=2000, complete=True, outcome="ok",
                 wall_s=100.0)
    recs = ingest_file(p)
    assert len(recs) == 1
    rec = recs[0]
    assert rec["kind"] == "run" and rec["key"].startswith("run:")
    assert rec["parsed"]["synthetic_step_ms"] == 12.0
    assert rec["parsed"]["n_states"] == 1000
    assert rec["parsed"]["states_per_sec"] == 10.0
    # same config -> same key; different bounds -> different key
    q = str(tmp_path / "other.events")
    append_event(q, "run_start", ts=20.0, engine="device",
                 universe={"servers": 5, "values": 2}, spec="election",
                 invariants=["NoTwoLeaders"], resumed=False)
    append_event(q, "run_end", ts=21.0, n_states=10, n_transitions=20,
                 complete=True, outcome="ok")
    assert ingest_file(q)[0]["key"] != rec["key"]


def test_store_baseline_is_per_field_median(tmp_path):
    store = HistoryStore(str(tmp_path / "h.jsonl"))
    for wall in (100.0, 300.0, 120.0):
        store.append(bench_record({"metric": "m", "unit": "s",
                                   "wall_s": wall}, ts=wall))
    key = store.load()[0]["key"]
    assert store.baseline(key) == {"wall_s": 120.0}   # median, not mean
    assert store.baseline("bench:nope") is None


def test_ingest_recorded_bench_artifacts():
    """The committed BENCH_r0*.json drivers are ingestible as seed
    history; a failed round (``"parsed": null``) yields no record."""
    assert len(BENCH_FILES) >= 5
    by_file = {os.path.basename(f): ingest_file(f) for f in BENCH_FILES}
    r04 = by_file["BENCH_r04.json"]
    assert r04 == []                        # parsed: null — no record
    total = [r for recs in by_file.values() for r in recs]
    assert len(total) == len(BENCH_FILES) - 1
    # rounds pinning the same metric share a key (comparable runs)
    keys = {}
    for rec in total:
        keys.setdefault(rec["key"], []).append(rec)
    assert any(len(v) >= 3 for v in keys.values())


def test_append_bench_gate(tmp_path, monkeypatch):
    monkeypatch.delenv("RAFT_TLA_HISTORY", raising=False)
    assert history_path(None) is None
    parsed = {"metric": "m", "unit": "s", "wall_s": 1.0}
    assert append_bench(parsed) is None               # gate off: no-op
    hist = str(tmp_path / "h.jsonl")
    monkeypatch.setenv("RAFT_TLA_HISTORY", hist)
    assert history_path(None) == hist
    assert append_bench(parsed, meta={"source": "test"}) == hist
    recs = HistoryStore(hist).load()
    assert len(recs) == 1 and recs[0]["meta"]["source"] == "test"


# --------------------------------------------------------------------------
# the CLI (in-process via main(argv) — the CI exit-code contract)


@pytest.fixture
def seeded_history(tmp_path):
    hist = str(tmp_path / "h.jsonl")
    assert main(["ingest", *BENCH_FILES, "--history", hist]) == EXIT_OK
    return hist


def test_regress_check_clean_rerun(seeded_history, capsys):
    """Same-config re-run against its own seed history: within
    tolerance (the ISSUE 20 acceptance's clean pass)."""
    r05 = os.path.join(REPO, "BENCH_r05.json")
    assert main(["check", r05, "--history", seeded_history]) == EXIT_OK
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert verdict["verdict"] == "ok" and verdict["drifted"] == []
    assert verdict["worst"][1] < 1.5


def test_regress_check_planted_drift(seeded_history, tmp_path, capsys):
    """A 10x wall regression against the median baseline must verdict
    drift with the CI exit code."""
    with open(os.path.join(REPO, "BENCH_r05.json")) as fh:
        doc = json.load(fh)
    for k, v in list(doc["parsed"].items()):
        if isinstance(v, (int, float)) and not isinstance(v, bool) \
                and ("wall" in k or "_ms" in k):
            doc["parsed"][k] = v * 10.0
    bad = str(tmp_path / "slow.json")
    with open(bad, "w") as fh:
        json.dump(doc, fh)
    assert main(["check", bad, "--history", seeded_history]) == EXIT_DRIFT
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert verdict["verdict"] == "drift" and verdict["drifted"]
    assert verdict["worst"][1] > 5.0


def test_regress_check_no_baseline(tmp_path, capsys):
    hist = str(tmp_path / "empty.jsonl")
    open(hist, "w").close()
    r05 = os.path.join(REPO, "BENCH_r05.json")
    assert main(["check", r05, "--history", hist]) == EXIT_NO_BASELINE
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert verdict["verdict"] == "no-baseline"


def test_regress_usage_without_history(monkeypatch, capsys):
    monkeypatch.delenv("RAFT_TLA_HISTORY", raising=False)
    r05 = os.path.join(REPO, "BENCH_r05.json")
    assert main(["check", r05]) == EXIT_USAGE
    assert main(["ingest", r05]) == EXIT_USAGE
    capsys.readouterr()


def test_regress_ab_reproduces_devdedup_refutation(capsys):
    """The recorded devdedup A/B (RESULTS.md: warm rate 0.44x on the
    full universe — gate REFUTED) must verdict drift mechanically."""
    out = os.path.join(REPO, "runs", "devdedup_ab.out")
    assert main(["ab", out]) == EXIT_DRIFT
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert verdict["verdict"] == "drift"
    drifted = set(verdict["drifted"])
    assert any("on_vs_off_warm_rate" in k for k in drifted)
    rates = {k: v for k, v in verdict["keys"].items()
             if "full.on_vs_off_warm_rate" in k}
    assert rates and all(abs(v["ratio"] - 0.444) < 0.01
                         for v in rates.values())


def test_regress_ab_clean_on_obs_overhead(capsys):
    """The recorded obs-overhead A/B stays within the gate (the
    events arm costs ~2%)."""
    out = os.path.join(REPO, "runs", "bench_obs_ab.out")
    assert main(["ab", out]) == EXIT_OK
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert verdict["verdict"] == "ok"
    assert verdict["keys"]["events_over_off"]["ratio"] < 1.1
