"""The speclint analyzer on the real tree: all three passes, both modes.

Tier-1: everything here is static analysis plus one host Init
evaluation — no model checking, no jit compiles — so the whole file
runs in seconds.  The flagship cfg must lint CLEAN in both parity and
faithful modes (the PR's acceptance bar); the diagnostic cases prove
each Pass 2/3 rule actually fires.  Deliberate kernel-level mutations
live in test_lint_mutations.py.
"""

import subprocess
import sys

import pytest

from raft_tla_tpu.analysis import cfglint, intervals as iv, jitlint, report
from raft_tla_tpu.analysis import widthcheck as wc
from raft_tla_tpu.config import Bounds
from raft_tla_tpu.utils import cfgparse

FLAGSHIP = "runs/MC3s2v.cfg"

MODES = [pytest.param(False, id="parity"), pytest.param(True, id="faithful")]


# -- Pass 1: width safety -----------------------------------------------------

@pytest.mark.parametrize("history", MODES)
@pytest.mark.parametrize("spec", ["full", "election", "replication"])
def test_width_proof_clean(history, spec):
    """The shipped kernels/tables/envelopes prove width-safe."""
    assert wc.check_widths(Bounds(history=history), spec) == []


@pytest.mark.parametrize("history", MODES)
def test_width_proof_clean_other_bounds(history):
    for b in (Bounds(n_servers=5, max_log=2, history=history),
              Bounds(n_servers=2, n_values=1, max_term=2, max_log=1,
                     history=history)):
        assert wc.check_widths(b) == []


def test_width_proof_clean_degenerate_log():
    """max_log=0 makes the AE entry-carry and conflict branches
    infeasible; the transfers must skip them, not crash on an empty
    meet (regression: check.py runs this pass by default on CLI runs
    with tiny bounds)."""
    b = Bounds(n_servers=2, n_values=1, max_term=2, max_log=0, max_msgs=2)
    assert wc.check_widths(b) == []
    assert wc.check_widths(b, "election") == []


def test_message_envelope_is_inductive():
    """Fixpoint sanity: every subfield interval fits its packed slot and
    AEResp.b (the relational a+c echo) stays within log_cap."""
    from raft_tla_tpu.models import spec as SP
    from raft_tla_tpu.ops import msgbits as mb
    b = Bounds()
    menv = wc.message_envelope(b, iv.expansion_envelope(b), wc.TRANSFERS)
    assert set(menv) == {SP.M_RVREQ, SP.M_RVRESP, SP.M_AEREQ, SP.M_AERESP}
    tables = dict(mb.HI_FIELDS)
    tables.update(mb.LO_FIELDS)
    for mt, fields in menv.items():
        for name, interval in fields.items():
            if "+" in name or (name == "g" and not b.history):
                continue
            _sh, w = tables[name]
            assert interval.fits_bits(w), (mt, name, interval)
    assert menv[SP.M_AERESP]["b"].hi <= b.log_cap


def test_interval_algebra():
    a, b = iv.Interval(1, 3), iv.Interval(2, 5)
    assert (a + b).as_tuple() == (3, 8)
    assert (b - 1).as_tuple() == (1, 4)
    assert a.join(b).as_tuple() == (1, 5)
    assert a.meet(b).as_tuple() == (2, 3)
    assert a.min_(b).as_tuple() == (1, 3)
    assert a.max_(b).as_tuple() == (2, 5)
    assert iv.Interval(0, 5).or_(iv.Interval(0, 2)).as_tuple() == (0, 7)
    assert iv.Interval(0, 7).fits_bits(3)
    assert not iv.Interval(0, 8).fits_bits(3)
    with pytest.raises(ValueError):
        iv.Interval(3, 1)
    with pytest.raises(ValueError):
        a.meet(iv.Interval(7, 9))


# -- Pass 2: cfg lint ---------------------------------------------------------

@pytest.mark.parametrize("history", MODES)
def test_flagship_cfg_lints_clean(history):
    cfg = cfgparse.load_cfg(FLAGSHIP)
    assert cfglint.lint_cfg(cfg, Bounds(history=history),
                            path=FLAGSHIP) == []


def _lint(text, bounds=None, **kw):
    return cfglint.lint_cfg(cfgparse.parse_cfg(text), bounds or Bounds(),
                            path="t.cfg", **kw)


BASE = "CONSTANTS\n Server = {s1, s2, s3}\n Value = {v1, v2}\n"


def test_unknown_invariant_with_suggestion():
    fs = _lint("INVARIANT NoTwoLeders\n" + BASE)
    [f] = fs
    assert f.code == "unknown-invariant" and f.severity == report.ERROR
    assert "NoTwoLeaders" in f.message          # did-you-mean
    assert f.line == 1


def test_unknown_property_symmetry_view():
    fs = _lint("PROPERTY EventualyLeader\nSYMMETRY Serv\nVIEW Nope\n" + BASE)
    codes = {f.code for f in fs}
    assert {"unknown-property", "unknown-symmetry", "unknown-view"} <= codes
    assert all(f.severity == report.ERROR for f in fs)


def test_constant_diagnostics():
    fs = _lint("INVARIANT NoTwoLeaders\nCONSTANTS\n Value = {v1}\n")
    assert any(f.code == "constant-missing" and f.field == "Server"
               for f in fs)
    fs = _lint(BASE + "CONSTANTS\n MaxTerm = 9\n")
    [f] = [f for f in fs if f.code == "constant-bounds-mismatch"]
    assert f.severity == report.WARNING and "9" in f.message
    fs = _lint(BASE, Bounds(n_servers=4))
    assert any(f.code == "constant-bounds-mismatch" and f.field == "Server"
               for f in fs)


def test_history_invariant_in_parity_is_error():
    fs = _lint("INVARIANT ElectionSafetyHist\n" + BASE)
    [f] = [f for f in fs if f.code == "invariant-needs-history"]
    assert f.severity == report.ERROR and "--faithful" in f.message
    hist = cfglint.lint_cfg(
        cfgparse.parse_cfg("INVARIANT ElectionSafetyHist\n" + BASE),
        Bounds(history=True), path="t.cfg")
    assert [f for f in hist if f.code == "invariant-needs-history"] == []


def test_vacuous_invariant_under_subspec():
    """LogMatching under the election subset: no transition can touch the
    log (Receive carries no AppendEntries records there), so the
    reachability-refined write-sets expose the vacuity."""
    fs = _lint("INVARIANT LogMatching\n" + BASE, spec="election")
    [f] = [f for f in fs if f.code == "invariant-vacuous"]
    assert f.severity == report.WARNING and f.field == "LogMatching"
    # ...and under the full spec it is NOT vacuous.
    assert [f for f in _lint("INVARIANT LogMatching\n" + BASE)
            if f.code == "invariant-vacuous"] == []


def test_invariant_under_view_warns(monkeypatch):
    from raft_tla_tpu.models import invariants as inv_mod
    monkeypatch.setitem(inv_mod.READS, "NaiveNoTwoLeaders",
                        ("role", "vResp"))
    fs = _lint("INVARIANT NaiveNoTwoLeaders\n" + BASE, view="deadvotes")
    [f] = [f for f in fs if f.code == "invariant-under-view"]
    assert f.severity == report.WARNING and "vResp" in f.message


def test_view_symmetry_incompatible(monkeypatch):
    from raft_tla_tpu.models import views as views_mod
    monkeypatch.setitem(views_mod.EQUIVARIANT_AXES, "deadvotes", ("Value",))
    fs = _lint("SYMMETRY Server\n" + BASE, view="deadvotes")
    [f] = [f for f in fs if f.code == "view-symmetry-incompatible"]
    assert f.severity == report.ERROR


# -- cfgparse diagnostics (satellite: loud line-numbered failures) -----------

def test_parse_errors_carry_line_numbers():
    with pytest.raises(ValueError, match=r"line 2.*NOT_A_STANZA"):
        cfgparse.parse_cfg("\\* a comment line\nNOT_A_STANZA foo\n")
    with pytest.raises(ValueError, match=r"line 2.*bad CONSTANTS"):
        cfgparse.parse_cfg("CONSTANTS\n no equals here\n")


def test_resolver_did_you_mean():
    cfg = cfgparse.parse_cfg("INVARIANT NoTwoLeders\n" + BASE)
    with pytest.raises(ValueError) as e:
        cfgparse.resolve_names(cfg.invariants, {"NoTwoLeaders"},
                               "invariant", cfg=cfg, path="x.cfg")
    msg = str(e.value)
    assert "x.cfg line 1" in msg and "NoTwoLeaders" in msg


def test_lines_recorded():
    cfg = cfgparse.load_cfg(FLAGSHIP)
    assert cfg.line_of("invariant", "NoTwoLeaders") is not None
    assert cfg.line_of("constant", "Server") is not None


# -- Pass 3: jit-hazard lint --------------------------------------------------

def test_jitlint_rules_fire():
    cases = {
        "traced-python-if": (
            "import jax.numpy as jnp\n"
            "def k(s, i):\n"
            "    if s['role'][i] == 1:\n"
            "        return jnp.ones(())\n"),
        "traced-scalar-cast": (
            "import jax.numpy as jnp\n"
            "def k(s, i):\n"
            "    return jnp.asarray(int(s[i]))\n"),
        "set-iteration": (
            "def build():\n"
            "    for f in {'a', 'b'}:\n"
            "        print(f)\n"),
        "narrow-astype": (
            "import jax.numpy as jnp\n"
            "def k(s):\n"
            "    return s.astype(jnp.int16)\n"),
    }
    for code, src in cases.items():
        fs = jitlint.lint_source(src, "case.py")
        assert [f.code for f in fs] == [code], code
        assert all(f.severity == report.WARNING for f in fs)


def test_jitlint_static_tests_not_flagged():
    clean = (
        "import jax.numpy as jnp\n"
        "def k(s, i, fields):\n"
        "    if s['x'].shape[0] > 2:\n"          # shape probe: static
        "        pass\n"
        "    if 'role' in fields:\n"             # membership: static
        "        pass\n"
        "    if len(s['x']) == 3:\n"             # len: static
        "        pass\n"
        "    return jnp.ones(())\n")
    assert jitlint.lint_source(clean, "clean.py") == []


def test_jitlint_waiver():
    src = ("import jax.numpy as jnp\n"
           "def k(s, i):\n"
           "    if s[i] == 1:   # lint: jit-ok\n"
           "        return jnp.ones(())\n")
    assert jitlint.lint_source(src, "w.py") == []


def test_jitlint_repo_is_clean():
    """The shipped kernel/engine sources carry no unwaived hazards —
    the RESULTS.md 'first full-repo lint' state, kept true."""
    assert jitlint.lint_paths() == []


def test_jitlint_default_targets_cover_whole_package():
    """DEFAULT_TARGETS is derived from a package walk, not a curated
    list — an independent os.walk must find nothing the lint misses, so
    a new module can never silently sit outside the scan set."""
    import os
    covered = set(jitlint.covered_files())
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(
        jitlint.__file__)))
    expected = set()
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        expected |= {os.path.join(dirpath, f) for f in filenames
                     if f.endswith(".py")}
    assert expected, "package walk found nothing — wrong root?"
    missed = expected - covered
    assert not missed, f"modules outside the jit lint: {sorted(missed)}"


# -- CLI ----------------------------------------------------------------------

def test_lint_cli_flagship_exits_zero():
    """Acceptance: `python -m raft_tla_tpu.lint runs/MC3s2v.cfg` exits 0
    with both modes proved."""
    proc = subprocess.run(
        [sys.executable, "-m", "raft_tla_tpu.lint", FLAGSHIP],
        capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 error(s)" in proc.stdout


def test_lint_cli_inprocess_modes():
    from raft_tla_tpu.lint import build_argparser, run_lint
    for extra in ([], ["--mode", "parity"], ["--mode", "faithful"],
                  ["--strict"]):
        args = build_argparser().parse_args([FLAGSHIP] + extra)
        findings, code = run_lint(args)
        assert findings == [] and code == 0, (extra, findings)


def test_lint_cli_skip_covers_all_five_passes():
    from raft_tla_tpu.lint import build_argparser, run_lint
    args = build_argparser().parse_args(
        [FLAGSHIP, "--skip", "width", "--skip", "cfg", "--skip", "jit",
         "--skip", "thread", "--skip", "contract"])
    findings, code = run_lint(args)
    assert findings == [] and code == 0


def test_lint_cli_bad_cfg_fails():
    from raft_tla_tpu.lint import build_argparser, run_lint
    args = build_argparser().parse_args(["/no/such/file.cfg"])
    findings, code = run_lint(args)
    assert code == 1 and findings[0].code == "cfg-unreadable"


def test_check_cli_runs_lint_by_default(monkeypatch, capsys):
    """check.py wiring: Pass 1 runs before any step build — warn-only by
    default, fatal under --lint strict, absent under --no-lint.  The
    engine run itself is stubbed out (this tests the wiring, not BFS)."""
    import types

    from raft_tla_tpu import check as check_mod
    planted = [report.Finding(
        report.WIDTH, report.ERROR, "width-overflow", "planted",
        transition="Timeout", field="term", interval=(1, 9), width=3)]
    monkeypatch.setattr(
        "raft_tla_tpu.analysis.widthcheck.check_widths",
        lambda bounds, spec: planted)
    monkeypatch.setattr(
        check_mod, "_run",
        lambda args, config: types.SimpleNamespace(
            n_states=1, diameter=0, n_transitions=0, coverage={},
            violation=None, complete=True))
    assert check_mod.main([FLAGSHIP]) == check_mod.EXIT_OK    # warn-only
    assert "width-overflow" in capsys.readouterr().err
    assert check_mod.main([FLAGSHIP, "--lint", "strict"]) == \
        check_mod.EXIT_ERROR
    capsys.readouterr()
    assert check_mod.main([FLAGSHIP, "--no-lint"]) == check_mod.EXIT_OK
    assert "width-overflow" not in capsys.readouterr().err


def test_check_cli_unknown_invariant_names_line(tmp_path, capsys):
    """The shared resolver: check.py reports the cfg line + did-you-mean."""
    from raft_tla_tpu import check as check_mod
    bad = tmp_path / "bad.cfg"
    bad.write_text("SPECIFICATION Spec\nINVARIANT NoTwoLeders\n"
                   "CONSTANTS\n Server = {s1, s2}\n Value = {v1}\n")
    rc = check_mod.main([str(bad), "--engine", "ref"])
    err = capsys.readouterr().err
    assert rc == check_mod.EXIT_ERROR
    assert "line 2" in err and "NoTwoLeaders" in err


def test_exit_code_policy():
    warn = report.Finding(report.JIT, report.WARNING, "x", "m")
    err = report.Finding(report.WIDTH, report.ERROR, "y", "m")
    assert report.exit_code([]) == 0
    assert report.exit_code([warn]) == 0
    assert report.exit_code([warn], strict=True) == 1
    assert report.exit_code([err]) == 1


def test_finding_format_carries_proof_fields():
    f = report.Finding(report.WIDTH, report.ERROR, "width-overflow", "boom",
                       transition="Timeout", field="term",
                       interval=(1, 9), width=3)
    txt = f.format()
    for part in ("Timeout", "term", "[1, 9]", "width=3"):
        assert part in txt
