"""Device-resident within-level fingerprint dedup (ops/devdedup.py).

The two-tier dedup's hot tier (ROADMAP item 5): an HBM-resident exact
set applied to segment output buffers before export, so within-level
duplicates never cross d2h.  Gates: hash-vs-sort backend equivalence
under adversarial streams (all-duplicate, all-unique, overflow-forcing
load factors), on/off BYTE-IDENTITY of discovery on the toy universe in
both retention modes (single-chip and the 4-device virtual mesh),
violation/deadlock trace identity, checkpoint resume across the gate in
both directions, and composition with the host-dedup and prefetch
gates.  The soundness invariant everywhere: a dropped lane is always an
exact duplicate of an earlier-streamed key — every lossy path (probe
overflow, capacity truncation, sentinel) widens the stream instead.
"""

import numpy as np
import pytest

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.ddd_engine import DDDCapacities, DDDEngine
from raft_tla_tpu.models import interp, refbfs
from raft_tla_tpu.ops import devdedup

# smoke tier: cross-section for mid-round changes (pytest -m smoke)
pytestmark = pytest.mark.smoke

CFG = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                max_log=0, max_msgs=2),
                  spec="election", invariants=("NoTwoLeaders",), chunk=32)
CAPS = DDDCapacities(block=256, table=1 << 14, flush=1 << 10, levels=64)


# -- backend unit gates -----------------------------------------------------

def _feed(backend, capacity, batches, oc=None):
    """Run a key-batch sequence through one backend; per-batch numpy
    (keep, idx, new_n, hits) plus the final set size."""
    import jax

    # jit like the engines do (_dedup_insert's probe loop is a
    # while_loop — it needs the traced path, not eager numpy)
    filt = jax.jit(devdedup.make_filter(backend))
    oc = oc or max(len(hi) for hi, _lo in batches)
    st = devdedup.init_set(capacity, backend)
    out = []
    for hi, lo in batches:
        n = len(hi)
        ph = np.zeros(oc, np.uint32)
        pl = np.zeros(oc, np.uint32)
        ph[:n], pl[:n] = hi, lo
        st, keep, idx, new_n, hits = filt(st, ph, pl, np.int32(n))
        out.append((np.asarray(keep), np.asarray(idx), int(new_n),
                    int(hits)))
    return out, st


def _batches(hi_lists):
    return [(np.asarray(h, np.uint32), np.asarray(h, np.uint32) ^ 0xABC)
            for h in hi_lists]


@pytest.mark.parametrize("stream", [
    [[1, 2, 3, 4, 5, 6, 7, 8]],                       # all unique
    [[9, 9, 9, 9, 9, 9, 9, 9]],                       # all duplicate
    [[1, 2, 1, 3, 2, 4, 1, 5]],                       # within-batch mix
    [[1, 2, 3, 4], [3, 4, 5, 6], [1, 6, 7, 7]],      # cross-batch mix
])
def test_backends_equivalent(stream):
    """With ample capacity the hash and sort backends make IDENTICAL
    keep decisions (the sort arm is the hash arm's parity oracle):
    exactly the first occurrence of each key this level survives, in
    stream order, and hits count the rest."""
    batches = _batches(stream)
    hout, _ = _feed("hash", 1 << 10, batches)
    sout, _ = _feed("sort", 1 << 10, batches)
    seen: set = set()
    for (hk, hi_, hn, hh), (sk, si, sn, sh), (bh, _bl) in zip(
            hout, sout, batches):
        n = len(bh)
        assert np.array_equal(hk[:n], sk[:n])
        assert (hn, hh) == (sn, sh)
        # oracle: keep iff first occurrence across the whole level
        expect = []
        for k in bh.tolist():
            expect.append(k not in seen)
            seen.add(k)
        assert hk[:n].tolist() == expect
        # compaction preserves stream order of the kept lanes
        kept_lanes = [i for i, e in enumerate(expect) if e]
        assert hi_[:hn].tolist() == kept_lanes
        assert si[:sn].tolist() == kept_lanes
        assert hn + hh == n                  # every lane accounted for


@pytest.mark.parametrize("backend", ["hash", "sort"])
def test_sentinel_always_streams(backend):
    """A genuine all-ones fingerprint aliases the empty-slot/padding
    key: it must stream every time (never dedup'd, never inserted) in
    BOTH backends — widening, not wrong answers."""
    s = 0xFFFFFFFF
    hi = np.asarray([s, 1, s, 1], np.uint32)
    lo = np.asarray([s, 1, s, 1], np.uint32)
    out, _ = _feed(backend, 1 << 6, [(hi, lo), (hi, lo)])
    # lane 3 is the only resolvable duplicate in batch 0; batch 1 keeps
    # only the sentinels (1 is now set-resident)
    assert out[0][0][:4].tolist() == [True, True, True, False]
    assert out[1][0][:4].tolist() == [True, False, True, False]


def test_hash_overflow_widens_not_drops():
    """Load factor > 1: a 32-slot table fed 64 unique keys must stream
    every unresolved lane (keep it) rather than drop it — and on a
    replay of the same keys, every DROPPED lane must be a key that
    streamed before (soundness), with kept + hits == n always."""
    keys = np.arange(1, 65, dtype=np.uint32)
    out, _ = _feed("hash", 32, _batches([keys.tolist(), keys.tolist()]))
    (k0, _i0, n0, h0), (k1, _i1, n1, h1) = out
    assert n0 == 64 and h0 == 0              # first sight: all stream
    assert n1 + h1 == 64                     # replay: all accounted
    assert h1 > 0                            # the table did hold SOME
    # soundness: a dropped lane in the replay is a key kept in pass 0
    dropped = keys[~k1[:64]]
    streamed_before = set(keys[k0[:64]].tolist())
    assert all(int(k) in streamed_before for k in dropped.tolist())


def test_sort_capacity_truncation_restreams():
    """Sort-set overflow keeps the smallest keys; overflowed keys simply
    re-stream on replay (hits bounded by capacity, never a drop of a
    first occurrence)."""
    keys = np.arange(1, 17, dtype=np.uint32)
    out, st = _feed("sort", 8, _batches([keys.tolist(), keys.tolist()]))
    (k0, _i0, n0, h0), (k1, _i1, n1, h1) = out
    assert n0 == 16 and h0 == 0              # first sight: all stream
    assert int(st.n) == 8                    # set clamped at capacity
    assert h1 == 8 and n1 == 8               # smallest 8 dedup'd
    # the dropped (dedup'd) keys are exactly the retained smallest 8
    assert sorted(keys[~k1[:16]].tolist()) == keys[:8].tolist()


# -- engine byte-identity ---------------------------------------------------

# Engine-level gates ride the slow tier (~17s of DDD toy run per cell —
# the 870s tier-1 box can't afford them every run); tier-1 keeps the
# pure-filter unit gates above, and tools/lint.sh smokes CLI-level
# on/off byte-identity on every lint.
@pytest.mark.slow
@pytest.mark.parametrize("backend,retention", [
    ("hash", "full"),
    ("hash", "frontier"),
    ("sort", "full"),
    ("sort", "frontier"),
])
def test_oracle_parity_both_backends_both_retentions(backend, retention,
                                                     monkeypatch):
    """The gate must not move a single byte of discovery: counts,
    levels, transition totals, and discovery-order coverage all match
    the oracle (and hence the gate-off run) in both retention modes."""
    monkeypatch.setenv("RAFT_TLA_DEVDEDUP", backend)
    ref = refbfs.check(CFG)
    caps = DDDCapacities(block=256, table=1 << 14, flush=1 << 10,
                         levels=64, retention=retention)
    got = DDDEngine(CFG, caps).check()
    assert got.n_states == ref.n_states == 3014
    assert got.diameter == ref.diameter == 17
    assert got.levels == ref.levels
    assert got.n_transitions == ref.n_transitions
    assert got.coverage == ref.coverage      # identical discovery order
    assert got.violation is None and got.complete


@pytest.mark.slow
def test_parity_under_forced_filter_eviction(monkeypatch):
    """Device dedup composes with the lossy filter's eviction churn: a
    128-slot filter re-sights constantly; the exact set drops only true
    within-level re-sights and the host absorbs the rest.  (slow: the
    churn multiplies segments ~8x over the other toy runs)"""
    monkeypatch.setenv("RAFT_TLA_DEVDEDUP", "hash")
    ref = refbfs.check(CFG)
    caps = DDDCapacities(block=256, table=1 << 7, flush=1 << 9, levels=64)
    got = DDDEngine(CFG, caps).check()
    assert got.n_states == ref.n_states
    assert got.levels == ref.levels
    assert got.n_transitions == ref.n_transitions
    assert got.coverage == ref.coverage


@pytest.mark.slow
@pytest.mark.parametrize("backend", ["hash", "sort"])
def test_violation_trace_identity(backend, monkeypatch):
    """The counterexample is part of the byte-identity contract: same
    violating state, same invariant, same replayable trace, same
    truncation-exact n_states with the gate on."""
    from raft_tla_tpu.models import spec as S
    from raft_tla_tpu.ops import msgbits as mb

    bounds = Bounds(n_servers=3, n_values=1, max_term=3, max_log=0,
                    max_msgs=4, max_dup=1)
    cfg = CheckConfig(bounds=bounds, spec="election",
                      invariants=("NaiveNoTwoLeaders",), chunk=64)
    start = interp.init_state(bounds)._replace(
        role=(S.LEADER, S.FOLLOWER, S.CANDIDATE),
        term=(2, 3, 3),
        votedFor=(1, 3, 0),
        vGrant=(0b011, 0, 0b100),
        msgs=tuple(sorted((m, 1) for m in
                          (mb.rv_response(3, 1, 1, 2),))),
    )
    caps = DDDCapacities(block=1 << 12, table=1 << 17, flush=1 << 12,
                         levels=64)
    monkeypatch.setenv("RAFT_TLA_DEVDEDUP", "off")
    off = DDDEngine(cfg, caps).check(init_override=start)
    monkeypatch.setenv("RAFT_TLA_DEVDEDUP", backend)
    on = DDDEngine(cfg, caps).check(init_override=start)
    assert off.violation is not None and on.violation is not None
    assert on.violation.invariant == off.violation.invariant
    assert on.violation.state == off.violation.state
    assert on.violation.trace == off.violation.trace
    assert on.n_states == off.n_states


@pytest.mark.slow
@pytest.mark.parametrize("backend", ["hash", "sort"])
def test_deadlock_identity(backend, monkeypatch):
    cfg = CheckConfig(bounds=Bounds(n_servers=1, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=(), chunk=16,
                      check_deadlock=True)
    caps = DDDCapacities(block=64, table=1 << 12, flush=1 << 8, levels=64)
    monkeypatch.setenv("RAFT_TLA_DEVDEDUP", "off")
    off = DDDEngine(cfg, caps).check()
    monkeypatch.setenv("RAFT_TLA_DEVDEDUP", backend)
    on = DDDEngine(cfg, caps).check()
    assert off.violation is not None and on.violation is not None
    assert on.violation.invariant == off.violation.invariant  # DEADLOCK
    assert on.violation.state == off.violation.state
    assert on.n_states == off.n_states


@pytest.mark.slow
def test_checkpoint_cross_gate(tmp_path, monkeypatch):
    """Checkpoints are gate-agnostic (the set is within-level and
    deliberately not part of the digest): written under either arm,
    resumable under the other, byte-identical finals both ways."""
    straight = DDDEngine(CFG, CAPS).check()
    for write, read in (("hash", "off"), ("off", "hash")):
        ck = str(tmp_path / f"ddd_dd_{write}_{read}.ckpt")
        monkeypatch.setenv("RAFT_TLA_DEVDEDUP", write)
        mid = DDDEngine(CFG, CAPS).check(checkpoint=ck,
                                         checkpoint_every_s=0.0)
        assert mid.n_states == straight.n_states
        monkeypatch.setenv("RAFT_TLA_DEVDEDUP", read)
        resumed = DDDEngine(CFG, CAPS).check(resume=ck)
        assert resumed.n_states == straight.n_states, (write, read)
        assert resumed.levels == straight.levels
        assert resumed.n_transitions == straight.n_transitions
        assert resumed.coverage == straight.coverage
        assert resumed.violation is None


@pytest.mark.slow
def test_composes_with_hostdedup_and_prefetch(monkeypatch):
    """All three gates at once — background host dedup, upload prefetch,
    device dedup — must still be byte-identical to the oracle."""
    monkeypatch.setenv("RAFT_TLA_HOSTDEDUP", "on")
    monkeypatch.setenv("RAFT_TLA_PREFETCH", "on")
    monkeypatch.setenv("RAFT_TLA_DEVDEDUP", "hash")
    ref = refbfs.check(CFG)
    got = DDDEngine(CFG, CAPS).check()
    assert got.n_states == ref.n_states == 3014
    assert got.levels == ref.levels
    assert got.n_transitions == ref.n_transitions
    assert got.coverage == ref.coverage
    assert got.violation is None and got.complete


@pytest.mark.slow
def test_observability_accounting(monkeypatch):
    """The schema-v9 counters close the books: with the gate on,
    export_rows + dev_dedup_hits equals the gate-off export_rows (every
    dropped row is a counted hit, nothing else moved)."""
    def run(mode):
        monkeypatch.setenv("RAFT_TLA_DEVDEDUP", mode)
        stats: list = []
        DDDEngine(CFG, CAPS).check(on_progress=stats.append)
        return stats

    off = run("off")
    on = run("hash")
    assert off and on and len(off) == len(on)
    assert [s["n_states"] for s in off] == [s["n_states"] for s in on]
    assert all("dev_dedup_hits" not in s for s in off)
    assert off[-1]["export_rows"] == (on[-1]["export_rows"]
                                      + on[-1]["dev_dedup_hits"])
    assert on[-1]["dev_dedup_hits"] > 0      # the toy HAS re-sights


# -- 4-device virtual mesh --------------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("backend", ["hash", "sort"])
def test_mesh_4dev_parity(backend, monkeypatch):
    """Per-shard sets under shard_map: totals, violation-free finals and
    coverage sums identical to the oracle on the 4-device virtual mesh,
    canonical (level, window, shard) drain order untouched."""
    from raft_tla_tpu.parallel.ddd_shard_engine import (
        DDDShardCapacities, DDDShardEngine)
    from raft_tla_tpu.parallel.shard_engine import make_mesh

    caps = DDDShardCapacities(block=256, table=1 << 14, seg_rows=1 << 14,
                              flush=1 << 10, levels=64)
    ref = refbfs.check(CFG)
    monkeypatch.setenv("RAFT_TLA_DEVDEDUP", "off")
    off = DDDShardEngine(CFG, make_mesh(4), caps).check()
    monkeypatch.setenv("RAFT_TLA_DEVDEDUP", backend)
    got = DDDShardEngine(CFG, make_mesh(4), caps).check()
    for r in (off, got):
        assert r.n_states == ref.n_states == 3014
        assert r.diameter == ref.diameter == 17
        assert r.levels == ref.levels
        assert r.n_transitions == ref.n_transitions
    assert got.coverage == off.coverage
    assert got.violation is None and got.complete


@pytest.mark.slow
def test_mesh_4dev_violation_identity(monkeypatch):
    """Shard-engine counterexample identity: the violator survives the
    per-shard filter (an equal earlier candidate would have violated
    first) and the remapped viol_pos still points at it."""
    from raft_tla_tpu.models import spec as S
    from raft_tla_tpu.ops import msgbits as mb
    from raft_tla_tpu.parallel.ddd_shard_engine import (
        DDDShardCapacities, DDDShardEngine)
    from raft_tla_tpu.parallel.shard_engine import make_mesh

    bounds = Bounds(n_servers=3, n_values=1, max_term=3, max_log=0,
                    max_msgs=4, max_dup=1)
    cfg = CheckConfig(bounds=bounds, spec="election",
                      invariants=("NaiveNoTwoLeaders",), chunk=64)
    start = interp.init_state(bounds)._replace(
        role=(S.LEADER, S.FOLLOWER, S.CANDIDATE),
        term=(2, 3, 3),
        votedFor=(1, 3, 0),
        vGrant=(0b011, 0, 0b100),
        msgs=tuple(sorted((m, 1) for m in
                          (mb.rv_response(3, 1, 1, 2),))),
    )
    caps = DDDShardCapacities(block=1 << 12, table=1 << 17,
                              seg_rows=1 << 14, flush=1 << 12, levels=64)
    monkeypatch.setenv("RAFT_TLA_DEVDEDUP", "off")
    off = DDDShardEngine(cfg, make_mesh(4), caps).check(
        init_override=start)
    monkeypatch.setenv("RAFT_TLA_DEVDEDUP", "hash")
    on = DDDShardEngine(cfg, make_mesh(4), caps).check(
        init_override=start)
    assert off.violation is not None and on.violation is not None
    assert on.violation.invariant == off.violation.invariant
    assert on.violation.state == off.violation.state
    assert on.violation.trace == off.violation.trace
    assert on.n_states == off.n_states
