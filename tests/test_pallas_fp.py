"""Pallas fingerprint kernel ≡ the NumPy reference, bit for bit.

Runs in Pallas interpret mode under the CPU test harness; the real-TPU
lowering is exercised by bench/ad-hoc runs (the deployment chip is shared,
so keep it out of the default suite).
"""

import numpy as np
import pytest

from raft_tla_tpu.ops import fingerprint as fpr
from raft_tla_tpu.ops import pallas_fp


@pytest.mark.parametrize("shape", [(256, 60), (300, 60), (1, 7), (512, 128)])
def test_bit_identical_to_numpy(shape):
    rng = np.random.default_rng(11)
    rows = rng.integers(-2**31, 2**31 - 1, size=shape, dtype=np.int32)
    hi_np, lo_np = fpr.fingerprint(rows, fpr.lane_constants(shape[1]), np)
    hi_pl, lo_pl = pallas_fp.fingerprint_rows(rows, interpret=True)
    np.testing.assert_array_equal(hi_np.astype(np.uint32), np.asarray(hi_pl))
    np.testing.assert_array_equal(lo_np.astype(np.uint32), np.asarray(lo_pl))


def test_padding_does_not_change_fingerprints():
    rng = np.random.default_rng(12)
    rows = rng.integers(0, 2**20, size=(100, 33), dtype=np.int32)
    hi_a, lo_a = pallas_fp.fingerprint_rows(rows, interpret=True)
    hi_b, lo_b = pallas_fp.fingerprint_rows(rows[:57], interpret=True)
    np.testing.assert_array_equal(np.asarray(hi_a)[:57], np.asarray(hi_b))
    np.testing.assert_array_equal(np.asarray(lo_a)[:57], np.asarray(lo_b))
