"""C++ host runtime ≡ NumPy twins (SURVEY §2.8 native components).

The fingerprint MUST be bit-identical across the np reference, the device
path, and the C++ path — sharding routes states by fingerprint, so a single
differing bit mis-routes a state and silently breaks dedup exactness.
"""

import numpy as np
import pytest

from raft_tla_tpu.ops import fingerprint as fpr
from raft_tla_tpu.utils import native


def test_native_toolchain_available():
    """The image bakes g++; the C++ path must actually be exercised here."""
    assert native.HAS_NATIVE


def test_fingerprint_bit_identical_cpp_vs_numpy():
    rng = np.random.default_rng(7)
    rows = rng.integers(-2**31, 2**31 - 1, size=(4096, 60), dtype=np.int32)
    hi_np, lo_np = fpr.fingerprint(rows, fpr.lane_constants(60), np)
    hi_cc, lo_cc = native.fingerprint_rows(rows)
    np.testing.assert_array_equal(hi_np.astype(np.uint32), hi_cc)
    np.testing.assert_array_equal(lo_np.astype(np.uint32), lo_cc)


def test_fingerprint_bit_identical_cpp_vs_device():
    import jax.numpy as jnp
    rng = np.random.default_rng(8)
    rows = rng.integers(0, 2**20, size=(512, 33), dtype=np.int32)
    consts = fpr.lane_constants(33)
    hi_d, lo_d = fpr.fingerprint(jnp.asarray(rows), jnp.asarray(consts), jnp)
    hi_cc, lo_cc = native.fingerprint_rows(rows)
    np.testing.assert_array_equal(np.asarray(hi_d), hi_cc)
    np.testing.assert_array_equal(np.asarray(lo_d), lo_cc)


@pytest.mark.parametrize("cls", [native.HostStore, native.PyHostStore])
def test_store_roundtrip(cls):
    if cls is native.HostStore and not native.HAS_NATIVE:
        pytest.skip("no toolchain")
    st = cls(width=7)
    rng = np.random.default_rng(9)
    all_rows = []
    for n in (1, 100, 70000, 3):        # spans the 65536-row block boundary
        rows = rng.integers(-1000, 1000, size=(n, 7), dtype=np.int32)
        all_rows.append(rows)
        st.append(rows)
    ref = np.concatenate(all_rows)
    assert len(st) == ref.shape[0]
    np.testing.assert_array_equal(st.read(0, len(st)), ref)
    np.testing.assert_array_equal(st.read(65530, 20), ref[65530:65550])
    with pytest.raises(IndexError):
        st.read(len(st) - 1, 2)
    st.close()


@pytest.mark.parametrize("cls", [native.HostStore, native.PyHostStore])
def test_links_and_trace_chain(cls):
    if cls is native.HostStore and not native.HAS_NATIVE:
        pytest.skip("no toolchain")
    st = cls(width=1)
    # a BFS-ish parent forest: row 0 is the root
    parent = np.asarray([-1, 0, 0, 1, 3, 4, 2], np.int32)
    lane = np.asarray([-1, 5, 6, 7, 8, 9, 10], np.int32)
    st.append_links(parent[:4], lane[:4])
    st.append_links(parent[4:], lane[4:])
    p, l = st.read_links(2, 3)
    np.testing.assert_array_equal(p, parent[2:5])
    np.testing.assert_array_equal(l, lane[2:5])
    np.testing.assert_array_equal(st.trace_chain(5), [0, 1, 3, 4, 5])
    np.testing.assert_array_equal(st.trace_chain(6), [0, 2, 6])
    np.testing.assert_array_equal(st.trace_chain(0), [0])
    st.close()


def test_cpp_store_matches_py_store_on_random_ops():
    if not native.HAS_NATIVE:
        pytest.skip("no toolchain")
    rng = np.random.default_rng(10)
    a, b = native.HostStore(5), native.PyHostStore(5)
    for _ in range(20):
        rows = rng.integers(-50, 50, size=(int(rng.integers(1, 500)), 5),
                            dtype=np.int32)
        a.append(rows)
        b.append(rows)
    assert len(a) == len(b)
    start = int(rng.integers(0, len(a) // 2))
    n = int(rng.integers(1, len(a) - start))
    np.testing.assert_array_equal(a.read(start, n), b.read(start, n))
    a.close()


@pytest.mark.parametrize("cls", [native.HostStore, native.PyHostStore])
def test_store_concurrent_append_and_disjoint_reads(cls):
    """The one-appender + disjoint-range-reader contract the upload
    prefetch rests on (utils/prefetch.py): a reader of rows below a
    previously observed ``len()`` must see exactly those rows while an
    appender thread keeps publishing past them — native (atomic block
    directory, release-published size) and fallback (snapshot reads)
    alike.  Block size is 65536 rows, so 3000-row appends cross block
    and chunk-internal boundaries repeatedly."""
    if cls is native.HostStore and not native.HAS_NATIVE:
        pytest.skip("no toolchain")
    import threading
    width, n_batches, rows_per = 6, 64, 3000
    rng = np.random.default_rng(11)
    batches = [rng.integers(-9, 9, size=(rows_per, width), dtype=np.int32)
               for _ in range(n_batches)]
    ref = np.concatenate(batches)
    st = cls(width=width)
    st.append(batches[0])
    published = threading.Event()
    errors = []

    def appender():
        try:
            for b in batches[1:]:
                st.append(b)
                published.set()
        except BaseException as e:     # noqa: BLE001 — surfaced below
            errors.append(e)
            published.set()

    t = threading.Thread(target=appender)
    t.start()
    try:
        reads = 0
        while t.is_alive() or reads < 50:
            hi = len(st)               # observe a published size...
            lo = max(0, hi - 2048)
            got = st.read(lo, hi - lo)  # ...then read only below it
            np.testing.assert_array_equal(got, ref[lo:hi])
            reads += 1
            if not t.is_alive() and reads >= 50:
                break
    finally:
        t.join()
    assert not errors, errors
    assert len(st) == ref.shape[0]
    np.testing.assert_array_equal(st.read(0, len(st)), ref)
    st.close()


def test_store_bounds_error_messages_native_fallback_parity():
    """read / read_links / trace_chain must fail with the SAME
    IndexError text on both backends — the engines and the prefetch
    layer treat these as one store type."""
    if not native.HAS_NATIVE:
        pytest.skip("no toolchain")
    stores = [native.HostStore(3), native.PyHostStore(3)]
    rows = np.arange(30, dtype=np.int32).reshape(10, 3)
    parent = np.asarray([-1, 0, 1], np.int32)
    lane = np.asarray([-1, 4, 5], np.int32)
    msgs = []
    for st in stores:
        st.append(rows)
        st.append_links(parent, lane)
        got = []
        for fn in (lambda: st.read(8, 5),
                   lambda: st.read_links(1, 9),
                   lambda: st.trace_chain(7)):
            with pytest.raises(IndexError) as ei:
                fn()
            got.append(str(ei.value))
        msgs.append(got)
        st.close()
    assert msgs[0] == msgs[1], msgs


def test_filestore_truncated_stream_diagnostic(tmp_path):
    """A stream file shorter than its committed header (torn copy,
    partial restore) must fail loudly with path + expected/got rows,
    not die inside a reshape."""
    import os
    path = str(tmp_path / "trunc.rows")
    st = native.FileStore(path, width=4)
    st.append(np.arange(400, dtype=np.int32).reshape(100, 4))
    st.sync()
    st.close()
    size = os.path.getsize(path)
    os.truncate(path, size - 10 * 4 * 4)     # drop the last 10 rows
    st = native.FileStore(path, width=4)
    np.testing.assert_array_equal(
        st.read(0, 90),
        np.arange(360, dtype=np.int32).reshape(90, 4))
    with pytest.raises(ValueError) as ei:
        st.read(0, 100)
    msg = str(ei.value)
    assert path in msg and "expected 100 rows" in msg and "got 90" in msg
    st.close()


def test_scc_csr_native_matches_python_fallback():
    """Both scc_csr implementations must induce the same partition
    (component ids may differ; membership must not) on random digraphs."""
    import numpy as np

    from raft_tla_tpu.utils import native

    rng = np.random.default_rng(3)
    for n, m in ((1, 0), (8, 12), (64, 200), (300, 1500)):
        src = rng.integers(0, n, m)
        dst = rng.integers(0, n, m).astype(np.int64)
        order = np.argsort(src, kind="stable")
        src, dst = src[order], dst[order]
        indptr = np.zeros(n + 1, np.int64)
        np.cumsum(np.bincount(src, minlength=n), out=indptr[1:])

        comp_n, nc_n = native.scc_csr(indptr, dst)
        # force the Python fallback
        saved = native.HAS_NATIVE
        native.HAS_NATIVE = False
        try:
            comp_p, nc_p = native.scc_csr(indptr, dst)
        finally:
            native.HAS_NATIVE = saved
        assert nc_n == nc_p
        # same partition: the id-of-id mapping must be a bijection
        pairs = {(int(a), int(b)) for a, b in zip(comp_n, comp_p)}
        assert len(pairs) == nc_n
        assert len({a for a, _ in pairs}) == nc_n
        assert len({b for _, b in pairs}) == nc_n
