"""frontend/predicate: the expression invariant compiler.

Tier-1: pure parsing plus host-side evaluation over tiny structs and
one Init state — no engine runs, no jit compiles beyond a single
un-jitted jnp evaluation, so the whole file runs in seconds.
"""

import numpy as np
import pytest

from raft_tla_tpu.analysis import cfglint
from raft_tla_tpu.config import Bounds
from raft_tla_tpu.frontend.predicate import (
    compile_predicate, is_expression, parse)
from raft_tla_tpu.models import interp
from raft_tla_tpu.models import invariants as inv_mod
from raft_tla_tpu.models import spec as S
from raft_tla_tpu.ops import state as st
from raft_tla_tpu.utils import cfgparse

TOY = Bounds(n_servers=2, n_values=1, max_term=2, max_log=1, max_msgs=2)


def _ev(text, struct=None, xp=np):
    return bool(compile_predicate(text).ev(
        {} if struct is None else struct, xp))


# -- precedence & associativity ----------------------------------------------

@pytest.mark.parametrize("text,want", [
    # => is right-associative: F => (F => F) = F => TRUE = TRUE;
    # the left-associative reading (F => F) => F would be FALSE.
    ("FALSE => FALSE => FALSE", True),
    # \/ binds tighter than =>: TRUE => (FALSE \/ FALSE) = FALSE.
    ("TRUE => FALSE \\/ FALSE", False),
    # /\ binds tighter than \/: TRUE \/ (FALSE /\ FALSE) = TRUE.
    ("TRUE \\/ FALSE /\\ FALSE", True),
    # ~ binds tighter than /\ but looser than comparisons:
    # (~FALSE) /\ TRUE, and ~(1 = 2).
    ("~FALSE /\\ TRUE", True),
    ("~1 = 2", True),
    # comparisons bind looser than +/-: (1 + 2) = (5 - 2).
    ("1 + 2 = 5 - 2", True),
    # * binds tighter than +: 1 + (2 * 3) = 7.
    ("1 + 2 * 3 = 7", True),
    # unary minus binds tighter than *: ((-2) * 3) = -(6).
    ("-2 * 3 = -6", True),
    ("2 - -1 = 3", True),
    # parentheses override: (1 + 2) * 3 = 9.
    ("(1 + 2) * 3 = 9", True),
])
def test_precedence(text, want):
    assert _ev(text) is want


def test_comparison_ops():
    for text, want in [("1 /= 2", True), ("2 <= 2", True), ("3 < 3", False),
                       ("3 >= 4", False), ("4 > 3", True), ("1 = 1", True)]:
        assert _ev(text) is want


# -- reducers and implicit universal quantification ---------------------------

def test_reducers():
    struct = {"x": np.array([0, 2, 3], dtype=np.int32)}
    assert _ev("any(x = 2)", struct)
    assert not _ev("all(x = 2)", struct)
    assert _ev("count(x > 0) = 2", struct)
    assert _ev("min(x) = 0 /\\ max(x) = 3", struct)


def test_implicit_forall():
    # A non-scalar boolean result is universally quantified at the top.
    struct = {"x": np.array([1, 1], dtype=np.int32)}
    assert _ev("x = 1", struct)
    struct = {"x": np.array([1, 2], dtype=np.int32)}
    assert not _ev("x = 1", struct)


def test_indexing():
    struct = {"x": np.array([4, 7], dtype=np.int32)}
    assert _ev("x[1] = 7 /\\ x[1 - 1] = 4", struct)


# -- dual backend -------------------------------------------------------------

def test_numpy_jnp_agree():
    import jax.numpy as jnp
    struct_np = {"x": np.array([0, 2, 3], dtype=np.int32),
                 "y": np.array([1, 1, 1], dtype=np.int32)}
    struct_jnp = {k: jnp.asarray(v) for k, v in struct_np.items()}
    for text in ("any(x = 2) => all(y = 1)", "count(x > 0) = 2",
                 "min(x) + max(x) = 3", "~all(x = y)",
                 "all(x <= 3) /\\ all(y >= 1)"):
        pred = compile_predicate(text)
        assert bool(pred.ev(struct_np, np)) == bool(pred.ev(struct_jnp, jnp))


# -- compile-time diagnostics -------------------------------------------------

def test_unknown_field_with_whitelist():
    with pytest.raises(ValueError, match="unknown field 'bogus'"):
        compile_predicate("bogus = 1", fields=("role", "term"))
    # without a whitelist any NAME is accepted (resolves at probe time)
    compile_predicate("bogus = 1")


def test_arithmetic_rejected_as_invariant():
    with pytest.raises(ValueError, match="arithmetic, not boolean"):
        compile_predicate("1 + 1")


def test_type_errors():
    with pytest.raises(ValueError, match="needs a boolean"):
        parse("~1")
    with pytest.raises(ValueError, match="needs an integer"):
        parse("TRUE + 1")
    with pytest.raises(ValueError, match="trailing input"):
        parse("1 = 1 1")
    with pytest.raises(ValueError, match="syntax error"):
        parse("1 = ")


def test_is_expression():
    assert not is_expression("NoTwoLeaders")
    assert not is_expression("  SomeName  ")
    assert is_expression("x = 1")
    assert is_expression("all(commitIndex <= logLen)")
    assert is_expression("~TRUE")


def test_reads():
    pred = compile_predicate("any(role = 2) => all(term <= commitIndex)")
    assert pred.reads == frozenset({"role", "term", "commitIndex"})


# -- width-boundary constants over the Raft schema ----------------------------

# (field, in-range bound at TOY, one-past-max probe) — both must agree
# through the py path (PyState -> to_vec -> unpack) and the jnp path.
_BOUNDARY = [
    ("role", "all(role <= 2)", "any(role > 2)"),
    ("term", "all(term <= 2)", "any(term > 2)"),
    ("votedFor", "all(votedFor <= 2)", "any(votedFor > 2)"),
    ("commitIndex", "all(commitIndex <= 1)", "any(commitIndex > 1)"),
    ("logLen", "all(logLen <= 1)", "any(logLen > 1)"),
]


@pytest.mark.parametrize("history",
                         [pytest.param(False, id="parity"),
                          pytest.param(True, id="faithful")])
@pytest.mark.parametrize("field,at_max,past_max", _BOUNDARY)
def test_width_boundary_both_encodings(history, field, at_max, past_max):
    import jax.numpy as jnp
    b = TOY if not history else Bounds(
        n_servers=2, n_values=1, max_term=2, max_log=1, max_msgs=2,
        history=True)
    init = interp.init_state(b)
    # py path: the registered-invariant probe shape
    assert inv_mod.py_invariant(at_max)(init, b) is True
    assert inv_mod.py_invariant(past_max)(init, b) is False
    # jnp path: the vmapped device probe shape
    lay = st.Layout.of(b)
    struct = st.unpack(jnp.asarray(interp.to_vec(init, b)), lay, jnp)
    assert bool(inv_mod.jnp_invariant(at_max, b)(struct)) is True
    assert bool(inv_mod.jnp_invariant(past_max, b)(struct)) is False


# -- cfg integration ----------------------------------------------------------

_CFG = """\
SPECIFICATION Spec
CONSTANT Server = {s1, s2}
CONSTANT Value = {v1}
INVARIANT
  NoTwoLeaders
  all(commitIndex <= logLen)
"""


def test_cfgparse_whole_line_expression():
    cfg = cfgparse.parse_cfg(_CFG)
    assert "NoTwoLeaders" in cfg.invariants
    assert "all(commitIndex <= logLen)" in cfg.invariants
    assert cfg.line_of("invariant", "all(commitIndex <= logLen)") == 6


def test_cfgparse_multi_name_line_stays_names():
    # stock-TLC style: several registry names sharing one line must NOT
    # be folded into one "expression" (the flagship cfg does this)
    cfg = cfgparse.parse_cfg(
        "INVARIANTS NoTwoLeaders LogMatching LeaderCompleteness\n")
    assert cfg.invariants == ["NoTwoLeaders", "LogMatching",
                              "LeaderCompleteness"]
    assert cfg.line_of("invariant", "LogMatching") == 1


def test_cfgparse_normalizes_whitespace():
    cfg = cfgparse.parse_cfg(
        "INVARIANT\n  all(  commitIndex   <= logLen )\n")
    assert cfg.invariants == ["all( commitIndex <= logLen )"]


def test_cfglint_expression_parse_error():
    cfg = cfgparse.parse_cfg("SPECIFICATION Spec\n"
                             "CONSTANT Server = {s1, s2}\n"
                             "CONSTANT Value = {v1}\n"
                             "INVARIANT\n  all(bogus = 1)\n")
    codes = [f.code for f in cfglint.lint_cfg(cfg, TOY)]
    assert "invariant-parse-error" in codes
    assert "unknown-invariant" not in codes


def test_cfglint_expression_vacuity():
    # Nothing in the election subset writes commitIndex or logLen, and
    # the predicate holds on Init — vacuous there, live under "full".
    cfg = cfgparse.parse_cfg(_CFG)
    election = cfglint.lint_cfg(cfg, TOY, spec="election")
    assert [(f.code, f.field) for f in election
            if f.code == "invariant-vacuous"] == \
        [("invariant-vacuous", "all(commitIndex <= logLen)")]
    full = cfglint.lint_cfg(cfg, TOY, spec="full")
    assert [f for f in full if f.code == "invariant-vacuous"] == []
