"""campaign/: preemption-tolerant supervision.

Tier-1: pure-logic units (health verdicts, log tailing, snapshot
integrity, quarantine/generation recovery, mesh fitting) plus the
single-chip reshard round-trip smoke on the 3014-state election toy.

Slow: the chaos integration — SIGKILL mid-level, SIGKILL on a level
boundary, a SIGINT/SIGKILL race, truncated-checkpoint quarantine with
generation restore, and a 1 -> 2 -> 1 mesh reshard — all required to
land on finals identical to an uninterrupted run, unattended.
"""

import json
import os

import numpy as np
import pytest

from raft_tla_tpu.campaign import (CampaignPolicy, CampaignSpec,
                                   CheckpointCorrupt, HealthMonitor,
                                   Supervisor, fit_mesh, snapshot_family,
                                   verify_snapshot)
from raft_tla_tpu.campaign.supervisor import _LogTail
from raft_tla_tpu.utils import ckpt

TOY_CFG = """
SPECIFICATION Spec
INVARIANT NoTwoLeaders
CONSTANTS
    Server = {s1, s2}
    Value = {v1}
    Follower = "Follower"
    Candidate = "Candidate"
    Leader = "Leader"
    Nil = "Nil"
    RequestVoteRequest = "RequestVoteRequest"
    RequestVoteResponse = "RequestVoteResponse"
    AppendEntriesRequest = "AppendEntriesRequest"
    AppendEntriesResponse = "AppendEntriesResponse"
"""
TOY_OPTIONS = {"max_term": 2, "max_log": 0, "max_msgs": 2}


@pytest.fixture
def toy_cfg(tmp_path):
    p = tmp_path / "toy.cfg"
    p.write_text(TOY_CFG)
    return str(p)


def toy_spec(cfg_path, **kw):
    kw.setdefault("window", 128)
    kw.setdefault("chunk", 32)
    kw.setdefault("cap", 1 << 14)
    kw.setdefault("levels", 64)
    return CampaignSpec(cfg_path=cfg_path, spec="election",
                        options=dict(TOY_OPTIONS), cpu=True, **kw)


# --------------------------------------------------------------------------
# integrity: structural snapshot verification


def make_family(tmp_path, n_states=20, P=4, name="snap"):
    """A synthetic full-retention family shaped like save_ddd_snapshot's."""
    path = str(tmp_path / name)
    streams = {".rows": P, ".links": 3, ".con": 1, ".keys": 2}
    for suf, w in streams.items():
        data = np.arange(n_states * w, dtype=np.int32).reshape(n_states, w)
        ckpt.stream_rows_out(path + suf,
                             lambda s, n, d=data: d[s:s + n], n_states, w)
    ckpt.atomic_savez(path, n_states=np.int64(n_states),
                      n_trans=np.uint64(3 * n_states),
                      cov=np.zeros(4, np.int64),
                      level_ends=np.asarray([8, n_states], np.int64),
                      blocks_done=np.int64(0),
                      config_digest=np.uint64(7))
    return path


def test_verify_snapshot_ok(tmp_path):
    path = make_family(tmp_path)
    info = verify_snapshot(path)
    assert info["n_states"] == 20
    assert info["levels"] == 2
    assert info["retention"] == "full"
    info = verify_snapshot(path, row_width=4)    # pinned width also OK
    assert info["n_states"] == 20


def test_verify_snapshot_catches_truncated_stream(tmp_path):
    path = make_family(tmp_path)
    size = os.path.getsize(path + ".rows")
    with open(path + ".rows", "r+b") as f:
        f.truncate(size // 2)
    with pytest.raises(CheckpointCorrupt, match="truncated|torn"):
        verify_snapshot(path)


def test_verify_snapshot_catches_missing_member(tmp_path):
    path = make_family(tmp_path)
    os.remove(path + ".keys")
    with pytest.raises(CheckpointCorrupt, match="missing"):
        verify_snapshot(path)


def test_verify_snapshot_catches_row_deficit(tmp_path):
    path = make_family(tmp_path, n_states=20)
    # metadata claims more states than the streams hold: torn snapshot
    ckpt.atomic_savez(path, n_states=np.int64(25),
                      n_trans=np.uint64(60), cov=np.zeros(4, np.int64),
                      level_ends=np.asarray([8, 25], np.int64),
                      blocks_done=np.int64(0), config_digest=np.uint64(7))
    with pytest.raises(CheckpointCorrupt, match="holds 20 rows"):
        verify_snapshot(path)


def test_verify_snapshot_catches_torn_npz(tmp_path):
    path = make_family(tmp_path)
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) // 2)
    with pytest.raises(CheckpointCorrupt, match="npz"):
        verify_snapshot(path)


def test_verify_snapshot_absent_is_not_corrupt(tmp_path):
    with pytest.raises(FileNotFoundError):
        verify_snapshot(str(tmp_path / "nope"))


def test_verify_snapshot_wrong_width_rejected(tmp_path):
    path = make_family(tmp_path, P=4)
    with pytest.raises(CheckpointCorrupt, match="width"):
        verify_snapshot(path, row_width=6)


def test_snapshot_family_lists_members_skips_tmp(tmp_path):
    path = make_family(tmp_path)
    (tmp_path / "snap.rows.tmp").write_bytes(b"torn")
    fam = snapshot_family(path)
    assert path in fam
    assert path + ".rows" in fam and path + ".keys" in fam
    assert len(fam) == 5
    assert not any(p.endswith(".tmp") for p in fam)


# --------------------------------------------------------------------------
# health monitoring


def test_health_stale_after_explicit_threshold():
    clk = [1000.0]
    hm = HealthMonitor(CampaignPolicy(stale_after_s=10.0),
                       clock=lambda: clk[0])
    hm.spawned_at = 1000.0
    hm.observe([{"event": "segment", "ts": 1000.0}])
    clk[0] = 1009.0
    assert hm.verdict() is None
    clk[0] = 1011.0
    reason, detail = hm.verdict()
    assert reason == "heartbeat-stale"
    assert "11s" in detail


def test_health_stale_threshold_from_cadence():
    clk = [0.0]
    hm = HealthMonitor(CampaignPolicy(), clock=lambda: clk[0])
    hm.spawned_at = 0.0
    # 5s segment cadence -> threshold 10x = 50s (within [30s, 1h])
    hm.observe([{"event": "segment", "ts": float(t)}
                for t in range(0, 30, 5)])
    assert hm.stale_threshold() == pytest.approx(50.0)
    clk[0] = 25.0 + 49.0
    assert hm.verdict() is None
    clk[0] = 25.0 + 51.0
    assert hm.verdict()[0] == "heartbeat-stale"
    # no cadence data at all: flat 300s default, anchored on spawn time
    hm2 = HealthMonitor(CampaignPolicy(), clock=lambda: clk[0])
    hm2.spawned_at = 0.0
    assert hm2.stale_threshold() == 300.0
    clk[0] = 301.0
    assert hm2.verdict()[0] == "heartbeat-stale"


def test_health_session_wall():
    clk = [0.0]
    hm = HealthMonitor(CampaignPolicy(session_wall_s=60.0),
                       clock=lambda: clk[0])
    hm.spawned_at = 0.0
    hm.observe([{"event": "segment", "ts": 0.0}])
    clk[0] = 59.0
    assert hm.verdict() is None
    clk[0] = 61.0
    assert hm.verdict()[0] == "session-wall"


def test_health_fiducial_drift():
    clk = [10.0]
    hm = HealthMonitor(CampaignPolicy(drift_max=1.5),
                       clock=lambda: clk[0],
                       fiducial_baseline={"synthetic_step_ms": 2.0})
    hm.spawned_at = 10.0
    hm.observe([{"event": "run_start", "ts": 10.0,
                 "fiducials": {"synthetic_step_ms": 3.5}}])
    reason, detail = hm.verdict()
    assert reason == "fiducial-drift"
    assert "1.75x" in detail
    # within threshold: healthy
    hm2 = HealthMonitor(CampaignPolicy(drift_max=2.0),
                        clock=lambda: clk[0],
                        fiducial_baseline={"synthetic_step_ms": 2.0})
    hm2.spawned_at = 10.0
    hm2.observe([{"event": "run_start", "ts": 10.0,
                  "fiducials": {"synthetic_step_ms": 3.5}}])
    assert hm2.verdict() is None


def test_logtail_incremental_and_partial_lines(tmp_path):
    p = str(tmp_path / "log")
    tail = _LogTail(p)
    assert tail.poll() == []             # no file yet
    with open(p, "w") as f:
        f.write('{"event": "a"}\n{"event": "b"')
        f.flush()
        assert [e["event"] for e in tail.poll()] == ["a"]
        f.write('}\n')
        f.flush()
    assert [e["event"] for e in tail.poll()] == ["b"]
    with open(p, "a") as f:
        f.write('not json\n{"event": "c"}\n')
    assert [e["event"] for e in tail.poll()] == ["c"]  # torn line skipped
    assert tail.poll() == []


def test_fit_mesh():
    assert fit_mesh(8, 128, 32) == 4     # 128/8 = 16 < chunk
    assert fit_mesh(4, 128, 32) == 4
    assert fit_mesh(3, 128, 32) == 2     # 3 does not divide 128
    assert fit_mesh(1, 128, 32) == 1
    assert fit_mesh(0, 128, 32) == 1


def test_classify_exit():
    end = {"event": "run_end", "outcome": "ok", "n_states": 5,
           "n_transitions": 9}
    assert Supervisor._classify(0, [end]) == ("ok", end)
    assert Supervisor._classify(12, [end]) == ("violation", end)
    assert Supervisor._classify(11, []) == ("deadlock", None)
    # exit 0 with no run_end in the log: not a verdict — recoverable
    assert Supervisor._classify(0, []) == (None, None)
    assert Supervisor._classify(14, [end]) == (None, end)   # stopped
    assert Supervisor._classify(-9, []) == (None, None)     # SIGKILL


# --------------------------------------------------------------------------
# supervisor recovery mechanics (no child processes)


def make_sup(tmp_path, cfg_path=None, **kw):
    spec = toy_spec(cfg_path or str(tmp_path / "unused.cfg"))
    return Supervisor(spec, str(tmp_path / "camp"), quiet=True, **kw)


def make_family_at(path, n_states=20):
    import pathlib
    return make_family(pathlib.Path(os.path.dirname(path)),
                       n_states=n_states, name=os.path.basename(path))


def test_backoff_schedule(tmp_path):
    """Decorrelated jitter: every positive-k delay is drawn from
    [base, min(cap, 3*prev)], k=0 resets the window, and a fixed seed
    pins the exact sequence (reproducible anti-thundering-herd)."""
    policy = CampaignPolicy(backoff_jitter_seed=7)
    sup = make_sup(tmp_path, policy=policy)
    assert sup._backoff(0) == 0.0
    seq = [sup._backoff(k) for k in (1, 2, 3, 4, 5)]
    prev = policy.backoff_base_s
    for d in seq:
        assert policy.backoff_base_s <= d <= policy.backoff_cap_s
        assert d <= max(policy.backoff_base_s, 3.0 * prev) + 1e-9
        prev = d
    # seedable: a sibling supervisor with the same seed replays the
    # exact sequence; k=0 resets the window but not the RNG stream
    sup2 = make_sup(tmp_path, policy=policy)
    assert [sup2._backoff(k) for k in (1, 2, 3, 4, 5)] == seq
    assert sup._backoff(0) == 0.0
    d = sup._backoff(1)
    assert d <= 3.0 * policy.backoff_base_s
    # the value the resume_attempt event reports is the drawn delay
    assert sup._last_backoff_s == d
    # different seeds: decorrelated sequences (the anti-herd property)
    sup3 = make_sup(tmp_path, policy=CampaignPolicy(backoff_jitter_seed=8))
    assert [sup3._backoff(k) for k in (1, 2, 3, 4, 5)] != seq


def test_backoff_jitter_pinned_sequence(tmp_path):
    """The exact delays under seed 42 — pinned so a refactor that
    silently changes the draw order (or de-seeds the RNG) fails loud."""
    from raft_tla_tpu.campaign.supervisor import DecorrelatedBackoff
    bo = DecorrelatedBackoff(0.5, 30.0, seed=42)
    seq = [round(bo.next(), 6) for _ in range(4)]
    bo2 = DecorrelatedBackoff(0.5, 30.0, seed=42)
    assert [round(bo2.next(), 6) for _ in range(4)] == seq
    import random
    rng = random.Random(42)
    prev, expect = 0.5, []
    for _ in range(4):
        prev = min(30.0, rng.uniform(0.5, prev * 3.0))
        expect.append(round(prev, 6))
    assert seq == expect
    bo2.reset()
    assert bo2.next() <= 1.5             # window re-anchored at base


def test_verify_or_recover_saves_generation(tmp_path):
    sup = make_sup(tmp_path)
    sup._save_state(ndev=1)
    make_family_at(sup.ckpt, n_states=20)
    assert sup._verify_or_recover(0) is True
    gens = sup._generations()
    assert len(gens) == 1
    meta = json.load(open(os.path.join(gens[0], "meta.json")))
    assert meta == {"n_states": 20, "ndev": 1}
    # verified again with no progress: deduped, still one generation
    assert sup._verify_or_recover(1) is True
    assert len(sup._generations()) == 1


def test_corrupt_family_quarantined_and_generation_restored(tmp_path):
    sup = make_sup(tmp_path)
    sup._save_state(ndev=1)
    make_family_at(sup.ckpt, n_states=20)
    assert sup._verify_or_recover(0) is True           # generation saved
    corrupt_member = sup.ckpt + ".rows"
    with open(corrupt_member, "r+b") as f:
        f.truncate(24)
    assert sup._verify_or_recover(1) is True           # restored from gen
    assert verify_snapshot(sup.ckpt)["n_states"] == 20
    # poison guarantee: the corrupt bytes were MOVED to quarantine,
    # never to be resumed again
    assert len(sup.quarantined) == 1
    qdir, reason = sup.quarantined[0]
    assert "torn" in reason or "truncated" in reason
    assert os.path.getsize(os.path.join(
        qdir, os.path.basename(corrupt_member))) == 24
    assert open(os.path.join(qdir, "reason.txt")).read().strip() == reason


def test_corrupt_family_without_generations_restarts_fresh(tmp_path):
    sup = make_sup(tmp_path)
    sup._save_state(ndev=1)
    make_family_at(sup.ckpt, n_states=20)
    with open(sup.ckpt, "r+b") as f:                   # torn npz
        f.truncate(os.path.getsize(sup.ckpt) // 2)
    assert sup._verify_or_recover(0) is False
    assert len(sup.quarantined) == 1
    # run() deletes any leftover family on a fresh start; here the
    # quarantine move already took every member
    assert snapshot_family(sup.ckpt) == []


def test_quarantine_names_are_unique(tmp_path):
    sup = make_sup(tmp_path)
    for k in range(2):
        make_family_at(sup.ckpt, n_states=10 + k)
        with open(sup.ckpt, "r+b") as f:
            f.truncate(10)
        assert sup._verify_or_recover(k) is False
    qdirs = {q for q, _ in sup.quarantined}
    assert len(qdirs) == 2


def test_child_argv_shapes(tmp_path, toy_cfg):
    sup = Supervisor(toy_spec(toy_cfg), str(tmp_path / "c"), quiet=True,
                     policy=CampaignPolicy(session_wall_s=99.0))
    argv1 = sup._child_argv(ndev=1, resume=False)
    assert "--engine" in argv1 and argv1[argv1.index("--engine") + 1] == "ddd"
    assert argv1[argv1.index("--block") + 1] == "128"
    assert argv1[argv1.index("--deadline") + 1] == "99.0"
    assert "--resume" not in argv1
    assert "--max-term" in argv1         # options forwarded
    argv4 = sup._child_argv(ndev=4, resume=True)
    assert argv4[argv4.index("--engine") + 1] == "ddd-shard"
    assert argv4[argv4.index("--devices") + 1] == "4"
    assert argv4[argv4.index("--block") + 1] == "32"   # W/ndev
    assert "--deadline" not in argv4     # ddd-only flag
    assert argv4[argv4.index("--resume") + 1] == sup.ckpt


def test_supervisor_rejects_bad_campaign_at_admission(tmp_path):
    cfg = tmp_path / "bad.cfg"
    cfg.write_text(TOY_CFG.replace("NoTwoLeaders", "NoSuchInvariant"))
    sup = Supervisor(toy_spec(str(cfg)), str(tmp_path / "camp"),
                     quiet=True)
    res = sup.run()
    assert res.outcome == "rejected"
    assert res.exit_code == 1
    assert res.attempts == 0
    assert "NoSuchInvariant" in res.detail


def test_window_must_be_chunk_aligned(tmp_path, toy_cfg):
    with pytest.raises(ValueError, match="chunk"):
        Supervisor(toy_spec(toy_cfg, window=100), str(tmp_path / "c"))


# --------------------------------------------------------------------------
# reshard round-trip smoke (single chip, pure numpy resharder)


def test_ddd_reshard_round_trip_toy(tmp_path):
    """1 -> 2 -> 1 on a real mid-run snapshot of the 3014-state toy:
    streams byte-identical after the round trip, and the round-tripped
    family resumes to oracle-exact totals on the single-chip engine."""
    from raft_tla_tpu.config import Bounds, CheckConfig
    from raft_tla_tpu.ddd_engine import DDDCapacities, DDDEngine
    from raft_tla_tpu.models import refbfs
    from raft_tla_tpu.parallel.ddd_shard_engine import (
        DDDShardCapacities, reshard_ddd_checkpoint)

    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=("NoTwoLeaders",),
                      chunk=32)
    caps = DDDCapacities(block=128, table=1 << 12, seg_rows=1 << 13,
                         levels=64)
    ck = str(tmp_path / "camp.ckpt")
    # deadline_s=0: lossless stop at the first boundary -> mid-run family
    res = DDDEngine(cfg, caps).check(checkpoint=ck,
                                     checkpoint_every_s=0.0,
                                     deadline_s=0.0)
    assert not res.complete
    info = verify_snapshot(ck)
    assert 0 < info["n_states"] < 3014

    def family_bytes(root):
        return {p[len(root):]: open(p, "rb").read()
                for p in snapshot_family(root) if p != root}

    before = family_bytes(ck)
    c1 = DDDShardCapacities(block=128, levels=64)
    c2 = DDDShardCapacities(block=64, levels=64)
    mid = str(tmp_path / "mid.ckpt")
    back = str(tmp_path / "back.ckpt")
    out = reshard_ddd_checkpoint(cfg, c1, ck, mid, 1, 2, c2)
    assert out["ndev_src"] == 1 and out["ndev_dst"] == 2
    reshard_ddd_checkpoint(cfg, c2, mid, back, 2, 1, c1)
    assert family_bytes(back) == before  # history is mesh-invariant
    with np.load(ck) as a, np.load(back) as b:
        assert set(a.files) == set(b.files)
        for k in a.files:
            assert np.array_equal(a[k], b[k]), k

    ref = refbfs.check(cfg)
    got = DDDEngine(cfg, caps).check(resume=back)
    assert got.complete
    assert got.n_states == ref.n_states == 3014
    assert got.n_transitions == ref.n_transitions
    assert got.levels == ref.levels
    assert got.violation is None


# --------------------------------------------------------------------------
# chaos integration (slow): kills, races, truncation, reshard


def read_final(events_path):
    ends = [json.loads(l) for l in open(events_path)
            if '"run_end"' in l]
    ends = [e for e in ends if e.get("event") == "run_end"]
    return ends[-1]


def chaos_policy():
    return CampaignPolicy(checkpoint_every_s=0.0, backoff_base_s=0.0,
                          grace_s=10.0, poll_s=0.05, max_resumes=8)


@pytest.mark.slow
def test_chaos_kills_and_mesh_reshard_byte_identical(tmp_path, toy_cfg):
    """The acceptance scenario: SIGKILL once mid-level and once on a
    level boundary, plus a SIGINT/SIGKILL race, across a 1 -> 2 -> 1
    mesh plan — finals byte-identical to an uninterrupted run, zero
    operator input.

    The boundary kill goes LAST: a boundary-shaped snapshot means a
    level's blocks discovered nothing new, which on this toy only
    happens at the final level — any kill scheduled after it would
    find the resumed child finishing before its trigger count."""
    from raft_tla_tpu.campaign.chaos import ChaosMonkey, run_reference

    spec = toy_spec(toy_cfg)
    ref = run_reference(spec, str(tmp_path / "ref"))
    assert ref == {"outcome": "ok", "n_states": 3014,
                   "n_transitions": 5274}

    monkey = ChaosMonkey(kills={0: ("kill", "mid-level"),
                                1: ("int-race", 2),
                                2: ("kill", "boundary")})
    sup = Supervisor(spec, str(tmp_path / "chaos"),
                     policy=chaos_policy(), mesh_plan=[1, 2, 1],
                     spawn_hook=monkey.spawn_hook,
                     pre_verify_hook=monkey.pre_verify_hook, quiet=True)
    res = sup.run()
    assert res.outcome == "ok"
    assert res.exit_code == 0
    assert len(monkey.fired) == 3, monkey.fired
    assert res.attempts >= 4
    assert res.reshards >= 2             # 1 -> 2 and 2 -> 1
    assert {"mid-level", "boundary"} <= monkey.kill_kinds()

    end = read_final(sup.events_path)
    assert (res.outcome, end["n_states"], end["n_transitions"]) == \
        (ref["outcome"], ref["n_states"], ref["n_transitions"])
    assert res.n_states == 3014

    # the supervisor's own journal: preempts none (kills were external),
    # reshard + resume_attempt lines present and schema-valid
    from raft_tla_tpu.obs import validate_event
    sup_evs = [json.loads(l) for l in open(sup.sup_events)]
    assert not [err for e in sup_evs for err in validate_event(e)]
    kinds = [e["event"] for e in sup_evs]
    assert kinds.count("reshard") == res.reshards
    assert "resume_attempt" in kinds


@pytest.mark.slow
def test_chaos_truncation_quarantine_generation_restore(tmp_path, toy_cfg):
    """A truncated snapshot is detected, quarantined, and the campaign
    recovers from the previous generation — byte-identical finals."""
    from raft_tla_tpu.campaign.chaos import ChaosMonkey, run_reference

    spec = toy_spec(toy_cfg)
    ref = run_reference(spec, str(tmp_path / "ref"))

    # attempt 0 dies after its 2nd checkpoint; attempt 1's verify sees a
    # good family (generation saved), dies after another checkpoint;
    # attempt 2 finds the npz truncated -> quarantine + gen restore
    monkey = ChaosMonkey(kills={0: ("kill", 2), 1: ("kill", 2)},
                         truncations={2: ""})
    sup = Supervisor(spec, str(tmp_path / "chaos"),
                     policy=chaos_policy(), mesh_plan=[1],
                     spawn_hook=monkey.spawn_hook,
                     pre_verify_hook=monkey.pre_verify_hook, quiet=True)
    res = sup.run()
    assert res.outcome == "ok"
    assert monkey.truncated, "the truncation never fired"
    assert len(res.quarantined) >= 1
    qdir, reason = res.quarantined[0]
    assert os.path.isdir(qdir)
    assert "npz" in reason or "digest" in reason or "torn" in reason

    end = read_final(sup.events_path)
    assert (res.outcome, end["n_states"], end["n_transitions"]) == \
        (ref["outcome"], ref["n_states"], ref["n_transitions"])


@pytest.mark.slow
def test_shard_reshard_round_trip_resumes_exact(tmp_path):
    """Satellite: the shard (table) engine's carry-rebuild resharder
    round-trips 2 -> 4 -> 2 losslessly.  Unlike the ddd stream
    resharder it is NOT byte-identical — it redistributes rows to
    their new fingerprint owners in owner-local discovery order — so
    the contract is: the same states come back (store rows equal as a
    multiset), level accounting is untouched, and a resume of the
    round-tripped snapshot lands on oracle-exact finals."""
    from raft_tla_tpu.config import Bounds, CheckConfig
    from raft_tla_tpu.models import refbfs
    from raft_tla_tpu.parallel import (ShardCapacities, ShardEngine,
                                       make_mesh)
    from raft_tla_tpu.parallel.shard_engine import reshard_checkpoint

    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=("NoTwoLeaders",),
                      chunk=64)
    ref = refbfs.check(cfg)
    caps = ShardCapacities(n_states=1 << 12, levels=64)
    ck = str(tmp_path / "m2.ckpt")
    ShardEngine(cfg, make_mesh(2), caps, seg_chunks=8).check(
        checkpoint=ck, checkpoint_every_s=0.0)
    mid = str(tmp_path / "m4.ckpt")
    back = str(tmp_path / "m2b.ckpt")
    out1 = reshard_checkpoint(cfg, caps, ck, mid, 4)
    out2 = reshard_checkpoint(cfg, caps, mid, back, 2)
    assert out1["ndev_dst"] == 4 and out2["ndev_dst"] == 2
    assert out1["n_states"] == out2["n_states"] == \
        sum(out2["per_device"])

    def sorted_rows(z):              # c0 is the packed state store
        rows = z["c0"]
        return rows[np.lexsort(rows.T[::-1])]

    with np.load(ck) as a, np.load(back) as b:
        assert set(a.files) == set(b.files)
        assert np.array_equal(sorted_rows(a), sorted_rows(b))
        assert np.array_equal(a["c14"], b["c14"])   # per-level counts
        assert int(a["c15"]) == int(b["c15"])       # current BFS level

    got = ShardEngine(cfg, make_mesh(2), caps).check(resume=back)
    assert got.n_states == ref.n_states == 3014
    assert got.levels == ref.levels
    assert got.n_transitions == ref.n_transitions
    assert sum(got.coverage.values()) == sum(ref.coverage.values())
    assert got.violation is None


@pytest.mark.slow
def test_ddd_shard_reshard_round_trip_on_mesh(tmp_path):
    """Satellite: mesh resharder 2 -> 4 -> 2 round trip on a real mesh
    snapshot — streams verbatim, metadata arrays bit-equal."""
    from raft_tla_tpu.config import Bounds, CheckConfig
    from raft_tla_tpu.parallel import make_mesh
    from raft_tla_tpu.parallel.ddd_shard_engine import (
        DDDShardCapacities, DDDShardEngine, reshard_ddd_checkpoint)

    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=("NoTwoLeaders",),
                      chunk=32)
    c2 = DDDShardCapacities(block=64, table=1 << 12, seg_rows=1 << 13,
                            flush=1 << 10, levels=64)
    c4 = DDDShardCapacities(block=32, table=1 << 12, seg_rows=1 << 13,
                            flush=1 << 10, levels=64)
    ck = str(tmp_path / "m2.ckpt")
    DDDShardEngine(cfg, make_mesh(2), c2).check(
        checkpoint=ck, checkpoint_every_s=0.0)

    def family_bytes(root):
        return {p[len(root):]: open(p, "rb").read()
                for p in snapshot_family(root) if p != root}

    before = family_bytes(ck)
    mid = str(tmp_path / "m4.ckpt")
    back = str(tmp_path / "m2b.ckpt")
    reshard_ddd_checkpoint(cfg, c2, ck, mid, 2, 4, c4)
    reshard_ddd_checkpoint(cfg, c4, mid, back, 4, 2, c2)
    assert family_bytes(back) == before
    with np.load(ck) as a, np.load(back) as b:
        assert set(a.files) == set(b.files)
        for k in a.files:
            assert np.array_equal(a[k], b[k]), k
